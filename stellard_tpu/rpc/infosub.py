"""InfoSub: pub/sub subscriber abstraction + subscription manager.

Reference: src/ripple_net/rpc/InfoSub.cpp + NetworkOPsImp's mSub* maps
(NetworkOPsImp.h:372-392) — streams: `ledger`, `server`, `transactions`,
`transactions_proposed` (rt_transactions), per-`accounts` and per-`books`
subscriptions. WS connections implement the InfoSub sink; closes fan out
from the close path.

Fan-out is SHARDED ([subs] shards=N, ROADMAP item 3): event delivery
rides N worker threads, each subscriber pinned to one shard so its
per-client order holds, with a bounded per-client send queue
(drop-OLDEST on overflow — a slow reader sees a gap, never a stale
stream) and slow-consumer eviction past a consecutive-drop threshold.
The publishing thread (in networked mode: the ordered persist worker)
only ENQUEUES — one wedged websocket can never stall publish for the
other 10k subscribers. shards=0 is the legacy inline path (tests that
want synchronous delivery construct the manager that way).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from ..protocol.sttx import SerializedTransaction
from ..protocol.ter import TER
from ..state.ledger import Ledger

__all__ = ["InfoSub", "SubscriptionManager"]


class InfoSub:
    """One subscriber (a WS connection or an in-process test sink)."""

    _next_id = 0

    def __init__(self, send: Callable[[dict], None], client_ip: str = ""):
        self.send = send
        InfoSub._next_id += 1
        self.id = InfoSub._next_id
        # resource-plane identity: path-update shedding/charging keys on
        # the client endpoint (empty for in-process sinks: never charged)
        self.client_ip = client_ip
        self.streams: set[str] = set()
        self.accounts: set[bytes] = set()
        self.accounts_proposed: set[bytes] = set()
        # live path-find subscriptions (reference: PathRequest) —
        # request id -> decoded {src, dst, dst_amount, send_max, echo}
        self.path_requests: dict[int, dict] = {}
        self._next_path_id = 0
        # sharded-fanout state (owned by the shard's lock, not this
        # object): bounded pending-event queue + slow-consumer tracking
        self.sendq: deque = deque()
        self.queued = False      # currently in its shard's ready ring
        self.drop_run = 0        # consecutive drops (resets on delivery)
        self.dropped = 0
        self.evicted = False
        # resume cursor: highest ledgerClosed seq ENQUEUED to this
        # client (guarded by the manager's replay lock) — the monotonic
        # floor that suppresses duplicates when a resume replay overlaps
        # a live publish (doc/follower.md "Resume cursors")
        self.last_seq = 0


class _FanoutShard:
    """One fanout worker: a ready-ring of subscribers with pending
    events, drained FIFO per subscriber. All queue state is guarded by
    this shard's lock; the actual send runs OUTSIDE it."""

    # per-turn drain budget: bounds how long one chatty subscriber can
    # hold the worker before the ring rotates
    DRAIN_BURST = 16

    def __init__(self, mgr: "SubscriptionManager", idx: int):
        self.mgr = mgr
        self.idx = idx
        self.cv = threading.Condition()
        self.ready: deque[InfoSub] = deque()
        # per-shard accounting (satellite of the tree scale-out): queue
        # depth + drop/evict counts, scraped via GET /metrics so the
        # watchdog's fanout rule can be cross-checked from Prometheus
        self.depth = 0       # pending events across this shard's subs
        self.dropped = 0
        self.evicted = 0
        self._stop = False
        self._idle = True
        self.thread = threading.Thread(
            target=self._run, name=f"subs-fanout-{idx}", daemon=True
        )
        self.thread.start()

    def enqueue(self, sub: InfoSub, msg: dict, now: float) -> None:
        mgr = self.mgr
        evict = False
        with self.cv:
            if sub.evicted:
                return
            if len(sub.sendq) >= mgr.sendq_cap:
                # drop-OLDEST: the freshest state wins; the client sees
                # a gap, never a stale stream stretching back minutes
                sub.sendq.popleft()
                sub.dropped += 1
                sub.drop_run += 1
                self.depth -= 1
                self.dropped += 1
                mgr._bump("dropped_events")
                if sub.drop_run >= mgr.evict_drops:
                    sub.evicted = True
                    evict = True
                    self.evicted += 1
                    self.depth -= len(sub.sendq)
                    sub.sendq.clear()
            if not evict:
                sub.sendq.append((msg, now))
                self.depth += 1
                mgr._bump("published")
                if not sub.queued:
                    sub.queued = True
                    self.ready.append(sub)
                    self.cv.notify()
        if evict:
            mgr._evict(sub, reason="slow_consumer")

    def _run(self) -> None:
        mgr = self.mgr
        while True:
            with self.cv:
                while not self.ready and not self._stop:
                    self._idle = True
                    self.cv.notify_all()  # flush() waits on idle
                    self.cv.wait(timeout=1.0)
                if self._stop:
                    return
                self._idle = False
                sub = self.ready.popleft()
                batch = []
                for _ in range(self.DRAIN_BURST):
                    if not sub.sendq:
                        break
                    batch.append(sub.sendq.popleft())
                self.depth -= len(batch)
                if sub.sendq:
                    self.ready.append(sub)  # rotate: fairness
                else:
                    sub.queued = False
            dead = False
            for msg, t_enq in batch:
                try:
                    sub.send(msg)
                except Exception:  # noqa: BLE001 — a dead subscriber must
                    dead = True    # not break the fan-out plane
                    break
                now = time.perf_counter()
                lag_ms = (now - t_enq) * 1000.0
                with mgr._stats_lock:
                    mgr.lag_hist.record(lag_ms)
                    mgr.stats["delivered"] += 1
                sub.drop_run = 0
                if (
                    mgr.tracer is not None
                    and mgr.tracer.enabled
                    and msg.get("type") == "ledgerClosed"
                    and sub.id % 256 == 1
                ):
                    # sampled publish→deliver spans (`subs.fanout`): one
                    # representative per ~256 subscribers per close, so
                    # a 10k-subscriber fanout leaves evidence without
                    # flooding the ring
                    mgr.tracer.complete(
                        "subs.fanout", "publish", t_enq, now,
                        shard=self.idx, sub=sub.id,
                        seq=msg.get("ledger_index"),
                    )
            if dead:
                with self.cv:
                    self.evicted += 1
                    self.depth -= len(sub.sendq)
                    sub.sendq.clear()
                mgr._evict(sub, reason="dead")

    def drained(self) -> bool:
        with self.cv:
            return self._idle and not self.ready

    def stop(self) -> None:
        with self.cv:
            self._stop = True
            self.cv.notify_all()
        self.thread.join(timeout=5)


class SubscriptionManager:
    """Fan-out hub wired into NetworkOPs' close/tx hooks."""

    def __init__(self, ops, shards: int = 0, sendq_cap: int = 512,
                 evict_drops: int = 64, push_retries: int = 5,
                 resume_horizon: int = 1024, tracer=None):
        from ..node.metrics import LatencyHist
        from ..node.tracer import STAGE_BOUNDS

        self.ops = ops
        self.tracer = tracer
        # liquidity plane (paths/plane.py), wired by the node when
        # [paths] is enabled; None keeps the legacy unbudgeted publisher
        self.path_plane = None
        self.sendq_cap = max(1, int(sendq_cap))
        self.evict_drops = max(1, int(evict_drops))
        self.push_retries = int(push_retries)
        self._lock = threading.Lock()
        self._subs: dict[int, InfoSub] = {}
        # url -> RpcSub (reference: NetworkOPs mRpcSubMap): HTTP-callback
        # subscriptions outlive any one request; found/created by
        # `subscribe` with a url (admin-only)
        self.rpc_subs: dict[str, InfoSub] = {}
        # fanout plane: publish→deliver lag + drop/evict accounting.
        # stats writes ride the shard locks (or the publish thread when
        # inline), so plain int bumps under those locks suffice.
        self.stats = {
            "published": 0, "delivered": 0, "dropped_events": 0,
            "slow_evicted": 0, "dead_evicted": 0,
            "resumed": 0, "resume_replayed": 0, "resume_cold": 0,
            "dup_suppressed": 0,
        }
        # resume-from-seq replay ring (reconnect-storm hardening): the
        # last `resume_horizon` ledgerClosed events, so a dropped client
        # replays its gap instead of re-subscribing cold. The replay
        # lock ALSO serializes each sub's cursor stamp (last_seq) with
        # resume's replay — without that, a live publish racing a replay
        # could jump the cursor past undelivered replayed seqs.
        self.resume_horizon = max(0, int(resume_horizon))
        self._replay: deque = deque(maxlen=max(1, self.resume_horizon))
        self._replay_lock = threading.Lock()
        # one lock for the shared counters + lag histogram: enqueues
        # ride per-shard locks and deliveries ride worker threads, so
        # bare `+=` across shards would lose updates
        self._stats_lock = threading.Lock()
        self.lag_hist = LatencyHist(bounds=STAGE_BOUNDS, interpolate=True)
        self._shards: list[_FanoutShard] = [
            _FanoutShard(self, i) for i in range(max(0, int(shards)))
        ]
        ops.on_ledger_closed.append(self._pub_ledger)
        ops.on_proposed_tx.append(self._pub_proposed)

    def rpc_sub(self, url: str, username: str = "", password: str = ""):
        """Find-or-create the RPCSub for a url (reference: findRpcSub /
        addRpcSub); fresh credentials update an existing sub."""
        from .rpcsub import RpcSub

        with self._lock:
            sub = self.rpc_subs.get(url)
            if sub is None:
                sub = RpcSub(url, username, password,
                             max_retries=self.push_retries)
                self.rpc_subs[url] = sub
            elif username or password:
                sub.set_credentials(username, password)
        # slow-consumer eviction for the HTTP-push side too: a url whose
        # listener keeps exhausting delivery retries is dead weight and
        # gets pruned outright (rpcsub.py fires this past its threshold)
        sub.on_dead = lambda s=sub: self._evict(s, reason="slow_consumer")
        return sub

    def rpc_sub_lookup(self, url: str):
        """Find only (unsubscribe must never create — a typo'd url would
        register a phantom subscription and report success)."""
        with self._lock:
            return self.rpc_subs.get(url)

    def prune_rpc_sub(self, sub) -> None:
        """Drop an RpcSub that no longer subscribes to anything: a url
        entry with no streams/accounts must not live (and get POSTed
        events) forever. Emptiness is re-checked under the registry
        lock so a concurrent re-subscribe (which adds a stream through
        the same lock-guarded find-or-create) is never destroyed."""
        with self._lock:
            if (sub.streams or sub.accounts or sub.accounts_proposed
                    or sub.path_requests):
                return
            self.rpc_subs.pop(getattr(sub, "url", None), None)
            self._subs.pop(sub.id, None)
        close = getattr(sub, "close", None)
        if close is not None:
            close()

    # -- subscribe / unsubscribe (reference: handlers/Subscribe.cpp) ------

    def add(self, sub: InfoSub) -> None:
        with self._lock:
            self._subs[sub.id] = sub

    def remove(self, sub_id: int) -> None:
        with self._lock:
            self._subs.pop(sub_id, None)

    def subscribe_streams(self, sub: InfoSub, streams: list[str]) -> dict:
        """Returns the initial result payload (ledger stream returns the
        current state snapshot, reference Subscribe.cpp:86-112)."""
        result: dict = {}
        for stream in streams:
            if stream not in ("ledger", "server", "transactions",
                              "transactions_proposed", "rt_transactions"):
                continue
            sub.streams.add(stream)
            if stream == "ledger":
                result.update(self._ledger_snapshot())
        self.add(sub)
        return result

    def unsubscribe_streams(self, sub: InfoSub, streams: list[str]) -> None:
        for stream in streams:
            sub.streams.discard(stream)

    def subscribe_accounts(self, sub: InfoSub, accounts: list[bytes],
                           proposed: bool = False) -> None:
        target = sub.accounts_proposed if proposed else sub.accounts
        target.update(accounts)
        self.add(sub)

    # -- path-find subscriptions (reference: PathRequests) ----------------

    def create_path_request(self, sub: InfoSub, request: dict) -> int:
        """Register a live path search; updates push on every close."""
        sub._next_path_id += 1
        rid = sub._next_path_id
        sub.path_requests[rid] = request
        self.add(sub)
        return rid

    def close_path_request(self, sub: InfoSub,
                           rid: Optional[int] = None) -> bool:
        if rid is None:
            had = bool(sub.path_requests)
            sub.path_requests.clear()
            return had
        return sub.path_requests.pop(rid, None) is not None

    def _pub_path_updates(self, ledger: Ledger) -> None:
        from ..paths import find_paths
        from ..paths.pathfinder import PATH_SEARCH_DEFAULT, PATH_SEARCH_FAST

        from ..protocol.stobject import STPathSet

        pairs = [
            (sub, rid, req)
            for sub in self._each()
            for rid, req in list(sub.path_requests.items())
        ]
        if not pairs:
            return
        # liquidity plane (ISSUE 17): all subscriptions of one close
        # share the incrementally-advanced book index, re-rank
        # stalest-first under the per-close budget, and shed (not queue)
        # past it or when the endpoint is resource-throttled
        plane = self.path_plane
        books = pre_rank = None
        if plane is not None:
            plane.begin_close(ledger.seq)
            books = plane.books_for(ledger)
            pre_rank = plane.make_pre_rank(ledger)
            by_key = {(sub.id, rid): (sub, rid, req)
                      for sub, rid, req in pairs}
            plane.sync_live(by_key.keys())
            pairs = [by_key[k]
                     for k in plane.order_keys(by_key.keys(), ledger.seq)]
        for sub, rid, req in pairs:
            if plane is not None:
                ip = getattr(sub, "client_ip", "")
                endpoint = (ip, 0) if ip else None
                if not plane.claim_update((sub.id, rid), ledger.seq,
                                          endpoint=endpoint):
                    continue
            # level ramp (reference: PathRequest.cpp:370-379 —
            # answer at PATH_SEARCH_FAST on the first update, then
            # jump to the full PATH_SEARCH level)
            level = (
                PATH_SEARCH_FAST
                if req.get("level", 0) < PATH_SEARCH_FAST
                else PATH_SEARCH_DEFAULT
            )
            req["level"] = level
            try:
                alts = find_paths(
                    ledger, req["src"], req["dst"], req["dst_amount"],
                    send_max=req.get("send_max"), level=level,
                    books=books, pre_rank=pre_rank,
                )
            except Exception:  # noqa: BLE001 — a bad request must not kill publishing
                continue
            if plane is not None:
                plane.note_ranked((sub.id, rid), ledger.seq)
            msg = {
                "type": "path_find",
                "id": rid,
                # only the full-depth search is a definitive answer;
                # the FAST first pass is marked partial so clients
                # wait for the deeper updates (reference:
                # PathRequest's iLastLevel / full_reply contract)
                "full_reply": level >= PATH_SEARCH_DEFAULT,
                "ledger_index": ledger.seq,
                "alternatives": [
                    {
                        "paths_computed": STPathSet(a["paths"]).to_json(),
                        "source_amount": a["source_amount"].to_json(),
                    }
                    for a in alts
                ],
                **req.get("echo", {}),
            }
            self._deliver(sub, msg)

    def unsubscribe_accounts(self, sub: InfoSub, accounts: list[bytes],
                             proposed: bool = False) -> None:
        target = sub.accounts_proposed if proposed else sub.accounts
        target.difference_update(accounts)

    def _ledger_snapshot(self) -> dict:
        lcl = self.ops.lm.closed_ledger()
        return {
            "ledger_index": lcl.seq,
            "ledger_hash": lcl.hash().hex().upper(),
            "ledger_time": lcl.close_time,
            "fee_base": lcl.base_fee,
            "fee_ref": lcl.reference_fee_units,
            "reserve_base": lcl.reserve_base,
            "reserve_inc": lcl.reserve_increment,
        }

    # -- fan-out ----------------------------------------------------------

    def _each(self):
        with self._lock:
            return list(self._subs.values())

    def _pub_ledger(self, ledger: Ledger, results: dict) -> None:
        """reference: NetworkOPs::pubLedger — ledgerClosed stream msg,
        then per-tx accepted messages."""
        msg = {
            "type": "ledgerClosed",
            "ledger_index": ledger.seq,
            "ledger_hash": ledger.hash().hex().upper(),
            "ledger_time": ledger.close_time,
            "fee_base": ledger.base_fee,
            "fee_ref": ledger.reference_fee_units,
            "reserve_base": ledger.reserve_base,
            "reserve_inc": ledger.reserve_increment,
            "txn_count": len(results),
        }
        if self.resume_horizon > 0:
            with self._replay_lock:
                self._replay.append((ledger.seq, msg))
        for sub in self._each():
            if "ledger" in sub.streams:
                self._deliver_ledger(sub, msg)
        # accepted transactions (reference: pubAcceptedTransaction)
        for txid, blob, meta in ledger.tx_entries():
            tx = ledger.parse_tx(txid, blob)
            ter = results.get(txid, TER.tesSUCCESS)
            self._pub_tx(tx, ter, ledger=ledger, validated=True, meta=meta)
        # live path-find subscriptions re-search against the new state on
        # a jtUPDATE_PF job (reference: PathRequests::updateAll) — NOT on
        # this thread, which in networked mode is the ordered persist
        # worker and must not serialize pathfinding into ledger persists
        if any(s.path_requests for s in self._each()):
            from ..node.jobqueue import JobType

            self.ops.jq.add_job(
                JobType.jtUPDATE_PF,
                "pathUpdates",
                lambda: self._pub_path_updates(ledger),
            )

    def pub_server_status(self) -> None:
        """serverStatus event to `server`-stream subscribers (reference:
        NetworkOPs::pubServer on load-factor movement)."""
        from ..node.loadmgr import NORMAL_FEE

        ft = getattr(self.ops, "fee_track", None)
        msg = {
            "type": "serverStatus",
            "server_status": self.ops.server_state(),
            "load_base": NORMAL_FEE,
            "load_factor": ft.load_factor if ft is not None else NORMAL_FEE,
        }
        for sub in self._each():
            if "server" in sub.streams:
                self._deliver(sub, msg)

    def _pub_proposed(self, tx: SerializedTransaction, ter: TER) -> None:
        self._pub_tx(tx, ter, ledger=None, validated=False)

    def _pub_tx(self, tx: SerializedTransaction, ter: TER,
                ledger: Optional[Ledger], validated: bool,
                meta: bytes = b"") -> None:
        msg = {
            "type": "transaction",
            "transaction": _tx_json_with_hash(tx),
            "status": "closed" if validated else "proposed",
            "engine_result": ter.token,
            "engine_result_code": int(ter),
            "engine_result_message": ter.human,
            "validated": validated,
        }
        if ledger is not None:
            msg["ledger_index"] = ledger.seq
            msg["ledger_hash"] = ledger.hash().hex().upper()
        if meta:
            from ..protocol.stobject import STObject

            msg["meta"] = STObject.from_bytes(meta).to_json()

        # accounts touched: from the metadata when we have it (covers
        # crossed offers, trust-line counterparties, issuers — reference
        # getAffectedAccounts); fall back to Account/Destination for
        # proposed txns that carry no meta yet
        touched = {tx.account}
        from ..protocol.sfields import sfDestination

        dest = tx.obj.get(sfDestination)
        if dest:
            touched.add(dest)
        if meta:
            from ..protocol.meta import affected_accounts

            touched.update(affected_accounts(meta))

        if self.tracer is not None and validated and self.tracer.enabled:
            # per-sampled-tx fanout leaf: the publish stage of the tx's
            # cross-node causal tree (subs.fanout spans stay the sampled
            # per-subscriber delivery evidence)
            self.tracer.instant(
                "subs.fanout.tx", "publish", txid=tx.txid(),
                ledger_seq=msg.get("ledger_index"),
            )

        for sub in self._each():
            wants = False
            if validated and "transactions" in sub.streams:
                wants = True
            if not validated and (
                "transactions_proposed" in sub.streams
                or "rt_transactions" in sub.streams
            ):
                wants = True
            if sub.accounts & touched and validated:
                wants = True
            if sub.accounts_proposed & touched:
                wants = True
            if wants:
                self._deliver(sub, msg)

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += n

    def _deliver(self, sub: InfoSub, msg: dict) -> None:
        """Route one event: shard enqueue (bounded, async) when the
        fanout plane is on, inline send otherwise."""
        if self._shards:
            shard = self._shards[sub.id % len(self._shards)]
            shard.enqueue(sub, msg, time.perf_counter())
            return
        self._bump("published")
        try:
            sub.send(msg)
            self._bump("delivered")
        except Exception:  # noqa: BLE001 — a dead subscriber must not break the pub path
            self.remove(sub.id)
            self._bump("dead_evicted")

    def _deliver_ledger(self, sub: InfoSub, msg: dict) -> None:
        """ledgerClosed funnel: monotonic per-client cursor stamp +
        duplicate suppression (a resume replay overlapping a live
        publish must deliver each seq once, in order). The stamp is
        serialized on the replay lock with resume()'s replay loop."""
        seq = msg.get("ledger_index", 0)
        with self._replay_lock:
            if seq <= sub.last_seq:
                self._bump("dup_suppressed")
                return
            sub.last_seq = seq
            self._deliver(sub, msg)

    def resume(self, sub: InfoSub, last_seq: int) -> dict:
        """Resume-from-seq cursor (reconnect-storm hardening): a
        reconnecting client presents its last-delivered ledgerClosed
        seq; every later event still inside the bounded replay ring is
        re-enqueued in order and the `ledger` stream re-attaches — no
        cold re-subscribe, no silent gap. A cursor PAST the horizon
        gets an explicit cold answer ({"cold": True} with the current
        replay floor) so the client knows to re-subscribe cold.

        The whole replay + registration runs under the replay lock:
        publishes that landed in the ring before we locked are replayed
        here, publishes after we release see the registered sub and
        deliver live, and the per-sub cursor stamp (serialized on the
        same lock) suppresses the overlap — zero gaps, zero dups."""
        with self._replay_lock:
            ring = list(self._replay) if self.resume_horizon > 0 else []
            floor = ring[0][0] if ring else 0
            # resumable iff the client's next event (last_seq+1) is at
            # or above the ring floor — exactly-at-horizon resumes
            cold = (
                self.resume_horizon <= 0
                or last_seq + 1 < floor
                or (not ring and last_seq > 0)
            )
            if cold:
                self._bump("resume_cold")
                return {
                    "resumed": False, "cold": True, "replayed": 0,
                    "horizon": floor,
                }
            sub.last_seq = max(sub.last_seq, int(last_seq))
            replayed = 0
            for seq, msg in ring:
                if seq <= sub.last_seq:
                    continue
                sub.last_seq = seq
                self._deliver(sub, msg)
                replayed += 1
            sub.streams.add("ledger")
            self.add(sub)
        self._bump("resumed")
        self._bump("resume_replayed", replayed)
        return {
            "resumed": True, "cold": False, "replayed": replayed,
            "horizon": floor,
        }

    def shard_stats(self) -> dict:
        """Flat per-shard depth/drop/evict gauges for the Prometheus
        hook (subs_shard.shard<N>_*)."""
        out = {}
        for s in self._shards:
            with s.cv:
                out[f"shard{s.idx}_depth"] = s.depth
                out[f"shard{s.idx}_dropped"] = s.dropped
                out[f"shard{s.idx}_evicted"] = s.evicted
        return out

    def _evict(self, sub: InfoSub, reason: str) -> None:
        """Drop a subscriber the fanout plane gave up on (slow consumer
        past the drop threshold, or a dead sink). Idempotent: the slow
        path and a later dead-sink detection may both fire for one
        sub."""
        with self._lock:
            already = getattr(sub, "_evict_done", False)
            sub._evict_done = True
            sub.evicted = True
            self._subs.pop(sub.id, None)
            url = getattr(sub, "url", None)
            if url is not None and self.rpc_subs.get(url) is sub:
                del self.rpc_subs[url]
        if already:
            return
        self._bump(
            "slow_evicted" if reason == "slow_consumer" else "dead_evicted"
        )
        close = getattr(sub, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until every shard drained its queues (tests/smokes that
        assert on delivered events; the serving path never calls it)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(s.drained() for s in self._shards):
                return True
            time.sleep(0.002)
        return all(s.drained() for s in self._shards)

    def stop(self) -> None:
        for s in self._shards:
            s.stop()
        with self._lock:
            rpc_subs = list(self.rpc_subs.values())
        for sub in rpc_subs:
            close = getattr(sub, "close", None)
            if close is not None:
                close()

    def get_json(self) -> dict:
        """`subs.*` counters for get_counts: fanout shape, publish /
        deliver / drop / evict counts, publish→deliver lag quantiles,
        and the HTTP-push (RPCSub) delivery aggregate."""
        with self._lock:
            n_subs = len(self._subs)
            rpc_list = list(self.rpc_subs.values())
        out = {
            "subscribers": n_subs,
            "rpc_subs": len(rpc_list),
            "shards": len(self._shards),
            "sendq_cap": self.sendq_cap,
            "evict_drops": self.evict_drops,
            "resume_horizon": self.resume_horizon,
            **self.stats,
            **self.shard_stats(),
        }
        if self.lag_hist.count:
            out["fanout_lag_p50_ms"] = self.lag_hist.quantile(0.5)
            out["fanout_lag_p99_ms"] = self.lag_hist.quantile(0.99)
        push = {"sent": 0, "retries": 0, "failures": 0, "dropped": 0}
        for sub in rpc_list:
            for k in push:
                push[k] += getattr(sub, "stats", {}).get(k, 0)
        out["push"] = push
        return out


def _tx_json_with_hash(tx: SerializedTransaction) -> dict:
    j = tx.obj.to_json()
    j["hash"] = tx.txid().hex().upper()
    return j
