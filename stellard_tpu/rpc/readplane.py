"""Read plane: the serving side's view of validated state.

Two pieces, both designed so the hot read path never touches the chain
lock (reference: reporting-mode rippled's read-only ETL tier; ROADMAP
item 3):

``ReadPlane`` holds an immutable validated-snapshot pointer.
``publish_closed_ledger`` hands each newly validated ledger here after
its persistence sinks ran; read RPCs resolve ``ledger_index:
"validated"`` from this pointer with a bare attribute read — a held
chain lock can no longer block ``account_info`` against the last
validated snapshot (pinned by test).

``ResultCache`` memoizes whole RPC results keyed by
``(validated_seq, method, canonical-params)``. A validated ledger is
immutable, so an entry is immutable by construction; invalidation is
by NEW SEQ, not by write tracking — publishing seq N+1 swaps the whole
generation. One slow epoch boundary beats per-entry bookkeeping on
every read.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Optional

__all__ = ["ReadPlane", "ResultCache", "CACHEABLE_METHODS",
           "forever_slot"]

# the hot read RPCs worth a whole-result cache (ISSUE 10); everything
# else recomputes — these dominate production read traffic.
# ripple_path_find joined in ISSUE 17: a path search is a pure function
# of the validated snapshot and by far the dearest entry in the fee
# schedule (FEE_PATH_FIND), so identical back-to-back queries within
# one validated epoch must not recompute.
CACHEABLE_METHODS = frozenset(
    {"account_info", "book_offers", "ledger", "account_tx",
     "ripple_path_find"}
)


class ReadPlane:
    """Latest-validated-snapshot pointer + its result cache epoch.

    ``publish`` is called from ``publish_closed_ledger`` AFTER its
    persistence sinks ran (leader close path and follower ingest path
    alike), so a cache epoch never opens before the SQL-index
    read-your-writes wait can see the ledger; ``snapshot`` is called
    from every read RPC. The pointer swap is a single attribute
    assignment — readers never block on a lock, and a reader that
    races a publish sees either snapshot, both of which are complete
    immutable closed ledgers.
    """

    def __init__(self, cache: Optional["ResultCache"] = None):
        self._snap = None  # latest validated Ledger (closed, immutable)
        self._lock = threading.Lock()  # serializes publishers only
        self.cache = cache
        self.published = 0
        # the two floors the snapshot must stay behind: the persisted
        # tip (publish_closed_ledger, post-sinks) and the quorum-
        # validated tip (LedgerMaster.on_validated). The snapshot is
        # min(persisted, validated) — never an unvalidated solo close,
        # never a validated-but-not-yet-persisted ledger (a cache epoch
        # must not open before _await_history can see its ledger).
        self._persisted = None
        self._validated_tip = None
        # archive mode (doc/archive.md): the verified floor — the
        # contiguous sealed-shard coverage hi. 0 = not an archive (or
        # nothing verified yet); > 0 arms the forever cache tier for
        # results whose window closes at or below it.
        self.archive_floor = 0

    def set_archive_floor(self, floor: int) -> None:
        """Publish the archive's verified floor. Monotonic: verified
        history never un-verifies, so the floor only rises."""
        with self._lock:
            self.archive_floor = max(self.archive_floor, int(floor))

    def note_persisted(self, ledger) -> None:
        """A closed ledger finished its persistence sinks."""
        with self._lock:
            if ledger is None or not getattr(ledger, "closed", False):
                return
            cur = self._persisted
            if cur is None or ledger.seq > cur.seq:
                self._persisted = ledger
            self._refresh_locked()

    def note_validated(self, ledger) -> None:
        """The chain's validated tip advanced (quorum landed). On a
        quorum net validations usually land AFTER the close persisted —
        this is the call that opens the epoch; without it the snapshot
        would lag a full round behind forever."""
        with self._lock:
            if ledger is None:
                return
            cur = self._validated_tip
            if cur is None or ledger.seq > cur.seq:
                self._validated_tip = ledger
            self._refresh_locked()

    def _refresh_locked(self) -> None:
        p, v = self._persisted, self._validated_tip
        if p is None or v is None:
            return
        cand = p if p.seq <= v.seq else v
        self._publish_locked(cand)

    def publish(self, ledger) -> None:
        """Adopt `ledger` as the serving snapshot if it advances the
        tip. Monotonic by seq: a late-persisting historical repair must
        never regress what reads see."""
        with self._lock:
            self._publish_locked(ledger)

    def _publish_locked(self, ledger) -> None:
        if ledger is None or not getattr(ledger, "closed", False):
            return
        cur = self._snap
        if cur is not None and ledger.seq <= cur.seq:
            return
        self._snap = ledger
        self.published += 1
        if self.cache is not None:
            self.cache.on_new_seq(ledger.seq)
        # out-of-core epoch contract: stamp the hot-node cache with the
        # new validated seq — nodes the serving snapshot touches from
        # here carry this epoch, and eviction takes older-epoch entries
        # first, so a history scan cannot thrash the snapshot's working
        # set out from under in-flight reads (state/hotcache.py)
        from ..state.shamap import inner_node_cache

        inner_node_cache().advance_epoch(ledger.seq)

    def snapshot(self):
        return self._snap

    def get_json(self) -> dict:
        snap = self._snap
        return {
            "published": self.published,
            "snapshot_seq": snap.seq if snap is not None else 0,
            "archive_floor": self.archive_floor,
        }


class ResultCache:
    """Validated-seq-keyed whole-result cache for the hot read RPCs.

    get/put carry the seq the caller resolved; only the CURRENT epoch's
    seq hits, so an entry can never serve stale state — a new validated
    seq invalidates everything older in O(1) (generation swap). Bounded:
    past `capacity` entries the current generation stops admitting (a
    hostile key-churn workload must not grow memory; legitimate hot keys
    land early in the epoch)."""

    def __init__(self, capacity: int = 8192):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._seq = -1
        self._gen: dict[tuple, dict] = {}
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.overflow = 0
        self.invalidated = 0
        # the forever tier (archive mode, doc/archive.md): results of
        # IMMUTABLE windows — closed at or below the archive's verified
        # floor — keyed by (method, params) alone. The epoch swap in
        # on_new_seq never touches it: sealed history cannot change, so
        # re-deriving these per epoch would be pure waste. Bounded by
        # the same capacity as a generation.
        self._forever: dict[tuple, dict] = {}
        self.forever_hits = 0
        self.forever_inserts = 0
        self.forever_overflow = 0

    def on_new_seq(self, seq: int) -> None:
        with self._lock:
            if seq == self._seq:
                return
            self.invalidated += len(self._gen)
            self._seq = seq
            self._gen = {}
            # self._forever survives by design — immutable-seq results
            # outlive every epoch (doc/archive.md)

    def get(self, seq: int, method: str, key: str) -> Optional[dict]:
        with self._lock:
            if seq != self._seq:
                self.misses += 1
                return None
            hit = self._gen.get((method, key))
            if hit is None:
                self.misses += 1
                return None
            self.hits += 1
        # shallow copy: the doors annotate results in place ("status")
        return dict(hit)

    def put(self, seq: int, method: str, key: str, result: dict) -> None:
        with self._lock:
            if seq != self._seq:
                return  # computed against a superseded epoch
            if len(self._gen) >= self.capacity:
                self.overflow += 1
                return
            self._gen[(method, key)] = result
            self.inserts += 1

    def get_forever(self, method: str, key: str) -> Optional[dict]:
        """Forever-tier lookup: no seq — the key IS the whole identity
        of an immutable-window result."""
        with self._lock:
            hit = self._forever.get((method, key))
            if hit is None:
                return None
            self.forever_hits += 1
        return dict(hit)

    def put_forever(self, method: str, key: str, result: dict) -> None:
        with self._lock:
            if len(self._forever) >= self.capacity:
                self.forever_overflow += 1
                return
            self._forever[(method, key)] = result
            self.forever_inserts += 1

    def get_json(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "seq": self._seq,
                "entries": len(self._gen),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / total, 4) if total else 0.0,
                "inserts": self.inserts,
                "overflow": self.overflow,
                "invalidated": self.invalidated,
                "forever_entries": len(self._forever),
                "forever_hits": self.forever_hits,
                "forever_inserts": self.forever_inserts,
                "forever_overflow": self.forever_overflow,
            }


def serving_validated(node):
    """The ledger "validated" reads serve: the read plane's published
    snapshot, or the chain's validated tip when it is newer (the
    snapshot publishes post-persist, so it can lag a just-validated
    ledger by one publish — reads must never go backwards). Bare
    attribute reads only: no chain lock."""
    plane = getattr(node, "read_plane", None)
    snap = plane.snapshot() if plane is not None else None
    lv = getattr(node, "ledger_master", None)
    lv = lv.validated if lv is not None else None
    if snap is None:
        return lv
    if lv is not None and lv.seq > snap.seq:
        return lv
    return snap


def cache_slot(ctx, method: str):
    """(serving_ledger, canonical-params-key) when this request is
    servable from the validated-seq cache, else None.

    Eligible: the method is one of the hot four, a validated snapshot
    exists, and the request is a pure function of that snapshot — the
    ledger-selector methods must target VALIDATED state (an explicit
    ``ledger_index: "validated"``, or the selector-less default on a
    node that serves validated by default — follower mode);
    ``account_tx`` reads the SQL history index, which also holds
    closed-but-not-yet-validated ledgers, so it is cacheable only when
    its window is EXPLICITLY bounded at or below the serving validated
    seq (persisted history ≤ the validated floor is immutable; an
    open-ended window keeps growing within one epoch on a node whose
    closes outpace its validations)."""
    node = ctx.node
    cache = getattr(node, "read_cache", None)
    if cache is None or method not in CACHEABLE_METHODS:
        return None
    # key by the ledger the request will actually serve. When the chain
    # validated ahead of the published snapshot, this seq is ahead of
    # the cache's epoch, so get/put are refused — caching simply stays
    # off until the epoch opens (post-persist, post-validation)
    snap = serving_validated(node)
    if snap is None:
        return None
    p = ctx.params
    if method == "account_tx":
        try:
            max_l = int(p.get("ledger_index_max", -1))
        except (TypeError, ValueError):
            return None
        if max_l < 0 or max_l > snap.seq:
            return None
    else:
        if p.get("ledger_hash"):
            return None
        sel = p.get("ledger_index")
        if sel is None:
            if not getattr(node, "serve_validated_default", False):
                return None
        elif sel != "validated":
            return None
    try:
        key = json.dumps(p, sort_keys=True, separators=(",", ":"))
    except (TypeError, ValueError):
        return None  # non-JSON params (embedded callers): uncacheable
    return snap, key


def forever_slot(ctx, method: str) -> Optional[str]:
    """Canonical params key when this request is IMMUTABLE — its window
    closes at or below the archive's verified floor (doc/archive.md) —
    else None.

    An immutable result is a pure function of offline-verified sealed
    history, so it survives every epoch swap: caching it per validated
    seq (the epoch tier) would re-derive the same bytes forever. Only
    two methods qualify, and only with an EXPLICITLY bounded window:

    - ``account_tx`` with ``0 <= ledger_index_max <= floor`` (an
      unbounded max keeps growing with the chain; above the floor the
      window includes un-verified — and on a validator, trimmable —
      history);
    - ``ledger`` addressed by a numeric seq at or below the floor
      ("validated"/"closed"/"current" selectors are moving targets).

    The floor itself only rises (verified history never un-verifies),
    so eligibility decided against an older floor stays correct."""
    node = ctx.node
    cache = getattr(node, "read_cache", None)
    plane = getattr(node, "read_plane", None)
    if cache is None or plane is None:
        return None
    floor = getattr(plane, "archive_floor", 0)
    if floor <= 0:
        return None
    p = ctx.params
    if method == "account_tx":
        try:
            max_l = int(p.get("ledger_index_max", -1))
        except (TypeError, ValueError):
            return None
        if max_l < 0 or max_l > floor:
            return None
    elif method == "ledger":
        if p.get("ledger_hash"):
            return None
        sel = p.get("ledger_index")
        if isinstance(sel, bool):
            return None
        try:
            seq = int(sel)
        except (TypeError, ValueError):
            return None
        if seq <= 0 or seq > floor:
            return None
    else:
        return None
    try:
        return json.dumps(p, sort_keys=True, separators=(",", ":"))
    except (TypeError, ValueError):
        return None  # non-JSON params (embedded callers): uncacheable


def cached_dispatch(ctx, method: str, compute) -> dict:
    """Wrap one handler call with the validated-seq result cache.
    ``compute()`` runs the real handler; error results are never
    cached (they may reflect transient state like a draining
    pipeline). The serving ledger is PINNED into the context so the
    handler resolves exactly the ledger the cache key names — without
    the pin, a validated tip advancing between keying and compute
    would cache a newer ledger's answer under the older epoch.

    Archive mode: the forever tier is consulted FIRST — an immutable-
    window result (forever_slot) hits across epoch swaps; a computed
    one is admitted to the forever tier (and, when also epoch-
    eligible, the per-seq generation)."""
    fkey = forever_slot(ctx, method)
    cache: Optional[ResultCache] = getattr(ctx.node, "read_cache", None)
    if fkey is not None and cache is not None:
        hit = cache.get_forever(method, fkey)
        if hit is not None:
            return hit
    slot = cache_slot(ctx, method)
    if slot is None:
        result = compute()
        if (fkey is not None and cache is not None
                and isinstance(result, dict) and "error" not in result):
            cache.put_forever(method, fkey, result)
            return dict(result)
        return result
    snap, key = slot
    ctx.pinned_validated = snap
    hit = cache.get(snap.seq, method, key)
    if hit is not None:
        if fkey is not None:
            # promote an epoch-tier hit whose window is immutable: the
            # next epoch swap must not evict it
            cache.put_forever(method, fkey, hit)
        return hit
    result = compute()
    if isinstance(result, dict) and "error" not in result:
        if fkey is not None:
            cache.put_forever(method, fkey, result)
        cache.put(snap.seq, method, key, result)
        return dict(result)  # callers may annotate; keep the cached copy clean
    return result
