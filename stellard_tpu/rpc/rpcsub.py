"""RPCSub: HTTP-callback subscriptions (reference:
src/ripple_net/rpc/RPCSub.cpp + NetworkOPs' mRpcSubMap).

`subscribe` with a `url` (admin-only) registers a server-side pusher:
every pub/sub event the subscription matches is POSTed to the client's
HTTP listener as a JSON-RPC request `{"method": "event", "params":
[event]}`, with a per-subscription monotonically increasing `seq`
stamped into the event (reference sendThread). Events queue up to 32
deep; on overflow the most recently queued event is dropped (the
reference's "drop the previous event" rule), never the oldest — a slow
listener sees a gap, not a stale stream. One daemon sender drains the
queue.

Delivery failures RETRY with bounded exponential backoff + jitter (the
reference's RPCSub keeps exactly this retry deque; the first cut here
dropped silently on the first error): an event re-enters the queue head
and waits ``backoff_base * 2^attempt`` (jittered ±25%, capped) before
the next POST. Past ``max_retries`` the event is dropped and counted;
``evict_failures`` consecutive dropped events fire ``on_dead`` so the
subscription manager can prune a listener that is gone for good.
"""

from __future__ import annotations

import base64
import json
import logging
import random
import threading
import time
import urllib.request
from collections import deque
from typing import Callable, Optional
from urllib.parse import urlparse

from .infosub import InfoSub

__all__ = ["RpcSub"]

log = logging.getLogger("stellard.rpcsub")

EVENT_QUEUE_MAX = 32  # reference RPCSub eventQueueMax


class RpcSub(InfoSub):
    """An InfoSub whose sink is a remote JSON-RPC listener."""

    # consecutive retry-exhausted drops before on_dead fires (the
    # slow-consumer eviction threshold for the HTTP-push side)
    EVICT_FAILURES = 4

    def __init__(self, url: str, username: str = "", password: str = "",
                 max_retries: int = 5, backoff_base: float = 0.25,
                 backoff_max: float = 10.0):
        parsed = urlparse(url)
        if parsed.scheme not in ("http", "https"):
            raise ValueError("only http and https are supported")
        if not parsed.hostname:
            raise ValueError("url has no host")
        self.url = url
        self.username = username
        self.password = password
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self._q: deque = deque()  # entries: (event, attempts_so_far)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._worker: Optional[threading.Thread] = None
        self._seq = 1
        self._closed = False
        self._rng = random.Random()
        self._drop_run = 0  # consecutive retry-exhausted drops
        self.stats = {"sent": 0, "retries": 0, "failures": 0, "dropped": 0}
        # pruning hook (SubscriptionManager wires _evict here): fired
        # once when EVICT_FAILURES consecutive events exhaust retries
        self.on_dead: Optional[Callable[[], None]] = None
        super().__init__(send=self._enqueue)

    def set_credentials(self, username: str, password: str) -> None:
        with self._lock:
            self.username = username
            self.password = password

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._q.clear()
            self._cv.notify_all()

    # -- sink --------------------------------------------------------------

    def _enqueue(self, obj: dict) -> None:
        with self._lock:
            if self._closed:
                return
            if len(self._q) >= EVENT_QUEUE_MAX:
                # reference: drop the PREVIOUS (most recently queued)
                # event — older queued events keep their slot
                self._q.pop()
                log.warning("rpcsub %s: queue full, dropping an event",
                            self.url)
            ev = dict(obj)
            ev["seq"] = self._seq
            self._seq += 1
            self._q.append((ev, 0))
            self._cv.notify()
            if self._worker is not None and self._worker.is_alive():
                return
            # ONE persistent sender per subscription (steady stream
            # traffic must not churn a thread per event)
            self._worker = threading.Thread(
                target=self._send_loop, name="rpcsub-send", daemon=True
            )
            self._worker.start()

    # -- delivery ----------------------------------------------------------

    def _post(self, ev: dict, user: str, pw: str) -> None:
        body = json.dumps({"method": "event", "params": [ev]}).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"},
        )
        if user or pw:
            tok = base64.b64encode(f"{user}:{pw}".encode()).decode()
            req.add_header("Authorization", f"Basic {tok}")
        with urllib.request.urlopen(req, timeout=10) as resp:
            resp.read()

    def _backoff(self, attempt: int) -> float:
        """Exponential backoff with ±25% jitter, capped — a fleet of
        pushers retrying a flapping listener must decorrelate."""
        delay = min(self.backoff_max, self.backoff_base * (2 ** attempt))
        return delay * (0.75 + 0.5 * self._rng.random())

    def _send_loop(self) -> None:
        dead = False
        while True:
            with self._lock:
                while not self._q and not self._closed:
                    self._cv.wait()
                if self._closed:
                    return
                ev, attempts = self._q.popleft()
                user, pw = self.username, self.password
            try:
                self._post(ev, user, pw)
            except Exception as exc:  # noqa: BLE001 — retry with backoff
                self.stats["failures"] += 1
                attempts += 1
                if attempts <= self.max_retries:
                    self.stats["retries"] += 1
                    delay = self._backoff(attempts - 1)
                    log.info("rpcsub %s: delivery failed (%s) — retry "
                             "%d/%d in %.2fs", self.url, exc, attempts,
                             self.max_retries, delay)
                    with self._lock:
                        if self._closed:
                            return
                        # head of the queue: per-subscription event order
                        # is preserved across the retry
                        self._q.appendleft((ev, attempts))
                        # interruptible sleep: close() must not wait out
                        # a 10s backoff, but an enqueue notification must
                        # not shortcut it either (the backoff is the
                        # whole point when the listener is down)
                        deadline = time.monotonic() + delay
                        while not self._closed:
                            left = deadline - time.monotonic()
                            if left <= 0:
                                break
                            self._cv.wait(timeout=left)
                        if self._closed:
                            return
                else:
                    self.stats["dropped"] += 1
                    self._drop_run += 1
                    log.warning("rpcsub %s: event dropped after %d "
                                "attempts: %s", self.url, attempts, exc)
                    if (self._drop_run >= self.EVICT_FAILURES
                            and self.on_dead is not None and not dead):
                        dead = True  # fire once; the manager prunes us
                        try:
                            self.on_dead()
                        except Exception:  # noqa: BLE001 — pruning must
                            pass           # not kill the sender thread
                continue
            self.stats["sent"] += 1
            self._drop_run = 0
            dead = False
