"""RPCSub: HTTP-callback subscriptions (reference:
src/ripple_net/rpc/RPCSub.cpp + NetworkOPs' mRpcSubMap).

`subscribe` with a `url` (admin-only) registers a server-side pusher:
every pub/sub event the subscription matches is POSTed to the client's
HTTP listener as a JSON-RPC request `{"method": "event", "params":
[event]}`, with a per-subscription monotonically increasing `seq`
stamped into the event (reference sendThread). Events queue up to 32
deep; on overflow the most recently queued event is dropped (the
reference's "drop the previous event" rule), never the oldest — a slow
listener sees a gap, not a stale stream. One daemon sender drains the
queue; delivery failures are logged and dropped (the reference retries
nothing either).
"""

from __future__ import annotations

import base64
import json
import logging
import threading
import urllib.request
from collections import deque
from typing import Optional
from urllib.parse import urlparse

from .infosub import InfoSub

__all__ = ["RpcSub"]

log = logging.getLogger("stellard.rpcsub")

EVENT_QUEUE_MAX = 32  # reference RPCSub eventQueueMax


class RpcSub(InfoSub):
    """An InfoSub whose sink is a remote JSON-RPC listener."""

    def __init__(self, url: str, username: str = "", password: str = ""):
        parsed = urlparse(url)
        if parsed.scheme not in ("http", "https"):
            raise ValueError("only http and https are supported")
        if not parsed.hostname:
            raise ValueError("url has no host")
        self.url = url
        self.username = username
        self.password = password
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._worker: Optional[threading.Thread] = None
        self._seq = 1
        self._closed = False
        super().__init__(send=self._enqueue)

    def set_credentials(self, username: str, password: str) -> None:
        with self._lock:
            self.username = username
            self.password = password

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._q.clear()
            self._cv.notify_all()

    # -- sink --------------------------------------------------------------

    def _enqueue(self, obj: dict) -> None:
        with self._lock:
            if self._closed:
                return
            if len(self._q) >= EVENT_QUEUE_MAX:
                # reference: drop the PREVIOUS (most recently queued)
                # event — older queued events keep their slot
                self._q.pop()
                log.warning("rpcsub %s: queue full, dropping an event",
                            self.url)
            ev = dict(obj)
            ev["seq"] = self._seq
            self._seq += 1
            self._q.append(ev)
            self._cv.notify()
            if self._worker is not None and self._worker.is_alive():
                return
            # ONE persistent sender per subscription (steady stream
            # traffic must not churn a thread per event)
            self._worker = threading.Thread(
                target=self._send_loop, name="rpcsub-send", daemon=True
            )
            self._worker.start()

    def _send_loop(self) -> None:
        while True:
            with self._lock:
                while not self._q and not self._closed:
                    self._cv.wait()
                if self._closed:
                    return
                ev = self._q.popleft()
                user, pw = self.username, self.password
            body = json.dumps(
                {"method": "event", "params": [ev]}
            ).encode()
            req = urllib.request.Request(
                self.url, data=body,
                headers={"Content-Type": "application/json"},
            )
            if user or pw:
                tok = base64.b64encode(f"{user}:{pw}".encode()).decode()
                req.add_header("Authorization", f"Basic {tok}")
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    resp.read()
            except Exception as exc:  # noqa: BLE001 — drop, like the reference
                log.info("rpcsub %s: delivery failed: %s", self.url, exc)
