"""Server-side transaction signing with autofill.

Reference: src/ripple_rpc/impl/TransactionSign.cpp — transactionSign
(:180) builds an STTx from tx_json, auto-fills Fee (load-scaled),
Sequence (from the open-ledger account state) and Flags, derives the
keypair from `secret`, signs, and optionally submits (:380).
"""

from __future__ import annotations

from ..protocol.keys import KeyPair, decode_seed, passphrase_to_seed
from ..protocol.sfields import (
    sfFee,
    sfSequence,
    sfSigningPubKey,
)
from ..protocol.stamount import STAmount
from ..protocol.stparsedjson import JsonParseError, parse_tx_json
from ..protocol.sttx import SerializedTransaction
from .errors import RPCError

__all__ = ["keypair_from_secret", "transaction_sign"]


def keypair_from_secret(secret: str) -> KeyPair:
    """A secret is a base58 seed (s...) or a passphrase (reference:
    RippleAddress::setSeedGeneric)."""
    try:
        return KeyPair.from_seed(decode_seed(secret))
    except (ValueError, KeyError):
        pass
    return KeyPair.from_seed(passphrase_to_seed(secret))


def transaction_sign(
    node, tx_json: dict, secret: str, build_path: bool = False
) -> SerializedTransaction:
    """Build + autofill + sign. Raises RPCError on malformed input.
    With build_path, a pathless cross-currency Payment gets a pathfinder
    path set attached (reference: TransactionSign.cpp bPath branch)."""
    if not isinstance(tx_json, dict):
        raise RPCError("invalidParams", "tx_json is not an object")
    if "Account" not in tx_json:
        raise RPCError("srcActMissing")
    try:
        obj = parse_tx_json(tx_json)
    except JsonParseError as exc:
        raise RPCError("invalidTransaction", str(exc)) from exc

    key = keypair_from_secret(secret)
    tx = SerializedTransaction(obj)

    ledger = node.ledger_master.current_ledger()

    # autofill Paths (reference: TransactionSign.cpp:195-224 — only for
    # a Payment that is not a plain native transfer and carries none)
    from ..protocol.formats import TxType as _TT
    from ..protocol.sfields import (
        sfAmount as _amt,
        sfDestination as _dst,
        sfPaths as _paths,
        sfSendMax as _smax,
        sfTransactionType as _tt,
    )

    if (
        build_path
        and obj.get(_tt) == int(_TT.ttPAYMENT)
        and _paths not in obj
    ):
        if _amt not in obj or _dst not in obj:
            raise RPCError(
                "invalidTransaction",
                "Payment needs Amount and Destination",
            )
        amount = obj[_amt]
        if not (amount.is_native and _smax not in obj):
            from ..paths.pathfinder import build_path_set
            from ..protocol.stobject import STPathSet

            found = build_path_set(
                ledger, tx.account, obj[_dst], amount,
                send_max=obj.get(_smax),
            )
            if found:
                obj[_paths] = STPathSet(found)

    # autofill Fee (reference: TransactionSign.cpp:225-240, load-scaled)
    if sfFee not in obj:
        obj[sfFee] = STAmount.from_drops(
            ledger.scale_fee_load(ledger.base_fee)
        )
    # autofill Sequence from the account root, bumped past any queued
    # open-ledger txns from the same account (reference :268-290)
    if sfSequence not in obj:
        acct = ledger.account_root(tx.account)
        if acct is None:
            raise RPCError("actNotFound", account=tx_json.get("Account"))
        from ..protocol.sfields import sfSequence as _seq

        obj[sfSequence] = predicted_sequence(ledger, tx.account, acct[_seq])

    # the secret must control the source account (master key path; regular
    # -key signing passes key authority checks at apply time)
    tx.sign(key)
    ok, why = tx.passes_local_checks()
    if not ok:
        raise RPCError("invalidTransaction", why)
    return tx


def predicted_sequence(ledger, account: bytes, account_seq: int) -> int:
    """Next usable sequence: account-root seq bumped past any queued
    open-ledger txns (reference walks the open tx map; here the ledger's
    per-account cache makes it O(1))."""
    cached = ledger.open_tx_seqs.get(account)
    if cached is not None and cached + 1 > account_seq:
        return cached + 1
    return account_seq
