"""WebSocket door: RFC 6455 server + command routing + pub/sub delivery.

Reference: src/ripple_app/websocket (WSDoor → WSServerHandler →
WSConnection over vendored websocketpp) — commands are JSON objects
{"command": ..., "id": ...} answered with {"result", "status", "type":
"response", "id"}; the connection doubles as an InfoSub sink receiving
stream messages. The frame layer here is a from-scratch RFC 6455
implementation (text frames, ping/pong, close), since the build vendors
no WebSocket library.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import json
import struct
import threading
from typing import Optional

from .handlers import Context, Role, dispatch
from .infosub import InfoSub, SubscriptionManager

__all__ = ["WsRpcServer"]

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
_MAX_MSG = 4 * 1024 * 1024


def _accept_key(key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((key + _WS_MAGIC).encode()).digest()
    ).decode()


def _encode_frame(opcode: int, payload: bytes) -> bytes:
    head = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        head += bytes([n])
    elif n < 65536:
        head += bytes([126]) + struct.pack(">H", n)
    else:
        head += bytes([127]) + struct.pack(">Q", n)
    return head + payload


async def _read_frame(reader: asyncio.StreamReader) -> tuple[int, bytes, bool]:
    """-> (opcode, payload, fin)"""
    b1, b2 = await reader.readexactly(2)
    fin = bool(b1 & 0x80)
    opcode = b1 & 0x0F
    masked = bool(b2 & 0x80)
    n = b2 & 0x7F
    if n == 126:
        (n,) = struct.unpack(">H", await reader.readexactly(2))
    elif n == 127:
        (n,) = struct.unpack(">Q", await reader.readexactly(8))
    if n > _MAX_MSG:
        raise ConnectionError("frame too large")
    mask = await reader.readexactly(4) if masked else b"\x00" * 4
    data = bytearray(await reader.readexactly(n))
    if masked:
        for i in range(len(data)):
            data[i] ^= mask[i & 3]
    return opcode, bytes(data), fin


class WsRpcServer:
    def __init__(self, node, host: str = "127.0.0.1", port: int = 0,
                 subs: Optional[SubscriptionManager] = None,
                 ssl_context=None):
        self._ssl = ssl_context  # reference [websocket_secure] (WSDoor SSL)
        self.node = node
        self.host = host
        self.port = port
        self.subs = subs or SubscriptionManager(node.ops)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._server = None

    # -- connection -------------------------------------------------------

    async def _handshake(self, reader, writer) -> bool:
        header = await reader.readuntil(b"\r\n\r\n")
        lines = header.decode("latin-1").split("\r\n")
        headers = {}
        for line in lines[1:]:
            if ":" in line:
                k, v = line.split(":", 1)
                headers[k.strip().lower()] = v.strip()
        key = headers.get("sec-websocket-key")
        if not key or "websocket" not in headers.get("upgrade", "").lower():
            writer.write(b"HTTP/1.1 400 Bad Request\r\n\r\n")
            await writer.drain()
            return False
        writer.write(
            b"HTTP/1.1 101 Switching Protocols\r\n"
            b"Upgrade: websocket\r\n"
            b"Connection: Upgrade\r\n"
            + f"Sec-WebSocket-Accept: {_accept_key(key)}\r\n\r\n".encode()
        )
        await writer.drain()
        return True

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        sub: Optional[InfoSub] = None
        try:
            if not await self._handshake(reader, writer):
                return

            send_lock = asyncio.Lock()
            loop = asyncio.get_running_loop()

            async def send_async(data: bytes) -> None:
                async with send_lock:
                    writer.write(_encode_frame(0x1, data))
                    await writer.drain()

            def send_json_threadsafe(msg: dict) -> None:
                # called from node threads (pub/sub fan-out)
                data = json.dumps(msg).encode()
                asyncio.run_coroutine_threadsafe(send_async(data), loop)

            from .http_server import _role_for_peer

            role = _role_for_peer(self.node, writer)
            peer = writer.get_extra_info("peername")
            client_ip = peer[0] if peer else ""
            # the sub carries its endpoint so per-close path-update
            # shedding/charging (paths/plane.py) keys the same balance
            # as the request door
            sub = InfoSub(send_json_threadsafe, client_ip=client_ip)

            buffer = b""
            while True:
                opcode, payload, fin = await _read_frame(reader)
                if opcode == 0x8:  # close
                    writer.write(_encode_frame(0x8, payload[:2]))
                    await writer.drain()
                    return
                if opcode == 0x9:  # ping
                    writer.write(_encode_frame(0xA, payload))
                    await writer.drain()
                    continue
                if opcode in (0x1, 0x2, 0x0):
                    if len(buffer) + len(payload) > _MAX_MSG:
                        raise ConnectionError("message too large")
                    buffer += payload
                    if not fin:
                        continue
                    message, buffer = buffer, b""
                    resp = await loop.run_in_executor(
                        None, self._process, message, sub, role, client_ip
                    )
                    await send_async(json.dumps(resp).encode())
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            if sub is not None:
                self.subs.remove(sub.id)
            writer.close()

    def _process(self, message: bytes, sub: InfoSub, role: Role,
                 client_ip: str = "") -> dict:
        """reference: WSConnection::invokeCommand — jtCLIENT job body.
        Non-admin commands charge the client's resource balance (same
        FEE_*_RPC schedule as the HTTP door); a client past the drop
        line gets rpcSLOW_DOWN until its balance decays."""
        from .handlers import charge_rpc_client

        try:
            req = json.loads(message)
        except ValueError:
            refused = charge_rpc_client(self.node, client_ip, None, role)
            if refused is not None:
                return {"type": "response", "status": "error",
                        "result": refused}
            return {"type": "error", "error": "jsonInvalid"}
        command = req.get("command")
        if not isinstance(command, str):
            refused = charge_rpc_client(self.node, client_ip, None, role)
            if refused is not None:
                return {"type": "response", "status": "error",
                        "result": refused}
            return {"type": "error", "error": "missingCommand"}
        params = {k: v for k, v in req.items() if k not in ("command", "id")}
        refused = charge_rpc_client(self.node, client_ip, command, role)
        if refused is not None:
            out = {"type": "response", "status": "error", "result": refused}
            if "id" in req:
                out["id"] = req["id"]
            return out
        result = dispatch(
            Context(node=self.node, params=params, role=role,
                    infosub=sub, subs=self.subs),
            command,
        )
        from .handlers import rpc_warning

        warn = rpc_warning(self.node, client_ip, role)
        if warn is not None:
            result["warning"] = warn
        status = "error" if "error" in result else "success"
        out = {"type": "response", "status": status, "result": result}
        if "id" in req:
            out["id"] = req["id"]
        return out

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "WsRpcServer":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rpc-ws")
        self._thread.start()
        self._started.wait(timeout=10)
        return self

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def boot():
            self._server = await asyncio.start_server(
                self._handle, self.host, self.port, limit=_MAX_MSG,
                ssl=self._ssl,
            )
            self.port = self._server.sockets[0].getsockname()[1]
            self._started.set()

        self._loop.run_until_complete(boot())
        try:
            self._loop.run_forever()
        finally:
            self._loop.close()

    def stop(self) -> None:
        if self._loop and self._loop.is_running():
            def _shutdown():
                if self._server:
                    self._server.close()
                self._loop.stop()

            self._loop.call_soon_threadsafe(_shutdown)
        if self._thread:
            self._thread.join(timeout=5)
