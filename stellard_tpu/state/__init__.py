"""Ledger state plane: SHAMap Merkle-radix tree, ledger, entry views.

Reference scope: src/ripple_app/shamap, src/ripple_app/ledger.
Design is TPU-first: the tree is a *persistent* (structurally shared)
functional radix tree — snapshots are O(1) and copy-on-write falls out of
immutability instead of the reference's sequence-number scheme
(src/ripple_app/shamap/SHAMap.h mSeq) — and node re-hashing is deferred and
level-synchronous so every close flushes one batched SHA-512 device call
per tree level instead of the reference's single-threaded recursive
updateHash (src/ripple_app/shamap/SHAMapTreeNode.cpp:253-295).
"""

from .shamap import SHAMap, SHAMapItem, TNType
from .ledger import Ledger
from .entryset import LedgerEntrySet

__all__ = ["SHAMap", "SHAMapItem", "TNType", "Ledger", "LedgerEntrySet"]
