"""The Stellar CLF layer: canonical-ledger persistence + typed SQL mirror.

Role parity with the reference's second (Stellar-specific) ledger plane
(/root/reference/src/ledger/): alongside the rippled-style NodeStore, every
ledger close is committed to a SQL database in one atomic transaction —

- ``StoreState``: the last-closed-ledger hash and its serialized header
  (LedgerDatabase.h:10-63 kLastClosedLedger/kLastClosedLedgerContent),
- typed row mirrors of the ledger entries: ``accounts`` / ``trustlines``
  / ``offers`` (AccountEntry/TrustLine/OfferEntry.cpp), updated from the
  SHAMap delta between the previous and new ledger (LedgerMaster::catchUp,
  LegacyCLF::getDeltaSince) or rebuilt from a full ledger walk
  (importLedgerState).

The scoped-transaction rule is the crash-safety contract
(LedgerDatabase.h ScopedTransaction): either the whole close lands (state
hash + rows) or none of it does, so a kill -9 mid-commit resumes from the
previous consistent ledger.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Optional

from ..protocol.formats import LedgerEntryType
from ..protocol.sfields import (
    sfAccount,
    sfBalance,
    sfFlags,
    sfHighLimit,
    sfLedgerEntryType as _LE_TYPE_FIELD,
    sfLowLimit,
    sfOwnerCount,
    sfRegularKey,
    sfSequence,
    sfTakerGets,
    sfTakerPays,
)
from ..protocol.stobject import STObject

__all__ = ["LedgerSqlDatabase", "CLFMirror"]

_SCHEMA = [
    "PRAGMA journal_mode=WAL;",
    "PRAGMA synchronous=NORMAL;",
    """CREATE TABLE IF NOT EXISTS StoreState (
        StateName TEXT PRIMARY KEY,
        State     BLOB
    );""",
    """CREATE TABLE IF NOT EXISTS accounts (
        account_id  TEXT PRIMARY KEY,
        balance     INTEGER,
        sequence    INTEGER,
        owner_count INTEGER,
        flags       INTEGER,
        regular_key TEXT
    );""",
    """CREATE TABLE IF NOT EXISTS trustlines (
        index_hex   TEXT PRIMARY KEY,
        low_account  TEXT,
        high_account TEXT,
        currency    TEXT,
        balance_str TEXT,
        low_limit   TEXT,
        high_limit  TEXT,
        flags       INTEGER
    );""",
    """CREATE TABLE IF NOT EXISTS offers (
        index_hex   TEXT PRIMARY KEY,
        account_id  TEXT,
        sequence    INTEGER,
        taker_pays  TEXT,
        taker_gets  TEXT,
        flags       INTEGER
    );""",
    "CREATE INDEX IF NOT EXISTS offers_by_account ON offers(account_id);",
    "CREATE INDEX IF NOT EXISTS lines_by_low ON trustlines(low_account);",
    "CREATE INDEX IF NOT EXISTS lines_by_high ON trustlines(high_account);",
]

K_LCL_HASH = "LastClosedLedger"
K_LCL_CONTENT = "LastClosedLedgerContent"


class LedgerSqlDatabase:
    """SQLite CLF store with explicit scoped transactions."""

    def __init__(self, path: str = ":memory:"):
        self.path = path
        # autocommit mode: transaction boundaries are ONLY the explicit
        # BEGIN/COMMIT of the scoped transaction (python sqlite3's
        # implicit-BEGIN magic would otherwise fight the scope)
        self._conn = sqlite3.connect(
            path, check_same_thread=False, isolation_level=None
        )
        self._lock = threading.RLock()
        with self._lock:
            for stmt in _SCHEMA:
                self._conn.execute(stmt)

    # -- state store ------------------------------------------------------

    def get_state(self, name: str) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute(
                "SELECT State FROM StoreState WHERE StateName=?", (name,)
            ).fetchone()
        return row[0] if row else None

    def set_state(self, name: str, value: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO StoreState (StateName, State) VALUES (?, ?)",
                (name, value),
            )

    # -- scoped transaction ----------------------------------------------

    def transaction(self):
        """`with db.transaction():` — commit on clean exit, rollback on
        exception (the reference ScopedTransaction contract)."""
        return _Scoped(self)

    # -- typed rows --------------------------------------------------------

    def store_entry(self, index: bytes, sle: STObject) -> None:
        letype = LedgerEntryType(sle[_LE_TYPE_FIELD])
        with self._lock:
            if letype == LedgerEntryType.ltACCOUNT_ROOT:
                self._conn.execute(
                    "INSERT OR REPLACE INTO accounts VALUES (?,?,?,?,?,?)",
                    (
                        sle[sfAccount].hex(),
                        sle[sfBalance].drops(),
                        sle.get(sfSequence, 0),
                        sle.get(sfOwnerCount, 0),
                        sle.get(sfFlags, 0),
                        (sle.get(sfRegularKey) or b"").hex(),
                    ),
                )
            elif letype == LedgerEntryType.ltRIPPLE_STATE:
                low = sle[sfLowLimit]
                high = sle[sfHighLimit]
                self._conn.execute(
                    "INSERT OR REPLACE INTO trustlines VALUES (?,?,?,?,?,?,?,?)",
                    (
                        index.hex(),
                        low.issuer.hex(),
                        high.issuer.hex(),
                        low.currency.hex(),
                        sle[sfBalance].value_text(),
                        low.value_text(),
                        high.value_text(),
                        sle.get(sfFlags, 0),
                    ),
                )
            elif letype == LedgerEntryType.ltOFFER:
                self._conn.execute(
                    "INSERT OR REPLACE INTO offers VALUES (?,?,?,?,?,?)",
                    (
                        index.hex(),
                        sle[sfAccount].hex(),
                        sle.get(sfSequence, 0),
                        repr(sle[sfTakerPays]),
                        repr(sle[sfTakerGets]),
                        sle.get(sfFlags, 0),
                    ),
                )
            # directory/amendment/fee singletons have no row mirror
            # (reference LedgerEntry::makeEntry returns null for them too)

    def delete_entry(self, index: bytes, sle: STObject) -> None:
        letype = LedgerEntryType(sle[_LE_TYPE_FIELD])
        with self._lock:
            if letype == LedgerEntryType.ltACCOUNT_ROOT:
                self._conn.execute(
                    "DELETE FROM accounts WHERE account_id=?",
                    (sle[sfAccount].hex(),),
                )
            elif letype == LedgerEntryType.ltRIPPLE_STATE:
                self._conn.execute(
                    "DELETE FROM trustlines WHERE index_hex=?", (index.hex(),)
                )
            elif letype == LedgerEntryType.ltOFFER:
                self._conn.execute(
                    "DELETE FROM offers WHERE index_hex=?", (index.hex(),)
                )

    def drop_all_entries(self) -> None:
        with self._lock:
            for table in ("accounts", "trustlines", "offers"):
                self._conn.execute(f"DELETE FROM {table}")

    def count(self, table: str) -> int:
        with self._lock:
            return self._conn.execute(f"SELECT COUNT(*) FROM {table}").fetchone()[0]

    def query(self, sql: str, args: tuple = ()) -> list:
        with self._lock:
            return self._conn.execute(sql, args).fetchall()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class _Scoped:
    def __init__(self, db: LedgerSqlDatabase):
        self.db = db

    def __enter__(self):
        self.db._lock.acquire()
        self.db._conn.execute("BEGIN")
        return self.db

    def __exit__(self, exc_type, exc, tb):
        try:
            if exc_type is None:
                self.db._conn.commit()
            else:
                self.db._conn.rollback()
        finally:
            self.db._lock.release()
        return False


class CLFMirror:
    """The stellar::LedgerMaster role: keep the SQL mirror in lockstep
    with the closed-ledger chain."""

    def __init__(self, db: LedgerSqlDatabase):
        self.db = db
        self.commits = 0
        self.full_imports = 0

    @property
    def last_closed_hash(self) -> Optional[bytes]:
        raw = self.db.get_state(K_LCL_HASH)
        return raw if raw else None

    # -- close commit -------------------------------------------------------

    def commit_ledger_close(self, new_ledger, prev_ledger=None) -> None:
        """One atomic SQL transaction: entry-row delta + LCL state
        (reference: commitLedgerClose → catchUp → updateDBFromLedger)."""
        stored = self.last_closed_hash
        if prev_ledger is None or stored != prev_ledger.hash():
            # mirror out of lockstep (fresh db, or we skipped ledgers):
            # rebuild from the full state walk
            self.import_ledger_state(new_ledger)
            return
        delta = new_ledger.state_map.compare(prev_ledger.state_map)
        with self.db.transaction():
            for tag, (new_item, old_item) in delta.items():
                # the engine pinned a parsed mirror on every item it
                # wrote (Ledger.write_entry); reuse it — re-parsing every
                # changed entry was the commit's dominant Python cost,
                # and on the close-pipeline worker it stole GIL time
                # from the next ledger's apply
                if new_item is not None:
                    sle = new_item.parsed
                    if sle is None:
                        sle = STObject.from_bytes(new_item.data)
                    self.db.store_entry(tag, sle)
                elif old_item is not None:
                    sle = old_item.parsed
                    if sle is None:
                        sle = STObject.from_bytes(old_item.data)
                    self.db.delete_entry(tag, sle)
            self._write_lcl_state(new_ledger)
        self.commits += 1

    def import_ledger_state(self, ledger) -> None:
        """Full rebuild (reference importLedgerState): drop rows, walk the
        whole state tree, then swap the LCL pointer — atomically."""
        with self.db.transaction():
            self.db.drop_all_entries()
            for item in ledger.state_map.items():
                sle = item.parsed
                if sle is None:
                    sle = STObject.from_bytes(item.data)
                self.db.store_entry(item.tag, sle)
            self._write_lcl_state(ledger)
        self.full_imports += 1

    def _write_lcl_state(self, ledger) -> None:
        self.db.set_state(K_LCL_HASH, ledger.hash())
        self.db.set_state(K_LCL_CONTENT, ledger.header_bytes())

    # -- resume -------------------------------------------------------------

    def load_last_known(self, nodestore, hash_batch=None, lazy=False):
        """reference loadLastKnownCLF: resume the chain from the SQL state
        pointer, rebuilding the ledger from the NodeStore; returns the
        Ledger or None when there is nothing (or something broken) saved.
        `lazy` opens the trees with on-demand node faulting (O(1) boot
        regardless of state size, out-of-core plane)."""
        from .ledger import Ledger

        lkcl = self.last_closed_hash
        if not lkcl:
            return None
        try:
            led = Ledger.load(nodestore, lkcl, hash_batch=hash_batch,
                              lazy=lazy)
        except (KeyError, ValueError):
            return None
        return led

    def get_json(self) -> dict:
        return {
            "last_closed": (self.last_closed_hash or b"").hex(),
            "accounts": self.db.count("accounts"),
            "trustlines": self.db.count("trustlines"),
            "offers": self.db.count("offers"),
            "commits": self.commits,
            "full_imports": self.full_imports,
        }
