"""LedgerEntrySet: transactional view over a ledger during tx application.

Reference: src/ripple_app/ledger/LedgerEntrySet.{h,cpp} (1.8k LoC) — entry
cache with CACHED/MODIFIED/DELETED/CREATED actions, directory-page
management (DIR_NODE_MAX=32, LedgerEntrySet.cpp:29,690-770 dirAdd,
:780-960 dirDelete), owner-count bookkeeping, and transaction-metadata
generation (calcRawMeta, LedgerEntrySet.cpp:1030-1160).

Because the underlying SHAMap is persistent, `apply()` simply writes the
final entries into the (cheap) current ledger — there is no undo machinery;
a failed transaction's entry set is dropped on the floor.
"""

from __future__ import annotations

from enum import IntEnum
from typing import Callable, Iterator, Optional

from ..protocol.formats import LedgerEntryType
from ..protocol.sfields import (
    sfAffectedNodes,
    sfCreatedNode,
    sfDeletedNode,
    sfFinalFields,
    sfIndexNext,
    sfIndexPrevious,
    sfIndexes,
    sfLedgerEntryType,
    sfLedgerIndex,
    sfModifiedNode,
    sfNewFields,
    sfOwnerCount,
    sfPreviousFields,
    sfPreviousTxnID,
    sfPreviousTxnLgrSeq,
    sfRootIndex,
    sfTransactionIndex,
    sfTransactionResult,
)
from ..protocol.stobject import STArray, STObject
from ..protocol.ter import TER
from . import indexes
from .ledger import Ledger

__all__ = ["LedgerEntrySet", "Action", "DIR_NODE_MAX"]

DIR_NODE_MAX = 32  # entries per directory page (LedgerEntrySet.cpp:29)

# Fields that always appear in metadata FinalFields/PreviousFields filters.
# The reference drives this off per-field metadata flags (SField sMD_*);
# here: everything except the entry type marker participates.
_META_SKIP = {sfLedgerEntryType}


class Action(IntEnum):
    """reference: LedgerEntryAction (LedgerEntrySet.h taaCACHED...)"""

    CACHED = 0
    MODIFIED = 1
    DELETED = 2
    CREATED = 3


class _Entry:
    __slots__ = ("sle", "action", "orig")

    def __init__(self, sle: Optional[STObject], action: Action,
                 orig: Optional[STObject]):
        self.sle = sle  # working copy (mutable)
        self.action = action
        self.orig = orig  # as read from the ledger (immutable baseline)


class LedgerEntrySet:
    def __init__(self, ledger: Ledger):
        self.ledger = ledger
        self._entries: dict[bytes, _Entry] = {}

    # -- entry cache ------------------------------------------------------

    def peek(self, index: bytes) -> Optional[STObject]:
        """Read-through cache; returns the working copy (mutate + call
        `modify` to record). reference: entryCache — a DELETED entry reads
        as absent (LedgerEntrySet.cpp getEntry taaDELETE arm)."""
        e = self._entries.get(index)
        if e is not None:
            return None if e.action == Action.DELETED else e.sle
        # orig is the SHARED pristine parse (never mutated here: it is
        # only compared/iterated for metadata deltas); the working copy
        # detaches from it
        orig = self.ledger.read_entry_pristine(index)
        if orig is None:
            return None
        work = orig.copy()
        self._entries[index] = _Entry(work, Action.CACHED, orig)
        return work

    def create(self, entry_type: LedgerEntryType, index: bytes) -> STObject:
        """reference: entryCreate (LedgerEntrySet.cpp:161-197) — create
        after delete collapses to a modify of the fresh object."""
        e = self._entries.get(index)
        sle = STObject()
        sle[sfLedgerEntryType] = int(entry_type)
        if e is not None:
            if e.action != Action.DELETED:
                raise ValueError(f"entry {index.hex()[:16]} already exists")
            e.sle = sle
            e.action = Action.MODIFIED
            return sle
        if self.ledger.read_entry_pristine(index) is not None:
            raise ValueError(f"entry {index.hex()[:16]} already in ledger")
        self._entries[index] = _Entry(sle, Action.CREATED, None)
        return sle

    def modify(self, index: bytes) -> None:
        """Mark a peeked entry dirty. reference: entryModify."""
        e = self._entries[index]
        if e.action == Action.CACHED:
            e.action = Action.MODIFIED
        elif e.action == Action.DELETED:
            raise ValueError("modify after delete")

    def erase(self, index: bytes) -> None:
        """reference: entryDelete"""
        e = self._entries.get(index)
        if e is None:
            if self.peek(index) is None:
                raise KeyError(index.hex())
            e = self._entries[index]
        if e.action == Action.CREATED:
            del self._entries[index]  # created then deleted: net nothing
        else:
            e.action = Action.DELETED

    def entries(self) -> Iterator[tuple[bytes, STObject, Action]]:
        for idx, e in self._entries.items():
            yield idx, e.sle, e.action

    # -- trial execution (reference: duplicate/swapWith, used by
    # RippleCalc to attempt a path and discard it on failure) -------------

    def duplicate(self) -> "LedgerEntrySet":
        dup = LedgerEntrySet(self.ledger)
        for idx, e in self._entries.items():
            dup._entries[idx] = _Entry(
                e.sle.copy() if e.sle is not None else None, e.action, e.orig
            )
        return dup

    def swap_with(self, other: "LedgerEntrySet") -> None:
        self._entries, other._entries = other._entries, self._entries

    # -- commit -----------------------------------------------------------

    def apply(self) -> None:
        """Write the dirty entries into the ledger (reference:
        LedgerEntrySet::apply)."""
        for idx, e in self._entries.items():
            if e.action in (Action.CREATED, Action.MODIFIED):
                self.ledger.write_entry(idx, e.sle)
            elif e.action == Action.DELETED:
                self.ledger.delete_entry(idx)

    # -- metadata ---------------------------------------------------------

    def calc_meta(self, result: TER, tx_index: int,
                  ledger_seq: int, txid: bytes) -> STObject:
        """Build TransactionMetaData (reference: calcRawMeta,
        LedgerEntrySet.cpp:1030-1160 + TransactionMeta).

        Threading: modified/deleted threaded entries get their
        PreviousTxnID/PreviousTxnLgrSeq advanced to this transaction,
        with the old values recorded in PreviousFields.
        """
        affected = STArray()
        for idx in sorted(self._entries):
            e = self._entries[idx]
            if e.action == Action.CACHED:
                continue
            if e.action == Action.MODIFIED and e.orig is not None and e.sle == e.orig:
                continue
            node = STObject()
            sle = e.sle if e.sle is not None else e.orig
            node[sfLedgerEntryType] = sle[sfLedgerEntryType]
            node[sfLedgerIndex] = idx

            if e.action == Action.CREATED:
                fields = STObject()
                for f, v in e.sle.fields():
                    if f not in _META_SKIP:
                        fields[f] = v
                if len(fields):
                    node[sfNewFields] = fields
                affected.append(sfCreatedNode, node)
            elif e.action == Action.DELETED:
                # PreviousFields: original values that were changed before
                # the delete (reference calcRawMeta DeletedNode arm)
                prevs = STObject()
                if e.orig is not None:
                    for f, v in e.orig.fields():
                        if f in _META_SKIP:
                            continue
                        if e.sle is not None and e.sle.get(f) != v:
                            prevs[f] = v
                if len(prevs):
                    node[sfPreviousFields] = prevs
                finals = STObject()
                for f, v in e.sle.fields():
                    if f not in _META_SKIP:
                        finals[f] = v
                if len(finals):
                    node[sfFinalFields] = finals
                affected.append(sfDeletedNode, node)
            else:  # MODIFIED
                # thread: advance PreviousTxnID on threaded entries
                if sfPreviousTxnID in e.sle:
                    if e.sle[sfPreviousTxnID] != txid:
                        e.sle[sfPreviousTxnID] = txid
                        e.sle[sfPreviousTxnLgrSeq] = ledger_seq
                prevs = STObject()
                if e.orig is not None:
                    for f, v in e.orig.fields():
                        if f in _META_SKIP:
                            continue
                        if e.sle.get(f) != v:
                            prevs[f] = v
                finals = STObject()
                for f, v in e.sle.fields():
                    if f not in _META_SKIP:
                        finals[f] = v
                if len(prevs):
                    node[sfPreviousFields] = prevs
                if len(finals):
                    node[sfFinalFields] = finals
                affected.append(sfModifiedNode, node)

        meta = STObject()
        meta[sfTransactionIndex] = tx_index
        meta[sfAffectedNodes] = affected
        meta[sfTransactionResult] = int(result) & 0xFF
        return meta

    # -- directories ------------------------------------------------------
    # A directory is a chain of ltDIR_NODE pages rooted at `root_index`,
    # each holding up to DIR_NODE_MAX entry indexes in sfIndexes; root
    # carries IndexPrevious = last page (reference dirAdd/dirDelete).

    def dir_add(self, root_index: bytes, entry_index: bytes,
                describe: Optional[Callable[[STObject, bool], None]] = None,
                ) -> tuple[TER, int]:
        """Append `entry_index`; returns (TER, page number)
        (reference: dirAdd, LedgerEntrySet.cpp:690-770)."""
        root = self.peek(root_index)
        if root is None:
            root = self.create(LedgerEntryType.ltDIR_NODE, root_index)
            root[sfRootIndex] = root_index
            if describe:
                describe(root, True)
            root[sfIndexes] = [entry_index]
            return TER.tesSUCCESS, 0

        page = root.get(sfIndexPrevious, 0)
        node_index = indexes.dir_node_index(root_index, page)
        node = self.peek(node_index) if page else root
        if node is None:  # corrupt chain: root points at a missing page
            return TER.tefBAD_LEDGER, 0
        idxs = list(node.get(sfIndexes, []))
        if len(idxs) < DIR_NODE_MAX:
            idxs.append(entry_index)
            node[sfIndexes] = idxs
            self.modify(node_index)
            return TER.tesSUCCESS, page

        new_page = page + 1
        if new_page >= 1 << 64:
            return TER.tecDIR_FULL, 0
        node[sfIndexNext] = new_page
        self.modify(node_index)
        root[sfIndexPrevious] = new_page
        self.modify(root_index)
        new_node = self.create(
            LedgerEntryType.ltDIR_NODE, indexes.dir_node_index(root_index, new_page)
        )
        new_node[sfRootIndex] = root_index
        if describe:
            describe(new_node, False)
        new_node[sfIndexes] = [entry_index]
        if page:
            new_node[sfIndexPrevious] = page
        return TER.tesSUCCESS, new_page

    def dir_delete(self, root_index: bytes, page: int,
                   entry_index: bytes) -> TER:
        """Remove `entry_index` from its page; unlink/delete empty pages
        (reference: dirDelete, LedgerEntrySet.cpp:780-960 — simplified:
        empty non-root pages are deleted and the chain relinked; an empty
        root with no other pages is deleted)."""
        node_index = indexes.dir_node_index(root_index, page)
        node = self.peek(node_index)
        if node is None:
            return TER.tefBAD_LEDGER
        idxs = list(node.get(sfIndexes, []))
        if entry_index not in idxs:
            return TER.tefBAD_LEDGER
        idxs.remove(entry_index)
        node[sfIndexes] = idxs
        self.modify(node_index)
        if idxs:
            return TER.tesSUCCESS

        # page is now empty
        if page == 0:
            root = node
            if not root.get(sfIndexPrevious, 0) and not root.get(sfIndexNext, 0):
                self.erase(root_index)
            return TER.tesSUCCESS

        prev_page = node.get(sfIndexPrevious, 0)
        next_page = node.get(sfIndexNext, 0)
        root = self.peek(root_index)
        prev_index = indexes.dir_node_index(root_index, prev_page)
        prev_node = self.peek(prev_index) if prev_page else root
        if prev_node is not None:
            if next_page:
                prev_node[sfIndexNext] = next_page
            else:
                prev_node.pop(sfIndexNext)
            self.modify(prev_index if prev_page else root_index)
        if next_page:
            next_index = indexes.dir_node_index(root_index, next_page)
            next_node = self.peek(next_index)
            if next_node is not None:
                if prev_page:
                    next_node[sfIndexPrevious] = prev_page
                else:
                    next_node.pop(sfIndexPrevious)
                self.modify(next_index)
        if root is not None and root.get(sfIndexPrevious, 0) == page:
            if prev_page:
                root[sfIndexPrevious] = prev_page
            else:
                root.pop(sfIndexPrevious)
            self.modify(root_index)
        self.erase(node_index)
        if (
            root is not None
            and not root.get(sfIndexes, [])
            and not root.get(sfIndexPrevious, 0)
            and not root.get(sfIndexNext, 0)
        ):
            self.erase(root_index)
        return TER.tesSUCCESS

    def dir_entries(self, root_index: bytes) -> Iterator[bytes]:
        """All entry indexes across the page chain (reference:
        dirFirst/dirNext)."""
        page = 0
        while True:
            node = self.peek(indexes.dir_node_index(root_index, page))
            if node is None:
                return
            for idx in node.get(sfIndexes, []):
                yield idx
            page = node.get(sfIndexNext, 0)
            if not page:
                return

    # -- account helpers --------------------------------------------------

    def account_root(self, account_id: bytes) -> Optional[STObject]:
        return self.peek(indexes.account_root_index(account_id))

    def adjust_owner_count(self, account_id: bytes, delta: int) -> None:
        """reference: LedgerEntrySet::incrementOwnerCount/decrement"""
        idx = indexes.account_root_index(account_id)
        sle = self.peek(idx)
        if sle is None:
            return
        sle[sfOwnerCount] = max(0, sle.get(sfOwnerCount, 0) + delta)
        self.modify(idx)
