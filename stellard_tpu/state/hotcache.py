"""HotNodeCache: the bounded hot-set for faulted SHAMap nodes.

The out-of-core state plane (doc/storage.md) keeps a ledger's tree on
disk and faults nodes into memory on first touch. This cache IS the
resident set: stubs in lazy trees hold nothing but a hash, so once a
faulted node ages out of here (and out of any live mutation path) the
garbage collector reclaims it and the next touch re-faults from the
NodeStore. That inversion — the cache owns residency, the tree owns
only identity — is what turns state size from a RAM problem into a
disk problem.

Three properties the plain TaggedCache (utils/taggedcache.py) lacked:

- **byte-bounded, not entry-bounded** (``[tree] cache_mb``): nodes are
  admitted with a size estimate (blob length + Python object overhead)
  and eviction runs until ``resident_bytes`` fits the budget — an
  entry count says nothing useful when leaves range from 100B SLEs to
  multi-KB directory pages;
- **single-flight faulting**: concurrent faults of the same hash share
  ONE store fetch and get the SAME node object back (per-key in-flight
  latches) — two RPC threads walking the same cold subtree must not
  double-parse or double-fetch, and object identity keeps the
  ``compare``/walk fast paths (``a is b``) effective across readers;
- **epoch-aware eviction** (the PR 9 readplane contract): every entry
  is stamped with the validated-seq epoch of its last touch, and
  eviction takes old-epoch entries first — the serving snapshot's
  working set (current epoch) survives a history scan that would
  otherwise flush it. Eviction is never *blocked* by an epoch: nodes
  remain in the store, so losing a cache entry costs a re-fault, never
  correctness; the epoch only orders the victims.

Counters ride ``get_counts.shamap_inner_cache``.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

__all__ = ["HotNodeCache"]

# per-node resident-size estimate: measured-ish Python costs on CPython
# 3.10 (object header + slots + the hash bytes the node pins). An inner
# additionally pins up to 16 stub objects once traversed; leaves pin
# their item blob. Estimates, not accounting — the bound they enforce
# is approximate by design (the oocsmoke gate checks real RSS).
_INNER_COST = 1200
_LEAF_BASE_COST = 300

# separate entry cap for EAGER from_store inserts: an eagerly-resolved
# inner pins its whole materialized subtree, which the per-node byte
# estimate cannot see — so eager entries keep the bounded-entry
# semantics of the TaggedCache they replaced (4096 entries, LRU) and
# only LAZY entries (whose pinning really is per-node) ride the
# cache_mb byte budget
EAGER_ENTRY_CAP = 4096


def node_cost(node, blob_len: int = 0) -> int:
    """Resident-byte estimate for a faulted node."""
    item = getattr(node, "item", None)
    if item is not None:  # leaf
        return _LEAF_BASE_COST + len(item.data)
    return _INNER_COST + blob_len


class HotNodeCache:
    """Byte-bounded, epoch-aware, single-flight node cache."""

    def __init__(self, name: str = "shamap_inners",
                 limit_bytes: int = 64 << 20):
        self.name = name
        self.limit_bytes = int(limit_bytes)
        # optional node tracer: faults emit `cache.fault` spans so a
        # cold-walk storm is visible on the timeline (node wires it)
        self.tracer = None
        self._lock = threading.Lock()
        # key -> [node, cost, epoch, eager] (mutable lists: hits
        # restamp the epoch in place — no per-hit tuple churn on the
        # fault-descent hot path); OrderedDict tail = most recent
        self._data: "OrderedDict[bytes, list]" = OrderedDict()
        self._inflight: dict[bytes, threading.Event] = {}
        self.resident_bytes = 0
        self.epoch = 0
        self._eager_count = 0
        # counters (get_counts.shamap_inner_cache)
        self.hits = 0
        self.misses = 0
        self.faults = 0          # loader invocations (store round-trips)
        self.fault_shared = 0    # faults answered by another thread's load
        self.evictions = 0
        self.evicted_bytes = 0
        self.epoch_first_evictions = 0  # victims taken for being old-epoch

    # -- configuration / epochs -------------------------------------------

    def set_limit(self, limit_bytes: int) -> None:
        with self._lock:
            self.limit_bytes = max(0, int(limit_bytes))
            self._evict_locked()

    def advance_epoch(self, epoch: int) -> None:
        """New validated seq published (rpc/readplane.py). Entries the
        new snapshot touches from here on are stamped with it; older
        stamps become preferred eviction victims."""
        with self._lock:
            if epoch > self.epoch:
                self.epoch = epoch

    # -- cache ops ---------------------------------------------------------

    def get(self, key: bytes):
        with self._lock:
            entry = self._data.get(key)
            if entry is None:
                self.misses += 1
                return None
            entry[2] = self.epoch
            self._data.move_to_end(key)
            self.hits += 1
            return entry[0]

    def put(self, key: bytes, node, blob_len: int = 0, *,
            cold: bool = False, eager: bool = False) -> None:
        """`cold` stamps the entry one epoch BEHIND current: faults from
        an explicitly cold walk (a historical-ledger RPC scan) become
        first-pass eviction victims, so they cannot thrash the serving
        snapshot's current-epoch working set even within one epoch —
        the mechanism behind the readplane epoch contract. A later hit
        promotes the entry to the current epoch (it proved shared).
        `eager` marks whole-subtree-pinning entries (see
        EAGER_ENTRY_CAP)."""
        cost = node_cost(node, blob_len)
        with self._lock:
            old = self._data.pop(key, None)
            if old is not None:
                self.resident_bytes -= old[1]
                if old[3]:
                    self._eager_count -= 1
            epoch = self.epoch - 1 if cold else self.epoch
            self._data[key] = [node, cost, epoch, eager]
            self.resident_bytes += cost
            if eager:
                self._eager_count += 1
            self._evict_locked()

    def get_or_load(self, key: bytes, loader: Callable[[bytes], tuple],
                    cold: bool = False):
        """Return the cached node for `key`, or run `loader(key)` exactly
        once across all concurrent callers. `loader` returns
        (node, blob_len); it may raise (KeyError: missing in store;
        ValueError: corrupt) — the error propagates to EVERY waiter of
        this flight and nothing is cached."""
        while True:
            ev = None
            with self._lock:
                entry = self._data.get(key)
                if entry is not None:
                    entry[2] = self.epoch
                    self._data.move_to_end(key)
                    self.hits += 1
                    return entry[0]
                self.misses += 1
                ev = self._inflight.get(key)
                if ev is None:
                    self._inflight[key] = ev = threading.Event()
                    mine = True
                else:
                    mine = False
            if not mine:
                # another thread is faulting this hash: wait for its
                # result, then re-check the cache (a failed load leaves
                # no entry — this caller retries the load itself, so a
                # transient error never poisons the key)
                ev.wait()
                with self._lock:
                    entry = self._data.get(key)
                    if entry is not None:
                        self.fault_shared += 1
                        # counted as a hit-by-wait, not a new fault
                        self.hits += 1
                        self.misses -= 1
                        return entry[0]
                continue
            try:
                self.faults += 1
                t0 = time.perf_counter()
                node, blob_len = loader(key)
                tr = self.tracer
                if tr is not None:
                    tr.complete("cache.fault", "state", t0,
                                time.perf_counter(), bytes=blob_len)
            except BaseException:
                with self._lock:
                    self._inflight.pop(key, None)
                ev.set()
                raise
            self.put(key, node, blob_len, cold=cold)
            with self._lock:
                self._inflight.pop(key, None)
            ev.set()
            return node

    # -- eviction ----------------------------------------------------------

    def _evict_locked(self) -> None:
        # pass 0: bound EAGER entries by count (each pins an unaccounted
        # whole subtree — TaggedCache-parity semantics for the eager
        # from_store role)
        if self._eager_count > EAGER_ENTRY_CAP:
            for key in [k for k, e in self._data.items() if e[3]]:
                if self._eager_count <= EAGER_ENTRY_CAP:
                    break
                _n, cost, _e, _eager = self._data.pop(key)
                self.resident_bytes -= cost
                self._eager_count -= 1
                self.evictions += 1
                self.evicted_bytes += cost
        if self.resident_bytes <= self.limit_bytes:
            return
        # pass 1: old-epoch entries in LRU order (the serving snapshot's
        # current-epoch working set survives a cold history scan)
        cur = self.epoch
        if any(e[2] < cur for e in self._data.values()):
            for key in [
                k for k, e in self._data.items() if e[2] < cur
            ]:
                if self.resident_bytes <= self.limit_bytes:
                    return
                _node, cost, _e, eager = self._data.pop(key)
                self.resident_bytes -= cost
                if eager:
                    self._eager_count -= 1
                self.evictions += 1
                self.evicted_bytes += cost
                self.epoch_first_evictions += 1
        # pass 2: pure LRU — current-epoch entries too, because the
        # byte bound always wins (re-faulting is cheap; OOM is not)
        while self.resident_bytes > self.limit_bytes and self._data:
            _key, (_node, cost, _e, eager) = self._data.popitem(last=False)
            self.resident_bytes -= cost
            if eager:
                self._eager_count -= 1
            self.evictions += 1
            self.evicted_bytes += cost

    # -- introspection -----------------------------------------------------

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self.resident_bytes = 0
            self._eager_count = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def get_json(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "size": len(self._data),
                # entry-count "target" kept for dashboard compatibility
                # with the TaggedCache this replaced; the real bound is
                # limit_bytes
                "target": self.limit_bytes,
                "limit_bytes": self.limit_bytes,
                "resident_bytes": self.resident_bytes,
                "epoch": self.epoch,
                "hits": self.hits,
                "misses": self.misses,
                "faults": self.faults,
                "fault_shared": self.fault_shared,
                "evictions": self.evictions,
                "evicted_bytes": self.evicted_bytes,
                "epoch_first_evictions": self.epoch_first_evictions,
            }
