"""Ledger-entry index calculators.

Each state-tree key is the SHA-512-half of a 2-byte namespace tag plus the
identifying fields (reference: src/ripple_app/ledger/Ledger.cpp:1497-1790,
namespace chars at src/ripple_data/protocol/LedgerFormats.h:80-93).
"""

from __future__ import annotations

from ..utils.hashes import sha512_half

__all__ = [
    "account_root_index",
    "offer_index",
    "owner_dir_index",
    "ripple_state_index",
    "dir_node_index",
    "book_base",
    "quality_index",
    "get_quality",
    "quality_next",
    "fee_index",
    "amendment_index",
    "skip_list_index",
    "skip_list_index_for",
]

# namespace tags (LedgerFormats.h:80-93)
_ACCOUNT = ord("a")
_DIR_NODE = ord("d")
_RIPPLE = ord("r")
_OFFER = ord("o")
_OWNER_DIR = ord("O")
_BOOK_DIR = ord("B")
_SKIP_LIST = ord("s")
_AMENDMENT = ord("f")
_FEE = ord("e")


def _idx(space: int, *parts: bytes) -> bytes:
    return sha512_half(space.to_bytes(2, "big") + b"".join(parts))


def account_root_index(account_id: bytes) -> bytes:
    """reference: Ledger::getAccountRootIndex (Ledger.cpp:1527)"""
    return _idx(_ACCOUNT, account_id)


def offer_index(account_id: bytes, sequence: int) -> bytes:
    """reference: Ledger::getOfferIndex (Ledger.cpp:1751)"""
    return _idx(_OFFER, account_id, sequence.to_bytes(4, "big"))


def owner_dir_index(account_id: bytes) -> bytes:
    """reference: Ledger::getOwnerDirIndex (Ledger.cpp:1762)"""
    return _idx(_OWNER_DIR, account_id)


def ripple_state_index(a: bytes, b: bytes, currency: bytes) -> bytes:
    """Trust-line key: low account first (reference:
    Ledger::getRippleStateIndex, Ledger.cpp:1772)."""
    lo, hi = (a, b) if a < b else (b, a)
    return _idx(_RIPPLE, lo, hi, currency)


def dir_node_index(dir_root: bytes, node_index: int) -> bytes:
    """reference: Ledger::getDirNodeIndex (Ledger.cpp:1733)"""
    if node_index == 0:
        return dir_root
    return _idx(_DIR_NODE, dir_root, node_index.to_bytes(8, "big"))


def quality_index(base: bytes, node_dir: int = 0) -> bytes:
    """Base index with the low 8 bytes replaced by big-endian `node_dir`
    (reference: Ledger::getQualityIndex, Ledger.cpp:1497)."""
    return base[:24] + node_dir.to_bytes(8, "big")


def get_quality(index: bytes) -> int:
    """reference: Ledger::getQuality (Ledger.cpp:1510)"""
    return int.from_bytes(index[24:32], "big")


def quality_next(base: bytes) -> bytes:
    """Smallest index with a strictly larger quality prefix
    (reference: Ledger::getQualityNext, Ledger.cpp:1515)."""
    v = int.from_bytes(base, "big") + (1 << 64)
    return v.to_bytes(32, "big")


def book_base(pays_currency: bytes, pays_issuer: bytes,
              gets_currency: bytes, gets_issuer: bytes) -> bytes:
    """Order-book directory base, quality zeroed (reference:
    Ledger::getBookBase, Ledger.cpp — note currency,currency,issuer,issuer
    field order)."""
    h = _idx(_BOOK_DIR, pays_currency, gets_currency, pays_issuer, gets_issuer)
    return quality_index(h, 0)


def fee_index() -> bytes:
    """reference: Ledger::getLedgerFeeIndex (Ledger.cpp:1537)"""
    return _idx(_FEE)


def amendment_index() -> bytes:
    """reference: Ledger::getLedgerAmendmentIndex (Ledger.cpp:1545)"""
    return _idx(_AMENDMENT)


def skip_list_index() -> bytes:
    """reference: Ledger::getLedgerHashIndex (Ledger.cpp:1553)"""
    return _idx(_SKIP_LIST)


def skip_list_index_for(ledger_seq: int) -> bytes:
    """Skip-list page holding hashes around `ledger_seq`
    (reference: Ledger::getLedgerHashIndex(seq), Ledger.cpp:1561)."""
    return _idx(_SKIP_LIST, (ledger_seq >> 16).to_bytes(4, "big"))
