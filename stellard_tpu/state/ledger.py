"""Ledger: one version of the replicated state.

Header + two SHAMaps (transaction map, account-state map), hash-compatible
with the reference (src/ripple_app/ledger/Ledger.cpp):

- header serialization: Ledger::addRaw (Ledger.cpp:1182-1196) — seq,
  totCoins, feePool, inflationSeq, parentHash, txHash, accountHash,
  parentCloseTime, closeTime, closeResolution, closeFlags,
- ledger hash = SHA512half(HP_LEDGER_MASTER || header),
- genesis: root account funded with SYSTEM_CURRENCY_START = 10^17 stroops
  (Config.h:37-40), seq 1 (Ledger.cpp:29-66).

Closing a ledger is functional: `close()` snapshots into an immutable
closed ledger and the caller opens a successor with `open_successor()` —
the persistent SHAMap makes both O(1).
"""

from __future__ import annotations

from typing import Callable, Optional

from ..nodestore.core import Database, NodeObjectType
from ..protocol.serializer import Serializer
from ..protocol.sfields import (
    sfBalance,
    sfSequence,
)
from ..protocol.stobject import STObject
from ..utils.hashes import HP_LEDGER_MASTER, HP_TXN_ID, prefix_hash
from . import indexes
from .shamap import SHAMap, SHAMapItem, TNType

__all__ = [
    "Ledger",
    "SYSTEM_CURRENCY_START",
    "LEDGER_TIME_ACCURACY",
    "parse_header",
]


def strip_ledger_prefix(body: bytes) -> bytes:
    """Drop the HP_LEDGER_MASTER domain prefix when present — stored
    ledger-header blobs carry it (save() above), wire headers do not."""
    if len(body) >= 4 and int.from_bytes(body[:4], "big") == HP_LEDGER_MASTER:
        return body[4:]
    return body


def parse_header(blob: bytes) -> dict:
    """Decode Ledger::addRaw header bytes — the single reader for the
    layout header_bytes() writes (reference: Ledger.cpp:1182-1196)."""
    from ..protocol.serializer import BinaryParser

    p = BinaryParser(blob)
    return {
        "seq": p.read32(),
        "tot_coins": p.read64(),
        "fee_pool": p.read64(),
        "inflation_seq": p.read32(),
        "parent_hash": p.read(32),
        "tx_hash": p.read(32),
        "account_hash": p.read(32),
        "parent_close_time": p.read32(),
        "close_time": p.read32(),
        "close_resolution": p.read8(),
        "close_flags": p.read8(),
    }

# reference: Config.h:37-40
SYSTEM_CURRENCY_START = 1000 * 100_000_000 * 1_000_000
# reference: LedgerTiming.h:47
LEDGER_TIME_ACCURACY = 30

# default fee schedule (reference: Config.cpp:30-34,127-139)
DEFAULT_BASE_FEE = 10
DEFAULT_REFERENCE_FEE_UNITS = 10
DEFAULT_RESERVE_BASE = 200 * 1_000_000
DEFAULT_RESERVE_INCREMENT = 50 * 1_000_000


class Ledger:
    def __init__(
        self,
        seq: int,
        parent_hash: bytes = b"\x00" * 32,
        tot_coins: int = SYSTEM_CURRENCY_START,
        fee_pool: int = 0,
        inflation_seq: int = 1,
        close_time: int = 0,
        parent_close_time: int = 0,
        close_resolution: int = LEDGER_TIME_ACCURACY,
        close_flags: int = 0,
        tx_map: Optional[SHAMap] = None,
        state_map: Optional[SHAMap] = None,
        hash_batch: Optional[Callable] = None,
    ):
        self.seq = seq
        self.parent_hash = parent_hash
        self.tot_coins = tot_coins
        self.fee_pool = fee_pool
        self.inflation_seq = inflation_seq
        self.close_time = close_time
        self.parent_close_time = parent_close_time
        self.close_resolution = close_resolution
        self.close_flags = close_flags
        kw = {"hash_batch": hash_batch} if hash_batch else {}
        self.tx_map = tx_map or SHAMap(TNType.TX_MD, **kw)
        self.state_map = state_map or SHAMap(TNType.ACCOUNT_STATE, **kw)
        self.closed = False
        self.accepted = False
        self.validated = False
        # per-account highest open-ledger tx sequence (O(1) seq prediction
        # for Transactor::checkSeq; maintained by the engine via
        # note_open_tx)
        self.open_tx_seqs: dict[bytes, int] = {}
        # fee schedule (reference: Ledger::updateFees)
        self.base_fee = DEFAULT_BASE_FEE
        self.reference_fee_units = DEFAULT_REFERENCE_FEE_UNITS
        self.reserve_base = DEFAULT_RESERVE_BASE
        self.reserve_increment = DEFAULT_RESERVE_INCREMENT
        self.load_factor = 256  # 256 = no load escalation (LoadFeeTrack)
        # txid -> parsed SerializedTransaction memo: the close path
        # parses each tx once and persist/publish reuse the object
        # instead of re-parsing the blob per consumer (the reference
        # passes SerializedTransaction::pointer around for the same
        # reason). Seeded by close_and_advance; consulted via parse_tx.
        self.parsed_txs: dict[bytes, object] = {}
        # txid -> parsed meta STObject, seeded by the engine as it
        # builds each meta so persist/publish never re-parse meta blobs
        self.parsed_metas: dict[bytes, object] = {}

    # -- genesis ----------------------------------------------------------

    @classmethod
    def genesis(cls, root_account_id: bytes,
                start_amount: int = SYSTEM_CURRENCY_START,
                close_time: int = 0,
                hash_batch: Optional[Callable] = None) -> "Ledger":
        """First ledger: all coins in the root account
        (reference: Ledger.cpp:29-66, Application.cpp startNewLedger)."""
        led = cls(seq=1, tot_coins=start_amount, close_time=close_time,
                  hash_batch=hash_batch)
        sle = STObject()
        from ..protocol.sfields import sfAccount, sfLedgerEntryType
        from ..protocol.formats import LedgerEntryType
        from ..protocol.stamount import STAmount

        sle[sfLedgerEntryType] = int(LedgerEntryType.ltACCOUNT_ROOT)
        sle[sfAccount] = root_account_id
        sle[sfBalance] = STAmount.from_drops(start_amount)
        sle[sfSequence] = 1
        from ..protocol.sfields import sfFlags, sfOwnerCount, sfPreviousTxnID, sfPreviousTxnLgrSeq

        sle[sfFlags] = 0
        sle[sfOwnerCount] = 0
        sle[sfPreviousTxnID] = b"\x00" * 32
        sle[sfPreviousTxnLgrSeq] = 0
        led.write_entry(indexes.account_root_index(root_account_id), sle)
        return led

    # -- header / hashing -------------------------------------------------

    def header_bytes(self) -> bytes:
        """reference: Ledger::addRaw (Ledger.cpp:1182-1196)"""
        s = Serializer()
        s.add32(self.seq)
        s.add64(self.tot_coins)
        s.add64(self.fee_pool)
        s.add32(self.inflation_seq)
        s.add_raw(self.parent_hash)
        s.add_raw(self.tx_map.get_hash())
        s.add_raw(self.state_map.get_hash())
        s.add32(self.parent_close_time)
        s.add32(self.close_time)
        s.add8(self.close_resolution)
        s.add8(self.close_flags)
        return s.data()

    def hash(self) -> bytes:
        return prefix_hash(HP_LEDGER_MASTER, self.header_bytes())

    @property
    def tx_hash(self) -> bytes:
        return self.tx_map.get_hash()

    @property
    def account_hash(self) -> bytes:
        return self.state_map.get_hash()

    # -- state entries (SLEs) --------------------------------------------

    def read_entry_pristine(self, index: bytes) -> Optional[STObject]:
        """Shared parsed entry (the reference's SLE cache role): one
        parse per immutable SHAMapItem, shared across ledger versions
        that alias the item. Callers MUST NOT mutate the result."""
        item = self.state_map.get(index)
        if item is None:
            return None
        if item.parsed is None:
            item.parsed = STObject.from_bytes(item.data)
        return item.parsed

    def read_entry(self, index: bytes) -> Optional[STObject]:
        sle = self.read_entry_pristine(index)
        return None if sle is None else sle.copy()

    def write_entry(self, index: bytes, sle: STObject) -> None:
        # Pin the just-written object as the item's parsed mirror: both
        # call sites (LedgerEntrySet.apply after calc_meta's threading
        # mutations, and the genesis writer) are done mutating `sle`,
        # and the mirror equals the item bytes by construction
        # (data IS sle.serialize()). Hot accounts are re-read by the
        # very next transaction, which otherwise re-parses every
        # written entry (~2 parses/tx on the payment workloads).
        item = SHAMapItem(index, sle.serialize())
        item.parsed = sle
        self.state_map.set_item(item)

    def delete_entry(self, index: bytes) -> None:
        self.state_map.del_item(index)

    def account_root(self, account_id: bytes) -> Optional[STObject]:
        return self.read_entry(indexes.account_root_index(account_id))

    # -- fees / reserves --------------------------------------------------

    def reserve(self, owner_count: int) -> int:
        """reference: Ledger::getReserve (Ledger.h:446-451)"""
        return self.reserve_base + owner_count * self.reserve_increment

    def scale_fee_base(self, fee: int) -> int:
        """reference: Ledger::scaleFeeBase — fee units → drops. With the
        default schedule (base_fee == reference_fee_units scaling) this is
        identity; kept as the seam for fee voting."""
        return fee

    def scale_fee_load(self, fee: int, admin: bool = False) -> int:
        """reference: Ledger::scaleFeeLoad via LoadFeeTrack — the load
        multiplier hooks in here (node runtime, stage 5); admin traffic is
        never load-scaled."""
        if admin:
            return fee
        return fee * self.load_factor // 256 if self.load_factor > 256 else fee

    # -- transactions -----------------------------------------------------

    def add_open_transaction(self, tx_blob: bytes) -> tuple[bytes, bool]:
        """Record a tx (no metadata) in an OPEN ledger's tx map
        (reference: Ledger::addTransaction(txID, s) — item data is the raw
        blob, node type tnTRANSACTION_NM). Returns (txid, added) — added is
        False if already present (tefALREADY race)."""
        txid = prefix_hash(HP_TXN_ID, tx_blob)
        if self.tx_map.get(txid) is not None:
            return txid, False
        self.tx_map.set_item(SHAMapItem(txid, tx_blob), TNType.TX_NM)
        return txid, True

    def note_open_tx(self, account: bytes, sequence: int) -> None:
        """Record an accepted open-ledger tx for O(1) sequence prediction."""
        cur = self.open_tx_seqs.get(account)
        if cur is None or sequence > cur:
            self.open_tx_seqs[account] = sequence

    @staticmethod
    def tx_item_data(tx_blob: bytes, metadata: bytes) -> bytes:
        """The TX_MD item payload: VL(tx) ‖ VL(metadata) — the ONE place
        that writes this layout (tx_entries/get_transaction read it).
        Shared by add_transaction and the delta-replay splice's batched
        tx-map inserts."""
        s = Serializer()
        s.add_vl(tx_blob)
        s.add_vl(metadata)
        return s.data()

    def add_transaction(self, tx_blob: bytes, metadata: bytes) -> bytes:
        """Insert a tx + its metadata into the tx map (reference:
        Ledger::addTransaction w/ metadata — item data is
        VL(tx) || VL(metadata), tag is the tx ID)."""
        txid = prefix_hash(HP_TXN_ID, tx_blob)
        self.tx_map.set_item(
            SHAMapItem(txid, self.tx_item_data(tx_blob, metadata)),
            TNType.TX_MD,
        )
        return txid

    def record_transaction(self, tx_blob: bytes, meta) -> bytes:
        """Close-path insert of a tx + its PARSED meta: serializes the
        meta into the tx map and memoizes the object for persist/publish
        (the speculative view overrides this to skip a serialization its
        scratch map would discard)."""
        txid = self.add_transaction(tx_blob, meta.serialize())
        self.parsed_metas[txid] = meta
        return txid

    def tx_entries(self):
        """Yield (txid, tx_blob, meta_blob) for every tx in this ledger —
        the one place that knows the TX_MD item layout VL(tx) || VL(meta)
        (open-ledger TX_NM items yield meta b\"\")."""
        from ..protocol.serializer import BinaryParser

        for leaf in self.tx_map.leaves():
            blob, meta = leaf.item.data, b""
            if leaf.type == TNType.TX_MD:
                p = BinaryParser(blob)
                blob, meta = p.read_vl(), p.read_vl()
            yield leaf.item.tag, blob, meta

    def parse_tx(self, txid: bytes, blob: bytes):
        """Parsed-transaction memo over tx_entries blobs."""
        tx = self.parsed_txs.get(txid)
        if tx is None:
            from ..protocol.sttx import SerializedTransaction

            tx = SerializedTransaction.from_bytes(blob)
            self.parsed_txs[txid] = tx
        return tx

    def get_transaction(self, txid: bytes) -> Optional[tuple[bytes, bytes]]:
        """-> (tx_blob, metadata) or None. Open-ledger items (raw blob, no
        metadata) return (blob, b"")."""
        leaf = self.tx_map.get_leaf(txid)
        if leaf is None:
            return None
        if leaf.type == TNType.TX_NM:
            return leaf.item.data, b""
        from ..protocol.serializer import BinaryParser

        p = BinaryParser(leaf.item.data)
        return p.read_vl(), p.read_vl()

    # -- lifecycle --------------------------------------------------------

    @staticmethod
    def round_close_time(close_time: int, close_resolution: int) -> int:
        """Round to the NEAREST resolution step
        (reference: Ledger::roundCloseTime, Ledger.cpp:1966-1973)."""
        if close_time == 0:
            return 0
        close_time += close_resolution // 2
        return close_time - (close_time % close_resolution)

    def close(self, close_time: int, close_resolution: int,
              correct_close_time: bool = True) -> None:
        """Seal this ledger (reference: Ledger::setAccepted,
        Ledger.cpp:330-340 — rounds the close time to the ledger's
        resolution unless consensus did not agree on a close time, in
        which case sLCF_NoConsensusTime is flagged)."""
        if correct_close_time:
            self.close_time = self.round_close_time(close_time, close_resolution)
        else:
            self.close_time = close_time
        self.close_resolution = close_resolution
        self.close_flags = 0 if correct_close_time else 1
        self.closed = True

    def open_successor(self) -> "Ledger":
        """Open ledger on top of this closed one (reference:
        Ledger::Ledger(bool, Ledger&) — shares the state map snapshot,
        fresh tx map)."""
        child = Ledger(
            seq=self.seq + 1,
            parent_hash=self.hash(),
            tot_coins=self.tot_coins,
            fee_pool=self.fee_pool,
            inflation_seq=self.inflation_seq,
            parent_close_time=self.close_time,
            close_resolution=self.close_resolution,
            tx_map=SHAMap(TNType.TX_MD, hash_batch=self.tx_map.hash_batch),
            state_map=self.state_map.snapshot(),
        )
        child.base_fee = self.base_fee
        child.reference_fee_units = self.reference_fee_units
        child.reserve_base = self.reserve_base
        child.reserve_increment = self.reserve_increment
        child.load_factor = self.load_factor
        return child

    def snapshot(self) -> "Ledger":
        """O(1) copy (both maps persistent)."""
        led = Ledger(
            seq=self.seq,
            parent_hash=self.parent_hash,
            tot_coins=self.tot_coins,
            fee_pool=self.fee_pool,
            inflation_seq=self.inflation_seq,
            close_time=self.close_time,
            parent_close_time=self.parent_close_time,
            close_resolution=self.close_resolution,
            close_flags=self.close_flags,
            tx_map=self.tx_map.snapshot(),
            state_map=self.state_map.snapshot(),
        )
        led.closed = self.closed
        led.accepted = self.accepted
        led.validated = self.validated
        led.open_tx_seqs = dict(self.open_tx_seqs)
        led.base_fee = self.base_fee
        led.reference_fee_units = self.reference_fee_units
        led.reserve_base = self.reserve_base
        led.reserve_increment = self.reserve_increment
        led.load_factor = self.load_factor
        return led

    # -- persistence ------------------------------------------------------

    def save(self, db: Database) -> bytes:
        """Persist both trees + the header into the NodeStore (reference:
        consensus flushDirty + Ledger::pendSaveValidated; header stored as
        hotLEDGER under the ledger hash). Uses the store's `flushed` set so
        repeated saves only write the delta; node blobs come off the
        shared flat-buffer encoding and are handed through the packed
        door AS-IS — (hashes, buf, offsets), blob == hashed bytes — so
        a log-structured backend lands the whole delta as one segment
        append (other backends decode once inside the façade)."""
        self.state_map.flush(
            db.store_fn(NodeObjectType.ACCOUNT_NODE), db.flushed,
            store_packed=db.store_packed_fn(NodeObjectType.ACCOUNT_NODE),
        )
        self.tx_map.flush(
            db.store_fn(NodeObjectType.TRANSACTION_NODE), db.flushed,
            store_packed=db.store_packed_fn(NodeObjectType.TRANSACTION_NODE),
        )
        h = self.hash()
        # the header rides the same SYNCHRONOUS door as the trees: the
        # close pipeline commits txdb/CLF right after save() returns,
        # and a header blob parked in the async write-behind queue at
        # that moment would be lost by a crash — leaving a CLF-covered
        # ledger whose root object never resolves
        blob = HP_LEDGER_MASTER.to_bytes(4, "big") + self.header_bytes()
        db.store_packed(NodeObjectType.LEDGER, [h], blob, [0, len(blob)])
        return h

    @classmethod
    def load(cls, db: Database, ledger_hash: bytes,
             hash_batch: Optional[Callable] = None,
             lazy: bool = False, cold: bool = False) -> "Ledger":
        """Rebuild a ledger (header + both trees) from the NodeStore —
        the checkpoint/resume path (reference: Application loadOldLedger,
        Ledger::Ledger(blob) Ledger.cpp:120-175).

        With `lazy` (the out-of-core plane) only the header and the two
        tree ROOTS are read now; every other node is a hash-only stub
        that faults from this store through the bounded hot-node cache
        on first touch. Opening a million-account ledger is O(1); the
        eager path's whole-tree hash re-verification is traded for
        per-node content verification at fault time (the same check,
        paid lazily)."""
        obj = db.fetch(ledger_hash)
        if obj is None:
            raise KeyError(f"missing ledger {ledger_hash.hex()}")
        body = obj.data
        if int.from_bytes(body[:4], "big") == HP_LEDGER_MASTER:
            body = body[4:]
        f = parse_header(body)

        fetched: set[bytes] = set()

        def fetch(h: bytes) -> Optional[bytes]:
            o = db.fetch(h)
            if o is not None:
                fetched.add(h)
            return o.data if o else None

        kw: dict = {"hash_batch": hash_batch} if hash_batch else {}
        if lazy:
            def fetch(h: bytes) -> Optional[bytes]:  # noqa: F811
                o = db.fetch(h)
                return o.data if o else None

            # store_known=db.flushed marks the trees as backed by THIS
            # store: flushing them (or descendants sharing their
            # subtrees) back into it never faults clean cold branches
            kw.update(lazy=True, store_known=db.flushed, cold=cold)
        led = cls(
            seq=f["seq"],
            parent_hash=f["parent_hash"],
            tot_coins=f["tot_coins"],
            fee_pool=f["fee_pool"],
            inflation_seq=f["inflation_seq"],
            close_time=f["close_time"],
            parent_close_time=f["parent_close_time"],
            close_resolution=f["close_resolution"],
            close_flags=f["close_flags"],
            tx_map=SHAMap.from_store(f["tx_hash"], fetch, TNType.TX_MD, **kw),
            state_map=SHAMap.from_store(f["account_hash"], fetch,
                                        TNType.ACCOUNT_STATE, **kw),
        )
        led.closed = True
        if led.hash() != ledger_hash:
            raise ValueError(
                f"ledger hash mismatch after load: want {ledger_hash.hex()} "
                f"got {led.hash().hex()}"
            )
        if not lazy:
            # only after the full tree verified do the fetched nodes
            # count as known-good in this store (a corrupt node must
            # stay rewritable); the lazy path never claims this — each
            # node verifies at fault time instead
            db.flushed.update(fetched)
        return led
