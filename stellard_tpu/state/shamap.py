"""SHAMap: 16-ary Merkle-radix tree over 256-bit keys.

Hash/wire compatible with the reference
(src/ripple_app/shamap/SHAMapTreeNode.cpp:253-295 updateHash,
:305-395 addRaw; src/ripple_app/shamap/SHAMapNodeID.cpp:147-176
selectBranch):

- inner node hash  = SHA512half(HP_INNER_NODE || 16 child hashes);
  an inner with no branches hashes to zero,
- tx leaf (no md)  = SHA512half(HP_TXN_ID || data)          (== the tx ID),
- tx leaf (w/ md)  = SHA512half(HP_TX_NODE || data || tag),
- state leaf       = SHA512half(HP_LEAF_NODE || data || tag).

Architecture differences from the reference (deliberate, TPU-first):

- **Persistent tree.** Nodes are immutable; every mutation returns a new
  root sharing unchanged subtrees. `snapshot()` is O(1); the reference's
  copy-on-write sequence numbers (SHAMap.h mSeq) and its mutable-node
  locking disappear.
- **Deferred, level-synchronous hashing.** Mutations never hash. Hashes are
  computed on demand by grouping all unhashed nodes by tree depth and
  hashing each level in ONE batched call through a pluggable `BatchHasher`
  (crypto.backend) — deepest level first, so parents always see hashed
  children. On TPU that is one device program per level over thousands of
  nodes, replacing the reference's per-node OpenSSL calls inside recursive
  flushDirty.
"""

from __future__ import annotations

from enum import IntEnum
from typing import Callable, Iterator, Optional

from ..utils.hashes import (
    HP_INNER_NODE,
    HP_LEAF_NODE,
    HP_TXN_ID,
    HP_TX_NODE,
    prefix_hash,
)

__all__ = [
    "TNType", "SHAMapItem", "SHAMap", "Leaf", "Inner",
    "Stub", "LazyInner", "NodeSource", "MissingNodeError",
    "resolve_node",
    "encode_nodes", "inner_node_cache", "configure_inner_cache",
]


class MissingNodeError(KeyError):
    """A tree node could not be fetched from the store. On lazy trees
    this can surface MID-WALK, long after the tree opened — e.g. an
    online-deletion sweep retired a cached historical ledger's nodes —
    so consumers that used to rely on Ledger.load's all-or-nothing
    materialization catch THIS (rpc dispatch maps it to lgrNotFound;
    the overlay serving path answers with silence) instead of leaking
    a bare KeyError."""


ZERO256 = b"\x00" * 32


class TNType(IntEnum):
    """Node types (reference: SHAMapTreeNode.h:47-53). The numeric values
    double as the wire-format trailer byte for leaves (addRaw snfWIRE)."""

    INNER = 1
    TX_NM = 2  # transaction, no metadata (tx map of an open ledger)
    TX_MD = 3  # transaction + metadata (tx map of a closed ledger)
    ACCOUNT_STATE = 4  # state map leaf


# wire-format trailer bytes (reference addRaw: snfWIRE)
_WIRE_TX_NM = 0
_WIRE_STATE = 1
_WIRE_INNER_FULL = 2
_WIRE_INNER_COMPRESSED = 3
_WIRE_TX_MD = 4

_LEAF_PREFIX = {
    TNType.TX_NM: HP_TXN_ID,
    TNType.TX_MD: HP_TX_NODE,
    TNType.ACCOUNT_STATE: HP_LEAF_NODE,
}


class SHAMapItem:
    """A keyed blob: 32-byte tag (index) + serialized payload
    (reference: src/ripple_app/shamap/SHAMapItem.h).

    ``parsed`` memoizes the deserialized STObject for this (immutable)
    blob — writes always construct fresh items, so the pristine parse
    can be shared across the persistent-map versions that alias the
    item (the reference's SLE cache role); consumers must COPY before
    mutating (Ledger.read_entry does)."""

    __slots__ = ("tag", "data", "parsed")

    def __init__(self, tag: bytes, data: bytes):
        assert len(tag) == 32
        self.tag = tag
        self.data = data
        self.parsed = None

    def __eq__(self, other):
        return (
            isinstance(other, SHAMapItem)
            and self.tag == other.tag
            and self.data == other.data
        )

    def __repr__(self):
        return f"SHAMapItem({self.tag.hex()[:16]}…, {len(self.data)}B)"


class Leaf:
    """Immutable leaf node. `_hash` is a lazily-filled, write-once cache —
    the only mutable slot, so sharing across snapshots stays safe."""

    __slots__ = ("item", "type", "_hash")

    def __init__(self, item: SHAMapItem, type: TNType, hash: Optional[bytes] = None):
        self.item = item
        self.type = type
        self._hash = hash

    def hash_payload(self) -> tuple[int, bytes]:
        """(prefix, payload) whose prefixed SHA-512-half is this node's hash
        (reference: SHAMapTreeNode.cpp updateHash leaf arms)."""
        prefix = _LEAF_PREFIX[self.type]
        if self.type == TNType.TX_NM:
            return prefix, self.item.data
        return prefix, self.item.data + self.item.tag


class Inner:
    """Immutable inner node: 16 child slots."""

    __slots__ = ("children", "_hash")

    def __init__(self, children: tuple, hash: Optional[bytes] = None):
        self.children = children  # tuple of 16 × (Leaf | Inner | None)
        self._hash = hash

    def is_empty(self) -> bool:
        return all(c is None for c in self.children)

    def branch_count(self) -> int:
        return sum(1 for c in self.children if c is not None)


EMPTY_INNER = Inner((None,) * 16, hash=ZERO256)


# --------------------------------------------------------------------------
# out-of-core faulting: Stub / LazyInner / NodeSource (doc/storage.md)
#
# A lazy tree holds *unmaterialized* child slots: a `Stub` knows only a
# node hash and the `NodeSource` to fault it from. Stubs always carry a
# hash (`_hash` is set at construction), so every hash-driven fast path
# — compute_hashes skipping sealed subtrees, compare's hash
# short-circuit, encode_nodes reading child hashes — works on a stub
# without touching the store. Only an actual *descent* through the slot
# faults, and the faulted node lives in the process-wide HotNodeCache
# (state/hotcache.py), NOT in the tree: the slot keeps its stub, so
# evicting the cache entry really frees the node and the resident set
# stays bounded by `[tree] cache_mb` regardless of state size.


class Stub:
    """Unmaterialized child slot: hash + where to fault it from."""

    __slots__ = ("_hash", "source")

    def __init__(self, hash: bytes, source: "NodeSource"):
        self._hash = hash
        self.source = source

    def resolve(self):
        """Fault the node (through the hot cache). Also the native
        bulk_merge's stub door — stser.cc calls this by name."""
        return self.source.load(self._hash)

    def __repr__(self):
        return f"Stub({self._hash.hex()[:16]}…)"


class LazyInner(Inner):
    """Faulted inner node that stays PACKED: the 512-byte child-hash
    area is kept as one bytes object (the flat-buffer seam —
    native/src/nodestore.cc's record layout hands it over verbatim) and
    `child(b)` resolves straight off a 32-byte slice. The 16-slot
    `children` tuple of Stub objects materializes only when something
    iterates it (mutation copies, whole-subtree walks); key-guided
    descents (`get`, `succ`, bulk_update path prefaults) never pay for
    the 16 sibling objects."""

    __slots__ = ("raw", "source")

    def __init__(self, raw: bytes, source: "NodeSource", hash: bytes):
        # deliberately NOT calling Inner.__init__: the `children` slot
        # stays unset until __getattr__ materializes it
        self.raw = raw
        self.source = source
        self._hash = hash

    def __getattr__(self, name):
        if name == "children":
            raw, src = self.raw, self.source
            ch = tuple(
                None if raw[i * 32: (i + 1) * 32] == ZERO256
                else Stub(raw[i * 32: (i + 1) * 32], src)
                for i in range(16)
            )
            # benign write race: concurrent materializers build equal
            # tuples of content-addressed stubs; either assignment wins
            self.children = ch
            return ch
        raise AttributeError(name)

    def child_hash(self, b: int) -> bytes:
        return self.raw[b * 32: (b + 1) * 32]

    def child(self, b: int):
        h = self.raw[b * 32: (b + 1) * 32]
        if h == ZERO256:
            return None
        return self.source.load(h)

    def is_empty(self) -> bool:
        return self.raw == ZERO256 * 16

    def branch_count(self) -> int:
        raw = self.raw
        return sum(
            1 for i in range(16)
            if raw[i * 32: (i + 1) * 32] != ZERO256
        )


class NodeSource:
    """The fault door of a lazy tree: content-addressed loads through
    the process-wide hot-node cache, single-flight per hash.

    `known` is the identity of the backing store (the Database's
    `flushed` set): SHAMap.flush skips any stub/lazy subtree whose
    source carries the same `known` object — those bytes are already
    durably in that store, so a close's save never faults the cold
    tail just to re-write it.

    `cold` marks a historical scan (an RPC touching an old ledger):
    its faults enter the hot cache one epoch behind, so a deep history
    walk becomes first-pass eviction fodder instead of flushing the
    serving snapshot's working set (the readplane epoch contract)."""

    __slots__ = ("fetch", "verify", "known", "cold")

    def __init__(self, fetch: Callable[[bytes], Optional[bytes]],
                 verify: bool = True, known: Optional[set] = None,
                 cold: bool = False):
        self.fetch = fetch
        self.verify = verify
        self.known = known
        self.cold = cold

    def load(self, h: bytes):
        """Leaf | LazyInner for `h`, faulting through the hot cache."""
        return inner_node_cache().get_or_load(h, self._load,
                                              cold=self.cold)

    def _load(self, h: bytes):
        blob = self.fetch(h)
        if blob is None:
            raise MissingNodeError(f"missing node {h.hex()}")
        if self.verify:
            from ..utils.hashes import sha512_half

            if sha512_half(blob) != h:
                raise ValueError(
                    f"node content hash mismatch: key {h.hex()[:16]}"
                )
        if len(blob) >= 4 and \
                int.from_bytes(blob[:4], "big") == HP_INNER_NODE:
            if len(blob) != 516:
                raise ValueError(f"bad inner node length {len(blob) - 4}")
            return LazyInner(blob[4:], self, h), len(blob)
        node = deserialize_node_prefix(blob)
        if isinstance(node, InnerStub):  # unreachable; defensive
            raise ValueError("inner blob misclassified")
        node._hash = h
        return node, len(blob)


def resolve_node(node):
    """Fault `node` if it is a stub; identity otherwise. The accessor
    every traversal outside this module uses before type-dispatching on
    Leaf/Inner (state/shamapsync.py walks, node/inbound.py serving)."""
    if type(node) is Stub:
        return node.resolve()
    return node


_resolve = resolve_node


def _step(node, b: int):
    """Child slot `b` of an inner: plain tuple index for Inner, packed
    raw-slice fault for LazyInner (no sibling-stub materialization)."""
    if type(node) is Inner:
        return node.children[b]
    return node.child(b)


def _nibble(key: bytes, depth: int) -> int:
    """Branch index at `depth` (reference: SHAMapNodeID::selectBranch —
    high nibble at even depths, low nibble at odd)."""
    b = key[depth // 2]
    return b & 0xF if depth & 1 else b >> 4


# --------------------------------------------------------------------------
# persistent-tree primitives (each returns a NEW node; inputs untouched)


def _set_item(node, key: bytes, leaf: Leaf, depth: int):
    node = _resolve(node)
    if node is None:
        return leaf
    if isinstance(node, Leaf):
        if node.item.tag == key:
            return leaf  # replace
        # leaf collision: grow inner nodes until the two keys diverge
        other = node
        branch_new = _nibble(key, depth)
        branch_old = _nibble(other.item.tag, depth)
        children = [None] * 16
        if branch_new == branch_old:
            children[branch_new] = _set_item(other, key, leaf, depth + 1)
        else:
            children[branch_new] = leaf
            children[branch_old] = other
        return Inner(tuple(children))
    # inner
    b = _nibble(key, depth)
    child = node.children[b]
    new_child = _set_item(child, key, leaf, depth + 1)
    children = list(node.children)
    children[b] = new_child
    return Inner(tuple(children))


def _del_item(node, key: bytes, depth: int):
    """Returns the replacement node (None if subtree empty), or raises
    KeyError. Collapses single-leaf inners on the way up (reference:
    SHAMap::delItem single-child fold-up)."""
    node = _resolve(node)
    if node is None:
        raise KeyError(key.hex())
    if isinstance(node, Leaf):
        if node.item.tag != key:
            raise KeyError(key.hex())
        return None
    b = _nibble(key, depth)
    new_child = _del_item(node.children[b], key, depth + 1)
    children = list(node.children)
    children[b] = new_child
    live = [c for c in children if c is not None]
    if len(live) == 1:
        only = _resolve(live[0])  # the fold-up candidate may be a stub
        if isinstance(only, Leaf):
            return only
    if not live:
        return None
    return Inner(tuple(children))


def _build_subtree(ops: list, lo: int, hi: int, depth: int):
    """Canonical subtree for ops[lo:hi] (sorted, unique (key, Leaf)
    set-ops) under an empty slot. Shared nibble runs recurse once — the
    path-copy cost of a batch is O(distinct inner nodes), not
    O(ops × depth). Index-range recursion: no slice copies."""
    if hi - lo == 1:
        return ops[lo][1]
    children = [None] * 16
    shift_odd = depth & 1
    byte_i = depth // 2
    i = lo
    while i < hi:
        kb = ops[i][0][byte_i]
        b = kb & 0xF if shift_odd else kb >> 4
        j = i + 1
        while j < hi:
            kb = ops[j][0][byte_i]
            if (kb & 0xF if shift_odd else kb >> 4) != b:
                break
            j += 1
        children[b] = _build_subtree(ops, i, j, depth + 1)
        i = j
    return Inner(tuple(children))


def _bulk_merge(node, ops: list, lo: int, hi: int, depth: int,
                dels: list):
    """Merge ops[lo:hi] (sorted, unique (key, Leaf|None); None = delete)
    into the persistent subtree at `node`; returns the replacement node
    (None when the subtree empties). One DFS pass: each dirty inner is
    copied once regardless of how many ops pass through it. Deleting a
    missing key raises KeyError — exact `_del_item` parity. `dels` is
    the delete-count prefix array over `ops` (dels[i] = deletes before
    index i): a subtree whose run carries no deletes can neither empty
    nor fold up, so the live-child scan is skipped entirely.

    The tree is CANONICAL (structure is a pure function of the final
    key set: inners exist exactly on shared prefixes of >= 2 leaves, and
    single-leaf inners collapse), so this produces byte-identical roots
    to any per-key application of the same final key->value map — the
    property the differential suite pins."""
    if lo >= hi:
        return node
    node = _resolve(node)
    if hi - lo == 1:
        # singleton run: the lean per-key primitives finish the path
        k, leaf = ops[lo]
        if leaf is None:
            return _del_item(node, k, depth)
        return _set_item(node, k, leaf, depth)
    if node is None:
        if dels[hi] != dels[lo]:
            for i in range(lo, hi):
                if ops[i][1] is None:
                    raise KeyError(ops[i][0].hex())
        return _build_subtree(ops, lo, hi, depth)
    if isinstance(node, Leaf):
        tag = node.item.tag
        merged: list = []
        replaced = False
        placed = False
        for i in range(lo, hi):
            k, leaf = ops[i]
            if not placed and not replaced and tag < k:
                merged.append((tag, node))
                placed = True
            if k == tag:
                replaced = True
                if leaf is not None:
                    merged.append((k, leaf))
            elif leaf is None:
                raise KeyError(k.hex())
            else:
                merged.append((k, leaf))
        if not replaced and not placed:
            merged.append((tag, node))
        if not merged:
            return None
        if len(merged) == 1:
            return merged[0][1]
        return _build_subtree(merged, 0, len(merged), depth)
    # inner: partition the sorted run into contiguous nibble runs
    children = list(node.children)
    shift_odd = depth & 1
    byte_i = depth // 2
    i = lo
    while i < hi:
        kb = ops[i][0][byte_i]
        b = kb & 0xF if shift_odd else kb >> 4
        j = i + 1
        while j < hi:
            kb = ops[j][0][byte_i]
            if (kb & 0xF if shift_odd else kb >> 4) != b:
                break
            j += 1
        children[b] = _bulk_merge(children[b], ops, i, j, depth + 1, dels)
        i = j
    if dels[hi] == dels[lo]:
        return Inner(tuple(children))  # no deletes below: cannot collapse
    live = [c for c in children if c is not None]
    if not live:
        return None
    if len(live) == 1:
        only = _resolve(live[0])  # the fold-up candidate may be a stub
        if isinstance(only, Leaf):
            return only  # single-leaf fold-up (del_item parity)
    return Inner(tuple(children))


def _get(node, key: bytes, depth: int) -> Optional[SHAMapItem]:
    while node is not None:
        node = _resolve(node)
        if isinstance(node, Leaf):
            return node.item if node.item.tag == key else None
        node = _step(node, _nibble(key, depth))
        depth += 1
    return None


def _walk_leaves(node) -> Iterator[Leaf]:
    """Leaves in ascending key order (radix order == numeric order)."""
    node = _resolve(node)
    if node is None:
        return
    if isinstance(node, Leaf):
        yield node
        return
    for c in node.children:
        if c is not None:
            yield from _walk_leaves(c)


# --------------------------------------------------------------------------
# batched hashing


def _collect_unhashed(root) -> list[list]:
    """Unhashed nodes grouped by depth (index = depth). A node whose hash is
    cached is a sealed subtree — nothing below it can be unhashed, because
    mutation always rebuilds the whole path from the root with fresh
    (hashless) nodes."""
    levels: list[list] = []

    def visit(node, depth):
        if node is None or node._hash is not None:
            return
        while len(levels) <= depth:
            levels.append([])
        levels[depth].append(node)
        if isinstance(node, Inner):
            for c in node.children:
                visit(c, depth + 1)

    visit(root, 0)
    return levels


def _default_hasher(prefixes, payloads):
    return [prefix_hash(p, d) for p, d in zip(prefixes, payloads)]


# --------------------------------------------------------------------------
# flat-buffer node encoding: every dirty node's prefix-format bytes packed
# into ONE contiguous buffer + offsets, instead of one Python payload
# object per node. The encoding doubles as (a) the exact hashed message
# per node (prefix-format blob == hashed bytes) and (b) the exact
# NodeStore blob, so hashing and flushing share one serialization.

_PFX_INNER = HP_INNER_NODE.to_bytes(4, "big")
_PFX_LEAF = {t: p.to_bytes(4, "big") for t, p in _LEAF_PREFIX.items()}

_native_pack = None
_native_merge = None
_native_merge_stub_ok = False
_native_resolved = False


def _resolve_native():
    """Bind the C fast paths (native/src/stser.cc pack_nodes +
    bulk_merge) once; pure-Python loops otherwise. Both are
    differential-tested byte-equal against the Python implementations.

    The stub door (bulk_merge's optional 5th arg, faulting lazy-tree
    stubs on the op path) is probed HERE via the module's
    BULK_MERGE_STUB_DOOR capability constant: a stale prebuilt library
    lacks it, and lazy trees then take the stub-aware Python merge
    instead of paying a TypeError round-trip on every bulk_update."""
    global _native_pack, _native_merge, _native_merge_stub_ok, \
        _native_resolved
    if not _native_resolved:
        _native_resolved = True
        try:
            from ..native import load_stser

            mod = load_stser()
            _native_pack = getattr(mod, "pack_nodes", None)
            _native_merge = getattr(mod, "bulk_merge", None)
            _native_merge_stub_ok = (
                _native_merge is not None
                and getattr(mod, "BULK_MERGE_STUB_DOOR", 0) >= 1
            )
        except Exception:  # noqa: BLE001 — toolchain-less box: python path
            _native_pack = _native_merge = None
            _native_merge_stub_ok = False


def _resolve_native_pack():
    _resolve_native()
    return _native_pack


def _resolve_native_merge():
    _resolve_native()
    return _native_merge


def _encode_nodes_py(nodes) -> tuple[bytes, list[int]]:
    buf = bytearray()
    ext = buf.extend
    offsets = [0]
    app = offsets.append
    for node in nodes:
        if isinstance(node, Inner):
            ext(_PFX_INNER)
            for c in node.children:
                ext(c._hash if c is not None else ZERO256)
        else:
            t = node.type
            ext(_PFX_LEAF[t])
            ext(node.item.data)
            if t is not TNType.TX_NM:
                ext(node.item.tag)
        app(len(buf))
    return bytes(buf), offsets


def encode_nodes(nodes) -> tuple[bytes, list[int]]:
    """Pack the prefix-format bytes of `nodes` (Leaf | Inner; inner
    children must already carry hashes) into one contiguous buffer.
    Returns (buffer, offsets[n+1]); node i's blob/message is
    buffer[offsets[i]:offsets[i+1]]."""
    nodes = nodes if isinstance(nodes, list) else list(nodes)
    pack = _resolve_native_pack()
    if pack is not None:
        return pack(nodes, int(HP_INNER_NODE), int(HP_TXN_ID),
                    int(HP_TX_NODE), int(HP_LEAF_NODE))
    return _encode_nodes_py(nodes)


def compute_hashes(root, hash_batch: Callable = _default_hasher) -> int:
    """Fill every missing node hash, one batched call per tree level,
    deepest level first. Returns the number of nodes hashed.

    This is the flushDirty replacement (reference:
    LedgerConsensus.cpp:993-996 → SHAMap::flushDirty): on TPU,
    `hash_batch` is the device SHA-512 kernel and each level is one
    device program over all dirty nodes of that level.
    """
    if hasattr(hash_batch, "hash_tree") \
            and getattr(hash_batch, "fused_enabled", True):
        # whole-tree device pipeline (TpuHasher.hash_tree): digests stay
        # device-resident across levels, one host transfer at the end.
        # [tree] fused=0 clears fused_enabled — the staged per-level
        # path below, kept as the fused-vs-staged identity leg
        return hash_batch.hash_tree(root)
    levels = _collect_unhashed(root)
    packed = getattr(hash_batch, "hash_packed", None)
    n = 0
    for level in reversed(levels):
        if packed is not None:
            # flat-buffer path: one contiguous encoding per level feeds
            # the batch hasher in a single call — no per-node payload
            # objects (the prep cost that dominated the host seal)
            targets = []
            for node in level:
                if isinstance(node, Inner) and node.is_empty():
                    node._hash = ZERO256
                else:
                    targets.append(node)
            if targets:
                buf, offsets = encode_nodes(targets)
                digests = packed(buf, offsets)
                for node, dg in zip(targets, digests):
                    node._hash = dg
            n += len(targets)
            continue
        prefixes, payloads = [], []
        for node in level:
            if isinstance(node, Leaf):
                p, d = node.hash_payload()
            else:
                if node.is_empty():
                    node._hash = ZERO256
                    continue
                p = HP_INNER_NODE
                d = b"".join(
                    (c._hash if c is not None else ZERO256) for c in node.children
                )
            prefixes.append(p)
            payloads.append(d)
        digests = hash_batch(prefixes, payloads) if prefixes else []
        i = 0
        for node in level:
            if node._hash is None:
                node._hash = digests[i]
                i += 1
        n += len(prefixes)
    return n


# --------------------------------------------------------------------------
# node (de)serialization — NodeStore uses the prefix format, the wire
# protocol the compressed format (reference addRaw/make from snfPREFIX /
# snfWIRE)


def serialize_node_prefix(node) -> bytes:
    if isinstance(node, Inner):
        out = HP_INNER_NODE.to_bytes(4, "big")
        return out + b"".join(
            (c._hash if c is not None else ZERO256) for c in node.children
        )
    prefix, payload = node.hash_payload()
    return prefix.to_bytes(4, "big") + payload


def serialize_node_wire(node) -> bytes:
    if isinstance(node, Inner):
        if node.branch_count() < 12:
            out = b""
            for i, c in enumerate(node.children):
                if c is not None:
                    out += c._hash + bytes([i])
            return out + bytes([_WIRE_INNER_COMPRESSED])
        return (
            b"".join((c._hash if c is not None else ZERO256) for c in node.children)
            + bytes([_WIRE_INNER_FULL])
        )
    item, t = node.item, node.type
    if t == TNType.TX_NM:
        return item.data + bytes([_WIRE_TX_NM])
    trailer = _WIRE_STATE if t == TNType.ACCOUNT_STATE else _WIRE_TX_MD
    return item.data + item.tag + bytes([trailer])


# process-wide memo of deserialized-and-resolved nodes, keyed by node
# hash (content-addressed, so sharing across stores/trees is always
# sound). Since the out-of-core plane this is the byte-bounded,
# epoch-aware HotNodeCache (state/hotcache.py): for lazy trees it IS
# the resident hot set ([tree] cache_mb) and its fault counters are the
# out-of-core evidence in get_counts.shamap_inner_cache; for the eager
# from_store path it plays the old TaggedCache role (a hit returns a
# whole resolved subtree in O(1)).
_INNER_CACHE = None


def inner_node_cache():
    global _INNER_CACHE
    if _INNER_CACHE is None:
        from .hotcache import HotNodeCache

        _INNER_CACHE = HotNodeCache("shamap_inners")
    return _INNER_CACHE


def configure_inner_cache(cache_mb: int) -> None:
    """Apply the `[tree] cache_mb` budget (node setup)."""
    inner_node_cache().set_limit(max(1, int(cache_mb)) << 20)


class InnerStub:
    """Parse-time placeholder: an inner node known only by child hashes.
    Resolved against a fetch source when the tree is materialized."""

    __slots__ = ("child_hashes",)

    def __init__(self, child_hashes: list[bytes]):
        self.child_hashes = child_hashes


def deserialize_node_prefix(blob: bytes):
    """Parse a NodeStore/prefix-format node → Leaf | InnerStub
    (reference: SHAMapTreeNode ctor, snfPREFIX arm)."""
    if len(blob) < 4:
        raise ValueError("short node blob")
    prefix = int.from_bytes(blob[:4], "big")
    body = blob[4:]
    if prefix == HP_INNER_NODE:
        if len(body) != 512:
            raise ValueError(f"bad inner node length {len(body)}")
        return InnerStub([body[i * 32 : (i + 1) * 32] for i in range(16)])
    if prefix == HP_TXN_ID:
        item = SHAMapItem(prefix_hash(HP_TXN_ID, body), body)
        return Leaf(item, TNType.TX_NM)
    if prefix == HP_TX_NODE:
        item = SHAMapItem(body[-32:], body[:-32])
        return Leaf(item, TNType.TX_MD)
    if prefix == HP_LEAF_NODE:
        item = SHAMapItem(body[-32:], body[:-32])
        return Leaf(item, TNType.ACCOUNT_STATE)
    raise ValueError(f"unknown node prefix {prefix:#x}")


def deserialize_node_wire(blob: bytes):
    """Parse a wire-format node (reference: SHAMapTreeNode ctor, snfWIRE)."""
    if not blob:
        raise ValueError("empty node blob")
    trailer, body = blob[-1], blob[:-1]
    if trailer == _WIRE_INNER_FULL:
        if len(body) != 512:
            raise ValueError("bad full inner length")
        return InnerStub([body[i * 32 : (i + 1) * 32] for i in range(16)])
    if trailer == _WIRE_INNER_COMPRESSED:
        if len(body) % 33:
            raise ValueError("bad compressed inner length")
        hashes = [ZERO256] * 16
        for i in range(0, len(body), 33):
            branch = body[i + 32]
            if branch >= 16:
                raise ValueError(f"bad branch index {branch}")
            hashes[branch] = body[i : i + 32]
        return InnerStub(hashes)
    if trailer == _WIRE_TX_NM:
        return Leaf(SHAMapItem(prefix_hash(HP_TXN_ID, body), body), TNType.TX_NM)
    if trailer == _WIRE_STATE:
        return Leaf(SHAMapItem(body[-32:], body[:-32]), TNType.ACCOUNT_STATE)
    if trailer == _WIRE_TX_MD:
        return Leaf(SHAMapItem(body[-32:], body[:-32]), TNType.TX_MD)
    raise ValueError(f"unknown wire trailer {trailer}")


# --------------------------------------------------------------------------


class SHAMap:
    """Mutable handle over a persistent radix tree.

    Mirrors the reference SHAMap surface (src/ripple_app/shamap/SHAMap.h):
    add/update/del items, hash, snapshot, compare, flush to a NodeStore,
    rebuild from a NodeStore by root hash.
    """

    def __init__(self, leaf_type: TNType = TNType.ACCOUNT_STATE, root=None,
                 hash_batch: Callable = _default_hasher,
                 source: Optional[NodeSource] = None):
        self.leaf_type = leaf_type
        self.root = root if root is not None else EMPTY_INNER
        self.hash_batch = hash_batch
        # non-None marks a lazy tree (out-of-core faulting): descents
        # may hit Stub slots, so bulk_update must hand the native merge
        # the Stub class (its fault door) or take the stub-aware Python
        # merge on a stale library
        self._source = source

    # -- queries ----------------------------------------------------------

    def get(self, key: bytes) -> Optional[SHAMapItem]:
        return _get(self.root, key, 0)

    def get_leaf(self, key: bytes) -> Optional[Leaf]:
        """Typed leaf lookup, O(depth)."""
        node, depth = self.root, 0
        while node is not None:
            node = _resolve(node)
            if isinstance(node, Leaf):
                return node if node.item.tag == key else None
            node = _step(node, _nibble(key, depth))
            depth += 1
        return None

    def __contains__(self, key: bytes) -> bool:
        return self.get(key) is not None

    def __len__(self) -> int:
        return sum(1 for _ in _walk_leaves(self.root))

    def items(self) -> Iterator[SHAMapItem]:
        for leaf in _walk_leaves(self.root):
            yield leaf.item

    def leaves(self) -> Iterator[Leaf]:
        """Typed leaves in key order (callers that must distinguish raw-tx
        vs tx+metadata items, reference visitLeaves)."""
        yield from _walk_leaves(self.root)

    def peek_first_item(self) -> Optional[SHAMapItem]:
        for leaf in _walk_leaves(self.root):
            return leaf.item
        return None

    def succ(self, key: bytes) -> Optional[SHAMapItem]:
        """First item with tag strictly greater than `key` (reference:
        SHAMap::peekNextItem — order-book/directory iteration). Key-guided
        descent, O(depth): at each inner node, recurse into the key's own
        branch first, then scan higher branches for their smallest leaf."""

        def smallest(node) -> Optional[SHAMapItem]:
            node = _resolve(node)
            while isinstance(node, Inner):
                node = _resolve(
                    next((c for c in node.children if c is not None), None)
                )
            return node.item if node is not None else None

        def descend(node, depth) -> Optional[SHAMapItem]:
            node = _resolve(node)
            if node is None:
                return None
            if isinstance(node, Leaf):
                return node.item if node.item.tag > key else None
            b = _nibble(key, depth)
            found = descend(_step(node, b), depth + 1)
            if found is not None:
                return found
            for c in node.children[b + 1 :]:
                if c is not None:
                    return smallest(c)
            return None

        return descend(self.root, 0)

    # -- mutation ---------------------------------------------------------

    def set_item(self, item: SHAMapItem, leaf_type: Optional[TNType] = None) -> None:
        leaf = Leaf(item, leaf_type or self.leaf_type)
        self.root = _set_item(self.root, item.tag, leaf, 0)

    def del_item(self, key: bytes) -> None:
        root = _del_item(self.root, key, 0)
        self.root = self._normalize_root(root)

    def bulk_update(self, sets=(), deletes=(),
                    leaf_type: Optional[TNType] = None,
                    missing_ok: bool = False) -> int:
        """Apply a whole write set in ONE key-sorted DFS pass: `sets` are
        SHAMapItems (replace-or-insert), `deletes` are keys (KeyError if
        missing — del_item parity). Shared path prefixes are copied once
        instead of once per write, which is what makes a close's spliced
        delta O(distinct dirty nodes) instead of O(writes x depth).

        Byte-contract: the resulting root (and hash) is identical to
        applying the same final key->value map through per-key
        set_item/del_item in any order — the tree is canonical in the
        final key set. A key in both `sets` and `deletes` is a caller
        bug (ValueError); duplicate keys within `sets` keep the LAST
        item. With `missing_ok`, deletes of keys absent from the tree
        are dropped instead of raising (a compacted create-then-delete
        nets to nothing). Returns the number of distinct keys applied."""
        lt = leaf_type or self.leaf_type
        ops: dict[bytes, Optional[Leaf]] = {}
        for item in sets:
            ops[item.tag] = Leaf(item, lt)
        for key in deletes:
            if ops.get(key) is not None:
                raise ValueError(
                    f"key {key.hex()[:16]} in both sets and deletes"
                )
            if missing_ok and self.get(key) is None:
                continue
            ops[key] = None
        if not ops:
            return 0
        sorted_ops = sorted(ops.items())
        merge_c = _resolve_native_merge()
        root = None
        merged = False
        if merge_c is not None:
            if self._source is None:
                root = merge_c(self.root, sorted_ops, Leaf, Inner)
                merged = True
            elif _native_merge_stub_ok:
                # lazy tree: the native merge faults op-path stubs via
                # Stub.resolve (stser.cc stub door); the capability was
                # probed at bind time (_resolve_native), so a stale
                # prebuilt library falls through to the stub-aware
                # Python merge below
                root = merge_c(self.root, sorted_ops, Leaf, Inner, Stub)
                merged = True
        if not merged:
            dels = [0] * (len(sorted_ops) + 1)
            for i, (_k, leaf) in enumerate(sorted_ops):
                dels[i + 1] = dels[i] + (leaf is None)
            root = _bulk_merge(
                self.root, sorted_ops, 0, len(sorted_ops), 0, dels
            )
        self.root = self._normalize_root(root)
        return len(ops)

    @staticmethod
    def _normalize_root(root):
        """The tree root is always an inner node (reference keeps a root
        inner even for a single item)."""
        if root is None:
            return EMPTY_INNER
        if isinstance(root, Leaf):
            children = [None] * 16
            children[_nibble(root.item.tag, 0)] = root
            return Inner(tuple(children))
        return root

    # -- hashing / snapshots ---------------------------------------------

    def get_hash(self) -> bytes:
        if isinstance(self.root, Inner) and self.root.is_empty():
            return ZERO256
        if self.root._hash is None:
            compute_hashes(self.root, self.hash_batch)
        return self.root._hash

    def snapshot(self) -> "SHAMap":
        """O(1) immutable snapshot: share the persistent root."""
        return SHAMap(self.leaf_type, self.root, self.hash_batch,
                      source=self._source)

    # -- delta ------------------------------------------------------------

    def compare(self, other: "SHAMap", limit: int = 2**31) -> dict[bytes, tuple]:
        """Key → (this_item|None, other_item|None) for keys that differ
        (reference: SHAMapDelta.cpp SHAMap::compare). Shared subtrees are
        skipped by object identity / node hash, so the cost is proportional
        to the delta, not the tree."""
        delta: dict[bytes, tuple] = {}

        def same(a, b) -> bool:
            if a is b:
                return True
            if a is None or b is None:
                return False
            if a._hash is not None and a._hash == b._hash:
                return True
            return False

        def walk(a, b):
            if len(delta) > limit or same(a, b):
                return
            # resolve only AFTER the hash short-circuit: shared subtrees
            # (stub vs anything carrying the same hash) never fault
            a, b = _resolve(a), _resolve(b)
            if a is None or isinstance(a, Leaf):
                a_items = {a.item.tag: a.item} if isinstance(a, Leaf) else {}
            else:
                a_items = None
            if b is None or isinstance(b, Leaf):
                b_items = {b.item.tag: b.item} if isinstance(b, Leaf) else {}
            else:
                b_items = None
            if a_items is not None or b_items is not None:
                if a_items is None:
                    a_items = {l.item.tag: l.item for l in _walk_leaves(a)}
                if b_items is None:
                    b_items = {l.item.tag: l.item for l in _walk_leaves(b)}
                for tag in set(a_items) | set(b_items):
                    ia, ib = a_items.get(tag), b_items.get(tag)
                    if ia != ib:
                        delta[tag] = (ia, ib)
                return
            for ca, cb in zip(a.children, b.children):
                walk(ca, cb)

        walk(self.root, other.root)
        if len(delta) > limit:
            raise ValueError("delta exceeds limit")
        return delta

    # -- NodeStore integration -------------------------------------------

    # encode-and-store chunk size: bounds the shared buffer so flushing
    # a whole genesis tree never materializes the full serialization
    FLUSH_CHUNK = 8192

    def flush(self, store: Callable[[bytes, bytes], None],
              known: Optional[set] = None,
              store_many: Optional[Callable[[list], None]] = None,
              store_packed: Optional[Callable] = None) -> int:
        """Hash everything, then persist every node the target store does
        not yet have, as (hash → prefix-format blob). Returns the number of
        nodes written.

        `known` is the per-store set of already-flushed hashes (e.g.
        nodestore.Database.flushed); a hash in `known` seals its whole
        subtree (flush adds bottom-up), so shared subtrees across ledger
        versions are skipped and the write cost per close is proportional
        to the delta, not total state. The set is per-store — flushing the
        same tree into a second store writes everything again there
        (the reference's flushDirty dirty-list behaves the same way).

        The write set serializes through the flat-buffer node encoder
        (the same encoding the hash plane consumes — a prefix-format
        blob IS the hashed byte sequence), not per-node
        serialize_node_prefix calls; with `store_many` (a batch sink,
        e.g. Database.store_many_fn) each chunk lands in the store in
        one call instead of one lock round-trip per node. With
        `store_packed` (the flat-buffer sink, Database.store_packed_fn)
        the encoded chunk is handed through AS-IS — (hashes, buf,
        offsets), no per-node blob slices at all — which a
        log-structured backend turns into one contiguous segment
        append.
        """
        self.get_hash()
        if known is None:
            known = set()
        nodes: list = []

        def visit(node):
            if node is None or node._hash in known:
                return
            # lazy subtrees: a stub or faulted-but-clean node whose
            # source is backed by THIS store ("known" is the source's
            # own flushed set) is already durably present — skip the
            # whole subtree without faulting it. Flushing into a
            # DIFFERENT store materializes and writes as usual.
            src = getattr(node, "source", None)
            if src is not None and src.known is known:
                return
            if type(node) is Stub:
                node = node.source.load(node._hash)
            if isinstance(node, Inner):
                for c in node.children:
                    visit(c)
            nodes.append(node)  # post-order: children land before parents

        if not (isinstance(self.root, Inner) and self.root.is_empty()):
            visit(self.root)
        for start in range(0, len(nodes), self.FLUSH_CHUNK):
            chunk = nodes[start : start + self.FLUSH_CHUNK]
            buf, offsets = encode_nodes(chunk)
            if store_packed is not None:
                store_packed([node._hash for node in chunk], buf, offsets)
            elif store_many is not None:
                store_many([
                    (node._hash, buf[offsets[i] : offsets[i + 1]])
                    for i, node in enumerate(chunk)
                ])
            else:
                for i, node in enumerate(chunk):
                    store(node._hash, buf[offsets[i] : offsets[i + 1]])
            # mark flushed only AFTER the store accepted the chunk: a
            # failing store must leave the flush retryable, never a
            # known-set claiming nodes the backend never saw
            known.update(node._hash for node in chunk)
        return len(nodes)

    @classmethod
    def from_store(
        cls,
        root_hash: bytes,
        fetch: Callable[[bytes], Optional[bytes]],
        leaf_type: TNType = TNType.ACCOUNT_STATE,
        hash_batch: Callable = _default_hasher,
        verify: bool = True,
        use_cache: bool = True,
        lazy: bool = False,
        store_known: Optional[set] = None,
        cold: bool = False,
    ) -> "SHAMap":
        """Materialize a full tree from a content-addressed store
        (reference: SHAMap fetchNodeExternal path). Raises KeyError on a
        missing node (the seam where network acquisition hooks in) and,
        with `verify` (default), ValueError when a fetched blob does not
        hash to its key (the reference verifies fetched nodes the same
        way, SHAMapTreeNode ctor hashValid path).

        With `lazy` (the out-of-core plane, doc/storage.md), only the
        ROOT node is fetched now; every child slot is a hash-only Stub
        that faults from the store through the bounded hot-node cache
        on first descent. Opening a million-account ledger is O(1);
        walks, succ cursors, bulk_update's DFS and the delta-replay
        splice all fault on demand, byte-identical to the eager tree.
        `store_known` identifies the backing store (the Database's
        `flushed` set) so flushing back into the same store never
        faults clean subtrees just to re-write them.

        With `use_cache` (default), resolved inner nodes memoize in the
        process-wide `inner_node_cache()` keyed by node hash — a hit
        returns a whole already-verified subtree, so materializing
        successive ledgers of a chain re-parses only the delta. Nodes
        are immutable + content-addressed, which is what makes the
        sharing sound across stores and trees."""
        if root_hash == ZERO256:
            return cls(leaf_type, EMPTY_INNER, hash_batch)
        if lazy:
            source = NodeSource(fetch, verify=verify, known=store_known,
                                cold=cold)
            root = source.load(root_hash)
            if isinstance(root, Leaf):
                children = [None] * 16
                children[_nibble(root.item.tag, 0)] = root
                root = Inner(tuple(children))
            return cls(leaf_type, root, hash_batch, source=source)
        cache = inner_node_cache() if use_cache else None

        def load(h: bytes):
            if cache is not None:
                hit = cache.get(h)
                # a LazyInner hit (faulted by the out-of-core plane)
                # must not leak into an EAGER tree: its descendants are
                # stubs, and eager trees (source=None) promise
                # stub-free structure to the native merge fast path
                if hit is not None and type(hit) is not LazyInner:
                    return hit
            blob = fetch(h)
            if blob is None:
                raise MissingNodeError(f"missing node {h.hex()}")
            node = deserialize_node_prefix(blob)
            if verify:
                # prefix-format blob == exactly the hashed bytes
                from ..utils.hashes import sha512_half

                actual = sha512_half(blob)
                if actual != h:
                    raise ValueError(
                        f"node content hash mismatch: key {h.hex()[:16]} "
                        f"content {actual.hex()[:16]}"
                    )
            if isinstance(node, InnerStub):
                children = tuple(
                    load(ch) if ch != ZERO256 else None for ch in node.child_hashes
                )
                node = Inner(children, hash=h)
                if cache is not None:
                    # eager: this entry pins its whole materialized
                    # subtree, so it rides the EAGER_ENTRY_CAP count
                    # bound, not the per-node byte budget
                    cache.put(h, node, eager=True)
            else:
                node._hash = h
            return node

        root = load(root_hash)
        if isinstance(root, Leaf):
            children = [None] * 16
            children[_nibble(root.item.tag, 0)] = root
            root = Inner(tuple(children))
        return cls(leaf_type, root, hash_batch)
