"""SHAMap: 16-ary Merkle-radix tree over 256-bit keys.

Hash/wire compatible with the reference
(src/ripple_app/shamap/SHAMapTreeNode.cpp:253-295 updateHash,
:305-395 addRaw; src/ripple_app/shamap/SHAMapNodeID.cpp:147-176
selectBranch):

- inner node hash  = SHA512half(HP_INNER_NODE || 16 child hashes);
  an inner with no branches hashes to zero,
- tx leaf (no md)  = SHA512half(HP_TXN_ID || data)          (== the tx ID),
- tx leaf (w/ md)  = SHA512half(HP_TX_NODE || data || tag),
- state leaf       = SHA512half(HP_LEAF_NODE || data || tag).

Architecture differences from the reference (deliberate, TPU-first):

- **Persistent tree.** Nodes are immutable; every mutation returns a new
  root sharing unchanged subtrees. `snapshot()` is O(1); the reference's
  copy-on-write sequence numbers (SHAMap.h mSeq) and its mutable-node
  locking disappear.
- **Deferred, level-synchronous hashing.** Mutations never hash. Hashes are
  computed on demand by grouping all unhashed nodes by tree depth and
  hashing each level in ONE batched call through a pluggable `BatchHasher`
  (crypto.backend) — deepest level first, so parents always see hashed
  children. On TPU that is one device program per level over thousands of
  nodes, replacing the reference's per-node OpenSSL calls inside recursive
  flushDirty.
"""

from __future__ import annotations

from enum import IntEnum
from typing import Callable, Iterator, Optional

from ..utils.hashes import (
    HP_INNER_NODE,
    HP_LEAF_NODE,
    HP_TXN_ID,
    HP_TX_NODE,
    prefix_hash,
)

__all__ = ["TNType", "SHAMapItem", "SHAMap", "Leaf", "Inner"]

ZERO256 = b"\x00" * 32


class TNType(IntEnum):
    """Node types (reference: SHAMapTreeNode.h:47-53). The numeric values
    double as the wire-format trailer byte for leaves (addRaw snfWIRE)."""

    INNER = 1
    TX_NM = 2  # transaction, no metadata (tx map of an open ledger)
    TX_MD = 3  # transaction + metadata (tx map of a closed ledger)
    ACCOUNT_STATE = 4  # state map leaf


# wire-format trailer bytes (reference addRaw: snfWIRE)
_WIRE_TX_NM = 0
_WIRE_STATE = 1
_WIRE_INNER_FULL = 2
_WIRE_INNER_COMPRESSED = 3
_WIRE_TX_MD = 4

_LEAF_PREFIX = {
    TNType.TX_NM: HP_TXN_ID,
    TNType.TX_MD: HP_TX_NODE,
    TNType.ACCOUNT_STATE: HP_LEAF_NODE,
}


class SHAMapItem:
    """A keyed blob: 32-byte tag (index) + serialized payload
    (reference: src/ripple_app/shamap/SHAMapItem.h).

    ``parsed`` memoizes the deserialized STObject for this (immutable)
    blob — writes always construct fresh items, so the pristine parse
    can be shared across the persistent-map versions that alias the
    item (the reference's SLE cache role); consumers must COPY before
    mutating (Ledger.read_entry does)."""

    __slots__ = ("tag", "data", "parsed")

    def __init__(self, tag: bytes, data: bytes):
        assert len(tag) == 32
        self.tag = tag
        self.data = data
        self.parsed = None

    def __eq__(self, other):
        return (
            isinstance(other, SHAMapItem)
            and self.tag == other.tag
            and self.data == other.data
        )

    def __repr__(self):
        return f"SHAMapItem({self.tag.hex()[:16]}…, {len(self.data)}B)"


class Leaf:
    """Immutable leaf node. `_hash` is a lazily-filled, write-once cache —
    the only mutable slot, so sharing across snapshots stays safe."""

    __slots__ = ("item", "type", "_hash")

    def __init__(self, item: SHAMapItem, type: TNType, hash: Optional[bytes] = None):
        self.item = item
        self.type = type
        self._hash = hash

    def hash_payload(self) -> tuple[int, bytes]:
        """(prefix, payload) whose prefixed SHA-512-half is this node's hash
        (reference: SHAMapTreeNode.cpp updateHash leaf arms)."""
        prefix = _LEAF_PREFIX[self.type]
        if self.type == TNType.TX_NM:
            return prefix, self.item.data
        return prefix, self.item.data + self.item.tag


class Inner:
    """Immutable inner node: 16 child slots."""

    __slots__ = ("children", "_hash")

    def __init__(self, children: tuple, hash: Optional[bytes] = None):
        self.children = children  # tuple of 16 × (Leaf | Inner | None)
        self._hash = hash

    def is_empty(self) -> bool:
        return all(c is None for c in self.children)

    def branch_count(self) -> int:
        return sum(1 for c in self.children if c is not None)


EMPTY_INNER = Inner((None,) * 16, hash=ZERO256)


def _nibble(key: bytes, depth: int) -> int:
    """Branch index at `depth` (reference: SHAMapNodeID::selectBranch —
    high nibble at even depths, low nibble at odd)."""
    b = key[depth // 2]
    return b & 0xF if depth & 1 else b >> 4


# --------------------------------------------------------------------------
# persistent-tree primitives (each returns a NEW node; inputs untouched)


def _set_item(node, key: bytes, leaf: Leaf, depth: int):
    if node is None:
        return leaf
    if isinstance(node, Leaf):
        if node.item.tag == key:
            return leaf  # replace
        # leaf collision: grow inner nodes until the two keys diverge
        other = node
        branch_new = _nibble(key, depth)
        branch_old = _nibble(other.item.tag, depth)
        children = [None] * 16
        if branch_new == branch_old:
            children[branch_new] = _set_item(other, key, leaf, depth + 1)
        else:
            children[branch_new] = leaf
            children[branch_old] = other
        return Inner(tuple(children))
    # inner
    b = _nibble(key, depth)
    child = node.children[b]
    new_child = _set_item(child, key, leaf, depth + 1)
    children = list(node.children)
    children[b] = new_child
    return Inner(tuple(children))


def _del_item(node, key: bytes, depth: int):
    """Returns the replacement node (None if subtree empty), or raises
    KeyError. Collapses single-leaf inners on the way up (reference:
    SHAMap::delItem single-child fold-up)."""
    if node is None:
        raise KeyError(key.hex())
    if isinstance(node, Leaf):
        if node.item.tag != key:
            raise KeyError(key.hex())
        return None
    b = _nibble(key, depth)
    new_child = _del_item(node.children[b], key, depth + 1)
    children = list(node.children)
    children[b] = new_child
    live = [c for c in children if c is not None]
    if len(live) == 1 and isinstance(live[0], Leaf):
        return live[0]
    if not live:
        return None
    return Inner(tuple(children))


def _get(node, key: bytes, depth: int) -> Optional[SHAMapItem]:
    while node is not None:
        if isinstance(node, Leaf):
            return node.item if node.item.tag == key else None
        node = node.children[_nibble(key, depth)]
        depth += 1
    return None


def _walk_leaves(node) -> Iterator[Leaf]:
    """Leaves in ascending key order (radix order == numeric order)."""
    if node is None:
        return
    if isinstance(node, Leaf):
        yield node
        return
    for c in node.children:
        yield from _walk_leaves(c)


# --------------------------------------------------------------------------
# batched hashing


def _collect_unhashed(root) -> list[list]:
    """Unhashed nodes grouped by depth (index = depth). A node whose hash is
    cached is a sealed subtree — nothing below it can be unhashed, because
    mutation always rebuilds the whole path from the root with fresh
    (hashless) nodes."""
    levels: list[list] = []

    def visit(node, depth):
        if node is None or node._hash is not None:
            return
        while len(levels) <= depth:
            levels.append([])
        levels[depth].append(node)
        if isinstance(node, Inner):
            for c in node.children:
                visit(c, depth + 1)

    visit(root, 0)
    return levels


def _default_hasher(prefixes, payloads):
    return [prefix_hash(p, d) for p, d in zip(prefixes, payloads)]


def compute_hashes(root, hash_batch: Callable = _default_hasher) -> int:
    """Fill every missing node hash, one batched call per tree level,
    deepest level first. Returns the number of nodes hashed.

    This is the flushDirty replacement (reference:
    LedgerConsensus.cpp:993-996 → SHAMap::flushDirty): on TPU,
    `hash_batch` is the device SHA-512 kernel and each level is one
    device program over all dirty nodes of that level.
    """
    if hasattr(hash_batch, "hash_tree"):
        # whole-tree device pipeline (TpuHasher.hash_tree): digests stay
        # device-resident across levels, one host transfer at the end
        return hash_batch.hash_tree(root)
    levels = _collect_unhashed(root)
    n = 0
    for level in reversed(levels):
        prefixes, payloads = [], []
        for node in level:
            if isinstance(node, Leaf):
                p, d = node.hash_payload()
            else:
                if node.is_empty():
                    node._hash = ZERO256
                    continue
                p = HP_INNER_NODE
                d = b"".join(
                    (c._hash if c is not None else ZERO256) for c in node.children
                )
            prefixes.append(p)
            payloads.append(d)
        digests = hash_batch(prefixes, payloads) if prefixes else []
        i = 0
        for node in level:
            if node._hash is None:
                node._hash = digests[i]
                i += 1
        n += len(prefixes)
    return n


# --------------------------------------------------------------------------
# node (de)serialization — NodeStore uses the prefix format, the wire
# protocol the compressed format (reference addRaw/make from snfPREFIX /
# snfWIRE)


def serialize_node_prefix(node) -> bytes:
    if isinstance(node, Inner):
        out = HP_INNER_NODE.to_bytes(4, "big")
        return out + b"".join(
            (c._hash if c is not None else ZERO256) for c in node.children
        )
    prefix, payload = node.hash_payload()
    return prefix.to_bytes(4, "big") + payload


def serialize_node_wire(node) -> bytes:
    if isinstance(node, Inner):
        if node.branch_count() < 12:
            out = b""
            for i, c in enumerate(node.children):
                if c is not None:
                    out += c._hash + bytes([i])
            return out + bytes([_WIRE_INNER_COMPRESSED])
        return (
            b"".join((c._hash if c is not None else ZERO256) for c in node.children)
            + bytes([_WIRE_INNER_FULL])
        )
    item, t = node.item, node.type
    if t == TNType.TX_NM:
        return item.data + bytes([_WIRE_TX_NM])
    trailer = _WIRE_STATE if t == TNType.ACCOUNT_STATE else _WIRE_TX_MD
    return item.data + item.tag + bytes([trailer])


class InnerStub:
    """Parse-time placeholder: an inner node known only by child hashes.
    Resolved against a fetch source when the tree is materialized."""

    __slots__ = ("child_hashes",)

    def __init__(self, child_hashes: list[bytes]):
        self.child_hashes = child_hashes


def deserialize_node_prefix(blob: bytes):
    """Parse a NodeStore/prefix-format node → Leaf | InnerStub
    (reference: SHAMapTreeNode ctor, snfPREFIX arm)."""
    if len(blob) < 4:
        raise ValueError("short node blob")
    prefix = int.from_bytes(blob[:4], "big")
    body = blob[4:]
    if prefix == HP_INNER_NODE:
        if len(body) != 512:
            raise ValueError(f"bad inner node length {len(body)}")
        return InnerStub([body[i * 32 : (i + 1) * 32] for i in range(16)])
    if prefix == HP_TXN_ID:
        item = SHAMapItem(prefix_hash(HP_TXN_ID, body), body)
        return Leaf(item, TNType.TX_NM)
    if prefix == HP_TX_NODE:
        item = SHAMapItem(body[-32:], body[:-32])
        return Leaf(item, TNType.TX_MD)
    if prefix == HP_LEAF_NODE:
        item = SHAMapItem(body[-32:], body[:-32])
        return Leaf(item, TNType.ACCOUNT_STATE)
    raise ValueError(f"unknown node prefix {prefix:#x}")


def deserialize_node_wire(blob: bytes):
    """Parse a wire-format node (reference: SHAMapTreeNode ctor, snfWIRE)."""
    if not blob:
        raise ValueError("empty node blob")
    trailer, body = blob[-1], blob[:-1]
    if trailer == _WIRE_INNER_FULL:
        if len(body) != 512:
            raise ValueError("bad full inner length")
        return InnerStub([body[i * 32 : (i + 1) * 32] for i in range(16)])
    if trailer == _WIRE_INNER_COMPRESSED:
        if len(body) % 33:
            raise ValueError("bad compressed inner length")
        hashes = [ZERO256] * 16
        for i in range(0, len(body), 33):
            branch = body[i + 32]
            if branch >= 16:
                raise ValueError(f"bad branch index {branch}")
            hashes[branch] = body[i : i + 32]
        return InnerStub(hashes)
    if trailer == _WIRE_TX_NM:
        return Leaf(SHAMapItem(prefix_hash(HP_TXN_ID, body), body), TNType.TX_NM)
    if trailer == _WIRE_STATE:
        return Leaf(SHAMapItem(body[-32:], body[:-32]), TNType.ACCOUNT_STATE)
    if trailer == _WIRE_TX_MD:
        return Leaf(SHAMapItem(body[-32:], body[:-32]), TNType.TX_MD)
    raise ValueError(f"unknown wire trailer {trailer}")


# --------------------------------------------------------------------------


class SHAMap:
    """Mutable handle over a persistent radix tree.

    Mirrors the reference SHAMap surface (src/ripple_app/shamap/SHAMap.h):
    add/update/del items, hash, snapshot, compare, flush to a NodeStore,
    rebuild from a NodeStore by root hash.
    """

    def __init__(self, leaf_type: TNType = TNType.ACCOUNT_STATE, root=None,
                 hash_batch: Callable = _default_hasher):
        self.leaf_type = leaf_type
        self.root = root if root is not None else EMPTY_INNER
        self.hash_batch = hash_batch

    # -- queries ----------------------------------------------------------

    def get(self, key: bytes) -> Optional[SHAMapItem]:
        return _get(self.root, key, 0)

    def get_leaf(self, key: bytes) -> Optional[Leaf]:
        """Typed leaf lookup, O(depth)."""
        node, depth = self.root, 0
        while node is not None:
            if isinstance(node, Leaf):
                return node if node.item.tag == key else None
            node = node.children[_nibble(key, depth)]
            depth += 1
        return None

    def __contains__(self, key: bytes) -> bool:
        return self.get(key) is not None

    def __len__(self) -> int:
        return sum(1 for _ in _walk_leaves(self.root))

    def items(self) -> Iterator[SHAMapItem]:
        for leaf in _walk_leaves(self.root):
            yield leaf.item

    def leaves(self) -> Iterator[Leaf]:
        """Typed leaves in key order (callers that must distinguish raw-tx
        vs tx+metadata items, reference visitLeaves)."""
        yield from _walk_leaves(self.root)

    def peek_first_item(self) -> Optional[SHAMapItem]:
        for leaf in _walk_leaves(self.root):
            return leaf.item
        return None

    def succ(self, key: bytes) -> Optional[SHAMapItem]:
        """First item with tag strictly greater than `key` (reference:
        SHAMap::peekNextItem — order-book/directory iteration). Key-guided
        descent, O(depth): at each inner node, recurse into the key's own
        branch first, then scan higher branches for their smallest leaf."""

        def smallest(node) -> Optional[SHAMapItem]:
            while isinstance(node, Inner):
                node = next((c for c in node.children if c is not None), None)
            return node.item if node is not None else None

        def descend(node, depth) -> Optional[SHAMapItem]:
            if node is None:
                return None
            if isinstance(node, Leaf):
                return node.item if node.item.tag > key else None
            b = _nibble(key, depth)
            found = descend(node.children[b], depth + 1)
            if found is not None:
                return found
            for c in node.children[b + 1 :]:
                if c is not None:
                    return smallest(c)
            return None

        return descend(self.root, 0)

    # -- mutation ---------------------------------------------------------

    def set_item(self, item: SHAMapItem, leaf_type: Optional[TNType] = None) -> None:
        leaf = Leaf(item, leaf_type or self.leaf_type)
        self.root = _set_item(self.root, item.tag, leaf, 0)

    def del_item(self, key: bytes) -> None:
        root = _del_item(self.root, key, 0)
        if root is None:
            root = EMPTY_INNER
        elif isinstance(root, Leaf):
            # the tree root is always an inner node (reference keeps a root
            # inner even for a single item)
            children = [None] * 16
            children[_nibble(root.item.tag, 0)] = root
            root = Inner(tuple(children))
        self.root = root

    # -- hashing / snapshots ---------------------------------------------

    def get_hash(self) -> bytes:
        if isinstance(self.root, Inner) and self.root.is_empty():
            return ZERO256
        if self.root._hash is None:
            compute_hashes(self.root, self.hash_batch)
        return self.root._hash

    def snapshot(self) -> "SHAMap":
        """O(1) immutable snapshot: share the persistent root."""
        return SHAMap(self.leaf_type, self.root, self.hash_batch)

    # -- delta ------------------------------------------------------------

    def compare(self, other: "SHAMap", limit: int = 2**31) -> dict[bytes, tuple]:
        """Key → (this_item|None, other_item|None) for keys that differ
        (reference: SHAMapDelta.cpp SHAMap::compare). Shared subtrees are
        skipped by object identity / node hash, so the cost is proportional
        to the delta, not the tree."""
        delta: dict[bytes, tuple] = {}

        def same(a, b) -> bool:
            if a is b:
                return True
            if a is None or b is None:
                return False
            if a._hash is not None and a._hash == b._hash:
                return True
            return False

        def walk(a, b):
            if len(delta) > limit or same(a, b):
                return
            if a is None or isinstance(a, Leaf):
                a_items = {a.item.tag: a.item} if isinstance(a, Leaf) else {}
            else:
                a_items = None
            if b is None or isinstance(b, Leaf):
                b_items = {b.item.tag: b.item} if isinstance(b, Leaf) else {}
            else:
                b_items = None
            if a_items is not None or b_items is not None:
                if a_items is None:
                    a_items = {l.item.tag: l.item for l in _walk_leaves(a)}
                if b_items is None:
                    b_items = {l.item.tag: l.item for l in _walk_leaves(b)}
                for tag in set(a_items) | set(b_items):
                    ia, ib = a_items.get(tag), b_items.get(tag)
                    if ia != ib:
                        delta[tag] = (ia, ib)
                return
            for ca, cb in zip(a.children, b.children):
                walk(ca, cb)

        walk(self.root, other.root)
        if len(delta) > limit:
            raise ValueError("delta exceeds limit")
        return delta

    # -- NodeStore integration -------------------------------------------

    def flush(self, store: Callable[[bytes, bytes], None],
              known: Optional[set] = None) -> int:
        """Hash everything, then persist every node the target store does
        not yet have, as (hash → prefix-format blob). Returns the number of
        nodes written.

        `known` is the per-store set of already-flushed hashes (e.g.
        nodestore.Database.flushed); a hash in `known` seals its whole
        subtree (flush adds bottom-up), so shared subtrees across ledger
        versions are skipped and the write cost per close is proportional
        to the delta, not total state. The set is per-store — flushing the
        same tree into a second store writes everything again there
        (the reference's flushDirty dirty-list behaves the same way).
        """
        self.get_hash()
        if known is None:
            known = set()
        count = 0

        def visit(node):
            nonlocal count
            if node is None or node._hash in known:
                return
            if isinstance(node, Inner):
                for c in node.children:
                    visit(c)
            store(node._hash, serialize_node_prefix(node))
            known.add(node._hash)
            count += 1

        if not (isinstance(self.root, Inner) and self.root.is_empty()):
            visit(self.root)
        return count

    @classmethod
    def from_store(
        cls,
        root_hash: bytes,
        fetch: Callable[[bytes], Optional[bytes]],
        leaf_type: TNType = TNType.ACCOUNT_STATE,
        hash_batch: Callable = _default_hasher,
        verify: bool = True,
    ) -> "SHAMap":
        """Materialize a full tree from a content-addressed store
        (reference: SHAMap fetchNodeExternal path). Raises KeyError on a
        missing node (the seam where network acquisition hooks in) and,
        with `verify` (default), ValueError when a fetched blob does not
        hash to its key (the reference verifies fetched nodes the same
        way, SHAMapTreeNode ctor hashValid path)."""
        if root_hash == ZERO256:
            return cls(leaf_type, EMPTY_INNER, hash_batch)

        def load(h: bytes):
            blob = fetch(h)
            if blob is None:
                raise KeyError(f"missing node {h.hex()}")
            node = deserialize_node_prefix(blob)
            if verify:
                # prefix-format blob == exactly the hashed bytes
                from ..utils.hashes import sha512_half

                actual = sha512_half(blob)
                if actual != h:
                    raise ValueError(
                        f"node content hash mismatch: key {h.hex()[:16]} "
                        f"content {actual.hex()[:16]}"
                    )
            if isinstance(node, InnerStub):
                children = tuple(
                    load(ch) if ch != ZERO256 else None for ch in node.child_hashes
                )
                node = Inner(children, hash=h)
            else:
                node._hash = h
            return node

        root = load(root_hash)
        if isinstance(root, Leaf):
            children = [None] * 16
            children[_nibble(root.item.tag, 0)] = root
            root = Inner(tuple(children))
        return cls(leaf_type, root, hash_batch)
