"""SHAMap network synchronization: incremental acquisition of a tree by
root hash, and fetch-pack production/consumption for fast catch-up.

Reference: src/ripple_app/shamap/SHAMapSync.cpp (getMissingNodes,
addKnownNode, getFetchPack) and the fetch-pack tests
(FetchPackTests.cpp). Every arriving node blob is verified against the
hash that named it before it is attached — a malicious peer cannot graft
bad state.

TPU shape: verification of arriving node blobs is batched SHA-512 — an
acquisition burst of N nodes is one BatchHasher call, not N host hashes.
"""

from __future__ import annotations

from typing import Callable, Iterator, Optional, Sequence

from ..utils.hashes import sha512_half
from .shamap import (
    SHAMap,
    TNType,
    ZERO256,
    deserialize_node_prefix,
    serialize_node_prefix,
    InnerStub,
)

__all__ = ["SHAMapNodeID", "IncompleteMap", "make_fetch_pack", "FetchPack"]


class SHAMapNodeID:
    """Position of a node in the tree: nibble path + depth
    (reference: SHAMapNodeID — 33-byte wire encoding, 32-byte padded
    path then a depth byte)."""

    __slots__ = ("path", "depth")

    def __init__(self, path: bytes = b"", depth: int = 0):
        # path holds ceil(depth/2) meaningful nibbles
        self.path = path
        self.depth = depth

    @classmethod
    def root(cls) -> "SHAMapNodeID":
        return cls(b"", 0)

    def child(self, branch: int) -> "SHAMapNodeID":
        nibbles = [self._nibble(i) for i in range(self.depth)] + [branch]
        raw = bytearray((len(nibbles) + 1) // 2)
        for i, nb in enumerate(nibbles):
            raw[i // 2] |= nb << (4 if i % 2 == 0 else 0)
        return SHAMapNodeID(bytes(raw), self.depth + 1)

    def _nibble(self, i: int) -> int:
        byte = self.path[i // 2]
        return (byte >> 4) if i % 2 == 0 else (byte & 0x0F)

    def nibbles(self) -> list[int]:
        return [self._nibble(i) for i in range(self.depth)]

    def encode(self) -> bytes:
        """33-byte wire form: zero-padded path ‖ depth."""
        return self.path.ljust(32, b"\x00") + bytes([self.depth])

    @classmethod
    def decode(cls, blob: bytes) -> "SHAMapNodeID":
        if len(blob) != 33:
            raise ValueError("bad node id")
        depth = blob[32]
        if depth > 64:
            raise ValueError("bad node depth")
        return cls(blob[: (depth + 1) // 2], depth)

    def __eq__(self, other):
        return (
            isinstance(other, SHAMapNodeID)
            and self.depth == other.depth
            and self.nibbles() == other.nibbles()
        )

    def __hash__(self):
        return hash((self.depth, tuple(self.nibbles())))

    def __repr__(self):
        return f"NodeID({''.join(f'{n:x}' for n in self.nibbles())}@{self.depth})"


class IncompleteMap:
    """A tree being synchronized from the network, identified by its root
    hash. Feed it `(node_id, blob)` pairs (from LedgerData replies or a
    fetch pack); ask it for `missing_nodes()` to request next. Blobs are
    prefix-format (the hashed byte sequence), so verification is
    `sha512_half(blob) == expected-hash-at-position`.
    """

    def __init__(self, root_hash: bytes, leaf_type: TNType = TNType.ACCOUNT_STATE,
                 hash_many: Optional[Callable[[Sequence[bytes]], list]] = None):
        self.root_hash = root_hash
        self.leaf_type = leaf_type
        self.hash_many = hash_many  # batched SHA-512-half over blobs
        self.nodes: dict[bytes, bytes] = {}  # node hash -> blob
        # node hash -> [(branch, child_hash)] (for parsed inners)
        self._children: dict[bytes, list[tuple[int, bytes]]] = {}
        # incremental frontier: position -> expected hash. Maintained by
        # _attach so progress queries never re-walk the whole tree (an
        # acquisition is O(nodes), not O(nodes²))
        self._missing: dict[SHAMapNodeID, bytes] = {}
        self._missing_by_hash: dict[bytes, set[SHAMapNodeID]] = {}
        if root_hash != ZERO256:
            self._note_missing(SHAMapNodeID.root(), root_hash)

    def _note_missing(self, nid: SHAMapNodeID, h: bytes) -> None:
        if h in self.nodes:
            # already have the content — expand straight through it
            for branch, ch in self._children.get(h, ()):
                self._note_missing(nid.child(branch), ch)
        else:
            self._missing[nid] = h
            self._missing_by_hash.setdefault(h, set()).add(nid)

    # -- feeding ----------------------------------------------------------

    def _digest_all(self, blobs: Sequence[bytes]) -> list[bytes]:
        if self.hash_many is not None:
            return list(self.hash_many(blobs))
        return [sha512_half(b) for b in blobs]

    def add_nodes(self, pairs: Sequence[tuple[bytes, bytes]]) -> int:
        """Add `(expected_hash, blob)` pairs; hash verification is one
        batch. Returns how many were new and valid."""
        fresh = [(h, b) for h, b in pairs if h not in self.nodes]
        if not fresh:
            return 0
        digests = self._digest_all([b for _h, b in fresh])
        added = 0
        for (h, blob), actual in zip(fresh, digests):
            if actual != h:
                continue  # corrupted/forged node — drop
            self._attach(h, blob)
            added += 1
        return added

    def add_known_node(self, expected_hash: bytes, blob: bytes) -> bool:
        """Single-node path (reference: addKnownNode)."""
        return self.add_nodes([(expected_hash, blob)]) == 1

    def _attach(self, h: bytes, blob: bytes) -> None:
        self.nodes[h] = blob
        node = deserialize_node_prefix(blob)
        if isinstance(node, InnerStub):
            self._children[h] = [
                (branch, c)
                for branch, c in enumerate(node.child_hashes)
                if c != ZERO256
            ]
        # resolve every frontier position waiting on this hash
        for nid in self._missing_by_hash.pop(h, set()):
            self._missing.pop(nid, None)
            for branch, ch in self._children.get(h, ()):
                self._note_missing(nid.child(branch), ch)

    # -- progress ---------------------------------------------------------

    def missing_nodes(self, limit: int = 256) -> list[tuple[SHAMapNodeID, bytes]]:
        """(node_id, node_hash) pairs we still need — read straight off
        the incrementally-maintained frontier (reference: getMissingNodes,
        which walks; here _attach keeps the frontier current so this is
        O(limit))."""
        out = []
        for nid, h in self._missing.items():
            out.append((nid, h))
            if len(out) >= limit:
                break
        return out

    def is_complete(self) -> bool:
        return not self._missing

    def have_node(self, h: bytes) -> bool:
        return h in self.nodes

    # -- completion -------------------------------------------------------

    def to_shamap(self, hash_batch: Optional[Callable] = None) -> SHAMap:
        assert self.is_complete(), "tree still has missing nodes"
        if hash_batch is not None:
            return SHAMap.from_store(
                self.root_hash, self.nodes.get, self.leaf_type,
                hash_batch, verify=False,  # verified on arrival
            )
        return SHAMap.from_store(
            self.root_hash, self.nodes.get, self.leaf_type, verify=False
        )


# -- fetch packs ----------------------------------------------------------


class FetchPack:
    """A bundle of (hash, blob) node pairs covering a ledger's trees (or
    their delta against a base), used to catch up without per-node
    round-trips (reference: getFetchPack / TMGetObjectByHash pack)."""

    def __init__(self, pairs: Optional[list[tuple[bytes, bytes]]] = None):
        self.pairs = pairs or []

    def __len__(self):
        return len(self.pairs)

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        return iter(self.pairs)


def _walk_nodes(map: SHAMap) -> Iterator[tuple[bytes, bytes]]:
    from .shamap import resolve_node

    map.get_hash()

    def visit(node):
        node = resolve_node(node)  # lazy trees: fault before serving
        if node is None:
            return
        yield node._hash, serialize_node_prefix(node)
        if hasattr(node, "children"):
            for c in node.children:
                yield from visit(c)

    from .shamap import Inner

    if isinstance(map.root, Inner) and map.root.is_empty():
        return
    yield from visit(map.root)


def make_fetch_pack(
    target: SHAMap, base: Optional[SHAMap] = None, max_nodes: int = 65536
) -> FetchPack:
    """All nodes of `target` (minus subtrees shared with `base`, matched
    by node hash — the reference builds packs as the delta against the
    requester's stated ledger)."""
    if base is None:
        pairs = []
        for h, blob in _walk_nodes(target):
            pairs.append((h, blob))
            if len(pairs) >= max_nodes:
                break
        return FetchPack(pairs)

    from .shamap import resolve_node

    base.get_hash()
    base_hashes: set[bytes] = set()

    def collect(node):
        node = resolve_node(node)
        if node is None:
            return
        base_hashes.add(node._hash)
        if hasattr(node, "children"):
            for c in node.children:
                collect(c)

    from .shamap import Inner

    if not (isinstance(base.root, Inner) and base.root.is_empty()):
        collect(base.root)

    target.get_hash()
    pairs: list[tuple[bytes, bytes]] = []

    def visit(node):
        if node is None or node._hash in base_hashes or len(pairs) >= max_nodes:
            return
        node = resolve_node(node)  # hash checks above never fault
        pairs.append((node._hash, serialize_node_prefix(node)))
        if hasattr(node, "children"):
            for c in node.children:
                visit(c)

    if not (isinstance(target.root, Inner) and target.root.is_empty()):
        visit(target.root)
    return FetchPack(pairs)
