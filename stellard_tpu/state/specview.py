"""SpecView: a ledger facade for speculative close-mode execution.

The delta-replay close (engine/deltareplay.py) re-executes each
open-accepted transaction in CLOSE mode at submit time, against the state
the real close will start from (the open ledger's state map never mutates
during the open window, so it IS the parent snapshot the close applies
onto). This module provides the view that execution runs against:

- an overlay of the speculative writes accumulated so far this open
  ledger (so same-account sequence chains and dependent txs execute
  against post-predecessor state, exactly like the serial close), and
- read/write-set capture in the Block-STM style (Gelashvili et al.,
  2022): every entry read records (key -> last writer id), every
  order-book/directory ``state_map.succ`` walk records
  (cursor -> next key), every write records the final SLE.

Writer ids are the txids of earlier speculative transactions, or the
PARENT sentinel for state inherited from the parent ledger. At close the
replay context validates a record by comparing these against the close's
own writer map — value equality by provenance, not version arithmetic —
and the succ records against the closing ledger's real state map (phantom
protection for book walks: an entry INSERTED between cursor and the
recorded next key must invalidate, which no per-key version can see).

The facade implements exactly the Ledger surface the close-mode engine
touches (audited in engine/, paths/flow.py, engine/offers.py); anything
else raising AttributeError is a seam audit failure, not a fallback.
"""

from __future__ import annotations

from bisect import bisect_right, insort
from typing import Optional

from ..protocol.stobject import STObject
from ..utils.hashes import HP_TXN_ID, prefix_hash
from .ledger import Ledger

__all__ = ["SpecView", "PARENT"]

# writer-id sentinel for "inherited from the parent ledger"; never
# collides with a txid (txids are 32 bytes)
PARENT = b"\x00parent"


class _ShimItem:
    """Minimal SHAMapItem stand-in for overlay-created keys returned by
    the succ shim (callers use .tag only — offers.py / paths/flow.py)."""

    __slots__ = ("tag",)

    def __init__(self, tag: bytes):
        self.tag = tag


class _StateMapShim:
    """state_map facade: parent map merged with the overlay for the
    ``succ`` order-book walks (engine/offers.py:179, paths/flow.py:275).
    Every result is captured as a range read."""

    __slots__ = ("_view",)

    def __init__(self, view: "SpecView"):
        self._view = view

    def succ(self, key: bytes):
        v = self._view
        # parent candidate, skipping keys the overlay deleted
        cur = key
        while True:
            item = v._parent.state_map.succ(cur)
            if item is None or v._overlay.get(item.tag, _MISS) is not None:
                break
            cur = item.tag
        created = v._created_after(key)
        if item is not None and (created is None or item.tag < created):
            res = item
        elif created is not None:
            res = _ShimItem(created)
        else:
            res = None
        v._succs.append((key, res.tag if res is not None else None))
        return res


class _TxMapShim:
    """tx_map facade: only ``get`` (Transactor::checkSeq's tefALREADY
    probe) is reachable in close mode; membership is the speculatively
    applied set."""

    __slots__ = ("_applied",)

    def __init__(self):
        self._applied: set[bytes] = set()

    def get(self, txid: bytes):
        return True if txid in self._applied else None

    def add(self, txid: bytes) -> None:
        self._applied.add(txid)


_MISS = object()


class SpecView:
    """Overlay view over an OPEN ledger with per-tx read/write capture.

    One instance lives for the whole open window; ``begin_tx`` /
    ``end_tx`` bracket each speculative execution. Callers run under the
    LedgerMaster lock, so no internal locking."""

    # borrowed verbatim: both read only scalar attrs this view carries
    reserve = Ledger.reserve
    scale_fee_load = Ledger.scale_fee_load

    def __init__(self, ledger: Ledger):
        self._parent = ledger
        # header scalars the close-mode engine/transactors read; the
        # close ledger is a sibling successor of the same parent, so
        # these are byte-equal to what the close view will present
        self.seq = ledger.seq
        self.parent_close_time = ledger.parent_close_time
        self.base_fee = ledger.base_fee
        self.reference_fee_units = ledger.reference_fee_units
        self.reserve_base = ledger.reserve_base
        self.reserve_increment = ledger.reserve_increment
        self.load_factor = ledger.load_factor
        # engine-mutated scratch (fee burn, inflation header deltas):
        # consumed per record, never written back to the real ledger
        self.tot_coins = ledger.tot_coins
        self.fee_pool = ledger.fee_pool
        self.inflation_seq = ledger.inflation_seq
        self.parsed_metas: dict[bytes, STObject] = {}
        self.state_map = _StateMapShim(self)
        self.tx_map = _TxMapShim()
        # overlay: key -> final SLE (None = deleted); writers: key ->
        # txid of the last speculative writer
        self._overlay: dict[bytes, Optional[STObject]] = {}
        self._writers: dict[bytes, bytes] = {}
        self._created: list[bytes] = []  # sorted overlay-created keys
        self._created_set: set[bytes] = set()
        # per-tx capture
        self._reads: dict[bytes, bytes] = {}
        self._succs: list[tuple[bytes, Optional[bytes]]] = []
        self._writes: list[tuple[bytes, Optional[STObject]]] = []
        self._txid: bytes = b""

    # -- capture brackets -------------------------------------------------

    def begin_tx(self, txid: bytes) -> None:
        self._txid = txid
        self._reads = {}
        self._succs = []
        self._writes = []

    def end_tx(self):
        """-> (reads, succs, writes) captured since begin_tx."""
        return self._reads, self._succs, self._writes

    # -- Ledger read surface ----------------------------------------------

    def read_entry_pristine(self, index: bytes) -> Optional[STObject]:
        sle = self._overlay.get(index, _MISS)
        if sle is not _MISS:
            if index not in self._reads:
                self._reads[index] = self._writers[index]
            return sle
        if index not in self._reads:
            self._reads[index] = PARENT
        return self._parent.read_entry_pristine(index)

    # -- Ledger write surface (reached only via LedgerEntrySet.apply /
    # the engine's commit tail, i.e. after a successful execution) --------

    def write_entry(self, index: bytes, sle: STObject) -> None:
        prev = self._overlay.get(index, _MISS)
        if index not in self._created_set and (prev is _MISS or prev is None):
            # key springing into existence: it joins the succ-shim merge
            # list only when the parent map lacks it (existence probe on
            # the raw map — not an execution read, so not captured)
            if self._parent.state_map.get(index) is None:
                insort(self._created, index)
                self._created_set.add(index)
        self._overlay[index] = sle
        self._writers[index] = self._txid
        self._writes.append((index, sle))

    def delete_entry(self, index: bytes) -> None:
        if index in self._created_set:
            self._created_set.remove(index)
            i = bisect_right(self._created, index) - 1
            if 0 <= i < len(self._created) and self._created[i] == index:
                del self._created[i]
        self._overlay[index] = None
        self._writers[index] = self._txid
        self._writes.append((index, None))

    def record_transaction(self, tx_blob: bytes, meta: STObject) -> bytes:
        """Engine commit-tail seam: membership for the checkSeq probe +
        the meta object for the record — the meta bytes are never needed
        here (the splice re-serializes after re-indexing anyway)."""
        txid = prefix_hash(HP_TXN_ID, tx_blob)
        self.tx_map.add(txid)
        self.parsed_metas[txid] = meta
        return txid

    # -- succ-shim helpers ------------------------------------------------

    def _created_after(self, key: bytes) -> Optional[bytes]:
        i = bisect_right(self._created, key)
        return self._created[i] if i < len(self._created) else None
