"""SpecView: a ledger facade for speculative close-mode execution.

The delta-replay close (engine/deltareplay.py) re-executes each
open-accepted transaction in CLOSE mode at submit time, against the state
the real close will start from (the open ledger's state map never mutates
during the open window, so it IS the parent snapshot the close applies
onto). This module provides the view that execution runs against:

- an overlay of the speculative writes accumulated so far this open
  ledger (so same-account sequence chains and dependent txs execute
  against post-predecessor state, exactly like the serial close), and
- read/write-set capture in the Block-STM style (Gelashvili et al.,
  2022): every entry read records (key -> last writer id), every
  order-book/directory ``state_map.succ`` walk records
  (cursor -> next key), every write records the final SLE.

Writer ids are the txids of earlier speculative transactions, or the
PARENT sentinel for state inherited from the parent ledger. At close the
replay context validates a record by comparing these against the close's
own writer map — value equality by provenance, not version arithmetic —
and the succ records against the closing ledger's real state map (phantom
protection for book walks: an entry INSERTED between cursor and the
recorded next key must invalidate, which no per-key version can see).

The parallel apply plane (engine/specexec.py) reuses this view as the
COMMITTED state of its Block-STM scheduler: worker executions capture
against a read-only alias of the overlay, and the commit step folds
validated records back in through ``apply_record`` — in speculation-index
order, by a single committer — so the overlay a later transaction reads
is byte-identical to what the serial path would have built. For process
workers the view also provides a picklable scalar snapshot
(``snapshot_scalars`` / ``from_snapshot``) and an incremental delta apply
(``apply_delta``), so a worker's local replica is a serialized parent
snapshot plus the shipped committed-writer map, never a full state copy.

The facade implements exactly the Ledger surface the close-mode engine
touches (audited in engine/, paths/flow.py, engine/offers.py); anything
else raising AttributeError is a seam audit failure, not a fallback.
"""

from __future__ import annotations

from bisect import bisect_right, insort
from typing import Optional

from ..protocol.stobject import STObject
from ..utils.hashes import HP_TXN_ID, prefix_hash
from .ledger import Ledger
from .shamap import SHAMapItem

__all__ = ["SpecView", "PARENT", "SCALARS"]

# writer-id sentinel for "inherited from the parent ledger"; never
# collides with a txid (txids are 32 bytes)
PARENT = b"\x00parent"

# the header scalars the close-mode engine/transactors read; one tuple so
# the in-process view, the picklable worker snapshot, and the capture
# alias can never drift on which fields a worker must carry
SCALARS = (
    "seq", "parent_close_time", "base_fee", "reference_fee_units",
    "reserve_base", "reserve_increment", "load_factor",
    "tot_coins", "fee_pool", "inflation_seq",
)


class _ShimItem:
    """Minimal SHAMapItem stand-in for overlay-created keys returned by
    the succ shim (callers use .tag only — offers.py / paths/flow.py)."""

    __slots__ = ("tag",)

    def __init__(self, tag: bytes):
        self.tag = tag


class _StateMapShim:
    """state_map facade: parent map merged with the overlay for the
    ``succ`` order-book walks (engine/offers.py:179, paths/flow.py:275).
    Every result is captured as a range read."""

    __slots__ = ("_view",)

    def __init__(self, view: "SpecView"):
        self._view = view

    def succ(self, key: bytes):
        v = self._view
        res = v.resolve_succ(key)
        v._succs.append((key, res.tag if res is not None else None))
        return res


class _TxMapShim:
    """tx_map facade: only ``get`` (Transactor::checkSeq's tefALREADY
    probe) is reachable in close mode; membership is the speculatively
    applied set."""

    __slots__ = ("_applied",)

    def __init__(self):
        self._applied: set[bytes] = set()

    def get(self, txid: bytes):
        return True if txid in self._applied else None

    def add(self, txid: bytes) -> None:
        self._applied.add(txid)


_MISS = object()


class SpecView:
    """Overlay view over an OPEN ledger with per-tx read/write capture.

    One instance lives for the whole open window; ``begin_tx`` /
    ``end_tx`` bracket each speculative execution. Serial callers run
    under the LedgerMaster lock; with the parallel executor, overlay
    mutation is confined to the single commit thread and worker reads
    are optimistic (any torn read is caught by commit validation)."""

    # borrowed verbatim: both read only scalar attrs this view carries
    reserve = Ledger.reserve
    scale_fee_load = Ledger.scale_fee_load

    def __init__(self, ledger: Ledger):
        self._parent = ledger
        # header scalars the close-mode engine/transactors read; the
        # close ledger is a sibling successor of the same parent, so
        # these are byte-equal to what the close view will present.
        # (tot_coins/fee_pool/inflation_seq are engine-mutated scratch —
        # fee burn, inflation header deltas — consumed per record,
        # never written back to the real ledger.)
        for name in SCALARS:
            setattr(self, name, getattr(ledger, name))
        self.parsed_metas: dict[bytes, STObject] = {}
        self.state_map = _StateMapShim(self)
        self.tx_map = _TxMapShim()
        # overlay: key -> final SLE (None = deleted); writers: key ->
        # txid of the last speculative writer
        self._overlay: dict[bytes, Optional[STObject]] = {}
        self._writers: dict[bytes, bytes] = {}
        self._created: list[bytes] = []  # sorted overlay-created keys
        self._created_set: set[bytes] = set()
        # per-tx capture
        self._reads: dict[bytes, bytes] = {}
        self._succs: list[tuple[bytes, Optional[bytes]]] = []
        self._writes: list[tuple[bytes, Optional[STObject]]] = []
        self._txid: bytes = b""

    # -- worker transport (engine/specexec.py process mode) ---------------

    def snapshot_scalars(self) -> dict:
        """Picklable header-scalar snapshot for worker transport: with a
        parent adapter (read-through to the real parent state) this is
        ALL the per-window state a worker needs up front — the overlay
        arrives incrementally as committed-writer deltas."""
        return {name: getattr(self, name) for name in SCALARS}

    @classmethod
    def from_snapshot(cls, scalars: dict, parent) -> "SpecView":
        """Rebuild a view in a worker process from ``snapshot_scalars``
        output plus a parent adapter exposing ``read_entry_pristine``
        and ``state_map.get/succ`` (the read-through IPC shim)."""
        view = cls.__new__(cls)
        view._parent = parent
        for name in SCALARS:
            setattr(view, name, scalars[name])
        view.parsed_metas = {}
        view.state_map = _StateMapShim(view)
        view.tx_map = _TxMapShim()
        view._overlay = {}
        view._writers = {}
        view._created = []
        view._created_set = set()
        view._reads = {}
        view._succs = []
        view._writes = []
        view._txid = b""
        return view

    # -- capture brackets -------------------------------------------------

    def begin_tx(self, txid: bytes) -> None:
        self._txid = txid
        self._reads = {}
        self._succs = []
        self._writes = []

    def end_tx(self):
        """-> (reads, succs, writes) captured since begin_tx."""
        return self._reads, self._succs, self._writes

    # -- Ledger read surface ----------------------------------------------

    def read_entry_pristine(self, index: bytes) -> Optional[STObject]:
        sle = self._overlay.get(index, _MISS)
        if sle is not _MISS:
            if type(sle) is SHAMapItem:
                sle = self._upgrade(index, sle)
            if index not in self._reads:
                # .get with the PARENT default (not [index]): a parallel
                # worker may observe the overlay key before the writer
                # entry lands — commit validation rejects the torn read
                self._reads[index] = self._writers.get(index, PARENT)
            return sle
        if index not in self._reads:
            self._reads[index] = PARENT
        return self._parent.read_entry_pristine(index)

    def _upgrade(self, index: bytes, item: SHAMapItem) -> STObject:
        """Parse a lazily-committed write item and promote it in place.
        Only commit-serialized readers (the committer's serial
        fallbacks, the close after end_window) may call this: the
        store-back mutates the shared overlay, and a thread-mode worker
        doing it concurrently with a commit could clobber a newer
        committed value with this stale parse."""
        sle = item.parsed
        if sle is None:
            sle = item.parsed = STObject.from_bytes(item.data)
        self._overlay[index] = sle
        return sle

    def peek(self, key: bytes):
        """(value, writer-provenance) for the MERGED view — overlay hit
        returns the committed writer's txid, parent fall-through returns
        PARENT — with NO read capture and NO overlay mutation: thread-
        mode workers call this concurrently with the committer, so the
        parse memo lands only on the item (idempotent), never as a
        store-back. Provenance is read BEFORE the value: paired with
        apply_record's value-before-writer store order, a torn read can
        only pair a NEWER value with an OLDER writer id — which commit
        validation rejects — never a stale value with the current
        writer id, which it would wrongly pass."""
        w = self._writers.get(key, PARENT)
        v = self._overlay.get(key, _MISS)
        if v is not _MISS:
            if type(v) is SHAMapItem:
                sle = v.parsed
                if sle is None:
                    sle = v.parsed = STObject.from_bytes(v.data)
                v = sle
            return v, w
        return self._parent.read_entry_pristine(key), PARENT

    def merged_has(self, key: bytes) -> bool:
        """Existence probe on the merged view (no parse, no capture) —
        the worker-view write path's spring-into-existence check."""
        v = self._overlay.get(key, _MISS)
        if v is not _MISS:
            return v is not None
        return self._parent.state_map.get(key) is not None

    def resolve_succ(self, key: bytes):
        """Overlay-merged ``state_map.succ``: the parent map's successor
        (skipping overlay-deleted keys) merged with overlay-created keys.
        Shared by the capture shim, the parallel executor's commit-time
        succ re-validation, and the serial path — one resolution, three
        callers."""
        cur = key
        while True:
            item = self._parent.state_map.succ(cur)
            if item is None or self._overlay.get(item.tag, _MISS) is not None:
                break
            cur = item.tag
        created = self._created_after(key)
        if item is not None and (created is None or item.tag < created):
            return item
        if created is not None:
            return _ShimItem(created)
        return None

    def _created_remove(self, key: bytes) -> bool:
        """Drop ``key`` from the overlay-created bookkeeping (set + the
        sorted succ-merge list). One definition for every writer — the
        serial write surface, the commit fold, worker delta application,
        and the worker replica's tentative chain/rollback — so the
        bisect boundary can never drift between copies. -> True when the
        key was tracked."""
        if key not in self._created_set:
            return False
        self._created_set.discard(key)
        i = bisect_right(self._created, key) - 1
        if 0 <= i < len(self._created) and self._created[i] == key:
            del self._created[i]
        return True

    # -- Ledger write surface (reached only via LedgerEntrySet.apply /
    # the engine's commit tail, i.e. after a successful execution) --------

    def write_entry(self, index: bytes, sle: STObject) -> None:
        prev = self._overlay.get(index, _MISS)
        if index not in self._created_set and (prev is _MISS or prev is None):
            # key springing into existence: it joins the succ-shim merge
            # list only when the parent map lacks it (existence probe on
            # the raw map — not an execution read, so not captured)
            if self._parent.state_map.get(index) is None:
                insort(self._created, index)
                self._created_set.add(index)
        self._overlay[index] = sle
        self._writers[index] = self._txid
        self._writes.append((index, sle))

    def delete_entry(self, index: bytes) -> None:
        self._created_remove(index)
        self._overlay[index] = None
        self._writers[index] = self._txid
        self._writes.append((index, None))

    def record_transaction(self, tx_blob: bytes, meta: STObject) -> bytes:
        """Engine commit-tail seam: membership for the checkSeq probe +
        the meta object for the record — the meta bytes are never needed
        here (the splice re-serializes after re-indexing anyway)."""
        txid = prefix_hash(HP_TXN_ID, tx_blob)
        self.tx_map.add(txid)
        self.parsed_metas[txid] = meta
        return txid

    # -- committed-state application (engine/specexec.py) -----------------

    def apply_record(self, txid: bytes, write_items, applied: bool):
        """Fold one validated parallel record's compacted write set into
        the overlay, exactly as the serial write surface would have —
        same spring-into-existence probe, same created-list upkeep —
        but with no capture (this is the COMMIT step, not an execution).
        Single-committer discipline: only the executor's commit thread
        calls this. Returns (created_added, created_removed) for the
        process-worker delta log."""
        added: list[bytes] = []
        removed: list[bytes] = []
        for k, item in write_items:
            if item is None:
                if self._created_remove(k):
                    removed.append(k)
                self._overlay[k] = None
            else:
                prev = self._overlay.get(k, _MISS)
                if k not in self._created_set and (prev is _MISS or prev is None):
                    if self._parent.state_map.get(k) is None:
                        insort(self._created, k)
                        self._created_set.add(k)
                        added.append(k)
                # store the item raw: the read path's _upgrade parses
                # lazily, keeping the commit thread off the per-write
                # STObject parse (wire items arrive unparsed)
                self._overlay[k] = item
            # writer AFTER the value (peek reads in the opposite order):
            # an optimistic reader can then only pair a stale PROVENANCE
            # with a newer value — a conservative validation abort — and
            # never the unsafe converse (stale value, current writer id),
            # which validation would pass
            self._writers[k] = txid
        if applied:
            self.tx_map.add(txid)
        return added, removed

    def apply_delta(self, txid: bytes, pairs, created_added,
                    created_removed, applied: bool,
                    writer=None) -> None:
        """Worker-side mirror of one committed record: raw (key, bytes)
        write pairs plus the AUTHORITATIVE created-set delta computed by
        the parent committer — so the worker replica never probes the
        parent map for existence (each probe would be an IPC round
        trip). ``writer`` overrides the provenance stored for these keys
        — the parallel executor passes an (txid, attempt) epoch so a
        read of a TENTATIVE (possibly-aborted) value can never validate
        against the txid's eventually-committed execution."""
        wid = writer if writer is not None else txid
        for k, data in pairs:
            self._writers[k] = wid
            # store the raw item and let the read path's _upgrade parse
            # it lazily: most committed writes are never read by this
            # replica, so the eager per-delta STObject parse is waste
            self._overlay[k] = (
                SHAMapItem(k, data) if data is not None else None
            )
        for k in created_removed:
            self._created_remove(k)
        for k in created_added:
            if k not in self._created_set:
                insort(self._created, k)
                self._created_set.add(k)
        if applied:
            self.tx_map.add(txid)

    # -- succ-shim helpers ------------------------------------------------

    def _created_after(self, key: bytes) -> Optional[bytes]:
        i = bisect_right(self._created, key)
        return self._created[i] if i < len(self._created) else None
