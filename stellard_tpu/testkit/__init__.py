"""Adversarial scenario plane.

One scenario definition — validators, fault schedule, workload,
byzantine slots — drives TWO transports:

- the deterministic in-process simnet (``scenario.run_simnet``): seeded,
  discrete-time, bit-reproducible — the same seed replays the identical
  fault schedule and produces the identical per-scenario scorecard
  (FoundationDB's deterministic-simulation argument, SIGMOD 2021);
- the real TCP+TLS process net (``scenario.run_tcp``, tools/netlab.py
  plumbing): wall-clock, kill -9 real processes — the same scenario
  shape under genuine sockets and schedulers.

Yuan et al. (OSDI 2014) found most catastrophic distributed-system
failures hide in untested error-handling paths reachable by SIMPLE fault
injection; this package makes those paths a regression-gated surface
(tools/scenariosmoke.py in tier-1) instead of a soak-day anecdote.
"""

from .schedule import FaultSchedule
from .scenario import Scenario, run_simnet
from .scenarios import MATRIX, build_scenario, corpus_scenarios, load_corpus

__all__ = [
    "FaultSchedule",
    "Scenario",
    "run_simnet",
    "MATRIX",
    "build_scenario",
    "load_corpus",
    "corpus_scenarios",
]
