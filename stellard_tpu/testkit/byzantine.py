"""ByzantineValidator: a trusted-but-hostile simnet validator slot.

It runs a REAL ValidatorNode (so its equivocations carry valid
signatures from a key every honest node trusts — the dangerous case)
and corrupts its outputs per behavior profile:

    equivocate       sign a second, conflicting proposal per position
                     and send both to a subset of peers
    duplicate        re-send every proposal and validation frame
    forge            emit validations signed by a NON-UNL rogue key and
                     validations with corrupted signatures
    stale            emit trusted-key validations with signing times far
                     outside the currency window (replayed history)
    garbage          send malformed frames (absurd length prefixes,
                     out-of-schema message types)
    oversized        send candidate tx sets past MAX_TXSET_BLOBS

Honest nodes must (a) keep converging on one chain and (b) prove via
``defense`` counters + tracer events that each hostile input was seen
and neutralized — the anti-vacuity half of every byzantine scenario.
"""

from __future__ import annotations

import random

from ..consensus.proposal import LedgerProposal
from ..consensus.txset import MAX_TXSET_BLOBS
from ..consensus.validation import STValidation
from ..overlay.simnet import RelayPeer, SimValidator
from ..overlay.wire import ProposeSet, TxSetData, ValidationMessage, frame
from ..protocol.keys import KeyPair

__all__ = ["ByzantineValidator", "BEHAVIORS", "FlooderPeer",
           "FLOOD_BEHAVIORS"]

BEHAVIORS = (
    "equivocate", "duplicate", "forge", "stale", "garbage", "oversized",
)


class ByzantineValidator(SimValidator):
    def __init__(self, net, nid, key, unl, quorum, idle_interval,
                 behaviors=BEHAVIORS, seed: int = 0, **kw):
        super().__init__(net, nid, key, unl, quorum, idle_interval, **kw)
        self.behaviors = frozenset(behaviors)
        self.rng = random.Random(0xB42 ^ seed ^ nid)
        self.rogue = KeyPair.from_passphrase(f"byz-rogue-{seed}-{nid}")
        self.emitted: dict[str, int] = {b: 0 for b in self.behaviors}
        self._sent_validations: list[bytes] = []

    def _others(self) -> list[int]:
        return [i for i in range(len(self.net.validators)) if i != self.nid]

    def _emit(self, behavior: str) -> None:
        self.emitted[behavior] = self.emitted.get(behavior, 0) + 1

    # -- corrupted adapter outputs ----------------------------------------

    def propose(self, proposal) -> None:
        data = frame(ProposeSet.from_proposal(proposal))
        for dst in self._others():
            self.net.send(self.nid, dst, data)
        if "duplicate" in self.behaviors:
            self._emit("duplicate")
            self.net.send(self.nid, self._others()[0], data)
        if "equivocate" in self.behaviors:
            # a SIGNED conflicting position at the same propose_seq —
            # sent to peers that also saw the real one, so the
            # conflicting-proposal defense actually fires
            fake = LedgerProposal(
                prev_ledger=proposal.prev_ledger,
                propose_seq=proposal.propose_seq,
                tx_set_hash=bytes([proposal.propose_seq & 0xFF] * 32),
                close_time=proposal.close_time,
            )
            fake.sign(self.node.key)
            fdata = frame(ProposeSet.from_proposal(fake))
            self._emit("equivocate")
            for dst in self._others()[: max(1, len(self._others()) // 2)]:
                self.net.send(self.nid, dst, fdata)

    def send_validation(self, val) -> None:
        data = frame(ValidationMessage(val.serialize()))
        self.net.broadcast(self.nid, data)
        self._sent_validations.append(val.serialize())
        if "duplicate" in self.behaviors:
            self._emit("duplicate")
            self.net.send(self.nid, self._others()[0], data)
        if "forge" in self.behaviors:
            self._emit("forge")
            # same statement signed by a key nobody trusts
            rogue = STValidation.from_bytes(val.serialize())
            rogue.sign(self.rogue)
            self.net.broadcast(
                self.nid, frame(ValidationMessage(rogue.serialize()))
            )
            # and a trusted-key statement with a corrupted signature
            broken = STValidation.from_bytes(val.serialize())
            sig = bytearray(broken.signature)
            sig[0] ^= 0xFF
            from ..protocol.sfields import sfSignature

            broken.obj[sfSignature] = bytes(sig)
            broken.set_sig_verdict(None)
            self.net.broadcast(
                self.nid, frame(ValidationMessage(broken.serialize()))
            )

    # -- per-step active hostility ----------------------------------------

    def act(self, step: int) -> None:
        """Called by the scenario runner once per step, BEFORE net.step().
        Deterministic: all randomness rides this validator's seeded rng."""
        others = self._others()
        if "garbage" in self.behaviors and step % 7 == 3:
            self._emit("garbage")
            dst = others[self.rng.randrange(len(others))]
            if self.rng.random() < 0.5:
                # absurd length prefix: FrameReader raises "oversized"
                self.net.send(self.nid, dst, b"\xff\xff\xff\xff\x00\x1e")
            else:
                # out-of-schema message type (mt 99)
                self.net.send(
                    self.nid, dst,
                    (3).to_bytes(4, "big") + (99).to_bytes(2, "big")
                    + b"\x00\x01\x02",
                )
        if "oversized" in self.behaviors and step % 11 == 5:
            self._emit("oversized")
            dst = others[self.rng.randrange(len(others))]
            msg = TxSetData(
                bytes(32), [b"j"] * (MAX_TXSET_BLOBS + 1)
            )
            self.net.send(self.nid, dst, frame(msg))
        if "stale" in self.behaviors and step % 9 == 4:
            self._emit("stale")
            from ..consensus.timing import LEDGER_VAL_INTERVAL

            lcl = self.node.lm.closed_ledger()
            old = STValidation.build(
                lcl.hash(),
                signing_time=max(
                    1, self.net.network_time() - LEDGER_VAL_INTERVAL - 30
                ),
                ledger_seq=lcl.seq,
            )
            old.sign(self.node.key)
            self.net.broadcast(
                self.nid, frame(ValidationMessage(old.serialize()))
            )


FLOOD_BEHAVIORS = ("garbage_flood", "dup_flood", "junk_tx_flood")


class FlooderPeer(RelayPeer):
    """A hostile relay-tier peer for the production-fan-in scenarios:
    it floods honest nodes at a configurable burst rate with

        garbage_flood    malformed frames — absurd length prefixes and
                         out-of-schema message types (FEE_INVALID_REQUEST
                         per frame at every receiver)
        dup_flood        the SAME fabricated proposal frame re-sent to
                         the same targets every step — the same-source
                         duplicate signature the resource plane prices
                         (FEE_UNWANTED_DATA per re-send)
        junk_tx_flood    TxMessage frames carrying unparseable blobs
                         (FEE_BAD_DATA at every validator that tries)

    The defense contract the scenarios assert: every honest node's
    ResourceManager walks this peer's balance to DROP, deliveries from
    it are then REFUSED (disconnect + gated readmission, visible in
    ``net.refusals``/`resource.*` counters), and honest consensus close
    cadence holds within budget of the no-flooder run of the same seed.
    Deterministic: all randomness rides one seeded rng.
    """

    def __init__(self, net, nid: int, behaviors=FLOOD_BEHAVIORS,
                 seed: int = 0, burst: int = 8, fan: int = 16):
        super().__init__(net, nid)
        self.behaviors = frozenset(behaviors)
        self.rng = random.Random(0xF700D ^ seed ^ nid)
        self.burst = burst  # frames per target per step
        self.fan = fan      # targets per step
        self.emitted: dict[str, int] = {b: 0 for b in self.behaviors}
        # one fabricated proposal frame, re-sent forever (the dup flood)
        fake = LedgerProposal(
            prev_ledger=bytes(32), propose_seq=1,
            tx_set_hash=bytes([0xF1] * 32), close_time=1,
        )
        fake.sign(KeyPair.from_passphrase(f"flooder-{seed}-{nid}"))
        self._dup_frame = frame(ProposeSet.from_proposal(fake))
        # a STABLE neighbor set, like a real overlay session list: a
        # flooder hammers the peers it is connected to, which is what
        # walks those endpoints' balances to DROP (spraying one frame
        # across 1000 nodes never crosses any threshold — that shape is
        # the tx-flood economics TxQ already prices). Two validators
        # are always among the victims so the defense evidence lands on
        # the consensus core too.
        self._neighbors: list[int] = []

    def _targets(self) -> list[int]:
        if not self._neighbors:
            n = len(self.net.nodes)
            n_val = len(self.net.validators)
            picks = [v for v in range(min(2, n_val)) if v != self.nid]
            while len(picks) < min(self.fan, n - 1):
                dst = self.rng.randrange(n)
                if dst != self.nid and dst not in picks:
                    picks.append(dst)
            self._neighbors = picks
        return self._neighbors

    def act(self, step: int) -> None:
        """Called by the scenario runner once per step."""
        targets = self._targets()
        if "garbage_flood" in self.behaviors:
            for dst in targets:
                for _ in range(self.burst):
                    self.emitted["garbage_flood"] += 1
                    if self.rng.random() < 0.5:
                        # absurd length prefix: FrameReader raises
                        self.net.send(
                            self.nid, dst, b"\xff\xff\xff\xff\x00\x1e"
                        )
                    else:
                        # out-of-schema message type (mt 99)
                        self.net.send(
                            self.nid, dst,
                            (3).to_bytes(4, "big") + (99).to_bytes(2, "big")
                            + b"\x00\x01\x02",
                        )
        if "dup_flood" in self.behaviors:
            for dst in targets:
                for _ in range(self.burst):
                    self.emitted["dup_flood"] += 1
                    self.net.send(self.nid, dst, self._dup_frame)
        if "junk_tx_flood" in self.behaviors:
            from ..overlay.wire import TxMessage

            for dst in targets:
                for _ in range(self.burst):
                    self.emitted["junk_tx_flood"] += 1
                    blob = bytes(
                        self.rng.randrange(256) for _ in range(24)
                    )
                    self.net.send(self.nid, dst, frame(TxMessage(blob)))
