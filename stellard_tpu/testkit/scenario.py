"""Scenario runner + per-scenario scorecard.

A ``Scenario`` is pure data (validators, fault schedule builder,
workload builder, byzantine slots, catch-up/admission knobs). The
simnet runner replays it deterministically: one seed → one fault
schedule → one scorecard, byte-identical across runs (pinned by test
and by tools/scenariosmoke.py). The TCP runner (testkit.tcpnet) drives
the kill/revive + flood subset of the same definitions against real
processes.

Scorecard fields (doc/scenarios.md):

    converged / tail_steps / final_seq / final_hash / single_hash
    validated_seqs   per-validator validated seq at the end
    submitted / committed / commit_rate
    splice           delta-replay spliced/fallback/invalidated (summed
                     over honest validators)
    byzantine        defense counters summed over honest validators
    byzantine_emitted  what the hostile slots actually sent (anti-vacuity)
    degraded_transitions  honest proposing→tracking→proposing flips
    catchup          cold-node segment-path counters + synced flag
    txq              admission stats + fairness verdicts (fee-order
                     drain, no-starvation, replace-by-fee)
    net              transport-level sent/dropped/duplicated/delayed
    fault_digest     digest of the replayed fault schedule
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field as dc_field, fields as dc_fields
from typing import Callable, Optional

from ..engine.engine import TxParams
from ..overlay.simnet import SimNet
from ..overlay.wire import frame
from ..protocol.sttx import SerializedTransaction
from ..protocol.ter import TER
from .schedule import FaultSchedule
from .workloads import TxFactory, build_spec_workload

__all__ = [
    "Scenario", "run_simnet", "apply_event", "SYNTH_BUG",
    "ARCHIVE_CORRUPT", "LAST_FLIGHT",
]

# the most recent run's flight recorder (node/health.py FlightRecorder,
# fed by the scorecard health watchdog): the search plane dumps it next
# to a corpus entry when a run violates invariants, so every repro
# ships its black box. Single-slot list — never enters the scorecard.
LAST_FLIGHT: list = []

# Test-only planted bug (the fuzz gate's ground truth): while armed,
# every replayed `synth_plant` fault event accumulates its magnitude on
# the net, the scorecard reports it under "synth", and the search
# plane's `synthetic_bug` invariant fires at total >= 3. The sweep must
# FIND a violating schedule and SHRINK it to the known minimum (two
# plant events, magnitudes summing to exactly 3); disarming is "the
# fix" — the same corpus entry must then replay clean. Never armed in
# production scenarios; tools/scenariofuzz.py --smoke and the tests arm
# it around their sweeps.
SYNTH_BUG = {"armed": False}

# Test-only planted corruption for the archive leg (the shard-byte-match
# invariant's ground truth, mirroring SYNTH_BUG): while armed, the
# archive leg flips one key byte inside its FIRST imported shard file
# after import — so every clean stage (wire transfer, verify-gated
# import) passed, but the archive's served answers no longer match the
# sealed source contents. search.check_invariants' `archive_byte_match`
# must fire on the armed run and stay silent on clean ones (anti-vacuity
# both ways). Never armed in production scenarios.
ARCHIVE_CORRUPT = {"armed": False}


@dataclass
class Scenario:
    name: str
    seed: int = 0
    n_validators: int = 4
    quorum: int = 3
    steps: int = 60
    latency_steps: int = 1
    idle_interval: int = 4
    # builders: called with (schedule, scenario) / (factory, rng, scenario)
    build_schedule: Optional[Callable] = None
    build_workload: Optional[Callable] = None
    # DATA forms of the two builders (lossless to_json/from_json needs
    # schedules and workloads as data, not closures): a pre-built
    # FaultSchedule replayed as-is, and a named-workload spec
    # ({"kind": <workloads.WORKLOADS name>, "n": N, ...}) interpreted by
    # build_spec_workload. Builders and data forms compose (events
    # merge; build_workload wins over workload when both are set).
    schedule: Optional[FaultSchedule] = None
    workload: Optional[dict] = None
    # nid -> behavior tuple (testkit.byzantine.BEHAVIORS subset)
    byzantine: dict = dc_field(default_factory=dict)
    # production fan-in plane (ISSUE 11): a lightweight relay-peer tier
    # of n_peers non-validator nodes, validator-message squelching
    # (squelch_size=0 = full flood, byte-for-byte the legacy transport)
    # and enforced per-source resource pricing on every honest node
    n_peers: int = 0
    squelch_size: int = 0
    squelch_rotate: int = 16
    resources: bool = False
    # relay-tier flooders: peer-tier index -> kwargs for FlooderPeer
    # (behaviors/burst/fan); nid = n_validators + index
    flooders: dict = dc_field(default_factory=dict)
    # cold-node catch-up: nids silenced from step 0, revived at join_at,
    # syncing via the segment bulk path; `segments` gives every honest
    # validator a real segstore the scenario persists closed ledgers to
    cold_nodes: tuple = ()
    join_at: int = 0
    segments: bool = False
    segment_bytes: int = 65536  # segstore floors at 64 KiB
    # history-shard tiering (requires segments): when a serving
    # validator's accepted chain reaches `shard_trim_seq`, every ledger
    # BELOW it rotates out of the live segstore into a sealed history
    # shard (nodestore/shards.py rotate_into_shards) — so a cold node
    # joining later must sync that range entirely from shards over the
    # combined GetSegments manifest, the production trim-then-tier
    # shape (doc/storage.md)
    shards: bool = False
    shard_trim_seq: int = 0
    garbage_server: Optional[int] = None   # serving nid that corrupts
    kill_server_at: Optional[int] = None   # kill the 2nd server mid-sync
    # admission plane: attach a per-validator TxQ (pinned soft cap) and
    # route injected txs through admit() on every validator
    txq_cap: Optional[int] = None
    # parallel speculation plane ([spec] workers=N, PR 8 follow-on):
    # attach a thread-mode SpecExecutor to every honest validator so
    # open-window speculation runs on a real worker pool UNDER the
    # scenario's faults. Worker timing is wall-clock, so the per-run
    # splice/retry counters are not replay-deterministic — the gate is
    # HASH IDENTITY: the final chain must match the workers=1 run of
    # the same seed byte-for-byte (tools/scenariosmoke.py).
    spec_workers: int = 1
    # follower read-plane tier (PR 9): n_followers non-consensus full
    # nodes (nids after the relay tier) ingesting the validated chain;
    # the scorecard's `followers` block carries their sync evidence
    n_followers: int = 0
    # cascading follower tree (ISSUE 19): follower_branching>0 arranges
    # the follower tier as a branching-ary tree rooted at the validator
    # core (overlay.followertree.plan_tree) — tier-1 followers anycast
    # to validators, deeper tiers acquire from their parent follower
    # and re-home UP the tree when it dies. The `followers.tree` block
    # carries shape + re-home evidence; 0 = flat tier (legacy shape)
    follower_branching: int = 0
    # sharded crypto plane (ISSUE 15): mesh_width>0 routes every honest
    # validator's tree hashing through the mesh-enabled device hasher
    # (forced-device routing for anti-vacuity), width clamped to the
    # visible devices — width 1 on a 1-device box is the SAME routed
    # plane, so the convergence/single-hash invariants always run
    # against the sharded code path. The gate is HASH IDENTITY with
    # the host-hashed run of the same seed (hashes are hashes), plus
    # the scorecard's `mesh` block as machinery-fired evidence.
    mesh_width: int = 0
    # liquidity plane (ISSUE 17): path_subs>0 rides an incremental book
    # index (paths/plane.py) + N synthetic path subscriptions on the
    # watch validator: every accepted close advances the index, checks
    # identity against a full state scan, and re-ranks stalest-first
    # under a deliberately tight ceil(n/2) budget so shedding leaves
    # scorecard evidence. The `paths` block is deterministic per seed.
    path_subs: int = 0
    # archive tier (ISSUE 20, requires shards): after convergence a
    # synthetic archive node cold-backfills every sealed shard from the
    # serving validators' segment sources through the REAL wire codec
    # (ShardBackfill + whole-file SHARD_FILE door, verify-gated
    # import), then every historical answer it serves — account-index
    # rows, tx blobs, raw records — is byte-compared against the sealed
    # source's verified contents. The `archive` scorecard block carries
    # imported/reject/condemnation counts and the byte-match verdict;
    # a garbage_server scenario exercises condemnation on this leg too.
    archive: bool = False
    # convergence tail
    converge_extra: int = 2
    max_tail_steps: int = 240
    transports: tuple = ("simnet",)

    # -- serialization (corpus entries / the shrinker need scenarios as
    #    data; digest-pinned round trip) ---------------------------------

    def to_json(self) -> dict:
        """Lossless JSON form. Raises if the scenario still carries
        closure builders (``build_schedule``/``build_workload``) — only
        the data forms (``schedule``/``workload``) serialize."""
        if self.build_schedule is not None or self.build_workload is not None:
            raise ValueError(
                "scenario carries closure builders; only data-form "
                "scenarios (schedule=/workload=) serialize"
            )
        out = {}
        for f in dc_fields(self):
            if f.name in ("build_schedule", "build_workload"):
                continue
            v = getattr(self, f.name)
            if f.name == "schedule":
                v = v.to_json() if v is not None else None
            elif f.name == "byzantine":
                v = {str(k): list(bs) for k, bs in sorted(v.items())}
            elif f.name == "flooders":
                v = {str(k): dict(sp) for k, sp in sorted(v.items())}
            elif isinstance(v, tuple):
                v = list(v)
            out[f.name] = v
        return out

    @classmethod
    def from_json(cls, obj: dict) -> "Scenario":
        kw = dict(obj)
        if kw.get("schedule") is not None:
            kw["schedule"] = FaultSchedule.from_json(kw["schedule"])
        kw["byzantine"] = {
            int(k): tuple(bs)
            for k, bs in (kw.get("byzantine") or {}).items()
        }
        kw["flooders"] = {
            int(k): dict(sp)
            for k, sp in (kw.get("flooders") or {}).items()
        }
        for name in ("cold_nodes", "transports"):
            if name in kw:
                kw[name] = tuple(kw[name])
        known = {f.name for f in dc_fields(cls)}
        return cls(**{k: v for k, v in kw.items() if k in known})

    def digest(self) -> str:
        """Stable digest of the whole scenario-as-data (round-trip and
        cross-process determinism pins compare this)."""
        import hashlib
        import json

        blob = json.dumps(self.to_json(), sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


def apply_event(net: SimNet, ev) -> None:
    kw = dict(ev.kwargs)
    if ev.kind == "partition":
        net.partition(set(ev.args[0]), set(ev.args[1]))
    elif ev.kind == "heal":
        for a in ev.args[0]:
            for b in ev.args[1]:
                net.heal_link(a, b)
    elif ev.kind == "kill":
        net.kill(ev.args[0])
    elif ev.kind == "revive":
        net.revive(ev.args[0])
    elif ev.kind == "link_fault":
        net.set_link_fault(ev.args[0], ev.args[1], **kw)
    elif ev.kind == "clear_link_fault":
        net.clear_link_fault(ev.args[0], ev.args[1])
    elif ev.kind == "synth_plant":
        # test-only planted bug (see SYNTH_BUG): a no-op on the network,
        # but while armed it accumulates scorecard evidence the search
        # plane's synthetic_bug invariant trips on
        if SYNTH_BUG["armed"]:
            net.synth_planted = (
                getattr(net, "synth_planted", 0) + int(ev.args[0])
            )
    else:
        raise ValueError(f"unknown fault kind {ev.kind!r}")


class _GarbageSegmentSource:
    """Wraps a segment source so every served segment carries one
    flipped blob byte — content-verification at the fetcher must catch
    it and fall back to another peer."""

    def __init__(self, inner):
        self.inner = inner

    def segments(self):
        return self.inner.segments()

    def fetch_segment(self, seg_id, offset=0, length=None):
        got = self.inner.fetch_segment(seg_id, offset=offset,
                                       length=length)
        if got is None:
            return None
        meta, data = got
        if offset == 0 and len(data) > 41:
            b = bytearray(data)
            b[40] ^= 0xFF  # inside the first record's blob
            data = bytes(b)
        return meta, data


def _setup_segments(net: SimNet, scn: Scenario, tmp_factory):
    """Give every honest serving validator a real segstore the accepted
    ledgers persist into, and the cold node a local store + the
    SegmentCatchup bulk fetcher."""
    from ..node.inbound import SegmentCatchup
    from ..nodestore.core import NodeObjectType, make_database

    dbs = {}
    shardstores = {}
    serving = [
        i for i in range(scn.n_validators)
        if i not in scn.cold_nodes and i not in scn.byzantine
    ]
    for i in serving:
        db = make_database(
            type="segstore", path=tmp_factory(f"seg-{i}"),
            durability="async", segment_bytes=scn.segment_bytes,
            async_writes=False,
        )
        dbs[i] = db
        v = net.validators[i]
        if scn.shards:
            # history-shard tiering: at shard_trim_seq the pre-floor
            # range rotates out of the live segstore into a sealed
            # shard — a cold node joining later syncs it from cold
            # storage over the combined manifest (the production
            # trim-then-tier shape, deterministic: seq-driven)
            from ..nodestore.shards import (
                CombinedSegmentSource, HistoryShardStore,
                rotate_into_shards,
            )

            ss = HistoryShardStore(tmp_factory(f"shards-{i}"))
            shardstores[i] = ss
            headers: list[dict] = []
            rotated = [False]

            def _save(led, db=db, ss=ss, headers=headers,
                      rotated=rotated):
                led.save(db)
                headers.append({
                    "hash": led.hash(), "seq": led.seq,
                    "parent_hash": led.parent_hash,
                    "account_hash": led.account_hash,
                    "tx_hash": led.tx_hash,
                })
                if not rotated[0] and scn.shard_trim_seq > 0 \
                        and led.seq >= scn.shard_trim_seq:
                    rotated[0] = True
                    retired = [
                        h for h in headers
                        if h["seq"] < scn.shard_trim_seq
                    ]
                    retained = [
                        h for h in headers
                        if h["seq"] >= scn.shard_trim_seq
                    ]
                    rotate_into_shards(db, ss, retired, retained)

            v.node.on_ledger.append(_save)
        else:
            v.node.on_ledger.append(lambda led, db=db: led.save(db))
        src = db.backend
        if i in shardstores:
            src = CombinedSegmentSource(src, shardstores[i])
        if scn.garbage_server == i:
            src = _GarbageSegmentSource(src)
        v.node.segment_source = src

    catchups = {}
    for nid in scn.cold_nodes:
        cold = net.validators[nid]
        colddb = make_database(type="memory", async_writes=False)
        dbs[nid] = colddb

        def _local_fetch(h, colddb=colddb):
            obj = colddb.fetch(h)
            return obj.data if obj is not None else None

        cold.node.inbound.local_fetch = _local_fetch
        sc = SegmentCatchup(
            send=lambda peer, msg, nid=nid: net.send(
                nid, peer, frame(msg)
            ),
            peers=lambda serving=serving: list(serving),
            store=lambda tb, key, blob, colddb=colddb: colddb.store(
                NodeObjectType(tb), key, blob
            ),
            clock=net.clock,
            request_timeout=4.0,
            backoff_base=1.0,
            backoff_max=8.0,
            seed=scn.seed,
            note_byzantine=cold.node.note_byzantine,
        )
        cold.node.segment_catchup = sc
        catchups[nid] = sc
    return dbs, catchups, shardstores


def _run_archive_leg(scn: Scenario, net: SimNet, shardstores: dict,
                     tmp_factory) -> dict:
    """Archive-tier leg (ISSUE 20): a synthetic archive node backfills
    every sealed shard from the serving validators' segment sources —
    a synchronous, deterministic pump (seeded peer discipline, fake
    clock, no net stepping) that round-trips EVERY message through the
    real wire codec so the range-row encoding is exercised, not
    shortcut. After backfill, every historical answer the archive can
    serve (account-index rows, tx blobs, raw records) is byte-compared
    against the sealed source's verified contents; the scorecard block
    is ints/bools only so scorecards stay byte-identical per seed."""
    import os as _os

    from ..node.archive import ShardBackfill
    from ..nodestore.shards import HistoryShardStore
    from ..overlay import wire as W

    serving = sorted(shardstores)
    sources = {i: net.validators[i].node.segment_source for i in serving}
    adir = tmp_factory("archive")
    ass = HistoryShardStore(adir)
    mt_gs = int(W._ENCODERS[W.GetSegments][0])
    mt_sd = int(W._ENCODERS[W.SegmentData][0])
    clock = [0.0]
    pending: list = []
    noted: list = []

    def send(peer, msg):
        pending.append(
            (peer, W.decode_message(mt_gs, W.encode_message(msg)))
        )

    sb = ShardBackfill(
        send=send, peers=lambda: list(serving), shardstore=ass,
        clock=lambda: clock[0], request_timeout=4.0, rescan_s=1e9,
        seed=scn.seed,
        note_byzantine=lambda kind, **kw: noted.append(kind),
    )
    sb.start()
    guard = 0
    while sb.active and guard < 50_000:
        guard += 1
        if not pending:
            clock[0] += 5.0  # starved request: drive the timeout path
            sb.tick(clock[0])
            continue
        peer, msg = pending.pop(0)
        src = sources.get(peer)
        if src is None:
            continue
        if msg.seg_id < 0:
            rows = [
                (d["id"], d["size"], d["live_bytes"], bool(d["active"]),
                 int(d.get("lo", 0)), int(d.get("hi", 0)),
                 int(d.get("file_bytes", 0)))
                for d in src.segments()
            ]
            reply = W.SegmentData(-1, 0, 0, b"", segments=rows,
                                  snap_epoch=1)
        else:
            got = src.fetch_segment(msg.seg_id, offset=msg.offset,
                                    length=1 << 15)
            if got is None:
                continue  # unanswerable: the timeout path handles it
            meta, data = got
            reply = W.SegmentData(msg.seg_id, meta["size"], msg.offset,
                                  data, snap_epoch=1)
        reply = W.decode_message(mt_sd, W.encode_message(reply))
        if reply.seg_id < 0:
            sb.on_manifest(peer, reply.segments, epoch=reply.snap_epoch)
        else:
            sb.on_data(peer, reply)

    if ARCHIVE_CORRUPT["armed"] and ass.shards():
        # planted post-import corruption (see ARCHIVE_CORRUPT): flip
        # one key byte of the first imported shard's first record —
        # structure-preserving, so serving still works but the served
        # bytes no longer match the sealed source
        from ..nodestore.shards import _HDR_SIZE

        sid0 = ass.shards()[0]["id"]
        path = _os.path.join(adir, f"shard-{sid0:06d}.shard")
        with open(path, "r+b") as f:
            f.seek(_HDR_SIZE + 5)  # first record's key, first byte
            b = f.read(1)
            f.seek(_HDR_SIZE + 5)
            f.write(bytes([b[0] ^ 0xFF]))

    # byte-match sweep: the invariant surface. Every acct-index row's
    # tx blob AND every raw record the archive would serve must equal
    # the sealed source's verified contents.
    queries = 0
    mismatches = 0
    src_stores = list(shardstores.values())
    for sh in ass.shards():
        sid = sh["id"]
        src_ss = next(
            (s for s in src_stores if s.covers(sh["lo"]) is not None),
            None,
        )
        if src_ss is None:
            continue
        src_sid = src_ss.covers(sh["lo"])
        src_recs = {
            k: (tb, blob) for k, tb, blob in src_ss.iter_records(src_sid)
        }
        for k, tb, blob in ass.iter_records(sid):
            queries += 1
            if src_recs.get(k) != (tb, blob):
                mismatches += 1
        for _acct, lseq, _tseq, txid in ass.acct_rows(sid):
            queries += 1
            if ass.tx_blob(sid, txid) != src_ss.tx_blob(
                src_ss.covers(lseq), txid
            ):
                mismatches += 1
    out = {
        "imported": sb.counters["imported"],
        "duplicates": sb.counters["duplicates"],
        "import_rejects": sb.counters["import_rejects"],
        "garbage_peers": sb.counters["garbage_peers"],
        "fallbacks": sb.counters["fallbacks"],
        "completed": sb.counters["completed"],
        "byzantine_noted": len(noted),
        "verified_floor": ass.contiguous_floor(),
        "queries": queries,
        "byte_match_failures": mismatches,
        "corrupt_armed": bool(ARCHIVE_CORRUPT["armed"]),
    }
    ass.close()
    return out


def _attach_txqs(net: SimNet, scn: Scenario) -> dict:
    from ..node.txq import FeeMetrics, TxQ

    txqs = {}
    for i in range(scn.n_validators):
        if i in scn.byzantine or i in scn.cold_nodes:
            continue
        txq = TxQ(
            metrics=FeeMetrics(
                min_cap=scn.txq_cap, max_cap=scn.txq_cap
            ),
            ledgers_in_queue=20,
        )
        net.validators[i].node.lm.txq = txq
        txqs[i] = txq
    return txqs


_CLIENT_RETRY_STEPS = 4

# admission outcomes a synchronous client retries: local shed (fee
# escalation), account not yet on-ledger (its funding is still in the
# queue), balance-bound chain refusal. terQUEUED/tes are successes;
# tem/tef are permanent.
_CLIENT_RETRY_TERS = frozenset((
    int(TER.telINSUF_FEE_P), int(TER.terNO_ACCOUNT),
    int(TER.terINSUF_FEE_B),
))


def _client_should_retry(got: Optional[tuple]) -> bool:
    return got is None or int(got[0]) in _CLIENT_RETRY_TERS


def _outcome_rank(ter: int, applied: bool) -> int:
    """Admission-outcome precedence for the fairness record: a tx's
    final story is the BEST outcome its reporting gate ever gave it
    (applied > queued > final reject > retryable shed) — a shed that
    later re-admits as terQUEUED must count as queued, or retry-heavy
    scenarios under-count the queue and skew the starvation ratio."""
    if applied:
        return 3
    if ter == int(TER.terQUEUED):
        return 2
    if ter in _CLIENT_RETRY_TERS:
        return 0
    return 1


def _record_admission(admissions: dict, gate_of: dict, gate: int,
                      tx: SerializedTransaction, ter: int,
                      applied: bool) -> None:
    """Record/upgrade the admission story of one tx as seen at its
    REPORTING gate (the first live validator that answered; retries at
    that gate upgrade the record by outcome precedence)."""
    txid = tx.txid()
    if gate_of.setdefault(txid, gate) != gate:
        return
    new = (int(ter), bool(applied), tx.fee.mantissa, tx.account,
           tx.sequence)
    old = admissions.get(txid)
    if old is None or _outcome_rank(new[0], new[1]) >= \
            _outcome_rank(old[0], old[1]):
        admissions[txid] = new


def _admit_at(net: SimNet, txqs: dict, i: int,
              blob: bytes) -> Optional[tuple]:
    """Admit one client tx copy at validator i's gate; None while the
    validator is down. Returns (ter, applied, parsed copy)."""
    if net.is_down(i):
        return None
    copy = SerializedTransaction.from_bytes(blob)
    copy.set_sig_verdict(True)  # pre-verified client submission
    v = net.validators[i]
    with v.node.lock:
        ter, applied = txqs[i].admit(
            copy, v.node.lm, TxParams.OPEN_LEDGER | TxParams.RETRY
        )
    return ter, applied, copy


def _inject(net: SimNet, scn: Scenario, nid: int,
            tx: SerializedTransaction, txqs: dict,
            admissions: dict, step: int = 0,
            retry_q: Optional[list] = None,
            gate_of: Optional[dict] = None) -> None:
    """One workload item enters the net. Without an admission plane it
    rides the normal client path (apply locally + flood). With TxQs
    attached, EVERY honest validator runs admit() on its own copy — the
    production shape where a flood reaches each node's admission gate.
    A client SEES a local shed (telINSUF_FEE_P) or a dead node
    synchronously and retries; fire-and-forget here manufactured
    permanent per-account sequence gaps behind which whole queued
    chains starved (a scenario-fuzzer false-positive class) — down/shed
    admissions defer onto `retry_q` instead."""
    if not txqs:
        if net.is_down(nid) or nid in scn.byzantine:
            nid = next(
                i for i in range(scn.n_validators)
                if not net.is_down(i) and i not in scn.byzantine
            )
        net.validators[nid].submit_client_tx(tx)
        return
    blob = tx.serialize()
    if gate_of is None:
        gate_of = {}
    for i in txqs:
        got = _admit_at(net, txqs, i, blob)
        if _client_should_retry(got):
            if retry_q is not None:
                retry_q.append((step + _CLIENT_RETRY_STEPS, i, blob, 0))
        if got is not None:
            ter, applied, copy = got
            _record_admission(admissions, gate_of, i, copy,
                              int(ter), applied)


def _drain_client_retries(net: SimNet, txqs: dict, retry_q: list,
                          step: int, admissions: Optional[dict] = None,
                          gate_of: Optional[dict] = None) -> None:
    """Re-admit deferred client submissions whose retry timer fired;
    still-down / still-shed ones re-defer. Order-preserving (a client's
    chain resubmits in sequence order). A retry outcome at the tx's
    reporting gate UPGRADES its admission record — a shed that later
    queues counts as queued in the fairness verdicts."""
    if not retry_q:
        return
    keep = []
    for due, i, blob, tries in retry_q:
        if due > step:
            keep.append((due, i, blob, tries))
            continue
        got = _admit_at(net, txqs, i, blob)
        if got is not None and admissions is not None \
                and gate_of is not None:
            ter, applied, copy = got
            _record_admission(admissions, gate_of, i, copy,
                              int(ter), applied)
        if _client_should_retry(got) and tries < 25:
            # a real client gives up eventually too — the bound keeps
            # the quiescence tail finite when a tx can never enter
            keep.append((step + _CLIENT_RETRY_STEPS, i, blob, tries + 1))
    retry_q[:] = keep


def _count_committed(watch, workload) -> int:
    """Workload (sender, sequence) pairs consumed on the FINAL validated
    chain of the watch validator. Sequence consumption is fork-proof
    ground truth: a sequence can only advance by applying the one
    workload tx that carries it (replace-by-fee pairs count once — the
    chain can only have taken one of the bids)."""
    from ..protocol.sfields import sfSequence

    final = watch.node.lm.validated
    if final is None:
        return 0
    next_seq: dict[bytes, int] = {}
    pairs = set()
    for _at, _nid, tx in workload:
        acct = tx.account
        if acct not in next_seq:
            root = final.account_root(acct)
            next_seq[acct] = root[sfSequence] if root is not None else 1
        if tx.sequence < next_seq[acct]:
            pairs.add((acct, tx.sequence))
    return len(pairs)


def _fairness(admissions: dict, commits: dict) -> dict:
    """Admission-plane fairness verdicts from observable outcomes on
    validator 0's chain: fee-ordered drain (queued high-fee txs commit
    no later, on average, than queued low-fee ones), no-starvation
    (every queued tx eventually commits), replace-by-fee (a replaced
    sequence commits at most once)."""
    # replace-by-fee: only ONE bid per (account, seq) can ever commit,
    # so queued bids collapse onto their chain slot — a replaced
    # original is not a starved tx (the fuzzer caught the per-txid
    # accounting under-reporting no_starvation on replacement-heavy
    # streams)
    slots: dict[tuple, list] = {}
    for txid, (ter, _applied, fee, acct, seq) in admissions.items():
        if ter == int(TER.terQUEUED):
            slots.setdefault((acct, seq), []).append((txid, fee))
    recs = admissions.values()
    out = {
        "admitted": sum(1 for _ter, a, _f, _a, _s in recs if a),
        "queued": len(slots),
        "rejected": sum(
            1 for ter, a, _f, _a, _s in recs
            if not a and ter != int(TER.terQUEUED)
        ),
    }
    if not slots:
        out.update(fee_order_drain=True, no_starvation=True)
        return out
    landed = []
    for bids in slots.values():
        done = [(fee, commits[txid]) for txid, fee in bids
                if txid in commits]
        if done:
            landed.append(max(done))  # the winning (highest) bid
    out["queued_committed"] = len(landed)
    out["no_starvation"] = len(landed) >= max(1, int(0.9 * len(slots)))
    if len(landed) >= 4:
        landed.sort(key=lambda p: -p[0])
        k = max(1, len(landed) // 4)
        top = sum(seq for _f, seq in landed[:k]) / k
        bot = sum(seq for _f, seq in landed[-k:]) / k
        out["fee_order_drain"] = top <= bot + 1e-9
    else:
        out["fee_order_drain"] = True
    return out


def _fork_seqs(net: SimNet, honest: list, common: int) -> list:
    """Seqs <= the common validated floor where honest validators'
    ledger histories disagree. After fork repair + validated-slot
    overwrite these must agree wherever two nodes both hold an entry."""
    out = []
    for seq in range(1, max(0, common) + 1):
        seen = {
            h for h in (
                net.validators[i].node.lm.ledger_history.get(seq)
                for i in honest
            ) if h is not None
        }
        if len(seen) > 1:
            out.append(seq)
    return out


def run_simnet(scn: Scenario, tmpdir: Optional[str] = None) -> dict:
    """Execute one scenario on the deterministic simnet; returns the
    scorecard. `tmpdir` is required for segment scenarios (the serving
    validators persist real segstores there); its CONTENT never enters
    the scorecard, so determinism holds across paths."""
    import os
    import tempfile

    from .byzantine import ByzantineValidator, FlooderPeer

    net = SimNet(
        scn.n_validators, quorum=scn.quorum,
        latency_steps=scn.latency_steps,
        idle_interval=scn.idle_interval, seed=scn.seed,
        n_peers=scn.n_peers, squelch_size=scn.squelch_size,
        squelch_rotate=scn.squelch_rotate, resources=scn.resources,
        n_followers=scn.n_followers,
        follower_branching=scn.follower_branching,
    )
    # swap hostile slots in BEFORE start() so their genesis matches
    byz_validators = {}
    for nid, behaviors in scn.byzantine.items():
        bv = ByzantineValidator(
            net, nid, net.keys[nid],
            {k.public for k in net.keys}, scn.quorum or 0,
            scn.idle_interval, behaviors=behaviors, seed=scn.seed,
        )
        net.validators[nid] = bv
        net.nodes[nid] = bv
        byz_validators[nid] = bv
    # relay-tier flooders (ISSUE 11 flood-survival shape): swap hostile
    # peers into the relay tier, inheriting the slot's squelch/resource
    # attachments so their DELIVERIES behave like any peer's — only
    # their act() is hostile
    flooder_peers = {}
    for idx, spec in scn.flooders.items():
        nid = scn.n_validators + int(idx)
        old = net.nodes[nid]
        fp = FlooderPeer(net, nid, seed=scn.seed, **spec)
        fp.squelch = old.squelch
        fp.resources = old.resources
        net.peers[int(idx)] = fp
        net.nodes[nid] = fp
        flooder_peers[nid] = fp

    # schedule: data-form events + user builder + the cold-node join
    # choreography, merged onto one replayed schedule (its digest rides
    # the scorecard, so the merge is part of the replay identity)
    sched = FaultSchedule(scn.seed)
    if scn.schedule is not None:
        sched.extend(scn.schedule.events)
    if scn.build_schedule is not None:
        scn.build_schedule(sched, scn)
    for nid in scn.cold_nodes:
        sched.kill(0, nid, revive_at=scn.join_at)
    if scn.kill_server_at is not None:
        # the cold node's CURRENT server (2nd in order once the garbage
        # server condemned itself) dies mid-sync; revived for the tail
        victims = [
            i for i in range(scn.n_validators)
            if i not in scn.cold_nodes and i not in scn.byzantine
            and i != scn.garbage_server
        ]
        sched.kill(scn.kill_server_at, victims[0],
                   revive_at=scn.kill_server_at + 10)

    # workload (closure builder wins; else the named-workload spec)
    fac = TxFactory(seed=scn.seed)
    wl_rng = random.Random(0x301C ^ scn.seed)
    workload = []
    build_workload = scn.build_workload
    if build_workload is None and scn.workload is not None:
        build_workload = build_spec_workload(scn.workload)
    if build_workload is not None:
        workload = build_workload(fac, wl_rng, scn)
    by_step: dict[int, list] = {}
    for at, nid, tx in workload:
        by_step.setdefault(at, []).append((nid, tx))

    own_tmp = None
    dbs, catchups, shardstores = {}, {}, {}
    if scn.segments:
        if tmpdir is None:
            own_tmp = tempfile.mkdtemp(prefix="scn-seg-")
            tmpdir = own_tmp
        dbs, catchups, shardstores = _setup_segments(
            net, scn, lambda name: os.path.join(tmpdir, name)
        )
    txqs = _attach_txqs(net, scn) if scn.txq_cap else {}

    honest = [
        i for i in range(scn.n_validators) if i not in scn.byzantine
    ]
    # sharded hash plane under faults (ISSUE 15): one shared meshed
    # watched hasher (forced-device routing — the cost model would
    # bench a CPU-emulated kernel out and leave the leg vacuous) on
    # every honest validator's trees. Digests are digests: the chain's
    # bytes are identical to the host-hashed run of the same seed,
    # which is exactly the invariant the fuzzer then checks.
    mesh_hasher = None
    if scn.mesh_width:
        from ..crypto.backend import make_watched_hasher
        from ..utils.xlacache import enable_compilation_cache

        enable_compilation_cache()  # compiles reuse across runs/processes
        mesh_hasher = make_watched_hasher(
            "tpu", mesh=str(scn.mesh_width), routing="device"
        )
        # the FLAT facade (no hash_tree): tree hashing level-batches
        # through the routed hash_packed path, i.e. the SHARDED
        # masked-SHA kernel — the per-level pack_nodes shape the close
        # path feeds, which is the plane this axis exists to cover
        mesh_flat = mesh_hasher.flat_hasher()
        for i in honest:
            v = net.validators[i].node
            v.hash_batch = mesh_flat
            v.lm.hash_batch = mesh_flat
    # parallel speculation under faults: thread-mode pools (the simnet
    # is in-process; forking workers per validator would be pure
    # overhead) on every honest validator's chain
    spec_execs = []
    if scn.spec_workers > 1:
        from ..engine.specexec import SpecExecutor

        for i in honest:
            ex = SpecExecutor(workers=scn.spec_workers, mode="thread")
            ex.start()
            net.validators[i].node.lm.spec_executor = ex
            spec_execs.append(ex)
    # committed txids observed on ANY honest validator's accept feed —
    # one observer is not enough: fork-repair adoption can skip
    # unresolvable intermediate ledgers (no on_ledger fires for them),
    # so a lagging node's feed under-reports txs the net committed
    watch = net.validators[honest[0]]
    commits: dict[bytes, int] = {}

    def _record(led):
        for txid, _blob, _meta in led.tx_entries():
            commits.setdefault(txid, led.seq)

    for i in honest:
        net.validators[i].node.on_ledger.append(_record)

    # liquidity plane under faults (ISSUE 17): the watch validator's
    # accept feed drives the incremental book index + scn.path_subs
    # synthetic subscriptions. Fork repair can skip or replay closes —
    # exactly the continuity seams the index must survive (falling back
    # to a full rebuild, never diverging).
    path_plane = None
    path_stats = {"closes": 0, "identity_ok": True}
    if scn.path_subs:
        from ..paths import OrderBookDB
        from ..paths.plane import PathPlane

        path_plane = PathPlane(
            max_updates_per_close=max(1, (scn.path_subs + 1) // 2))
        path_keys = [("pathsub", j) for j in range(scn.path_subs)]

        def _path_close(led):
            path_plane.begin_close(led.seq)
            db = path_plane.books_for(led)
            if db.books != OrderBookDB().setup(led).books:
                path_stats["identity_ok"] = False
            path_stats["closes"] += 1
            for k in path_plane.order_keys(path_keys, led.seq):
                if path_plane.claim_update(k, led.seq):
                    path_plane.note_ranked(k, led.seq)

        watch.node.on_ledger.append(_path_close)

    net.start()
    admissions: dict = {}
    gate_of: dict = {}
    retry_q: list = []
    cur_step = [0]

    # SLO health dimension (node/health.py): a watchdog on VIRTUAL
    # step-time over the watch validator's close cadence — status is a
    # pure function of the replayed schedule, so the scorecard block is
    # deterministic per seed. The search plane's health invariants gate
    # on the (max observed gap, worst status) pair: an injected stall
    # must trip it, a clean run must not.
    from ..node.health import _RANK, FlightRecorder, HealthWatchdog

    health_flight = FlightRecorder(spans_cap=512)
    idle = float(max(1, scn.idle_interval))
    health_stall_warn = 10.0 * idle
    hw = HealthWatchdog(
        target_close_s=idle,
        stall_warn_s=health_stall_warn,
        stall_crit_s=30.0 * idle,
        drift_factor=8.0,
        clock=lambda: float(cur_step[0]),
        flight=health_flight,
    )
    health_state = {"worst": "ok", "last": None, "max_gap": 0}

    def _health_close(led):
        now = cur_step[0]
        if health_state["last"] is not None:
            gap = now - health_state["last"]
            if gap > health_state["max_gap"]:
                health_state["max_gap"] = gap
        health_state["last"] = now
        hw.note_close(led.seq, ts=float(now))

    watch.node.on_ledger.append(_health_close)

    def _health_tick():
        st = hw.evaluate()
        if _RANK[st] > _RANK[health_state["worst"]]:
            health_state["worst"] = st
    if txqs:
        # the client also RESUBMITS a tx the queue dropped (evicted /
        # expired while consensus stalled) — the product signals this
        # through TxQ.on_drop into LocalTxs, whose push_back makes the
        # tx resubmittable; without it, entries expiring under long
        # kill-stall windows read as admission-plane starvation.
        # Bounded per (txid, gate) so a permanently-dead tx terminates.
        blob_of: dict[bytes, bytes] = {}
        resubmits: dict[tuple, int] = {}

        def _mk_on_drop(i):
            def on_drop(txid):
                blob = blob_of.get(txid)
                n = resubmits.get((txid, i), 0)
                if blob is not None and n < 5:
                    resubmits[(txid, i)] = n + 1
                    retry_q.append((
                        cur_step[0] + _CLIENT_RETRY_STEPS, i, blob, 20,
                    ))
            return on_drop

        for i, txq in txqs.items():
            txq.on_drop = _mk_on_drop(i)
    submitted = 0
    try:
        for step in range(scn.steps):
            cur_step[0] = step
            for ev in sched.events_at(step):
                apply_event(net, ev)
            _drain_client_retries(net, txqs, retry_q, step,
                                  admissions, gate_of)
            for nid, tx in by_step.get(step, ()):
                if txqs:
                    blob_of[tx.txid()] = tx.serialize()
                _inject(net, scn, nid, tx, txqs, admissions,
                        step=step, retry_q=retry_q, gate_of=gate_of)
                submitted += 1
            for bv in byz_validators.values():
                if not net.is_down(bv.nid):
                    bv.act(step)
            for fp in flooder_peers.values():
                if not net.is_down(fp.nid):
                    fp.act(step)
            net.step()
            _health_tick()

        # drain the remaining schedule (heals/revives past the horizon)
        for ev in sorted(
            (e for e in sched.events if e.at >= scn.steps),
            key=lambda e: (e.at, e.order),
        ):
            if ev.kind in ("heal", "revive", "clear_link_fault"):
                apply_event(net, ev)

        # convergence tail: every honest validator quorum-validated on
        # one identical chain, `converge_extra` ledgers past the top
        def _hseqs():
            return [
                net.validators[i].node.lm.validated.seq
                if net.validators[i].node.lm.validated else 0
                for i in honest
            ]

        def _fseqs():
            return [
                f.node.lm.validated.seq if f.node.lm.validated else 0
                for f in net.followers
            ]

        def _tiers_at_target(target):
            # followers tail the validator wave one delivery-latency
            # behind by construction; their bar tracks the CURRENT
            # honest floor (validators keep closing during the
            # quiescence wait — a fixed target let the tail exit with
            # a follower legally-at-target but behind the floor the
            # synced verdict is judged against)
            hmin = min(_hseqs())
            if hmin < target:
                return False
            return not net.followers or min(_fseqs()) >= hmin - 1

        # two-phase tail: first reach the convergence target, then keep
        # stepping until the committed-tx count is QUIESCENT (held /
        # queued / disputed txs land a few rounds after the flood ends —
        # judging commit counts at first convergence undercounts them).
        # Quiescence additionally requires NO pending client work on
        # any live honest validator: a held sequence chain re-fires up
        # to ~2 retry horizons after a revive, and cutting the tail
        # inside that window reported healthy retries as lost txs
        # (a scenario-fuzzer false-positive class, fixed here)
        def _pending_client_work() -> bool:
            if retry_q:
                return True
            for i in honest:
                if net.is_down(i):
                    continue  # a frozen node's queues can't drain
                vn = net.validators[i].node
                if len(vn.local_txs):
                    return True
                if vn.lm.held:
                    return True
                txq = getattr(vn.lm, "txq", None)
                if txq is not None and len(txq):
                    return True
            return False

        target = max(_hseqs()) + scn.converge_extra
        tail = 0
        last_commits, stable = -1, 0
        while tail < scn.max_tail_steps:
            if _tiers_at_target(target):
                if len(commits) == last_commits:
                    stable += 1
                    if stable >= 3 * scn.idle_interval and \
                            not _pending_client_work():
                        break
                else:
                    stable = 0
                    last_commits = len(commits)
            cur_step[0] = scn.steps + tail
            _drain_client_retries(net, txqs, retry_q, scn.steps + tail,
                                  admissions, gate_of)
            net.step()
            _health_tick()
            tail += 1
        converged = min(_hseqs()) >= target
        common = min(_hseqs())
        hashes = {
            net.validators[i].node.lm.ledger_history.get(common)
            for i in honest
        }
        hashes.discard(None)

        splice: dict[str, int] = {}
        defense: dict[str, int] = {}
        degraded_transitions = 0
        for i in honest:
            vn = net.validators[i].node
            for k, v in vn.lm.delta_stats.snapshot().items():
                if isinstance(v, int):
                    splice[k] = splice.get(k, 0) + v
            for k, v in vn.defense.snapshot().items():
                defense[k] = defense.get(k, 0) + v
            degraded_transitions += vn.degrade_transitions

        card = {
            "scenario": scn.name,
            "seed": scn.seed,
            "transport": "simnet",
            "steps": scn.steps,
            "tail_steps": tail,
            "converged": converged,
            "final_seq": common,
            "final_hash": (
                next(iter(hashes)).hex() if len(hashes) == 1 else None
            ),
            "single_hash": len(hashes) == 1,
            "validated_seqs": _hseqs(),
            "submitted": submitted,
            "committed": _count_committed(watch, workload),
            "rounds": len(net.accept_log),
            "net": dict(net.net_stats),
            "splice": splice,
            "byzantine": {k: v for k, v in defense.items() if v},
            "byzantine_emitted": {
                nid: dict(bv.emitted)
                for nid, bv in byz_validators.items()
            },
            "degraded_transitions": degraded_transitions,
            "fault_digest": sched.digest(),
            # single-validated-hash-per-seq evidence: seqs at or below
            # the common validated floor where two honest validators'
            # repaired histories still disagree (must be empty — the
            # search plane's invariant registry gates on it)
            "fork_seqs": _fork_seqs(net, honest, common),
        }
        # SLO health dimension: deterministic ints/strings only (the
        # search plane's health_missed_stall / health_false_positive
        # invariants read the gap/worst pair)
        card["health"] = {
            "worst": health_state["worst"],
            "final": hw.status,
            "transitions": hw.transitions,
            "max_close_gap_steps": int(health_state["max_gap"]),
            "stall_warn_steps": int(health_stall_warn),
        }
        LAST_FLIGHT[:] = [health_flight]
        planted = getattr(net, "synth_planted", 0)
        if planted:
            card["synth"] = {"planted": planted}
        if scn.n_followers:
            fl_seqs = _fseqs()
            watch_hist = watch.node.lm.ledger_history
            card["followers"] = {
                "validated_seqs": fl_seqs,
                # every follower within one in-flight round of the
                # honest floor (the steady-state tailing lag) AND
                # byte-identical to the honest chain at its OWN floor
                "synced": bool(
                    converged
                    and len(hashes) == 1
                    and all(s >= common - 1 for s in fl_seqs)
                    and all(
                        f.node.lm.ledger_history.get(min(s, common))
                        == watch_hist.get(min(s, common))
                        for f, s in zip(net.followers, fl_seqs)
                    )
                ),
            }
            if scn.follower_branching:
                # tree shape + re-home evidence (ISSUE 19): leader
                # children bounded by branching, and a mid-tree kill
                # leaves a nonzero re-home count while `synced` above
                # still demands byte-identical reconvergence
                card["followers"]["tree"] = net.tree_json()
        if scn.squelch_size or scn.n_peers:
            # relay fan-out evidence: the squelch bound the flood gate
            # asserts (fan-out <= squelch_size + n_validators, never
            # the peer count)
            card["relay"] = {
                k: net.net_stats.get(k, 0)
                for k in ("relay_proposal", "relay_validation",
                          "relay_fanout_max")
            }
        if scn.resources:
            # `resource.*` evidence: charges paid, WARN/DROP crossings,
            # throttled sheds, refused deliveries per honest node
            card["resource"] = net.resource_json()
        if flooder_peers:
            card["flooders"] = {
                str(nid): {
                    "emitted": dict(fp.emitted),
                    # how many honest nodes reached DROP for this source
                    # and refused its deliveries (disconnect + gated
                    # readmission, collapsed onto the sim transport)
                    "refused_by": len(net.refusals.get(nid, ())),
                    # drop latency: virtual ms of flooding before the
                    # first honest node shut the door
                    "first_refusal_ms": net.first_refusal_ms.get(nid),
                }
                for nid, fp in sorted(flooder_peers.items())
            }
        if catchups:
            nid = scn.cold_nodes[0]
            cold = net.validators[nid].node
            cold_seq = cold.lm.validated.seq if cold.lm.validated else 0
            card["catchup"] = {
                "cold_nid": nid,
                "cold_validated_seq": cold_seq,
                "synced": (
                    converged
                    and cold_seq >= common
                    and cold.lm.ledger_history.get(common)
                    == next(iter(hashes), None)
                ),
                "segfetch": catchups[nid].get_json(),
            }
            if shardstores:
                # history-shard tier evidence: sealed ranges + how many
                # cold reads the shards actually served (anti-vacuity —
                # a shard leg where nothing read from a shard proves
                # nothing). trimmed=True pins that the live segstores
                # really lost the pre-floor range.
                reads = sum(
                    ss.segment_reads for ss in shardstores.values()
                )
                sealed = sum(ss.sealed for ss in shardstores.values())
                card["catchup"]["shards"] = {
                    "sealed": sealed,
                    "segment_reads": reads,
                    "trim_seq": scn.shard_trim_seq,
                }
        if scn.archive and shardstores:
            # archive tier (ISSUE 20): shard-network backfill into a
            # synthetic archive node + the byte-match invariant sweep
            card["archive"] = _run_archive_leg(
                scn, net, shardstores,
                lambda name: os.path.join(tmpdir, name),
            )
        if txqs:
            q0 = txqs[honest[0]]
            card["txq"] = {
                "stats": dict(q0.stats),
                "remaining": len(q0),
                **_fairness(admissions, commits),
            }
        if mesh_hasher is not None:
            # machinery-fired evidence for the mesh legs: the effective
            # width the plane resolved to and whether the device kernel
            # actually hashed nodes (booleans/config only — raw counts
            # stay out so scorecards remain byte-identical per seed)
            mj = mesh_hasher.get_json()
            card["mesh"] = {
                "width_requested": scn.mesh_width,
                "width": (mj.get("mesh") or {}).get("mesh_width"),
                "device_active": bool(mj.get("device_nodes")),
                "wedged": bool(mj.get("wedged")),
            }
        if spec_execs:
            # anti-vacuity evidence for the spec-pool legs: the pools
            # actually dispatched/committed work (wall-clock-dependent
            # counts — excluded from determinism comparisons by design)
            agg: dict[str, int] = {}
            for ex in spec_execs:
                for k, v in ex.counters.snapshot().items():
                    if isinstance(v, int):
                        agg[k] = agg.get(k, 0) + v
            card["spec"] = {
                "workers": scn.spec_workers,
                "dispatched": agg.get("dispatched", 0),
                "committed": agg.get("committed", 0),
                "retries": agg.get("retries", 0),
                "serial_fallbacks": agg.get("serial_fallbacks", 0),
            }
        if path_plane is not None:
            # liquidity-plane evidence: per-close identity held, the
            # budgeted re-ranker ran (anti-vacuity), bounded staleness,
            # and the index's advance/carry/rebuild mix — all
            # deterministic ints/bools, safe for scorecard identity
            pc = path_plane.index.counters()
            card["paths"] = {
                "subs": scn.path_subs,
                "closes": path_stats["closes"],
                "identity_ok": path_stats["identity_ok"],
                "reranked": path_plane.reranked,
                "shed_budget": path_plane.shed_budget,
                "staleness_max": path_plane.staleness_max,
                "incremental_advances": pc["incremental_advances"],
                "carries": pc["carries"],
                "full_rebuilds": pc["full_rebuilds"],
                "book_rereads": pc["book_rereads"],
            }
        return card
    finally:
        for ex in spec_execs:
            try:
                ex.stop()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for db in dbs.values():
            try:
                db.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        if own_tmp is not None:
            import shutil

            shutil.rmtree(own_tmp, ignore_errors=True)
