"""The scenario matrix (doc/scenarios.md): the adversarial shapes the
ISSUE/ROADMAP name, as parameterized builders, plus the permanent
minimal-repro corpus the fuzz plane (testkit/search.py) maintains.
``build_scenario`` is the single entry the smokes, the tests, the fuzz
harness and the bench leg share — matrix names first, then corpus
entries (each a shrunk, replayable scenario checked in under
``testkit/corpus/``).

(a) partition_kills   partitions healing on schedule + rotating
                      validator kills, under payment flood
(b) byzantine         a trusted-but-hostile validator emitting
                      equivocations, forged/stale validations,
                      oversized txsets and malformed frames
(c) cold_catchup      a node joining mid-flood syncs via the segment
                      bulk path; the first server serves garbage, the
                      second is killed mid-sync
(d) hostile workloads hot_account / order_books / fee_gaming
(e) fan-in/read axes  flood_survival, squelch-rotation-vs-flood,
                      chaos under spec workers, follower-under-
                      partition, cascading follower tree with a
                      mid-tree kill (follower_tree)

Every matrix scenario is DATA-form (``schedule=``/``workload=`` rather
than closures), so each round-trips losslessly through
``Scenario.to_json`` — the property the shrinker and the corpus build
on.
"""

from __future__ import annotations

import json
import os

from .schedule import FaultSchedule
from .scenario import Scenario

__all__ = [
    "MATRIX", "build_scenario", "CORPUS_DIR", "load_corpus",
    "corpus_scenarios",
]


def scenario_partition_kills(seed: int = 0) -> Scenario:
    # an even split that must stall (safety), healing on schedule,
    # then rotating single-validator kills under continuing flood
    sched = FaultSchedule(seed)
    sched.partition(14, {0, 1}, {2, 3, 4}, heal_at=26)
    sched.rotate_kills(range(5), start=34, every=12, downtime=5, count=3)
    return Scenario(
        name="partition_kills", seed=seed, n_validators=5, quorum=3,
        steps=80,
        schedule=sched,
        workload={"kind": "payment_flood", "n": 60},
    )


def scenario_chaos(seed: int = 0, steps: int = 120,
                   kill_every: int = 40, downtime: int = 5) -> Scenario:
    """Rotating validator kills under continuous flood — the pre-graft
    chaos-soak shape, now ONE definition driven through BOTH transports
    (tools/scenariofuzz.py --soak runs it on the real TCP net; the
    smoke and the matrix run it deterministically on the simnet)."""
    kills = max(1, (steps - 20) // kill_every)
    sched = FaultSchedule(seed)
    sched.rotate_kills(
        range(4), start=14, every=kill_every, downtime=downtime,
        count=kills,
    )
    return Scenario(
        name="chaos", seed=seed, n_validators=4, quorum=3,
        steps=steps,
        schedule=sched,
        workload={"kind": "payment_flood", "n": max(24, steps // 2)},
        transports=("simnet", "tcp"),
    )


def scenario_chaos_spec2(seed: int = 0) -> Scenario:
    """Chaos with [spec] workers=2 thread pools on every honest
    validator (ROADMAP item 5's workers>1-under-fire axis as a
    permanent matrix leg; tools/scenariosmoke.py gates hash identity
    against the serial run of the same seed)."""
    scn = scenario_chaos(seed)
    scn.name = "chaos_spec2"
    scn.spec_workers = 2
    scn.transports = ("simnet",)
    return scn


def scenario_byzantine(seed: int = 0) -> Scenario:
    return Scenario(
        name="byzantine", seed=seed, n_validators=4, quorum=3,
        steps=70,
        byzantine={3: (
            "equivocate", "duplicate", "forge", "stale", "garbage",
            "oversized",
        )},
        workload={"kind": "payment_flood", "n": 40},
    )


def scenario_cold_catchup(seed: int = 0) -> Scenario:
    return Scenario(
        name="cold_catchup", seed=seed, n_validators=5, quorum=3,
        steps=90,
        cold_nodes=(4,), join_at=40,
        segments=True, segment_bytes=65536,
        garbage_server=0,       # first pick serves garbage → per-peer
        kill_server_at=44,      # fallback, then the next server dies
                                # right as the transfer lands on it
        workload={"kind": "payment_flood", "n": 70},
        max_tail_steps=300,
    )


def scenario_shard_cold_catchup(seed: int = 0) -> Scenario:
    """The trim-then-tier leg (doc/storage.md): serving validators
    rotate every pre-floor ledger out of their live segstores into
    sealed history shards BEFORE the cold node joins, so the joiner
    must sync that range entirely from cold storage over the combined
    GetSegments manifest."""
    return Scenario(
        name="shard_cold_catchup", seed=seed, n_validators=5, quorum=3,
        steps=90,
        cold_nodes=(4,), join_at=50,
        segments=True, segment_bytes=65536,
        shards=True, shard_trim_seq=6,
        workload={"kind": "payment_flood", "n": 70},
        max_tail_steps=300,
    )


def scenario_archive_backfill(seed: int = 0) -> Scenario:
    """The archive-tier leg (ISSUE 20): on top of the trim-then-tier
    shape, a synthetic archive node backfills every sealed shard from
    the serving validators over the shard distribution network and
    byte-matches its served history against the sealed contents.
    garbage_server=0 makes the first-pick peer serve corrupted bytes,
    so the leg ALSO exercises verify-gated rejection + condemnation +
    refetch-elsewhere before the byte-match sweep runs."""
    return Scenario(
        name="archive_backfill", seed=seed, n_validators=5, quorum=3,
        steps=90,
        cold_nodes=(4,), join_at=50,
        segments=True, segment_bytes=65536,
        shards=True, shard_trim_seq=6,
        archive=True,
        garbage_server=0,
        workload={"kind": "payment_flood", "n": 70},
        max_tail_steps=300,
    )


def scenario_hot_account(seed: int = 0) -> Scenario:
    return Scenario(
        name="hot_account", seed=seed, n_validators=4, quorum=3,
        steps=60,
        workload={"kind": "hot_account_flood", "n": 80},
    )


def scenario_order_books(seed: int = 0) -> Scenario:
    return Scenario(
        name="order_books", seed=seed, n_validators=4, quorum=3,
        steps=70,
        workload={"kind": "order_book_crossfire", "n": 60},
    )


def scenario_follower_partition(seed: int = 0) -> Scenario:
    """Follower-attached-under-partition (ROADMAP item 5's read-plane
    axis): one follower node (nid 4) tails a 4-validator net under
    flood; mid-run the follower is partitioned away from every
    validator, then a validator dies and revives while the follower is
    still dark, then the partition heals — the follower must re-sync
    and end on the honest chain (scorecard `followers.synced`)."""
    sched = FaultSchedule(seed)
    sched.partition(18, {4}, {0, 1, 2, 3}, heal_at=38)
    sched.kill(24, 1, revive_at=30)
    return Scenario(
        name="follower_partition", seed=seed, n_validators=4, quorum=3,
        steps=64, n_followers=1,
        schedule=sched,
        workload={"kind": "payment_flood", "n": 48},
    )


def scenario_follower_tree(seed: int = 0) -> Scenario:
    """Cascading follower tree under mid-tree death (ISSUE 19): six
    followers arranged as a branching-2 tree over a 4-validator core
    (followers 0-1 dial the leader tier, 2-3 hang off follower 0, 4-5
    off follower 1), squelched relay so validations cascade through
    the tier, payment flood running — then the mid-tree follower 0
    (nid 4) DIES under load and revives late. Its downstream subtree
    must re-home up the tree (`followers.tree.rehomed` > 0) and every
    follower must reconverge byte-identical to the honest chain
    (`followers.synced`), with leader fan-out still bounded by the
    squelch subset, never the follower count."""
    sched = FaultSchedule(seed)
    sched.kill(24, 4, revive_at=40)
    return Scenario(
        name="follower_tree", seed=seed, n_validators=4, quorum=3,
        steps=64, n_followers=6, follower_branching=2,
        squelch_size=4,
        schedule=sched,
        workload={"kind": "payment_flood", "n": 48},
    )


def scenario_flood_survival(
    seed: int = 0,
    n_peers: int = 495,
    squelch: int = 8,
    flooder: bool = True,
    steps: int = 48,
) -> Scenario:
    """Overlay at production fan-in (ISSUE 11): a 5-validator core plus
    an n_peers relay tier (500 nodes at the default), squelched
    validator-message relay, enforced resource pricing on every honest
    node, and one hostile relay peer flooding garbage + duplicates +
    junk txs. The gate (tools/floodsmoke.py): honest validators
    converge on ONE hash, the flooder's endpoint reaches DROP at the
    nodes it floods and is refused readmission (`resource.*`
    counters), relay fan-out stays <= squelch + |UNL| (never the peer
    count), and close cadence holds within budget of the
    ``flooder=False`` run of the same seed."""
    return Scenario(
        name="flood_survival" if flooder else "flood_baseline",
        seed=seed, n_validators=5, quorum=4, steps=steps,
        n_peers=n_peers, squelch_size=squelch, resources=True,
        flooders=(
            {0: {"burst": 8, "fan": 24}} if flooder else {}
        ),
        workload={"kind": "payment_flood", "n": 30},
        max_tail_steps=160,
    )


def scenario_squelch_rotation_flood(seed: int = 0) -> Scenario:
    """Squelching-vs-byzantine-flood (ROADMAP item 5's last missing
    axis): the flood_survival shape with the squelch epoch rotating
    MID-FLOOD (rotate=3 → several epochs inside one run) — the
    rotating relay subsets must keep the fan-out bound while the PR 10
    flooder hammers its neighbor set, and enforcement (DROP + refusal)
    must survive the subset churn."""
    return Scenario(
        name="squelch_rotation_flood", seed=seed,
        n_validators=5, quorum=4, steps=60,
        n_peers=59, squelch_size=6, squelch_rotate=3, resources=True,
        flooders={0: {"burst": 8, "fan": 20}},
        workload={"kind": "payment_flood", "n": 30},
        max_tail_steps=160,
    )


def scenario_mesh_hash(seed: int = 0) -> Scenario:
    """Sharded crypto plane under faults (ISSUE 15): partitions +
    a kill while every honest validator's tree hashing routes through
    the mesh-enabled device hasher (forced-device, width clamped to
    visible devices — width 1 on a 1-device box is the same routed
    plane). The invariants are the usual convergence/single-hash set:
    a sharded hasher that produced different bytes would fork the net
    on the spot, so chaos coverage IS the identity gate."""
    sched = FaultSchedule(seed)
    sched.partition(10, {0, 1}, {2, 3}, heal_at=20)
    sched.kill(28, 3, revive_at=34)
    scn = Scenario(
        name="mesh_hash", seed=seed, n_validators=4, quorum=3,
        steps=56,
        schedule=sched,
        workload={"kind": "payment_flood", "n": 28},
    )
    scn.mesh_width = 8
    return scn


def scenario_fee_gaming(seed: int = 0) -> Scenario:
    return Scenario(
        name="fee_gaming", seed=seed, n_validators=4, quorum=3,
        steps=96,
        txq_cap=6,
        # flood ends ~36 steps before the horizon: the queue must DRAIN
        # in fee order (the fairness checks judge the drained outcome)
        workload={"kind": "fee_gaming", "n": 70, "end_margin": 36},
    )


MATRIX = {
    "partition_kills": scenario_partition_kills,
    "chaos": scenario_chaos,
    "chaos_spec2": scenario_chaos_spec2,
    "byzantine": scenario_byzantine,
    "cold_catchup": scenario_cold_catchup,
    "shard_cold_catchup": scenario_shard_cold_catchup,
    "archive_backfill": scenario_archive_backfill,
    "hot_account": scenario_hot_account,
    "order_books": scenario_order_books,
    "follower_partition": scenario_follower_partition,
    "follower_tree": scenario_follower_tree,
    "mesh_hash": scenario_mesh_hash,
    "fee_gaming": scenario_fee_gaming,
    "flood_survival": scenario_flood_survival,
    "squelch_rotation_flood": scenario_squelch_rotation_flood,
}

# -- the minimal-repro corpus (testkit/corpus/*.json) ---------------------
#
# Every entry is a shrunk scenario the fuzz plane (or a human triaging
# one of its finds) checked in: {"name", "invariant", "detail",
# "found" provenance, "expect" ("pass" once the bug is fixed), and the
# full data-form "scenario"}. They load through build_scenario like any
# matrix name and replay as permanent regressions in the fuzz smoke.

CORPUS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "corpus")


def load_corpus(corpus_dir: str | None = None) -> dict[str, dict]:
    """name -> corpus entry dict, sorted by filename (deterministic
    replay order). Missing directory = empty corpus."""
    d = corpus_dir or CORPUS_DIR
    out: dict[str, dict] = {}
    if not os.path.isdir(d):
        return out
    for fn in sorted(os.listdir(d)):
        if not fn.endswith(".json"):
            continue
        with open(os.path.join(d, fn)) as f:
            entry = json.load(f)
        if entry["name"] in out:
            # two files carrying one name would silently shadow a
            # checked-in regression out of the replay gate
            raise ValueError(
                f"duplicate corpus entry name {entry['name']!r} "
                f"(file {fn})"
            )
        out[entry["name"]] = entry
    return out


def corpus_scenarios(corpus_dir: str | None = None) -> dict[str, "Scenario"]:
    return {
        name: Scenario.from_json(entry["scenario"])
        for name, entry in load_corpus(corpus_dir).items()
    }


def build_scenario(name: str, seed: int = 0) -> Scenario:
    if name in MATRIX:
        return MATRIX[name](seed)
    entry = load_corpus().get(name)
    if entry is not None:
        # corpus scenarios carry their own pinned seed — the repro IS
        # the data; the seed argument does not apply
        return Scenario.from_json(entry["scenario"])
    raise KeyError(name)
