"""The scenario matrix (doc/scenarios.md): the four adversarial shapes
the ISSUE/ROADMAP name, as parameterized builders. ``build_scenario``
is the single entry the smoke, the tests, and the bench leg share.

(a) partition_kills   partitions healing on schedule + rotating
                      validator kills, under payment flood
(b) byzantine         a trusted-but-hostile validator emitting
                      equivocations, forged/stale validations,
                      oversized txsets and malformed frames
(c) cold_catchup      a node joining mid-flood syncs via the segment
                      bulk path; the first server serves garbage, the
                      second is killed mid-sync
(d) hostile workloads hot_account / order_books / fee_gaming
"""

from __future__ import annotations

from .scenario import Scenario
from .workloads import (
    fee_gaming,
    hot_account_flood,
    order_book_crossfire,
    payment_flood,
)

__all__ = ["MATRIX", "build_scenario"]


def _funded_flood(workload_fn, n_txs, end_margin: int = 6, **wl_kw):
    """Fund the scenario accounts during the opening steps, then run the
    hostile stream over the remaining window (`end_margin` steps of
    quiet tail let queues/holds drain before convergence is judged)."""

    def build(fac, rng, scn):
        items = [(0, 0, tx) for tx in fac.fund_all()]
        items += workload_fn(
            fac, rng, start=6, end=scn.steps - end_margin, n=n_txs,
            n_validators=scn.n_validators, **wl_kw,
        )
        items.sort(key=lambda it: it[0])
        return items

    return build


def scenario_partition_kills(seed: int = 0) -> Scenario:
    def schedule(sched, scn):
        # an even split that must stall (safety), healing on schedule,
        # then rotating single-validator kills under continuing flood
        sched.partition(14, {0, 1}, {2, 3, 4}, heal_at=26)
        sched.rotate_kills(
            range(scn.n_validators), start=34, every=12, downtime=5,
            count=3,
        )

    return Scenario(
        name="partition_kills", seed=seed, n_validators=5, quorum=3,
        steps=80,
        build_schedule=schedule,
        build_workload=_funded_flood(payment_flood, 60),
    )


def scenario_chaos(seed: int = 0, steps: int = 120,
                   kill_every: int = 40, downtime: int = 5) -> Scenario:
    """Rotating validator kills under continuous flood — the pre-graft
    chaos-soak shape, now ONE definition driven through BOTH transports
    (tools/chaos_soak.py runs it on the real TCP net; the smoke and the
    matrix run it deterministically on the simnet)."""
    kills = max(1, (steps - 20) // kill_every)

    def schedule(sched, scn):
        sched.rotate_kills(
            range(scn.n_validators), start=14, every=kill_every,
            downtime=downtime, count=kills,
        )

    return Scenario(
        name="chaos", seed=seed, n_validators=4, quorum=3,
        steps=steps,
        build_schedule=schedule,
        build_workload=_funded_flood(
            payment_flood, max(24, steps // 2)
        ),
        transports=("simnet", "tcp"),
    )


def scenario_byzantine(seed: int = 0) -> Scenario:
    return Scenario(
        name="byzantine", seed=seed, n_validators=4, quorum=3,
        steps=70,
        byzantine={3: (
            "equivocate", "duplicate", "forge", "stale", "garbage",
            "oversized",
        )},
        build_workload=_funded_flood(payment_flood, 40),
    )


def scenario_cold_catchup(seed: int = 0) -> Scenario:
    return Scenario(
        name="cold_catchup", seed=seed, n_validators=5, quorum=3,
        steps=90,
        cold_nodes=(4,), join_at=40,
        segments=True, segment_bytes=65536,
        garbage_server=0,       # first pick serves garbage → per-peer
        kill_server_at=44,      # fallback, then the next server dies
                                # right as the transfer lands on it
        build_workload=_funded_flood(payment_flood, 70),
        max_tail_steps=300,
    )


def scenario_hot_account(seed: int = 0) -> Scenario:
    return Scenario(
        name="hot_account", seed=seed, n_validators=4, quorum=3,
        steps=60,
        build_workload=_funded_flood(hot_account_flood, 80),
    )


def scenario_order_books(seed: int = 0) -> Scenario:
    return Scenario(
        name="order_books", seed=seed, n_validators=4, quorum=3,
        steps=70,
        build_workload=_funded_flood(order_book_crossfire, 60),
    )


def scenario_flood_survival(
    seed: int = 0,
    n_peers: int = 495,
    squelch: int = 8,
    flooder: bool = True,
    steps: int = 48,
) -> Scenario:
    """Overlay at production fan-in (ISSUE 11): a 5-validator core plus
    an n_peers relay tier (500 nodes at the default), squelched
    validator-message relay, enforced resource pricing on every honest
    node, and one hostile relay peer flooding garbage + duplicates +
    junk txs. The gate (tools/floodsmoke.py): honest validators
    converge on ONE hash, the flooder's endpoint reaches DROP at the
    nodes it floods and is refused readmission (`resource.*`
    counters), relay fan-out stays <= squelch + |UNL| (never the peer
    count), and close cadence holds within budget of the
    ``flooder=False`` run of the same seed."""
    return Scenario(
        name="flood_survival" if flooder else "flood_baseline",
        seed=seed, n_validators=5, quorum=4, steps=steps,
        n_peers=n_peers, squelch_size=squelch, resources=True,
        flooders=(
            {0: {"burst": 8, "fan": 24}} if flooder else {}
        ),
        build_workload=_funded_flood(payment_flood, 30),
        max_tail_steps=160,
    )


def scenario_fee_gaming(seed: int = 0) -> Scenario:
    return Scenario(
        name="fee_gaming", seed=seed, n_validators=4, quorum=3,
        steps=96,
        txq_cap=6,
        # flood ends ~36 steps before the horizon: the queue must DRAIN
        # in fee order (the fairness checks judge the drained outcome)
        build_workload=_funded_flood(fee_gaming, 70, end_margin=36),
    )


MATRIX = {
    "partition_kills": scenario_partition_kills,
    "chaos": scenario_chaos,
    "byzantine": scenario_byzantine,
    "cold_catchup": scenario_cold_catchup,
    "hot_account": scenario_hot_account,
    "order_books": scenario_order_books,
    "fee_gaming": scenario_fee_gaming,
    "flood_survival": scenario_flood_survival,
}


def build_scenario(name: str, seed: int = 0) -> Scenario:
    return MATRIX[name](seed)
