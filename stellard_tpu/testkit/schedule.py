"""Fault-schedule DSL: a declarative, step-indexed list of network
faults, built once (optionally with a seeded RNG for randomized
placement) and then REPLAYED — the schedule is data, so a seed maps to
exactly one fault pattern and the scorecard can carry a digest of it.

Event kinds map 1:1 onto the simnet's fault plane:

    partition(at, a, b[, heal_at])     cut every a<->b link (+ heal)
    kill(at, nid[, revive_at])         silence a validator (+ revive)
    link_fault(at, a, b, ..., until=)  drop/dup/delay/jitter on a link
    rotate_kills(nids, ...)            chaos-soak style rotating victims

The TCP runner consumes the same schedule but only supports the kinds a
process net can express (kill/revive); a scenario tagged for both
transports must restrict itself to that subset.
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass

__all__ = ["FaultEvent", "FaultSchedule"]


@dataclass(frozen=True)
class FaultEvent:
    at: int       # step index (simnet) / ~seconds (tcp)
    order: int    # tiebreak: schedule-build order, stable across runs
    kind: str
    args: tuple = ()
    kwargs: tuple = ()  # sorted (key, value) pairs


class FaultSchedule:
    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(0xFA17 ^ seed)
        self.events: list[FaultEvent] = []
        self._order = 0

    def add(self, at: int, kind: str, *args, **kwargs) -> "FaultSchedule":
        self.events.append(FaultEvent(
            int(at), self._order, kind, tuple(args),
            tuple(sorted(kwargs.items())),
        ))
        self._order += 1
        return self

    # -- composite builders ------------------------------------------------

    def partition(self, at: int, group_a, group_b,
                  heal_at: int | None = None) -> "FaultSchedule":
        self.add(at, "partition", tuple(sorted(group_a)),
                 tuple(sorted(group_b)))
        if heal_at is not None:
            self.add(heal_at, "heal", tuple(sorted(group_a)),
                     tuple(sorted(group_b)))
        return self

    def kill(self, at: int, nid: int,
             revive_at: int | None = None) -> "FaultSchedule":
        self.add(at, "kill", nid)
        if revive_at is not None:
            self.add(revive_at, "revive", nid)
        return self

    def link_fault(self, at: int, a: int, b: int,
                   until: int | None = None, **fault) -> "FaultSchedule":
        self.add(at, "link_fault", a, b, **fault)
        if until is not None:
            self.add(until, "clear_link_fault", a, b)
        return self

    def rotate_kills(self, nids, start: int, every: int, downtime: int,
                     count: int) -> "FaultSchedule":
        """Chaos-soak shape: every `every` steps from `start`, kill a
        seeded-random victim for `downtime` steps, `count` times.
        Victims never overlap (a revive always lands before the next
        kill when downtime < every)."""
        nids = list(nids)
        at = start
        for _ in range(count):
            victim = self.rng.choice(nids)
            self.kill(at, victim, revive_at=at + downtime)
            at += every
        return self

    # -- replay ------------------------------------------------------------

    def events_at(self, step: int) -> list[FaultEvent]:
        return sorted(
            (e for e in self.events if e.at == step),
            key=lambda e: e.order,
        )

    def max_step(self) -> int:
        return max((e.at for e in self.events), default=0)

    def describe(self) -> list[tuple]:
        """Canonical, deterministic event list (scorecard material)."""
        return [
            (e.at, e.order, e.kind, e.args, e.kwargs)
            for e in sorted(self.events, key=lambda e: (e.at, e.order))
        ]

    def digest(self) -> str:
        """Stable digest of the whole schedule: two runs of one seed must
        agree on this, and the smoke pins it."""
        h = hashlib.sha256(repr(self.describe()).encode())
        return h.hexdigest()[:16]
