"""Fault-schedule DSL: a declarative, step-indexed list of network
faults, built once (optionally with a seeded RNG for randomized
placement) and then REPLAYED — the schedule is data, so a seed maps to
exactly one fault pattern and the scorecard can carry a digest of it.

Event kinds map 1:1 onto the simnet's fault plane:

    partition(at, a, b[, heal_at])     cut every a<->b link (+ heal)
    kill(at, nid[, revive_at])         silence a validator (+ revive)
    link_fault(at, a, b, ..., until=)  drop/dup/delay/jitter on a link
    rotate_kills(nids, ...)            chaos-soak style rotating victims

The TCP runner consumes the same schedule but only supports the kinds a
process net can express (kill/revive); a scenario tagged for both
transports must restrict itself to that subset.
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass

__all__ = ["FaultEvent", "FaultSchedule"]


@dataclass(frozen=True)
class FaultEvent:
    at: int       # step index (simnet) / ~seconds (tcp)
    order: int    # tiebreak: schedule-build order, stable across runs
    kind: str
    args: tuple = ()
    kwargs: tuple = ()  # sorted (key, value) pairs


class FaultSchedule:
    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(0xFA17 ^ seed)
        self.events: list[FaultEvent] = []
        self._order = 0

    def add(self, at: int, kind: str, *args, **kwargs) -> "FaultSchedule":
        self.events.append(FaultEvent(
            int(at), self._order, kind, tuple(args),
            tuple(sorted(kwargs.items())),
        ))
        self._order += 1
        return self

    # -- composite builders ------------------------------------------------

    def partition(self, at: int, group_a, group_b,
                  heal_at: int | None = None) -> "FaultSchedule":
        self.add(at, "partition", tuple(sorted(group_a)),
                 tuple(sorted(group_b)))
        if heal_at is not None:
            self.add(heal_at, "heal", tuple(sorted(group_a)),
                     tuple(sorted(group_b)))
        return self

    def kill(self, at: int, nid: int,
             revive_at: int | None = None) -> "FaultSchedule":
        self.add(at, "kill", nid)
        if revive_at is not None:
            self.add(revive_at, "revive", nid)
        return self

    def link_fault(self, at: int, a: int, b: int,
                   until: int | None = None, **fault) -> "FaultSchedule":
        self.add(at, "link_fault", a, b, **fault)
        if until is not None:
            self.add(until, "clear_link_fault", a, b)
        return self

    def rotate_kills(self, nids, start: int, every: int, downtime: int,
                     count: int) -> "FaultSchedule":
        """Chaos-soak shape: every `every` steps from `start`, kill a
        seeded-random victim for `downtime` steps, `count` times.
        Victims never overlap (a revive always lands before the next
        kill when downtime < every)."""
        nids = list(nids)
        at = start
        for _ in range(count):
            victim = self.rng.choice(nids)
            self.kill(at, victim, revive_at=at + downtime)
            at += every
        return self

    def extend(self, events) -> "FaultSchedule":
        """Merge pre-built events (data-form schedules, corpus entries)
        AS-IS — original `order` values preserved so replay tiebreaks
        match the source — and renumber the build counter past them so
        later add() calls stay unique. The one owner of that invariant
        (run_simnet, run_tcp and from_json all merge through here)."""
        self.events.extend(events)
        self._order = 1 + max(
            (e.order for e in self.events), default=-1
        )
        return self

    # -- replay ------------------------------------------------------------

    def events_at(self, step: int) -> list[FaultEvent]:
        return sorted(
            (e for e in self.events if e.at == step),
            key=lambda e: e.order,
        )

    def max_step(self) -> int:
        return max((e.at for e in self.events), default=0)

    def describe(self) -> list[tuple]:
        """Canonical, deterministic event list (scorecard material)."""
        return [
            (e.at, e.order, e.kind, e.args, e.kwargs)
            for e in sorted(self.events, key=lambda e: (e.at, e.order))
        ]

    def digest(self) -> str:
        """Stable digest of the whole schedule: two runs of one seed must
        agree on this, and the smoke pins it."""
        h = hashlib.sha256(repr(self.describe()).encode())
        return h.hexdigest()[:16]

    # -- serialization (the shrinker/corpus need schedules as DATA) --------

    def to_json(self) -> dict:
        """Lossless JSON form: ``from_json(to_json())`` reproduces the
        identical event list AND ``digest()`` (pinned by test). The
        schedule's build-time RNG state is NOT captured — a deserialized
        schedule is replayed/edited as data, never re-randomized."""
        return {
            "seed": self.seed,
            "events": [
                [e.at, e.order, e.kind, _jsonify(e.args),
                 _jsonify(e.kwargs)]
                for e in self.events
            ],
        }

    @classmethod
    def from_json(cls, obj: dict) -> "FaultSchedule":
        sched = cls(int(obj.get("seed", 0)))
        sched.extend(
            FaultEvent(
                int(at), int(order), str(kind),
                _tupleize(args), _tupleize(kwargs),
            )
            for at, order, kind, args, kwargs in obj["events"]
        )
        return sched


def _jsonify(v):
    """Tuples → lists, recursively (JSON has no tuple type)."""
    if isinstance(v, tuple):
        return [_jsonify(x) for x in v]
    return v


def _tupleize(v):
    """Inverse of _jsonify: lists → tuples, recursively. Event args only
    ever hold ints/floats/strs and (nested) tuples of them, so the
    round trip is lossless and digest-stable."""
    if isinstance(v, list):
        return tuple(_tupleize(x) for x in v)
    return v
