"""Coverage-guided scenario search: the fault-schedule DSL as a bug
factory (ROADMAP item 5; tools/scenariofuzz.py is the CLI).

FoundationDB's lesson (Zhou et al., SIGMOD 2021) is that deterministic
simulation pays off through SEARCH — thousands of seeded schedules, any
failure replaying exactly from its data — and Yuan et al. (OSDI 2014)
that the catastrophic bugs live in rarely-driven error-handling paths
simple fault injection reaches. This module supplies the three pieces
around the unchanged ``run_simnet``:

- **generation**: a seeded ``ScenarioGenerator`` builds/mutates
  data-form ``Scenario``s — fault-schedule step groups (partitions,
  kills, link faults, rotating kills), scenario axes (validator count,
  quorum within safety bounds, workload kind/size, admission caps,
  relay tier + squelch + flooders, followers, cold-node joins) — all
  inside validity constraints, all randomness from ONE ``random.Random``
  stream so a fuzz seed maps to exactly one scenario sequence;
- **coverage**: each run's scorecard collapses to a fixed-shape
  dynamics state (``coverage_state``), bucketed and hashed into a
  signature; the sweep keeps a
  pool of scenarios that reached NOVEL signatures and spends most of
  its budget mutating high-energy pool entries (energy = rewarded on
  novelty, decayed on stale) instead of sampling uniformly — the
  scorecard-as-coverage analog of AFL's branch-edge map;
- **invariants + shrinking**: a first-class registry classifies every
  run (convergence, one hash per seq, committed-workload floor,
  anti-vacuity of configured faults, TxQ fairness, follower/cold sync,
  byte-identical re-run of the same seed, and the test-only planted
  ``synthetic_bug``); on violation a greedy shrinker drops schedule
  step groups and weakens axes while the SAME invariant keeps firing,
  emitting a minimal data-form scenario as a corpus entry
  (``testkit/corpus/``) that ``build_scenario`` loads as a permanent
  regression.

Everything here is a pure function of (fuzz_seed, code): the generated
scenario digests, the coverage map trajectory, and the shrink
trajectory are byte-identical across processes and PYTHONHASHSEED
values (pinned by tests/test_search.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
from dataclasses import dataclass
from typing import Callable, Optional

from .byzantine import BEHAVIORS
from .scenario import SYNTH_BUG, Scenario, run_simnet
from .schedule import FaultSchedule

__all__ = [
    "coverage_signature",
    "coverage_state",
    "counter_vector",
    "check_invariants",
    "Violation",
    "ScenarioGenerator",
    "schedule_groups",
    "shrink_scenario",
    "sweep",
    "coverage_comparison",
    "corpus_entry",
    "write_corpus_entry",
    "SYNTH_THRESHOLD",
]

# the planted bug (scenario.SYNTH_BUG) trips at this total magnitude;
# the known-minimal repro is therefore two plant events summing to
# exactly 3 (one event is capped at magnitude 2 by the generator)
SYNTH_THRESHOLD = 3


# -- coverage signal ------------------------------------------------------

def _bucket(v: int) -> int:
    """AFL-style hit-count bucketing, one class per ~2 octaves: 0, 1-3,
    4-15, 16-63, 64-255, 256+. Coarse on purpose — the signature must
    answer "which defense/fault/admission machinery fired, and at what
    order of magnitude", not echo every scenario's exact traffic count
    (log2-fine buckets made nearly every run a \"novel\" state, which
    starves the novelty bias of signal)."""
    v = int(v)
    if v <= 0:
        return 0
    return min(5, 1 + max(0, (v.bit_length() - 1) // 2))


def counter_vector(card: dict) -> dict[str, int]:
    """Flatten a scorecard into one deterministic counter dict — the
    TRIAGE view (tools/scenariofuzz.py --replay prints it). The
    coverage map itself hashes the much coarser ``coverage_state``;
    this keeps every counter, for humans reading a repro. Wall-clock-
    dependent blocks (``spec``) are excluded by design."""
    out: dict[str, int] = {}

    def put(key: str, v) -> None:
        if isinstance(v, bool):
            v = int(v)
        if isinstance(v, (int, float)) and v is not None:
            out[key] = int(v)

    for k in ("converged", "single_hash", "rounds", "tail_steps",
              "degraded_transitions", "submitted", "committed",
              "final_seq"):
        put(k, card.get(k, 0))
    put("lost", card.get("submitted", 0) - card.get("committed", 0))
    put("fork_seqs", len(card.get("fork_seqs", ())))
    for blk in ("net", "splice", "byzantine", "resource", "relay",
                "synth"):
        for k, v in (card.get(blk) or {}).items():
            put(f"{blk}.{k}", v)
    txq = card.get("txq") or {}
    for k, v in (txq.get("stats") or {}).items():
        put(f"txq.{k}", v)
    for k in ("admitted", "queued", "rejected", "queued_committed",
              "fee_order_drain", "no_starvation"):
        if k in txq:
            put(f"txq.{k}", txq[k])
    cu = card.get("catchup") or {}
    put("catchup.synced", cu.get("synced"))
    for k, v in (cu.get("segfetch") or {}).items():
        put(f"segfetch.{k}", v)
    for k, v in (cu.get("shards") or {}).items():
        put(f"shards.{k}", v)  # history-shard tier coverage axis
    fol = card.get("followers") or {}
    put("followers.synced", fol.get("synced"))
    # sharded hash plane (triage view only — width is CONFIG echo, so
    # it stays OUT of coverage_state's config-blind dynamics vector)
    mesh = card.get("mesh") or {}
    put("mesh.width", mesh.get("width"))
    put("mesh.device_active", mesh.get("device_active"))
    for nid, fl in (card.get("flooders") or {}).items():
        put(f"flooder.{nid}.refused_by", fl.get("refused_by", 0))
    return out


# the defense-counter kinds (ValidatorNode.defense bundle order)
_DEFENSE_KINDS = (
    "bad_proposal_sig", "bad_validation_sig", "conflicting_proposal",
    "duplicate_proposal", "conflicting_validation",
    "duplicate_validation", "stale_validation", "untrusted_validation",
    "oversized_txset", "txset_mismatch", "malformed_frame",
    "garbage_segment",
)


def coverage_state(card: dict) -> tuple:
    """One scorecard -> its DYNAMICS state: a fixed-shape vector of
    verdicts, which-machinery-fired bits, and coarse magnitudes. This
    deliberately ignores configuration echo (traffic volume, exact
    counts): two payment floods of different sizes that exercised the
    same machinery are the SAME state, so the map saturates under
    uniform sampling and novelty is a real signal (counter_vector keeps
    the full flattened view for triage/diagnostics)."""
    net = card.get("net") or {}
    sp = card.get("splice") or {}
    byz = card.get("byzantine") or {}
    cu = card.get("catchup") or {}
    sf = cu.get("segfetch") or {}
    txq = card.get("txq") or {}
    res = card.get("resource") or {}
    return (
        bool(card.get("converged")),
        bool(card.get("single_hash")),
        bool(card.get("fork_seqs")),
        _bucket(card.get("submitted", 0) - card.get("committed", 0)),
        _bucket(sp.get("fallback", 0)),
        _bucket(sp.get("invalidated", 0)),
        card.get("degraded_transitions", 0) > 0,
        net.get("dropped_down", 0) > 0,
        net.get("dropped_link", 0) > 0,
        net.get("dropped_fault", 0) > 0,
        net.get("duplicated", 0) > 0,
        net.get("delayed", 0) > 0,
        tuple(byz.get(k, 0) > 0 for k in _DEFENSE_KINDS),
        _bucket(res.get("dropped", 0)),
        _bucket(res.get("throttled", 0)),
        res.get("refused", 0) > 0,
        cu.get("synced"),
        sf.get("garbage_peers", 0) > 0,
        _bucket(sf.get("timeouts", 0)),
        _bucket(sf.get("retries", 0)),
        _bucket(txq.get("queued", 0)),
        txq.get("no_starvation"),
        txq.get("fee_order_drain"),
        (card.get("followers") or {}).get("synced"),
        _bucket((card.get("synth") or {}).get("planted", 0)),
        # archive tier (ISSUE 20) — appended at the END so every
        # pre-existing coverage signature stays stable
        (card.get("archive") or {}).get("imported", 0) > 0,
        (card.get("archive") or {}).get("byte_match_failures", 0) > 0,
        (card.get("archive") or {}).get("garbage_peers", 0) > 0,
    )


def coverage_signature(card: dict) -> str:
    """One scorecard -> one coverage-state hash (fixed-shape dynamics
    vector: PYTHONHASHSEED-proof and cross-process stable)."""
    return hashlib.sha256(
        repr(coverage_state(card)).encode()
    ).hexdigest()[:16]


# -- invariant registry ---------------------------------------------------

@dataclass(frozen=True)
class Violation:
    invariant: str
    detail: str


def _strip_nondeterministic(card: dict) -> dict:
    out = dict(card)
    out.pop("spec", None)  # wall-clock worker counters, by design
    return out


def check_invariants(
    scn: Scenario, card: dict, recard: Optional[dict] = None
) -> list[Violation]:
    """Classify one run. Ordered most-specific-first: the FIRST entry
    names the failure for shrinking/corpus purposes. `recard`, when
    given, is a second run of the identical scenario — byte-identical
    scorecards are part of the contract (the FoundationDB property)."""
    v: list[Violation] = []
    ev_kinds = [e.kind for e in _events_of(scn)]

    # (0) the planted test-only bug: scorecard evidence past threshold
    planted = (card.get("synth") or {}).get("planted", 0)
    if planted >= SYNTH_THRESHOLD:
        v.append(Violation(
            "synthetic_bug", f"planted magnitude {planted} >= "
            f"{SYNTH_THRESHOLD}"
        ))

    # (1) determinism: same seed, byte-identical scorecard
    if recard is not None:
        a = json.dumps(_strip_nondeterministic(card), sort_keys=True)
        b = json.dumps(_strip_nondeterministic(recard), sort_keys=True)
        if a != b:
            diff = [
                k for k in sorted(set(card) | set(recard))
                if card.get(k) != recard.get(k) and k != "spec"
            ]
            v.append(Violation(
                "determinism", f"re-run diverged in fields {diff}"
            ))

    # (2) liveness: every honest validator quorum-validated the target
    if not card.get("converged"):
        v.append(Violation(
            "convergence",
            f"validated_seqs={card.get('validated_seqs')} after "
            f"{card.get('tail_steps')} tail steps",
        ))

    # (3) safety: one hash at the common seq, and one per seq below it
    if card.get("converged") and not card.get("single_hash"):
        v.append(Violation(
            "single_hash", f"fork at seq {card.get('final_seq')}"
        ))
    if card.get("fork_seqs"):
        v.append(Violation(
            "single_hash_history",
            f"honest histories disagree at seqs {card['fork_seqs']}",
        ))

    # (4) committed-workload floor: client submissions must land on the
    # final chain (with an admission plane attached, the queue's own
    # fairness verdicts replace the exact floor)
    if card.get("converged"):
        if scn.txq_cap:
            txq = card.get("txq") or {}
            if not txq.get("no_starvation", True):
                v.append(Violation(
                    "txq_no_starvation",
                    f"queued={txq.get('queued')} "
                    f"queued_committed={txq.get('queued_committed')}",
                ))
            if not txq.get("fee_order_drain", True):
                v.append(Violation(
                    "txq_fee_order",
                    "queued high-fee txs committed later than low-fee",
                ))
        elif card.get("committed", 0) != card.get("submitted", 0):
            v.append(Violation(
                "committed_floor",
                f"{card.get('committed')}/{card.get('submitted')} "
                f"workload txs on the final chain",
            ))

    # (5) attached tiers must end synced
    if card.get("converged"):
        if scn.cold_nodes and not (card.get("catchup") or {}).get(
            "synced", True
        ):
            v.append(Violation(
                "cold_sync",
                f"cold node at seq "
                f"{card.get('catchup', {}).get('cold_validated_seq')}",
            ))
        if getattr(scn, "shards", False):
            # anti-vacuity for the shard-tier leg: the rotation must
            # have sealed shards AND the cold sync must have actually
            # read from one — a "passing" leg where the cold node never
            # touched cold storage proves nothing about the tier
            sh = (card.get("catchup") or {}).get("shards") or {}
            if not sh.get("sealed") or not sh.get("segment_reads"):
                v.append(Violation(
                    "shard_tier_vacuous",
                    f"sealed={sh.get('sealed')} "
                    f"segment_reads={sh.get('segment_reads')}",
                ))
        if getattr(scn, "archive", False):
            # archive tier (ISSUE 20): every historical answer the
            # archive serves must byte-match the sealed shard's
            # verified contents — and the leg must have actually
            # imported and queried something (a backfill that moved
            # zero shards or compared zero bytes proves nothing)
            ar = card.get("archive") or {}
            if ar.get("byte_match_failures", 0) > 0:
                v.append(Violation(
                    "archive_byte_match",
                    f"{ar.get('byte_match_failures')}/"
                    f"{ar.get('queries')} archive answers diverged "
                    f"from sealed shard contents",
                ))
            if not ar.get("imported") or not ar.get("queries"):
                v.append(Violation(
                    "archive_tier_vacuous",
                    f"imported={ar.get('imported')} "
                    f"queries={ar.get('queries')}",
                ))
        if scn.n_followers and not (card.get("followers") or {}).get(
            "synced", True
        ):
            v.append(Violation(
                "follower_sync",
                f"followers at {card.get('followers', {}).get('validated_seqs')}",
            ))
        if getattr(scn, "path_subs", 0):
            # liquidity plane (ISSUE 17): the incremental index must
            # equal the full scan at every close, the budgeted
            # re-ranker must have run (anti-vacuity), and stalest-first
            # under a ceil(n/2) budget bounds worst-case staleness —
            # a subscription starving past 4 closes is a scheduler bug
            p = card.get("paths") or {}
            if not p.get("identity_ok", True):
                v.append(Violation(
                    "path_index_identity",
                    f"incremental book index diverged from the full "
                    f"scan ({p.get('closes')} closes)",
                ))
            if p.get("closes", 0) > 0 and not p.get("reranked"):
                v.append(Violation(
                    "anti_vacuity",
                    "path subscriptions configured but zero re-ranks",
                ))
            if p.get("staleness_max", 0) > 4:
                v.append(Violation(
                    "path_staleness",
                    f"subscription staleness hit "
                    f"{p.get('staleness_max')} closes under a "
                    f"ceil(n/2) budget",
                ))

    # (6) no-silent-fault anti-vacuity: every configured hostile input
    # must leave counter evidence — a scenario that silently stopped
    # injecting faults greenwashes, and must fail instead
    net = card.get("net") or {}
    has_traffic = card.get("submitted", 0) > 0 or net.get("sent", 0) > 0
    if has_traffic:
        if ("kill" in ev_kinds and net.get("dropped_down", 0) == 0):
            v.append(Violation(
                "anti_vacuity", "kill events but zero dropped_down"
            ))
        if ("partition" in ev_kinds and net.get("dropped_link", 0) == 0):
            v.append(Violation(
                "anti_vacuity", "partition events but zero dropped_link"
            ))
        # link faults: require EXPOSURE (messages crossed the faulted
        # link while it was armed), not probabilistic outcomes — a drop
        # fault that got a lucky streak is not a silent fault, but one
        # whose window never saw traffic is
        if any(e.kind == "link_fault" for e in _events_of(scn)) and \
                net.get("fault_exposed", 0) == 0:
            v.append(Violation(
                "anti_vacuity",
                "link fault armed but zero messages crossed it",
            ))
    if scn.byzantine:
        emitted = card.get("byzantine_emitted") or {}
        for nid, em in emitted.items():
            for behavior, n in em.items():
                if n <= 0:
                    v.append(Violation(
                        "anti_vacuity",
                        f"byzantine slot {nid} behavior {behavior} "
                        f"emitted nothing",
                    ))
        if sum((card.get("byzantine") or {}).values()) == 0:
            v.append(Violation(
                "anti_vacuity", "byzantine slot but zero defense counters"
            ))
    for nid, fl in (card.get("flooders") or {}).items():
        if sum(fl.get("emitted", {}).values()) == 0:
            v.append(Violation(
                "anti_vacuity", f"flooder {nid} emitted nothing"
            ))

    # (7) SLO watchdog wiring (ISSUE 18): a close-cadence stall the run
    # OBSERVED (max gap past the warn line) must trip the health
    # dimension — a watchdog that sleeps through an injected stall is
    # vacuous; and with no faults injected a run must stay ok — a
    # watchdog that cries on a clean run is noise, not observability
    h = card.get("health") or {}
    if h:
        gap = h.get("max_close_gap_steps", 0)
        warn_at = h.get("stall_warn_steps", 0)
        if warn_at and gap > warn_at and h.get("worst") == "ok":
            v.append(Violation(
                "health_missed_stall",
                f"close gap {gap} steps > warn line {warn_at} but "
                f"health stayed ok",
            ))
        faultless = (
            not ev_kinds and scn.build_schedule is None
            and not scn.byzantine and not scn.flooders
            and not scn.cold_nodes and scn.kill_server_at is None
        )
        if faultless and h.get("worst", "ok") != "ok":
            v.append(Violation(
                "health_false_positive",
                f"health hit {h.get('worst')} with no injected faults",
            ))

    # dedup (anti-vacuity can repeat), order-preserving
    seen = set()
    out = []
    for viol in v:
        key = (viol.invariant, viol.detail)
        if key not in seen:
            seen.add(key)
            out.append(viol)
    return out


def _events_of(scn: Scenario) -> list:
    return list(scn.schedule.events) if scn.schedule is not None else []


# -- schedule step groups (drop/retime units for mutation + shrinking) ----

def schedule_groups(sched: Optional[FaultSchedule]) -> list[list]:
    """Pair opener/closer events (partition+heal, kill+revive,
    link_fault+clear) into atomic groups so dropping a fault never
    leaves a dangling heal — the unit of mutation and shrinking."""
    if sched is None:
        return []
    events = sorted(sched.events, key=lambda e: (e.order,))
    claimed: set[int] = set()
    closer_for = {
        "partition": "heal", "kill": "revive",
        "link_fault": "clear_link_fault",
    }
    match_args = {
        "heal": lambda o, c: c.args == o.args,
        "revive": lambda o, c: c.args == o.args,
        "clear_link_fault": lambda o, c: c.args[:2] == o.args[:2],
    }
    groups: list[list] = []
    for i, e in enumerate(events):
        if i in claimed:
            continue
        if e.kind in ("heal", "revive", "clear_link_fault"):
            groups.append([e])  # orphan closer: standalone group
            continue
        group = [e]
        want = closer_for.get(e.kind)
        if want is not None:
            for j in range(i + 1, len(events)):
                c = events[j]
                if (j not in claimed and c.kind == want
                        and match_args[want](e, c)):
                    claimed.add(j)
                    group.append(c)
                    break
        groups.append(group)
    return groups


def _sched_from_groups(seed: int, groups: list[list]) -> Optional[FaultSchedule]:
    flat = [e for g in groups for e in g]
    if not flat:
        return None
    sched = FaultSchedule(seed)
    for e in sorted(flat, key=lambda e: e.order):
        sched.add(e.at, e.kind, *e.args, **dict(e.kwargs))
    return sched


# -- generation -----------------------------------------------------------

_WORKLOAD_KINDS = (
    "payment_flood", "payment_flood", "payment_flood",
    "hot_account_flood", "order_book_crossfire", "fee_gaming",
)


class ScenarioGenerator:
    """Seeded scenario generation + mutation. ONE rng stream drives
    every choice, so a fuzz seed maps to exactly one sequence of
    scenarios regardless of process or PYTHONHASHSEED. ``allow_synth``
    arms the planted-bug fault kind (the smoke's ground truth)."""

    def __init__(self, seed: int = 0, allow_synth: bool = False):
        self.seed = seed
        self.rng = random.Random(0x5CA12C4 ^ seed)
        self.allow_synth = allow_synth
        self.counter = 0

    # -- validity-constrained axis choices --------------------------------

    def _quorum(self, n: int, byz: bool) -> int:
        lo = n // 2 + 1
        if byz:
            # safety under one equivocator: quorum > (n + f) / 2
            lo = max(lo, (n + 1) // 2 + 1)
        hi = max(lo, n - 1)
        return self.rng.randint(lo, hi)

    def _schedule_group(self, rng, n: int, steps: int,
                        protected: tuple = ()) -> list[tuple]:
        """One validity-constrained fault group as (at, kind, args,
        kwargs) tuples. `protected` nids (cold nodes) are never killed —
        the join choreography owns their downtime."""
        kind = rng.choice(
            ("partition", "partition", "kill", "kill", "kill",
             "link_fault", "link_fault", "rotate_kills")
        )
        if kind == "partition":
            nids = list(range(n))
            rng.shuffle(nids)
            cut = rng.randint(1, n - 1)
            a, b = tuple(sorted(nids[:cut])), tuple(sorted(nids[cut:]))
            at = rng.randint(8, max(9, steps - 18))
            heal = at + rng.randint(6, 12)
            return [(at, "partition", (a, b), ()),
                    (heal, "heal", (a, b), ())]
        if kind == "kill":
            victims = [i for i in range(n) if i not in protected]
            nid = rng.choice(victims)
            at = rng.randint(8, max(9, steps - 14))
            rev = at + rng.randint(3, 8)
            return [(at, "kill", (nid,), ()),
                    (rev, "revive", (nid,), ())]
        if kind == "link_fault":
            a = rng.randrange(n)
            b = rng.choice([i for i in range(n) if i != a])
            at = rng.randint(6, max(7, steps - 18))
            until = at + rng.randint(8, 16)
            fault = {}
            # at least one nonzero fault component, all strong enough to
            # leave counter evidence over the window (anti-vacuity)
            roll = rng.random()
            if roll < 0.55:
                fault["drop"] = rng.choice((0.2, 0.35))
            if 0.35 < roll < 0.8:
                fault["dup"] = 0.25
            if roll >= 0.8 or not fault:
                fault["delay_steps"] = rng.randint(1, 2)
                fault["jitter_steps"] = rng.randint(1, 2)
            return [(at, "link_fault", (a, b),
                     tuple(sorted(fault.items()))),
                    (until, "clear_link_fault", (a, b), ())]
        # rotate_kills: staggered non-overlapping kill/revive pairs
        start = rng.randint(10, max(11, steps // 2))
        every = rng.randint(10, 16)
        downtime = rng.randint(3, min(8, every - 2))
        count = rng.randint(2, 3)
        victims = [i for i in range(n) if i not in protected]
        out = []
        at = start
        for _ in range(count):
            nid = rng.choice(victims)
            out.append((at, "kill", (nid,), ()))
            out.append((at + downtime, "revive", (nid,), ()))
            at += every
        return out

    def _attach_overlay_tier(self, rng, scn: Scenario) -> None:
        """Randomize the relay/squelch/resource/flooder tier onto a
        scenario (shared by fresh() and the compose_axis mutation so
        the two sampling sites can never drift apart)."""
        scn.n_peers = rng.randint(12, 40)
        scn.squelch_size = rng.choice((4, 6, 8))
        scn.squelch_rotate = rng.choice((3, 8, 16))
        scn.resources = True
        if rng.random() < 0.5:
            scn.flooders = {0: {
                "burst": rng.randint(4, 8),
                "fan": rng.randint(8, 16),
            }}

    def _materialize(self, seed: int, raw: list[tuple]) -> FaultSchedule:
        sched = FaultSchedule(seed)
        for at, kind, args, kwargs in raw:
            sched.add(at, kind, *args, **dict(kwargs))
        return sched

    def fresh(self) -> Scenario:
        """One new validity-constrained random scenario."""
        rng = self.rng
        self.counter += 1
        cold = rng.random() < 0.10
        n = rng.choice((5, 6)) if cold else rng.choice((4, 5, 6))
        byz = (not cold) and rng.random() < 0.22
        steps = rng.randint(44, 68)
        # quorum over the FULL validator count — a cold node is down,
        # not absent, and a sub-majority quorum lets two disjoint
        # quorums validate different ledgers at one seq (the fuzzer
        # demonstrated exactly that with 3-of-6)
        quorum = self._quorum(n, byz)

        kind = rng.choice(_WORKLOAD_KINDS)
        wl_n = rng.randint(24, 52)
        workload = {"kind": kind, "n": wl_n}
        txq_cap = None
        if kind == "fee_gaming":
            workload["end_margin"] = 30
            txq_cap = rng.randint(4, 8)
        elif rng.random() < 0.12:
            txq_cap = rng.randint(5, 9)

        scn = Scenario(
            name=f"fuzz-{self.seed}-{self.counter}",
            seed=rng.randrange(1 << 16),
            n_validators=n, quorum=quorum, steps=steps,
            workload=workload, txq_cap=txq_cap,
            max_tail_steps=280,
        )
        if byz:
            k = rng.randint(1, len(BEHAVIORS))
            scn.byzantine = {
                n - 1: tuple(sorted(rng.sample(BEHAVIORS, k)))
            }
        if cold:
            scn.cold_nodes = (n - 1,)
            scn.join_at = rng.randint(steps // 3, steps // 2)
            scn.segments = True
            scn.max_tail_steps = 320
            if rng.random() < 0.40:
                # history-shard axis: serving validators trim-then-tier
                # the early chain into shards BEFORE the cold node
                # joins, so the sync crosses the cold-storage boundary
                # under whatever faults this schedule carries
                scn.shards = True
                scn.shard_trim_seq = rng.randint(3, 6)
                # archive-tier axis (ISSUE 20): derived from the
                # already-drawn scenario seed rather than a fresh rng
                # draw, so the generator's stream — and every
                # previously generated scenario — stays bit-identical.
                # ~1 in 4 shard runs also backfill a synthetic archive
                # from the sealed tier and byte-match its answers.
                if scn.seed & 0x3 == 0x1:
                    scn.archive = True
        if not cold and not byz and rng.random() < 0.18:
            self._attach_overlay_tier(rng, scn)
        if rng.random() < 0.15:
            scn.n_followers = 1
        # sharded hash-plane axis (ISSUE 15): derived from the already-
        # drawn scenario seed rather than a fresh rng draw, so adding
        # the axis leaves the generator's existing stream — and every
        # previously generated scenario — bit-identical. ~1 in 16 runs
        # route honest tree hashing through the meshed device hasher.
        if scn.seed & 0xF == 0:
            scn.mesh_width = (2, 4, 8)[(scn.seed >> 4) % 3]
        # liquidity-plane axis (ISSUE 17): seed-derived like the mesh
        # axis (the generator's rng stream stays bit-identical). ~1 in
        # 8 runs ride 2-5 synthetic path subscriptions on the watch
        # validator — per-close index identity + budgeted re-ranking
        # under whatever faults this schedule carries.
        if scn.seed & 0x7 == 0x3:
            scn.path_subs = 2 + ((scn.seed >> 3) & 0x3)
        # cascading-follower-tree axis (ISSUE 19): seed-derived like
        # the mesh/path axes, so the generator's rng stream — and every
        # previously generated scenario — stays bit-identical. ~1 in 8
        # runs grow a 3-6 follower tier arranged as a branching-2/3
        # tree (squelched so validations cascade through it): upstream
        # acquisition, re-home on mid-tree death, and byte-identical
        # reconvergence under whatever faults this schedule carries.
        if scn.seed & 0x7 == 0x5:
            scn.n_followers = max(scn.n_followers,
                                  3 + ((scn.seed >> 3) & 0x3))
            scn.follower_branching = 2 + ((scn.seed >> 5) & 0x1)
            if not scn.squelch_size:
                scn.squelch_size = 4

        raw: list[tuple] = []
        hostile = n - 1 if (byz or cold) else None
        protected = (hostile,) if cold else ()
        for _ in range(rng.randint(1, 3)):
            raw.extend(self._schedule_group(rng, n, steps, protected))
        if self.allow_synth and rng.random() < 0.4:
            for _ in range(rng.randint(1, 2)):
                raw.append((
                    rng.randint(4, steps - 4), "synth_plant",
                    (rng.randint(1, 2),), (),
                ))
        scn.schedule = self._materialize(scn.seed, raw)
        return scn

    def mutate(self, parent: Scenario) -> Scenario:
        """1-2 structure-preserving edits on a pool scenario."""
        rng = self.rng
        self.counter += 1
        scn = Scenario.from_json(parent.to_json())
        scn.name = f"fuzz-{self.seed}-{self.counter}"
        for _ in range(rng.randint(1, 2)):
            op = rng.choice((
                "reseed", "resize_workload", "retime", "add_group",
                "add_group", "drop_group", "resteps", "compose_axis",
            ))
            groups = schedule_groups(scn.schedule)
            if op == "reseed":
                scn.seed = rng.randrange(1 << 16)
            elif op == "resize_workload" and scn.workload:
                wl = dict(scn.workload)
                wl["n"] = max(8, int(wl["n"] * rng.choice((0.7, 1.4))))
                scn.workload = wl
            elif op == "retime" and groups:
                gi = rng.randrange(len(groups))
                shift = rng.choice((-6, -3, 3, 6))
                # clamp the SHIFT, not the events: independent clamping
                # could collapse a group (kill and revive on one step)
                # or push an opener past the horizon where the main
                # loop never applies it — an armed-but-dead fault the
                # anti-vacuity invariant would then (rightly) flag
                lo = min(e.at for e in groups[gi])
                opener_ats = [
                    e.at for e in groups[gi]
                    if e.kind not in ("heal", "revive",
                                      "clear_link_fault")
                ] or [lo]
                opener_hi = max(opener_ats)
                shift = max(shift, 2 - lo)
                shift = min(shift, (scn.steps - 4) - opener_hi)
                shifted = []
                for g_idx, g in enumerate(groups):
                    for e in g:
                        at = e.at + shift if g_idx == gi else e.at
                        shifted.append((at, e.kind, e.args, e.kwargs))
                sched = FaultSchedule(scn.seed)
                for at, kind, args, kwargs in shifted:
                    sched.add(at, kind, *args, **dict(kwargs))
                scn.schedule = sched
            elif op == "add_group":
                protected = scn.cold_nodes
                raw = self._schedule_group(
                    rng, scn.n_validators, scn.steps, protected
                )
                if self.allow_synth and rng.random() < 0.35:
                    raw.append((
                        rng.randint(4, scn.steps - 4), "synth_plant",
                        (rng.randint(1, 2),), (),
                    ))
                sched = scn.schedule or FaultSchedule(scn.seed)
                for at, kind, args, kwargs in raw:
                    sched.add(at, kind, *args, **dict(kwargs))
                scn.schedule = sched
            elif op == "drop_group" and len(groups) > 1:
                gi = rng.randrange(len(groups))
                scn.schedule = _sched_from_groups(
                    scn.seed, groups[:gi] + groups[gi + 1:]
                )
            elif op == "resteps":
                floor = max(
                    (e.at for e in _events_of(scn)), default=20
                ) + 10
                scn.steps = max(floor, scn.steps + rng.choice((-8, 8)))
            elif op == "compose_axis":
                # the exploration edge over uniform generation: COMPOSE
                # a hostile axis onto a scenario that already reached a
                # novel state — uniform sampling rarely stacks tiers,
                # mutation of a proven parent does it deliberately
                axis = rng.choice(
                    ("byzantine", "follower", "overlay", "txq")
                )
                if axis == "byzantine" and not scn.cold_nodes:
                    if scn.byzantine:
                        scn.byzantine = {}
                    else:
                        k = rng.randint(1, len(BEHAVIORS))
                        scn.byzantine = {
                            scn.n_validators - 1:
                            tuple(sorted(rng.sample(BEHAVIORS, k)))
                        }
                        scn.quorum = max(
                            scn.quorum,
                            (scn.n_validators + 1) // 2 + 1,
                        )
                elif axis == "follower":
                    scn.n_followers = 0 if scn.n_followers else 1
                elif axis == "overlay" and not scn.byzantine \
                        and not scn.cold_nodes:
                    if scn.n_peers or scn.resources:
                        scn.n_peers = 0
                        scn.squelch_size = 0
                        scn.resources = False
                        scn.flooders = {}
                    else:
                        self._attach_overlay_tier(rng, scn)
                elif axis == "txq" and scn.workload is not None:
                    scn.txq_cap = (
                        None if scn.txq_cap else rng.randint(4, 9)
                    )
        return scn


# -- shrinking ------------------------------------------------------------

def _weaken_ops(scn: Scenario) -> list[tuple[str, Scenario]]:
    """Candidate single-axis weakenings of a failing scenario, each a
    (label, new scenario) pair. Only applicable ones are returned."""
    out: list[tuple[str, Scenario]] = []

    def clone() -> Scenario:
        return Scenario.from_json(scn.to_json())

    ev_max = max((e.at for e in _events_of(scn)), default=0)
    floor = ev_max + 12
    if scn.steps > floor:
        c = clone()
        c.steps = floor
        out.append(("shrink_steps", c))
    if scn.workload is not None:
        c = clone()
        c.workload = None
        c.txq_cap = None
        out.append(("drop_workload", c))
        if scn.workload.get("n", 0) > 8:
            c = clone()
            wl = dict(c.workload)
            wl["n"] = max(8, int(wl["n"] * 0.5))
            c.workload = wl
            out.append(("halve_workload", c))
    if scn.txq_cap is not None and scn.workload is not None:
        c = clone()
        c.txq_cap = None
        out.append(("drop_txq", c))
    if scn.n_peers or scn.squelch_size or scn.resources or scn.flooders:
        c = clone()
        c.n_peers = 0
        c.squelch_size = 0
        c.resources = False
        c.flooders = {}
        out.append(("drop_overlay_tier", c))
    if scn.n_followers:
        c = clone()
        c.n_followers = 0
        out.append(("drop_followers", c))
    if getattr(scn, "follower_branching", 0):
        # flatten the cascade but keep the tier: isolates tree
        # plumbing (upstream acquisition / re-home) from plain
        # follower ingest as the failing axis
        c = clone()
        c.follower_branching = 0
        out.append(("drop_follower_tree", c))
    if getattr(scn, "mesh_width", 0):
        c = clone()
        c.mesh_width = 0
        out.append(("drop_mesh", c))
    if getattr(scn, "path_subs", 0):
        c = clone()
        c.path_subs = 0
        out.append(("drop_path_subs", c))
    if scn.byzantine:
        c = clone()
        c.byzantine = {}
        out.append(("drop_byzantine", c))
        for nid, behaviors in sorted(scn.byzantine.items()):
            if len(behaviors) > 1:
                for b in behaviors:
                    c = clone()
                    bs = tuple(x for x in behaviors if x != b)
                    c.byzantine = {**scn.byzantine, nid: bs}
                    out.append((f"drop_behavior:{b}", c))
    if getattr(scn, "archive", False):
        # keep the shard tier but drop the archive backfill: isolates
        # the distribution-network leg from the cold-sync leg
        c = clone()
        c.archive = False
        out.append(("drop_archive", c))
    if getattr(scn, "shards", False):
        c = clone()
        c.shards = False
        c.shard_trim_seq = 0
        c.archive = False
        out.append(("drop_shard_tier", c))
    if scn.cold_nodes:
        c = clone()
        c.cold_nodes = ()
        c.segments = False
        c.garbage_server = None
        c.kill_server_at = None
        c.shards = False
        c.shard_trim_seq = 0
        c.archive = False
        out.append(("drop_cold_node", c))
    # per-event weakenings: plant magnitude down, fault probs halved
    for i, e in enumerate(_events_of(scn)):
        if e.kind == "synth_plant" and e.args[0] > 1:
            c = clone()
            evs = list(c.schedule.events)
            evs[i] = type(e)(e.at, e.order, e.kind,
                             (e.args[0] - 1,), e.kwargs)
            c.schedule.events = evs
            out.append((f"weaken_plant:{i}", c))
        elif e.kind == "link_fault":
            kw = dict(e.kwargs)
            changed = False
            for key in ("drop", "dup"):
                if kw.get(key, 0) > 0.1:
                    kw[key] = round(kw[key] / 2, 3)
                    changed = True
            if changed:
                c = clone()
                evs = list(c.schedule.events)
                evs[i] = type(e)(e.at, e.order, e.kind, e.args,
                                 tuple(sorted(kw.items())))
                c.schedule.events = evs
                out.append((f"weaken_link_fault:{i}", c))
    return out


def shrink_scenario(
    scn: Scenario,
    violation: Violation,
    run_fn: Callable[[Scenario], dict] = run_simnet,
    max_runs: int = 80,
) -> tuple[Scenario, list[dict]]:
    """Greedy schedule shrinking: repeatedly (a) drop whole fault
    groups, (b) weaken one axis, keeping any edit under which the SAME
    invariant still fires, until a fixpoint or the run budget. Returns
    (minimal scenario, trajectory); the trajectory is deterministic for
    a deterministic run_fn (pinned by test)."""
    runs = 0
    trajectory: list[dict] = []

    def reproduces(cand: Scenario) -> bool:
        nonlocal runs
        runs += 1
        card = run_fn(cand)
        recard = run_fn(cand) if violation.invariant == "determinism" \
            else None
        viols = check_invariants(cand, card, recard)
        return any(v.invariant == violation.invariant for v in viols)

    cur = scn
    outer = True
    while outer and runs < max_runs:
        outer = False
        # pass A: drop whole fault groups, first-fit, restart on success
        progress = True
        while progress and runs < max_runs:
            progress = False
            groups = schedule_groups(cur.schedule)
            if len(groups) <= 1 and cur.workload is None:
                break
            for gi in range(len(groups)):
                cand = Scenario.from_json(cur.to_json())
                cand.schedule = _sched_from_groups(
                    cand.seed, groups[:gi] + groups[gi + 1:]
                )
                ok = reproduces(cand)
                trajectory.append({
                    "op": f"drop_group:{gi}", "kept": ok,
                    "digest": cand.digest(),
                })
                if ok:
                    cur = cand
                    progress = True
                    outer = True
                    break
        # pass B: single-axis weakenings, first-fit
        progress = True
        while progress and runs < max_runs:
            progress = False
            for label, cand in _weaken_ops(cur):
                ok = reproduces(cand)
                trajectory.append({
                    "op": label, "kept": ok, "digest": cand.digest(),
                })
                if ok:
                    cur = cand
                    progress = True
                    outer = True
                    break
    return cur, trajectory


# -- corpus ---------------------------------------------------------------

def corpus_entry(scn: Scenario, violation: Violation,
                 found: dict, expect: str = "pass",
                 flight_dump: Optional[str] = None) -> dict:
    """A corpus entry: the shrunk data-form scenario plus provenance.
    `expect` records the entry's contract under replay — "pass" for a
    fixed bug pinned as a regression, "violation" for a live repro
    (only the planted synthetic bug ships that way, and only inside
    the armed smoke). `flight_dump` references the violating run's
    flight-recorder black box on disk (node/health.py)."""
    name = f"fuzz_{violation.invariant}_{scn.digest()[:8]}"
    entry = {
        "corpus_format": 1,
        "name": name,
        "invariant": violation.invariant,
        "detail": violation.detail,
        "found": found,
        "expect": expect,
        "scenario": scn.to_json(),
    }
    if flight_dump:
        entry["flight_dump"] = flight_dump
    return entry


def _dump_violation_flight(scn: Scenario, violation: Violation) -> Optional[str]:
    """Ship the violating run's black box (the most recent run_simnet's
    FlightRecorder) to a stable temp location; -> path or None."""
    from .scenario import LAST_FLIGHT

    rec = LAST_FLIGHT[0] if LAST_FLIGHT else None
    if rec is None:
        return None
    import tempfile

    d = os.path.join(tempfile.gettempdir(), "stellard-flight")
    return rec.dump(
        f"fuzz-{violation.invariant}-{scn.digest()[:8]}", directory=d
    )


def write_corpus_entry(entry: dict, corpus_dir: str) -> str:
    os.makedirs(corpus_dir, exist_ok=True)
    path = os.path.join(corpus_dir, f"{entry['name']}.json")
    with open(path, "w") as f:
        json.dump(entry, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


# -- the sweep ------------------------------------------------------------

_ENERGY_NOVEL = 8
_ENERGY_REWARD = 4


def _pick_weighted(pool: list[dict], rng: random.Random) -> dict:
    total = sum(p["energy"] for p in pool)
    x = rng.random() * total
    acc = 0.0
    for p in pool:
        acc += p["energy"]
        if x <= acc:
            return p
    return pool[-1]


def sweep(
    fuzz_seed: int,
    n_runs: int,
    guided: bool = True,
    allow_synth: bool = False,
    shrink: bool = True,
    determinism_check: bool = True,
    run_fn: Callable[[Scenario], dict] = run_simnet,
    on_progress: Optional[Callable[[dict], None]] = None,
    max_shrink_runs: int = 80,
) -> dict:
    """One coverage-guided fuzz sweep: `n_runs` generated scenarios
    through `run_fn`, the coverage map biasing generation toward
    schedules reaching novel scorecard states (AFL-style energy over a
    pool of novelty-reaching parents; `guided=False` = uniform random
    generation, the baseline the smoke compares against). Novel-state
    scenarios are re-run for the byte-identical-scorecard invariant
    when `determinism_check`. Every invariant violation is (optionally)
    shrunk to a minimal scenario. Deterministic: the returned
    `scenario_digests`, `coverage_trajectory`, and every shrink
    trajectory are pure functions of (fuzz_seed, flags)."""
    was_armed = SYNTH_BUG["armed"]
    if allow_synth:
        SYNTH_BUG["armed"] = True
    try:
        gen = ScenarioGenerator(fuzz_seed, allow_synth=allow_synth)
        pool: list[dict] = []
        seen: dict[str, int] = {}
        scenario_digests: list[str] = []
        coverage_trajectory: list[str] = []
        violations: list[dict] = []
        for i in range(n_runs):
            parent = None
            if guided and pool and gen.rng.random() >= 0.3:
                parent = _pick_weighted(pool, gen.rng)
                scn = gen.mutate(Scenario.from_json(parent["scenario"]))
            else:
                scn = gen.fresh()
            card = run_fn(scn)
            sig = coverage_signature(card)
            scenario_digests.append(scn.digest())
            coverage_trajectory.append(sig)
            novel = sig not in seen
            recard = None
            if novel:
                seen[sig] = i
                if determinism_check:
                    recard = run_fn(scn)
                if guided:
                    pool.append({
                        "scenario": scn.to_json(),
                        "energy": _ENERGY_NOVEL,
                    })
                    if parent is not None:
                        parent["energy"] += _ENERGY_REWARD
            elif parent is not None:
                parent["energy"] = max(1, parent["energy"] - 1)
            viols = check_invariants(scn, card, recard)
            flight_path = (
                _dump_violation_flight(scn, viols[0]) if viols else None
            )
            # one record per invariant CLASS per run: recording only
            # the first would let an armed synth_plant violation (always
            # ordered first) mask a co-occurring REAL violation from
            # the smoke's any-real-violation-is-red gate
            seen_kinds: set = set()
            for v in viols:
                if v.invariant in seen_kinds:
                    continue
                seen_kinds.add(v.invariant)
                rec = {
                    "iteration": i,
                    "invariant": v.invariant,
                    "detail": v.detail,
                    "scenario": scn.to_json(),
                }
                if flight_path:
                    rec["flight_dump"] = flight_path
                # shrink budget: one full shrink per invariant NAME per
                # sweep — later repros of the same class are recorded
                # raw (the first minimal entry is the regression pin)
                first_of_kind = v.invariant not in {
                    x["invariant"] for x in violations
                }
                if shrink and first_of_kind:
                    minimal, traj = shrink_scenario(
                        scn, v, run_fn=run_fn,
                        max_runs=max_shrink_runs,
                    )
                    rec["shrunk"] = minimal.to_json()
                    rec["shrink_trajectory"] = traj
                    rec["entry"] = corpus_entry(
                        minimal, v,
                        found={"fuzz_seed": fuzz_seed, "iteration": i},
                        expect="pass",
                        flight_dump=flight_path,
                    )
                violations.append(rec)
            if on_progress is not None:
                on_progress({
                    "iteration": i, "novel": novel, "signature": sig,
                    "violations": len(violations),
                    "scenario": scn.name,
                })
        return {
            "fuzz_seed": fuzz_seed,
            "runs": n_runs,
            "guided": guided,
            "distinct_signatures": len(seen),
            "scenario_digests": scenario_digests,
            "coverage_trajectory": coverage_trajectory,
            "violations": violations,
        }
    finally:
        SYNTH_BUG["armed"] = was_armed


def coverage_comparison(
    fuzz_seed: int, n_runs: int,
    run_fn: Callable[[Scenario], dict] = run_simnet,
    on_progress: Optional[Callable[[dict], None]] = None,
) -> dict:
    """Coverage-guided vs uniform random generation over the same
    budget (the ISSUE's novelty-bias criterion): distinct scorecard
    coverage states per N runs, same fuzz seed, no shrinking and no
    determinism re-runs so the comparison is purely about generation."""
    guided = sweep(
        fuzz_seed, n_runs, guided=True, shrink=False,
        determinism_check=False, run_fn=run_fn,
        on_progress=on_progress,
    )
    uniform = sweep(
        fuzz_seed, n_runs, guided=False, shrink=False,
        determinism_check=False, run_fn=run_fn,
        on_progress=on_progress,
    )
    return {
        "runs": n_runs,
        "guided_distinct": guided["distinct_signatures"],
        "uniform_distinct": uniform["distinct_signatures"],
        "guided_violations": len(guided["violations"]),
        "uniform_violations": len(uniform["violations"]),
    }
