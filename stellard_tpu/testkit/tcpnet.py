"""Real-process TCP+TLS transport for the scenario plane.

The net-lab helpers (config template, launcher, RPC client) used to
live only in tools/netlab.py; they are the package's now so the
scenario runner, tests/test_multiproc_net.py and tools/chaos_soak.py
share exactly one implementation (tools/netlab.py re-exports).

``run_tcp`` drives the SAME ``Scenario`` definitions as
``scenario.run_simnet`` — fault schedule (the kill/revive subset a
process net can express: a kill is a real SIGTERM/SIGKILL, a revive a
respawn that must catch up over genuine sockets), workload (the
identical pre-signed tx stream, submitted as tx_blob over the RPC
door), convergence tail, scorecard. Wall-clock and scheduler noise make
the TCP scorecard non-deterministic; its value is that the same
scenario shape survives real processes, not replayability.
"""

from __future__ import annotations

import json
import os
import random
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

from ..protocol.keys import KeyPair
from .schedule import FaultSchedule
from .workloads import TxFactory, build_spec_workload

__all__ = [
    "free_ports", "rpc", "wait_until", "validator_config",
    "spawn_validator", "run_tcp", "hostile_flood", "REPO", "SPEED",
]

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
SPEED = 5.0  # virtual seconds per real second (clock_speed knob)


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def rpc(port: int, method: str, params: dict | None = None, timeout=5.0):
    body = json.dumps({"method": method, "params": [params or {}]}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=body,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)["result"]


def wait_until(pred, timeout: float, interval: float = 0.5):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            last = pred()
            if last:
                return last
        except Exception:
            pass
        time.sleep(interval)
    return last


def validator_config(i: int, keys, peer_ports, rpc_port, ws_port=None,
                     quorum=3, speed=SPEED) -> str:
    """One validator's INI (the shape the reference's private-net
    example config documents: UNL of the OTHER validators, fixed peer
    list, quorum)."""
    n = len(keys)
    others_keys = "\n".join(
        keys[j].human_node_public for j in range(n) if j != i
    )
    others_addrs = "\n".join(
        f"127.0.0.1 {peer_ports[j]}" for j in range(n) if j != i
    )
    ws = f"\n[websocket_port]\n{ws_port}\n" if ws_port is not None else ""
    return f"""
[standalone]
0

[node_db]
type=memory

[signature_backend]
type=cpu

[validation_seed]
{keys[i].human_seed}

[validators]
{others_keys}

[validation_quorum]
{quorum}

[peer_port]
{peer_ports[i]}

[peer_ssl]
require

[ips]
{others_addrs}

[clock_speed]
{speed}

[rpc_port]
{rpc_port}
{ws}"""


def spawn_validator(cfg_path: str, stdout=subprocess.DEVNULL):
    """Launch one validator process from its config (never grabbing the
    TPU tunnel)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-m", "stellard_tpu", "--conf", cfg_path,
         "--start"],
        cwd=REPO, env=env, stdout=stdout, stderr=subprocess.STDOUT,
    )


def hostile_flood(
    peer_port: int,
    frames: int = 200,
    mode: str = "junk_tx",
    host: str = "127.0.0.1",
    passphrase: str = "tcp-flooder",
    reconnects: int = 3,
) -> dict:
    """The byzantine matrix promoted onto the REAL TCP net (carried
    PR 8 follow-on): a hostile client that completes a genuine
    nonce+signed-hello handshake with a throwaway key, then floods the
    victim with hostile frames until the victim's resource plane drops
    it. Modes:

        junk_tx    TxMessage frames with unparseable blobs
                   (FEE_BAD_DATA per frame at the victim)
        garbage    out-of-schema message types (kills the session per
                   frame — exercised via `reconnects` handshake loops)

    Returns {"sent", "disconnected", "reconnect_refused"} — the caller
    asserts the victim disconnected the flooder AND refuses its
    readmission (the `resource.*` drop gate), while staying healthy.
    Works against any plaintext [peer_port] (in-process TcpOverlay or
    a spawned validator)."""
    from ..overlay.tcp import HP_SESSION, PROTO_VERSION
    from ..overlay.wire import FrameReader, Hello, TxMessage, frame
    from ..utils.hashes import prefix_hash

    key = KeyPair.from_passphrase(passphrase)
    rng = random.Random(0x7C9F)
    stats = {"sent": 0, "disconnected": False, "reconnect_refused": False}

    def handshake(sock) -> bool:
        sock.settimeout(5.0)
        nonce = os.urandom(32)
        while nonce[0] == 0x16:  # never look like a TLS ClientHello
            nonce = os.urandom(32)
        sock.sendall(nonce)
        theirs = b""
        while len(theirs) < 32:
            chunk = sock.recv(32 - len(theirs))
            if not chunk:
                return False
            theirs += chunk
        session_hash = prefix_hash(
            HP_SESSION, min(nonce, theirs) + max(nonce, theirs)
        )
        hello = Hello(
            PROTO_VERSION, 35_000_000, key.public,
            key.sign(session_hash), 1, b"\x00" * 32, 0,
        )
        sock.sendall(frame(hello))
        reader = FrameReader()
        while True:
            data = sock.recv(65536)
            if not data:
                return False
            if reader.feed(data):
                return True

    def closed(sock, timeout=10.0) -> bool:
        sock.settimeout(timeout)
        try:
            while True:
                if sock.recv(65536) == b"":
                    return True
        except (ConnectionResetError, BrokenPipeError):
            return True
        except OSError:
            return False

    for _episode in range(max(1, reconnects)):
        try:
            sock = socket.create_connection((host, peer_port), timeout=5.0)
        except OSError:
            stats["reconnect_refused"] = True
            return stats
        try:
            if not handshake(sock):
                # refused before/at hello: the admission gate is shut
                stats["reconnect_refused"] = stats["disconnected"]
                return stats
            for _ in range(frames):
                if mode == "garbage":
                    data = (
                        (3).to_bytes(4, "big") + (99).to_bytes(2, "big")
                        + b"\x00\x01\x02"
                    )
                else:
                    blob = bytes(rng.randrange(256) for _ in range(24))
                    data = frame(TxMessage(blob))
                try:
                    sock.sendall(data)
                except OSError:
                    stats["disconnected"] = True
                    break
                stats["sent"] += 1
            if not stats["disconnected"]:
                stats["disconnected"] = closed(sock)
        except OSError:
            stats["disconnected"] = True
        finally:
            try:
                sock.close()
            except OSError:
                pass
        if stats["disconnected"]:
            # probe readmission: a dropped endpoint must be refused at
            # accept (closed without a nonce) until its balance decays
            try:
                probe = socket.create_connection(
                    (host, peer_port), timeout=5.0
                )
            except OSError:
                stats["reconnect_refused"] = True
                return stats
            try:
                probe.settimeout(5.0)
                got = b""
                try:
                    got = probe.recv(32)
                except (socket.timeout, OSError):
                    got = b""
                stats["reconnect_refused"] = got == b""
            finally:
                probe.close()
            return stats
    return stats


TCP_EVENT_KINDS = {"kill", "revive"}


def run_tcp(scn, step_seconds: float = 1.0,
            mesh_timeout: float = 120.0) -> dict:
    """Execute a Scenario's kill/revive + workload shape on a real
    process net; returns a (non-deterministic) scorecard with the same
    field names as the simnet one where they apply."""
    # same data-form + builder merge as run_simnet: matrix scenarios
    # now carry schedule=/workload= DATA rather than closures, and the
    # TCP runner must consume both forms or a migrated scenario runs
    # with no faults and no traffic (a vacuous soak that greenwashes)
    sched = FaultSchedule(scn.seed)
    if scn.schedule is not None:
        sched.extend(scn.schedule.events)
    if scn.build_schedule is not None:
        scn.build_schedule(sched, scn)
    unsupported = {
        e.kind for e in sched.events if e.kind not in TCP_EVENT_KINDS
    }
    if unsupported:
        raise ValueError(
            f"scenario {scn.name!r} uses fault kinds the TCP transport "
            f"cannot express: {sorted(unsupported)}"
        )

    fac = TxFactory(seed=scn.seed)
    wl_rng = random.Random(0x301C ^ scn.seed)
    build_workload = scn.build_workload
    if build_workload is None and scn.workload is not None:
        build_workload = build_spec_workload(scn.workload)
    workload = (
        build_workload(fac, wl_rng, scn)
        if build_workload is not None else []
    )
    by_step: dict[int, list] = {}
    for at, nid, tx in workload:
        by_step.setdefault(at, []).append((nid, tx))

    n = scn.n_validators
    tmp = tempfile.mkdtemp(prefix="scn-tcp-")
    ports = free_ports(2 * n)
    peer_ports, rpc_ports = ports[:n], ports[n:]
    keys = [KeyPair.from_passphrase(f"chaos-val-{i}") for i in range(n)]
    cfg_paths = []
    for i in range(n):
        p = os.path.join(tmp, f"v{i}.cfg")
        with open(p, "w") as f:
            f.write(validator_config(
                i, keys, peer_ports, rpc_ports[i], quorum=scn.quorum
            ))
        cfg_paths.append(p)

    procs: list = [None] * n
    down: set[int] = set()
    stats = {"submitted": 0, "errors": 0, "kills": 0}

    def respawn(i):
        procs[i] = spawn_validator(cfg_paths[i])

    def terminate(i):
        p = procs[i]
        if p is None:
            return
        p.terminate()
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()

    for i in range(n):
        respawn(i)

    try:
        def meshed():
            try:
                return all(
                    rpc(p, "server_info")["info"]["peers"] == n - 1
                    for p in rpc_ports
                )
            except Exception:
                return False

        if not wait_until(meshed, mesh_timeout, 2.0):
            raise RuntimeError("net never meshed")

        def submit(nid, tx):
            order = [nid] + [i for i in range(n) if i != nid]
            for i in order:
                if i in down:
                    continue
                try:
                    rpc(rpc_ports[i], "submit",
                        {"tx_blob": tx.serialize().hex()}, timeout=15)
                    stats["submitted"] += 1
                    return
                except Exception:
                    continue
            stats["errors"] += 1

        for step in range(scn.steps):
            t0 = time.monotonic()
            for ev in sched.events_at(step):
                if ev.kind == "kill":
                    terminate(ev.args[0])
                    down.add(ev.args[0])
                    stats["kills"] += 1
                elif ev.kind == "revive":
                    respawn(ev.args[0])
                    down.discard(ev.args[0])
            for nid, tx in by_step.get(step, ()):
                submit(nid, tx)
            left = step_seconds - (time.monotonic() - t0)
            if left > 0:
                time.sleep(left)
        for ev in sorted(
            (e for e in sched.events if e.at >= scn.steps),
            key=lambda e: (e.at, e.order),
        ):
            if ev.kind == "revive":
                respawn(ev.args[0])
                down.discard(ev.args[0])

        def seqs():
            out = []
            for p in rpc_ports:
                try:
                    out.append(
                        rpc(p, "server_info")["info"]["validated_ledger"]["seq"]
                    )
                except Exception:
                    out.append(-1)
            return out

        target = max(seqs()) + scn.converge_extra
        budget = max(120.0, scn.max_tail_steps * step_seconds)
        deadline = time.monotonic() + budget
        last = seqs()
        while min(last) < target and time.monotonic() < deadline:
            time.sleep(3)
            last = seqs()
        converged = min(last) >= target
        common = min(last)
        hashes = set()
        single = False
        if converged:
            try:
                hashes = {
                    rpc(p, "ledger", {"ledger_index": common})
                    ["ledger"]["hash"]
                    for p in rpc_ports
                }
                single = len(hashes) == 1
            except Exception:
                single = False
        return {
            "scenario": scn.name,
            "seed": scn.seed,
            "transport": "tcp",
            "steps": scn.steps,
            "converged": converged,
            "final_seq": common,
            "final_hash": next(iter(hashes)) if single else None,
            "single_hash": single,
            "validated_seqs": last,
            "submitted": stats["submitted"],
            "errors": stats["errors"],
            "kills": stats["kills"],
            "fault_digest": sched.digest(),
        }
    finally:
        for i in range(n):
            terminate(i)
