"""Hostile workload generators: deterministic transaction streams that
stress the planes PERF.md's friendly payment flood never touches —
hot-account write contention (delta-replay splice rate collapses to
fallbacks), crossing-heavy order books (succ-walk phantom checks), and
queue-gaming fee patterns (admission-plane fairness under adversarial
fee bidding).

A workload is a list of ``(step, origin_nid, tx)`` items; ``TxFactory``
owns the deterministic key material and per-sender sequence chains so a
seed maps to exactly one byte-identical stream.
"""

from __future__ import annotations

import random

from ..protocol.formats import TxType
from ..protocol.keys import KeyPair
from ..protocol.sfields import (
    sfAmount,
    sfDestination,
    sfLimitAmount,
    sfTakerGets,
    sfTakerPays,
)
from ..protocol.stamount import STAmount, currency_from_iso
from ..protocol.sttx import SerializedTransaction

__all__ = [
    "TxFactory",
    "payment_flood",
    "hot_account_flood",
    "order_book_crossfire",
    "fee_gaming",
    "WORKLOADS",
    "build_spec_workload",
]

XRP = 1_000_000
USD = currency_from_iso("USD")


class TxFactory:
    """Deterministic tx material: passphrase-derived keys (stable across
    processes) and per-sender sequence counters."""

    def __init__(self, seed: int = 0, n_accounts: int = 8):
        self.seed = seed
        self.master = KeyPair.from_passphrase("masterpassphrase")
        self.accounts = [
            KeyPair.from_passphrase(f"scn-{seed}-acct-{i}")
            for i in range(n_accounts)
        ]
        self.gateway = KeyPair.from_passphrase(f"scn-{seed}-gateway")
        self._seqs: dict[bytes, int] = {}

    def next_seq(self, kp: KeyPair) -> int:
        s = self._seqs.get(kp.account_id, 1)
        self._seqs[kp.account_id] = s + 1
        return s

    def _build(self, kp: KeyPair, tx_type, fields: dict,
               fee: int = 10) -> SerializedTransaction:
        tx = SerializedTransaction.build(
            tx_type, kp.account_id, self.next_seq(kp), fee, fields
        )
        tx.sign(kp)
        return tx

    def payment(self, src: KeyPair, dst: bytes, drops: int,
                fee: int = 10) -> SerializedTransaction:
        return self._build(
            src, TxType.ttPAYMENT,
            {sfAmount: STAmount.from_drops(drops), sfDestination: dst},
            fee=fee,
        )

    def payment_at_seq(self, src: KeyPair, seq: int, dst: bytes,
                       drops: int, fee: int) -> SerializedTransaction:
        """Explicit-sequence payment (replace-by-fee gaming needs to
        re-issue one sequence at a higher fee)."""
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, src.account_id, seq, fee,
            {sfAmount: STAmount.from_drops(drops), sfDestination: dst},
        )
        tx.sign(src)
        return tx

    def trust(self, src: KeyPair, issuer: KeyPair,
              limit: int) -> SerializedTransaction:
        return self._build(
            src, TxType.ttTRUST_SET,
            {sfLimitAmount: STAmount.from_iou(
                USD, issuer.account_id, limit, 0
            )},
        )

    def iou_payment(self, src: KeyPair, dst: bytes, value: int,
                    exponent: int = 0) -> SerializedTransaction:
        return self._build(
            src, TxType.ttPAYMENT,
            {
                sfAmount: STAmount.from_iou(
                    USD, self.gateway.account_id, value, exponent
                ),
                sfDestination: dst,
            },
        )

    def offer(self, src: KeyPair, taker_pays: STAmount,
              taker_gets: STAmount) -> SerializedTransaction:
        return self._build(
            src, TxType.ttOFFER_CREATE,
            {sfTakerPays: taker_pays, sfTakerGets: taker_gets},
        )

    def fund_all(self, drops: int = 10_000 * XRP) -> list:
        """Master funds every scenario account (+ the gateway)."""
        out = [
            self.payment(self.master, kp.account_id, drops)
            for kp in self.accounts
        ]
        out.append(self.payment(self.master, self.gateway.account_id, drops))
        return out


def _spread(rng: random.Random, txs, start: int, end: int,
            n_validators: int, origin=None) -> list:
    """Assign steps (uniform in [start, end)) and origins to a tx list,
    keeping per-sender order (sequence chains must submit in order) and
    a STABLE per-sender origin (a chain scattered across validators
    scrambles into terPRE_SEQ holds before the relay catches up — a
    real client talks to one node)."""
    items = []
    step_of_sender: dict[bytes, int] = {}
    for tx in txs:
        lo = max(start, step_of_sender.get(tx.account, start))
        at = rng.randrange(lo, max(lo + 1, end))
        step_of_sender[tx.account] = at  # same step ok: FIFO within step
        nid = origin if origin is not None else (
            int.from_bytes(tx.account[:4], "big") % n_validators
        )
        items.append((at, nid, tx))
    items.sort(key=lambda it: it[0])
    return items


def payment_flood(fac: TxFactory, rng: random.Random, *, start: int,
                  end: int, n: int, n_validators: int) -> list:
    """Friendly-ish baseline flood: independent senders, spread dests."""
    txs = []
    for i in range(n):
        src = fac.accounts[i % len(fac.accounts)]
        dst = fac.accounts[(i + 1) % len(fac.accounts)].account_id
        txs.append(fac.payment(src, dst, (1 + i % 7) * XRP))
    return _spread(rng, txs, start, end, n_validators)


def hot_account_flood(fac: TxFactory, rng: random.Random, *, start: int,
                      end: int, n: int, n_validators: int) -> list:
    """Hot-account contention: every tx touches ONE destination account
    root (and half share one sender), so speculative records chain on a
    single entry — the adversarial shape for delta-replay splicing."""
    hot_dst = fac.accounts[0].account_id
    txs = []
    for i in range(n):
        src = fac.accounts[0] if i % 2 else fac.accounts[1 + i % (
            len(fac.accounts) - 1
        )]
        if src.account_id == hot_dst:
            dst = fac.accounts[1].account_id
        else:
            dst = hot_dst
        txs.append(fac.payment(src, dst, (1 + i % 3) * XRP))
    return _spread(rng, txs, start, end, n_validators)


def order_book_crossfire(fac: TxFactory, rng: random.Random, *,
                         start: int, end: int, n: int,
                         n_validators: int) -> list:
    """Crossing-heavy one-book mix: trust lines + issuance up front,
    then alternating buy/sell offers priced to cross — every apply walks
    the book directories (the succ-cursor phantom-protection seam)."""
    a, b = fac.accounts[0], fac.accounts[1]
    setup = [
        fac.trust(a, fac.gateway, 1_000_000),
        fac.trust(b, fac.gateway, 1_000_000),
    ]
    issue = [
        fac.iou_payment(fac.gateway, a.account_id, 100_000),
        fac.iou_payment(fac.gateway, b.account_id, 100_000),
    ]
    offers = []
    for i in range(n):
        # a sells USD for XRP; b crosses it buying USD with XRP — price
        # wobbles so some offers rest, some cross fully, some partially
        usd = STAmount.from_iou(USD, fac.gateway.account_id, 10 + i % 5, 0)
        xrp = STAmount.from_drops((5 + i % 7) * XRP)
        if i % 2 == 0:
            offers.append(fac.offer(a, xrp, usd))
        else:
            offers.append(fac.offer(b, usd, xrp))
    mid = start + max(2, (end - start) // 6)
    items = _spread(rng, setup, start, start + 1, n_validators, origin=0)
    items += _spread(rng, issue, start + 1, mid, n_validators, origin=0)
    items += _spread(rng, offers, mid, end, n_validators)
    items.sort(key=lambda it: it[0])
    return items


def build_spec_workload(spec: dict):
    """Workloads as DATA (scenario serialization / the fuzz generator):
    ``{"kind": <WORKLOADS name>, "n": N[, "start": S, "end_margin": M,
    ...extra kwargs]}`` becomes the standard funded-flood builder —
    master funds every scenario account at step 0, then the named
    stream runs over ``[start, scn.steps - end_margin)``. The returned
    builder is a pure function of (seed, scenario), so a serialized
    scenario replays byte-identically."""
    spec = dict(spec)
    fn = WORKLOADS[spec.pop("kind")]
    n = int(spec.pop("n"))
    start = int(spec.pop("start", 6))
    end_margin = int(spec.pop("end_margin", 6))

    def build(fac: TxFactory, rng: random.Random, scn) -> list:
        items = [(0, 0, tx) for tx in fac.fund_all()]
        items += fn(
            fac, rng, start=start,
            end=max(start + 1, scn.steps - end_margin), n=n,
            n_validators=scn.n_validators, **spec,
        )
        items.sort(key=lambda it: it[0])
        return items

    return build


def fee_gaming(fac: TxFactory, rng: random.Random, *, start: int,
               end: int, n: int, n_validators: int,
               origin: int = 0) -> list:
    """Queue-gaming fee patterns against the admission plane on ONE
    node: a base-fee flood past the soft cap, high-fee bursts that must
    jump the line, and replace-by-fee re-bids of queued sequences. The
    runner checks fee-ordered drain and no-starvation."""
    txs = []
    senders = fac.accounts[: max(4, len(fac.accounts) // 2)]
    for i in range(n):
        src = senders[i % len(senders)]
        dst = fac.accounts[(i + 3) % len(fac.accounts)].account_id
        burst = (i // len(senders)) % 4 == 3
        fee = 10 if not burst else 10 * (20 + i % 10)
        seq = fac.next_seq(src)
        txs.append(fac.payment_at_seq(src, seq, dst, XRP, fee))
        if burst and i % 5 == 0:
            # replace-by-fee: re-issue the SAME sequence at +50%
            txs.append(fac.payment_at_seq(src, seq, dst, XRP,
                                          int(fee * 3 // 2)))
    return _spread(rng, txs, start, end, n_validators, origin=origin)


# named-workload registry: the serializable half of every scenario's
# workload axis (build_spec_workload interprets {"kind": <name>, ...})
WORKLOADS = {
    "payment_flood": payment_flood,
    "hot_account_flood": hot_account_flood,
    "order_book_crossfire": order_book_crossfire,
    "fee_gaming": fee_gaming,
}
