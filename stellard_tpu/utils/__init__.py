from .hashes import sha512_half, prefix_hash, hash160, sha256d_checksum
from .base58 import b58_encode, b58_decode, b58check_encode, b58check_decode
