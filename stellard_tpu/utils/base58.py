"""Base58 / Base58Check with the Stellar alphabet.

The reference uses a custom alphabet beginning with 'g' (so version-0
account IDs render as g...) — reference:
src/ripple/types/impl/Base58.cpp:43-49.  Check encoding appends the first
4 bytes of double-SHA256 (Base58.cpp:52-88, 212-233).
"""

from __future__ import annotations

from .hashes import sha256d_checksum

# Protocol constant (reference: Base58.cpp:46)
STELLAR_ALPHABET = "gsphnaf39wBUDNEGHJKLM4PQRST7VWXYZ2bcdeCr65jkm8oFqi1tuvAxyz"
_INDEX = {c: i for i, c in enumerate(STELLAR_ALPHABET)}


def b58_encode(data: bytes, alphabet: str = STELLAR_ALPHABET) -> str:
    n = int.from_bytes(data, "big")
    out = []
    while n > 0:
        n, r = divmod(n, 58)
        out.append(alphabet[r])
    # each leading zero byte encodes as the zero character
    for b in data:
        if b == 0:
            out.append(alphabet[0])
        else:
            break
    return "".join(reversed(out))


def b58_decode(s: str, alphabet: str = STELLAR_ALPHABET) -> bytes:
    index = _INDEX if alphabet is STELLAR_ALPHABET else {c: i for i, c in enumerate(alphabet)}
    n = 0
    for c in s:
        if c not in index:
            raise ValueError(f"invalid base58 character {c!r}")
        n = n * 58 + index[c]
    raw = n.to_bytes((n.bit_length() + 7) // 8, "big") if n else b""
    pad = 0
    for c in s:
        if c == alphabet[0]:
            pad += 1
        else:
            break
    return b"\x00" * pad + raw


def b58check_encode(version: int, payload: bytes) -> str:
    """Version byte + payload + 4-byte double-SHA256 checksum."""
    data = bytes([version]) + payload
    return b58_encode(data + sha256d_checksum(data))


def b58check_decode(s: str, expected_version: int | None = None) -> tuple[int, bytes]:
    raw = b58_decode(s)
    if len(raw) < 5:
        raise ValueError("base58check string too short")
    data, check = raw[:-4], raw[-4:]
    if sha256d_checksum(data) != check:
        raise ValueError("base58check checksum mismatch")
    version = data[0]
    if expected_version is not None and version != expected_version:
        raise ValueError(f"base58check version {version} != expected {expected_version}")
    return version, data[1:]
