"""Device-call watchdog: run accelerator calls on a sacrificial thread.

The TPU tunnel's observed failure mode is an indefinite HANG — client
init or any device op blocks forever without raising (r3 judge probe;
r4 on-chip sessions; the hang does not hold the GIL). A validator must
degrade to its CPU backends instead of freezing mid-consensus: the
reference treats a stalled subsystem as a loudly-reported fault, never
a silent freeze (LoadManager deadlock detector role,
src/ripple_core/functional/LoadManager.cpp:180-214).

``call_with_deadline`` runs ``fn`` on a daemon thread and waits up to
``timeout_s``. On timeout the thread is abandoned (a wedged tunnel call
may never return; the leaked thread is daemon and holds no locks of
ours) and ``DeviceWedged`` raises. ``DeviceHealth`` records a permanent
verdict so every later device call skips the dead backend instantly —
one wedge disables the device plane for the life of the process; a
restart (or the ``--sustain`` supervisor) is the recovery path, matching
how operators handle a sick accelerator in practice.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable

log = logging.getLogger("stellard.device")


class DeviceWedged(RuntimeError):
    """A device call exceeded its deadline (wedged tunnel / driver)."""


def resolve_timeouts(
    first: float | None, warm: float | None
) -> tuple[float, float]:
    """Shared env-backed deadline resolution for every device plane:
    (first-call/compile deadline, warmed-call deadline) in seconds."""
    import os

    if first is None:
        first = float(os.environ.get("STELLARD_DEVICE_FIRST_TIMEOUT_S", "900"))
    if warm is None:
        warm = float(os.environ.get("STELLARD_DEVICE_WARM_TIMEOUT_S", "60"))
    return first, warm


class DeviceHealth:
    """Process-wide device liveness verdict (sticky once dead)."""

    def __init__(self) -> None:
        self._dead = threading.Event()
        self.reason = ""

    @property
    def dead(self) -> bool:
        return self._dead.is_set()

    def mark_dead(self, reason: str) -> None:
        if not self._dead.is_set():
            self.reason = reason
            self._dead.set()
            log.error("device plane marked DEAD: %s — all device work "
                      "now routes to CPU backends for the life of this "
                      "process", reason)

    def reset(self) -> None:
        """Test seam."""
        self._dead = threading.Event()
        self.reason = ""


# one verdict per process: a wedged tunnel wedges every device plane
HEALTH = DeviceHealth()


def call_with_deadline(
    fn: Callable[[], Any],
    timeout_s: float,
    *,
    label: str = "device",
    health: DeviceHealth = HEALTH,
) -> Any:
    """Run ``fn()`` under ``timeout_s``; raise DeviceWedged on overrun.

    A timeout marks ``health`` dead (sticky). Exceptions from ``fn``
    propagate unchanged.
    """
    if health.dead:
        raise DeviceWedged(health.reason)
    box: dict[str, Any] = {}
    done = threading.Event()

    def run() -> None:
        try:
            box["r"] = fn()
        except BaseException as exc:  # noqa: BLE001 — relayed to caller
            box["e"] = exc
        finally:
            done.set()

    t = threading.Thread(target=run, daemon=True, name=f"{label}-call")
    t.start()
    if not done.wait(timeout_s):
        health.mark_dead(
            f"{label} call exceeded {timeout_s:.0f}s (wedged tunnel?)"
        )
        raise DeviceWedged(health.reason)
    if "e" in box:
        raise box["e"]
    return box["r"]
