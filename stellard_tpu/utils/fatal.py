"""Fatal-error reporting (reference: FatalErrorReporter + the
terminateHandler wiring at Application.cpp:645-653): uncaught exceptions
and hard faults are logged with full context before the process dies,
instead of vanishing into a bare traceback on a detached stderr."""

from __future__ import annotations

import faulthandler
import logging
import sys

_log = logging.getLogger("stellard.fatal")
_installed = False


def install() -> None:
    """Idempotently install the fault/exception reporters."""
    global _installed
    if _installed:
        return
    _installed = True
    # native-level faults (SIGSEGV/SIGABRT/...) dump all thread stacks
    try:
        faulthandler.enable()
    except (RuntimeError, AttributeError):  # no usable stderr (daemonized)
        pass
    previous = sys.excepthook

    def report(exc_type, exc, tb):
        _log.critical("FATAL: uncaught %s: %s", exc_type.__name__, exc,
                      exc_info=(exc_type, exc, tb))
        previous(exc_type, exc, tb)

    sys.excepthook = report

    # background threads bypass sys.excepthook — and that's where nearly
    # all of the node's runtime work happens (job workers, overlay
    # readers, watchdog)
    import threading

    prev_thread = threading.excepthook

    def thread_report(args):
        _log.critical(
            "FATAL in thread %s: uncaught %s: %s",
            args.thread.name if args.thread else "?",
            args.exc_type.__name__,
            args.exc_value,
            exc_info=(args.exc_type, args.exc_value, args.exc_traceback),
        )
        prev_thread(args)

    threading.excepthook = thread_report
