"""Protocol hash primitives.

The reference derives every identity and tree hash from two constructions
(reference: src/ripple_data/protocol/Serializer.cpp:342-390,
src/ripple/sslutil/api/HashUtilities.h:32-54):

- **SHA-512-half**: the first 256 bits of SHA-512 over the payload, with an
  optional 4-byte big-endian domain-separation prefix
  (src/ripple_data/protocol/HashPrefix.cpp:25-32).
- **Hash160**: RIPEMD160(SHA256(payload)) — account IDs from public keys.
"""

from __future__ import annotations

import hashlib

__all__ = [
    "sha512_half",
    "prefix_hash",
    "hash160",
    "sha256d_checksum",
    "HP_TXN_ID",
    "HP_TX_NODE",
    "HP_LEAF_NODE",
    "HP_INNER_NODE",
    "HP_LEDGER_MASTER",
    "HP_TX_SIGN",
    "HP_VALIDATION",
    "HP_PROPOSAL",
]


def _hp(a: str, b: str, c: str) -> int:
    """4-byte hash prefix: three ASCII chars then a zero byte
    (reference: src/ripple_data/protocol/HashPrefix.h:48-55)."""
    return (ord(a) << 24) | (ord(b) << 16) | (ord(c) << 8)


# Domain-separation prefixes (reference: HashPrefix.cpp:25-32). Protocol
# constants — these exact values are part of the wire/hash format.
HP_TXN_ID = _hp("T", "X", "N")  # transaction plus signature -> txn ID
HP_TX_NODE = _hp("S", "N", "D")  # tx-tree leaf (tx plus metadata)
HP_LEAF_NODE = _hp("M", "L", "N")  # state-tree leaf
HP_INNER_NODE = _hp("M", "I", "N")  # inner tree node
HP_LEDGER_MASTER = _hp("L", "W", "R")  # ledger header
HP_TX_SIGN = _hp("S", "T", "X")  # transaction signing
HP_VALIDATION = _hp("V", "A", "L")  # validation signing
HP_PROPOSAL = _hp("P", "R", "P")  # proposal signing


def sha512_half(data: bytes) -> bytes:
    """First 32 bytes of SHA-512 (reference: Serializer.cpp:356-365)."""
    return hashlib.sha512(data).digest()[:32]


def prefix_hash(prefix: int, data: bytes) -> bytes:
    """SHA-512-half of (4-byte BE prefix || data)
    (reference: Serializer.cpp:380-390, getPrefixHash)."""
    return hashlib.sha512(prefix.to_bytes(4, "big") + data).digest()[:32]


def hash160(data: bytes) -> bytes:
    """RIPEMD160(SHA256(data)) — 20-byte account ID from a public key
    (reference: sslutil HashUtilities Hash160; StellarPublicKey.cpp:37-40)."""
    inner = hashlib.sha256(data).digest()
    try:
        h = hashlib.new("ripemd160")
        h.update(inner)
        return h.digest()
    except ValueError:  # pragma: no cover - openssl without ripemd160
        from .ripemd160 import ripemd160 as _rmd

        return _rmd(inner)


def sha256d_checksum(data: bytes) -> bytes:
    """First 4 bytes of SHA256(SHA256(data)) — Base58Check checksum
    (reference: src/ripple/types/impl/Base58.cpp encodeWithCheck)."""
    return hashlib.sha256(hashlib.sha256(data).digest()).digest()[:4]
