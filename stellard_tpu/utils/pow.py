"""Proof-of-work anti-DoS challenges.

Role parity with the reference's ProofOfWork plane
(/root/reference/src/ripple_app/misc/ProofOfWork.{h,cpp}:27-120,
ProofOfWorkFactory.cpp): a server hands an untrusted client a
(challenge, iterations, target) tuple; the client searches for a
32-byte solution whose iterated SHA-512-half chain folds to a digest
<= target; verification replays the chain once. The factory binds
challenges to an expiring token so solutions can't be stockpiled.

The chain construction matches the reference exactly (it is a wire-level
behavior): buf2[i] = H(challenge || solution || buf2[i+1]-chain), accept
iff H(buf2[0..n-1]) <= target.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import time
from dataclasses import dataclass
from typing import Optional

from .hashes import sha512_half

__all__ = ["ProofOfWork", "PowFactory", "MAX_ITERATIONS", "MIN_TARGET"]

MAX_ITERATIONS = 256
# easiest permissible target (reference sMinTarget): 2^224-ish ceiling
MIN_TARGET = int.from_bytes(
    bytes.fromhex(
        "00000000FFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFF"
    ),
    "big",
)

# difficulty ladder: (iterations, leading zero bits of target)
_DIFFICULTY = [
    (16, 8),
    (32, 10),
    (64, 12),
    (128, 14),
    (256, 16),
]


def _target_bytes(zero_bits: int) -> bytes:
    t = (1 << (256 - zero_bits)) - 1
    return t.to_bytes(32, "big")


@dataclass(frozen=True)
class ProofOfWork:
    token: str
    iterations: int
    challenge: bytes  # 32 bytes
    target: bytes  # 32 bytes big-endian bound

    def _final_digest(self, solution: bytes) -> bytes:
        link = b"\x00" * 32
        chain: list[bytes] = [b""] * self.iterations
        for i in range(self.iterations - 1, -1, -1):
            link = sha512_half(self.challenge + solution + link)
            chain[i] = link
        return sha512_half(b"".join(chain))

    def check_solution(self, solution: bytes) -> bool:
        if self.iterations > MAX_ITERATIONS or len(solution) != 32:
            return False
        return self._final_digest(solution) <= self.target

    def solve(self, max_attempts: int = 1 << 22) -> Optional[bytes]:
        """Search candidate solutions (reference ProofOfWork::solve walks
        a deterministic candidate sequence; any 32-byte preimage works)."""
        seed = sha512_half(os.urandom(32) + self.challenge)
        for n in range(max_attempts):
            candidate = sha512_half(seed + n.to_bytes(8, "big"))
            if self._final_digest(candidate) <= self.target:
                return candidate
        return None

    @property
    def difficulty(self) -> int:
        """Approximate expected hash count (reference getDifficulty)."""
        t = int.from_bytes(self.target, "big")
        return self.iterations * ((1 << 256) // (t + 1))


class PowFactory:
    """Issues and verifies bound challenges (ProofOfWorkFactory role)."""

    def __init__(self, validity_s: int = 300, difficulty: int = 0):
        self.secret = os.urandom(32)
        self.validity_s = validity_s
        self.difficulty = max(0, min(difficulty, len(_DIFFICULTY) - 1))
        # bucket -> accepted solutions; buckets past expiry are dropped,
        # so replay memory stays bounded by two validity windows
        self._used: dict[int, set[bytes]] = {}

    def _token(self, challenge: bytes, bucket: int) -> str:
        mac = hmac.new(
            self.secret, challenge + bucket.to_bytes(8, "big"), hashlib.sha256
        )
        return f"{bucket}-{mac.hexdigest()[:32]}"

    def get_proof(self, now: Optional[float] = None) -> ProofOfWork:
        bucket = int((now if now is not None else time.time()) // self.validity_s)
        challenge = os.urandom(32)
        iterations, bits = _DIFFICULTY[self.difficulty]
        return ProofOfWork(
            self._token(challenge, bucket),
            iterations,
            challenge,
            _target_bytes(bits),
        )

    def check_proof(
        self, token: str, challenge: bytes, solution: bytes,
        now: Optional[float] = None,
    ) -> tuple[bool, str]:
        """-> (ok, reason). Tokens expire after ~validity and are
        single-use (reference: powCORRUPT / powEXPIRED / powREUSED)."""
        t = now if now is not None else time.time()
        bucket_now = int(t // self.validity_s)
        try:
            bucket = int(token.split("-", 1)[0])
        except (ValueError, IndexError):
            return False, "invalid token"
        if token != self._token(challenge, bucket):
            return False, "invalid token"
        if bucket_now - bucket > 1:
            return False, "expired"
        for stale in [b for b in self._used if bucket_now - b > 1]:
            del self._used[stale]
        if any(solution in s for s in self._used.values()):
            return False, "reused"
        iterations, bits = _DIFFICULTY[self.difficulty]
        pow_ = ProofOfWork(token, iterations, challenge, _target_bytes(bits))
        if not pow_.check_solution(solution):
            return False, "incorrect"
        self._used.setdefault(bucket, set()).add(solution)
        return True, "ok"
