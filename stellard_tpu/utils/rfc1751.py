"""RFC 1751 human-readable 128-bit keys (reference:
src/ripple_data/crypto/RFC1751.cpp).

The reference's LIVE use is `getWordFromBlob` — `server_info`'s
`hostid` is one dictionary word chosen from the node's address
(NetworkOPs.cpp:1696). The key<->English conversions
(getKeyFromEnglish/getEnglishFromKey) have no remaining call sites
there (vestigial API) but are implemented for parity: 16-byte key ->
12 words, 11 bits/word from a 66-bit stream per 8-byte half (64 data
bits + 2 parity bits, parity = sum of 2-bit groups mod 4).

The 2048-word dictionary is the published RFC 1751 appendix constant
(unavoidable-similarity class: a standard table, like SHA round
constants). Deliberate divergences, both node-local and cosmetic:
- `word_from_blob` hashes with SHA-256 (first 4 bytes, big-endian)
  instead of beast::Murmur — a different hostid naming seed, never on
  the wire and never compared across implementations.
- input normalization (lowercase->upper, 1->L, 0->O, 5->S) actually
  APPLIES here; the reference's `standard()` mutates a by-value loop
  variable, so its normalization is a no-op bug we do not reproduce.
"""

from __future__ import annotations

import bisect
import hashlib

__all__ = ["key_to_english", "english_to_key", "word_from_blob"]

WORDS = (
    "A", "ABE", "ACE", "ACT", "AD", "ADA", "ADD", "AGO", "AID", "AIM",
    "AIR", "ALL", "ALP", "AM", "AMY", "AN", "ANA", "AND", "ANN", "ANT",
    "ANY", "APE", "APS", "APT", "ARC", "ARE", "ARK", "ARM", "ART", "AS",
    "ASH", "ASK", "AT", "ATE", "AUG", "AUK", "AVE", "AWE", "AWK", "AWL",
    "AWN", "AX", "AYE", "BAD", "BAG", "BAH", "BAM", "BAN", "BAR", "BAT",
    "BAY", "BE", "BED", "BEE", "BEG", "BEN", "BET", "BEY", "BIB", "BID",
    "BIG", "BIN", "BIT", "BOB", "BOG", "BON", "BOO", "BOP", "BOW", "BOY",
    "BUB", "BUD", "BUG", "BUM", "BUN", "BUS", "BUT", "BUY", "BY", "BYE",
    "CAB", "CAL", "CAM", "CAN", "CAP", "CAR", "CAT", "CAW", "COD", "COG",
    "COL", "CON", "COO", "COP", "COT", "COW", "COY", "CRY", "CUB", "CUE",
    "CUP", "CUR", "CUT", "DAB", "DAD", "DAM", "DAN", "DAR", "DAY", "DEE",
    "DEL", "DEN", "DES", "DEW", "DID", "DIE", "DIG", "DIN", "DIP", "DO",
    "DOE", "DOG", "DON", "DOT", "DOW", "DRY", "DUB", "DUD", "DUE", "DUG",
    "DUN", "EAR", "EAT", "ED", "EEL", "EGG", "EGO", "ELI", "ELK", "ELM",
    "ELY", "EM", "END", "EST", "ETC", "EVA", "EVE", "EWE", "EYE", "FAD",
    "FAN", "FAR", "FAT", "FAY", "FED", "FEE", "FEW", "FIB", "FIG", "FIN",
    "FIR", "FIT", "FLO", "FLY", "FOE", "FOG", "FOR", "FRY", "FUM", "FUN",
    "FUR", "GAB", "GAD", "GAG", "GAL", "GAM", "GAP", "GAS", "GAY", "GEE",
    "GEL", "GEM", "GET", "GIG", "GIL", "GIN", "GO", "GOT", "GUM", "GUN",
    "GUS", "GUT", "GUY", "GYM", "GYP", "HA", "HAD", "HAL", "HAM", "HAN",
    "HAP", "HAS", "HAT", "HAW", "HAY", "HE", "HEM", "HEN", "HER", "HEW",
    "HEY", "HI", "HID", "HIM", "HIP", "HIS", "HIT", "HO", "HOB", "HOC",
    "HOE", "HOG", "HOP", "HOT", "HOW", "HUB", "HUE", "HUG", "HUH", "HUM",
    "HUT", "I", "ICY", "IDA", "IF", "IKE", "ILL", "INK", "INN", "IO", "ION",
    "IQ", "IRA", "IRE", "IRK", "IS", "IT", "ITS", "IVY", "JAB", "JAG",
    "JAM", "JAN", "JAR", "JAW", "JAY", "JET", "JIG", "JIM", "JO", "JOB",
    "JOE", "JOG", "JOT", "JOY", "JUG", "JUT", "KAY", "KEG", "KEN", "KEY",
    "KID", "KIM", "KIN", "KIT", "LA", "LAB", "LAC", "LAD", "LAG", "LAM",
    "LAP", "LAW", "LAY", "LEA", "LED", "LEE", "LEG", "LEN", "LEO", "LET",
    "LEW", "LID", "LIE", "LIN", "LIP", "LIT", "LO", "LOB", "LOG", "LOP",
    "LOS", "LOT", "LOU", "LOW", "LOY", "LUG", "LYE", "MA", "MAC", "MAD",
    "MAE", "MAN", "MAO", "MAP", "MAT", "MAW", "MAY", "ME", "MEG", "MEL",
    "MEN", "MET", "MEW", "MID", "MIN", "MIT", "MOB", "MOD", "MOE", "MOO",
    "MOP", "MOS", "MOT", "MOW", "MUD", "MUG", "MUM", "MY", "NAB", "NAG",
    "NAN", "NAP", "NAT", "NAY", "NE", "NED", "NEE", "NET", "NEW", "NIB",
    "NIL", "NIP", "NIT", "NO", "NOB", "NOD", "NON", "NOR", "NOT", "NOV",
    "NOW", "NU", "NUN", "NUT", "O", "OAF", "OAK", "OAR", "OAT", "ODD",
    "ODE", "OF", "OFF", "OFT", "OH", "OIL", "OK", "OLD", "ON", "ONE", "OR",
    "ORB", "ORE", "ORR", "OS", "OTT", "OUR", "OUT", "OVA", "OW", "OWE",
    "OWL", "OWN", "OX", "PA", "PAD", "PAL", "PAM", "PAN", "PAP", "PAR",
    "PAT", "PAW", "PAY", "PEA", "PEG", "PEN", "PEP", "PER", "PET", "PEW",
    "PHI", "PI", "PIE", "PIN", "PIT", "PLY", "PO", "POD", "POE", "POP",
    "POT", "POW", "PRO", "PRY", "PUB", "PUG", "PUN", "PUP", "PUT", "QUO",
    "RAG", "RAM", "RAN", "RAP", "RAT", "RAW", "RAY", "REB", "RED", "REP",
    "RET", "RIB", "RID", "RIG", "RIM", "RIO", "RIP", "ROB", "ROD", "ROE",
    "RON", "ROT", "ROW", "ROY", "RUB", "RUE", "RUG", "RUM", "RUN", "RYE",
    "SAC", "SAD", "SAG", "SAL", "SAM", "SAN", "SAP", "SAT", "SAW", "SAY",
    "SEA", "SEC", "SEE", "SEN", "SET", "SEW", "SHE", "SHY", "SIN", "SIP",
    "SIR", "SIS", "SIT", "SKI", "SKY", "SLY", "SO", "SOB", "SOD", "SON",
    "SOP", "SOW", "SOY", "SPA", "SPY", "SUB", "SUD", "SUE", "SUM", "SUN",
    "SUP", "TAB", "TAD", "TAG", "TAN", "TAP", "TAR", "TEA", "TED", "TEE",
    "TEN", "THE", "THY", "TIC", "TIE", "TIM", "TIN", "TIP", "TO", "TOE",
    "TOG", "TOM", "TON", "TOO", "TOP", "TOW", "TOY", "TRY", "TUB", "TUG",
    "TUM", "TUN", "TWO", "UN", "UP", "US", "USE", "VAN", "VAT", "VET",
    "VIE", "WAD", "WAG", "WAR", "WAS", "WAY", "WE", "WEB", "WED", "WEE",
    "WET", "WHO", "WHY", "WIN", "WIT", "WOK", "WON", "WOO", "WOW", "WRY",
    "WU", "YAM", "YAP", "YAW", "YE", "YEA", "YES", "YET", "YOU", "ABED",
    "ABEL", "ABET", "ABLE", "ABUT", "ACHE", "ACID", "ACME", "ACRE", "ACTA",
    "ACTS", "ADAM", "ADDS", "ADEN", "AFAR", "AFRO", "AGEE", "AHEM", "AHOY",
    "AIDA", "AIDE", "AIDS", "AIRY", "AJAR", "AKIN", "ALAN", "ALEC", "ALGA",
    "ALIA", "ALLY", "ALMA", "ALOE", "ALSO", "ALTO", "ALUM", "ALVA", "AMEN",
    "AMES", "AMID", "AMMO", "AMOK", "AMOS", "AMRA", "ANDY", "ANEW", "ANNA",
    "ANNE", "ANTE", "ANTI", "AQUA", "ARAB", "ARCH", "AREA", "ARGO", "ARID",
    "ARMY", "ARTS", "ARTY", "ASIA", "ASKS", "ATOM", "AUNT", "AURA", "AUTO",
    "AVER", "AVID", "AVIS", "AVON", "AVOW", "AWAY", "AWRY", "BABE", "BABY",
    "BACH", "BACK", "BADE", "BAIL", "BAIT", "BAKE", "BALD", "BALE", "BALI",
    "BALK", "BALL", "BALM", "BAND", "BANE", "BANG", "BANK", "BARB", "BARD",
    "BARE", "BARK", "BARN", "BARR", "BASE", "BASH", "BASK", "BASS", "BATE",
    "BATH", "BAWD", "BAWL", "BEAD", "BEAK", "BEAM", "BEAN", "BEAR", "BEAT",
    "BEAU", "BECK", "BEEF", "BEEN", "BEER", "BEET", "BELA", "BELL", "BELT",
    "BEND", "BENT", "BERG", "BERN", "BERT", "BESS", "BEST", "BETA", "BETH",
    "BHOY", "BIAS", "BIDE", "BIEN", "BILE", "BILK", "BILL", "BIND", "BING",
    "BIRD", "BITE", "BITS", "BLAB", "BLAT", "BLED", "BLEW", "BLOB", "BLOC",
    "BLOT", "BLOW", "BLUE", "BLUM", "BLUR", "BOAR", "BOAT", "BOCA", "BOCK",
    "BODE", "BODY", "BOGY", "BOHR", "BOIL", "BOLD", "BOLO", "BOLT", "BOMB",
    "BONA", "BOND", "BONE", "BONG", "BONN", "BONY", "BOOK", "BOOM", "BOON",
    "BOOT", "BORE", "BORG", "BORN", "BOSE", "BOSS", "BOTH", "BOUT", "BOWL",
    "BOYD", "BRAD", "BRAE", "BRAG", "BRAN", "BRAY", "BRED", "BREW", "BRIG",
    "BRIM", "BROW", "BUCK", "BUDD", "BUFF", "BULB", "BULK", "BULL", "BUNK",
    "BUNT", "BUOY", "BURG", "BURL", "BURN", "BURR", "BURT", "BURY", "BUSH",
    "BUSS", "BUST", "BUSY", "BYTE", "CADY", "CAFE", "CAGE", "CAIN", "CAKE",
    "CALF", "CALL", "CALM", "CAME", "CANE", "CANT", "CARD", "CARE", "CARL",
    "CARR", "CART", "CASE", "CASH", "CASK", "CAST", "CAVE", "CEIL", "CELL",
    "CENT", "CERN", "CHAD", "CHAR", "CHAT", "CHAW", "CHEF", "CHEN", "CHEW",
    "CHIC", "CHIN", "CHOU", "CHOW", "CHUB", "CHUG", "CHUM", "CITE", "CITY",
    "CLAD", "CLAM", "CLAN", "CLAW", "CLAY", "CLOD", "CLOG", "CLOT", "CLUB",
    "CLUE", "COAL", "COAT", "COCA", "COCK", "COCO", "CODA", "CODE", "CODY",
    "COED", "COIL", "COIN", "COKE", "COLA", "COLD", "COLT", "COMA", "COMB",
    "COME", "COOK", "COOL", "COON", "COOT", "CORD", "CORE", "CORK", "CORN",
    "COST", "COVE", "COWL", "CRAB", "CRAG", "CRAM", "CRAY", "CREW", "CRIB",
    "CROW", "CRUD", "CUBA", "CUBE", "CUFF", "CULL", "CULT", "CUNY", "CURB",
    "CURD", "CURE", "CURL", "CURT", "CUTS", "DADE", "DALE", "DAME", "DANA",
    "DANE", "DANG", "DANK", "DARE", "DARK", "DARN", "DART", "DASH", "DATA",
    "DATE", "DAVE", "DAVY", "DAWN", "DAYS", "DEAD", "DEAF", "DEAL", "DEAN",
    "DEAR", "DEBT", "DECK", "DEED", "DEEM", "DEER", "DEFT", "DEFY", "DELL",
    "DENT", "DENY", "DESK", "DIAL", "DICE", "DIED", "DIET", "DIME", "DINE",
    "DING", "DINT", "DIRE", "DIRT", "DISC", "DISH", "DISK", "DIVE", "DOCK",
    "DOES", "DOLE", "DOLL", "DOLT", "DOME", "DONE", "DOOM", "DOOR", "DORA",
    "DOSE", "DOTE", "DOUG", "DOUR", "DOVE", "DOWN", "DRAB", "DRAG", "DRAM",
    "DRAW", "DREW", "DRUB", "DRUG", "DRUM", "DUAL", "DUCK", "DUCT", "DUEL",
    "DUET", "DUKE", "DULL", "DUMB", "DUNE", "DUNK", "DUSK", "DUST", "DUTY",
    "EACH", "EARL", "EARN", "EASE", "EAST", "EASY", "EBEN", "ECHO", "EDDY",
    "EDEN", "EDGE", "EDGY", "EDIT", "EDNA", "EGAN", "ELAN", "ELBA", "ELLA",
    "ELSE", "EMIL", "EMIT", "EMMA", "ENDS", "ERIC", "EROS", "EVEN", "EVER",
    "EVIL", "EYED", "FACE", "FACT", "FADE", "FAIL", "FAIN", "FAIR", "FAKE",
    "FALL", "FAME", "FANG", "FARM", "FAST", "FATE", "FAWN", "FEAR", "FEAT",
    "FEED", "FEEL", "FEET", "FELL", "FELT", "FEND", "FERN", "FEST", "FEUD",
    "FIEF", "FIGS", "FILE", "FILL", "FILM", "FIND", "FINE", "FINK", "FIRE",
    "FIRM", "FISH", "FISK", "FIST", "FITS", "FIVE", "FLAG", "FLAK", "FLAM",
    "FLAT", "FLAW", "FLEA", "FLED", "FLEW", "FLIT", "FLOC", "FLOG", "FLOW",
    "FLUB", "FLUE", "FOAL", "FOAM", "FOGY", "FOIL", "FOLD", "FOLK", "FOND",
    "FONT", "FOOD", "FOOL", "FOOT", "FORD", "FORE", "FORK", "FORM", "FORT",
    "FOSS", "FOUL", "FOUR", "FOWL", "FRAU", "FRAY", "FRED", "FREE", "FRET",
    "FREY", "FROG", "FROM", "FUEL", "FULL", "FUME", "FUND", "FUNK", "FURY",
    "FUSE", "FUSS", "GAFF", "GAGE", "GAIL", "GAIN", "GAIT", "GALA", "GALE",
    "GALL", "GALT", "GAME", "GANG", "GARB", "GARY", "GASH", "GATE", "GAUL",
    "GAUR", "GAVE", "GAWK", "GEAR", "GELD", "GENE", "GENT", "GERM", "GETS",
    "GIBE", "GIFT", "GILD", "GILL", "GILT", "GINA", "GIRD", "GIRL", "GIST",
    "GIVE", "GLAD", "GLEE", "GLEN", "GLIB", "GLOB", "GLOM", "GLOW", "GLUE",
    "GLUM", "GLUT", "GOAD", "GOAL", "GOAT", "GOER", "GOES", "GOLD", "GOLF",
    "GONE", "GONG", "GOOD", "GOOF", "GORE", "GORY", "GOSH", "GOUT", "GOWN",
    "GRAB", "GRAD", "GRAY", "GREG", "GREW", "GREY", "GRID", "GRIM", "GRIN",
    "GRIT", "GROW", "GRUB", "GULF", "GULL", "GUNK", "GURU", "GUSH", "GUST",
    "GWEN", "GWYN", "HAAG", "HAAS", "HACK", "HAIL", "HAIR", "HALE", "HALF",
    "HALL", "HALO", "HALT", "HAND", "HANG", "HANK", "HANS", "HARD", "HARK",
    "HARM", "HART", "HASH", "HAST", "HATE", "HATH", "HAUL", "HAVE", "HAWK",
    "HAYS", "HEAD", "HEAL", "HEAR", "HEAT", "HEBE", "HECK", "HEED", "HEEL",
    "HEFT", "HELD", "HELL", "HELM", "HERB", "HERD", "HERE", "HERO", "HERS",
    "HESS", "HEWN", "HICK", "HIDE", "HIGH", "HIKE", "HILL", "HILT", "HIND",
    "HINT", "HIRE", "HISS", "HIVE", "HOBO", "HOCK", "HOFF", "HOLD", "HOLE",
    "HOLM", "HOLT", "HOME", "HONE", "HONK", "HOOD", "HOOF", "HOOK", "HOOT",
    "HORN", "HOSE", "HOST", "HOUR", "HOVE", "HOWE", "HOWL", "HOYT", "HUCK",
    "HUED", "HUFF", "HUGE", "HUGH", "HUGO", "HULK", "HULL", "HUNK", "HUNT",
    "HURD", "HURL", "HURT", "HUSH", "HYDE", "HYMN", "IBIS", "ICON", "IDEA",
    "IDLE", "IFFY", "INCA", "INCH", "INTO", "IONS", "IOTA", "IOWA", "IRIS",
    "IRMA", "IRON", "ISLE", "ITCH", "ITEM", "IVAN", "JACK", "JADE", "JAIL",
    "JAKE", "JANE", "JAVA", "JEAN", "JEFF", "JERK", "JESS", "JEST", "JIBE",
    "JILL", "JILT", "JIVE", "JOAN", "JOBS", "JOCK", "JOEL", "JOEY", "JOHN",
    "JOIN", "JOKE", "JOLT", "JOVE", "JUDD", "JUDE", "JUDO", "JUDY", "JUJU",
    "JUKE", "JULY", "JUNE", "JUNK", "JUNO", "JURY", "JUST", "JUTE", "KAHN",
    "KALE", "KANE", "KANT", "KARL", "KATE", "KEEL", "KEEN", "KENO", "KENT",
    "KERN", "KERR", "KEYS", "KICK", "KILL", "KIND", "KING", "KIRK", "KISS",
    "KITE", "KLAN", "KNEE", "KNEW", "KNIT", "KNOB", "KNOT", "KNOW", "KOCH",
    "KONG", "KUDO", "KURD", "KURT", "KYLE", "LACE", "LACK", "LACY", "LADY",
    "LAID", "LAIN", "LAIR", "LAKE", "LAMB", "LAME", "LAND", "LANE", "LANG",
    "LARD", "LARK", "LASS", "LAST", "LATE", "LAUD", "LAVA", "LAWN", "LAWS",
    "LAYS", "LEAD", "LEAF", "LEAK", "LEAN", "LEAR", "LEEK", "LEER", "LEFT",
    "LEND", "LENS", "LENT", "LEON", "LESK", "LESS", "LEST", "LETS", "LIAR",
    "LICE", "LICK", "LIED", "LIEN", "LIES", "LIEU", "LIFE", "LIFT", "LIKE",
    "LILA", "LILT", "LILY", "LIMA", "LIMB", "LIME", "LIND", "LINE", "LINK",
    "LINT", "LION", "LISA", "LIST", "LIVE", "LOAD", "LOAF", "LOAM", "LOAN",
    "LOCK", "LOFT", "LOGE", "LOIS", "LOLA", "LONE", "LONG", "LOOK", "LOON",
    "LOOT", "LORD", "LORE", "LOSE", "LOSS", "LOST", "LOUD", "LOVE", "LOWE",
    "LUCK", "LUCY", "LUGE", "LUKE", "LULU", "LUND", "LUNG", "LURA", "LURE",
    "LURK", "LUSH", "LUST", "LYLE", "LYNN", "LYON", "LYRA", "MACE", "MADE",
    "MAGI", "MAID", "MAIL", "MAIN", "MAKE", "MALE", "MALI", "MALL", "MALT",
    "MANA", "MANN", "MANY", "MARC", "MARE", "MARK", "MARS", "MART", "MARY",
    "MASH", "MASK", "MASS", "MAST", "MATE", "MATH", "MAUL", "MAYO", "MEAD",
    "MEAL", "MEAN", "MEAT", "MEEK", "MEET", "MELD", "MELT", "MEMO", "MEND",
    "MENU", "MERT", "MESH", "MESS", "MICE", "MIKE", "MILD", "MILE", "MILK",
    "MILL", "MILT", "MIMI", "MIND", "MINE", "MINI", "MINK", "MINT", "MIRE",
    "MISS", "MIST", "MITE", "MITT", "MOAN", "MOAT", "MOCK", "MODE", "MOLD",
    "MOLE", "MOLL", "MOLT", "MONA", "MONK", "MONT", "MOOD", "MOON", "MOOR",
    "MOOT", "MORE", "MORN", "MORT", "MOSS", "MOST", "MOTH", "MOVE", "MUCH",
    "MUCK", "MUDD", "MUFF", "MULE", "MULL", "MURK", "MUSH", "MUST", "MUTE",
    "MUTT", "MYRA", "MYTH", "NAGY", "NAIL", "NAIR", "NAME", "NARY", "NASH",
    "NAVE", "NAVY", "NEAL", "NEAR", "NEAT", "NECK", "NEED", "NEIL", "NELL",
    "NEON", "NERO", "NESS", "NEST", "NEWS", "NEWT", "NIBS", "NICE", "NICK",
    "NILE", "NINA", "NINE", "NOAH", "NODE", "NOEL", "NOLL", "NONE", "NOOK",
    "NOON", "NORM", "NOSE", "NOTE", "NOUN", "NOVA", "NUDE", "NULL", "NUMB",
    "OATH", "OBEY", "OBOE", "ODIN", "OHIO", "OILY", "OINT", "OKAY", "OLAF",
    "OLDY", "OLGA", "OLIN", "OMAN", "OMEN", "OMIT", "ONCE", "ONES", "ONLY",
    "ONTO", "ONUS", "ORAL", "ORGY", "OSLO", "OTIS", "OTTO", "OUCH", "OUST",
    "OUTS", "OVAL", "OVEN", "OVER", "OWLY", "OWNS", "QUAD", "QUIT", "QUOD",
    "RACE", "RACK", "RACY", "RAFT", "RAGE", "RAID", "RAIL", "RAIN", "RAKE",
    "RANK", "RANT", "RARE", "RASH", "RATE", "RAVE", "RAYS", "READ", "REAL",
    "REAM", "REAR", "RECK", "REED", "REEF", "REEK", "REEL", "REID", "REIN",
    "RENA", "REND", "RENT", "REST", "RICE", "RICH", "RICK", "RIDE", "RIFT",
    "RILL", "RIME", "RING", "RINK", "RISE", "RISK", "RITE", "ROAD", "ROAM",
    "ROAR", "ROBE", "ROCK", "RODE", "ROIL", "ROLL", "ROME", "ROOD", "ROOF",
    "ROOK", "ROOM", "ROOT", "ROSA", "ROSE", "ROSS", "ROSY", "ROTH", "ROUT",
    "ROVE", "ROWE", "ROWS", "RUBE", "RUBY", "RUDE", "RUDY", "RUIN", "RULE",
    "RUNG", "RUNS", "RUNT", "RUSE", "RUSH", "RUSK", "RUSS", "RUST", "RUTH",
    "SACK", "SAFE", "SAGE", "SAID", "SAIL", "SALE", "SALK", "SALT", "SAME",
    "SAND", "SANE", "SANG", "SANK", "SARA", "SAUL", "SAVE", "SAYS", "SCAN",
    "SCAR", "SCAT", "SCOT", "SEAL", "SEAM", "SEAR", "SEAT", "SEED", "SEEK",
    "SEEM", "SEEN", "SEES", "SELF", "SELL", "SEND", "SENT", "SETS", "SEWN",
    "SHAG", "SHAM", "SHAW", "SHAY", "SHED", "SHIM", "SHIN", "SHOD", "SHOE",
    "SHOT", "SHOW", "SHUN", "SHUT", "SICK", "SIDE", "SIFT", "SIGH", "SIGN",
    "SILK", "SILL", "SILO", "SILT", "SINE", "SING", "SINK", "SIRE", "SITE",
    "SITS", "SITU", "SKAT", "SKEW", "SKID", "SKIM", "SKIN", "SKIT", "SLAB",
    "SLAM", "SLAT", "SLAY", "SLED", "SLEW", "SLID", "SLIM", "SLIT", "SLOB",
    "SLOG", "SLOT", "SLOW", "SLUG", "SLUM", "SLUR", "SMOG", "SMUG", "SNAG",
    "SNOB", "SNOW", "SNUB", "SNUG", "SOAK", "SOAR", "SOCK", "SODA", "SOFA",
    "SOFT", "SOIL", "SOLD", "SOME", "SONG", "SOON", "SOOT", "SORE", "SORT",
    "SOUL", "SOUR", "SOWN", "STAB", "STAG", "STAN", "STAR", "STAY", "STEM",
    "STEW", "STIR", "STOW", "STUB", "STUN", "SUCH", "SUDS", "SUIT", "SULK",
    "SUMS", "SUNG", "SUNK", "SURE", "SURF", "SWAB", "SWAG", "SWAM", "SWAN",
    "SWAT", "SWAY", "SWIM", "SWUM", "TACK", "TACT", "TAIL", "TAKE", "TALE",
    "TALK", "TALL", "TANK", "TASK", "TATE", "TAUT", "TEAL", "TEAM", "TEAR",
    "TECH", "TEEM", "TEEN", "TEET", "TELL", "TEND", "TENT", "TERM", "TERN",
    "TESS", "TEST", "THAN", "THAT", "THEE", "THEM", "THEN", "THEY", "THIN",
    "THIS", "THUD", "THUG", "TICK", "TIDE", "TIDY", "TIED", "TIER", "TILE",
    "TILL", "TILT", "TIME", "TINA", "TINE", "TINT", "TINY", "TIRE", "TOAD",
    "TOGO", "TOIL", "TOLD", "TOLL", "TONE", "TONG", "TONY", "TOOK", "TOOL",
    "TOOT", "TORE", "TORN", "TOTE", "TOUR", "TOUT", "TOWN", "TRAG", "TRAM",
    "TRAY", "TREE", "TREK", "TRIG", "TRIM", "TRIO", "TROD", "TROT", "TROY",
    "TRUE", "TUBA", "TUBE", "TUCK", "TUFT", "TUNA", "TUNE", "TUNG", "TURF",
    "TURN", "TUSK", "TWIG", "TWIN", "TWIT", "ULAN", "UNIT", "URGE", "USED",
    "USER", "USES", "UTAH", "VAIL", "VAIN", "VALE", "VARY", "VASE", "VAST",
    "VEAL", "VEDA", "VEIL", "VEIN", "VEND", "VENT", "VERB", "VERY", "VETO",
    "VICE", "VIEW", "VINE", "VISE", "VOID", "VOLT", "VOTE", "WACK", "WADE",
    "WAGE", "WAIL", "WAIT", "WAKE", "WALE", "WALK", "WALL", "WALT", "WAND",
    "WANE", "WANG", "WANT", "WARD", "WARM", "WARN", "WART", "WASH", "WAST",
    "WATS", "WATT", "WAVE", "WAVY", "WAYS", "WEAK", "WEAL", "WEAN", "WEAR",
    "WEED", "WEEK", "WEIR", "WELD", "WELL", "WELT", "WENT", "WERE", "WERT",
    "WEST", "WHAM", "WHAT", "WHEE", "WHEN", "WHET", "WHOA", "WHOM", "WICK",
    "WIFE", "WILD", "WILL", "WIND", "WINE", "WING", "WINK", "WINO", "WIRE",
    "WISE", "WISH", "WITH", "WOLF", "WONT", "WOOD", "WOOL", "WORD", "WORE",
    "WORK", "WORM", "WORN", "WOVE", "WRIT", "WYNN", "YALE", "YANG", "YANK",
    "YARD", "YARN", "YAWL", "YAWN", "YEAH", "YEAR", "YELL", "YOGA", "YOKE",
)

_SHORT_MAX = 571  # words[0:571] are 1-3 chars; words[571:] are 4 chars


def _extract(buf: bytearray, start: int, length: int) -> int:
    """`length` bits (<= 11) starting at bit `start`, MSB-first
    (reference: RFC1751::extract)."""
    cl = buf[start // 8]
    cc = buf[start // 8 + 1] if start // 8 + 1 < len(buf) else 0
    cr = buf[start // 8 + 2] if start // 8 + 2 < len(buf) else 0
    x = (cl << 16) | (cc << 8) | cr
    x >>= 24 - (length + (start % 8))
    return x & (0xFFFF >> (16 - length))


def _insert(buf: bytearray, x: int, start: int, length: int) -> None:
    """OR `length` bits of x into buf at bit `start` (reference:
    RFC1751::insert)."""
    shift = (8 - ((start + length) % 8)) % 8
    y = x << shift
    i = start // 8
    if shift + length > 16:
        buf[i] |= (y >> 16) & 0xFF
        buf[i + 1] |= (y >> 8) & 0xFF
        buf[i + 2] |= y & 0xFF
    elif shift + length > 8:
        buf[i] |= (y >> 8) & 0xFF
        buf[i + 1] |= y & 0xFF
    else:
        buf[i] |= y & 0xFF


def _btoe(data8: bytes) -> list[str]:
    """8 bytes -> 6 words (64 data bits + 2 parity bits)."""
    buf = bytearray(data8) + bytearray(1)
    p = sum(_extract(buf, i, 2) for i in range(0, 64, 2))
    buf[8] = (p & 3) << 6
    return [WORDS[_extract(buf, i * 11, 11)] for i in range(6)]


def _standard(word: str) -> str:
    return (word.upper().replace("1", "L").replace("0", "O")
            .replace("5", "S"))


def _etob(words6: list[str]) -> bytes:
    """6 words -> 8 bytes; ValueError on malformed/unknown/parity."""
    if len(words6) != 6:
        raise ValueError("malformed: need 6 words per half")
    buf = bytearray(9)
    pos = 0
    for w in words6:
        if not 1 <= len(w) <= 4:
            raise ValueError(f"malformed word {w!r}")
        w = _standard(w)
        lo, hi = (0, _SHORT_MAX) if len(w) < 4 else (_SHORT_MAX, 2048)
        # binary search within the length-partitioned dictionary range
        i = bisect.bisect_left(WORDS, w, lo, hi)
        if i >= hi or WORDS[i] != w:
            raise ValueError(f"unknown word {w!r}")
        _insert(buf, i, pos, 11)
        pos += 11
    p = sum(_extract(buf, i, 2) for i in range(0, 64, 2))
    if (p & 3) != _extract(buf, 64, 2):
        raise ValueError("parity check failed")
    return bytes(buf[:8])


def key_to_english(key: bytes) -> str:
    """16-byte key -> 12 space-separated dictionary words
    (reference: getEnglishFromKey)."""
    if len(key) != 16:
        raise ValueError("key must be 16 bytes")
    return " ".join(_btoe(key[:8]) + _btoe(key[8:]))


def english_to_key(text: str) -> bytes:
    """12 words -> 16-byte key (reference: getKeyFromEnglish);
    ValueError on malformed input, unknown words, or bad parity."""
    words = text.split()
    if len(words) != 12:
        raise ValueError("malformed: need 12 words")
    return _etob(words[:6]) + _etob(words[6:])


def word_from_blob(data: bytes) -> str:
    """One deterministic dictionary word for a blob — the `hostid` role
    (reference: getWordFromBlob; hash choice diverges, see header)."""
    h = hashlib.sha256(data).digest()
    return WORDS[int.from_bytes(h[:4], "big") % len(WORDS)]
