"""TaggedCache / KeyCache: expiring keyed caches with sweep.

Role parity with /root/reference/src/ripple/common/TaggedCache.h and
KeyCache.h (tuned at Application.cpp:723-727, swept on the sweep timer):
bounded, aged caches in front of the NodeStore and ledger history so hot
fetch paths stop re-walking storage. The reference splits "cached with
value" (TaggedCache) from "presence only" (KeyCache); both shapes live
here.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Generic, Hashable, Optional, TypeVar

__all__ = ["TaggedCache", "KeyCache"]

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")


class TaggedCache(Generic[K, V]):
    """LRU + age-bounded value cache (TaggedCache.h role)."""

    def __init__(
        self,
        name: str,
        target_size: int = 1024,
        expiration_s: float = 120.0,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.name = name
        self.target_size = target_size
        self.expiration_s = expiration_s
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._data: OrderedDict[K, tuple[float, V]] = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key: K) -> Optional[V]:
        with self._lock:
            entry = self._data.get(key)
            if entry is None:
                self.misses += 1
                return None
            at, value = entry
            now = self._clock()
            if now - at > self.expiration_s:
                del self._data[key]
                self.misses += 1
                return None
            # age by LAST ACCESS (reference TaggedCache): continuously
            # used entries never expire
            self._data[key] = (now, value)
            self._data.move_to_end(key)
            self.hits += 1
            return value

    def put(self, key: K, value: V) -> None:
        with self._lock:
            self._data[key] = (self._clock(), value)
            self._data.move_to_end(key)
            while len(self._data) > self.target_size:
                self._data.popitem(last=False)

    def fetch(self, key: K, loader: Callable[[], Optional[V]]) -> Optional[V]:
        """get() or load-and-cache (the canonical fetch path shape)."""
        value = self.get(key)
        if value is not None:
            return value
        value = loader()
        if value is not None:
            self.put(key, value)
        return value

    def sweep(self) -> int:
        """Drop expired entries (reference: doSweep timer). Returns the
        number removed."""
        now = self._clock()
        with self._lock:
            dead = [
                k
                for k, (at, _v) in self._data.items()
                if now - at > self.expiration_s
            ]
            for k in dead:
                del self._data[k]
            return len(dead)

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def get_json(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "size": len(self._data),
                "target": self.target_size,
                "hits": self.hits,
                "misses": self.misses,
            }


class KeyCache(Generic[K]):
    """Presence-only cache (KeyCache.h / FullBelowCache role): remembers
    that a key was seen recently, e.g. 'this subtree is fully present
    below' so sync walks skip it."""

    def __init__(
        self,
        name: str,
        target_size: int = 65536,
        expiration_s: float = 120.0,
        clock: Optional[Callable[[], float]] = None,
    ):
        self._cache: TaggedCache[K, bool] = TaggedCache(
            name, target_size, expiration_s, clock
        )

    def insert(self, key: K) -> None:
        self._cache.put(key, True)

    def __contains__(self, key: K) -> bool:
        return self._cache.get(key) is not None

    def sweep(self) -> int:
        return self._cache.sweep()

    def __len__(self) -> int:
        return len(self._cache)

    def get_json(self) -> dict:
        return self._cache.get_json()
