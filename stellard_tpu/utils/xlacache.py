"""Shared persistent XLA compilation cache setup.

The big kernels (batched Ed25519 verify, tree hashing) take minutes to
compile for the CPU backend and tens of seconds for TPU; one on-disk cache
under the repo root makes every process after the first fast. Used by
tests/conftest.py and bench.py so the knobs can never drift apart.

The cache directory is keyed by a host-CPU-feature fingerprint: XLA:CPU
AOT blobs encode the compiling machine's ISA features, and replaying a
foreign blob can SIGILL an unattended bench (or at best spam the
machine-feature-mismatch warning every replay). A box with different CPU
features simply gets its own subdirectory and recompiles once.
"""

from __future__ import annotations

import hashlib
import os
import platform


def host_cpu_fingerprint() -> str:
    """Short stable digest of the host's CPU feature set (ISA flags +
    machine arch). Two hosts share a cache subdir only when an AOT blob
    compiled on one is guaranteed executable on the other."""
    feats = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    # one flags line suffices; identical across cores
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    if not feats:
        feats = platform.processor() or "unknown"
    key = f"{platform.machine()}|{feats}"
    return hashlib.sha256(key.encode()).hexdigest()[:12]


def enable_compilation_cache(cache_dir: str | None = None) -> str:
    """Point JAX's persistent compilation cache at
    `<repo>/.jax_cache/<cpu-fingerprint>` (or `cache_dir`, used as given).
    Safe to call more than once. Returns the dir."""
    import jax

    if cache_dir is None:
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        cache_dir = os.path.join(
            pkg_root, ".jax_cache", host_cpu_fingerprint()
        )
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    return cache_dir
