"""Shared persistent XLA compilation cache setup.

The big kernels (batched Ed25519 verify, tree hashing) take minutes to
compile for the CPU backend and tens of seconds for TPU; one on-disk cache
under the repo root makes every process after the first fast. Used by
tests/conftest.py and bench.py so the knobs can never drift apart.
"""

from __future__ import annotations

import os


def enable_compilation_cache(cache_dir: str | None = None) -> str:
    """Point JAX's persistent compilation cache at `<repo>/.jax_cache`
    (or `cache_dir`). Safe to call more than once. Returns the dir."""
    import jax

    if cache_dir is None:
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        cache_dir = os.path.join(pkg_root, ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    return cache_dir
