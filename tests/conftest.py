"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-runs the
multi-chip path). The axon TPU plugin is registered by a sitecustomize
hook and pinned via JAX_PLATFORMS=axon in the env, so we must override the
platform through jax.config before any computation runs.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
from stellard_tpu.utils.xlacache import enable_compilation_cache  # noqa: E402

enable_compilation_cache()
