"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-runs the
multi-chip path). The axon TPU plugin is registered by a sitecustomize
hook and pinned via JAX_PLATFORMS=axon in the env, so we must override the
platform through jax.config before any computation runs.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
from stellard_tpu.utils.xlacache import enable_compilation_cache  # noqa: E402

enable_compilation_cache()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _stellard_env_guard():
    """Snapshot/restore STELLARD_* env around every test: node setup
    applies [kernel_tuning] as process-wide env setdefaults, and tests
    force kernel knobs — neither may leak into later tests. (Module-
    import-time sets in test files intentionally persist: the kernel
    modules read them once at import.)"""
    saved = {
        k: v for k, v in os.environ.items() if k.startswith("STELLARD_")
    }
    yield
    for k in [k for k in os.environ if k.startswith("STELLARD_")]:
        if k not in saved:
            del os.environ[k]
    os.environ.update(saved)
