"""Archive tier (ISSUE 20 / ROADMAP item 4): the shard distribution
network and full-history reporting nodes.

Covers the wire-level shard-range manifest rows (legacy byte-identity
pinned), the offline verify/import door (`verify_shard_blob` /
`import_shard` — zero hostile bytes retained), the ShardBackfill
fetcher (peer discipline, condemnation, epoch restarts, self-arming
rescans), the full-history index feed (`feed_shard` into a
never-trimming ArchiveTxDatabase), the forever result-cache tier
(immutable seqs survive epoch swaps; mutable windows never admitted),
the WS-door `resume` cursor (explicit cold answer past the horizon),
account_tx paging across the shard/live boundary under a concurrent
sql_trim, and the archive config gates (dead-config rejection).
"""

from __future__ import annotations

import threading

import jax
import pytest

jax.config.update("jax_platforms", "cpu")

from stellard_tpu.node.archive import (  # noqa: E402
    ArchiveTxDatabase,
    ShardBackfill,
    feed_shard,
)
from stellard_tpu.node.config import Config  # noqa: E402
from stellard_tpu.node.node import Node  # noqa: E402
from stellard_tpu.nodestore.shards import (  # noqa: E402
    SHARD_FILE_BASE,
    SHARD_SEG_BASE,
    HistoryShardStore,
    collect_retired,
    verify_shard_blob,
)
from stellard_tpu.protocol.formats import TxType  # noqa: E402
from stellard_tpu.protocol.keys import KeyPair  # noqa: E402
from stellard_tpu.protocol.sfields import sfAmount, sfDestination  # noqa: E402
from stellard_tpu.protocol.stamount import STAmount  # noqa: E402
from stellard_tpu.protocol.sttx import SerializedTransaction  # noqa: E402
from stellard_tpu.rpc.handlers import Context, Role, dispatch  # noqa: E402


# -- shared fixture: a tx-bearing chain sealed into source shards ---------


def _sealed_chain(tmp_path, n_ledgers=6, splits=((1, 3), (4, 6)),
                  txs_per_ledger=2):
    """A real mini-chain with payments, sealed into a SOURCE shard
    store along `splits` (inclusive seq ranges). Returns a dict with
    the store, header dicts, per-range acct rows and txids."""
    from stellard_tpu.nodestore.core import make_database
    from stellard_tpu.state.ledger import Ledger

    master = KeyPair.from_passphrase("masterpassphrase")
    dest = KeyPair.from_passphrase("archive-dest").account_id
    db = make_database(type="segstore", path=str(tmp_path / "src-ns"),
                       async_writes=False)
    led = Ledger.genesis(master.account_id)
    headers, acct_rows = [], []
    txids_by_seq: dict[int, list[bytes]] = {}
    seq_counter = 0
    for i in range(n_ledgers):
        nxt = led.open_successor() if i else led
        if i:
            for t in range(txs_per_ledger):
                seq_counter += 1
                tx = SerializedTransaction.build(
                    TxType.ttPAYMENT, master.account_id, seq_counter, 10,
                    {sfAmount: STAmount.from_drops(1000),
                     sfDestination: dest},
                )
                tx.sign(master)
                txid = nxt.add_transaction(tx.serialize(), b"\x01\x02")
                acct_rows.append((master.account_id, nxt.seq, t, txid))
                txids_by_seq.setdefault(nxt.seq, []).append(txid)
        nxt.close(close_time=1000 + 30 * i, close_resolution=30)
        nxt.save(db)
        headers.append({
            "hash": nxt.hash(), "seq": nxt.seq,
            "parent_hash": nxt.parent_hash,
            "account_hash": nxt.account_hash,
            "tx_hash": nxt.tx_hash,
        })
        led = nxt

    def fetch(h):
        o = db.fetch(h, populate_cache=False)
        return o.data if o else None

    ss = HistoryShardStore(str(tmp_path / "src-shards"))
    by_seq = {h["seq"]: h for h in headers}
    sids = []
    for lo, hi in splits:
        hs = [by_seq[s] for s in range(lo, hi + 1)]
        recs = collect_retired(fetch, hs, set())
        rows = [r for r in acct_rows if lo <= r[1] <= hi]
        sid = ss.seal(lo, hi, recs, rows,
                      first_hash=by_seq[lo]["hash"],
                      last_hash=by_seq[hi]["hash"])
        sids.append(sid)
    db.close()
    return {
        "ss": ss, "sids": sids, "headers": headers,
        "acct_rows": acct_rows, "txids_by_seq": txids_by_seq,
        "master": master, "dest": dest,
    }


def _file_blob(ss: HistoryShardStore, sid: int) -> bytes:
    """The shard's whole on-disk image via the distribution door."""
    fid = SHARD_FILE_BASE + sid
    out = bytearray()
    meta, chunk = ss.fetch_segment(fid)
    out += chunk
    while len(out) < meta["size"]:
        _m, chunk = ss.fetch_segment(fid, offset=len(out), length=1 << 16)
        out += chunk
    return bytes(out)


# -- wire: shard-range manifest rows --------------------------------------


class TestShardManifestWireRows:
    def _mt(self, W):
        return int(W._ENCODERS[W.SegmentData][0])

    def test_legacy_rows_byte_identical(self):
        """The range fields ride nonzero-only: a legacy 4-tuple row and
        its zero-extended 7-tuple encode to the SAME bytes — old peers
        see an unchanged wire."""
        import stellard_tpu.overlay.wire as W

        legacy = W.SegmentData(seg_id=-1, segments=[(3, 100, 90, True)])
        extended = W.SegmentData(
            seg_id=-1, segments=[(3, 100, 90, True, 0, 0, 0)]
        )
        assert W.encode_message(legacy) == W.encode_message(extended)

    def test_range_rows_roundtrip(self):
        import stellard_tpu.overlay.wire as W

        rows = [
            (0, 10, 10, True),
            (SHARD_SEG_BASE + 1, 4096, 4096, False, 5, 9, 123456),
        ]
        m = W.SegmentData(seg_id=-1, segments=rows)
        out = W.decode_message(self._mt(W), W.encode_message(m))
        assert out.segments[0] == (0, 10, 10, True, 0, 0, 0)
        assert out.segments[1] == rows[1]

    def test_store_advertises_ranges(self, tmp_path):
        env = _sealed_chain(tmp_path)
        rows = env["ss"].segments()
        shard_rows = sorted(
            (r for r in rows if r["id"] >= SHARD_SEG_BASE),
            key=lambda r: r["lo"],
        )
        assert [(r["lo"], r["hi"]) for r in shard_rows] == [(1, 3), (4, 6)]
        for r in shard_rows:
            assert r["file_bytes"] > 0
        env["ss"].close()


# -- verify_shard_blob / import_shard -------------------------------------


class TestVerifyImport:
    def test_verify_ok_and_report(self, tmp_path):
        env = _sealed_chain(tmp_path)
        blob = _file_blob(env["ss"], env["sids"][0])
        rep = verify_shard_blob(blob)
        assert rep["ok"], rep
        assert (rep["lo"], rep["hi"]) == (1, 3)
        assert rep["records"] > 0
        env["ss"].close()

    def test_verify_rejects_corruption_and_truncation(self, tmp_path):
        env = _sealed_chain(tmp_path)
        blob = _file_blob(env["ss"], env["sids"][0])
        env["ss"].close()
        bad = bytearray(blob)
        bad[len(bad) // 2] ^= 0xFF
        assert not verify_shard_blob(bytes(bad))["ok"]
        assert not verify_shard_blob(blob[:-7])["ok"]
        assert not verify_shard_blob(b"")["ok"]
        assert not verify_shard_blob(b"NOTSHARD" + blob[8:])["ok"]

    def test_import_installs_and_serves(self, tmp_path):
        env = _sealed_chain(tmp_path)
        src, master = env["ss"], env["master"]
        dst = HistoryShardStore(str(tmp_path / "dst"))
        for sid in env["sids"]:
            res = dst.import_shard(_file_blob(src, sid))
            assert res["ok"] and not res.get("duplicate"), res
        assert dst.range() == (1, 6)
        assert dst.contiguous_floor() == 6
        # imported shards serve account_tx byte-identically to the src
        want = src.account_tx(master.account_id, 1, 6, limit=100,
                              forward=True)
        got = dst.account_tx(master.account_id, 1, 6, limit=100,
                             forward=True)
        assert [r["txid"] for r in got] == [r["txid"] for r in want]
        # ... and RE-SERVE over the distribution door (an archive is
        # itself a source in the shard network)
        for row in dst.segments():
            if row["id"] >= SHARD_SEG_BASE:
                assert row["lo"] > 0 and row["file_bytes"] > 0
        sid0 = dst.shards()[0]["id"]
        assert verify_shard_blob(_file_blob(dst, sid0))["ok"]
        # reopen: imported shards survive restart
        dst.close()
        dst2 = HistoryShardStore(str(tmp_path / "dst"))
        assert dst2.contiguous_floor() == 6
        src.close()
        dst2.close()

    def test_import_duplicate_and_overlap(self, tmp_path):
        env = _sealed_chain(tmp_path, splits=((1, 3), (2, 5)))
        src = env["ss"]
        dst = HistoryShardStore(str(tmp_path / "dst"))
        assert dst.import_shard(_file_blob(src, env["sids"][0]))["ok"]
        res = dst.import_shard(_file_blob(src, env["sids"][0]))
        assert res["ok"] and res["duplicate"]
        # partial overlap ([2,5] vs held [1,3]) is an inconsistency,
        # not mergeable data: rejected
        res = dst.import_shard(_file_blob(src, env["sids"][1]))
        assert not res["ok"]
        assert len(dst.shards()) == 1
        src.close()
        dst.close()

    def test_import_reject_retains_zero_bytes(self, tmp_path):
        import os

        env = _sealed_chain(tmp_path)
        blob = bytearray(_file_blob(env["ss"], env["sids"][0]))
        env["ss"].close()
        blob[-10] ^= 0x01
        dst = HistoryShardStore(str(tmp_path / "dst"))
        before = sorted(os.listdir(tmp_path / "dst"))
        res = dst.import_shard(bytes(blob))
        assert not res["ok"] and "error" in res
        assert dst.imported == 0 and dst.import_rejects == 1
        assert sorted(os.listdir(tmp_path / "dst")) == before
        assert dst.range() is None
        dst.close()

    def test_contiguous_floor_gap_semantics(self, tmp_path):
        env = _sealed_chain(
            tmp_path, n_ledgers=7, splits=((1, 2), (3, 4), (6, 7))
        )
        src = env["ss"]
        dst = HistoryShardStore(str(tmp_path / "dst"))
        assert dst.contiguous_floor() == 0
        dst.import_shard(_file_blob(src, env["sids"][0]))  # [1,2]
        assert dst.contiguous_floor() == 2
        dst.import_shard(_file_blob(src, env["sids"][2]))  # [6,7]: gap at 5
        assert dst.contiguous_floor() == 2
        dst.import_shard(_file_blob(src, env["sids"][1]))  # [3,4]
        assert dst.contiguous_floor() == 4  # 5 still missing
        assert dst.get_json()["contiguous_floor"] == 4
        src.close()
        dst.close()


# -- ShardBackfill ---------------------------------------------------------


class _FakeNet:
    def __init__(self):
        self.sent = []  # (peer, msg)

    def send(self, peer, msg):
        self.sent.append((peer, msg))


def _manifest_rows(ss: HistoryShardStore) -> list:
    return [
        (r["id"], r["size"], r["live_bytes"], bool(r["active"]),
         r.get("lo", 0), r.get("hi", 0), r.get("file_bytes", 0))
        for r in ss.segments()
    ]


def _serve_file(ss: HistoryShardStore, msg, chunk=1 << 15, epoch=0):
    """One SegmentData chunk reply for a whole-file GetSegments."""
    import stellard_tpu.overlay.wire as W

    meta, data = ss.fetch_segment(msg.seg_id, offset=msg.offset,
                                  length=chunk)
    return W.SegmentData(msg.seg_id, meta["size"], msg.offset, data,
                         snap_epoch=epoch)


class TestShardBackfill:
    def _mk(self, tmp_path, net, peers=("a", "b", "c"), **kw):
        dst = HistoryShardStore(str(tmp_path / "bf-dst"))
        clock = [0.0]
        imported = []
        sb = ShardBackfill(
            send=net.send,
            peers=lambda: list(peers),
            shardstore=dst,
            clock=lambda: clock[0],
            request_timeout=2.0,
            backoff_base=1.0,
            backoff_max=4.0,
            rescan_s=30.0,
            seed=1,
            on_imported=imported.append,
            **kw,
        )
        return sb, dst, clock, imported

    def _drain(self, sb, net, src, epoch=0):
        """Serve every outstanding request from `src` until idle."""
        guard = 0
        while net.sent:
            guard += 1
            assert guard < 10_000
            peer, msg = net.sent.pop(0)
            if msg.seg_id < 0:
                sb.on_manifest(peer, _manifest_rows(src), epoch=epoch)
            else:
                sb.on_data(peer, _serve_file(src, msg, epoch=epoch))

    def test_backfill_oldest_first_chunked(self, tmp_path):
        env = _sealed_chain(tmp_path)
        src = env["ss"]
        net = _FakeNet()
        sb, dst, clock, imported = self._mk(tmp_path, net)
        assert sb.start()
        peer, msg = net.sent.pop(0)
        assert msg.seg_id == -1
        sb.on_manifest(peer, _manifest_rows(src), epoch=7)
        # oldest history first: the [1,3] shard's file id is requested
        # before [4,6]'s
        first_fid = net.sent[0][1].seg_id
        assert first_fid == SHARD_FILE_BASE + env["sids"][0]
        self._drain(sb, net, src, epoch=7)
        assert sb.state == "done" and not sb.active
        assert sb.counters["imported"] == 2
        assert dst.contiguous_floor() == 6
        assert [r["lo"] for r in imported] == [1, 4]
        assert sb.get_json()["verified_floor"] == 6
        src.close()
        dst.close()

    def test_covered_shards_skipped(self, tmp_path):
        env = _sealed_chain(tmp_path)
        src = env["ss"]
        net = _FakeNet()
        sb, dst, clock, _imp = self._mk(tmp_path, net)
        dst.import_shard(_file_blob(src, env["sids"][0]))  # pre-held [1,3]
        sb.start()
        peer, _ = net.sent.pop(0)
        sb.on_manifest(peer, _manifest_rows(src))
        fids = [m.seg_id for _p, m in net.sent]
        assert SHARD_FILE_BASE + env["sids"][0] not in fids
        self._drain(sb, net, src)
        assert sb.counters["imported"] == 1
        assert dst.contiguous_floor() == 6
        src.close()
        dst.close()

    def test_nothing_to_do_completes(self, tmp_path):
        env = _sealed_chain(tmp_path)
        net = _FakeNet()
        sb, dst, clock, _imp = self._mk(tmp_path, net)
        sb.start()
        peer, _ = net.sent.pop(0)
        sb.on_manifest(peer, [(0, 10, 10, True)])  # live rows only
        assert sb.state == "done" and sb.counters["completed"] == 1
        env["ss"].close()
        dst.close()

    def test_garbage_peer_condemned_refetched_zero_retained(self, tmp_path):
        import os

        env = _sealed_chain(tmp_path, splits=((1, 3),))
        src = env["ss"]
        net = _FakeNet()
        noted, charged = [], []
        sb, dst, clock, _imp = self._mk(
            tmp_path, net,
            note_byzantine=lambda kind, **kw: noted.append(kind),
            on_condemn=charged.append,
        )
        sb.start()
        peer, _ = net.sent.pop(0)
        sb.on_manifest(peer, _manifest_rows(src))
        peer2, msg2 = net.sent.pop(0)
        meta, _ = src.fetch_segment(msg2.seg_id)
        bad = bytearray(_file_blob(src, env["sids"][0]))
        bad[40] ^= 0xFF  # hostile image: fails the offline contract
        files_before = sorted(os.listdir(tmp_path / "bf-dst"))
        sb.on_data(peer2, __import__(
            "stellard_tpu.overlay.wire", fromlist=["wire"]
        ).SegmentData(msg2.seg_id, len(bad), 0, bytes(bad)))
        assert sb.counters["import_rejects"] == 1
        assert sb.counters["garbage_peers"] == 1
        assert noted == ["garbage_segment"]
        assert charged == [peer2]
        # zero hostile bytes retained
        assert sorted(os.listdir(tmp_path / "bf-dst")) == files_before
        # the SAME shard refetches from another peer and completes
        peer3, msg3 = net.sent.pop(0)
        assert peer3 != peer2 and msg3.seg_id == msg2.seg_id
        net.sent.insert(0, (peer3, msg3))
        self._drain(sb, net, src)
        assert sb.state == "done" and sb.counters["imported"] == 1
        assert dst.contiguous_floor() == 3
        src.close()
        dst.close()

    def test_all_peers_garbage_falls_back(self, tmp_path):
        env = _sealed_chain(tmp_path, splits=((1, 3),))
        src = env["ss"]
        net = _FakeNet()
        sb, dst, clock, _imp = self._mk(tmp_path, net, peers=("a", "b"))
        sb.start()
        peer, _ = net.sent.pop(0)
        sb.on_manifest(peer, _manifest_rows(src))
        blob = _file_blob(src, env["sids"][0])
        bad = bytearray(blob)
        bad[40] ^= 0xFF
        import stellard_tpu.overlay.wire as W

        for _ in range(2):
            p, m = net.sent.pop(0)
            sb.on_data(p, W.SegmentData(m.seg_id, len(bad), 0, bytes(bad)))
        assert sb.state == "fallback" and not sb.active
        assert sb.counters["garbage_peers"] == 2
        assert sb.counters["fallbacks"] == 1
        src.close()
        dst.close()

    def test_oversized_transfer_condemned(self, tmp_path):
        env = _sealed_chain(tmp_path, splits=((1, 3),))
        src = env["ss"]
        net = _FakeNet()
        sb, dst, clock, _imp = self._mk(tmp_path, net)
        sb.start()
        peer, _ = net.sent.pop(0)
        sb.on_manifest(peer, _manifest_rows(src))
        p, m = net.sent.pop(0)
        import stellard_tpu.overlay.wire as W

        # a total far past advertised+slack never buys unbounded RAM
        sb.on_data(p, W.SegmentData(
            m.seg_id, ShardBackfill.MAX_SHARD_TRANSFER, 0, b"x"
        ))
        assert sb.counters["garbage_peers"] == 1
        src.close()
        dst.close()

    def test_epoch_move_restarts_from_manifest(self, tmp_path):
        env = _sealed_chain(tmp_path)
        src = env["ss"]
        net = _FakeNet()
        sb, dst, clock, _imp = self._mk(tmp_path, net)
        sb.start()
        peer, _ = net.sent.pop(0)
        sb.on_manifest(peer, _manifest_rows(src), epoch=3)
        p, m = net.sent.pop(0)
        # the source rotated mid-transfer: its epoch moved
        sb.on_data(p, _serve_file(src, m, epoch=4))
        assert sb.counters["epoch_restarts"] == 1
        assert sb.state == "manifest"
        p2, m2 = net.sent.pop(0)
        assert m2.seg_id == -1  # fresh manifest, never splice snapshots
        src.close()
        dst.close()

    def test_timeout_switches_peer_then_rescan_rearms(self, tmp_path):
        env = _sealed_chain(tmp_path, splits=((1, 3),))
        src = env["ss"]
        net = _FakeNet()
        sb, dst, clock, _imp = self._mk(tmp_path, net)
        sb.start()
        first_peer, _ = net.sent.pop(0)
        clock[0] = 2.5  # past request_timeout: manifest never answered
        sb.tick(clock[0])
        assert sb.counters["timeouts"] == 1
        clock[0] += 2.0
        sb.tick(clock[0])
        assert sb.counters["retries"] == 1
        retry_peer, _ = net.sent.pop(0)
        assert retry_peer != first_peer
        # finish the session, then the self-arming rescan starts a new
        # one after rescan_s without any external trigger
        sb.on_manifest(retry_peer, _manifest_rows(src))
        self._drain(sb, net, src)
        assert sb.state == "done"
        sb.tick(clock[0] + 1.0)
        assert not sb.active
        clock[0] += 40.0
        sb.tick(clock[0])
        assert sb.active and sb.counters["started"] == 2
        assert sb.counters["rescans"] >= 1
        src.close()
        dst.close()


# -- feed_shard / ArchiveTxDatabase ---------------------------------------


class TestFeedShard:
    def test_archive_txdb_never_trims(self):
        db = ArchiveTxDatabase(":memory:")
        with pytest.raises(RuntimeError, match="never trims"):
            db.trim_below(5)
        assert db.retain_floor == 0
        db.close()

    def test_feed_populates_all_three_stores(self, tmp_path):
        env = _sealed_chain(tmp_path)
        src, master = env["ss"], env["master"]
        txdb = ArchiveTxDatabase(":memory:")
        sunk: dict[bytes, bytes] = {}
        total = {"records": 0, "txs": 0}
        for sid in env["sids"]:
            out = feed_shard(
                src, sid,
                store=lambda tb, key, blob: sunk.__setitem__(key, blob),
                txdb=txdb,
            )
            total["records"] += out["records"]
            total["txs"] += out["txs"]
        assert total["txs"] == len(env["acct_rows"])
        assert len(sunk) > 0
        # ledger headers queryable (deep `ledger` RPCs resolve these)
        for h in env["headers"][:6]:
            got = txdb.get_ledger_header(seq=h["seq"])
            assert got is not None and got["hash"] == h["hash"]
        # account_tx pages in (ledger_seq, txn_seq) order, bytes
        # matching the sealed shard's verified contents
        rows = txdb.account_transactions(master.account_id, 1, 6,
                                         limit=100, forward=True)
        assert [(r["ledger_seq"], r["txn_seq"]) for r in rows] == [
            (r[1], r[2]) for r in env["acct_rows"]
        ]
        for row in rows:
            sid = src.covers(row["ledger_seq"])
            raw, meta = src.tx_blob(sid, row["txid"])
            assert row["raw"] == raw and row["meta"] == meta
            assert row["status"] == "tesSUCCESS"
        src.close()
        txdb.close()


# -- forever cache (immutable historical seqs) ----------------------------


@pytest.fixture
def std_node():
    n = Node(Config(signature_backend="cpu")).setup()
    yield n
    n.stop()


def _fund(n: Node, kp: KeyPair, drops: int = 1_000_000_000) -> None:
    from stellard_tpu.protocol.sfields import sfSequence

    master = n.master_keys
    root = n.ledger_master.current_ledger().account_root(master.account_id)
    tx = SerializedTransaction.build(
        TxType.ttPAYMENT, master.account_id, root[sfSequence], 10,
        {sfAmount: STAmount.from_drops(drops),
         sfDestination: kp.account_id},
    )
    tx.sign(master)
    ter, applied = n.submit(tx)
    assert applied, ter


def _call(n: Node, method: str, **params) -> dict:
    return dispatch(Context(n, params, Role.ADMIN), method)


class TestForeverCache:
    def _flood(self, node, n_closes=5):
        alice = KeyPair.from_passphrase("forever-alice")
        _fund(node, alice)
        node.close_ledger()
        for _ in range(n_closes - 1):
            _fund(node, alice, drops=1_000_000)
            node.close_ledger()
        return alice

    def test_below_floor_account_tx_survives_epoch_swap(self, std_node):
        node = std_node
        alice = self._flood(node)
        node.read_plane.set_archive_floor(4)
        r1 = _call(node, "account_tx", account=alice.human_account_id,
                   ledger_index_min=1, ledger_index_max=4)
        assert "error" not in r1
        cj = node.read_cache.get_json()
        assert cj["forever_entries"] == 1 and cj["forever_inserts"] == 1
        r2 = _call(node, "account_tx", account=alice.human_account_id,
                   ledger_index_min=1, ledger_index_max=4)
        assert node.read_cache.get_json()["forever_hits"] == 1
        assert r2["transactions"] == r1["transactions"]
        # an epoch swap (new validated seq) evicts the per-seq tier but
        # NEVER the forever tier: immutable history is immutable
        node.read_cache.on_new_seq(10_000)
        r3 = _call(node, "account_tx", account=alice.human_account_id,
                   ledger_index_min=1, ledger_index_max=4)
        cj = node.read_cache.get_json()
        assert cj["forever_hits"] == 2 and cj["forever_entries"] == 1
        assert r3["transactions"] == r1["transactions"]

    def test_unbounded_or_above_floor_never_forever(self, std_node):
        node = std_node
        alice = self._flood(node)
        node.read_plane.set_archive_floor(3)
        # unbounded max: the window grows with the chain
        r = _call(node, "account_tx", account=alice.human_account_id)
        assert "error" not in r
        # bounded above the floor: includes un-verified history
        r = _call(node, "account_tx", account=alice.human_account_id,
                  ledger_index_min=1, ledger_index_max=5)
        assert "error" not in r
        assert node.read_cache.get_json()["forever_entries"] == 0

    def test_ledger_by_seq_forever_but_selectors_never(self, std_node):
        node = std_node
        self._flood(node)
        node.read_plane.set_archive_floor(3)
        r1 = _call(node, "ledger", ledger_index=2)
        assert "error" not in r1, r1
        assert node.read_cache.get_json()["forever_entries"] == 1
        node.read_cache.on_new_seq(10_000)
        _call(node, "ledger", ledger_index=2)
        assert node.read_cache.get_json()["forever_hits"] == 1
        # moving-target selectors are never admitted
        _call(node, "ledger", ledger_index="validated")
        assert node.read_cache.get_json()["forever_entries"] == 1

    def test_no_floor_means_no_forever_tier(self, std_node):
        node = std_node
        alice = self._flood(node)
        r = _call(node, "account_tx", account=alice.human_account_id,
                  ledger_index_min=1, ledger_index_max=3)
        assert "error" not in r
        assert node.read_cache.get_json()["forever_entries"] == 0

    def test_floor_is_monotonic(self, std_node):
        node = std_node
        node.read_plane.set_archive_floor(9)
        node.read_plane.set_archive_floor(4)  # verified never un-verifies
        assert node.read_plane.archive_floor == 9
        assert node.read_plane.get_json()["archive_floor"] == 9


# -- WS-door resume cursors (satellite: PR 19 resume on the raw door) -----


@pytest.fixture(scope="module")
def ws_node():
    cfg = Config()
    cfg.rpc_port = 0
    cfg.websocket_port = 0
    cfg.subs_resume_horizon = 3
    n = Node(cfg).setup().serve()
    yield n
    n.stop()


class TestWsResumeDoor:
    def _ws(self, node):
        from test_rpc_server import WsClient

        return WsClient(node.ws_server.port)

    def _close(self, node, n=1):
        for _ in range(n):
            node.close_ledger()

    def test_resume_replays_missed_events(self, ws_node):
        node = ws_node
        ws1 = self._ws(node)
        try:
            resp = ws1.call("subscribe", streams=["ledger"])
            assert resp["status"] == "success"
            self._close(node)
            ws1.sock.settimeout(10)
            msg = ws1.recv()
            while msg.get("type") != "ledgerClosed":
                msg = ws1.recv()
            last_seen = msg["ledger_index"]
        finally:
            ws1.close()
        self._close(node, 2)  # missed while disconnected
        ws2 = self._ws(node)
        try:
            ws2.send({"id": 1, "command": "subscribe",
                      "streams": ["ledger"], "resume": last_seen})
            ws2.sock.settimeout(10)
            replayed_events, result = [], None
            while result is None or len(replayed_events) < 2:
                msg = ws2.recv()
                if msg.get("type") == "response":
                    result = msg["result"]
                elif msg.get("type") == "ledgerClosed":
                    replayed_events.append(msg["ledger_index"])
            assert result["resumed"] is True and result["cold"] is False
            assert result["replayed"] >= 2
            # gap-free: replay starts exactly after the cursor
            assert replayed_events[0] == last_seen + 1
            assert replayed_events == sorted(replayed_events)
        finally:
            ws2.close()

    def test_resume_past_horizon_explicit_cold(self, ws_node):
        node = ws_node
        self._close(node, 5)  # horizon=3: early events fell off the ring
        ws = self._ws(node)
        try:
            resp = ws.call("subscribe", streams=["ledger"], resume=1)
            assert resp["status"] == "success"
            r = resp["result"]
            assert r["cold"] is True and r["resumed"] is False
            assert r["replayed"] == 0 and r["horizon"] > 2
        finally:
            ws.close()

    def test_malformed_resume_rejected(self, ws_node):
        ws = self._ws(ws_node)
        try:
            for bad in (True, {"last_seq": -1}, "nope", [3], -2):
                resp = ws.call("subscribe", streams=["ledger"], resume=bad)
                assert resp["status"] == "error", (bad, resp)
                assert resp["result"]["error"] == "invalidParams", (bad, resp)
        finally:
            ws.close()

    def test_http_door_resume_also_works(self, ws_node):
        # the embedded dispatch path (no infosub) must not crash on a
        # resume param; it has no stream connection to resume
        from test_rpc_server import rpc

        r = rpc(ws_node, "server_info")
        assert r["status"] == "success"


# -- account_tx paging across the shard/live boundary under trim ----------


class TestBoundaryPagingUnderTrim:
    def _node_with_shards(self, tmp_path):
        cfg = Config(signature_backend="cpu")
        cfg.node_db_shards = str(tmp_path / "live-shards")
        return Node(cfg).setup()

    def _flood(self, node, n_closes=8):
        alice = KeyPair.from_passphrase("boundary-alice")
        _fund(node, alice)
        node.close_ledger()
        for _ in range(n_closes - 1):
            _fund(node, alice, drops=1_000_000)
            node.close_ledger()
        return alice

    def _seal_range(self, node, lo, hi):
        headers = [node.txdb.get_ledger_header(seq=s)
                   for s in range(lo, hi + 1)]
        assert all(h is not None for h in headers)

        def fetch(h):
            o = node.nodestore.fetch(h, populate_cache=False)
            return o.data if o else None

        recs = collect_retired(fetch, headers, set())
        rows = node.txdb.account_tx_index(lo, hi)
        node.shardstore.seal(lo, hi, recs, rows,
                             first_hash=headers[0]["hash"],
                             last_hash=headers[-1]["hash"])

    def _page_all(self, node, alice, limit, on_page=None):
        pages, marker = [], None
        for _ in range(64):
            params = {"account": alice.human_account_id,
                      "ledger_index_min": 1, "ledger_index_max": 99,
                      "limit": limit, "forward": True}
            if marker is not None:
                params["marker"] = marker
            r = _call(node, "account_tx", **params)
            assert "error" not in r, r
            pages.append(r)
            if on_page is not None:
                on_page(len(pages), r)
            marker = r.get("marker")
            if marker is None:
                break
        return pages

    def test_trim_mid_pagination_no_gap_no_empty_page(self, tmp_path):
        node = self._node_with_shards(tmp_path)
        try:
            alice = self._flood(node)
            full = _call(node, "account_tx",
                         account=alice.human_account_id,
                         ledger_index_min=1, ledger_index_max=99,
                         limit=400, forward=True)
            want = [t["tx"]["hash"] for t in full["transactions"]]
            assert len(want) >= 8
            # seal [2,3] and [4,5]; trim to 4: shard tier serves 2..3
            self._seal_range(node, 2, 3)
            self._seal_range(node, 4, 5)
            node.txdb.trim_below(4)

            def raise_floor(page_no, _r):
                if page_no == 1:
                    # CONCURRENT sql_trim raising the retain floor
                    # mid-pagination: [4,5] drops from SQL but stays
                    # served from its sealed shard
                    node.txdb.trim_below(6)

            pages = self._page_all(node, alice, limit=2,
                                   on_page=raise_floor)
            got = [t["tx"]["hash"] for p in pages
                   for t in p["transactions"]]
            assert got == want  # no gap, no duplicate, no silent loss
            # every page that advertised a marker carried rows
            for p in pages[:-1]:
                assert p["transactions"], "silent empty page"
            # effective-range echo: the floor is the oldest shard, not
            # the (raised) SQL retain floor
            assert all(p["ledger_index_min"] == 2 for p in pages)
        finally:
            node.stop()

    def test_marker_straddles_boundary_after_trim(self, tmp_path):
        node = self._node_with_shards(tmp_path)
        try:
            alice = self._flood(node)
            self._seal_range(node, 2, 4)
            node.txdb.trim_below(5)
            # a marker INSIDE the sealed range resumes from the shard
            # tier and crosses into live SQL seamlessly
            r = _call(node, "account_tx",
                      account=alice.human_account_id,
                      ledger_index_min=1, ledger_index_max=99,
                      limit=3, forward=True,
                      marker={"ledger": 3, "seq": 0})
            assert "error" not in r, r
            seqs = [t["tx"]["ledger_index"] for t in r["transactions"]]
            assert seqs and seqs[0] >= 3
            # a marker below the oldest shard stays a loud error
            r = _call(node, "account_tx",
                      account=alice.human_account_id,
                      marker={"ledger": 1, "seq": 0})
            assert r.get("error") == "lgrIdxInvalid"
        finally:
            node.stop()

    def test_threaded_trim_race(self, tmp_path):
        """A real concurrent trim thread: pagination never sees an
        error or a gap while the floor rises under it."""
        node = self._node_with_shards(tmp_path)
        try:
            alice = self._flood(node)
            full = _call(node, "account_tx",
                         account=alice.human_account_id,
                         ledger_index_min=1, ledger_index_max=99,
                         limit=400, forward=True)
            want = [t["tx"]["hash"] for t in full["transactions"]]
            self._seal_range(node, 2, 3)
            self._seal_range(node, 4, 5)
            node.txdb.trim_below(4)
            started = threading.Event()

            def trimmer():
                started.wait(5)
                node.txdb.trim_below(6)

            th = threading.Thread(target=trimmer)
            th.start()
            try:
                pages = self._page_all(
                    node, alice, limit=2,
                    on_page=lambda n, _r: started.set(),
                )
            finally:
                th.join(10)
            got = [t["tx"]["hash"] for p in pages
                   for t in p["transactions"]]
            assert got == want
        finally:
            node.stop()


# -- fuzzer archive leg ----------------------------------------------------


class TestFuzzerArchiveLeg:
    def test_archive_backfill_scenario_clean(self):
        from stellard_tpu.testkit.scenario import run_simnet
        from stellard_tpu.testkit.scenarios import build_scenario
        from stellard_tpu.testkit.search import (
            check_invariants,
            coverage_state,
        )

        scn = build_scenario("archive_backfill", seed=3)
        card = run_simnet(scn)
        assert card["converged"]
        ar = card["archive"]
        assert ar["imported"] >= 1 and ar["queries"] > 0
        assert ar["byte_match_failures"] == 0
        # the garbage first-pick peer served a corrupt image: condemned
        # (verify-gated reject), then the honest refetch imported
        assert ar["import_rejects"] >= 1 and ar["garbage_peers"] >= 1
        assert ar["verified_floor"] > 0
        assert check_invariants(scn, card) == []
        # archive dynamics ride the END of the coverage vector
        assert coverage_state(card)[-3:] == (True, False, True)

    def test_planted_corruption_trips_byte_match(self):
        from stellard_tpu.testkit.scenario import (
            ARCHIVE_CORRUPT,
            run_simnet,
        )
        from stellard_tpu.testkit.scenarios import build_scenario
        from stellard_tpu.testkit.search import check_invariants

        scn = build_scenario("archive_backfill", seed=3)
        ARCHIVE_CORRUPT["armed"] = True
        try:
            card = run_simnet(scn)
        finally:
            ARCHIVE_CORRUPT["armed"] = False
        assert card["archive"]["byte_match_failures"] > 0
        inv = [v.invariant for v in check_invariants(scn, card)]
        assert "archive_byte_match" in inv

    def test_shrinker_offers_drop_archive(self):
        from stellard_tpu.testkit.scenarios import build_scenario
        from stellard_tpu.testkit.search import _weaken_ops

        scn = build_scenario("archive_backfill", seed=3)
        ops = dict(_weaken_ops(scn))
        assert "drop_archive" in ops
        assert ops["drop_archive"].shards is True  # tier kept, leg cut
        # dropping the shard tier also drops the dependent archive leg
        assert ops["drop_shard_tier"].archive is False
        assert ops["drop_cold_node"].archive is False

    def test_scenario_roundtrips_with_archive_field(self):
        from stellard_tpu.testkit.scenario import Scenario
        from stellard_tpu.testkit.scenarios import build_scenario

        scn = build_scenario("archive_backfill", seed=3)
        assert scn.archive is True
        rt = Scenario.from_json(scn.to_json())
        assert rt.archive is True
        assert rt.digest() == scn.digest()


# -- config gates ----------------------------------------------------------


class TestArchiveConfigGates:
    def test_mode_archive_parses_with_stanza(self):
        cfg = Config.from_ini(
            "[node]\nmode=archive\nupstream=127.0.0.1:5005\n"
            "[archive]\npath=/tmp/x\nbackfill=1\nrescan_s=9.5\n"
        )
        assert cfg.node_mode == "archive"
        assert cfg.archive_path == "/tmp/x"
        assert cfg.archive_backfill == 1
        assert cfg.archive_rescan_s == 9.5

    def test_unknown_archive_key_rejected(self):
        with pytest.raises(ValueError, match="archive"):
            Config.from_ini(
                "[node]\nmode=archive\n[archive]\nbackfil=1\n"
            )

    def test_archive_stanza_requires_archive_mode(self):
        with pytest.raises(ValueError, match="mode=archive"):
            Config.from_ini("[node]\nmode=validator\n[archive]\npath=/x\n")

    def test_nonpositive_rescan_rejected(self):
        with pytest.raises(ValueError, match="rescan_s"):
            Config.from_ini(
                "[node]\nmode=archive\n[archive]\nrescan_s=0\n"
            )

    def test_archive_requires_networked_node(self, tmp_path):
        with pytest.raises(ValueError, match="networked"):
            Node(Config(node_mode="archive", standalone=True,
                        archive_path=str(tmp_path / "a")))

    def test_online_delete_incompatible_with_archive(self, tmp_path):
        with pytest.raises(ValueError, match="online_delete"):
            Node(Config(node_mode="archive",
                        archive_path=str(tmp_path / "a"),
                        node_db_online_delete=4))

    def test_unknown_mode_still_rejected(self):
        with pytest.raises(ValueError, match="mode"):
            Config.from_ini("[node]\nmode=reporting\n")
