"""Auxiliary subsystems (SURVEY §5 / VERDICT r2 missing rows):
SNTP network clock, insight/statsd metrics, LocalTxs re-application,
cluster load sharing, protocol-version gate, slow-reader backpressure.
"""

from __future__ import annotations

import socket
import struct
import threading
import time

import pytest

from stellard_tpu.node.localtxs import LocalTxs, HOLD_LEDGERS
from stellard_tpu.node.metrics import (
    CollectorManager,
    NullCollector,
    StatsDCollector,
)
from stellard_tpu.node.netclock import NTP_EPOCH_DELTA, SntpClient


class TestSntp:
    def _fake_server(self, skew: float):
        """A one-shot SNTP responder applying a clock skew."""
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]

        def serve():
            data, addr = sock.recvfrom(512)
            reply = bytearray(48)
            reply[0] = (4 << 3) | 4  # VN=4 Mode=4 (server)
            tx = time.time() + skew + NTP_EPOCH_DELTA
            sec = int(tx)
            frac = int((tx - sec) * (1 << 32))
            struct.pack_into(">II", reply, 40, sec, frac)
            sock.sendto(bytes(reply), addr)
            sock.close()

        threading.Thread(target=serve, daemon=True).start()
        return port

    def test_learns_offset_from_skewed_server(self):
        port = self._fake_server(skew=42.0)
        c = SntpClient([("127.0.0.1", port)], timeout=3.0)
        assert c.query_once()
        assert c.synced
        assert abs(c.offset - 42.0) < 1.0
        assert abs(c.network_unix_time() - (time.time() + 42.0)) < 1.0

    def test_insane_offset_rejected(self):
        port = self._fake_server(skew=10_000.0)
        c = SntpClient([("127.0.0.1", port)], timeout=3.0)
        assert not c.query_once()
        assert not c.synced

    def test_unreachable_server_is_clean(self):
        c = SntpClient([("127.0.0.1", 1)], timeout=0.2)
        assert not c.query_once()
        assert c.offset == 0.0


class TestMetrics:
    def test_instruments_and_statsd_lines(self):
        mgr = CollectorManager(NullCollector())
        mgr.counter("tx.processed").inc(5)
        mgr.gauge("jobq.depth").set(17)
        mgr.meter("peer.msgs").mark(3)
        mgr.hook("verify", lambda: {"batches": 2, "rate": 1.5})
        lines = mgr.flush_once()
        assert "tx.processed:5|c" in lines
        assert "jobq.depth:17|g" in lines
        # meters ship as counters: "|m" is not a statsd metric type and
        # real statsd daemons drop unknown types on the floor
        assert "peer.msgs:3|c" in lines
        assert not any(line.endswith("|m") for line in lines)
        assert "verify.batches:2|g" in lines
        # counters flush deltas, not totals
        mgr.counter("tx.processed").inc(2)
        lines = mgr.flush_once()
        assert "tx.processed:2|c" in lines
        # meters drain per flush: nothing marked since -> no line
        assert not any(line.startswith("peer.msgs:") for line in lines)

    def test_concurrent_flushes_never_double_report_counter_deltas(self):
        """_last_counter_vals updates under _lock: racing flushes must
        partition a counter's increments, never double-count them."""
        mgr = CollectorManager(NullCollector())
        c = mgr.counter("races")
        seen: list[int] = []
        stop = threading.Event()

        def flusher():
            while not stop.is_set():
                for line in mgr.flush_once():
                    if line.startswith("races:"):
                        seen.append(int(line.split(":")[1].split("|")[0]))

        threads = [threading.Thread(target=flusher) for _ in range(4)]
        for t in threads:
            t.start()
        for _ in range(2000):
            c.inc()
        stop.set()
        for t in threads:
            t.join(timeout=10)
        seen.extend(
            int(line.split(":")[1].split("|")[0])
            for line in mgr.flush_once()
            if line.startswith("races:")
        )
        assert sum(seen) == 2000

    def test_statsd_udp_export(self):
        rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        rx.bind(("127.0.0.1", 0))
        rx.settimeout(3.0)
        port = rx.getsockname()[1]
        mgr = CollectorManager(StatsDCollector("127.0.0.1", port, "testnode"))
        mgr.counter("closes").inc()
        mgr.flush_once()
        data, _ = rx.recvfrom(2048)
        assert b"testnode.closes:1|c" in data
        rx.close()
        mgr.stop()

    def test_from_config(self):
        assert isinstance(CollectorManager.from_config("").collector, NullCollector)
        m = CollectorManager.from_config("statsd:127.0.0.1:8125:pfx")
        assert isinstance(m.collector, StatsDCollector)
        assert m.collector.prefix == "pfx"
        m.collector.close()

    def test_broken_hook_does_not_kill_flush(self):
        mgr = CollectorManager(NullCollector())
        mgr.hook("bad", lambda: 1 / 0)
        mgr.gauge("ok").set(1)
        assert "ok:1|g" in mgr.flush_once()


class TestLocalTxs:
    def test_reapply_until_landed_then_swept(self):
        """A local tx left out of one consensus set re-applies to the next
        open ledger and sweeps once it lands in a validated ledger."""
        from stellard_tpu.engine.engine import TxParams
        from stellard_tpu.node.ledgermaster import LedgerMaster
        from stellard_tpu.protocol.formats import TxType
        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.protocol.sfields import sfAmount, sfDestination
        from stellard_tpu.protocol.stamount import STAmount
        from stellard_tpu.protocol.sttx import SerializedTransaction

        master = KeyPair.from_passphrase("masterpassphrase")
        alice = KeyPair.from_passphrase("alice")
        lm = LedgerMaster()
        lm.min_validations = 0
        lm.start_new_ledger(master.account_id, 1000)
        lt = LocalTxs()

        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, master.account_id, 1, 10,
            {sfAmount: STAmount.from_drops(500_000_000),
             sfDestination: alice.account_id},
        )
        tx.sign(master)
        lm.do_transaction(tx, TxParams.OPEN_LEDGER | TxParams.RETRY)
        lt.push_back(lm.closed_ledger().seq, tx)

        # consensus closes WITHOUT our tx (another node's empty set won)
        lcl, _ = lm.close_with_txset([], 2000, 10)
        assert lt.sweep(lcl) == 0  # not landed, not expired
        assert len(lt) == 1
        lt.apply_to_open(lm, TxParams.OPEN_LEDGER | TxParams.RETRY)
        # next close includes the open ledger (normal close path)
        lcl2, _ = lm.close_and_advance(3000, 10)
        assert lcl2.account_root(alice.account_id) is not None
        assert lt.sweep(lcl2) == 1  # landed -> swept
        assert len(lt) == 0

    def test_expiry_and_permanent_failure(self):
        from stellard_tpu.engine.engine import TxParams
        from stellard_tpu.node.ledgermaster import LedgerMaster
        from stellard_tpu.protocol.formats import TxType
        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.protocol.sfields import sfAmount, sfDestination
        from stellard_tpu.protocol.stamount import STAmount
        from stellard_tpu.protocol.sttx import SerializedTransaction

        master = KeyPair.from_passphrase("masterpassphrase")
        alice = KeyPair.from_passphrase("alice")
        lm = LedgerMaster()
        lm.min_validations = 0
        lm.start_new_ledger(master.account_id, 1000)
        lt = LocalTxs()
        # a tx with a far-future sequence can never apply
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, master.account_id, 99, 10,
            {sfAmount: STAmount.from_drops(1_000_000),
             sfDestination: alice.account_id},
        )
        tx.sign(master)
        lt.push_back(lm.closed_ledger().seq, tx)
        for i in range(HOLD_LEDGERS + 2):
            lcl, _ = lm.close_and_advance(2000 + i * 10, 10)
        assert lt.sweep(lcl) == 1  # expired
        assert len(lt) == 0


class TestOverlayHardening:
    def _mini_net(self, n=2, **kw):
        import sys

        sys.path.insert(0, "/root/repo/tests")
        from test_peerfinder import free_ports, make_overlay, MASTER

        from stellard_tpu.protocol.keys import KeyPair

        ports = free_ports(n)
        keys = [KeyPair.from_passphrase(f"aux-val-{i}") for i in range(n)]
        unl = {k.public for k in keys}
        t0 = time.monotonic()
        clock = lambda: (time.monotonic() - t0) * 5.0
        ntime = lambda: 40_000_000 + int(clock())
        overlays = [
            make_overlay(
                keys[i], unl, ports[i],
                [("127.0.0.1", ports[j]) for j in range(n) if j != i],
                ntime, clock, **(kw if isinstance(kw, dict) else {}),
            )
            for i in range(n)
        ]
        for ov in overlays:
            ov.start(MASTER.account_id, close_time=ntime())
        return overlays, ports

    def test_version_skew_rejected(self):
        """A peer announcing a different protocol version is refused after
        the hello (clean close, no session registered)."""
        import os

        from stellard_tpu.overlay.tcp import HP_SESSION, PROTO_VERSION
        from stellard_tpu.overlay.wire import FrameReader, Hello, frame
        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.utils.hashes import prefix_hash

        overlays, ports = self._mini_net(2)
        try:
            me = KeyPair.from_passphrase("skewed-node")
            s = socket.create_connection(("127.0.0.1", ports[0]), timeout=3)
            s.settimeout(3.0)
            their_nonce = s.recv(32)
            nonce = os.urandom(32)
            s.sendall(nonce)
            session_hash = prefix_hash(
                HP_SESSION,
                min(nonce, their_nonce) + max(nonce, their_nonce),
            )
            hello = Hello(
                PROTO_VERSION + 7,  # skewed version
                0, me.public, me.sign(session_hash), 1, b"\x00" * 32, 0,
            )
            s.sendall(frame(hello))
            # server closes on us; no session appears under our key
            deadline = time.monotonic() + 5
            closed = False
            while time.monotonic() < deadline:
                try:
                    if s.recv(65536) == b"":
                        closed = True
                        break
                except socket.timeout:
                    break
                except OSError:
                    closed = True
                    break
            assert closed
            assert me.public not in overlays[0].peers
        finally:
            for ov in overlays:
                ov.stop()

    def test_slow_reader_does_not_wedge_the_net(self):
        """A connected peer that stops reading (full kernel buffer) must
        not block broadcasts: bounded sends mark it dead and the rest of
        the net keeps closing ledgers."""
        overlays, ports = self._mini_net(2)
        try:
            assert any(
                _wait(lambda: ov.peer_count() == 1, 15) for ov in overlays
            )
            victim = overlays[0]
            # grab the live session and wedge its socket: stop the reader
            # thread cooperatively by pausing recv via shrinking the
            # peer's socket buffer and never reading from our side
            with victim._peers_lock:
                peer = next(iter(victim.peers.values()))
            # flood a burst of large frames; bounded SO_SNDTIMEO on the
            # sender side guarantees send() returns (dead or sent)
            big = b"\x00" * 512 * 1024
            t0 = time.monotonic()
            from stellard_tpu.overlay.wire import TxSetData, frame as fr

            for _ in range(64):
                peer.send(fr(TxSetData(b"\x11" * 32, [big])))
                if not peer.alive:
                    break
            elapsed = time.monotonic() - t0
            assert elapsed < 60, "send path wedged"
            # the node itself still ticks (timer thread not blocked)
            seq0 = victim.node.lm.closed_ledger().seq
            assert _wait(
                lambda: victim.node.lm.closed_ledger().seq > seq0, 10
            )
        finally:
            for ov in overlays:
                ov.stop()

    def test_cluster_load_fee_propagates(self):
        from stellard_tpu.node.loadmgr import LoadFeeTrack
        from stellard_tpu.protocol.keys import KeyPair

        keys = [KeyPair.from_passphrase(f"aux-clu-{i}") for i in range(2)]
        cluster = {k.public for k in keys}
        tracks = [LoadFeeTrack(), LoadFeeTrack()]
        import sys

        sys.path.insert(0, "/root/repo/tests")
        from test_peerfinder import free_ports, MASTER

        from stellard_tpu.overlay.tcp import TcpOverlay

        ports = free_ports(2)
        t0 = time.monotonic()
        clock = lambda: (time.monotonic() - t0) * 5.0
        ntime = lambda: 41_000_000 + int(clock())
        overlays = []
        for i in range(2):
            overlays.append(TcpOverlay(
                key=keys[i],
                unl=cluster,
                quorum=2,
                port=ports[i],
                peer_addrs=[("127.0.0.1", ports[1 - i])],
                network_time=ntime,
                clock=clock,
                timer_interval=0.15,
                idle_interval=4,
                gossip_interval=0.3,
                cluster=cluster,
                fee_track=tracks[i],
            ))
        for ov in overlays:
            ov.start(MASTER.account_id, close_time=ntime())
        try:
            assert _wait(lambda: all(o.peer_count() == 1 for o in overlays), 15)
            # node 0 is overloaded; node 1 must learn the remote fee
            for _ in range(6):
                tracks[0].raise_local_fee()
            lf = tracks[0].load_factor
            assert _wait(lambda: tracks[1].load_factor >= lf, 15), (
                tracks[1].get_json()
            )
        finally:
            for ov in overlays:
                ov.stop()


def _wait(pred, timeout):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


class TestCppLogCompression:
    """[node_db] compression=zlib (the snappy role, SURVEY §2.8): blobs
    deflate when that saves bytes, flagged per record, and raw/deflated
    records interoperate within one store and across reopens."""

    def test_roundtrip_and_mixed_records(self, tmp_path):
        import zlib as _zlib

        from stellard_tpu.nodestore.core import (
            NodeObject,
            NodeObjectType,
            make_backend,
        )

        path = str(tmp_path / "c.cpplog")
        import random as _random

        compressible = b"AB" * 300  # deflates well
        random_blob = _random.Random(7).randbytes(512)  # stays raw
        assert len(_zlib.compress(random_blob, 1)) >= len(random_blob)

        be = make_backend("cpplog", path=path, compression="zlib")
        import hashlib

        k1 = hashlib.sha256(compressible).digest()
        k2 = hashlib.sha256(random_blob).digest()
        be.store_batch([
            NodeObject(NodeObjectType.ACCOUNT_NODE, k1, compressible),
            NodeObject(NodeObjectType.TRANSACTION_NODE, k2, random_blob),
        ])
        for k, want, t in [(k1, compressible, NodeObjectType.ACCOUNT_NODE),
                           (k2, random_blob, NodeObjectType.TRANSACTION_NODE)]:
            got = be.fetch(k)
            assert got is not None and got.data == want and got.type == t
        be.close()

        # a reader WITHOUT compression configured still reads both
        be2 = make_backend("cpplog", path=path)
        assert be2.fetch(k1).data == compressible
        assert be2.fetch(k2).data == random_blob
        be2.close()

        # the store really is smaller than raw for the compressible blob
        raw_len = len(compressible)
        assert len(_zlib.compress(compressible, 1)) < raw_len
        import os as _os

        assert _os.path.getsize(path) < raw_len + len(random_blob) + 200

    def test_unknown_compression_rejected(self, tmp_path):
        import pytest as _pytest

        from stellard_tpu.nodestore.core import make_backend

        with _pytest.raises(ValueError):
            make_backend("cpplog", path=str(tmp_path / "x.cpplog"),
                         compression="snappy")
