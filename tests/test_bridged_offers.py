"""Auto-bridged (through-STR) offer crossing.

The reference planned autobridging for IOU/IOU offers but shipped a
placeholder (transactors/CreateOffer.cpp:21 'no autobridging transactor
exists yet'); this build implements the real thing: each step the taker
consumes one price level from whichever is cheaper — the direct IOU/IOU
book or the composite of the IOU->STR and STR->IOU books.
"""

from __future__ import annotations

from stellard_tpu.engine import views
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import (
    sfAmount,
    sfDestination,
    sfLimitAmount,
    sfTakerGets,
    sfTakerPays,
)
from stellard_tpu.protocol.stamount import STAmount, currency_from_iso
from stellard_tpu.protocol.ter import TER

import sys

sys.path.insert(0, "/root/repo/tests")
from test_engine import Net, ALICE, BOB, CAROL, GATEWAY, ROOT_KEY, USD  # noqa: E402

EUR = currency_from_iso("EUR")
XRP = 1_000_000
MAKER1 = KeyPair.from_seed(b"\x55" * 32)
MAKER2 = KeyPair.from_seed(b"\x66" * 32)


def usd(v: int, issuer=GATEWAY) -> STAmount:
    return STAmount.from_iou(USD, issuer.account_id, v, 0)


def eur(v: int, issuer=GATEWAY) -> STAmount:
    return STAmount.from_iou(EUR, issuer.account_id, v, 0)


def setup_net() -> Net:
    """Gateway issues USD+EUR; two makers hold inventory."""
    net = Net(ALICE, BOB, CAROL, GATEWAY, MAKER1, MAKER2, fund=100_000 * XRP)
    for k in (ALICE, BOB, CAROL, MAKER1, MAKER2):
        net.trust(k, GATEWAY, 1_000_000, USD)
        net.trust(k, GATEWAY, 1_000_000, currency=EUR)
    net.pay(GATEWAY, MAKER1.account_id, usd(10_000))
    net.pay(GATEWAY, MAKER1.account_id, eur(10_000))
    net.pay(GATEWAY, MAKER2.account_id, usd(10_000))
    net.pay(GATEWAY, MAKER2.account_id, eur(10_000))
    net.pay(GATEWAY, ALICE.account_id, usd(1_000))
    return net


def offer(net, key, pays: STAmount, gets: STAmount, expect=TER.tesSUCCESS):
    return net.apply(key, TxType.ttOFFER_CREATE, expect,
                     fields={sfTakerPays: pays, sfTakerGets: gets})


def iou_bal(net, holder, currency) -> STAmount:
    from stellard_tpu.state.entryset import LedgerEntrySet

    les = LedgerEntrySet(net.ledger)
    return views.ripple_balance(
        les, holder.account_id, GATEWAY.account_id, currency
    )


class TestAutoBridge:
    def test_bridges_when_no_direct_book(self):
        """USD->EUR taker fills entirely through USD->STR and STR->EUR."""
        net = setup_net()
        # maker1 sells STR for USD at 1 STR = 1 USD (wants USD, gives STR)
        offer(net, MAKER1, usd(100), STAmount.from_drops(100 * XRP))
        # maker2 sells EUR for STR at 1 STR = 1 EUR
        offer(net, MAKER2, STAmount.from_drops(100 * XRP), eur(100))
        # alice: buy 50 EUR paying up to 60 USD (no direct USD/EUR book)
        before = iou_bal(net, ALICE, EUR)
        offer(net, ALICE, eur(50), usd(60))
        got = iou_bal(net, ALICE, EUR)
        assert got.signum() > 0 and before.is_zero(), "bridge did not fill"
        # 1:1 through both legs -> 50 EUR for 50 USD
        assert got.value_text() == "50"
        # alice paid 50 USD (started with 1000)
        assert iou_bal(net, ALICE, USD).value_text() == "950"
        # leftover of her offer (10 USD worth) rests in the book
        # (remainder placed at original rate)

    def test_prefers_cheaper_direct_book(self):
        """With a direct book cheaper than the bridge, the direct fills."""
        net = setup_net()
        # bridge priced 1 EUR = 1.25 USD (worse)
        offer(net, MAKER1, usd(125), STAmount.from_drops(100 * XRP))
        offer(net, MAKER2, STAmount.from_drops(100 * XRP), eur(100))
        # direct book: maker2 sells 100 EUR for 100 USD (1:1, better)
        offer(net, MAKER2, usd(100), eur(100))
        offer(net, ALICE, eur(80), usd(100))
        assert iou_bal(net, ALICE, EUR).value_text() == "80"
        # paid 80 USD direct, not 100 via bridge
        assert iou_bal(net, ALICE, USD).value_text() == "920"
        # maker2's direct offer was consumed for 80
        assert iou_bal(net, MAKER2, USD).value_text() == "10080"

    def test_mixes_direct_and_bridge_for_best_execution(self):
        """Small cheap direct level first, then the bridge fills the rest."""
        net = setup_net()
        # direct: only 20 EUR at 1:1
        offer(net, MAKER1, usd(20), eur(20))
        # bridge: 1 EUR = 1.1 USD composite (10 STR levels)
        offer(net, MAKER1, usd(110), STAmount.from_drops(100 * XRP))
        offer(net, MAKER2, STAmount.from_drops(100 * XRP), eur(100))
        offer(net, ALICE, eur(50), usd(60))
        # 20 direct at 1.0 (20 USD) + 30 bridged at 1.1 (33 USD) = 53 USD
        # (the bridge buys whole drops of STR, so the USD side may round
        # a fraction of a drop against the taker — reference offer
        # arithmetic rounds in the maker's favor the same way)
        assert iou_bal(net, ALICE, EUR).value_text() == "50"
        from fractions import Fraction

        paid = Fraction(1000) - Fraction(iou_bal(net, ALICE, USD).value_text())
        assert Fraction(53) <= paid < Fraction(53) + Fraction(1, 10**5), paid

    def test_threshold_respected_no_overpriced_fill(self):
        """Bridge pricier than the taker's limit: nothing crosses, the
        offer rests."""
        net = setup_net()
        offer(net, MAKER1, usd(200), STAmount.from_drops(100 * XRP))  # 2 USD/STR
        offer(net, MAKER2, STAmount.from_drops(100 * XRP), eur(100))
        # alice offers max 1.2 USD/EUR; bridge costs 2.0
        before = iou_bal(net, ALICE, USD)
        offer(net, ALICE, eur(50), usd(60))
        assert iou_bal(net, ALICE, EUR).is_zero()
        assert iou_bal(net, ALICE, USD) == before  # nothing spent

    def test_partial_bridge_when_legs_dry_up(self):
        """Bridge capacity below the ask: fills what exists, rests the rest."""
        net = setup_net()
        offer(net, MAKER1, usd(30), STAmount.from_drops(30 * XRP))
        offer(net, MAKER2, STAmount.from_drops(30 * XRP), eur(30))
        offer(net, ALICE, eur(50), usd(60))
        assert iou_bal(net, ALICE, EUR).value_text() == "30"
        # 30 USD spent at 1:1 composite
        assert iou_bal(net, ALICE, USD).value_text() == "970"
