"""LedgerCleaner repair: broken/missing stored ledgers are re-acquired
from peers and re-persisted (reference: LedgerCleaner.cpp's acquire
path), via the per-acquisition callback seam in InboundLedgers."""

from __future__ import annotations

import threading
import time
from types import SimpleNamespace

from stellard_tpu.node import Config, Node
from stellard_tpu.node.inbound import InboundLedgers, serve_get_ledger
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import sfAmount, sfDestination
from stellard_tpu.protocol.stamount import STAmount
from stellard_tpu.protocol.sttx import SerializedTransaction
from stellard_tpu.state.ledger import Ledger

XRP = 1_000_000


def _build_history(node: Node, ledgers: int = 3, per: int = 5):
    master = node.master_keys
    seq = 1
    for _ in range(ledgers):
        for _ in range(per):
            dest = KeyPair.from_passphrase(f"clean-{seq}")
            tx = SerializedTransaction.build(
                TxType.ttPAYMENT, master.account_id, seq, 10,
                {
                    sfAmount: STAmount.from_drops(100 * XRP),
                    sfDestination: dest.account_id,
                },
            )
            tx.sign(master)
            ter, _ = node.submit(tx)
            assert int(ter) == 0, ter
            seq += 1
        node.close_ledger()


class TestCleanerRepair:
    def test_missing_ledgers_reacquired_from_peer(self, tmp_path):
        # source node with full history
        src = Node(Config(standalone=True, signature_backend="cpu")).setup()
        _build_history(src)

        # victim: has the HEADERS (it knew these ledgers) but an empty
        # NodeStore — every load fails, as after store loss/corruption
        victim = Node(Config(
            standalone=True, signature_backend="cpu",
            database_path=str(tmp_path / "victim.db"),
        )).setup()
        seqs = src.txdb.ledger_seqs()
        for s in seqs:
            hdr_led = src.ledger_master.get_ledger_by_seq(s)
            if hdr_led is not None:
                victim.txdb.save_ledger_header(hdr_led)

        # loopback acquisition plane: GetLedger requests answer from the
        # source's chain synchronously (the TCP overlay's role)
        def loopback(msg):
            led = src.ledger_master.get_ledger_by_hash(msg.ledger_hash)
            reply = serve_get_ledger(led, msg)
            if reply is not None:
                inbound.take_ledger_data(reply)

        inbound = InboundLedgers(send=loopback, hash_batch=victim.hasher)
        victim.overlay = SimpleNamespace(
            node=SimpleNamespace(lock=threading.RLock(), inbound=inbound)
        )

        victim.ledger_cleaner.start()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            st = victim.ledger_cleaner.get_json()
            if st["state"] == "done":
                break
            time.sleep(0.05)
        assert st["state"] == "done"
        assert st["failure_count"] >= len(seqs) - 1
        assert st["repairs_requested"] >= 1
        assert st["repaired"] >= 1, st

        # the repaired ledgers genuinely load from the victim's store now
        repaired_loads = 0
        for s in seqs:
            hdr = victim.txdb.get_ledger_header(seq=s)
            if hdr is None:
                continue
            try:
                led = Ledger.load(
                    victim.nodestore, hdr["hash"], hash_batch=victim.hasher
                )
            except (KeyError, ValueError):
                continue
            assert led.seq == s
            repaired_loads += 1
        assert repaired_loads >= st["repaired"] >= 1

        src.stop()
        victim.stop()
