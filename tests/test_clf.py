"""Stellar CLF plane: SQL entry mirror + atomic LCL state + crash resume.

Reference behaviors (SURVEY §2.4 'stellar CLF layer', VERDICT r2 Missing
#6 — /root/reference/src/ledger/):
- every close commits the entry-row delta + LCL pointer in ONE SQL
  transaction (LedgerDatabase ScopedTransaction, LedgerMaster::
  commitLedgerClose),
- the typed tables (accounts/trustlines/offers) mirror the state tree
  (AccountEntry/TrustLine/OfferEntry),
- a process killed between closes resumes via the CLF pointer to the
  identical ledger hash (loadLastKnownCLF), with the mirror intact,
- a mirror that is out of lockstep rebuilds via the full import path
  (importLedgerState).
"""

from __future__ import annotations

import os

import pytest

from stellard_tpu.node.config import Config
from stellard_tpu.node.node import Node
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import (
    sfAmount,
    sfDestination,
    sfLimitAmount,
    sfTakerGets,
    sfTakerPays,
)
from stellard_tpu.protocol.stamount import STAmount, currency_from_iso
from stellard_tpu.protocol.sttx import SerializedTransaction
from stellard_tpu.state.clf import CLFMirror, LedgerSqlDatabase

XRP = 1_000_000
USD = currency_from_iso("USD")


def make_node(tmp_path, start_up="fresh") -> Node:
    cfg = Config(
        standalone=True,
        signature_backend="cpu",
        start_up=start_up,
        database_path=str(tmp_path / "tx.db"),
        node_db_type="sqlite",
        node_db_path=str(tmp_path / "nodestore.db"),
    )
    return Node(cfg).setup()


def stop_node(n: Node) -> None:
    n.verify_plane.stop()
    n.job_queue.stop()
    n.txdb.close()
    n.clf.db.close()
    n.nodestore.close()


def payment(key, seq, dest, drops, fee=10):
    tx = SerializedTransaction.build(
        TxType.ttPAYMENT, key.account_id, seq, fee,
        {sfAmount: STAmount.from_drops(drops), sfDestination: dest},
    )
    tx.sign(key)
    return tx


class TestMirror:
    def test_rows_follow_closes_and_deltas(self, tmp_path):
        node = make_node(tmp_path)
        master = node.master_keys
        alice = KeyPair.from_passphrase("alice")
        bob = KeyPair.from_passphrase("bob")
        try:
            node.submit(payment(master, 1, alice.account_id, 1000 * XRP))
            node.submit(payment(master, 2, bob.account_id, 500 * XRP))
            node.close_ledger()
            assert node.clf.db.count("accounts") == 3
            # trust line + offer land in their tables
            trust = SerializedTransaction.build(
                TxType.ttTRUST_SET, alice.account_id, 1, 10,
                {sfLimitAmount: STAmount.from_iou(
                    USD, master.account_id, 100, 0)},
            )
            trust.sign(alice)
            node.submit(trust)
            offer = SerializedTransaction.build(
                TxType.ttOFFER_CREATE, bob.account_id, 1, 10,
                {sfTakerPays: STAmount.from_iou(
                    USD, master.account_id, 5, 0),
                 sfTakerGets: STAmount.from_drops(5 * XRP)},
            )
            offer.sign(bob)
            node.submit(offer)
            node.close_ledger()
            assert node.clf.db.count("trustlines") == 1
            assert node.clf.db.count("offers") == 1
            # sanity: the account row carries live values
            row = node.clf.db.query(
                "SELECT balance, sequence FROM accounts WHERE account_id=?",
                (alice.account_id.hex(),),
            )[0]
            assert row[0] < 1000 * XRP  # paid trust-set fee
            assert row[1] == 2
            # LCL pointer tracks the chain
            assert node.clf.last_closed_hash == (
                node.ledger_master.closed_ledger().hash()
            )
            assert node.clf.commits >= 1
        finally:
            stop_node(node)

    def test_crash_resume_identical_hash(self, tmp_path):
        node = make_node(tmp_path)
        master = node.master_keys
        alice = KeyPair.from_passphrase("alice")
        try:
            node.submit(payment(master, 1, alice.account_id, 777 * XRP))
            node.close_ledger()
            node.submit(payment(master, 2, alice.account_id, 111 * XRP))
            node.close_ledger()
            want_hash = node.ledger_master.closed_ledger().hash()
            want_seq = node.ledger_master.closed_ledger().seq
        finally:
            # abrupt stop: no graceful save beyond the per-close commits
            stop_node(node)

        node2 = make_node(tmp_path, start_up="load")
        try:
            got = node2.ledger_master.closed_ledger()
            assert got.hash() == want_hash
            assert got.seq == want_seq
            # mirror survived too and matches the resumed chain
            assert node2.clf.last_closed_hash == want_hash
            assert node2.clf.db.count("accounts") == 2
            # and the chain keeps going
            node2.submit(payment(node2.master_keys, 3, alice.account_id, XRP))
            node2.close_ledger()
            assert node2.ledger_master.closed_ledger().seq == want_seq + 1
        finally:
            stop_node(node2)

    def test_atomicity_on_failed_commit(self, tmp_path):
        """A failure mid-commit must roll back rows AND state pointer."""
        db = LedgerSqlDatabase(str(tmp_path / "clf.db"))
        db.set_state("LastClosedLedger", b"\x01" * 32)
        try:
            with db.transaction():
                db.set_state("LastClosedLedger", b"\x02" * 32)
                raise RuntimeError("crash mid-commit")
        except RuntimeError:
            pass
        assert db.get_state("LastClosedLedger") == b"\x01" * 32
        db.close()

    def test_out_of_lockstep_triggers_full_import(self, tmp_path):
        node = make_node(tmp_path)
        master = node.master_keys
        alice = KeyPair.from_passphrase("alice")
        try:
            node.submit(payment(master, 1, alice.account_id, 1000 * XRP))
            node.close_ledger()
            # wreck the mirror pointer to simulate divergence
            node.clf.db.set_state("LastClosedLedger", b"\x99" * 32)
            before = node.clf.full_imports
            node.submit(payment(master, 2, alice.account_id, 10 * XRP))
            node.close_ledger()
            assert node.clf.full_imports == before + 1
            assert node.clf.last_closed_hash == (
                node.ledger_master.closed_ledger().hash()
            )
            assert node.clf.db.count("accounts") == 2
        finally:
            stop_node(node)
