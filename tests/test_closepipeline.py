"""Ledger-close pipeline: ordered async persistence off the close path.

Covers the pipeline contracts the node relies on:
- equivalence: a multi-ledger flood closed through the pipeline yields
  byte-identical ledger hashes, per-tx results, and stored history vs
  the serial close path;
- drain-on-stop: nothing persisted is lost and the CLF resume pointer
  lands on the last closed ledger;
- read-your-writes: header/txn fetches for a queued-but-unpersisted
  ledger resolve from the in-flight entry;
- backpressure: a full queue blocks the submitter instead of growing;
- strict order: the CLF pointer never observes N+1 before N;
- metrics: stage histograms + queue gauges surface in get_counts /
  server_state.
"""

import threading

from stellard_tpu.node.closepipeline import ClosePipeline, LatencyHist
from stellard_tpu.node.config import Config
from stellard_tpu.node.node import Node
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import sfAmount, sfDestination
from stellard_tpu.protocol.stamount import STAmount
from stellard_tpu.protocol.sttx import SerializedTransaction
from stellard_tpu.rpc.handlers import Context, dispatch

MASTER = KeyPair.from_passphrase("masterpassphrase")
DESTS = [KeyPair.from_passphrase(f"cp-dest-{i}").account_id for i in range(4)]


def _payments(n, start_seq=1):
    txs = []
    for i in range(n):
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, MASTER.account_id, start_seq + i, 10,
            {sfAmount: STAmount.from_drops(250_000_000),
             sfDestination: DESTS[i % len(DESTS)]},
        )
        tx.sign(MASTER)
        txs.append(tx)
    return txs


def _drive(node, txs, per_ledger):
    """Submit + close every per_ledger txs -> (hashes, {txid: int(ter)}).
    Closes via ops.accept_ledger — the PIPELINED path (Node.close_ledger
    is the synchronous-durable test convenience and would flush)."""
    hashes = []
    results_all = {}
    for start in range(0, len(txs), per_ledger):
        for tx in txs[start : start + per_ledger]:
            node.submit(SerializedTransaction.from_bytes(tx.serialize()))
        closed, results = node.ops.accept_ledger()
        hashes.append(closed.hash())
        results_all.update({k: int(v) for k, v in results.items()})
    return hashes, results_all


class TestEquivalence:
    def test_pipelined_flood_matches_serial(self):
        txs = _payments(90)
        runs = {}
        for mode, enabled in (("pipelined", True), ("serial", False)):
            node = Node(Config(close_pipeline_enabled=enabled)).setup()
            hashes, results = _drive(node, txs, per_ledger=30)
            assert node.close_pipeline.flush(timeout=60)
            stored = [
                node.txdb.get_transaction(tx.txid()) for tx in txs
            ]
            headers = [
                node.txdb.get_ledger_header(seq=s)
                for s in range(2, 2 + len(hashes))
            ]
            clf = node.clf.last_closed_hash
            runs[mode] = (hashes, results, stored, headers, clf)
            node.stop()

        p, s = runs["pipelined"], runs["serial"]
        assert p[0] == s[0], "ledger hashes diverge between modes"
        assert p[1] == s[1], "per-tx results diverge between modes"
        assert all(r is not None for r in p[2]), "pipelined run lost tx rows"
        assert p[2] == s[2], "stored tx rows diverge between modes"
        assert all(h is not None for h in p[3]), "pipelined run lost headers"
        assert p[3] == s[3], "stored headers diverge between modes"
        assert p[4] == s[4] == p[0][-1], "CLF pointer not on the last close"

    def test_serial_mode_bypasses_worker(self):
        node = Node(Config(close_pipeline_enabled=False)).setup()
        _drive(node, _payments(10), per_ledger=10)
        assert node.close_pipeline.persisted == 0
        assert node.txdb.get_ledger_header(seq=2) is not None
        node.stop()


class TestDrainOnStop:
    def test_stop_drains_everything_queued(self, tmp_path):
        from stellard_tpu.node.txdb import TxDatabase
        from stellard_tpu.state.clf import LedgerSqlDatabase

        db = str(tmp_path / "drain.db")
        node = Node(Config(close_pipeline_depth=16, database_path=db)).setup()
        txs = _payments(60)
        hashes, _ = _drive(node, txs, per_ledger=15)
        # stop immediately — whatever is still queued must persist first
        node.stop()
        # reopen the FILES: drain-on-stop means everything closed before
        # stop() is durable and the CLF pointer is on the last close
        txdb = TxDatabase(db)
        try:
            for seq in range(2, 2 + len(hashes)):
                assert txdb.get_ledger_header(seq=seq) is not None
            for tx in txs:
                assert txdb.get_transaction(tx.txid()) is not None
        finally:
            txdb.close()
        clf = LedgerSqlDatabase(db + ".clf")
        try:
            assert clf.get_state("LastClosedLedger") == hashes[-1]
        finally:
            clf.close()


class TestReadYourWrites:
    def _gated_node(self):
        """Node whose pipeline save stage blocks until `gate` is set, so a
        close stays queued-but-unpersisted for the duration of a test."""
        node = Node(Config()).setup()
        gate = threading.Event()
        inner = node.close_pipeline.save_stage

        def blocking_save(led):
            gate.wait(timeout=30)
            inner(led)

        node.close_pipeline.save_stage = blocking_save
        return node, gate

    def test_queued_ledger_header_and_txns_resolve(self):
        node, gate = self._gated_node()
        try:
            txs = _payments(5)
            for tx in txs:
                node.submit(tx)
            closed, _ = node.ops.accept_ledger()
            h = closed.hash()
            txid = txs[0].txid()
            # not yet in the stores
            assert node.txdb.get_transaction(txid) is None
            assert node.txdb.get_ledger_header(seq=closed.seq) is None
            # in-flight entry resolves by hash and by seq
            assert node.close_pipeline.get(h) is closed
            assert node.close_pipeline.get_by_seq(closed.seq) is closed
            # the tx RPC serves the queued tx
            out = dispatch(Context(node, {"transaction": txid.hex()}), "tx")
            assert out.get("ledger_index") == closed.seq
            assert "error" not in out
            # the ledger RPC resolves the queued seq
            out = dispatch(
                Context(node, {"ledger_index": str(closed.seq)}), "ledger"
            )
            assert "error" not in out
            # fetch_fallback (history-cache path) sees the in-flight entry
            assert node.ledger_master.fetch_fallback(h) is closed
        finally:
            gate.set()
            assert node.close_pipeline.flush(timeout=60)
            # after persist the stores serve it and the entry is gone
            assert node.txdb.get_transaction(txs[0].txid()) is not None
            assert node.close_pipeline.get(h) is None
            node.stop()


    def test_account_tx_sees_just_closed_ledger(self):
        """account_tx rides the SQL index, so it WAITS for the drain
        rather than merging in-flight entries — a tx reported COMMITTED
        must appear in account history immediately after the close."""
        node = Node(Config()).setup()
        try:
            txs = _payments(3)
            for tx in txs:
                node.submit(tx)
            node.ops.accept_ledger()  # pipelined: no flush
            out = dispatch(
                Context(node, {"account": MASTER.human_account_id}),
                "account_tx",
            )
            assert "error" not in out, out
            got = {t["tx"]["hash"].lower() for t in out["transactions"]}
            assert {tx.txid().hex() for tx in txs} <= got
        finally:
            node.stop()


class TestBackpressureAndOrder:
    def test_full_queue_blocks_submitter(self):
        release = threading.Event()
        started = threading.Event()
        order = []

        def slow_save(led):
            started.set()
            release.wait(timeout=30)

        pipe = ClosePipeline(
            save_stage=slow_save,
            txdb_stage=lambda led, results: None,
            clf_stage=lambda led: order.append(led.seq),
            depth=1,
        )

        class FakeLedger:
            def __init__(self, seq):
                self.seq = seq

            def hash(self):
                return self.seq.to_bytes(32, "big")

        pipe.submit_close(FakeLedger(1), {})  # drains into the worker
        assert started.wait(timeout=10)
        pipe.submit_close(FakeLedger(2), {})  # fills the depth-1 queue

        blocked_done = threading.Event()
        t = threading.Thread(
            target=lambda: (pipe.submit_close(FakeLedger(3), {}),
                            blocked_done.set()),
        )
        t.start()
        assert not blocked_done.wait(timeout=0.5), "submit did not block"
        release.set()
        assert blocked_done.wait(timeout=10), "submit never unblocked"
        t.join()
        assert pipe.stop(timeout=30)
        # strict order: CLF commits observed 1, 2, 3 — never out of order
        assert order == [1, 2, 3]
        assert pipe.backpressure_waits >= 1

    def test_stop_during_backpressure_fails_the_blocked_submitter(self):
        """stop() while a submitter is blocked in backpressure: the entry
        must take the on_failed path, never strand with no worker left."""
        release = threading.Event()
        failed = threading.Event()

        def slow_save(led):
            release.wait(timeout=30)

        pipe = ClosePipeline(
            save_stage=slow_save,
            txdb_stage=lambda led, results: None,
            clf_stage=lambda led: None,
            depth=1,
        )

        class FakeLedger:
            def __init__(self, seq):
                self.seq = seq

            def hash(self):
                return self.seq.to_bytes(32, "big")

        pipe.submit_close(FakeLedger(1), {})  # drains into the worker
        pipe.submit_close(FakeLedger(2), {})  # fills the depth-1 queue
        t = threading.Thread(
            target=lambda: pipe.submit_close(
                FakeLedger(3), {}, on_failed=failed.set
            )
        )
        t.start()
        # begin stop() while the WORKER is still blocked in the save
        # stage: the queue stays full, so the blocked submitter can only
        # leave its wait via the _stopping path — deterministic
        stopper = threading.Thread(target=lambda: pipe.stop(timeout=30))
        stopper.start()
        assert failed.wait(timeout=10), (
            "blocked submitter's on_failed never fired"
        )
        release.set()  # let the worker drain 1 and 2; stop() completes
        stopper.join(timeout=30)
        t.join(timeout=10)
        assert not t.is_alive(), "submitter still blocked after stop()"
        assert pipe.pending() == 0, "entry stranded in a dead pipeline"

    def test_failed_persist_releases_accounting_and_continues(self):
        failures = []
        boom = {"on": True}

        def bad_txdb(led, results):
            if boom["on"]:
                raise RuntimeError("disk on fire")

        pipe = ClosePipeline(
            save_stage=lambda led: None,
            txdb_stage=bad_txdb,
            clf_stage=lambda led: None,
            depth=4,
        )

        class FakeLedger:
            def __init__(self, seq):
                self.seq = seq

            def hash(self):
                return self.seq.to_bytes(32, "big")

        pipe.submit_close(FakeLedger(1), {}, on_failed=lambda: failures.append(1))
        assert pipe.flush(timeout=10)
        assert failures == [1] and pipe.failed == 1
        boom["on"] = False
        done = []
        pipe.submit_close(FakeLedger(2), {}, done=lambda r: done.append(2))
        assert pipe.flush(timeout=10)
        assert done == [2], "worker died after a failed persist"
        assert pipe.stop(timeout=10)


class TestMetrics:
    def test_counts_and_server_state_surface_pipeline(self):
        node = Node(Config()).setup()
        _drive(node, _payments(10), per_ledger=5)
        assert node.close_pipeline.flush(timeout=60)
        counts = dispatch(Context(node, {}), "get_counts")
        cp = counts["close_pipeline"]
        assert cp["persisted"] == 2
        assert set(cp["stages"]) == {
            "queue_wait", "nodestore", "txdb", "clf", "total"
        }
        assert cp["stages"]["total"]["count"] == 2
        assert cp["stages"]["total"]["p50_ms"] > 0
        assert counts["persist_backlog"] == 0
        state = dispatch(Context(node, {}), "server_state")
        assert state["state"]["close_pipeline"]["depth"] == 0
        node.stop()

    def test_latency_hist_quantiles(self):
        h = LatencyHist()
        assert h.quantile(0.5) == 0.0
        for ms in (0.5, 1.5, 3.0, 8.0, 40.0):
            h.record(ms)
        j = h.get_json()
        assert j["count"] == 5
        assert j["max_ms"] == 40.0
        assert j["p50_ms"] == 5.0  # bucket upper bound holding the median
        assert h.quantile(1.0) == 50.0


class TestConfigKnobs:
    def test_close_pipeline_section_parses(self):
        cfg = Config.from_ini(
            "[close_pipeline]\nenabled=0\ndepth=3\n"
        )
        assert cfg.close_pipeline_enabled is False
        assert cfg.close_pipeline_depth == 3
        cfg = Config.from_ini("[close_pipeline]\nenabled=1\n")
        assert cfg.close_pipeline_enabled is True
        assert Config().close_pipeline_enabled is True
