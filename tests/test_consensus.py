"""Consensus plane unit tests: timing rules, disputed-tx avalanche,
validation/proposal signing, tx sets, validations store quorum."""

import hashlib

import pytest

from stellard_tpu.consensus import (
    DisputedTx,
    LedgerProposal,
    STValidation,
    TxSet,
    ValidationsStore,
    have_consensus,
    next_close_resolution,
    should_close,
)
from stellard_tpu.consensus.timing import (
    LEDGER_VAL_INTERVAL,
    avalanche_threshold,
)
from stellard_tpu.protocol.keys import KeyPair


def kp(n: int) -> KeyPair:
    return KeyPair.from_seed(hashlib.sha256(bytes([n]) * 4).digest())


H = lambda n: hashlib.sha256(bytes([n])).digest()


# -- timing ---------------------------------------------------------------


class TestShouldClose:
    def test_minimum_open_window(self):
        # even with txns, never close inside LEDGER_MIN_CLOSE
        assert not should_close(True, 4, 0, 1000, 1000)

    def test_tx_after_window_closes(self):
        assert should_close(True, 4, 0, 3000, 3000)

    def test_idle_waits_for_interval(self):
        assert not should_close(False, 4, 0, 9000, 9000, idle_interval=15)
        assert should_close(False, 4, 0, 15000, 15000, idle_interval=15)

    def test_majority_closed_forces_close(self):
        # 3 of 4 proposers already closed → follow even inside min window
        assert should_close(False, 4, 3, 500, 500)


class TestHaveConsensus:
    def test_missing_proposers_slow_down_but_cannot_deadlock(self):
        # <3/4 of last round's proposers present: wait one extra
        # prev-round-time for stragglers...
        assert not have_consensus(4, 2, 2, since_consensus_ms=3500,
                                  prev_round_ms=3000)
        # ...then judge on who is actually here (a crashed validator must
        # not halt the network forever)
        assert have_consensus(4, 2, 2, since_consensus_ms=6500,
                              prev_round_ms=3000)

    def test_eighty_pct_locks(self):
        # 3 peers + us, all agree: (3*100+100)/4 = 100
        assert have_consensus(4, 3, 3)
        # 3 peers, only 2 agree: (200+100)/4 = 75 < 80
        assert not have_consensus(4, 3, 2)

    def test_single_node_network(self):
        assert have_consensus(1, 0, 0)


class TestCloseResolution:
    def test_agree_tightens_on_stride(self):
        assert next_close_resolution(30, True, 8) == 20
        assert next_close_resolution(30, True, 7) == 30

    def test_disagree_loosens_every_seq(self):
        assert next_close_resolution(30, False, 5) == 60

    def test_clamped_at_ends(self):
        assert next_close_resolution(10, True, 8) == 10
        assert next_close_resolution(120, False, 3) == 120

    def test_avalanche_ladder(self):
        assert avalanche_threshold(0) == 50
        assert avalanche_threshold(50) == 65
        assert avalanche_threshold(85) == 70
        assert avalanche_threshold(200) == 95


# -- disputed tx ----------------------------------------------------------


class TestDisputedTx:
    def test_holds_yes_with_majority(self):
        d = DisputedTx(H(1), b"blob", our_vote=True)
        for i in range(3):
            d.set_vote(H(10 + i), True)
        d.set_vote(H(20), False)
        assert not d.update_vote(10, proposing=True)
        assert d.our_vote

    def test_flips_no_when_outvoted(self):
        d = DisputedTx(H(1), b"blob", our_vote=True)
        for i in range(4):
            d.set_vote(H(10 + i), False)
        # weight = 100/5 = 20 < 50
        assert d.update_vote(10, proposing=True)
        assert not d.our_vote

    def test_escalating_threshold_flips_marginal_yes(self):
        # 60% yes passes at the start (>50) but fails late (>70)
        d = DisputedTx(H(1), b"blob", our_vote=True)
        for i in range(6):
            d.set_vote(H(10 + i), True)
        for i in range(4):
            d.set_vote(H(30 + i), False)
        assert not d.update_vote(10, proposing=True)  # 63% > 50
        assert d.update_vote(90, proposing=True)  # 63% < 70 → flip
        assert not d.our_vote

    def test_observer_adopts_majority(self):
        d = DisputedTx(H(1), b"", our_vote=False)
        d.set_vote(H(2), True)
        assert d.update_vote(0, proposing=False)
        assert d.our_vote


# -- proposal / validation signing ---------------------------------------


class TestLedgerProposal:
    def test_sign_verify_roundtrip(self):
        p = LedgerProposal(H(1), 0, H(2), 1234)
        p.sign(kp(1))
        assert p.check_sign()

    def test_tamper_detected(self):
        p = LedgerProposal(H(1), 0, H(2), 1234)
        p.sign(kp(1))
        q = LedgerProposal(H(1), 0, H(3), 1234, p.node_public, p.signature)
        assert not q.check_sign()

    def test_advanced_increments_seq(self):
        p = LedgerProposal(H(1), 0, H(2), 30)
        q = p.advanced(H(3), 60)
        assert q.propose_seq == 1 and q.tx_set_hash == H(3)
        assert p.bowout().is_bowout()


class TestSTValidation:
    def test_sign_verify_roundtrip(self):
        v = STValidation.build(H(5), signing_time=999, ledger_seq=7)
        v.sign(kp(2))
        assert v.is_valid()
        assert v.ledger_hash == H(5)
        assert v.ledger_seq == 7
        assert v.is_full

    def test_wire_roundtrip(self):
        v = STValidation.build(H(5), signing_time=999, ledger_seq=7)
        v.sign(kp(2))
        w = STValidation.from_bytes(v.serialize())
        assert w.is_valid()
        assert w.signer == kp(2).public
        assert w.signing_hash() == v.signing_hash()

    def test_bad_sig_rejected(self):
        v = STValidation.build(H(5), signing_time=999)
        v.sign(kp(2))
        v.obj[__import__("stellard_tpu.protocol.sfields", fromlist=["sfSigningTime"]).sfSigningTime] = 1000
        assert not STValidation.from_bytes(v.serialize()).is_valid()


# -- tx set ---------------------------------------------------------------


class TestTxSet:
    def test_hash_is_order_independent(self):
        a, b = TxSet(), TxSet()
        items = [(H(i), b"tx%d" % i) for i in range(8)]
        for t, blob in items:
            a.add(t, blob)
        for t, blob in reversed(items):
            b.add(t, blob)
        assert a.hash() == b.hash()

    def test_differences(self):
        a, b = TxSet(), TxSet()
        for i in range(4):
            a.add(H(i), b"x")
        for i in range(2, 6):
            b.add(H(i), b"x")
        assert a.differences(b) == {H(0), H(1), H(4), H(5)}

    def test_copy_and_remove(self):
        a = TxSet()
        a.add(H(1), b"x")
        c = a.copy()
        c.remove(H(1))
        assert H(1) in a and H(1) not in c and a.hash() != c.hash()


# -- validations store ----------------------------------------------------


class TestValidationsStore:
    def _store(self, trusted: set, now: list):
        return ValidationsStore(lambda pk: pk in trusted, lambda: now[0])

    def test_equivocating_signer_single_vote_in_election(self):
        """A signer that validates TWO different hashes for the same
        round contributes one count to EACH hash bucket (per-hash store,
        reference Validations.cpp addValidation) but only its LATEST
        validation to the current-ledger election — equivocation cannot
        double a node's electoral weight."""
        k = kp(1)
        now = [10_000]
        store = self._store({k.public}, now)
        v1 = STValidation.build(H(1), signing_time=now[0], ledger_seq=5)
        v1.sign(k)
        v2 = STValidation.build(H(2), signing_time=now[0] + 1, ledger_seq=5)
        v2.sign(k)
        assert store.add(v1)
        assert store.add(v2)
        assert store.trusted_count_for(H(1)) == 1
        assert store.trusted_count_for(H(2)) == 1
        weights = store.current_ledger_weights()
        assert weights.get(H(2)) == 1
        assert H(1) not in weights, "equivocator kept two current votes"
        # re-sending the SAME validation never double-counts
        store.add(v2)
        assert store.trusted_count_for(H(2)) == 1

    def test_quorum_counts_trusted_only(self):
        keys = [kp(i) for i in range(4)]
        trusted = {k.public for k in keys[:3]}
        now = [10_000]
        store = self._store(trusted, now)
        for k in keys:
            v = STValidation.build(H(9), signing_time=now[0], ledger_seq=3)
            v.sign(k)
            store.add(v)
        assert store.trusted_count_for(H(9)) == 3
        assert len(store.validations_for(H(9))) == 4

    def test_stale_validations_expire_from_current(self):
        k = kp(1)
        now = [10_000]
        store = self._store({k.public}, now)
        v = STValidation.build(H(9), signing_time=now[0])
        v.sign(k)
        assert store.add(v)
        assert len(store.current_trusted()) == 1
        now[0] += LEDGER_VAL_INTERVAL + 1
        assert store.current_trusted() == []

    def test_ledger_weights_election(self):
        keys = [kp(i) for i in range(4)]
        now = [10_000]
        store = self._store({k.public for k in keys}, now)
        for i, k in enumerate(keys):
            h = H(1) if i < 3 else H(2)
            v = STValidation.build(h, signing_time=now[0])
            v.sign(k)
            store.add(v)
        w = store.current_ledger_weights()
        assert w[H(1)] == 3 and w[H(2)] == 1

    def test_newer_validation_replaces_current(self):
        k = kp(1)
        now = [10_000]
        store = self._store({k.public}, now)
        v1 = STValidation.build(H(1), signing_time=now[0])
        v1.sign(k)
        store.add(v1)
        now[0] += 5
        v2 = STValidation.build(H(2), signing_time=now[0])
        v2.sign(k)
        store.add(v2)
        assert store.current_ledger_weights() == {H(2): 1}


# -- byzantine inputs at the unit level -----------------------------------


class _NullAdapter:
    def propose(self, proposal):
        pass

    def share_tx_set(self, txset):
        pass

    def acquire_tx_set(self, set_hash):
        return None

    def send_validation(self, val):
        pass

    def request_ledger_data(self, msg):
        pass

    def relay_disputed_tx(self, blob):
        pass

    def on_accepted(self, ledger, round_ms):
        pass


def _node(keys, quorum=2):
    from stellard_tpu.node.validator import ValidatorNode

    now = [10_000]
    node = ValidatorNode(
        key=keys[0],
        unl={k.public for k in keys},
        adapter=_NullAdapter(),
        quorum=quorum,
        network_time=lambda: now[0],
        clock=lambda: float(now[0]),
    )
    node.start(b"\x07" * 20, close_time=now[0])
    return node, now


class TestByzantineInputs:
    """Hostile consensus inputs must be counted, dropped, and never
    double-counted toward quorum (ISSUE 9 satellite)."""

    def test_conflicting_proposals_one_key_count_once(self):
        keys = [kp(i) for i in range(3)]
        node, _now = _node(keys)
        prev = node.lm.closed_ledger().hash()
        real = LedgerProposal(prev, 0, H(2), 30)
        real.sign(keys[1])
        assert node.handle_proposal(real)
        # same key, same propose_seq, DIFFERENT position: equivocation
        fake = LedgerProposal(prev, 0, H(3), 30)
        fake.sign(keys[1])
        assert not node.handle_proposal(fake)
        assert node.defense["conflicting_proposal"] == 1
        # the first-seen position stands; the proposer counts ONCE
        assert node.round.peer_positions[keys[1].public].tx_set_hash == H(2)
        assert len(node.round.peer_positions) == 1

    def test_duplicate_proposal_counted_and_dropped(self):
        keys = [kp(i) for i in range(3)]
        node, _now = _node(keys)
        prev = node.lm.closed_ledger().hash()
        p = LedgerProposal(prev, 0, H(2), 30)
        p.sign(keys[1])
        assert node.handle_proposal(p)
        replay = LedgerProposal(prev, 0, H(2), 30, p.node_public,
                                p.signature)
        assert not node.handle_proposal(replay)
        assert node.defense["duplicate_proposal"] == 1
        assert node.defense["conflicting_proposal"] == 0
        assert len(node.round.peer_positions) == 1

    def test_bogus_validation_signature_counted_never_stored(self):
        keys = [kp(i) for i in range(3)]
        node, now = _node(keys)
        target = H(9)
        v = STValidation.build(target, signing_time=now[0], ledger_seq=5)
        v.sign(keys[1])
        blob = bytearray(v.serialize())
        # corrupt the signature in the wire image
        tampered = STValidation.from_bytes(bytes(blob))
        from stellard_tpu.protocol.sfields import sfSignature

        sig = bytearray(tampered.signature)
        sig[0] ^= 0xFF
        tampered.obj[sfSignature] = bytes(sig)
        tampered = STValidation.from_bytes(tampered.serialize())
        assert not node.handle_validation(tampered)
        assert node.defense["bad_validation_sig"] == 1
        assert node.validations.trusted_count_for(target) == 0

    def test_untrusted_selfsigned_validation_zero_quorum_weight(self):
        keys = [kp(i) for i in range(3)]
        node, now = _node(keys)
        rogue = kp(77)  # correctly signed, NOT on the UNL
        v = STValidation.build(H(9), signing_time=now[0], ledger_seq=5)
        v.sign(rogue)
        node.handle_validation(v)
        assert node.defense["untrusted_validation"] == 1
        assert node.validations.trusted_count_for(H(9)) == 0

    def test_replayed_stale_validation_counted_not_current(self):
        from stellard_tpu.consensus.timing import LEDGER_VAL_INTERVAL

        keys = [kp(i) for i in range(3)]
        node, now = _node(keys)
        old = STValidation.build(
            H(4), signing_time=now[0] - LEDGER_VAL_INTERVAL - 60,
            ledger_seq=2,
        )
        old.sign(keys[1])
        assert not node.handle_validation(old)
        assert node.defense["stale_validation"] == 1
        # stored for the per-hash record but never a current vote
        assert node.validations.current_trusted() == []
        # replaying it N more times never double-counts toward quorum
        for _ in range(3):
            node.handle_validation(
                STValidation.from_bytes(old.serialize())
            )
        assert node.validations.trusted_count_for(H(4)) == 1

    def test_duplicate_current_validation_counts_once(self):
        keys = [kp(i) for i in range(3)]
        node, now = _node(keys)
        v = STValidation.build(H(9), signing_time=now[0], ledger_seq=5)
        v.sign(keys[1])
        assert node.handle_validation(v)
        assert not node.handle_validation(
            STValidation.from_bytes(v.serialize())
        )
        assert node.defense["duplicate_validation"] == 1
        assert node.validations.trusted_count_for(H(9)) == 1

    def test_conflicting_validations_same_seq_counted(self):
        keys = [kp(i) for i in range(3)]
        node, now = _node(keys)
        v1 = STValidation.build(H(1), signing_time=now[0], ledger_seq=5)
        v1.sign(keys[1])
        node.handle_validation(v1)
        v2 = STValidation.build(H(2), signing_time=now[0] + 1,
                                ledger_seq=5)
        v2.sign(keys[1])
        node.handle_validation(v2)
        assert node.defense["conflicting_validation"] == 1
        # one key, one current electoral vote (the newer statement)
        weights = node.validations.current_ledger_weights()
        assert weights.get(H(2)) == 1 and H(1) not in weights


# -- VerifyPlane integration ----------------------------------------------


class TestBatchedVerifySeam:
    def test_validator_verifies_proposals_via_verify_plane(self):
        from stellard_tpu.consensus.consensus import ConsensusAdapter
        from stellard_tpu.node.validator import ValidatorNode
        from stellard_tpu.node.verifyplane import VerifyPlane

        class NullAdapter(ConsensusAdapter):
            def propose(self, proposal):
                pass

            def share_tx_set(self, txset):
                pass

            def acquire_tx_set(self, set_hash):
                return None

            def send_validation(self, val):
                pass

        plane = VerifyPlane(backend="cpu")
        keys = [kp(i) for i in range(3)]
        unl = {k.public for k in keys}
        now = [10_000]
        node = ValidatorNode(
            key=keys[0],
            unl=unl,
            adapter=NullAdapter(),
            quorum=2,
            network_time=lambda: now[0],
            clock=lambda: now[0] / 1.0,
            verify_many=plane.verify_many,
        )
        node.start(b"\x07" * 20, close_time=now[0])
        prev = node.lm.closed_ledger().hash()
        good = LedgerProposal(prev, 0, H(2), 30)
        good.sign(keys[1])
        assert node.handle_proposal(good)
        bad = LedgerProposal(prev, 1, H(3), 30)
        bad.sign(keys[2])
        bad.tx_set_hash = H(4)  # tamper
        assert not node.handle_proposal(bad)
        val = STValidation.build(prev, signing_time=now[0], ledger_seq=1)
        val.sign(keys[1])
        assert node.handle_validation(val) in (True, False)  # no crash
        assert node.validations.trusted_count_for(prev) == 1
        plane.stop()
