"""Device crypto-plane tests: JAX SHA-512 and Ed25519 kernels vs host
references, plus the backend registry seam.

Runs on the CPU XLA backend (see conftest). The ed25519 kernel compile is
the slow part (~40 s once per batch shape); tests share one shape.
"""

import hashlib
import os
import random

import numpy as np
import pytest

from stellard_tpu.crypto import VerifyRequest, make_hasher, make_verifier
from stellard_tpu.ops import ed25519_ref as ref
from stellard_tpu.ops.sha512_jax import sha512_half_batch
from stellard_tpu.protocol.keys import ED25519_L, KeyPair
from stellard_tpu.utils.hashes import HP_INNER_NODE, prefix_hash


class TestSha512Kernel:
    def test_matches_hashlib_single_block(self):
        msgs = [os.urandom(n) for n in [0, 1, 55, 96, 111]]
        for m, d in zip(msgs, sha512_half_batch(msgs)):
            assert d == hashlib.sha512(m).digest()[:32]

    def test_matches_hashlib_multi_block(self):
        msgs = [os.urandom(516) for _ in range(4)]  # SHAMap inner-node size
        for m, d in zip(msgs, sha512_half_batch(msgs)):
            assert d == hashlib.sha512(m).digest()[:32]

    def test_rejects_mixed_block_counts(self):
        with pytest.raises(ValueError):
            sha512_half_batch([b"a", os.urandom(200)])


class TestFieldArithmetic:
    def test_mul_add_sub_vs_bignum(self):
        import jax.numpy as jnp

        from stellard_tpu.ops import fe25519 as F

        rng = random.Random(3)
        xs = [rng.randrange(F.P) for _ in range(32)]
        ys = [rng.randrange(F.P) for _ in range(32)]
        # limb-major layout: [20, B]
        X = jnp.asarray(np.stack([F.int_to_limbs_np(v) for v in xs], axis=1))
        Y = jnp.asarray(np.stack([F.int_to_limbs_np(v) for v in ys], axis=1))
        mul = np.asarray(F.fe_reduce_full(F.fe_mul(X, Y)))
        sub = np.asarray(F.fe_reduce_full(F.fe_sub(X, Y)))
        add = np.asarray(F.fe_reduce_full(F.fe_add(X, Y)))
        for i in range(32):
            assert F.limbs_to_int(mul[:, i]) == xs[i] * ys[i] % F.P
            assert F.limbs_to_int(sub[:, i]) == (xs[i] - ys[i]) % F.P
            assert F.limbs_to_int(add[:, i]) == (xs[i] + ys[i]) % F.P


def _make_cases(n=32):
    """Mixed valid/invalid signature cases; expected via the Python oracle."""
    rng = random.Random(11)
    k = KeyPair.from_passphrase("edge")
    m = b"\x11" * 32
    good = k.sign(m)
    cases = [
        (bytes(32), m, good),  # y=0 pubkey
        ((1).to_bytes(32, "little"), m, good),  # identity pubkey
        (b"\xff" * 32, m, good),  # invalid encoding
        ((ref.P + 1).to_bytes(32, "little"), m, good),  # non-canonical y
        (k.public, m, b"\xff" * 32 + good[32:]),  # bad R
        (k.public, m, good),  # valid
    ]
    s_int = int.from_bytes(good[32:], "little") + ED25519_L
    if s_int < (1 << 256):
        cases.append((k.public, m, good[:32] + s_int.to_bytes(32, "little")))
    while len(cases) < n:
        kk = KeyPair.from_seed(os.urandom(32))
        mm = os.urandom(32)
        ss = bytearray(kk.sign(mm))
        mode = len(cases) % 3
        if mode == 1:
            ss[rng.randrange(64)] ^= 1 << rng.randrange(8)
        elif mode == 2:
            mm = os.urandom(32)
        cases.append((kk.public, mm, bytes(ss)))
    return cases[:n]


class TestEd25519Kernel:
    def test_kernel_matches_oracle(self):
        from stellard_tpu.ops.ed25519_jax import verify_batch

        cases = _make_cases(32)
        pubs, msgs, sigs = (list(t) for t in zip(*cases))
        got = verify_batch(pubs, msgs, sigs)
        want = np.array([ref.verify(p, m, s) for p, m, s in cases])
        assert np.array_equal(got, want)

    def test_oracle_matches_cryptography_lib(self):
        from stellard_tpu.protocol.keys import verify_signature

        for _ in range(8):
            k = KeyPair.from_seed(os.urandom(32))
            m = os.urandom(32)
            s = k.sign(m)
            assert ref.verify(k.public, m, s)
            assert verify_signature(k.public, m, s)
            bad = bytearray(s)
            bad[5] ^= 2
            assert not ref.verify(k.public, m, bytes(bad))
            assert not verify_signature(k.public, m, bytes(bad))


class TestWireFormats:
    """The raw-bytes wire (STELLARD_WIRE=raw, the default) ships 32-byte
    S/h scalars and expands windows + signed digits ON DEVICE; verdicts
    must be identical to the digits wire, and the device-side signed
    recode must match the host recode bit-for-bit."""

    def test_device_signed_recode_matches_host(self):
        from stellard_tpu.ops import ed25519_jax as ej

        rng = np.random.default_rng(5)
        b = rng.integers(0, 256, (256, 32), dtype=np.uint8)
        b[:, 31] &= 0x1F  # the < 2^253 recode contract
        # carry-chain edge: long runs of 0x77 nibbles (p=carry-propagate)
        b[0, :] = 0x77
        b[1, :16] = 0x78
        b[2, :] = 0
        host = ej._signed_digits_le(b).astype(np.int32)
        dev = np.asarray(ej.expand_h_digits(b))
        assert np.array_equal(host, dev)

    @pytest.mark.slow  # ~1+ min wall clock (both wire kernels compile)
    def test_raw_and_digit_wires_agree(self, monkeypatch):
        from stellard_tpu.ops import ed25519_jax as ej

        cases = _make_cases(16)
        pubs, msgs, sigs = (list(t) for t in zip(*cases))
        monkeypatch.setenv("STELLARD_WIRE", "digits")
        legacy = np.asarray(ej.verify_kernel(
            **ej.prepare_batch(pubs, msgs, sigs)))
        monkeypatch.setenv("STELLARD_WIRE", "raw")
        inp = ej.prepare_batch(pubs, msgs, sigs)
        assert np.asarray(inp["s_windows"]).shape[-1] == 32  # raw bytes
        raw = np.asarray(ej.verify_kernel(**inp))
        assert np.array_equal(legacy, raw)
        want = np.array([ref.verify(p, m, s) for p, m, s in cases])
        assert np.array_equal(raw, want)


class TestBackendSeam:
    def test_registry(self):
        assert make_verifier("cpu").name == "cpu"
        assert make_hasher("tpu").name == "tpu"
        with pytest.raises(KeyError):
            make_verifier("gpu")

    def test_cpu_and_tpu_verifiers_agree(self):
        cases = _make_cases(20)
        reqs = [VerifyRequest(p, m, s) for p, m, s in cases]
        cpu = make_verifier("cpu").verify_batch(reqs)
        tpu = make_verifier("tpu", min_batch=32).verify_batch(reqs)
        # cpu lib may be stricter than libsodium-2014 on weird pubkeys; both
        # must agree on well-formed cases (index >= 7 here)
        assert np.array_equal(cpu[7:], tpu[7:])
        want = np.array([ref.verify(p, m, s) for p, m, s in cases])
        assert np.array_equal(tpu, want)

    def test_hashers_agree(self):
        payloads = [os.urandom(n) for n in (12, 512, 512, 12)]
        prefixes = [HP_INNER_NODE] * 4
        cpu = make_hasher("cpu").prefix_hash_batch(prefixes, payloads)
        tpu = make_hasher("tpu").prefix_hash_batch(prefixes, payloads)
        assert cpu == tpu
        assert cpu[0] == prefix_hash(HP_INNER_NODE, payloads[0])
