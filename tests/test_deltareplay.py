"""Conflict seam of the speculative delta-replay close.

Every test here pins the one property the optimization must never trade
away: a delta-replay close produces BYTE-IDENTICAL ledgers (hash +
per-tx results) to the full serial re-apply, on exactly the workloads
engineered to stress the splice/fallback boundary — same-account bursts
under the canonical shuffle, cross-account conflicts on shared entries,
offers crossing one book, tec fee claims and terPRE_SEQ holds promoted
mid-flood, and a close against a different parent than the open pass
saw (which must force 100% fallback via the parent gate).
"""

from __future__ import annotations

import pytest

from stellard_tpu.engine.engine import TxParams
from stellard_tpu.node.config import Config
from stellard_tpu.node.ledgermaster import CanonicalTXSet, LedgerMaster
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import (
    sfAmount,
    sfDestination,
    sfLimitAmount,
    sfOfferSequence,
    sfTakerGets,
    sfTakerPays,
)
from stellard_tpu.protocol.stamount import STAmount
from stellard_tpu.protocol.ter import TER
from stellard_tpu.protocol.sttx import SerializedTransaction

MASTER = KeyPair.from_passphrase("masterpassphrase")
USD = b"USD" + b"\x00" * 17
OPEN = TxParams.OPEN_LEDGER | TxParams.RETRY


def build(tx_type, kp, seq, fields, fee=10):
    tx = SerializedTransaction.build(tx_type, kp.account_id, seq, fee, fields)
    tx.sign(kp)
    return tx


def fresh(tx):
    """Re-parse so memoized per-object state never leaks across modes."""
    return SerializedTransaction.from_bytes(tx.serialize())


def run_workload(phases, delta_replay):
    """Drive `phases` (list of tx lists, one close per phase) through a
    fresh chain; -> (per-close hashes, per-close sorted results, stats)."""
    lm = LedgerMaster()
    lm.delta_replay = delta_replay
    lm.start_new_ledger(MASTER.account_id, close_time=1000)
    hashes, results_log = [], []
    for i, phase in enumerate(phases):
        for tx in phase:
            ter, ok = lm.do_transaction(fresh(tx), OPEN)
            if ter == TER.terPRE_SEQ:
                lm.add_held_transaction(fresh(tx))
        closed, results = lm.close_and_advance(2000 + i * 30, 30)
        hashes.append(closed.hash())
        results_log.append(sorted(
            (txid.hex(), int(ter)) for txid, ter in results.items()
        ))
    return hashes, results_log, dict(lm.delta_stats)


def assert_identical(phases):
    """Run both modes; byte-identity is the contract. Returns the
    delta-mode stats for workload-specific assertions."""
    h1, r1, stats = run_workload(phases, delta_replay=True)
    h0, r0, _ = run_workload(phases, delta_replay=False)
    assert h1 == h0, "delta-replay close diverged from serial re-apply"
    assert r1 == r0, "per-tx results diverged from serial re-apply"
    return stats


def payment(kp, seq, dest, drops=250_000_000):
    return build(TxType.ttPAYMENT, kp, seq,
                 {sfAmount: STAmount.from_drops(drops), sfDestination: dest})


class TestByteIdentity:
    def test_same_account_burst_splices(self):
        """One account's seq chain: CanonicalTXSet preserves per-account
        order, so every record must splice — and still match serial."""
        dests = [KeyPair.from_passphrase(f"dr-d{i}").account_id
                 for i in range(4)]
        phases = [
            [payment(MASTER, 1 + i, dests[i % 4]) for i in range(20)],
            [payment(MASTER, 21 + i, dests[i % 4]) for i in range(20)],
        ]
        stats = assert_identical(phases)
        assert stats["spliced"] == 40
        assert stats["fallback"] == 0

    def test_cross_account_shared_destination_conflicts(self):
        """Independent senders all paying ONE hot account: the canonical
        shuffle reorders them against submission order, so records
        conflict on the shared destination root and must fall back —
        byte-identically."""
        senders = [KeyPair.from_passphrase(f"dr-s{i}") for i in range(6)]
        hot = KeyPair.from_passphrase("dr-hot").account_id
        fund = [payment(MASTER, 1 + i, s.account_id, 2_000_000_000)
                for i, s in enumerate(senders)]
        work = []
        for rnd in range(3):
            for s in senders:
                work.append(payment(s, 1 + rnd, hot, 210_000_000))
        stats = assert_identical([fund, work])
        total = stats["spliced"] + stats["fallback"]
        assert total == len(fund) + len(work)
        # the shuffle makes SOME conflict order-dependent; the exact
        # split is salt-dependent, but a zero-fallback run would mean
        # the workload exercised nothing
        assert stats["fallback"] > 0
        assert stats["invalidated"] > 0

    def test_offers_crossing_one_book(self):
        """Asks and crossing bids from many accounts on one USD/XRP book
        (plus cancels): book-dir succ walks, partial fills, offer
        deletions — the densest conflict surface we have."""
        gateway = KeyPair.from_passphrase("dr-gw")
        traders = [KeyPair.from_passphrase(f"dr-t{i}") for i in range(5)]
        fund = [payment(MASTER, 1 + i, who.account_id, 1_500_000_000)
                for i, who in enumerate([gateway] + traders)]
        trust = [
            build(TxType.ttTRUST_SET, t, 1,
                  {sfLimitAmount: STAmount.from_iou(
                      USD, gateway.account_id, 10**9, 0)})
            for t in traders
        ]
        seqs = {gateway.account_id: 1}
        for t in traders:
            seqs[t.account_id] = 2
        work, live = [], []
        for i in range(40):
            if i % 7 == 6 and live:
                kp, oseq = live.pop(0)
                tx = build(TxType.ttOFFER_CANCEL, kp, seqs[kp.account_id],
                           {sfOfferSequence: oseq})
            elif i % 2 == 0:
                price = 50 + (i % 15)
                tx = build(
                    TxType.ttOFFER_CREATE, gateway,
                    seqs[gateway.account_id],
                    {sfTakerPays: STAmount.from_drops(price * 1_000_000),
                     sfTakerGets: STAmount.from_iou(
                         USD, gateway.account_id, 100, 0)},
                )
                live.append((gateway, seqs[gateway.account_id]))
            else:
                kp = traders[i % len(traders)]
                price = 40 + (i % 20)  # overlaps the asks -> crossings
                tx = build(
                    TxType.ttOFFER_CREATE, kp, seqs[kp.account_id],
                    {sfTakerPays: STAmount.from_iou(
                        USD, gateway.account_id, 100, 0),
                     sfTakerGets: STAmount.from_drops(price * 1_000_000)},
                )
                live.append((kp, seqs[kp.account_id]))
            seqs[tx.account] = tx.sequence + 1
            work.append(tx)
        stats = assert_identical([fund, trust, work])
        assert stats["spliced"] + stats["fallback"] > 0

    def test_tec_claim_and_held_promotion_mid_flood(self):
        """A below-reserve payment tec's (fee claim on the final pass
        only — splicing it early would renumber every later meta), and a
        seq-gap hold promotes after the close."""
        d = [KeyPair.from_passphrase(f"dr-h{i}").account_id for i in range(3)]
        phase1 = [
            payment(MASTER, 1, d[0]),
            payment(MASTER, 2, d[1], drops=1_000_000),  # below reserve: tec
            payment(MASTER, 3, d[2]),
            payment(MASTER, 5, d[0]),  # GAP: held as terPRE_SEQ
            payment(MASTER, 4, d[1]),  # fills the gap
        ]
        stats = assert_identical([phase1, []])  # close 2 applies the hold
        assert stats["closes"] == 2

    def test_spliced_deletions_offer_create_then_cancel(self):
        """One account creates offers then cancels them in the same
        ledger: the cancel's record carries entry DELETIONS (offer +
        directory pages) that must splice byte-identically."""
        maker = KeyPair.from_passphrase("dr-maker")
        fund = [payment(MASTER, 1, maker.account_id, 2_000_000_000)]
        work = []
        for i in range(4):
            work.append(build(
                TxType.ttOFFER_CREATE, maker, 1 + i,
                {sfTakerPays: STAmount.from_iou(
                    USD, MASTER.account_id, 10, 0),
                 sfTakerGets: STAmount.from_drops(5_000_000)},
            ))
        for i in range(4):
            work.append(build(TxType.ttOFFER_CANCEL, maker, 5 + i,
                              {sfOfferSequence: 1 + i}))
        stats = assert_identical([fund, work])
        # a single account's chain rides the canonical order untouched:
        # creates AND cancels (deletions) all splice
        assert stats["fallback"] == 0
        assert stats["spliced"] == len(fund) + len(work)

    def test_empty_and_repeat_closes(self):
        dests = [KeyPair.from_passphrase("dr-e").account_id]
        stats = assert_identical([[], [payment(MASTER, 1, dests[0])], []])
        # only the close that had open-accepted txs carries a spec state
        # (it is created lazily on first accept), so exactly one close
        # ran the replay context
        assert stats["closes"] == 1
        assert stats["spliced"] == 1


class TestParentGate:
    def test_close_against_different_parent_forces_full_fallback(self):
        """Records speculated against parent P must never splice into a
        close whose parent is P' (consensus moved the chain under us):
        the parent gate forces 100% fallback, and the result still
        matches a from-scratch serial apply."""
        dests = [KeyPair.from_passphrase(f"dr-p{i}").account_id
                 for i in range(3)]
        lm = LedgerMaster()
        lm.start_new_ledger(MASTER.account_id, close_time=1000)
        txs = [payment(MASTER, 1 + i, dests[i % 3]) for i in range(9)]
        for tx in txs:
            ter, ok = lm.do_transaction(fresh(tx), OPEN)
            assert ok, ter
        spec = lm.current._spec_state
        assert spec is not None and len(spec.records) == 9

        # a DIFFERENT parent with the same state: one empty close ahead
        lm2 = LedgerMaster()
        lm2.start_new_ledger(MASTER.account_id, close_time=1000)
        lm2.close_and_advance(2000, 30)
        parent = lm2.closed_ledger()
        assert parent.hash() != lm.closed_ledger().hash()

        def apply_onto(spec_arg):
            target = parent.open_successor()
            txset = CanonicalTXSet(parent.hash())
            for tx in txs:
                txset.insert(fresh(tx))
            results = lm2._apply_transactions(target, txset, spec=spec_arg)
            return target, sorted(
                (txid.hex(), int(ter)) for txid, ter in results.items()
            )

        led_replay, res_replay = apply_onto(spec)
        led_serial, res_serial = apply_onto(None)
        assert led_replay.state_map.get_hash() == led_serial.state_map.get_hash()
        assert led_replay.tx_map.get_hash() == led_serial.tx_map.get_hash()
        assert res_replay == res_serial
        assert lm2.delta_stats["spliced"] == 0
        assert lm2.delta_stats["fallback"] == 9
        assert lm2.last_close["parent_ok"] is False


class TestKnobAndCounters:
    def test_config_knob(self):
        cfg = Config.from_ini("[close]\ndelta_replay=0\n")
        assert cfg.close_delta_replay is False
        cfg = Config.from_ini("[close]\ndelta_replay=1\n")
        assert cfg.close_delta_replay is True
        assert Config().close_delta_replay is True

    def test_server_state_and_get_counts_expose_split(self):
        from stellard_tpu.node.node import Node
        from stellard_tpu.rpc.handlers import Context, Role, dispatch

        n = Node(Config(standalone=True, signature_backend="cpu")).setup()
        try:
            dest = KeyPair.from_passphrase("dr-rpc").account_id
            for i in range(5):
                ter, ok = n.submit(fresh(payment(MASTER, 1 + i, dest)))
                assert ok, ter
            n.close_ledger()

            state = dispatch(
                Context(n, {}, Role.ADMIN), "server_state"
            )["state"]
            assert state["delta_replay"]["enabled"] is True
            assert state["delta_replay"]["spliced"] == 5
            assert state["delta_replay"]["fallback"] == 0
            assert "apply_p50_ms" in state["delta_replay"]

            counts = dispatch(Context(n, {}, Role.ADMIN), "get_counts")
            assert counts["delta_replay"]["closes"] == 1
            assert "invalidated" in counts["delta_replay"]
        finally:
            n.verify_plane.stop()
            n.job_queue.stop()

    def test_disabled_knob_records_nothing(self):
        lm = LedgerMaster()
        lm.delta_replay = False
        lm.start_new_ledger(MASTER.account_id, close_time=1000)
        dest = KeyPair.from_passphrase("dr-off").account_id
        ter, ok = lm.do_transaction(fresh(payment(MASTER, 1, dest)), OPEN)
        assert ok, ter
        assert getattr(lm.current, "_spec_state", None) is None
        lm.close_and_advance(2000, 30)
        assert lm.delta_stats["closes"] == 0
