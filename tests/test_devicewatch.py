"""Device-wedge watchdog: a hung accelerator call must degrade the node
to its CPU backends, never freeze it.

The tunnel's observed failure mode (r3 judge probe, r4 on-chip sessions)
is an indefinite hang with the GIL released. These tests plant a
verifier/hasher that blocks forever and assert the planes detect the
wedge, answer every request via the CPU side, and route around the dead
device from then on. Reference stance: a stalled subsystem is a
loudly-reported fault (LoadManager deadlock detector,
src/ripple_core/functional/LoadManager.cpp:180-214), not a silent freeze.
"""

import threading
import time

import numpy as np
import pytest

from stellard_tpu.crypto.backend import (
    BatchHasher,
    BatchVerifier,
    CpuHasher,
    VerifyRequest,
    WatchdogHasher,
)
from stellard_tpu.node.verifyplane import VerifyPlane
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.state.shamap import SHAMap, SHAMapItem, TNType, compute_hashes
from stellard_tpu.utils import devicewatch
from stellard_tpu.utils.devicewatch import (
    DeviceHealth,
    DeviceWedged,
    call_with_deadline,
)


@pytest.fixture(autouse=True)
def _fresh_health():
    """The process-wide verdict is sticky by design; tests need it fresh."""
    devicewatch.HEALTH.reset()
    yield
    devicewatch.HEALTH.reset()


class _Wedge(BatchVerifier):
    """verify_batch blocks until released (never, by default)."""

    name = "tpu"

    def __init__(self):
        self.release = threading.Event()
        self.calls = 0

    def verify_batch(self, batch):
        self.calls += 1
        self.release.wait()
        return np.ones(len(batch), bool)


class _WedgeHasher(BatchHasher):
    name = "tpu"

    def __init__(self):
        self.release = threading.Event()

    def prefix_hash_batch(self, prefixes, payloads):
        self.release.wait()
        return CpuHasher().prefix_hash_batch(prefixes, payloads)

    def hash_tree(self, root, cancelled=None, cancel_lock=None) -> int:
        self.release.wait()
        lock = cancel_lock if cancel_lock is not None else threading.Lock()
        with lock:
            if cancelled is not None and cancelled.is_set():
                return 0
            return compute_hashes(root)


def _reqs(n: int) -> list[VerifyRequest]:
    kp = KeyPair.from_seed(b"\x11" * 32)
    out = []
    for i in range(n):
        msg = bytes([i % 256]) * 32
        out.append(VerifyRequest(kp.public, msg, kp.sign(msg)))
    return out


class TestCallWithDeadline:
    def test_fast_call_returns(self):
        h = DeviceHealth()
        assert call_with_deadline(lambda: 42, 5.0, health=h) == 42
        assert not h.dead

    def test_timeout_marks_dead_and_raises(self):
        h = DeviceHealth()
        with pytest.raises(DeviceWedged):
            call_with_deadline(
                lambda: threading.Event().wait(), 0.1, health=h
            )
        assert h.dead
        # later calls refuse instantly (no new sacrificial thread wait)
        t0 = time.perf_counter()
        with pytest.raises(DeviceWedged):
            call_with_deadline(lambda: 1, 5.0, health=h)
        assert time.perf_counter() - t0 < 0.5

    def test_exceptions_propagate(self):
        h = DeviceHealth()
        with pytest.raises(ValueError):
            call_with_deadline(
                lambda: (_ for _ in ()).throw(ValueError("x")), 5.0, health=h
            )
        assert not h.dead


class TestVerifyPlaneWedge:
    def _plane(self, wedge):
        plane = VerifyPlane(
            backend="cpu",  # construct cheap, then plant the wedge
            window_ms=1.0,
            min_device_batch=4,
            device_first_timeout=0.3,
            device_warm_timeout=0.3,
        )
        plane.verifier = wedge
        plane._device_capable = True
        return plane

    def test_wedged_device_falls_back_and_verifies(self):
        wedge = _Wedge()
        plane = self._plane(wedge)
        reqs = _reqs(16)
        t0 = time.perf_counter()
        out = plane.verify_many(reqs)
        assert out.all()  # every signature still verified (CPU side)
        assert time.perf_counter() - t0 < 10
        assert plane.device_wedged
        assert wedge.calls == 1
        stats = plane.get_json()
        assert stats["device_wedged"] is True
        assert stats["cpu_sigs"] == 16 and stats["device_sigs"] == 0

    def test_after_wedge_device_never_retried(self):
        wedge = _Wedge()
        plane = self._plane(wedge)
        plane.verify_many(_reqs(8))
        assert wedge.calls == 1
        for _ in range(3):
            out = plane.verify_many(_reqs(8))
            assert out.all()
        assert wedge.calls == 1  # no re-exploration of a dead device

    def test_node_closes_ledgers_through_a_wedged_device(self):
        """Node-level wiring: a validator whose device wedges mid-run
        must keep accepting transactions and closing ledgers on the CPU
        side — the subsystem degrades, the chain does not stall."""
        from stellard_tpu.node.config import Config
        from stellard_tpu.node.node import Node
        from stellard_tpu.protocol.formats import TxType
        from stellard_tpu.protocol.sfields import sfAmount, sfDestination
        from stellard_tpu.protocol.stamount import STAmount
        from stellard_tpu.protocol.sttx import SerializedTransaction

        node = Node(Config()).setup()
        try:
            # plant a wedge in the live plane (as if the tunnel hung);
            # min_device_batch=1 so even single-signature batches explore
            # the device (normal routing would shield them from it)
            node.verify_plane.verifier = _Wedge()
            node.verify_plane._device_capable = True
            node.verify_plane._t_first = 0.3
            node.verify_plane._t_warm = 0.3
            node.verify_plane.min_device_batch = 1
            node.verify_plane.model.min_device_batch = 1
            master = KeyPair.from_passphrase("masterpassphrase")
            dest = KeyPair.from_seed(b"\x33" * 32)
            done = threading.Semaphore(0)
            results = []

            def cb(tx, ter, applied):
                results.append((ter, applied))
                done.release()

            for seq in (1, 2):
                tx = SerializedTransaction.build(
                    TxType.ttPAYMENT, master.account_id, seq, 10,
                    {sfAmount: STAmount.from_drops(300_000_000),
                     sfDestination: dest.account_id},
                )
                tx.sign(master)
                # async intake: signature rides the verify plane, which
                # explores the (wedged) device on the first batch
                node.ops.submit_transaction(tx, cb)
                assert done.acquire(timeout=30)
                node.ops.accept_ledger()
            assert node.ledger_master.closed_ledger().seq >= 3
            assert all(applied for _, applied in results), results
            assert node.verify_plane.device_wedged
            assert node.verify_plane.get_json()["cpu_sigs"] >= 2
        finally:
            node.stop()

    def test_healthy_device_unaffected(self):
        class _Ok(BatchVerifier):
            name = "tpu"

            def verify_batch(self, batch):
                from stellard_tpu.crypto.backend import CpuVerifier

                return CpuVerifier(threads=1).verify_batch(batch)

        plane = self._plane(_Ok())
        out = plane.verify_many(_reqs(8))
        assert out.all()
        assert not plane.device_wedged
        assert plane.get_json()["device_sigs"] == 8


class TestWatchdogHasher:
    def _map(self, n=12) -> SHAMap:
        m = SHAMap(TNType.ACCOUNT_STATE)
        for i in range(n):
            m.set_item(SHAMapItem(bytes([i]) * 32, b"payload-%d" % i))
        return m

    def test_wedged_batch_hash_falls_back(self):
        wd = WatchdogHasher(
            _WedgeHasher(), CpuHasher(), first_timeout=0.2, warm_timeout=0.2
        )
        out = wd.prefix_hash_batch([0x12345678], [b"abc"])
        assert out == CpuHasher().prefix_hash_batch([0x12345678], [b"abc"])
        assert wd.device_wedged

    def test_wedged_tree_hash_matches_host(self):
        expect = self._map()
        expect_hash = expect.get_hash()

        wd = WatchdogHasher(
            _WedgeHasher(), CpuHasher(), first_timeout=0.2, warm_timeout=0.2
        )
        m = self._map()
        m.hash_batch = wd
        assert m.get_hash() == expect_hash  # fallback path, same root hash
        assert wd.device_wedged

    def test_abandoned_call_cannot_stamp_the_tree(self):
        """The zombie thread finishing late must not write node hashes."""
        inner = _WedgeHasher()
        wd = WatchdogHasher(
            inner, CpuHasher(), first_timeout=0.2, warm_timeout=0.2
        )
        m = self._map()
        before = m.get_hash()  # plain host hashing for the expectation
        m2 = self._map()
        m2.hash_batch = wd
        assert m2.get_hash() == before
        inner.release.set()  # zombie wakes up — sees cancelled, returns 0
        time.sleep(0.2)
        assert m2.get_hash() == before

    def test_healthy_inner_passthrough(self):
        wd = WatchdogHasher(CpuHasher(), CpuHasher(), first_timeout=5.0)
        out = wd.prefix_hash_batch([0x11111111], [b"x"])
        assert out == CpuHasher().prefix_hash_batch([0x11111111], [b"x"])
        assert not wd.device_wedged

    def test_inner_without_hash_tree_still_used_when_healthy(self):
        """A healthy inner lacking hash_tree (e.g. the native cpp hasher)
        must hash trees THROUGH the watchdog's batch path, not silently
        via the fallback (review finding r4)."""

        class _Counting(BatchHasher):
            name = "cpp"
            calls = 0

            def prefix_hash_batch(self, prefixes, payloads):
                self.calls += 1
                return CpuHasher().prefix_hash_batch(prefixes, payloads)

        inner, fb = _Counting(), _Counting()
        wd = WatchdogHasher(inner, fb, first_timeout=5.0, warm_timeout=5.0)
        expect = self._map().get_hash()
        m = self._map()
        m.hash_batch = wd
        assert m.get_hash() == expect
        assert inner.calls > 0  # the watched inner did the level batches
        assert fb.calls == 0  # the fallback was never touched


class TestHashCostRouting:
    """WatchdogHasher's measured-cost routing (the VerifyPlane stance
    applied to hashing): the device must EARN traffic — a measured-slow
    device floors at the host path with bounded re-exploration, and an
    unmeasured device is explored (first, compile-laden sample
    discarded)."""

    class _Fake:
        name = "fake"

        def __init__(self, delay_s):
            self.delay = delay_s
            self.calls = 0
            self.device_nodes = 0
            self.host_nodes = 0

        def prefix_hash_batch(self, prefixes, payloads):
            import hashlib
            import time as _t

            self.calls += 1
            _t.sleep(self.delay)
            return [
                hashlib.sha512(p.to_bytes(4, "big") + d).digest()[:32]
                for p, d in zip(prefixes, payloads)
            ]

    def _mk(self, dev_delay, host_delay):
        from stellard_tpu.crypto.backend import WatchdogHasher

        dev = self._Fake(dev_delay)
        host = self._Fake(host_delay)
        w = WatchdogHasher(dev, host, first_timeout=30, warm_timeout=30)
        return w, dev, host

    def test_slow_device_floors_at_host(self):
        w, dev, host = self._mk(dev_delay=0.02, host_delay=0.0)
        batch = ([0x1234] * 8, [b"x" * 40] * 8)
        for _ in range(12):
            w.prefix_hash_batch(*batch)
        # exploration: first (discarded) + second (recorded) device
        # samples, one host measurement, then the host wins every call
        assert dev.calls <= 3
        assert host.calls >= 8

    def test_fast_device_keeps_traffic(self):
        w, dev, host = self._mk(dev_delay=0.0, host_delay=0.02)
        batch = ([0x1234] * 8, [b"x" * 40] * 8)
        for _ in range(12):
            w.prefix_hash_batch(*batch)
        # one host measurement for the comparison; device keeps the rest
        assert host.calls == 1
        assert dev.calls >= 10

    def test_device_mode_restores_unconditional_routing(self, monkeypatch):
        monkeypatch.setenv("STELLARD_HASH_ROUTING", "device")
        w, dev, host = self._mk(dev_delay=0.02, host_delay=0.0)
        batch = ([0x1234] * 4, [b"x" * 40] * 4)
        for _ in range(6):
            w.prefix_hash_batch(*batch)
        assert host.calls == 0 and dev.calls == 6

    def test_results_identical_across_routes(self):
        w, dev, host = self._mk(dev_delay=0.01, host_delay=0.0)
        batch = ([0x1234] * 4, [b"a" * 33, b"b" * 100, b"", b"c" * 7])
        outs = {tuple(w.prefix_hash_batch(*batch)) for _ in range(8)}
        assert len(outs) == 1  # device and host routes agree bytes-for-bytes
