"""Transaction-engine tests: the transactor pipeline and every tx type.

Workload shapes mirror the reference's JS integration tests
(test/send-test.js payments, test/gateway-test.js trust+IOU,
test/offer-test.js offers, test/account_merge-test.js, inflation-test.js)
run against the engine directly (no node/RPC yet).
"""

import hashlib

import pytest

from stellard_tpu.engine import TransactionEngine, TxParams
from stellard_tpu.engine import views
from stellard_tpu.engine.flags import tfSell, tfSetNoRipple
from stellard_tpu.engine.inflation import (
    INFLATION_FREQUENCY,
    INFLATION_START_TIME,
)
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import (
    sfAmount,
    sfBalance,
    sfDestination,
    sfInflateSeq,
    sfInflationDest,
    sfLimitAmount,
    sfOfferSequence,
    sfOwnerCount,
    sfRegularKey,
    sfSequence,
    sfSetFlag,
    sfTakerGets,
    sfTakerPays,
)
from stellard_tpu.protocol.stamount import STAmount, currency_from_iso
from stellard_tpu.protocol.sttx import SerializedTransaction
from stellard_tpu.protocol.ter import TER
from stellard_tpu.state import indexes
from stellard_tpu.state.ledger import Ledger

USD = currency_from_iso("USD")
FEE = 10
START = 10_000 * 1_000_000  # 10k STR each

ROOT_KEY = KeyPair.from_passphrase("masterpassphrase")
ALICE = KeyPair.from_seed(b"\x11" * 32)
BOB = KeyPair.from_seed(b"\x22" * 32)
CAROL = KeyPair.from_seed(b"\x33" * 32)
GATEWAY = KeyPair.from_seed(b"\x44" * 32)


def build_tx(key: KeyPair, tx_type: TxType, seq: int, fee: int = FEE,
             fields: dict | None = None) -> SerializedTransaction:
    tx = SerializedTransaction.build(tx_type, key.account_id, seq, fee)
    for f, v in (fields or {}).items():
        tx.obj[f] = v
    tx.sign(key)
    return tx


class Net:
    """A closed-ledger test harness: genesis + funded accounts, applying
    transactions directly in closing mode (the standalone-node shape)."""

    def __init__(self, *keys: KeyPair, fund: int = START):
        self.ledger = Ledger.genesis(ROOT_KEY.account_id)
        self.ledger.parent_close_time = 700_000_000
        self.engine = TransactionEngine(self.ledger)
        self.seqs: dict[bytes, int] = {ROOT_KEY.account_id: 1}
        for k in keys:
            self.pay(ROOT_KEY, k.account_id, STAmount.from_drops(fund))

    def seq(self, key: KeyPair) -> int:
        return self.seqs.setdefault(key.account_id, 1)

    def apply(self, key: KeyPair, tx_type: TxType, expect=TER.tesSUCCESS,
              fee: int = FEE, fields: dict | None = None):
        tx = build_tx(key, tx_type, self.seq(key), fee, fields)
        ter, did = self.engine.apply_transaction(tx, TxParams.NONE)
        assert ter == expect, f"{tx_type.name}: got {ter!r} want {expect!r}"
        if did:
            self.seqs[key.account_id] = self.seq(key) + 1
        return ter, did

    def pay(self, key: KeyPair, dst: bytes, amount: STAmount, expect=TER.tesSUCCESS):
        return self.apply(key, TxType.ttPAYMENT, expect,
                          fields={sfDestination: dst, sfAmount: amount})

    def balance(self, key: KeyPair) -> int:
        acct = self.ledger.account_root(key.account_id)
        return acct[sfBalance].mantissa if acct else 0

    def iou_balance(self, holder: KeyPair, issuer: KeyPair,
                    currency: bytes = USD) -> STAmount:
        from stellard_tpu.state.entryset import LedgerEntrySet

        les = LedgerEntrySet(self.ledger)
        return views.ripple_balance(
            les, holder.account_id, issuer.account_id, currency
        )

    def trust(self, key: KeyPair, issuer: KeyPair, limit: int,
              currency: bytes = USD, flags: int = 0, expect=TER.tesSUCCESS):
        from stellard_tpu.protocol.sfields import sfFlags

        fields = {
            sfLimitAmount: STAmount.from_iou(
                currency, issuer.account_id, limit, 0
            )
        }
        if flags:
            fields[sfFlags] = flags
        return self.apply(key, TxType.ttTRUST_SET, expect, fields=fields)


# --------------------------------------------------------------------------
# payments


class TestPayments:
    def test_create_account_via_payment(self):
        net = Net()
        assert net.ledger.account_root(ALICE.account_id) is None
        net.pay(ROOT_KEY, ALICE.account_id, STAmount.from_drops(START))
        acct = net.ledger.account_root(ALICE.account_id)
        assert acct is not None
        assert acct[sfBalance].mantissa == START
        assert acct[sfSequence] == 1

    def test_payment_below_reserve_fails(self):
        net = Net()
        net.pay(ROOT_KEY, ALICE.account_id, STAmount.from_drops(100),
                expect=TER.tecNO_DST_INSUF_STR)

    def test_direct_payment_moves_funds_and_burns_fee(self):
        net = Net(ALICE, BOB)
        coins_before = net.ledger.tot_coins
        a0, b0 = net.balance(ALICE), net.balance(BOB)
        net.pay(ALICE, BOB.account_id, STAmount.from_drops(1_000_000))
        assert net.balance(ALICE) == a0 - 1_000_000 - FEE
        assert net.balance(BOB) == b0 + 1_000_000
        assert net.ledger.tot_coins == coins_before - FEE
        assert net.ledger.fee_pool >= FEE

    def test_tx_recorded_with_metadata(self):
        net = Net(ALICE, BOB)
        tx = build_tx(ALICE, TxType.ttPAYMENT, net.seq(ALICE),
                      fields={sfDestination: BOB.account_id,
                         sfAmount: STAmount.from_drops(500)})
        ter, did = net.engine.apply_transaction(tx, TxParams.NONE)
        assert did
        stored = net.ledger.get_transaction(tx.txid())
        assert stored is not None
        blob, meta = stored
        assert blob == tx.serialize()
        assert len(meta) > 10

    def test_bad_signature_rejected(self):
        net = Net(ALICE, BOB)
        tx = build_tx(ALICE, TxType.ttPAYMENT, net.seq(ALICE),
                      fields={sfDestination: BOB.account_id,
                         sfAmount: STAmount.from_drops(500)})
        from stellard_tpu.protocol.sfields import sfTxnSignature

        sig = bytearray(tx.obj[sfTxnSignature])
        sig[5] ^= 0xFF
        tx.obj[sfTxnSignature] = bytes(sig)
        ter, did = net.engine.apply_transaction(tx, TxParams.NONE)
        assert ter == TER.temINVALID and not did

    def test_wrong_sequence(self):
        net = Net(ALICE, BOB)
        tx = build_tx(ALICE, TxType.ttPAYMENT, 99,
                      fields={sfDestination: BOB.account_id,
                         sfAmount: STAmount.from_drops(500)})
        ter, _ = net.engine.apply_transaction(tx, TxParams.NONE)
        assert ter == TER.terPRE_SEQ
        tx2 = build_tx(ALICE, TxType.ttPAYMENT, 0,
                       fields={sfDestination: BOB.account_id,
                          sfAmount: STAmount.from_drops(500)})
        ter, _ = net.engine.apply_transaction(tx2, TxParams.NONE)
        assert ter == TER.tefPAST_SEQ

    def test_unfunded_payment_claims_fee(self):
        net = Net(ALICE, BOB)
        a0 = net.balance(ALICE)
        net.pay(ALICE, BOB.account_id,
                STAmount.from_drops(START * 2),
                expect=TER.tecUNFUNDED_PAYMENT)
        # fee still burned (tec semantics)
        assert net.balance(ALICE) == a0 - FEE

    def test_self_payment_rejected(self):
        net = Net(ALICE)
        net.pay(ALICE, ALICE.account_id, STAmount.from_drops(100),
                expect=TER.temREDUNDANT)

    def test_open_ledger_mode_records_but_does_not_apply(self):
        net = Net(ALICE, BOB)
        b0 = net.balance(BOB)
        tx = build_tx(ALICE, TxType.ttPAYMENT, net.seq(ALICE),
                      fields={sfDestination: BOB.account_id,
                         sfAmount: STAmount.from_drops(777)})
        ter, did = net.engine.apply_transaction(tx, TxParams.OPEN_LEDGER)
        assert ter == TER.tesSUCCESS and did
        assert net.balance(BOB) == b0  # no state change yet
        assert net.ledger.tx_map.get(tx.txid()) is not None
        # same tx again: tefALREADY
        ter, did = net.engine.apply_transaction(tx, TxParams.OPEN_LEDGER)
        assert ter == TER.tefALREADY and not did
        # next tx with the following seq passes open-ledger seq prediction
        tx2 = build_tx(ALICE, TxType.ttPAYMENT, net.seq(ALICE) + 1,
                       fields={sfDestination: BOB.account_id,
                          sfAmount: STAmount.from_drops(1)})
        ter, did = net.engine.apply_transaction(tx2, TxParams.OPEN_LEDGER)
        assert ter == TER.tesSUCCESS and did


# --------------------------------------------------------------------------
# trust lines + IOU payments (gateway-test.js shape)


class TestTrustAndIOU:
    def make_gateway_net(self):
        net = Net(ALICE, BOB, GATEWAY)
        net.trust(ALICE, GATEWAY, 1000)
        net.trust(BOB, GATEWAY, 1000)
        return net

    def test_trust_line_created(self):
        net = self.make_gateway_net()
        line = net.ledger.read_entry(indexes.ripple_state_index(
            ALICE.account_id, GATEWAY.account_id, USD
        ))
        assert line is not None
        acct = net.ledger.account_root(ALICE.account_id)
        assert acct[sfOwnerCount] == 1

    def test_issue_and_pay_iou(self):
        net = self.make_gateway_net()
        # gateway issues 100 USD to alice
        net.pay(GATEWAY, ALICE.account_id,
                STAmount.from_iou(USD, GATEWAY.account_id, 100, 0))
        bal = net.iou_balance(ALICE, GATEWAY)
        assert bal == STAmount.from_iou(USD, GATEWAY.account_id, 100, 0)
        # alice pays bob 30 USD (through the gateway)
        net.pay(ALICE, BOB.account_id,
                STAmount.from_iou(USD, GATEWAY.account_id, 30, 0))
        assert not net.iou_balance(BOB, GATEWAY).is_zero()

    def test_issue_beyond_limit_fails(self):
        net = self.make_gateway_net()
        net.pay(GATEWAY, ALICE.account_id,
                STAmount.from_iou(USD, GATEWAY.account_id, 5000, 0),
                expect=TER.tecPATH_DRY)

    def test_redeem_iou(self):
        net = self.make_gateway_net()
        net.pay(GATEWAY, ALICE.account_id,
                STAmount.from_iou(USD, GATEWAY.account_id, 100, 0))
        net.pay(ALICE, GATEWAY.account_id,
                STAmount.from_iou(USD, GATEWAY.account_id, 40, 0))
        bal = net.iou_balance(ALICE, GATEWAY)
        assert bal == STAmount.from_iou(USD, GATEWAY.account_id, 60, 0)

    def test_redeem_more_than_held_fails(self):
        net = self.make_gateway_net()
        net.pay(GATEWAY, ALICE.account_id,
                STAmount.from_iou(USD, GATEWAY.account_id, 10, 0))
        net.pay(ALICE, GATEWAY.account_id,
                STAmount.from_iou(USD, GATEWAY.account_id, 40, 0),
                expect=TER.tecPATH_PARTIAL)

    def test_trust_line_delete_on_default(self):
        net = Net(ALICE, GATEWAY)
        net.trust(ALICE, GATEWAY, 1000)
        net.trust(ALICE, GATEWAY, 0)  # reset to default -> deleted
        line = net.ledger.read_entry(indexes.ripple_state_index(
            ALICE.account_id, GATEWAY.account_id, USD
        ))
        assert line is None
        assert net.ledger.account_root(ALICE.account_id)[sfOwnerCount] == 0

    def test_no_line_redundant(self):
        net = Net(ALICE, GATEWAY)
        net.trust(ALICE, GATEWAY, 0, expect=TER.tecNO_LINE_REDUNDANT)

    def test_third_party_transfer_through_issuer(self):
        net = self.make_gateway_net()
        net.pay(GATEWAY, ALICE.account_id,
                STAmount.from_iou(USD, GATEWAY.account_id, 100, 0))
        net.pay(ALICE, BOB.account_id,
                STAmount.from_iou(USD, GATEWAY.account_id, 25, 0))
        assert net.iou_balance(ALICE, GATEWAY) == STAmount.from_iou(
            USD, GATEWAY.account_id, 75, 0
        )
        assert net.iou_balance(BOB, GATEWAY) == STAmount.from_iou(
            USD, GATEWAY.account_id, 25, 0
        )


# --------------------------------------------------------------------------
# offers (offer-test.js shape)


class TestOffers:
    def net_with_book(self):
        net = Net(ALICE, BOB, GATEWAY)
        net.trust(ALICE, GATEWAY, 10_000)
        net.trust(BOB, GATEWAY, 10_000)
        net.pay(GATEWAY, ALICE.account_id,
                STAmount.from_iou(USD, GATEWAY.account_id, 1000, 0))
        return net

    def test_offer_placed(self):
        net = self.net_with_book()
        # alice sells 100 USD for 50 STR
        ter, _ = net.apply(
            ALICE, TxType.ttOFFER_CREATE,
            fields={sfTakerPays: STAmount.from_drops(50_000_000),
               sfTakerGets: STAmount.from_iou(USD, GATEWAY.account_id, 100, 0)})
        offer_idx = indexes.offer_index(ALICE.account_id, net.seq(ALICE) - 1)
        offer = net.ledger.read_entry(offer_idx)
        assert offer is not None
        assert offer[sfTakerGets] == STAmount.from_iou(
            USD, GATEWAY.account_id, 100, 0
        )
        # owner count rose (reserve)
        assert net.ledger.account_root(ALICE.account_id)[sfOwnerCount] == 2

    def test_offer_crossing_full(self):
        net = self.net_with_book()
        # alice sells 100 USD for 50 STR
        net.apply(ALICE, TxType.ttOFFER_CREATE,
                  fields={sfTakerPays: STAmount.from_drops(50_000_000),
                     sfTakerGets: STAmount.from_iou(USD, GATEWAY.account_id, 100, 0)})
        alice_seq = net.seq(ALICE) - 1
        b_str0 = net.balance(BOB)
        a_str0 = net.balance(ALICE)
        # bob buys 100 USD paying 50 STR -> crosses fully
        net.apply(BOB, TxType.ttOFFER_CREATE,
                  fields={sfTakerPays: STAmount.from_iou(USD, GATEWAY.account_id, 100, 0),
                     sfTakerGets: STAmount.from_drops(50_000_000)})
        # alice's offer fully consumed
        assert net.ledger.read_entry(
            indexes.offer_index(ALICE.account_id, alice_seq)
        ) is None
        assert net.iou_balance(BOB, GATEWAY) == STAmount.from_iou(
            USD, GATEWAY.account_id, 100, 0
        )
        assert net.balance(ALICE) == a_str0 + 50_000_000
        assert net.balance(BOB) == b_str0 - 50_000_000 - FEE
        # bob's crossing offer fully filled: no resting offer
        assert net.ledger.read_entry(
            indexes.offer_index(BOB.account_id, net.seq(BOB) - 1)
        ) is None

    def test_offer_crossing_partial(self):
        net = self.net_with_book()
        net.apply(ALICE, TxType.ttOFFER_CREATE,
                  fields={sfTakerPays: STAmount.from_drops(50_000_000),
                     sfTakerGets: STAmount.from_iou(USD, GATEWAY.account_id, 100, 0)})
        alice_seq = net.seq(ALICE) - 1
        # bob only wants 40 USD (pays up to 20 STR, same price)
        net.apply(BOB, TxType.ttOFFER_CREATE,
                  fields={sfTakerPays: STAmount.from_iou(USD, GATEWAY.account_id, 40, 0),
                     sfTakerGets: STAmount.from_drops(20_000_000)})
        rest = net.ledger.read_entry(
            indexes.offer_index(ALICE.account_id, alice_seq)
        )
        assert rest is not None
        assert rest[sfTakerGets] == STAmount.from_iou(
            USD, GATEWAY.account_id, 60, 0
        )
        assert rest[sfTakerPays] == STAmount.from_drops(30_000_000)
        assert net.iou_balance(BOB, GATEWAY) == STAmount.from_iou(
            USD, GATEWAY.account_id, 40, 0
        )

    def test_offer_no_cross_below_price(self):
        net = self.net_with_book()
        net.apply(ALICE, TxType.ttOFFER_CREATE,
                  fields={sfTakerPays: STAmount.from_drops(50_000_000),
                     sfTakerGets: STAmount.from_iou(USD, GATEWAY.account_id, 100, 0)})
        # bob bids too little: wants 100 USD for only 10 STR
        net.apply(BOB, TxType.ttOFFER_CREATE,
                  fields={sfTakerPays: STAmount.from_iou(USD, GATEWAY.account_id, 100, 0),
                     sfTakerGets: STAmount.from_drops(10_000_000)})
        # both offers rest; no trade
        assert net.iou_balance(BOB, GATEWAY).is_zero()
        assert net.ledger.read_entry(
            indexes.offer_index(BOB.account_id, net.seq(BOB) - 1)
        ) is not None

    def test_offer_cancel(self):
        net = self.net_with_book()
        net.apply(ALICE, TxType.ttOFFER_CREATE,
                  fields={sfTakerPays: STAmount.from_drops(50_000_000),
                     sfTakerGets: STAmount.from_iou(USD, GATEWAY.account_id, 100, 0)})
        alice_seq = net.seq(ALICE) - 1
        net.apply(ALICE, TxType.ttOFFER_CANCEL,
                  fields={sfOfferSequence: alice_seq})
        assert net.ledger.read_entry(
            indexes.offer_index(ALICE.account_id, alice_seq)
        ) is None
        assert net.ledger.account_root(ALICE.account_id)[sfOwnerCount] == 1

    def test_unfunded_offer_rejected(self):
        net = Net(ALICE, BOB)  # alice holds no USD
        net.apply(ALICE, TxType.ttOFFER_CREATE,
                  expect=TER.tecUNFUNDED_OFFER,
                  fields={sfTakerPays: STAmount.from_drops(50_000_000),
                     sfTakerGets: STAmount.from_iou(USD, GATEWAY.account_id, 100, 0)})

    def test_str_for_str_rejected(self):
        net = Net(ALICE)
        net.apply(ALICE, TxType.ttOFFER_CREATE,
                  expect=TER.temBAD_OFFER,
                  fields={sfTakerPays: STAmount.from_drops(100),
                     sfTakerGets: STAmount.from_drops(50)})


# --------------------------------------------------------------------------
# account ops


class TestAccountOps:
    def test_set_regular_key_and_sign_with_it(self):
        net = Net(ALICE, BOB)
        regular = KeyPair.from_seed(b"\x55" * 32)
        net.apply(ALICE, TxType.ttREGULAR_KEY_SET,
                  fields={sfRegularKey: regular.account_id})
        acct = net.ledger.account_root(ALICE.account_id)
        assert acct[sfRegularKey] == regular.account_id
        # sign a payment with the regular key
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, ALICE.account_id, net.seq(ALICE), FEE,
            fields={sfDestination: BOB.account_id,
                    sfAmount: STAmount.from_drops(100)})
        tx.sign(regular)
        ter, did = net.engine.apply_transaction(tx, TxParams.NONE)
        assert ter == TER.tesSUCCESS and did

    def test_wrong_key_rejected(self):
        net = Net(ALICE, BOB)
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, ALICE.account_id, net.seq(ALICE), FEE,
            fields={sfDestination: BOB.account_id,
                    sfAmount: STAmount.from_drops(100)})
        tx.sign(BOB)  # bob's key, alice's account, no regular key set
        ter, _ = net.engine.apply_transaction(tx, TxParams.NONE)
        assert ter == TER.temBAD_AUTH_MASTER

    def test_account_set_inflation_dest(self):
        net = Net(ALICE, BOB)
        net.apply(ALICE, TxType.ttACCOUNT_SET,
                  fields={sfInflationDest: BOB.account_id})
        acct = net.ledger.account_root(ALICE.account_id)
        assert acct[sfInflationDest] == BOB.account_id

    def test_account_merge(self):
        net = Net(ALICE, BOB)
        a_bal = net.balance(ALICE)
        b_bal = net.balance(BOB)
        net.apply(ALICE, TxType.ttACCOUNT_MERGE,
                  fields={sfDestination: BOB.account_id})
        assert net.ledger.account_root(ALICE.account_id) is None
        assert net.balance(BOB) == b_bal + a_bal - FEE

    def test_account_merge_with_iou(self):
        net = Net(ALICE, BOB, GATEWAY)
        net.trust(ALICE, GATEWAY, 1000)
        net.trust(BOB, GATEWAY, 1000)
        net.pay(GATEWAY, ALICE.account_id,
                STAmount.from_iou(USD, GATEWAY.account_id, 100, 0))
        net.apply(ALICE, TxType.ttACCOUNT_MERGE,
                  fields={sfDestination: BOB.account_id})
        assert net.ledger.account_root(ALICE.account_id) is None
        assert net.iou_balance(BOB, GATEWAY) == STAmount.from_iou(
            USD, GATEWAY.account_id, 100, 0
        )
        # alice's line is gone
        assert net.ledger.read_entry(indexes.ripple_state_index(
            ALICE.account_id, GATEWAY.account_id, USD
        )) is None


# --------------------------------------------------------------------------
# inflation (inflation-test.js shape)


class TestInflation:
    def test_inflation_dole(self):
        net = Net(ALICE, BOB, fund=10**15)  # big voters
        net.apply(ALICE, TxType.ttACCOUNT_SET,
                  fields={sfInflationDest: BOB.account_id})
        net.apply(ROOT_KEY, TxType.ttACCOUNT_SET,
                  fields={sfInflationDest: BOB.account_id})
        # advance time so inflation is due
        net.ledger.parent_close_time = (
            INFLATION_START_TIME + 1 * INFLATION_FREQUENCY + 10
        )
        coins0 = net.ledger.tot_coins
        fee_pool0 = net.ledger.fee_pool
        b0 = net.balance(BOB)
        net.apply(ALICE, TxType.ttINFLATION, fee=0,
                  fields={sfInflateSeq: 1})
        assert net.ledger.inflation_seq == 2
        assert net.ledger.fee_pool == 0
        gained = net.balance(BOB) - b0
        expected_new = coins0 * 190_721_000 // 10**12
        assert gained > 0
        assert abs(gained - (expected_new + fee_pool0)) <= 2
        # the fee pool returns to circulation; new coins on top
        assert net.ledger.tot_coins == coins0 + gained

    def test_inflation_too_early(self):
        net = Net(ALICE, fund=10**15)
        net.ledger.parent_close_time = 1000  # way before start
        net.apply(ALICE, TxType.ttINFLATION, fee=0,
                  expect=TER.telNOT_TIME, fields={sfInflateSeq: 1})

    def test_inflation_wrong_seq(self):
        net = Net(ALICE, fund=10**15)
        net.ledger.parent_close_time = (
            INFLATION_START_TIME + INFLATION_FREQUENCY * 5
        )
        net.apply(ALICE, TxType.ttINFLATION, fee=0,
                  expect=TER.telNOT_TIME, fields={sfInflateSeq: 7})

    def test_inflation_with_fee_rejected(self):
        net = Net(ALICE, fund=10**15)
        net.ledger.parent_close_time = (
            INFLATION_START_TIME + INFLATION_FREQUENCY + 10
        )
        net.apply(ALICE, TxType.ttINFLATION, fee=10,
                  expect=TER.temBAD_FEE, fields={sfInflateSeq: 1})


class TestTrustAutoClear:
    """reference: test/path2-test.js 'trust auto clear' — clearing the
    limit while a balance is outstanding keeps the line alive; the line
    auto-deletes the moment the balance returns to zero with both sides
    at defaults."""

    def test_line_survives_cleared_limit_then_auto_deletes(self):
        from stellard_tpu.state import indexes
        from stellard_tpu.state.entryset import LedgerEntrySet

        net = Net(ALICE, BOB)
        net.trust(ALICE, BOB, 1000)
        net.pay(BOB, ALICE.account_id,
                STAmount.from_iou(USD, BOB.account_id, 50, 0))
        net.trust(ALICE, BOB, 0)  # clear limit; 50 USD still held
        idx = indexes.ripple_state_index(
            ALICE.account_id, BOB.account_id, USD
        )
        assert LedgerEntrySet(net.ledger).peek(idx) is not None, (
            "line with outstanding balance must survive a cleared limit"
        )
        assert net.iou_balance(ALICE, BOB).value_text() == "50"
        net.pay(ALICE, BOB.account_id,
                STAmount.from_iou(USD, BOB.account_id, 50, 0))
        assert LedgerEntrySet(net.ledger).peek(idx) is None, (
            "defaulted line must auto-delete when the balance zeroes"
        )


class TestAccountSetFlags:
    """reference: test/account_set-test.js — RequireDestTag and
    RequireAuth end-to-end behavior (the flags were implemented; the
    behaviors were unpinned)."""

    def test_require_dest_tag(self):
        from stellard_tpu.engine.flags import asfRequireDest
        from stellard_tpu.protocol.sfields import (
            sfDestinationTag,
            sfSetFlag,
            sfClearFlag,
        )

        net = Net(ALICE, BOB)
        net.apply(BOB, TxType.ttACCOUNT_SET,
                  fields={sfSetFlag: int(asfRequireDest)})
        # untagged payment refused; tagged succeeds
        net.pay(ALICE, BOB.account_id, STAmount.from_drops(1_000_000),
                expect=TER.tefDST_TAG_NEEDED)
        net.apply(ALICE, TxType.ttPAYMENT, fields={
            sfDestination: BOB.account_id,
            sfAmount: STAmount.from_drops(1_000_000),
            sfDestinationTag: 7,
        })
        # clearing the flag restores untagged payments
        net.apply(BOB, TxType.ttACCOUNT_SET,
                  fields={sfClearFlag: int(asfRequireDest)})
        net.pay(ALICE, BOB.account_id, STAmount.from_drops(1_000_000))

    def test_require_auth_gates_trust_issuance(self):
        from stellard_tpu.engine.flags import asfRequireAuth, tfSetfAuth
        from stellard_tpu.protocol.sfields import sfFlags, sfSetFlag

        gateway, holder = KeyPair.from_passphrase("asf-gw"), ALICE
        net = Net(gateway, holder)
        # authorizing before RequireAuth is set is an error
        net.apply(gateway, TxType.ttTRUST_SET,
                  expect=TER.tefNO_AUTH_REQUIRED,
                  fields={sfLimitAmount: STAmount.from_iou(
                      USD, holder.account_id, 0, 0), sfFlags: tfSetfAuth})
        net.apply(gateway, TxType.ttACCOUNT_SET,
                  fields={sfSetFlag: int(asfRequireAuth)})
        net.trust(holder, gateway, 1000)
        # unauthorized line: the issuer cannot be paid ACROSS it yet —
        # pathfinding refuses the unauthorized hop
        from stellard_tpu.paths import find_paths

        alts = find_paths(
            net.ledger, gateway.account_id, holder.account_id,
            STAmount.from_iou(USD, gateway.account_id, 5, 0),
        )
        assert alts == [], "unauthorized line must not carry paths"
        # direct issuance across the unauthorized line is refused
        # (reference: calcNodeAccountRev terNO_AUTH)
        net.pay(gateway, holder.account_id,
                STAmount.from_iou(USD, gateway.account_id, 5, 0),
                expect=TER.terNO_AUTH)
        # the gateway authorizes the holder's line, then issuance works
        net.apply(gateway, TxType.ttTRUST_SET, fields={
            sfLimitAmount: STAmount.from_iou(
                USD, holder.account_id, 0, 0),
            sfFlags: tfSetfAuth,
        })
        net.pay(gateway, holder.account_id,
                STAmount.from_iou(USD, gateway.account_id, 5, 0))
        assert net.iou_balance(holder, gateway).value_text() == "5"
