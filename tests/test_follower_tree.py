"""Cascading follower trees (ISSUE 19): deterministic topology
planning, resume-from-seq cursors on the sharded fanout, follower
_check_lcl kick coalescing, and the epoch-pinned snapshot handoff."""

from __future__ import annotations

import hashlib
import struct

import jax
import pytest

jax.config.update("jax_platforms", "cpu")

from stellard_tpu.node.config import Config  # noqa: E402
from stellard_tpu.node.inbound import SegmentCatchup  # noqa: E402
from stellard_tpu.node.node import Node  # noqa: E402
from stellard_tpu.overlay.followertree import (  # noqa: E402
    plan_tree,
    select_children,
    tier_of,
    tree_stats,
)
from stellard_tpu.overlay.simnet import SimNet  # noqa: E402
from stellard_tpu.overlay.wire import (  # noqa: E402
    FrameReader,
    GetSegments,
    SegmentData,
    frame,
)
from stellard_tpu.protocol.keys import KeyPair  # noqa: E402
from stellard_tpu.rpc.infosub import InfoSub, SubscriptionManager  # noqa: E402
from stellard_tpu.utils.hashes import sha512_half  # noqa: E402


@pytest.fixture
def node():
    n = Node(Config(signature_backend="cpu")).setup()
    yield n
    n.stop()


# -- topology planning ----------------------------------------------------


class TestTreePlan:
    def test_heap_layout(self):
        # branching 2: followers 0-1 dial the leader, 2-3 hang off
        # follower 0, 4-5 off follower 1
        assert plan_tree(6, 2) == [-1, -1, 0, 0, 1, 1]
        assert plan_tree(4, 3) == [-1, -1, -1, 0]
        assert plan_tree(0, 2) == []

    def test_leader_children_bounded_by_branching(self):
        for b in (1, 2, 3, 4):
            stats = tree_stats(plan_tree(40, b), b)
            assert stats["leader_children"] <= b
            assert stats["max_children"] <= b

    def test_tiers(self):
        assert tier_of(0, 2) == 1
        assert tier_of(1, 2) == 1
        assert tier_of(2, 2) == 2
        assert tier_of(5, 2) == 2
        assert tier_of(6, 2) == 3
        assert tree_stats(plan_tree(6, 2), 2)["depth"] == 2

    def test_select_children_deterministic_and_rotating(self):
        cands = [struct.pack(">I", i) for i in range(12)]
        a = select_children(b"parent", 5, cands, lambda c: c, 4, rotate=16)
        b = select_children(b"parent", 5, cands, lambda c: c, 4, rotate=16)
        assert a == b and len(a) == 4
        # same epoch (seq 5 and 6 share epoch 0 at rotate=16)
        assert select_children(b"parent", 6, cands, lambda c: c, 4) == a
        # a later epoch re-randomizes the subset
        later = select_children(b"parent", 16, cands, lambda c: c, 4)
        assert later != a
        # under-subscribed: everyone is a child
        assert select_children(b"p", 0, cands[:3], lambda c: c, 4) == \
            cands[:3]


class TestSimnetTree:
    def test_upstream_assignment_and_rehome(self):
        net = SimNet(n_validators=2, quorum=2, n_followers=6,
                     follower_branching=2)
        base = 2
        # tier-1 followers anycast (upstream=None); deeper tiers name
        # their parent follower
        assert net.followers[0].upstream is None
        assert net.followers[1].upstream is None
        assert net.followers[2].upstream == base + 0
        assert net.followers[5].upstream == base + 1
        # live parent resolves directly
        assert net.upstream_for(base + 2) == base + 0
        # dead parent: the child re-homes UP the tree (here: to the
        # leader tier, i.e. validator anycast) and the move is counted
        net.kill(base + 0)
        assert net.upstream_for(base + 2) is None
        assert net.net_stats["rehomed"] == 1
        # revive: back to the parent
        net.revive(base + 0)
        assert net.upstream_for(base + 2) == base + 0

    def test_flat_tier_unchanged(self):
        net = SimNet(n_validators=2, quorum=2, n_followers=2)
        assert all(f.upstream is None for f in net.followers)
        assert net.upstream_for(2 + 0) is None
        assert "rehomed" not in net.net_stats  # legacy stats shape


# -- resume-from-seq cursors (satellite c) ---------------------------------


class TestResumeCursors:
    def _mgr(self, node, **kw):
        return SubscriptionManager(node.ops, **kw)

    def _fill(self, node, mgr, n):
        """Close n ledgers through the real publish hook; returns the
        published seqs."""
        seqs = []
        for _ in range(n):
            node.close_ledger()
            seqs.append(node.ledger_master.closed_ledger().seq)
        return seqs

    def test_resume_exactly_at_horizon(self, node):
        mgr = self._mgr(node, resume_horizon=3)
        seqs = self._fill(node, mgr, 5)
        ring = seqs[-3:]  # bounded ring kept only the newest 3
        got: list = []
        sub = InfoSub(got.append)
        # cursor exactly at the horizon: next event == ring floor
        res = mgr.resume(sub, ring[0] - 1)
        assert res["resumed"] and not res["cold"]
        assert res["replayed"] == 3
        assert [m["ledger_index"] for m in got] == ring
        # registered live: the next close flows without a re-subscribe
        node.close_ledger()
        assert got[-1]["ledger_index"] == ring[-1] + 1

    def test_resume_past_horizon_explicit_cold(self, node):
        mgr = self._mgr(node, resume_horizon=3)
        seqs = self._fill(node, mgr, 5)
        got: list = []
        sub = InfoSub(got.append)
        res = mgr.resume(sub, seqs[-3] - 2)  # next event below the floor
        assert res["cold"] and not res["resumed"]
        assert res["horizon"] == seqs[-3]  # the floor, so the client
        assert got == []                   # knows WHERE cold starts
        with mgr._lock:
            assert sub.id not in mgr._subs  # never silently attached
        assert mgr.get_json()["resume_cold"] == 1

    def test_resume_disabled_always_cold(self, node):
        mgr = self._mgr(node, resume_horizon=0)
        self._fill(node, mgr, 2)
        res = mgr.resume(InfoSub(lambda m: None), 2)
        assert res["cold"]

    def test_fresh_client_empty_ring_resumes(self, node):
        # a from-genesis client (last_seq 0) against an empty ring is a
        # valid attach, not a cold refusal
        mgr = self._mgr(node, resume_horizon=8)
        res = mgr.resume(InfoSub(lambda m: None), 0)
        assert res["resumed"] and res["replayed"] == 0
        # but a real cursor against an empty ring IS cold (history aged
        # out entirely)
        res = mgr.resume(InfoSub(lambda m: None), 7)
        assert res["cold"]

    def test_duplicate_suppression_on_overlapping_replay(self, node):
        """A live publish racing the replay must not double-deliver:
        the per-sub cursor (serialized on the replay lock) suppresses
        the overlap."""
        mgr = self._mgr(node, resume_horizon=8)
        seqs = self._fill(node, mgr, 4)
        got: list = []
        sub = InfoSub(got.append)
        mgr.subscribe_streams(sub, ["ledger"])
        node.close_ledger()
        top = node.ledger_master.closed_ledger().seq
        assert [m["ledger_index"] for m in got] == [top]
        # a replayed/raced event AT or BELOW the cursor is suppressed
        before = mgr.get_json()["dup_suppressed"]
        mgr._deliver_ledger(sub, {"type": "ledgerClosed",
                                  "ledger_index": top})
        mgr._deliver_ledger(sub, {"type": "ledgerClosed",
                                  "ledger_index": seqs[-1]})
        assert [m["ledger_index"] for m in got] == [top]
        assert mgr.get_json()["dup_suppressed"] == before + 2
        # resume with a stale cursor on the SAME sub replays nothing
        # (its cursor already advanced past the whole ring)
        res = mgr.resume(sub, seqs[0])
        assert res["resumed"] and res["replayed"] == 0
        assert [m["ledger_index"] for m in got] == [top]

    def test_cursor_survives_eviction_and_reconnect(self, node):
        """The fanout plane evicts a dying subscriber; the CLIENT still
        holds its last-delivered seq and resumes from it — replaying the
        events it lost while evicted, with zero gaps."""
        mgr = self._mgr(node, shards=1, sendq_cap=64, resume_horizon=32)
        try:
            delivered: list = []

            def dying(msg):
                if delivered:
                    raise RuntimeError("sink died")
                delivered.append(msg)

            sub = InfoSub(dying)
            mgr.subscribe_streams(sub, ["ledger"])
            node.close_ledger()
            assert mgr.flush(timeout=10.0)
            assert len(delivered) == 1
            last_seen = delivered[0]["ledger_index"]
            node.close_ledger()  # this send raises -> dead-sink evict
            assert mgr.flush(timeout=10.0)
            assert sub.evicted
            # the network keeps closing while the client is gone
            for _ in range(3):
                node.close_ledger()
            assert mgr.flush(timeout=10.0)
            top = node.ledger_master.closed_ledger().seq
            # reconnect: a fresh InfoSub presents the client's cursor
            got: list = []
            sub2 = InfoSub(got.append)
            res = mgr.resume(sub2, last_seen)
            assert res["resumed"], res
            assert mgr.flush(timeout=10.0)
            replayed = [m["ledger_index"] for m in got]
            assert replayed == list(range(last_seen + 1, top + 1)), (
                f"gap after eviction+resume: {replayed}"
            )
            assert mgr.get_json()["dead_evicted"] == 1
        finally:
            mgr.stop()

    def test_shard_stats_exposed(self, node):
        # satellite (b): per-shard depth/drop/evict gauges ride
        # get_json and the subs_shard collector hook shape
        mgr = self._mgr(node, shards=2)
        try:
            j = mgr.get_json()
            for i in range(2):
                for k in ("depth", "dropped", "evicted"):
                    assert f"shard{i}_{k}" in j
            assert set(mgr.shard_stats()) == {
                f"shard{i}_{k}" for i in range(2)
                for k in ("depth", "dropped", "evicted")
            }
        finally:
            mgr.stop()


# -- follower kick coalescing (satellite a) --------------------------------


class _NullAdapter:
    def request_ledger_data(self, msg):
        pass


class TestFollowerKickCoalescing:
    def _follower(self, n_keys=4):
        from stellard_tpu.node.validator import ValidatorNode

        keys = [
            KeyPair.from_seed(hashlib.sha256(bytes([i]) * 4).digest())
            for i in range(n_keys)
        ]
        now = [10_000]
        vn = ValidatorNode(
            key=KeyPair.from_passphrase("tree-follower"),
            unl={k.public for k in keys},
            adapter=_NullAdapter(),
            quorum=3,
            network_time=lambda: now[0],
            clock=lambda: float(now[0]),
            follower=True,
        )
        vn.start(b"\x07" * 20, close_time=now[0])
        return vn, keys, now

    def test_follower_kick_coalescing(self):
        """A validation burst of |UNL| for ONE target seq runs ONE
        inline election, not |UNL| (the remaining kicks coalesce)."""
        from stellard_tpu.consensus.validation import STValidation

        vn, keys, now = self._follower()
        kicks = []
        vn._check_lcl = lambda: kicks.append(1)  # count, no side effects
        target = hashlib.sha256(b"tree-target").digest()
        for k in keys:
            v = STValidation.build(target, signing_time=now[0],
                                   ledger_seq=5)
            v.sign(k)
            assert vn.handle_validation(v)
        assert len(kicks) == 1
        assert vn.lcl_inline_kicks == 1
        assert vn.lcl_kicks_coalesced == len(keys) - 1
        # a HIGHER seq kicks again (progress is never coalesced away)
        v = STValidation.build(hashlib.sha256(b"t6").digest(),
                               signing_time=now[0] + 1, ledger_seq=6)
        v.sign(keys[0])
        assert vn.handle_validation(v)
        assert len(kicks) == 2
        assert vn.lcl_inline_kicks == 2
        j = vn.follower_json()
        assert j["lcl_inline_kicks"] == 2
        assert j["lcl_kicks_coalesced"] == len(keys) - 1


# -- epoch-pinned snapshot handoff -----------------------------------------


def _record(blob: bytes, type_byte: int = 3) -> bytes:
    key = sha512_half(blob)
    body = bytes([type_byte]) + blob
    return struct.pack("<IB", len(body), 0) + key + body


class _FakeNet:
    def __init__(self):
        self.sent = []

    def send(self, peer, msg):
        self.sent.append((peer, msg))


class TestEpochPinnedHandoff:
    def _mk(self, net, peers=("a", "b")):
        stored = []
        clock = [0.0]
        sc = SegmentCatchup(
            send=net.send,
            peers=lambda: list(peers),
            store=lambda tb, k, b: stored.append((tb, k, b)),
            clock=lambda: clock[0],
            seed=1,
        )
        return sc, stored, clock

    def test_wire_fields_round_trip_and_compat(self):
        # nonzero snap fields survive the codec
        g = GetSegments(2, 64, snap_epoch=77)
        fr = FrameReader()
        (g2,) = fr.feed(frame(g))
        assert (g2.seg_id, g2.offset, g2.snap_epoch) == (2, 64, 77)
        d = SegmentData(2, 100, 0, b"xy", snap_epoch=77, snap_seq=41)
        (d2,) = fr.feed(frame(d))
        assert (d2.snap_epoch, d2.snap_seq) == (77, 41)
        # zero fields are NOT emitted: byte-identical legacy wire
        assert len(frame(GetSegments(2, 64))) < len(frame(g))
        (d3,) = fr.feed(frame(SegmentData(2, 100, 0, b"xy")))
        assert d3.snap_epoch == 0 and d3.snap_seq == 0

    def test_epoch_move_restarts_from_fresh_manifest(self):
        """A chunk stamped with a DIFFERENT epoch than the manifest's
        (the server rotated/compacted mid-transfer) restarts the
        session from a fresh manifest — never a peer condemnation,
        never a torn buffer."""
        net = _FakeNet()
        sc, stored, _clock = self._mk(net)
        sc.start()
        peer, m0 = net.sent.pop()
        assert isinstance(m0, GetSegments) and m0.seg_id == -1
        seg = _record(b"epoch-node")
        sc.on_manifest(peer, [(0, len(seg), len(seg), False)],
                       epoch=5, snap_seq=9)
        assert sc._snap_epoch == 5 and sc._snap_seq == 9
        peer2, m1 = net.sent.pop()
        # the chunk fetch is PINNED to the offered epoch
        assert m1.seg_id == 0 and m1.snap_epoch == 5
        # server's sealed set moved: chunk arrives under epoch 6
        sc.on_data(peer2, SegmentData(0, len(seg), 0, seg, snap_epoch=6))
        assert sc.counters["epoch_restarts"] == 1
        assert sc.state == "manifest"
        assert not stored  # nothing torn was kept
        _peer3, m2 = net.sent.pop()
        assert m2.seg_id == -1  # fresh manifest request
        # the retried handoff under the new epoch completes
        sc.on_manifest(_peer3, [(0, len(seg), len(seg), False)],
                       epoch=6, snap_seq=10)
        peer4, m3 = net.sent.pop()
        assert m3.snap_epoch == 6
        sc.on_data(peer4, SegmentData(0, len(seg), 0, seg, snap_epoch=6))
        assert sc.state == "done"
        assert len(stored) == 1

    def test_same_epoch_and_epochless_chunks_flow(self):
        net = _FakeNet()
        sc, stored, _clock = self._mk(net)
        sc.start()
        peer, _ = net.sent.pop()
        seg = _record(b"zz")
        sc.on_manifest(peer, [(0, len(seg), len(seg), False)], epoch=5)
        peer2, _ = net.sent.pop()
        # pre-epoch server: chunks without a stamp are accepted (0 on
        # the wire means "no epoch", not a mismatch)
        sc.on_data(peer2, SegmentData(0, len(seg), 0, seg))
        assert sc.state == "done" and len(stored) == 1
        assert sc.counters["epoch_restarts"] == 0
