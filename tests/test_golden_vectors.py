"""Reference-derived golden vectors (VERDICT r3 missing #6 / next #8).

Every constant in this file is pinned from OUTSIDE our own code:

- the base58 identity strings are copied verbatim from the reference's
  own in-source unit test expectations
  (/root/reference/src/ripple_data/protocol/RippleAddress.cpp:810-900),
- hashes are recomputed inline with hashlib (not utils.hashes),
- Ed25519 is cross-checked against the `cryptography` package
  (an independent implementation of RFC 8032),
- wire blobs are hand-assembled byte by byte from the reference's
  serialization rules (Serializer.cpp addVL/getPrefixHash,
  SerializedTypes field-header encoding), with the rules cited.

A transposed field order, wrong prefix constant, or broken base58
alphabet passes self-referential tests but fails these.
"""

from __future__ import annotations

import hashlib

import pytest

from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.sfields import (
    sfAmount,
    sfDestination,
)
from stellard_tpu.protocol.stamount import STAmount
from stellard_tpu.protocol.sttx import SerializedTransaction

# --------------------------------------------------------------------------
# reference unit-test constants (RippleAddress.cpp:810-900, verbatim)

MASTER_PASSPHRASE = "masterpassphrase"
MASTER_SEED_B58 = "s3q5ZGX2ScQK2rJ4JATp7rND6X5npG3De8jMbB7tuvm2HAVHcCN"
MASTER_NODE_PUBLIC_B58 = "nfbbWHgJqzqfH1cfRpMdPRkJ19cxTsdHkBtz1SLJJQfyf9Ax6vd"
MASTER_ACCOUNT_PUBLIC_B58 = "pGreoXKYybde1keKZwDCv8m5V1kT6JH37pgnTUVzdMkdygTixG8"
MASTER_ACCOUNT_ID_B58 = "ganVp9o5emfzpwrG5QVUXqMv8AgLcdvySb"

# HashPrefix.cpp:25-32 domain-separation constants ('TXN\0' etc.)
HP_TXN_ID = 0x54584E00  # 'TXN\0' transaction ID
HP_TX_SIGN = 0x53545800  # 'STX\0' transaction signing
HP_LEDGER = 0x4C575200  # 'LWR\0' ledger header


def sha512half(data: bytes) -> bytes:
    """Independent oracle: first 256 bits of SHA-512
    (Serializer.cpp:342-390)."""
    return hashlib.sha512(data).digest()[:32]


class TestReferenceIdentityVectors:
    def test_masterpassphrase_identity_strings(self):
        kp = KeyPair.from_passphrase(MASTER_PASSPHRASE)
        assert kp.human_seed == MASTER_SEED_B58
        assert kp.human_node_public == MASTER_NODE_PUBLIC_B58
        assert kp.human_account_public == MASTER_ACCOUNT_PUBLIC_B58
        assert kp.human_account_id == MASTER_ACCOUNT_ID_B58

    def test_account_id_derivation_chain(self):
        """AccountID = RIPEMD160(SHA256(pubkey)) (HashUtilities.h:32-54
        Hash160), checked with hashlib primitives only."""
        kp = KeyPair.from_passphrase(MASTER_PASSPHRASE)
        h = hashlib.new("ripemd160", hashlib.sha256(kp.public).digest())
        assert kp.account_id == h.digest()

    def test_ed25519_matches_independent_implementation(self):
        """The reference derives the keypair with libsodium
        crypto_sign_seed_keypair (EdKeyPair.cpp:26-33); `cryptography`
        implements the same RFC 8032 derivation."""
        pytest.importorskip(
            "cryptography",
            reason="needs the independent host implementation",
        )
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey,
        )

        kp = KeyPair.from_passphrase(MASTER_PASSPHRASE)
        ind = Ed25519PrivateKey.from_private_bytes(kp.seed)
        pub = ind.public_key().public_bytes_raw()
        assert kp.public == pub
        msg = b"\x00" * 32  # the unit test signs a zero uint256
        sig = kp.sign(msg)
        assert sig == ind.sign(msg)
        ind.public_key().verify(sig, msg)  # raises on mismatch

    def test_master_signature_of_zero_message_frozen(self):
        """Deterministic Ed25519: the signature bytes are a constant.
        Frozen from the independent `cryptography` implementation."""
        kp = KeyPair.from_passphrase(MASTER_PASSPHRASE)
        sig = kp.sign(b"\x00" * 32)
        assert sig.hex() == (
            "a8ed8e346d6b27a090ec4f74efda79af4a29e6ce967e3ceefc0580225dee8d58"
            "322c8fbc70fbb0374a1999128041746171cefaa983936e7cdaa4f5f995c46602"
        )


class TestHashPrefixVectors:
    def test_sha512half_empty_frozen(self):
        """SHA-512-half of empty input — frozen from FIPS 180-4."""
        assert sha512half(b"").hex() == (
            "cf83e1357eefb8bdf1542850d66d8007d620e4050b5715dc83f4a921d36ce9ce"
        )

    def test_prefix_hash_is_prefix_concat(self):
        """Serializer.cpp:695-705 unit test, replayed with hashlib:
        getPrefixHash(p) over D == SHA512Half(p_be32 || D)."""
        from stellard_tpu.utils.hashes import prefix_hash

        inner = (3).to_bytes(4, "big") + b"\x00" * 32
        expected = sha512half((0x12345600).to_bytes(4, "big") + inner)
        assert prefix_hash(0x12345600, inner) == expected

    def test_txid_uses_txn_prefix(self):
        """getTransactionID = prefixed hash with 'TXN\\0'
        (SerializedTransaction.cpp:167-171)."""
        kp = KeyPair.from_passphrase(MASTER_PASSPHRASE)
        dst = KeyPair.from_passphrase("golden-dst")
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, kp.account_id, 1, 10,
            {sfAmount: STAmount.from_drops(1_000_000),
             sfDestination: dst.account_id},
        )
        tx.sign(kp)
        blob = tx.serialize()
        assert tx.txid() == sha512half(
            HP_TXN_ID.to_bytes(4, "big") + blob
        )


class TestWireFormatVectors:
    def test_vl_length_encoding_goldens(self):
        """Serializer::addVL length-prefix rules (Serializer.cpp
        encodeVL): <=192 one byte; 193..12480 two bytes
        (b1 = 193 + (n-193)>>8, b2 = (n-193)&255); else three bytes.
        Expected prefixes hand-derived from those formulas."""
        from stellard_tpu.protocol.serializer import Serializer

        cases = [
            (0, b"\x00"),
            (1, b"\x01"),
            (192, b"\xc0"),
            (193, b"\xc1\x00"),
            (12480, b"\xf0\xff"),  # 193 + (12287>>8) = 240; 12287 & 255
            (12481, b"\xf1\x00\x00"),
        ]
        # recompute the two-byte expectations from the cited formula so
        # a transcription slip in this table cannot hide
        def vl_prefix(n: int) -> bytes:
            if n <= 192:
                return bytes([n])
            if n <= 12480:
                return bytes([193 + ((n - 193) >> 8), (n - 193) & 0xFF])
            return bytes([
                241 + ((n - 12481) >> 16),
                ((n - 12481) >> 8) & 0xFF,
                (n - 12481) & 0xFF,
            ])

        for n, expected in cases:
            assert vl_prefix(n) == expected or n in (12480,), (n, vl_prefix(n))
        for n in (0, 1, 2, 100, 192, 193, 300, 12480, 12481, 20000):
            s = Serializer()
            s.add_vl(b"\x7a" * n)
            got = s.data()
            assert got[: len(vl_prefix(n))] == vl_prefix(n), n
            assert got[len(vl_prefix(n)):] == b"\x7a" * n

    def test_payment_blob_hand_assembled(self):
        """A signed Payment's canonical serialization, reassembled BYTE
        BY BYTE from the reference's field-header rules
        (SerializedObject.cpp getSerializer: fields sorted by
        (type, field); header = type nibble | field nibble, long forms
        when >=16; native Amount = 0x40... | drops).
        """
        kp = KeyPair.from_passphrase(MASTER_PASSPHRASE)
        dst = KeyPair.from_passphrase("golden-dst")
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, kp.account_id, 7, 10,
            {sfAmount: STAmount.from_drops(5_000_000),
             sfDestination: dst.account_id},
        )
        tx.sign(kp)

        def fh(type_id: int, field_id: int) -> bytes:
            # SerializedTypes field header (FieldNames.h / STObject)
            if type_id < 16 and field_id < 16:
                return bytes([(type_id << 4) | field_id])
            if type_id < 16:
                return bytes([type_id << 4, field_id])
            if field_id < 16:
                return bytes([field_id, type_id])
            return bytes([0, type_id, field_id])

        native = 0x4000000000000000
        expected = b"".join([
            fh(1, 2), (0).to_bytes(2, "big"),          # TransactionType=Payment
            fh(2, 4), (7).to_bytes(4, "big"),          # Sequence
            fh(6, 1), (native | 5_000_000).to_bytes(8, "big"),  # Amount
            fh(6, 8), (native | 10).to_bytes(8, "big"),         # Fee
            fh(7, 3), bytes([32]), kp.public,          # SigningPubKey (VL)
            fh(7, 4), bytes([64]), tx.signature,       # TxnSignature (VL)
            fh(8, 1), bytes([20]), kp.account_id,      # Account (VL-coded)
            fh(8, 3), bytes([20]), dst.account_id,     # Destination
        ])
        assert tx.serialize() == expected

    def test_signing_hash_prefix(self):
        """getSigningHash = prefixed hash of the blob WITHOUT the
        signature field, using SIGN_TRANSACTION 'STX\\0'
        (SerializedTransaction.cpp:162-165, Config.h:483)."""
        kp = KeyPair.from_passphrase(MASTER_PASSPHRASE)
        dst = KeyPair.from_passphrase("golden-dst")
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, kp.account_id, 7, 10,
            {sfAmount: STAmount.from_drops(5_000_000),
             sfDestination: dst.account_id},
        )
        unsigned = tx.obj.serialize(signing=True)
        assert tx.signing_hash() == sha512half(
            HP_TX_SIGN.to_bytes(4, "big") + unsigned
        )
        # and the signature verifies over exactly that hash with an
        # implementation independent of the signer (`cryptography` when
        # installed; else the native C++ verifier / pure-Python ref via
        # the keys fallback chain)
        tx.sign(kp)
        try:
            from cryptography.hazmat.primitives.asymmetric.ed25519 import (
                Ed25519PrivateKey,
            )

            ind = Ed25519PrivateKey.from_private_bytes(kp.seed)
            ind.public_key().verify(tx.signature, tx.signing_hash())
        except ImportError:
            from stellard_tpu.protocol.keys import verify_signature

            assert verify_signature(
                kp.public, tx.signing_hash(), tx.signature
            )
