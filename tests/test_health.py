"""SLO health watchdog + flight recorder (PR 18 tentpole legs 3/4).

Every rule is exercised with crafted feeds/snapshots and a virtual
clock, so each verdict is deterministic:

- close cadence: stall warn/crit lines and the EWMA drift rule;
- validation lag warn/crit, and the closed>=validated ordering
  invariant under the note_validated feed;
- fanout delivery p99 over registered latency hists;
- routing flips counted as window deltas via on_snapshot;
- cache hit collapse — ONLY with real traffic (the volume guard: a
  fresh cache with hit_rate=0 is silent, not sick);
- persist backlog gauges;
- no data at all => ok (rules without evidence report nothing — the
  anti-vacuity gate lives in the scenario fuzzer, tests/test_search.py);
- transitions: counted once per status change, on_transition observers
  fire, `health.*` tracer instants land, the flight recorder keeps the
  transition;
- FlightRecorder: bounded deques, atomic dump (valid JSON, no .tmp
  litter, path recorded in .dumps), unwritable directory returns None
  instead of raising.
"""

from __future__ import annotations

import json
import os

from stellard_tpu.node.health import (
    HEALTH_CRITICAL,
    HEALTH_OK,
    HEALTH_WARN,
    FlightRecorder,
    HealthWatchdog,
)
from stellard_tpu.node.tracer import Tracer


def _wd(**kw):
    """Watchdog on a virtual clock the test advances by hand."""
    clock = [0.0]
    kw.setdefault("target_close_s", 1.0)
    kw.setdefault("stall_warn_s", 10.0)
    kw.setdefault("stall_crit_s", 30.0)
    wd = HealthWatchdog(clock=lambda: clock[0], **kw)
    return wd, clock


class TestCadenceRules:
    def test_no_data_is_ok(self):
        wd, clock = _wd()
        clock[0] = 1000.0  # however late, silence is not a stall
        assert wd.evaluate() == HEALTH_OK
        assert wd.reasons == []

    def test_stall_warn_then_critical(self):
        # drift_factor parked high: this test isolates the stall lines,
        # and the 31s recovery gap would legitimately trip the EWMA rule
        wd, clock = _wd(drift_factor=100.0)
        wd.note_close(1)
        clock[0] = 5.0
        assert wd.evaluate() == HEALTH_OK
        clock[0] = 11.0
        assert wd.evaluate() == HEALTH_WARN
        assert any(r.startswith("close_stall") for r in wd.reasons)
        clock[0] = 31.0
        assert wd.evaluate() == HEALTH_CRITICAL
        # recovery: a close clears the stall on the next evaluation
        wd.note_close(2)
        clock[0] = 32.0
        assert wd.evaluate() == HEALTH_OK

    def test_ewma_drift_trips_before_stall(self):
        wd, clock = _wd(drift_factor=2.5)
        # steady closes at 4x the 1s target: each gap is under the 10s
        # stall line but the EWMA settles near 4s > 2.5 x 1s
        for i in range(10):
            clock[0] = i * 4.0
            wd.note_close(i + 1)
        clock[0] += 1.0
        assert wd.evaluate() == HEALTH_WARN
        assert any(r.startswith("close_drift") for r in wd.reasons)

    def test_on_target_cadence_stays_ok(self):
        wd, clock = _wd(drift_factor=2.5)
        for i in range(20):
            clock[0] = i * 1.0
            wd.note_close(i + 1)
        clock[0] += 0.5
        assert wd.evaluate() == HEALTH_OK


class TestLagRule:
    def test_validation_lag_warn_and_crit(self):
        wd, _ = _wd(lag_warn=4, lag_crit=16)
        wd.note_seqs(closed=10, validated=8)
        assert wd.evaluate() == HEALTH_OK
        wd.note_seqs(closed=13, validated=8)
        assert wd.evaluate() == HEALTH_WARN
        assert wd.reasons == ["validation_lag:5"]
        wd.note_seqs(closed=40, validated=8)
        assert wd.evaluate() == HEALTH_CRITICAL

    def test_zero_validated_never_lags(self):
        # a node that has never seen a validation (bootstrap) is silent
        wd, _ = _wd()
        wd.note_seqs(closed=100, validated=0)
        assert wd.evaluate() == HEALTH_OK

    def test_note_validated_keeps_pair_ordered(self):
        wd, _ = _wd()
        wd.note_validated(7)  # validated implies closed
        assert wd.get_json()["closed_seq"] == 7
        assert wd.get_json()["validated_seq"] == 7
        wd.note_validated(5)  # never regresses
        assert wd.get_json()["validated_seq"] == 7
        assert wd.evaluate() == HEALTH_OK


class TestSnapshotRules:
    def test_fanout_p99(self):
        wd, _ = _wd(fanout_p99_warn_ms=250.0)
        snap = {"hists": {"subs.fanout_lag": {"count": 50, "p99_ms": 400.0}}}
        assert wd.evaluate(snap=snap) == HEALTH_WARN
        assert wd.reasons == ["fanout_p99:subs.fanout_lag=400ms"]
        # an empty hist (count 0) reports nothing
        snap = {"hists": {"subs.fanout_lag": {"count": 0, "p99_ms": 400.0}}}
        assert wd.evaluate(snap=snap) == HEALTH_OK
        # unrelated hists are ignored no matter the p99
        snap = {"hists": {"close.pipeline": {"count": 9, "p99_ms": 9000.0}}}
        assert wd.evaluate(snap=snap) == HEALTH_OK

    def test_routing_flips_window_deltas(self):
        wd, _ = _wd(flips_warn=8)
        # flips arrive as cumulative counters: the rule fires on the
        # windowed DELTA sum, not the lifetime value
        wd.on_snapshot({"ts": 1.0, "counters": {},
                        "hooks": {"verify_routing.flips": 0}})
        assert wd.status == HEALTH_OK
        wd.on_snapshot({"ts": 2.0, "counters": {},
                        "hooks": {"verify_routing.flips": 12}})
        assert wd.status == HEALTH_WARN
        assert wd.reasons == ["routing_flips:12"]

    def test_flips_counter_name_variant(self):
        wd, _ = _wd(flips_warn=2)
        wd.on_snapshot({"ts": 1.0, "counters": {"hash.routing_flip": 0}})
        wd.on_snapshot({"ts": 2.0, "counters": {"hash.routing_flip": 5}})
        assert wd.status == HEALTH_WARN

    def test_cache_collapse_needs_traffic(self):
        wd, _ = _wd(cache_hit_warn=0.10)
        # fresh cache: zero hit rate, zero traffic -> silent
        snap = {"gauges": {}, "hooks": {"cache.hit_rate": 0.0,
                                        "cache.hits": 0,
                                        "cache.misses": 3}}
        assert wd.evaluate(snap=snap) == HEALTH_OK
        # same rate with real traffic -> collapse
        snap = {"gauges": {}, "hooks": {"cache.hit_rate": 0.02,
                                        "cache.hits": 4,
                                        "cache.misses": 196}}
        assert wd.evaluate(snap=snap) == HEALTH_WARN
        assert wd.reasons == ["cache_collapse:cache.hit_rate=0.02"]
        # healthy rate with traffic -> ok
        snap = {"gauges": {}, "hooks": {"cache.hit_rate": 0.9,
                                        "cache.hits": 900,
                                        "cache.misses": 100}}
        assert wd.evaluate(snap=snap) == HEALTH_OK

    def test_persist_backlog(self):
        wd, _ = _wd(persist_depth_warn=512.0)
        snap = {"gauges": {"persist.queue_depth": 513.0}}
        assert wd.evaluate(snap=snap) == HEALTH_WARN
        snap = {"gauges": {"persist.queue_depth": 12.0}}
        assert wd.evaluate(snap=snap) == HEALTH_OK

    def test_worst_rule_wins(self):
        wd, clock = _wd()
        wd.note_close(1)
        clock[0] = 31.0  # critical stall
        snap = {"gauges": {"persist.queue_depth": 9999.0}}  # plus a warn
        assert wd.evaluate(snap=snap) == HEALTH_CRITICAL
        assert len(wd.reasons) == 2


class TestTransitions:
    def test_transition_accounting_and_observers(self):
        flight = FlightRecorder(spans_cap=64)
        tracer = Tracer(enabled=True, sample=1.0)
        clock = [0.0]
        wd = HealthWatchdog(stall_warn_s=10.0, stall_crit_s=30.0,
                            drift_factor=100.0,
                            tracer=tracer, flight=flight,
                            clock=lambda: clock[0])
        seen = []
        wd.on_transition.append(lambda old, new, rs: seen.append((old, new)))
        wd.note_close(1)
        assert wd.evaluate() == HEALTH_OK
        assert wd.transitions == 0
        clock[0] = 11.0
        wd.evaluate()
        clock[0] = 12.0
        wd.evaluate()  # still warn: NOT a second transition
        assert wd.transitions == 1
        assert seen == [(HEALTH_OK, HEALTH_WARN)]
        wd.note_close(2)
        clock[0] = 13.0
        wd.evaluate()
        assert wd.transitions == 2
        assert seen == [(HEALTH_OK, HEALTH_WARN), (HEALTH_WARN, HEALTH_OK)]
        # each transition left a health.* tracer instant...
        names = [e["name"] for e in tracer.chrome_trace()["traceEvents"]]
        assert "health.warn" in names and "health.ok" in names
        # ...and a flight-recorder transition record
        assert flight.get_json()["transitions"] == 2

    def test_observer_exception_never_breaks_watchdog(self):
        wd, clock = _wd()
        wd.on_transition.append(lambda *_a: 1 / 0)
        wd.note_close(1)
        clock[0] = 11.0
        assert wd.evaluate() == HEALTH_WARN  # no raise

    def test_get_json_shape(self):
        wd, clock = _wd()
        wd.note_close(3)
        clock[0] = 2.0
        wd.note_close(4)
        wd.evaluate()
        j = wd.get_json()
        assert j["status"] == HEALTH_OK
        assert j["closed_seq"] == 4
        assert j["evaluations"] == 1
        assert j["ewma_close_gap_s"] == 2.0


class TestFlightRecorder:
    def test_bounded_feeds(self):
        fr = FlightRecorder(spans_cap=16, events_cap=4)
        for i in range(1000):
            fr.note_span("X", f"s{i}", "tx", None, 1.0)
            fr.note_transition("warn", ["r"], float(i))
        p = fr.payload("test")
        assert len(p["spans"]) == 16
        assert len(p["health_transitions"]) == 4
        # newest survive
        assert p["spans"][-1][2] == "s999"

    def test_dump_atomic_valid_json(self, tmp_path):
        fr = FlightRecorder(directory=str(tmp_path), spans_cap=32)
        fr.note_span("X", "close.pipeline", "ledger", "ledger-7", 12.5)
        fr.note_transition("critical", ["close_stall:31.0s>30s"], 31.0)
        fr.note_counters({"ts": 31.0, "counters": {"close.count": 7}})
        path = fr.dump("degraded-tracking")
        assert path is not None and os.path.exists(path)
        assert fr.dumps == [path]
        assert "degraded-tracking" in os.path.basename(path)
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)  # complete, parseable JSON
        assert obj["reason"] == "degraded-tracking"
        assert obj["spans"][-1][2] == "close.pipeline"
        assert obj["health_transitions"][0][1] == "critical"
        assert obj["counter_snapshots"][0]["counters"]["close.count"] == 7
        # no torn temp files left behind
        assert [p for p in os.listdir(tmp_path) if p.endswith(".tmp")] == []

    def test_dump_reason_sanitized_and_numbered(self, tmp_path):
        fr = FlightRecorder(directory=str(tmp_path))
        p1 = fr.dump("crash: /dev/null !!")
        p2 = fr.dump("crash: /dev/null !!")
        assert p1 != p2  # numbered, never overwrites
        assert "/" not in os.path.basename(p1).replace("flight-", "", 1)
        assert fr.dumps == [p1, p2]

    def test_unwritable_directory_returns_none(self):
        fr = FlightRecorder(directory="/proc/definitely-not-writable")
        assert fr.dump("crash") is None
        assert fr.dumps == []

    def test_get_json_counts(self):
        fr = FlightRecorder(spans_cap=16)
        fr.note_span("i", "health.warn", "health", None, 0.0)
        j = fr.get_json()
        assert j == {"spans": 1, "transitions": 0, "dumps": []}
