"""The optional-`cryptography` seam: the pure-Python RFC 8032 path must
be byte-identical with the host library, because a box without the
wheel derives keys and signs with it (protocol/keys.py falls back to
ops/ed25519_ref). Pinned against the RFC 8032 test vectors so the
fallback stays covered even on boxes WITH the wheel installed."""

import pytest

from stellard_tpu.ops import ed25519_ref as ref
from stellard_tpu.protocol.keys import (
    HAVE_CRYPTOGRAPHY,
    KeyPair,
    verify_signature,
)

# RFC 8032 §7.1 test vectors (seed, public, message, signature)
VECTORS = [
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


class TestRfc8032Vectors:
    @pytest.mark.parametrize("seed,public,msg,sig", VECTORS)
    def test_derive_sign_verify(self, seed, public, msg, sig):
        seed_b = bytes.fromhex(seed)
        pub_b = bytes.fromhex(public)
        msg_b = bytes.fromhex(msg)
        sig_b = bytes.fromhex(sig)
        assert ref.derive_public(seed_b) == pub_b
        assert ref.sign(seed_b, pub_b, msg_b) == sig_b
        assert ref.verify(pub_b, msg_b, sig_b)
        assert not ref.verify(pub_b, msg_b + b"x", sig_b)

    def test_fixed_base_comb_matches_ladder(self):
        # the comb-accelerated [s]B must equal the bit-serial ladder
        for s in (1, 2, 7, ref.L - 1, 0x1234567890ABCDEF):
            assert ref.pt_encode(ref.scalar_mult_base(s)) == ref.pt_encode(
                ref.scalar_mult(s, ref.BASE)
            )


class TestKeyPairSeam:
    def test_keypair_round_trip_is_self_consistent(self):
        kp = KeyPair.from_passphrase("fallback-seam")
        h = b"\x42" * 32
        sig = kp.sign(h)
        assert verify_signature(kp.public, h, sig)
        assert not verify_signature(kp.public, b"\x43" * 32, sig)

    def test_keypair_matches_reference_implementation(self):
        # whichever backend KeyPair uses, it must match the pure-Python
        # reference byte-for-byte (both claim RFC 8032)
        kp = KeyPair.from_passphrase("fallback-seam")
        assert kp.public == ref.derive_public(kp.seed)
        h = b"\x42" * 32
        assert kp.sign(h) == ref.sign(kp.seed, kp.public, h)

    @pytest.mark.skipif(
        not HAVE_CRYPTOGRAPHY, reason="cryptography wheel not installed"
    )
    def test_wheel_path_in_use_when_available(self):
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey,
        )

        kp = KeyPair.from_passphrase("fallback-seam")
        ind = Ed25519PrivateKey.from_private_bytes(kp.seed)
        assert ind.public_key().public_bytes_raw() == kp.public
