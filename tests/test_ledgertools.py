"""Dump / transaction-stream / replay tooling tests
(reference coverage: LedgerDump.cpp modes, --replay)."""

from __future__ import annotations

import io

import pytest

from stellard_tpu.engine.engine import TxParams
from stellard_tpu.node.ledgermaster import LedgerMaster
from stellard_tpu.node.ledgertools import (
    replay_range,
    dump_ledger,
    dump_transactions,
    load_transactions,
    replay_ledger,
)
from stellard_tpu.nodestore.core import make_database
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import sfAmount, sfBalance, sfDestination
from stellard_tpu.protocol.stamount import STAmount
from stellard_tpu.protocol.sttx import SerializedTransaction

XRP = 1_000_000
MASTER = KeyPair.from_passphrase("masterpassphrase")


def payment(key, seq, dest, drops):
    tx = SerializedTransaction.build(
        TxType.ttPAYMENT, key.account_id, seq, 10,
        {sfAmount: STAmount.from_drops(drops), sfDestination: dest},
    )
    tx.sign(key)
    return tx


@pytest.fixture()
def chain():
    """A 4-ledger chain with payments, persisted to a memory NodeStore."""
    lm = LedgerMaster()
    lm.start_new_ledger(MASTER.account_id, close_time=1000)
    db = make_database(type="memory")
    accounts = [KeyPair.from_passphrase(f"lt-{i}") for i in range(3)]
    ledgers = []
    mseq = 1
    for i, acct in enumerate(accounts):
        tx = payment(MASTER, mseq, acct.account_id, (1000 + i) * XRP)
        mseq += 1
        ter, _ = lm.do_transaction(tx, TxParams.OPEN_LEDGER)
        assert int(ter) == 0
        closed, _ = lm.close_and_advance(2000 + i * 10, 30)
        closed.save(db)
        ledgers.append(closed)
    return lm, db, ledgers, accounts


class TestDumpLedger:
    def test_dump_round_numbers(self, chain):
        _lm, _db, ledgers, accounts = chain
        j = dump_ledger(ledgers[-1])
        assert j["ledger_index"] == ledgers[-1].seq
        assert j["ledger_hash"] == ledgers[-1].hash().hex().upper()
        assert len(j["transactions"]) == 1
        # all three paid accounts plus master are in state
        assert len(j["accountState"]) >= 4


class TestTxStreams:
    def test_dump_then_load_reproduces_balances(self, chain):
        _lm, _db, ledgers, accounts = chain
        buf = io.StringIO()
        n = dump_transactions(iter(ledgers), buf)
        assert n == 3
        buf.seek(0)
        lm2 = LedgerMaster()
        lm2.start_new_ledger(MASTER.account_id, close_time=1000)
        applied, failed = load_transactions(buf, lm2)
        assert (applied, failed) == (3, 0)
        led = lm2.current_ledger()
        for i, acct in enumerate(accounts):
            root = led.account_root(acct.account_id)
            assert root[sfBalance].drops() == (1000 + i) * XRP


class TestReplay:
    def test_replay_reproduces_exact_hash(self, chain):
        _lm, db, ledgers, _accounts = chain
        for target in ledgers[1:]:
            stats = replay_ledger(db, target.hash())
            assert stats["ok"], stats
            assert stats["state_hash_ok"] and stats["tx_hash_ok"]
            assert stats["tx_count"] == 1

    def test_replay_detects_divergence(self, chain):
        """A corrupted parent state must fail the hash comparison, not
        silently pass — replay is a correctness oracle."""
        _lm, db, ledgers, accounts = chain
        target = ledgers[-1]
        stats = replay_ledger(db, target.hash())
        assert stats["ok"]
        # sanity: replaying with the wrong target hash raises (missing key)
        with pytest.raises((KeyError, ValueError)):
            replay_ledger(db, b"\x13" * 32)

    def test_replay_batched_reverify_seam(self, chain):
        """Replay re-verifies every tx signature in ONE batched
        verify_many call and memoizes the verdicts (catch-up trust
        model, HashRouter SF_SIGGOOD role). A refused verdict makes the
        replay diverge instead of silently trusting stored history."""
        _lm, db, ledgers, _accounts = chain
        target = ledgers[-1]

        calls = []

        def spy_ok(reqs):
            calls.append(len(reqs))
            import numpy as np

            return np.ones(len(reqs), bool)

        stats = replay_ledger(db, target.hash(), verify_many=spy_ok)
        assert stats["ok"]
        assert calls == [stats["tx_count"]], "one batch for the whole set"

        def spy_reject(reqs):
            import numpy as np

            return np.zeros(len(reqs), bool)

        stats = replay_ledger(db, target.hash(), verify_many=spy_reject)
        assert not stats["ok"], "rejected signatures must fail the replay"

    def test_replay_range_one_batch_for_the_whole_span(self, chain):
        """Bulk catch-up (replay_range) verifies EVERY signature across
        the ledger span in ONE verify_many call — the TPU-native
        formulation of the reference's per-ledger history re-check —
        and reproduces every ledger hash."""
        _lm, db, ledgers, _accounts = chain
        hashes = [l.hash() for l in ledgers[1:]]

        calls = []

        def spy_ok(reqs):
            import numpy as np

            calls.append(len(reqs))
            return np.ones(len(reqs), bool)

        stats = replay_range(db, hashes, verify_many=spy_ok)
        assert stats["ok"], stats
        assert stats["ledger_count"] == len(hashes)
        assert calls == [stats["tx_count"]], "one batch for the whole SPAN"
        assert stats["tx_count"] == sum(
            s["tx_count"] for s in stats["ledgers"]
        )

    def test_replay_range_bad_sig_fails_only_its_ledger(self, chain):
        """A rejected historic signature fails its own ledger's replay,
        not the whole span — identical verdict semantics to per-ledger
        replay."""
        _lm, db, ledgers, _accounts = chain
        hashes = [l.hash() for l in ledgers[1:]]

        seen = {"n": 0}

        def reject_first(reqs):
            import numpy as np

            out = np.ones(len(reqs), bool)
            if seen["n"] == 0:
                out[0] = False  # first tx of the span = first ledger's tx
            seen["n"] += 1
            return out

        stats = replay_range(db, hashes, verify_many=reject_first)
        assert not stats["ok"]
        per = stats["ledgers"]
        assert not per[0]["ok"], "the corrupted ledger fails"
        assert all(s["ok"] for s in per[1:]), "later ledgers unaffected"
