"""Load plane: fee escalation, backlog shed, deadlock watchdog.

Reference behaviors (SURVEY §2.2 LoadFeeTrack/LoadMonitor, §2.1
LoadManager; VERDICT r2 'no overload behavior is testable'):
- sustained job-queue overload raises the local load fee geometrically;
  recovery decays it back to normal (LoadFeeTrackImp.cpp),
- the scaled open-ledger fee actually rejects under-paying transactions
  with telINSUF_FEE_P (Transactor::payFee + Ledger::scaleFeeLoad),
- network-tx intake sheds outright past a 100-job backlog
  (PeerImp.cpp:64-66),
- the deadlock canary fires when the heartbeat stops (LoadManager.cpp
  81-204).
"""

from __future__ import annotations

import time

import pytest

from stellard_tpu.node.config import Config
from stellard_tpu.node.jobqueue import JobQueue, JobType
from stellard_tpu.node.loadmgr import (
    LoadFeeTrack,
    LoadManager,
    NORMAL_FEE,
    TX_BACKLOG_SHED,
)
from stellard_tpu.node.node import Node
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import sfAmount, sfDestination
from stellard_tpu.protocol.stamount import STAmount
from stellard_tpu.protocol.sttx import SerializedTransaction
from stellard_tpu.protocol.ter import TER

XRP = 1_000_000


class TestLoadFeeTrack:
    def test_raise_lower_dynamics(self):
        ft = LoadFeeTrack()
        assert ft.load_factor == NORMAL_FEE and not ft.is_loaded
        for _ in range(4):
            ft.raise_local_fee()
        raised = ft.load_factor
        assert raised > NORMAL_FEE
        # the single fee-scaling implementation is Ledger.scale_fee_load,
        # driven by the factor stamped from this track
        from stellard_tpu.state.ledger import Ledger

        led = Ledger(seq=1)
        led.load_factor = raised
        assert led.scale_fee_load(10) == 10 * raised // NORMAL_FEE
        assert led.scale_fee_load(10, admin=True) == 10  # admin never scaled
        while ft.is_loaded:
            ft.lower_local_fee()
        assert ft.load_factor == NORMAL_FEE
        led.load_factor = ft.load_factor
        assert led.scale_fee_load(10) == 10

    def test_remote_fee_merges(self):
        ft = LoadFeeTrack()
        ft.set_remote_fee(512)
        assert ft.load_factor == 512  # max(local, remote)

    def test_remote_report_freshness_ordering(self):
        """A relayed copy of a report we already hold (same or older
        report_time) must neither refresh its TTL nor overwrite a fresher
        direct report — only strictly newer reports land (reference:
        TMCluster carries the ORIGINAL reportTime so receivers keep only
        the newest)."""
        ft = LoadFeeTrack()
        src = b"\x02" * 33
        ft.set_remote_fee(512, source=src, report_time=100)
        # stale relay: older report_time, different fee -> dropped
        ft.set_remote_fee(999, source=src, report_time=99)
        ft.set_remote_fee(999, source=src, report_time=100)  # same: dropped
        assert ft.load_factor == 512
        reports = ft.remote_reports()
        assert reports == [(src, 512, 100)]
        # strictly newer report wins (even lowering the fee)
        ft.set_remote_fee(300, source=src, report_time=101)
        assert ft.remote_reports() == [(src, 300, 101)]

    def test_remote_report_ttl_not_refreshed_by_relay(self):
        """Replaying the same report right before expiry must not extend
        its life — a crashed member's high-load report ages out even while
        other members keep relaying it."""
        ft = LoadFeeTrack()
        ft.REMOTE_TTL = 0.1
        src = b"\x03" * 33
        ft.set_remote_fee(800, source=src, report_time=50)
        time.sleep(0.06)
        ft.set_remote_fee(800, source=src, report_time=50)  # relay echo
        time.sleep(0.06)  # past the ORIGINAL expiry
        assert ft.load_factor == NORMAL_FEE
        assert ft.remote_reports() == []


class TestLoadFeeTrackConcurrency:
    """The track is hammered from several threads at once in production:
    the LoadManager watchdog (raise/lower), peer threads (set_remote_fee)
    and the TxQ close path (set_queue_fee), while RPC workers read
    load_factor. These tests pin the invariants that must hold under
    that interleaving."""

    def test_concurrent_raise_lower_remote_bounded(self):
        import threading

        ft = LoadFeeTrack()
        from stellard_tpu.node.loadmgr import MAX_FEE

        stop = threading.Event()
        violations = []

        def reader():
            while not stop.is_set():
                f = ft.load_factor
                if not (NORMAL_FEE <= f <= MAX_FEE):
                    violations.append(f)
                j = ft.get_json()
                if j["load_factor"] < max(j["local_fee"], j["remote_fee"],
                                          j["queue_fee"]):
                    violations.append(j)

        def raiser():
            for _ in range(400):
                ft.raise_local_fee()

        def lowerer():
            for _ in range(400):
                ft.lower_local_fee()

        def remote(i):
            src = bytes([i]) * 33
            for t in range(200):
                ft.set_remote_fee(NORMAL_FEE * (1 + t % 7), source=src,
                                  report_time=t)

        threads = (
            [threading.Thread(target=raiser) for _ in range(3)]
            + [threading.Thread(target=lowerer) for _ in range(3)]
            + [threading.Thread(target=remote, args=(i,)) for i in range(3)]
            + [threading.Thread(target=reader) for _ in range(2)]
        )
        for t in threads[:-2]:
            t.start()
        for t in threads[-2:]:
            t.start()
        for t in threads[:-2]:
            t.join()
        stop.set()
        for t in threads[-2:]:
            t.join()
        assert not violations
        # after the storm: lowering fully decays back to normal
        for _ in range(200):
            ft.lower_local_fee()
        ft._remote.clear()
        ft.set_queue_fee(0)
        assert ft.load_factor == NORMAL_FEE

    def test_load_factor_monotone_under_pure_raise_flood(self):
        """During a sustained overload (only raises arriving, remote
        reports static) sampled load_factor must never move DOWN — a
        dip would let a flood burst through under the stale lower fee."""
        import threading

        ft = LoadFeeTrack()
        ft.set_remote_fee(512, source=b"\x09" * 33, report_time=1)
        samples = []
        stop = threading.Event()

        def sampler():
            while not stop.is_set():
                samples.append(ft.load_factor)

        s = threading.Thread(target=sampler)
        s.start()
        for _ in range(300):
            ft.raise_local_fee()
        stop.set()
        s.join()
        assert samples == sorted(samples)

    def test_stale_remote_expiry_under_concurrent_readers(self):
        """Remote-report expiry is evaluated inside load_factor reads;
        concurrent readers must agree the report died after its TTL and
        the fee floor returns to the local component."""
        import threading

        ft = LoadFeeTrack()
        ft.REMOTE_TTL = 0.05
        ft.set_remote_fee(4096, source=b"\x0a" * 33, report_time=7)
        assert ft.load_factor == 4096
        time.sleep(0.08)
        results = []
        threads = [
            threading.Thread(target=lambda: results.append(ft.load_factor))
            for _ in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == [NORMAL_FEE] * 8
        assert ft.remote_reports() == []


class TestLoadManager:
    def test_overload_raises_then_recovers(self):
        jq = JobQueue(threads=2)
        ft = LoadFeeTrack()
        lm = LoadManager(jq, ft)
        # saturate with slow jtTRANSACTION jobs until the EWMA (which
        # includes queue wait) exceeds the 250ms target
        for _ in range(60):
            jq.add_job(JobType.jtTRANSACTION, "slow", lambda: time.sleep(0.02))
        deadline = time.monotonic() + 10
        while not jq.is_overloaded() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert jq.is_overloaded()
        lm.tick()
        assert ft.is_loaded
        jq.drain(10)
        # queue idle: ticks decay the fee back to normal
        for _ in range(50):
            lm.tick()
        assert not ft.is_loaded
        jq.stop()

    def test_deadlock_canary_fires_once(self):
        now = [0.0]
        fired = []
        lm = LoadManager(
            None,
            LoadFeeTrack(),
            clock=lambda: now[0],
            deadlock_timeout=500.0,
            on_deadlock=lambda: fired.append(1),
        )
        lm.jq = _IdleJq()
        lm.arm()
        now[0] = 499.0
        lm.tick()
        assert not fired
        lm.reset_deadlock_detector()
        now[0] = 998.0
        lm.tick()
        assert not fired  # heartbeat kept it alive
        now[0] = 1600.0
        lm.tick()
        lm.tick()
        assert fired == [1]  # fires exactly once


class _IdleJq:
    def is_overloaded(self):
        return False


class TestEndToEndLoad:
    @pytest.fixture()
    def node(self):
        n = Node(Config(standalone=True, signature_backend="cpu")).setup()
        yield n
        n.verify_plane.stop()
        n.job_queue.stop()

    def test_scaled_fee_rejects_underpayer(self, node):
        """With load escalation active, a tx paying the normal fee gets
        telINSUF_FEE_P; paying the scaled fee passes."""
        alice = KeyPair.from_passphrase("alice")
        master = node.master_keys
        for _ in range(8):
            node.fee_track.raise_local_fee()
        factor = node.fee_track.load_factor
        assert factor > NORMAL_FEE
        scaled = 10 * factor // NORMAL_FEE

        def pay(seq, fee):
            tx = SerializedTransaction.build(
                TxType.ttPAYMENT, master.account_id, seq, fee,
                {sfAmount: STAmount.from_drops(100 * XRP),
                 sfDestination: alice.account_id},
            )
            tx.sign(master)
            return node.ops.process_transaction(tx)

        ter, applied = pay(1, 10)
        assert ter == TER.telINSUF_FEE_P and not applied
        ter, applied = pay(1, scaled)
        assert ter == TER.tesSUCCESS and applied
        # load drops back to normal: base fee applies again
        while node.fee_track.is_loaded:
            node.fee_track.lower_local_fee()
        ter, applied = pay(2, 10)
        assert ter == TER.tesSUCCESS and applied

    def test_backlog_shed(self, node):
        """submit_transaction drops network txs past the 100-job backlog."""
        # wedge the queue with blockers so jtTRANSACTION jobs pile up
        import threading

        gate = threading.Event()
        for _ in range(len(node.job_queue._threads)):
            node.job_queue.add_job(
                JobType.jtTRANSACTION, "blocker", lambda: gate.wait(30)
            )
        alice = KeyPair.from_passphrase("alice")
        master = node.master_keys

        def submit(i):
            tx = SerializedTransaction.build(
                TxType.ttPAYMENT, master.account_id, i + 1, 10,
                {sfAmount: STAmount.from_drops(XRP),
                 sfDestination: alice.account_id},
            )
            tx.sign(master)
            node.ops.submit_transaction(tx)

        # wave 1: fill the backlog (verification is async, so wait for
        # the verified txs to land). Intake batching keeps the QUEUED
        # job count at ~1 — the backlog accumulates in ops._intake, and
        # the shed gate counts job_count + len(_intake); assert on the
        # gate's own quantity.
        def backlog():
            return (node.job_queue.get_job_count(JobType.jtTRANSACTION)
                    + len(node.ops._intake))

        for i in range(TX_BACKLOG_SHED + 20):
            submit(i)
        deadline = time.monotonic() + 15
        while backlog() <= TX_BACKLOG_SHED and time.monotonic() < deadline:
            time.sleep(0.02)
        assert backlog() > TX_BACKLOG_SHED
        # wave 2: intake now sheds at the door
        for i in range(TX_BACKLOG_SHED + 20, TX_BACKLOG_SHED + 40):
            submit(i)
        assert node.ops.stats.get("shed", 0) > 0
        gate.set()
        node.job_queue.drain(15)
