"""Multi-chip mesh integrated into the production verify plane
(VERDICT r2 #3): TpuVerifier shards over every visible device, exercised
here on the 8-device virtual CPU mesh the conftest pins.

Covers: uneven (padded) batches, invalid signatures landing in specific
shards, the psum count path, and the VerifyPlane wiring end-to-end.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

import jax

from stellard_tpu.crypto.backend import TpuVerifier, VerifyRequest
from stellard_tpu.ops import ed25519_ref as ref
from stellard_tpu.ops.ed25519_jax import prepare_batch
from stellard_tpu.parallel.mesh import make_mesh, verify_and_count
from stellard_tpu.protocol.keys import KeyPair

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual CPU mesh"
)


def make_reqs(n: int, corrupt: set[int] = frozenset()):
    rng = np.random.default_rng(3)
    keys = [KeyPair.from_seed(rng.bytes(32)) for _ in range(8)]
    reqs, want = [], []
    for i in range(n):
        k = keys[i % 8]
        m = rng.bytes(32)
        s = bytearray(k.sign(m))
        if i in corrupt:
            s[rng.integers(0, 64)] ^= 1 << int(rng.integers(0, 8))
        reqs.append(VerifyRequest(k.public, m, bytes(s)))
        want.append(ref.verify(k.public, m, bytes(s)))
    return reqs, np.array(want)


class TestMeshVerifier:
    def test_verifier_auto_meshes_over_all_devices(self):
        v = TpuVerifier(min_batch=64)
        v._resolve_kernel()
        assert v.n_devices == len(jax.devices())

    def test_uneven_batch_with_bad_sigs_in_specific_shards(self):
        # 300 requests pad to 512 over 8 shards of 64; corrupt indexes
        # chosen to land in shards 0, 3 and 7
        corrupt = {1, 2, 200, 290, 299}
        reqs, want = make_reqs(300, corrupt)
        v = TpuVerifier(min_batch=64)
        got = v.verify_batch(reqs)
        assert np.array_equal(got, want)
        assert not got[list(corrupt)].any()

    def test_pallas_impl_shards_over_the_mesh(self):
        """STELLARD_VERIFY_IMPL=pallas in mesh mode: each device runs
        the whole-verify-in-VMEM kernel on its batch shard (explicit
        shard_map — a pallas_call is a custom call XLA cannot
        auto-partition). Interpreter mode on the CPU mesh."""
        os.environ["STELLARD_VERIFY_IMPL"] = "pallas"
        # forced, not setdefault: an earlier node test's [kernel_tuning]
        # application may have set the 512 production default, and an
        # 8-shard interpreter run at block 512 is minutes of dead time.
        # (If ed25519_pallas is already imported this is a no-op — the
        # test sizes its batch from the ACTUAL P.BLOCK below.)
        prev_block = os.environ.get("STELLARD_PALLAS_BLOCK")
        os.environ["STELLARD_PALLAS_BLOCK"] = "128"
        try:
            from stellard_tpu.ops import ed25519_pallas as P

            # at least the mesh floor, or the small-batch bypass routes
            # the chunk to the single-chip kernel (by design)
            n = len(jax.devices()) * P.BLOCK
            corrupt = {0, n // 2, n - 1}
            reqs, want = make_reqs(n, corrupt)
            v = TpuVerifier(min_batch=64, max_batch=n)
            got = v.verify_batch(reqs)
            assert v.n_devices == len(jax.devices())
            assert np.array_equal(got, want)
            assert not got[list(corrupt)].any()

            # below the floor: the bypass must still verify correctly
            # (single-chip kernel on shard-sized padding)
            small_reqs, small_want = make_reqs(40, {3})
            got2 = v.verify_batch(small_reqs)
            assert np.array_equal(got2, small_want)
        finally:
            del os.environ["STELLARD_VERIFY_IMPL"]
            if prev_block is None:
                os.environ.pop("STELLARD_PALLAS_BLOCK", None)
            else:
                os.environ["STELLARD_PALLAS_BLOCK"] = prev_block

    def test_multi_chunk_pipeline(self):
        reqs, want = make_reqs(96, corrupt={5, 50})
        v = TpuVerifier(min_batch=8, max_batch=32)  # forces 3 chunks
        got = v.verify_batch(reqs)
        assert np.array_equal(got, want)

    def test_psum_count_with_shard_local_failures(self):
        n = 128
        corrupt = {0, 1, 64, 127}
        reqs, want = make_reqs(n, corrupt)
        inp = prepare_batch(
            [r.public for r in reqs],
            [r.signing_hash for r in reqs],
            [r.signature for r in reqs],
        )
        mesh = make_mesh()
        flags, total = verify_and_count(mesh)(
            inp["a_words"], inp["r_words"], inp["s_windows"],
            inp["h_digits"], inp["s_canonical"],
        )
        assert int(total) == int(want.sum())
        assert np.array_equal(np.asarray(flags), want)

    @pytest.mark.slow  # ~1.5 min wall clock on the CI box
    def test_verifyplane_uses_meshed_verifier(self):
        from stellard_tpu.node.verifyplane import VerifyPlane

        plane = VerifyPlane(backend="tpu", min_device_batch=8)
        try:
            reqs, want = make_reqs(64, corrupt={7})
            # force-teach the model that the device wins so routing is
            # deterministic in this test
            plane.model.observe_cpu(10, 1000.0)
            got = plane.verify_many(reqs)
            assert np.array_equal(got, want)
            assert plane.device_batches == 1
            assert isinstance(plane.verifier, TpuVerifier)
            assert plane.verifier.n_devices == len(jax.devices())
        finally:
            plane.stop()


class TestMeshedHashing:
    """The hashing twin: flat-batch SHA-512-half shards over the mesh."""

    def test_prefix_hash_batch_shards_and_matches_host(self):
        from stellard_tpu.crypto.backend import CpuHasher, TpuHasher

        rng = np.random.default_rng(5)
        prefixes = [0x54584E00] * 100
        payloads = [rng.bytes(int(rng.integers(10, 900))) for _ in range(100)]
        tpu = TpuHasher()
        got = tpu.prefix_hash_batch(prefixes, payloads)
        want = CpuHasher().prefix_hash_batch(prefixes, payloads)
        assert got == want
        assert tpu.n_devices == 8  # mesh="auto" default on the 8-dev env
        # the kernel in use really is the mesh-sharded jit (its input
        # shardings name the batch axis)
        kern = tpu._masked_kernel()
        shardings = getattr(kern, "_in_shardings", None) or getattr(
            kern, "in_shardings", None
        )
        if shardings is not None:  # jax version exposes them
            assert any(s is not None for s in shardings)

    def test_every_width_matches_host_bytes(self):
        """mesh= is a config axis: widths 1/2/4/8 of the SAME sharded
        program produce byte-identical digests on ragged batches (37
        messages — not divisible by any width)."""
        from stellard_tpu.crypto.backend import CpuHasher, TpuHasher

        rng = np.random.default_rng(11)
        prefixes = [0x4D494E00] * 37
        payloads = [rng.bytes(int(rng.integers(1, 700))) for _ in range(37)]
        want = CpuHasher().prefix_hash_batch(prefixes, payloads)
        for width in (1, 2, 4, 8):
            h = TpuHasher(mesh=str(width))
            assert h.prefix_hash_batch(prefixes, payloads) == want
            assert h.n_devices == width

    def test_non_pow2_width_rounds_down(self):
        from stellard_tpu.crypto.backend import TpuHasher

        h = TpuHasher(mesh="3")
        h.prefix_hash_batch([0x1234], [b"x"])
        assert h.n_devices == 2  # pow2 floor: the leaf batcher pads
        # rows to powers of two, only pow2 widths divide them evenly
