"""Sharded multi-chip crypto + hash plane (ISSUE 15).

Mesh width as a config axis: [signature_backend]/[hash_backend] mesh=
round-trips through config parsing with validation, backend options
reach the factories (and unknown keys fail loudly — the dead-config
seam), width 1 and width N execute the same routed plane, and the
three-way host/1-chip/N-chip cost routing picks arms by measured cost.
Byte identity is pinned sharded-vs-single-device-vs-host on ragged
batches, bad signatures in every shard position, and masked-SHA packed
buffers — all on the virtual 8-device CPU mesh, no TPU required.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from stellard_tpu.crypto.backend import (
    BatchHasher,
    BatchVerifier,
    CpuHasher,
    TpuVerifier,
    VerifyRequest,
    WatchdogHasher,
    _HashCostModel,
    make_hasher,
    make_verifier,
    make_watched_hasher,
    mesh_wants_width,
    parse_mesh,
    register_verifier,
    resolve_mesh_width,
)
from stellard_tpu.node.config import Config
from stellard_tpu.node.verifyplane import VerifyPlane, _LatencyModel
from stellard_tpu.ops import ed25519_ref as ref
from stellard_tpu.protocol.keys import KeyPair

EIGHT_DEVICES = len(jax.devices()) >= 8


def make_reqs(n: int, corrupt: set = frozenset(), seed: int = 9):
    rng = np.random.default_rng(seed)
    keys = [KeyPair.from_seed(rng.bytes(32)) for _ in range(8)]
    reqs, want = [], []
    for i in range(n):
        k = keys[i % 8]
        m = rng.bytes(32)
        s = bytearray(k.sign(m))
        if i in corrupt:
            s[int(rng.integers(0, 64))] ^= 1 << int(rng.integers(0, 8))
        reqs.append(VerifyRequest(k.public, m, bytes(s)))
        want.append(ref.verify(k.public, m, bytes(s)))
    return reqs, np.array(want)


class TestMeshAxisParsing:
    def test_parse_mesh_canonical_forms(self):
        assert parse_mesh(None) == "0"
        assert parse_mesh("") == "0"
        assert parse_mesh("off") == "0"
        assert parse_mesh(0) == "0"
        assert parse_mesh("4") == "4"
        assert parse_mesh(" AUTO ") == "auto"

    def test_parse_mesh_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_mesh("many")
        with pytest.raises(ValueError):
            parse_mesh("-2")

    def test_resolve_width_clamps_and_floors(self):
        assert resolve_mesh_width("0", 8) == 1
        assert resolve_mesh_width("auto", 8) == 8
        assert resolve_mesh_width("4", 8) == 4
        assert resolve_mesh_width("16", 8) == 8  # clamped, loudly
        assert resolve_mesh_width("auto", 1) == 1
        assert resolve_mesh_width("6", 8, pow2=True) == 4
        assert resolve_mesh_width("auto", 6, pow2=True) == 4

    def test_mesh_wants_width(self):
        assert mesh_wants_width("auto")
        assert mesh_wants_width("2")
        assert not mesh_wants_width("0")
        assert not mesh_wants_width("1")
        assert not mesh_wants_width(None)


class TestConfigRoundTrip:
    def test_mesh_round_trips_both_sections(self):
        cfg = Config.from_ini(
            "[signature_backend]\ntype=tpu\nmesh=4\nrouting=device\n"
            "[hash_backend]\ntype=tpu\nmesh=auto\nmin_device_nodes=32\n"
        )
        assert cfg.verify_mesh == "4"
        assert cfg.verify_routing == "device"
        assert cfg.hash_mesh == "auto"
        assert cfg.hash_min_device_nodes == 32

    def test_mesh_zero_and_defaults(self):
        cfg = Config.from_ini("[signature_backend]\ntype=tpu\nmesh=0\n")
        assert cfg.verify_mesh == "0"
        # defaults: auto (today's all-visible-devices behavior)
        cfg = Config.from_ini("[signature_backend]\ntype=tpu\n")
        assert cfg.verify_mesh == "auto"
        assert cfg.hash_mesh == "auto"
        assert cfg.verify_routing == "" and cfg.hash_routing == ""

    def test_mesh_on_host_backend_is_loud(self):
        with pytest.raises(ValueError, match="meaningless"):
            Config.from_ini("[signature_backend]\ntype=cpu\nmesh=4\n")
        with pytest.raises(ValueError, match="meaningless"):
            Config.from_ini("[hash_backend]\ntype=cpu\nmesh=auto\n")
        # mesh=0 with a host backend is fine (explicitly off)
        cfg = Config.from_ini("[signature_backend]\ntype=cpu\nmesh=0\n")
        assert cfg.verify_mesh == "0"

    def test_bad_mesh_and_routing_rejected(self):
        with pytest.raises(ValueError):
            Config.from_ini("[signature_backend]\ntype=tpu\nmesh=lots\n")
        with pytest.raises(ValueError, match="routing"):
            Config.from_ini("[hash_backend]\ntype=tpu\nrouting=maybe\n")

    def test_unknown_keys_fail_loudly(self):
        # the dead-config seam: use_mesh= parsed clean and did nothing
        with pytest.raises(ValueError, match="use_mesh"):
            Config.from_ini("[signature_backend]\ntype=tpu\nuse_mesh=1\n")
        with pytest.raises(ValueError, match="unknown key"):
            Config.from_ini("[hash_backend]\ntype=cpu\nfloor=64\n")

    def test_backend_mismatched_keys_fail_loudly(self):
        """Keys only one backend type honors must not parse clean and
        be silently dropped downstream (the dead-config class again)."""
        with pytest.raises(ValueError, match="only apply to type=tpu"):
            Config.from_ini("[hash_backend]\ntype=cpu\nrouting=device\n")
        with pytest.raises(ValueError, match="only apply to type=tpu"):
            Config.from_ini("[hash_backend]\ntype=cpu\nmin_device_nodes=5\n")
        with pytest.raises(ValueError, match="only apply to type=tpu"):
            Config.from_ini(
                "[signature_backend]\ntype=cpu\ndevice_first_timeout_s=2\n"
            )
        with pytest.raises(ValueError, match="only apply to host"):
            Config.from_ini("[signature_backend]\ntype=tpu\nthreads=16\n")

    def test_timeouts_threads_and_floors_plumbed(self):
        cfg = Config.from_ini(
            "[signature_backend]\ntype=tpu\ndevice_first_timeout_s=123\n"
            "device_warm_timeout_s=4.5\n"
            "[hash_backend]\ntype=tpu\ndevice_first_timeout_s=99\n"
        )
        assert cfg.verify_device_first_timeout_s == 123.0
        assert cfg.verify_device_warm_timeout_s == 4.5
        assert cfg.hash_device_first_timeout_s == 99.0
        cfg = Config.from_ini("[signature_backend]\ntype=cpu\nthreads=7\n")
        assert cfg.verify_threads == 7
        assert cfg.verify_backend_opts() == {"threads": 7}

    def test_verify_backend_opts_for_tpu(self):
        cfg = Config.from_ini(
            "[signature_backend]\ntype=tpu\nmesh=2\nmax_batch=512\n"
        )
        assert cfg.verify_backend_opts() == {"mesh": "2", "max_batch": 512}


class TestFactoryOptionValidation:
    def test_unknown_verifier_option_fails_loudly(self):
        with pytest.raises(ValueError, match="bogus"):
            make_verifier("cpu", bogus=1)
        with pytest.raises(ValueError, match="threads"):
            make_verifier("tpu", threads=4)

    def test_unknown_hasher_option_fails_loudly(self):
        with pytest.raises(ValueError, match="mesh"):
            make_hasher("cpu", mesh="4")

    def test_accepted_options_pass(self):
        v = make_verifier("tpu", mesh="2", min_batch=8, max_batch=64)
        assert isinstance(v, TpuVerifier)
        assert v.mesh == "2"
        h = make_hasher("tpu", mesh="0")
        assert h.mesh == "0"

    def test_bad_mesh_fails_at_build_not_first_batch(self):
        with pytest.raises(ValueError):
            make_verifier("tpu", mesh="wide")
        with pytest.raises(ValueError):
            make_hasher("tpu", mesh="-1")


@pytest.mark.skipif(not EIGHT_DEVICES, reason="needs the 8-device mesh")
class TestVerifierWidthIdentity:
    """Width is config, not code path: every width of the same sharded
    program returns byte-identical verdicts on ragged batches with bad
    signatures planted in every shard position of the widest mesh."""

    def test_every_width_matches_reference(self):
        # 61 sigs pad to 64: shard size 8 at width 8 — one corrupt
        # signature lands in every shard (position 58 covers the shard
        # that also holds the padding rows)
        corrupt = {0, 9, 17, 26, 33, 42, 49, 58}
        reqs, want = make_reqs(61, corrupt)
        for width in (1, 2, 4, 8):
            v = TpuVerifier(min_batch=8, max_batch=64, mesh=str(width))
            got = v.verify_batch(reqs)
            assert np.array_equal(got, want), f"width {width} diverged"
            assert v.n_devices == width
            assert v.kernel_selected == f"xla-sharded@{width}"
            assert not got[list(corrupt)].any()

    # NOTE: the three tests below deliberately use 40+-sig batches so
    # they pad to the SAME 64-row shapes the widths test compiles —
    # every fresh (pad-shape, width) combo is a multi-second XLA:CPU
    # compile on a cold cache, and identity is already pinned per shape

    def test_width_request_clamps_to_visible(self):
        v = TpuVerifier(min_batch=64, max_batch=64, mesh="16")
        reqs, want = make_reqs(40, {3})
        assert np.array_equal(v.verify_batch(reqs), want)
        assert v.n_devices == len(jax.devices())

    def test_mesh_zero_is_width_one_same_path(self):
        v = TpuVerifier(min_batch=64, max_batch=64, mesh="0")
        reqs, want = make_reqs(40, {0, 9})
        assert np.array_equal(v.verify_batch(reqs), want)
        assert v.n_devices == 1
        assert v.kernel_selected == "xla-sharded@1"

    def test_describe_reports_provenance(self):
        v = TpuVerifier(min_batch=64, max_batch=64, mesh="2")
        v.verify_batch(make_reqs(40)[0])
        d = v.describe()
        assert d["mesh_requested"] == "2"
        assert d["mesh_width"] == 2
        assert d["devices_visible"] == len(jax.devices())
        assert d["kernel"] == "xla-sharded@2"


class TestMeshFloorBypass:
    """The pallas small-batch bypass boundary, pinned with fake kernels
    (no interpreter wall-clock): padded sizes below _mesh_floor route to
    the single-chip kernel, at/above it to the sharded kernel."""

    def _fake(self, calls, tag):
        def kern(a_words, *rest):
            calls.append((tag, int(a_words.shape[0])))
            return np.ones(int(a_words.shape[0]), bool)

        return kern

    def test_boundary(self):
        v = TpuVerifier(min_batch=8, max_batch=64, mesh="8")
        calls = []
        v._kernel = self._fake(calls, "wide")
        v._small_kernel = self._fake(calls, "small")
        v._mesh_floor = 32
        v.n_devices = 8
        reqs, _ = make_reqs(9)  # pads to 16 < 32: bypass
        v.verify_batch(reqs)
        assert calls[-1][0] == "small"
        reqs, _ = make_reqs(30)  # pads to 32 == floor: sharded
        v.verify_batch(reqs)
        assert calls[-1][0] == "wide"


@pytest.mark.skipif(not EIGHT_DEVICES, reason="needs the 8-device mesh")
class TestHashPlaneIdentity:
    def test_packed_flat_identity_every_width(self):
        """hash_packed (the pack_nodes/seal-flush contract: blob ==
        hashed bytes) through the watched three-way plane, forced
        device, ragged 37-message buffer — byte parity with hashlib
        (CpuHasher) at every width."""
        rng = np.random.default_rng(13)
        msgs = [
            b"MIN\0" + rng.bytes(int(rng.integers(1, 500)))
            for _ in range(37)
        ]
        buf = b"".join(msgs)
        offsets = [0]
        for m in msgs:
            offsets.append(offsets[-1] + len(m))
        want = CpuHasher().hash_packed(buf, offsets)
        for width in ("0", "2", "8", "auto"):
            h = make_watched_hasher(
                "tpu", mesh=width, routing="device", min_device_nodes=0
            )
            assert h.hash_packed(buf, offsets) == want, f"width {width}"
            assert h.device_nodes == 37

    def test_tree_hash_parity_vs_host(self):
        """Whole-SHAMap hashing (the seal/drainer shape) through the
        meshed watched hasher == the host-hashed root, bytes."""
        from stellard_tpu.state.shamap import SHAMap, SHAMapItem, TNType

        rng = np.random.default_rng(17)

        def build(hash_batch=None):
            m = (SHAMap(TNType.ACCOUNT_STATE, hash_batch=hash_batch)
                 if hash_batch is not None
                 else SHAMap(TNType.ACCOUNT_STATE))
            r = np.random.default_rng(17)
            for _ in range(60):
                m.set_item(SHAMapItem(r.bytes(32), r.bytes(90)))
            return m

        host_root = build().get_hash()
        meshed = make_watched_hasher(
            "tpu", mesh="8", routing="device", min_device_nodes=0
        )
        dev_map = build(hash_batch=meshed)
        assert dev_map.get_hash() == host_root
        assert meshed.device_nodes > 0


class TestThreeArmCostModel:
    def test_explores_then_routes_cheapest(self):
        m = _HashCostModel(reexplore_every=8, arms=("dev1", "devN"))
        # declared order explored first while unmeasured
        assert m.choose(100) == "dev1"
        m.observe("dev1", 100, 100.0)  # compile sample: discarded
        assert m.choose(100) == "dev1"  # still unmeasured
        m.observe("dev1", 100, 4.0)
        assert m.choose(100) == "devN"  # next unmeasured arm
        m.observe("devN", 100, 100.0)
        m.observe("devN", 100, 12.0)
        assert m.choose(100) == "host"  # host measured once
        m.observe("host", 100, 100.0)  # 1 ms/node
        # 100 nodes: host 100ms, dev1 4ms, devN 12ms -> dev1
        assert m.choose(100) == "dev1"
        # teach the big bucket the opposite ordering: wide wins
        for _ in range(2):
            m.observe("dev1", 5000, 80.0)
            m.observe("devN", 5000, 20.0)
        assert m.choose(5000) == "devN"

    def test_small_batches_stay_on_host(self):
        m = _HashCostModel(
            reexplore_every=8, min_device_nodes=64, arms=("dev1", "devN")
        )
        assert m.choose(63) == "host"
        assert m.choose(64) == "dev1"

    def test_losing_arm_reexplored_bounded(self):
        m = _HashCostModel(reexplore_every=5, arms=("dev1", "devN"))
        for arm, ms in (("dev1", 10.0), ("devN", 30.0)):
            m.observe(arm, 100, 999.0)
            m.observe(arm, 100, ms)
        m.observe("host", 100, 10000.0)  # 100 ms/node: devices win
        # devN loses to dev1 but sits within 4x: re-explored every 5
        picks = [m.choose(100) for _ in range(11)]
        assert picks.count("devN") == 2
        assert all(p in ("dev1", "devN") for p in picks)

    def test_hopeless_arm_never_reexplored(self):
        m = _HashCostModel(reexplore_every=3, arms=("dev1", "devN"))
        for arm, ms in (("dev1", 1.0), ("devN", 50.0)):
            m.observe(arm, 100, 999.0)
            m.observe(arm, 100, ms)
        m.observe("host", 100, 200.0)  # 2 ms/node -> 200ms; dev1 wins
        # devN at 50ms is within 4x of dev1's 1ms? no: 50 > 4*1 — hopeless
        assert all(m.choose(100) == "dev1" for _ in range(20))

    def test_get_json_snapshots_all_arms(self):
        m = _HashCostModel(reexplore_every=8, arms=("dev1", "devN"))
        m.observe("dev1", 10, 5.0)
        m.observe("devN", 10, 7.0)
        j = m.get_json()
        assert set(j["arms"]) == {"dev1", "devN"}
        # legacy view tracks the PRIMARY (widest) arm — the one still
        # accumulating after a 1-chip arm collapse
        assert j["buckets"] == j["arms"]["devN"]

    def test_legacy_single_arm_shims(self):
        m = _HashCostModel(reexplore_every=8)
        m.observe_device(100, 999.0)
        m.observe_device(100, 5.0)
        m.observe_host(100, 1000.0)
        assert m.use_device(100)


class TestLatencyModelArms:
    def test_route_picks_cheapest_arm(self):
        m = _LatencyModel(min_device_batch=8, device_arms=("dev1", "devN"))
        m.observe_cpu(100, 50.0)  # 0.5 ms/sig
        for arm, small, big in (("dev1", 2.0, 60.0), ("devN", 10.0, 12.0)):
            for _ in range(2):
                m.observe_device(16, small, arm=arm)
                m.observe_device(1024, big, arm=arm)
        assert m.route(16) == "dev1"   # 8ms cpu > 2ms dev1 < 10ms devN
        assert m.route(1024) == "devN"  # 512 cpu > 12 devN < 60 dev1
        assert m.route(4) == "cpu"      # below floor

    def test_legacy_use_device_still_works(self):
        m = _LatencyModel(min_device_batch=64)
        m.observe_cpu(100, 10.0)
        for _ in range(2):
            m.observe_device(256, 50.0)
        assert not m.use_device(200)
        assert m.use_device(1000)


class FakeMeshVerifier(BatchVerifier):
    """Fake device backend whose factory accepts mesh= (dual-arm plane
    tests): records calls per instance."""

    name = "fake-mesh"

    def __init__(self, mesh="auto", **_):
        self.mesh = mesh
        self.n_devices = 1 if mesh == "0" else 4
        self.calls: list[int] = []

    def verify_batch(self, batch):
        self.calls.append(len(batch))
        return np.ones(len(batch), bool)


register_verifier("fake-mesh", FakeMeshVerifier)


def garbage_reqs(n):
    return [VerifyRequest(b"\x01" * 32, b"\x02" * 32, b"\x03" * 64)] * n


class TestPlaneDualArms:
    def test_plane_builds_and_routes_both_arms(self):
        plane = VerifyPlane(
            backend="fake-mesh", backend_opts={"mesh": "4"},
            min_device_batch=8, window_ms=1.0,
        )
        try:
            wide: FakeMeshVerifier = plane.verifier
            one: FakeMeshVerifier = plane._one_chip
            assert one is not None and one.mesh == "0"
            assert plane.model.device_arms == ("dev1", "devN")
            m = plane.model
            m.observe_cpu(100, 50.0)  # 0.5 ms/sig
            for arm, small, big in (
                ("dev1", 2.0, 60.0), ("devN", 10.0, 12.0),
            ):
                for _ in range(2):
                    m.observe_device(16, small, arm=arm)
                    m.observe_device(1024, big, arm=arm)
            plane.verify_many(garbage_reqs(16))
            assert one.calls == [16] and wide.calls == []
            plane.verify_many(garbage_reqs(1024))
            assert wide.calls == [1024]
            j = plane.get_json()
            assert j["arms"]["dev1"]["sigs"] == 16
            assert j["arms"]["devN"]["sigs"] == 1024
            assert j["backend"] == "fake-mesh"
        finally:
            plane.stop()

    def test_arms_collapse_when_wide_resolves_single(self):
        plane = VerifyPlane(
            backend="fake-mesh", backend_opts={"mesh": "4"},
            min_device_batch=8, window_ms=1.0,
        )
        try:
            plane.verifier.n_devices = 1  # "mesh wider than the box"
            assert plane._device_arms() == ("devN",)
            assert plane._one_chip is None
        finally:
            plane.stop()

    def test_forced_device_routing(self):
        plane = VerifyPlane(
            backend="fake-mesh", backend_opts={"mesh": "4"},
            min_device_batch=8, window_ms=1.0, routing="device",
        )
        try:
            wide: FakeMeshVerifier = plane.verifier
            # no model training at all: device mode forces the widest
            plane.verify_many(garbage_reqs(32))
            assert wide.calls == [32]
            # below the floor still goes cpu even when forced
            plane.verify_many(garbage_reqs(4))
            assert wide.calls == [32]
            assert plane.get_json()["routing"] == "device"
        finally:
            plane.stop()

    def test_bad_routing_rejected(self):
        with pytest.raises(ValueError, match="routing"):
            VerifyPlane(backend="cpu", routing="sometimes")

    def test_no_mesh_opts_keeps_single_arm(self):
        plane = VerifyPlane(backend="fake-mesh", window_ms=1.0)
        try:
            assert plane._one_chip is None
            assert plane.model.device_arms == ("device",)
        finally:
            plane.stop()


class TestSyncSubmitRidesThePlane:
    def test_process_transaction_counts_through_verify_plane(self):
        """The RPC submit path (NetworkOPs.process_transaction) verifies
        THROUGH the routed plane: before ISSUE 15 it called
        tx.check_sign() inline, so a mesh-enabled node could serve a
        whole RPC flood with device_sigs frozen at 0 and no routing
        evidence."""
        from stellard_tpu.node.config import Config
        from stellard_tpu.node.node import Node
        from stellard_tpu.protocol.formats import TxType
        from stellard_tpu.protocol.sfields import sfAmount, sfDestination
        from stellard_tpu.protocol.stamount import STAmount
        from stellard_tpu.protocol.sttx import SerializedTransaction

        n = Node(Config(signature_backend="cpu", kernel_tuning="none")).setup()
        try:
            master = KeyPair.from_passphrase("masterpassphrase")
            dest = KeyPair.from_passphrase("plane-sync").account_id
            tx = SerializedTransaction.build(
                TxType.ttPAYMENT, master.account_id, 1, 10,
                {sfAmount: STAmount.from_drops(250_000_000),
                 sfDestination: dest},
            )
            tx.sign(master)
            before = n.verify_plane.verified
            ter, applied = n.ops.process_transaction(tx)
            assert applied
            assert n.verify_plane.verified == before + 1
            assert n.verify_plane.cpu_sigs >= 1
            # tampered signature: rejected THROUGH the plane, not inline
            tx2 = SerializedTransaction.build(
                TxType.ttPAYMENT, master.account_id, 2, 10,
                {sfAmount: STAmount.from_drops(250_000_000),
                 sfDestination: dest},
            )
            tx2.sign(master)
            blob = bytearray(tx2.serialize())
            blob[-5] ^= 0x40
            bad = SerializedTransaction.from_bytes(bytes(blob))
            from stellard_tpu.protocol.ter import TER

            ter2, applied2 = n.ops.process_transaction(bad)
            assert ter2 == TER.temINVALID and not applied2
            assert n.verify_plane.verified == before + 2
        finally:
            n.stop()


class FakeDevHasher(BatchHasher):
    name = "tpu"

    def __init__(self, n_devices=8):
        self.n_devices = n_devices
        self.calls = 0
        self.device_nodes = 0
        self.host_nodes = 0

    def prefix_hash_batch(self, prefixes, payloads):
        self.calls += 1
        self.device_nodes += len(prefixes)
        from stellard_tpu.utils.hashes import prefix_hash

        return [prefix_hash(p, d) for p, d in zip(prefixes, payloads)]


class TestWatchdogThreeWay:
    def _mk(self, routing=None):
        wide, one, host = FakeDevHasher(8), FakeDevHasher(1), CpuHasher()
        w = WatchdogHasher(wide, host, inner_one=one,
                           min_device_nodes=0, routing=routing)
        return w, wide, one, host

    def test_cost_routes_three_ways(self):
        w, wide, one, _ = self._mk()
        batch = ([0x1234] * 16, [b"x" * 40] * 16)
        m = w._flat
        for arm, small, big in (("dev1", 1.0, 50.0), ("devN", 9.0, 5.0)):
            m.observe(arm, 16, 999.0)
            m.observe(arm, 16, small)
            m.observe(arm, 2048, 999.0)
            m.observe(arm, 2048, big)
        m.observe("host", 16, 160.0)  # 10 ms/node: devices win
        w.prefix_hash_batch(*batch)
        assert one.calls == 1 and wide.calls == 0
        big_batch = ([0x1234] * 2048, [b"x" * 40] * 2048)
        w.prefix_hash_batch(*big_batch)
        assert wide.calls == 1
        j = w.get_json()
        assert j["arms"] == ["dev1", "devN"]
        assert set(j["flat_model"]["arms"]) == {"dev1", "devN"}

    def test_forced_device_uses_widest_arm(self):
        w, wide, one, _ = self._mk(routing="device")
        w.prefix_hash_batch([0x1234] * 4, [b"x" * 40] * 4)
        assert wide.calls == 1 and one.calls == 0
        assert w.get_json()["routing"] == "device"

    def test_arms_collapse_when_wide_is_single(self):
        w, wide, one, _ = self._mk()
        wide.n_devices = 1
        assert w._live_arms() == ("devN",)
        assert w.inner_one is None

    def test_counters_sum_both_arms(self):
        w, wide, one, _ = self._mk(routing="device")
        w.prefix_hash_batch([0x1234] * 4, [b"x" * 40] * 4)
        one.device_nodes += 3  # as if the 1-chip arm also ran
        assert w.device_nodes == 7
        w.device_nodes = 0
        assert w.device_nodes == 0

    def test_make_watched_hasher_arm_construction(self):
        w = make_watched_hasher("tpu", mesh="8")
        assert isinstance(w, WatchdogHasher)
        assert w.inner_one is not None  # wide request: 1-chip arm built
        w0 = make_watched_hasher("tpu", mesh="0")
        assert w0.inner_one is None
        host = make_watched_hasher("cpu")
        assert isinstance(host, CpuHasher)  # host passes through
