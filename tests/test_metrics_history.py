"""Embedded metrics history (Monarch-style in-system time series) and
the Prometheus exposition door.

Contracts under test (PR 18 tentpole leg 2):

- MetricsHistory is a BOUNDED ring: capacity = window/interval fixed at
  construction, memory never grows past it no matter how long the node
  runs; eviction accounting (appended - rows) is exact;
- snapshots are monotone for counters/meters across flush_once() —
  flushing drains a meter's interval count but never its cumulative
  total, so the history never shows a counter going backwards;
- Prometheus text format 0.0.4: legal metric names from dotted insight
  names, HELP escaping, histogram buckets CUMULATIVE with a +Inf bucket
  equal to _count;
- copy-on-read: a rows() result taken mid-append is immutable — a
  reader holding it is unaffected by concurrent sampling;
- history_json / metrics_history RPC shape, since/limit filters.
"""

from __future__ import annotations

import threading

from stellard_tpu.node.metrics import (
    CollectorManager,
    LatencyHist,
    MetricsHistory,
    NullCollector,
    prometheus_escape_help,
    prometheus_escape_label,
    prometheus_name,
)


class TestHistoryRing:
    def test_capacity_is_window_over_interval(self):
        h = MetricsHistory(interval=5.0, window=300.0)
        assert h.capacity == 60
        tiny = MetricsHistory(interval=10.0, window=1.0)  # window < interval
        assert tiny.capacity == 2  # floor: at least two rows

    def test_bounded_under_long_runs(self):
        h = MetricsHistory(interval=1.0, window=10.0)
        for i in range(10_000):
            h.append({"ts": float(i), "counters": {"n": i}})
        rows = h.rows()
        assert len(rows) == h.capacity == 10
        # the ring kept the NEWEST rows and the eviction count is exact
        assert [r["ts"] for r in rows] == [float(i) for i in range(9990, 10000)]
        assert h.appended == 10_000
        j = h.get_json()
        assert j["rows"] == 10 and j["appended"] == 10_000

    def test_since_and_limit_filters(self):
        h = MetricsHistory(interval=1.0, window=100.0)
        for i in range(20):
            h.append({"ts": float(i)})
        assert [r["ts"] for r in h.rows(since=15.0)] == [15.0, 16.0, 17.0,
                                                         18.0, 19.0]
        assert [r["ts"] for r in h.rows(limit=3)] == [17.0, 18.0, 19.0]
        assert [r["ts"] for r in h.rows(since=10.0, limit=2)] == [18.0, 19.0]

    def test_copy_on_read_under_concurrent_append(self):
        h = MetricsHistory(interval=1.0, window=50.0)
        for i in range(50):
            h.append({"ts": float(i)})
        held = h.rows()
        stop = threading.Event()

        def writer():
            i = 50
            while not stop.is_set():
                h.append({"ts": float(i)})
                i += 1

        t = threading.Thread(target=writer)
        t.start()
        try:
            snapshot = list(held)
            for _ in range(200):
                assert held == snapshot  # a held result never mutates
        finally:
            stop.set()
            t.join()


class TestSnapshotMonotonicity:
    def test_counters_and_meters_survive_flush_drain(self):
        cm = CollectorManager(collector=NullCollector())
        c = cm.counter("close.count")
        m = cm.meter("tx.applied")
        c.inc(3)
        m.mark(7)
        before = cm.instruments_snapshot()
        lines = cm.flush_once()  # drains the meter's interval count
        assert any(line.startswith("tx.applied:7|c") for line in lines)
        c.inc(1)
        m.mark(2)
        after = cm.instruments_snapshot()
        # cumulative view is monotone across the drain
        assert before["counters"]["close.count"] == 3
        assert after["counters"]["close.count"] == 4
        assert before["counters"]["tx.applied"] == 7
        assert after["counters"]["tx.applied"] == 9
        cm.stop()

    def test_sample_history_stamps_ts_and_notifies(self):
        cm = CollectorManager(collector=NullCollector())
        cm.enable_history(interval=1.0, window=10.0)
        cm.counter("a").inc(5)
        seen = []
        cm.on_sample(seen.append)
        snap = cm.sample_history(now=123.5)
        assert snap["ts"] == 123.5
        assert snap["counters"]["a"] == 5
        assert seen == [snap]
        assert cm.history.rows()[-1] is snap
        cm.stop()

    def test_history_series_monotone_across_flushes(self):
        cm = CollectorManager(collector=NullCollector())
        cm.enable_history(interval=1.0, window=100.0)
        m = cm.meter("fanout.delivered")
        for step in range(1, 6):
            m.mark(10)
            cm.flush_once()  # drain between every sample
            cm.sample_history(now=float(step))
        series = [r["counters"]["fanout.delivered"]
                  for r in cm.history.rows()]
        assert series == [10, 20, 30, 40, 50]
        assert series == sorted(series)
        cm.stop()

    def test_history_json_shape(self):
        cm = CollectorManager(collector=NullCollector())
        assert cm.history_json() == {"enabled": False, "rows": []}
        cm.enable_history(interval=2.0, window=20.0)
        cm.gauge("depth").set(4)
        cm.sample_history(now=1.0)
        cm.sample_history(now=3.0)
        j = cm.history_json(since=2.0)
        assert j["enabled"] is True
        assert j["capacity"] == 10 and j["appended"] == 2
        assert [r["ts"] for r in j["series"]] == [3.0]
        assert j["series"][0]["gauges"]["depth"] == 4
        cm.stop()


class TestPrometheusExposition:
    def test_name_mangling(self):
        assert prometheus_name("close.pipeline.p50-ms") == (
            "close_pipeline_p50_ms"
        )
        assert prometheus_name("9lives") == "_lives"
        assert prometheus_name("") == "_"

    def test_help_and_label_escaping(self):
        assert prometheus_escape_help("a\\b\nc") == "a\\\\b\\nc"
        assert prometheus_escape_label('say "hi"\n') == 'say \\"hi\\"\\n'

    def test_exposition_types_and_values(self):
        cm = CollectorManager(collector=NullCollector())
        cm.counter("tx.count").inc(12)
        cm.gauge("queue.depth").set(3.5)
        cm.hook("cache", lambda: {"hit_rate": 0.75})
        text = cm.prometheus_text(extra_gauges={"health_status": 1})
        lines = text.splitlines()
        assert "# TYPE stellard_tx_count counter" in lines
        assert "stellard_tx_count 12" in lines
        assert "# TYPE stellard_queue_depth gauge" in lines
        assert "stellard_queue_depth 3.5" in lines
        assert "stellard_cache_hit_rate 0.75" in lines
        assert "stellard_health_status 1" in lines
        assert text.endswith("\n")  # 0.0.4: final line feed required
        cm.stop()

    def test_histogram_buckets_cumulative_inf_equals_count(self):
        cm = CollectorManager(collector=NullCollector())
        h = LatencyHist(bounds=(1.0, 10.0, 100.0))
        for ms in (0.5, 0.7, 5.0, 50.0, 5000.0):
            h.record(ms)
        cm.register_hist("close.ms", h)
        lines = cm.prometheus_text().splitlines()
        assert "# TYPE stellard_close_ms histogram" in lines

        def bucket(le):
            row = [ln for ln in lines
                   if ln.startswith(f'stellard_close_ms_bucket{{le="{le}"}}')]
            return int(row[0].rsplit(" ", 1)[1])

        counts = [bucket("1"), bucket("10"), bucket("100"), bucket("+Inf")]
        assert counts == sorted(counts)  # cumulative => monotone
        assert counts == [2, 3, 4, 5]
        count_row = [ln for ln in lines
                     if ln.startswith("stellard_close_ms_count ")][0]
        assert int(count_row.rsplit(" ", 1)[1]) == counts[-1] == 5
        sum_row = [ln for ln in lines
                   if ln.startswith("stellard_close_ms_sum ")][0]
        assert float(sum_row.rsplit(" ", 1)[1]) > 0
        cm.stop()

    def test_scrape_safe_under_concurrent_flush(self):
        cm = CollectorManager(collector=NullCollector())
        m = cm.meter("tx.relayed")
        cm.enable_history(interval=0.1, window=1.0)
        stop = threading.Event()
        errors = []

        def churn():
            i = 0
            try:
                while not stop.is_set():
                    m.mark(3)
                    cm.flush_once()
                    cm.sample_history(now=float(i))
                    i += 1
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        t = threading.Thread(target=churn)
        t.start()
        try:
            last = -1
            for _ in range(100):
                text = cm.prometheus_text()
                row = [ln for ln in text.splitlines()
                       if ln.startswith("stellard_tx_relayed ")]
                if row:
                    v = int(row[0].rsplit(" ", 1)[1])
                    assert v >= last  # cumulative across scrapes
                    last = v
        finally:
            stop.set()
            t.join()
        assert errors == []
        cm.stop()
