"""4-validator private net as SEPARATE PROCESSES over real sockets —
the deployment BASELINE config #4 describes (one host per validator),
driven end-to-end through the CLI + RPC planes (reference: the Vagrant
one-box testnet, doc/stellard-example.cfg private-net template).

Each validator is `python -m stellard_tpu --conf <ini> --start`: the full
application container (NodeStore, CLF mirror, JobQueue, VerifyPlane,
TcpOverlay + ValidatorNode consensus, HTTP RPC). The test asserts the
net closes ledgers in agreement and that a payment submitted over RPC to
one validator commits network-wide.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

import pytest

from stellard_tpu.protocol.keys import KeyPair

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

# shared net-lab helpers (tools/netlab.py) — one config template /
# launcher / RPC helper for this suite AND tools/chaos_soak.py
from netlab import SPEED, free_ports, rpc, wait_until  # noqa: E402


@pytest.fixture(scope="module")
def net(tmp_path_factory):
    n = 4
    tmp = tmp_path_factory.mktemp("mpnet")
    ports = free_ports(3 * n)
    peer_ports, rpc_ports, ws_ports = ports[:n], ports[n : 2 * n], ports[2 * n :]
    keys = [KeyPair.from_passphrase(f"mp-val-{i}") for i in range(n)]

    procs = []
    for i in range(n):
        others_keys = "\n".join(
            keys[j].human_node_public for j in range(n) if j != i
        )
        others_addrs = "\n".join(
            f"127.0.0.1 {peer_ports[j]}" for j in range(n) if j != i
        )
        cfg = f"""
[standalone]
0

[node_db]
type=memory

[signature_backend]
type=cpu

[validation_seed]
{keys[i].human_seed}

[validators]
{others_keys}

[validation_quorum]
3

[peer_port]
{peer_ports[i]}

[peer_ssl]
require

[ips]
{others_addrs}

[clock_speed]
{SPEED}

[rpc_port]
{rpc_ports[i]}

[websocket_port]
{ws_ports[i]}
"""
        path = tmp / f"validator-{i}.cfg"
        path.write_text(cfg)

    procs.extend([None] * n)

    def respawn(i: int) -> subprocess.Popen:
        """(Re)launch validator i from its config. On relaunch the memory
        node_db means a FRESH genesis that must catch up over the wire."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"  # never grab the TPU tunnel from tests
        p = subprocess.Popen(
            [sys.executable, "-m", "stellard_tpu", "--conf",
             str(tmp / f"validator-{i}.cfg"), "--start"],
            cwd=REPO,
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
        )
        procs[i] = p
        return p

    for i in range(n):
        respawn(i)

    try:
        yield {"rpc_ports": rpc_ports, "ws_ports": ws_ports, "procs": procs,
               "respawn": respawn}
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow
class TestMultiProcessNet:
    def test_ledgers_close_and_agree(self, net):
        rpc_ports = net["rpc_ports"]

        # all four servers come up and connect to each other
        assert wait_until(
            lambda: all(
                rpc(p, "server_info")["info"]["peers"] == 3 for p in rpc_ports
            ),
            # four fresh interpreters share 1-2 cores on this box; cold
            # startup alone can eat ~35s under ambient load (measured),
            # so the mesh wait must not be the startup race's victim
            timeout=90,
        ), "validators never fully meshed"

        # the net closes ledgers: every validator advances past seq 3
        def advanced():
            seqs = [
                rpc(p, "server_info")["info"]["validated_ledger"]["seq"]
                for p in rpc_ports
            ]
            return all(s >= 3 for s in seqs)

        assert wait_until(advanced, timeout=60), "net never closed 3 ledgers"

        # agreement: at a common validated sequence the hashes match
        infos = [rpc(p, "server_info")["info"] for p in rpc_ports]
        common = min(i["validated_ledger"]["seq"] for i in infos)
        hashes = {
            rpc(p, "ledger", {"ledger_index": common})["ledger"]["hash"]
            for p in rpc_ports
        }
        assert len(hashes) == 1, f"fork at seq {common}: {hashes}"

    def test_rpc_payment_commits_network_wide(self, net):
        rpc_ports = net["rpc_ports"]
        alice = KeyPair.from_passphrase("mp-alice")
        amount = 5_000 * 1_000_000

        res = rpc(
            rpc_ports[0],
            "submit",
            {
                "secret": "masterpassphrase",
                "tx_json": {
                    "TransactionType": "Payment",
                    "Account": KeyPair.from_passphrase(
                        "masterpassphrase"
                    ).human_account_id,
                    "Destination": alice.human_account_id,
                    "Amount": str(amount),
                },
            },
            timeout=15.0,
        )
        assert res["engine_result"] in ("tesSUCCESS", "terQUEUED"), res

        # the payment lands in a validated ledger on EVERY validator
        def landed():
            for p in rpc_ports:
                info = rpc(p, "account_info", {"account": alice.human_account_id,
                                               "ledger_index": "validated"})
                if int(info["account_data"]["Balance"]) != amount:
                    return False
            return True

        assert wait_until(landed, timeout=60), "payment never committed net-wide"

    def test_ws_ledger_stream_on_networked_validator(self, net):
        """The WS ledger stream must publish CONSENSUS closes, not just
        standalone ledger_accept ones (the publish path rides the
        overlay's accepted-ledger hook)."""
        from test_rpc_server import WsClient

        ws = WsClient(net["ws_ports"][1])
        try:
            resp = ws.call("subscribe", streams=["ledger"])
            assert resp.get("status") == "success", resp
            # consensus closes arrive as ledgerClosed events
            ws.sock.settimeout(30)
            evt = ws.recv()
            assert evt["type"] == "ledgerClosed", evt
            assert evt["ledger_index"] >= 1
        finally:
            ws.close()

    def test_validator_crash_catchup_rejoin(self, net):
        """Failure recovery across PROCESSES (SURVEY §5 failure
        detection/recovery): kill one validator; the remaining three
        (own validation counts toward quorum, reference accept
        :1023-1045) keep closing; the restarted validator boots from a
        FRESH genesis (memory node_db) and must catch up to the live
        net over the wire (InboundLedger/GetLedger + LCL switch) and
        re-converge on the same hashes."""
        rpc_ports = net["rpc_ports"]
        procs = net["procs"]

        victim = 3
        survivors = [p for i, p in enumerate(rpc_ports) if i != victim]

        # order-independent: wait for a fully-meshed, closing net first
        assert wait_until(
            lambda: all(
                rpc(p, "server_info")["info"]["peers"] == 3
                and rpc(p, "server_info")["info"]["validated_ledger"]["seq"]
                >= 2
                for p in rpc_ports
            ),
            timeout=60,
        ), "net not healthy before the crash"

        procs[victim].terminate()
        procs[victim].wait(timeout=10)

        # the degraded net keeps closing ledgers
        base = max(
            rpc(p, "server_info")["info"]["validated_ledger"]["seq"]
            for p in survivors
        )
        assert wait_until(
            lambda: all(
                rpc(p, "server_info")["info"]["validated_ledger"]["seq"]
                >= base + 2
                for p in survivors
            ),
            timeout=90,
        ), "net stalled after losing one of four validators"

        # restart: fresh genesis, must catch up to the net's ledger
        net["respawn"](victim)
        vport = rpc_ports[victim]

        def caught_up():
            target = max(
                rpc(p, "server_info")["info"]["validated_ledger"]["seq"]
                for p in survivors
            )
            mine = rpc(vport, "server_info")["info"]["validated_ledger"]["seq"]
            return mine >= target - 1 and mine > base

        assert wait_until(caught_up, timeout=120), (
            "restarted validator never caught up to the live net"
        )

        # convergence: pick a sequence the REJOINED validator holds (its
        # fresh-genesis history only starts at the LCL-switch point) and
        # wait until every node serves the same hash for it
        def converged():
            seq = rpc(vport, "server_info")["info"]["validated_ledger"]["seq"]
            if seq <= base:
                return False
            hashes = set()
            for p in rpc_ports:
                led = rpc(p, "ledger", {"ledger_index": seq}).get("ledger")
                if led is None:  # a lagging node hasn't got this seq yet
                    return False
                hashes.add(led["hash"])
            return len(hashes) == 1

        assert wait_until(converged, timeout=60), (
            "validators never converged on one post-rejoin ledger hash"
        )

    def test_load_restart_convergence(self, net):
        """CI-sized version of the build-time net soak that exposed the
        round-4 fork-repair fixes: continuous submissions while one
        validator restarts from fresh genesis; afterwards every
        validator's QUORUM-VALIDATED chain must advance and agree."""
        import threading

        rpc_ports = net["rpc_ports"]
        procs = net["procs"]

        assert wait_until(
            lambda: all(
                rpc(p, "server_info")["info"]["peers"] == 3 for p in rpc_ports
            ),
            timeout=60,
        ), "net not meshed before load"

        master = KeyPair.from_passphrase("masterpassphrase")
        stop = threading.Event()
        submitted = [0]

        def load():
            i = 0
            while not stop.is_set():
                try:
                    rpc(
                        rpc_ports[i % 4],
                        "submit",
                        {
                            "secret": "masterpassphrase",
                            "tx_json": {
                                "TransactionType": "Payment",
                                "Account": master.human_account_id,
                                "Destination": KeyPair.from_passphrase(
                                    f"lr-{i % 3}"
                                ).human_account_id,
                                "Amount": str(1_500_000_000),
                            },
                        },
                        timeout=15,
                    )
                    submitted[0] += 1
                except Exception:
                    pass
                i += 1
                stop.wait(1.5)

        t = threading.Thread(target=load, daemon=True)
        t.start()
        try:
            time.sleep(12)
            victim = 1
            procs[victim].terminate()
            procs[victim].wait(timeout=10)
            time.sleep(4)
            net["respawn"](victim)
            time.sleep(20)
        finally:
            stop.set()
            t.join(timeout=10)
        assert submitted[0] > 0

        def validated_seqs():
            return [
                rpc(p, "server_info")["info"]["validated_ledger"]["seq"]
                for p in rpc_ports
            ]

        target = max(validated_seqs()) + 2
        assert wait_until(
            lambda: min(validated_seqs()) >= target, timeout=120
        ), f"validated chains never converged: {validated_seqs()}"
        common = min(validated_seqs())
        hashes = {
            rpc(p, "ledger", {"ledger_index": common})["ledger"]["hash"]
            for p in rpc_ports
        }
        assert len(hashes) == 1, f"fork at {common}: {hashes}"
