"""Native C++ component tests: batched SHA-512 and the cpplog NodeStore
backend (role parity with the reference's OpenSSL hashing and vendored
LevelDB/RocksDB backends, SURVEY §2.8). Skipped when the toolchain can't
produce the library."""

import hashlib
import os

import pytest

from stellard_tpu.native import native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable"
)


class TestNativeSha512:
    def test_differential_vs_hashlib(self):
        from stellard_tpu.crypto.backend import make_hasher

        h = make_hasher("cpp")
        rng = os.urandom
        payloads = [rng(n % 517) for n in (0, 1, 31, 32, 127, 128, 129, 516)]
        prefixes = [0, 0x54584E00, 0x4D4C4E00, 0, 0x53545800, 0, 1, 0xFFFFFFFF]
        got = h.prefix_hash_batch(prefixes, payloads)
        for p, m, g in zip(prefixes, payloads, got):
            # backends are bit-interchangeable: a zero prefix is still
            # four bytes on the wire
            data = p.to_bytes(4, "big") + m
            assert g == hashlib.sha512(data).digest()[:32]

    def test_empty_batch(self):
        from stellard_tpu.crypto.backend import make_hasher

        assert make_hasher("cpp").prefix_hash_batch([], []) == []

    def test_shamap_hashing_identical_across_backends(self):
        from stellard_tpu.crypto.backend import make_hasher
        from stellard_tpu.state.shamap import SHAMap, SHAMapItem

        cpp = make_hasher("cpp")
        a = SHAMap(hash_batch=cpp.prefix_hash_batch)
        b = SHAMap()  # default python hasher
        for i in range(100):
            item = SHAMapItem(hashlib.sha256(b"%d" % i).digest(), b"v%d" % i)
            a.set_item(item)
            b.set_item(SHAMapItem(item.tag, item.data))
        assert a.get_hash() == b.get_hash()


class TestCppLogBackend:
    def test_roundtrip_and_replay(self, tmp_path):
        from stellard_tpu.nodestore.core import NodeObjectType, make_database

        path = str(tmp_path / "store.cpplog")
        db = make_database(type="cpplog", path=path)
        objs = [(os.urandom(32), os.urandom(64 + i)) for i in range(300)]
        for k, v in objs:
            db.store(NodeObjectType.ACCOUNT_NODE, k, v)
        db.sync()
        for k, v in objs:
            o = db.fetch(k)
            assert o is not None and o.data == v
        db.close()
        # crash-safe replay: reopen rebuilds the index from the log
        db2 = make_database(type="cpplog", path=path)
        for k, v in objs:
            got = db2.fetch(k)
            assert got is not None and got.data == v
        assert db2.fetch(os.urandom(32)) is None
        db2.close()

    def test_ledger_save_load_through_cpplog(self, tmp_path):
        from stellard_tpu.nodestore.core import make_database
        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.state.ledger import Ledger

        db = make_database(type="cpplog", path=str(tmp_path / "l.cpplog"))
        master = KeyPair.from_passphrase("masterpassphrase")
        led = Ledger.genesis(master.account_id)
        led.close(1000, 30)
        h = led.save(db)
        db.sync()
        again = Ledger.load(db, h)
        assert again.hash() == led.hash()
        db.close()

    def test_content_addressed_dedup(self, tmp_path):
        from stellard_tpu.native import CppLogLib

        path = str(tmp_path / "d.cpplog")
        db = CppLogLib(path)
        key = os.urandom(32)
        db.put(key, 3, b"payload")
        db.sync()
        size1 = os.path.getsize(path)
        db.put(key, 3, b"payload")  # duplicate: no growth
        db.sync()
        assert os.path.getsize(path) == size1
        assert db.count() == 1
        db.close()

    def test_torn_tail_truncated_on_reopen(self, tmp_path):
        from stellard_tpu.native import CppLogLib

        path = str(tmp_path / "torn.cpplog")
        db = CppLogLib(path)
        k1, v1 = os.urandom(32), os.urandom(80)
        db.put(k1, 1, v1)
        db.sync()
        db.close()
        # simulate a crash mid-append: torn header claiming 1000 bytes
        with open(path, "ab") as fh:
            fh.write((1001).to_bytes(4, "little") + b"\x00" + os.urandom(32)
                     + b"partial")
        db = CppLogLib(path)
        assert db.get(k1) == (1, v1)
        k2, v2 = os.urandom(32), os.urandom(40)
        db.put(k2, 2, v2)
        db.sync()
        db.close()
        # replay again: both records intact, torn tail gone
        db = CppLogLib(path)
        assert db.get(k1) == (1, v1)
        assert db.get(k2) == (2, v2)
        assert db.count() == 2
        db.close()

    def test_large_blob_grows_read_buffer(self, tmp_path):
        from stellard_tpu.native import CppLogLib

        db = CppLogLib(str(tmp_path / "big.cpplog"))
        key = os.urandom(32)
        blob = os.urandom(200_000)
        db.put(key, 1, blob)
        got = db.get(key)
        assert got is not None and got[1] == blob
        db.close()


class TestNativeEd25519Verify:
    """Differential tests of the batched C++ verifier
    (native/src/ed25519_verify.cc) against the host-library path
    (keys.verify_signature -> OpenSSL), incl. the adversarial cases the
    reference's canonical-S rule exists for
    (RippleAddress.cpp:226-252)."""

    def _keys_msgs_sigs(self, n=48, seed=11):
        import numpy as np

        from stellard_tpu.protocol.keys import KeyPair

        rng = np.random.default_rng(seed)
        keys = [
            KeyPair.from_seed(bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
            for _ in range(8)
        ]
        msgs = [
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)) for _ in range(n)
        ]
        pubs = [keys[i % 8].public for i in range(n)]
        sigs = [keys[i % 8].sign(msgs[i]) for i in range(n)]
        return pubs, msgs, sigs

    def test_differential_with_planted_failures(self):
        import numpy as np

        from stellard_tpu.native import Ed25519NativeVerify
        from stellard_tpu.protocol.keys import ED25519_L, verify_signature

        pubs, msgs, sigs = self._keys_msgs_sigs()
        # corrupt R, corrupt S, corrupt A, wrong message
        sigs[3] = sigs[3][:2] + bytes([sigs[3][2] ^ 1]) + sigs[3][3:]
        sigs[5] = sigs[5][:40] + bytes([sigs[5][40] ^ 1]) + sigs[5][41:]
        pubs[7] = pubs[7][:1] + bytes([pubs[7][1] ^ 0x10]) + pubs[7][2:]
        msgs[9] = msgs[9][:-1] + bytes([msgs[9][-1] ^ 1])
        # non-canonical S: s + l still satisfies the curve equation but
        # must be rejected (signatureIsCanonical)
        s_int = int.from_bytes(sigs[11][32:], "little")
        if s_int + ED25519_L < (1 << 256):
            sigs[11] = sigs[11][:32] + (s_int + ED25519_L).to_bytes(32, "little")
        got = Ed25519NativeVerify().verify_batch(pubs, msgs, sigs)
        want = np.array(
            [verify_signature(p, m, s) for p, m, s in zip(pubs, msgs, sigs)]
        )
        assert np.array_equal(got, want)
        assert not got[[3, 5, 7, 9, 11]].any()
        assert got.sum() == len(pubs) - 5

    def test_non_canonical_pubkey_encoding_rejected(self):
        """A pubkey whose y-coordinate encoding is >= p must be rejected
        (RFC 8032 decode), matching the host library."""
        from stellard_tpu.native import Ed25519NativeVerify
        from stellard_tpu.protocol.keys import verify_signature

        pubs, msgs, sigs = self._keys_msgs_sigs(n=2)
        # y = p (non-canonical encoding of 0) and y = 2^255 - 1
        p_bytes = ((1 << 255) - 19).to_bytes(32, "little")
        big = ((1 << 255) - 1).to_bytes(32, "little")
        pubs = [p_bytes, big]
        got = Ed25519NativeVerify().verify_batch(pubs, msgs, sigs)
        assert not got.any()
        assert not any(
            verify_signature(p, m, s) for p, m, s in zip(pubs, msgs, sigs)
        )

    def test_variable_length_messages(self):
        import numpy as np

        from stellard_tpu.native import Ed25519NativeVerify
        from stellard_tpu.protocol.keys import KeyPair

        k = KeyPair.from_passphrase("varlen")
        msgs = [b"", b"x", b"y" * 100, b"z" * 1000]
        sigs = [k.sign_raw(m) if hasattr(k, "sign_raw") else None for m in msgs]
        if sigs[0] is None:
            # KeyPair.sign requires 32-byte hashes; sign via the pure-
            # Python RFC 8032 reference (independent of the native C++
            # verifier under test) to cover non-32-byte message lengths
            from stellard_tpu.ops.ed25519_ref import sign as ref_sign

            sigs = [ref_sign(k.seed, k.public, m) for m in msgs]
        got = Ed25519NativeVerify().verify_batch(
            [k.public] * 4, msgs, sigs
        )
        assert np.array_equal(got, np.ones(4, bool))

    def test_empty_batch(self):
        from stellard_tpu.native import Ed25519NativeVerify

        assert len(Ed25519NativeVerify().verify_batch([], [], [])) == 0

    def test_backend_seam_prefers_native(self, monkeypatch):
        monkeypatch.delenv("STELLARD_HOST_VERIFY", raising=False)
        from stellard_tpu.crypto.backend import make_verifier

        v = make_verifier("cpu")
        assert v.name == "cpu"
        assert v.impl == "native"
        monkeypatch.setenv("STELLARD_HOST_VERIFY", "python")
        assert make_verifier("cpu").impl == "openssl"


class TestNativeStser:
    """The _stser CPython extension (native/src/stser.cc) must be
    byte-identical to the Python encode loop across every wire shape —
    a divergence is consensus-fatal (hashes change)."""

    def _py_bytes(self, obj, signing=False):
        from stellard_tpu.protocol import stobject as so

        st = so._STSER
        so._STSER = None
        try:
            return obj.serialize(signing=signing)
        finally:
            so._STSER = st

    def test_differential_all_shapes(self):
        import random

        from stellard_tpu.protocol import stobject as so
        from stellard_tpu.protocol.formats import TxType
        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.protocol.sfields import (
            sfAccount,
            sfAffectedNodes,
            sfAmount,
            sfBalance,
            sfDestination,
            sfDomain,
            sfFinalFields,
            sfIndexes,
            sfLedgerEntryType,
            sfLedgerIndex,
            sfModifiedNode,
            sfPaths,
            sfSequence,
        )
        from stellard_tpu.protocol.stamount import STAmount
        from stellard_tpu.protocol.stobject import (
            PathElement,
            STArray,
            STObject,
            STPathSet,
        )
        from stellard_tpu.protocol.sttx import SerializedTransaction

        if so._get_stser() is None:
            import pytest

            pytest.skip("native stser unavailable (no toolchain)")

        rng = random.Random(1)
        k = KeyPair.from_passphrase("stser-test")
        dest = KeyPair.from_passphrase("stser-dest")
        cases = []
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, k.account_id, 7, 10,
            {sfAmount: STAmount.from_drops(123456),
             sfDestination: dest.account_id},
        )
        tx.sign(k)
        cases.append(tx.obj)
        o = STObject()
        o[sfSequence] = 0xDEADBEEF
        o[sfAmount] = STAmount.from_iou(
            b"USD" + b"\0" * 17, dest.account_id, 123456789, -3, True)
        o[sfAccount] = k.account_id
        o[sfLedgerIndex] = bytes(range(32))
        o[sfPaths] = STPathSet([[
            PathElement(account=dest.account_id),
            PathElement(currency=b"EUR" + b"\0" * 17, issuer=k.account_id),
        ]])
        o[sfIndexes] = [bytes([i] * 32) for i in range(3)]
        for n in (0, 1, 192, 193, 12480, 12481, 50000):  # VL edges
            o[sfDomain] = bytes(rng.randbytes(n))
            cases.append(STObject.from_bytes(self._py_bytes(o)))
        meta = STObject()
        arr = STArray()
        node = STObject()
        node[sfLedgerEntryType] = 0x61
        node[sfLedgerIndex] = bytes(32)
        ff = STObject()
        ff[sfBalance] = STAmount.from_drops(999)
        ff[sfSequence] = 3
        node[sfFinalFields] = ff
        arr.append(sfModifiedNode, node)
        meta[sfAffectedNodes] = arr
        cases.append(meta)

        for obj in cases:
            for signing in (False, True):
                a = obj.serialize(signing=signing)
                obj._pairs = None  # both paths must re-walk
                assert a == self._py_bytes(obj, signing=signing)

        tx2 = SerializedTransaction.from_bytes(tx.serialize())
        assert tx2.signing_hash() == tx.signing_hash()
        assert tx2.txid() == tx.txid()
        assert tx2.check_sign()


class TestNativeStparse:
    """The native binary parser must produce objects equal to the Python
    loop's and reject malformed input with the same error class."""

    def _both(self, fn):
        from stellard_tpu.protocol import stobject as so

        if so._get_stser() is None:
            import pytest

            pytest.skip("native stser unavailable")
        native = fn()
        st = so._STSER
        so._STSER = None
        try:
            python = fn()
        finally:
            so._STSER = st
        return native, python

    def test_equal_objects_and_reserialization(self):
        from stellard_tpu.protocol.formats import TxType
        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.protocol.sfields import (
            sfAmount,
            sfDestination,
            sfIndexes,
            sfPaths,
        )
        from stellard_tpu.protocol.stamount import STAmount
        from stellard_tpu.protocol.stobject import (
            PathElement,
            STObject,
            STPathSet,
        )
        from stellard_tpu.protocol.sttx import SerializedTransaction

        k = KeyPair.from_passphrase("np-test")
        d = KeyPair.from_passphrase("np-dest")
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, k.account_id, 3, 10,
            {sfAmount: STAmount.from_iou(b"USD" + b"\0" * 17,
                                         d.account_id, 5, -1),
             sfDestination: d.account_id,
             sfPaths: STPathSet([[PathElement(account=d.account_id)],
                                 [PathElement(currency=b"EUR" + b"\0" * 17,
                                              issuer=k.account_id)]]),
             sfIndexes: [bytes([i]) * 32 for i in range(2)]},
        )
        tx.sign(k)
        blob = tx.serialize()
        native, python = self._both(lambda: STObject.from_bytes(blob))
        assert native == python
        assert native.serialize() == blob

    def test_error_classes_match(self):
        import pytest

        from stellard_tpu.protocol.stobject import STObject

        cases = [
            bytes([0x21]),            # truncated uint32 (underflow)
            bytes([0x00, 0x01, 0x01]),  # invalid field id encoding
            bytes([0xE9, 0xFF]),      # unknown field (14, 9 unregistered?) -> use (13,1)
            bytes([0xD1]),            # type 13 value 1: unknown field
            bytes([0xF9, 0x21]),      # array with truncated content
        ]
        for blob in cases:
            native_exc, python_exc = self._both(
                lambda b=blob: self._exc(STObject, b))
            assert type(native_exc) is type(python_exc) is ValueError, (
                blob.hex(), native_exc, python_exc)

    @staticmethod
    def _exc(cls, blob):
        try:
            cls.from_bytes(blob)
        except ValueError as e:
            return e
        raise AssertionError(f"no error for {blob.hex()}")


class TestParserDoSResistance:
    """A crafted deeply-nested blob must raise (RecursionError like the
    Python loop), never overflow the C stack — peer blobs reach the
    parser, so an unguarded recursion would be a remote node crash."""

    def test_deep_nesting_raises_not_crashes(self):
        import pytest

        from stellard_tpu.protocol.stobject import STObject, _get_stser

        blob = b"\xe2" * 50_000 + b"\xe1" * 50_000
        with pytest.raises((RecursionError, ValueError)):
            STObject.from_bytes(blob)
        if _get_stser() is not None:
            # and again explicitly through the Python loop for parity
            from stellard_tpu.protocol import stobject as so

            st = so._STSER
            so._STSER = None
            try:
                with pytest.raises((RecursionError, ValueError)):
                    STObject.from_bytes(blob)
            finally:
                so._STSER = st
