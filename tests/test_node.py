"""Node runtime tests: standalone open/close loop, held txns, RPC
handlers in-process — the shape of the reference's JS integration tests
(test/send-test.js, test/account_tx-test.js) without the sockets.
"""

from __future__ import annotations

import pytest

from stellard_tpu.node import Config, Node
from stellard_tpu.node.jobqueue import JobQueue, JobType
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair, encode_account_id
from stellard_tpu.protocol.sfields import (
    sfAmount,
    sfBalance,
    sfDestination,
    sfLimitAmount,
    sfSequence,
)
from stellard_tpu.protocol.stamount import STAmount, currency_from_iso
from stellard_tpu.protocol.sttx import SerializedTransaction
from stellard_tpu.protocol.ter import TER
from stellard_tpu.rpc.handlers import Context, Role, dispatch

XRP = 1_000_000  # drops per unit


@pytest.fixture()
def node():
    n = Node(Config()).setup()
    yield n
    n.stop()


def payment(key: KeyPair, seq: int, dest: bytes, drops: int,
            fee: int = 10) -> SerializedTransaction:
    tx = SerializedTransaction.build(
        TxType.ttPAYMENT, key.account_id, seq, fee,
        {sfAmount: STAmount.from_drops(drops), sfDestination: dest},
    )
    tx.sign(key)
    return tx


def fund(node: Node, dest: KeyPair, drops: int = 1000 * XRP):
    from stellard_tpu.rpc.txsign import predicted_sequence

    master = node.master_keys
    led = node.ledger_master.current_ledger()
    seq = predicted_sequence(
        led, master.account_id,
        led.account_root(master.account_id)[sfSequence],
    )
    ter, _ = node.submit(payment(master, seq, dest.account_id, drops))
    assert ter == TER.tesSUCCESS, ter


class TestStandaloneClose:
    def test_payment_and_close(self, node):
        alice = KeyPair.from_passphrase("alice")
        fund(node, alice)
        node.close_ledger()
        led = node.ledger_master.current_ledger()
        assert led.account_root(alice.account_id)[sfBalance].drops() == 1000 * XRP

    def test_chain_of_closes(self, node):
        alice = KeyPair.from_passphrase("alice")
        bob = KeyPair.from_passphrase("bob")
        fund(node, alice)
        fund(node, bob)  # above-reserve funding; below-reserve can't create
        node.close_ledger()
        for i in range(3):
            tx = payment(alice, i + 1, bob.account_id, 10 * XRP)
            ter, _ = node.submit(tx)
            assert ter == TER.tesSUCCESS
            node.close_ledger()
        led = node.ledger_master.current_ledger()
        assert (
            led.account_root(bob.account_id)[sfBalance].drops()
            == 1030 * XRP
        )
        # chain integrity: each close advanced seq by 1 and linked hashes
        lm = node.ledger_master
        assert lm.closed_ledger().seq == 5
        l4 = lm.get_ledger_by_seq(4)
        assert lm.closed_ledger().parent_hash == l4.hash()

    def test_held_future_seq_applies_after_close(self, node):
        alice = KeyPair.from_passphrase("alice")
        bob = KeyPair.from_passphrase("bob")
        fund(node, alice)
        fund(node, bob)
        node.close_ledger()
        # seq 2 before seq 1: queued by the admission plane (the legacy
        # held pile reports terPRE_SEQ when [txq] enabled=0)
        tx2 = payment(alice, 2, bob.account_id, 5 * XRP)
        ter, applied = node.submit(tx2)
        assert ter in (TER.terQUEUED, TER.terPRE_SEQ) and not applied
        tx1 = payment(alice, 1, bob.account_id, 5 * XRP)
        ter, applied = node.submit(tx1)
        assert ter == TER.tesSUCCESS
        node.close_ledger()  # applies tx1, re-applies held tx2 to next open
        node.close_ledger()  # commits tx2
        led = node.ledger_master.current_ledger()
        assert (
            led.account_root(bob.account_id)[sfBalance].drops()
            == 1010 * XRP
        )

    def test_bad_signature_rejected(self, node):
        alice = KeyPair.from_passphrase("alice")
        tx = payment(node.master_keys, 1, alice.account_id, XRP)
        from stellard_tpu.protocol.sfields import sfTxnSignature

        sig = bytearray(tx.obj[sfTxnSignature])
        sig[5] ^= 0xFF
        tx.obj[sfTxnSignature] = bytes(sig)
        tx.set_sig_verdict(None) if False else None
        tx._sig_good = None
        ter, applied = node.submit(tx)
        assert ter == TER.temINVALID and not applied

    def test_async_submit_batches(self, node):
        """submit_transaction routes through the VerifyPlane coalescer."""
        alice = KeyPair.from_passphrase("alice")
        fund(node, alice)
        node.close_ledger()
        results = []
        import threading

        done = threading.Event()
        bob = KeyPair.from_passphrase("bob")
        n = 20
        for i in range(n):
            def cb(tx, ter, applied, _res=results):
                _res.append(ter)
                if len(_res) == n:
                    done.set()

            node.ops.submit_transaction(
                payment(alice, i + 1, bob.account_id, XRP), cb
            )
        assert done.wait(timeout=30)
        assert all(t == TER.tesSUCCESS for t in results)
        assert node.verify_plane.verified >= n


class TestPersistence:
    def test_closed_ledger_saved_and_loadable(self, node):
        from stellard_tpu.state.ledger import Ledger

        alice = KeyPair.from_passphrase("alice")
        fund(node, alice)
        closed, _ = node.close_ledger()
        loaded = Ledger.load(node.nodestore, closed.hash())
        assert loaded.hash() == closed.hash()
        assert loaded.account_root(alice.account_id)[sfBalance].drops() == 1000 * XRP

    def test_tx_history_indexed(self, node):
        alice = KeyPair.from_passphrase("alice")
        fund(node, alice)
        node.close_ledger()
        rows = node.txdb.account_transactions(alice.account_id)
        assert len(rows) == 1
        assert rows[0]["status"] == "tesSUCCESS"
        hdr = node.txdb.get_ledger_header(seq=2)
        assert hdr is not None and hdr["seq"] == 2


class TestJobQueue:
    def test_priority_order(self):
        jq = JobQueue(threads=0)
        ran = []
        jq.add_job(JobType.jtCLIENT, "low", lambda: ran.append("low"))
        jq.add_job(JobType.jtACCEPT, "high", lambda: ran.append("high"))
        jq.set_thread_count(1)
        assert jq.drain()
        jq.stop()
        assert ran == ["high", "low"]

    def test_concurrency_limit(self):
        import threading
        import time

        jq = JobQueue(threads=4)
        active = []
        peak = []
        lock = threading.Lock()

        def work():
            with lock:
                active.append(1)
                peak.append(len(active))
            time.sleep(0.05)
            with lock:
                active.pop()

        for _ in range(6):
            jq.add_job(JobType.jtLEDGER_DATA, "limited", work)  # limit 2
        assert jq.drain()
        jq.stop()
        assert max(peak) <= 2


class TestRpcHandlers:
    def call(self, node, method, **params):
        return dispatch(Context(node=node, params=params), method)

    def test_server_info(self, node):
        r = self.call(node, "server_info")
        assert r["info"]["server_state"] == "full"
        assert r["info"]["complete_ledgers"] == "1"
        # identity split (reference NetworkOPs.cpp:1721-1726): node
        # identity always present; validator key "none" when not set
        assert r["info"]["pubkey_node"].startswith("n")
        assert r["info"]["pubkey_validator"] == "none"
        assert r["info"]["uptime"] >= 0

    def test_wallet_propose_roundtrip(self, node):
        r = self.call(node, "wallet_propose", passphrase="alice")
        alice = KeyPair.from_passphrase("alice")
        assert r["account_id"] == alice.human_account_id
        assert r["master_seed"] == alice.human_seed

    def test_account_info_and_not_found(self, node):
        master = node.master_keys
        r = self.call(node, "account_info", account=master.human_account_id)
        assert r["account_data"]["Sequence"] == 1
        ghost = KeyPair.from_passphrase("ghost")
        r = self.call(node, "account_info", account=ghost.human_account_id)
        assert r["error"] == "actNotFound"

    def test_submit_tx_json_and_close(self, node):
        alice = KeyPair.from_passphrase("alice")
        r = self.call(
            node, "submit",
            secret="masterpassphrase",
            tx_json={
                "TransactionType": "Payment",
                "Account": node.master_keys.human_account_id,
                "Destination": alice.human_account_id,
                "Amount": str(500 * XRP),
            },
        )
        assert r["engine_result"] == "tesSUCCESS", r
        self.call(node, "ledger_accept")
        r = self.call(node, "account_info", account=alice.human_account_id)
        assert r["account_data"]["Balance"] == str(500 * XRP)

    def test_submit_tx_blob(self, node):
        alice = KeyPair.from_passphrase("alice")
        tx = payment(node.master_keys, 1, alice.account_id, 100 * XRP)
        r = self.call(node, "submit", tx_blob=tx.serialize().hex())
        assert r["engine_result"] == "tesSUCCESS"

    def test_sign_only_does_not_apply(self, node):
        alice = KeyPair.from_passphrase("alice")
        r = self.call(
            node, "sign",
            secret="masterpassphrase",
            tx_json={
                "TransactionType": "Payment",
                "Account": node.master_keys.human_account_id,
                "Destination": alice.human_account_id,
                "Amount": "1000000",
            },
        )
        assert "tx_blob" in r
        assert (
            self.call(node, "account_info", account=alice.human_account_id)[
                "error"
            ]
            == "actNotFound"
        )

    def test_ledger_handlers(self, node):
        alice = KeyPair.from_passphrase("alice")
        fund(node, alice)
        node.close_ledger()
        r = self.call(node, "ledger_closed")
        assert r["ledger_index"] == 2
        r = self.call(node, "ledger", ledger_index="closed", transactions=True)
        assert len(r["ledger"]["transactions"]) == 1
        r = self.call(node, "ledger", ledger_index=2, transactions=True,
                      expand=True)
        assert r["ledger"]["transactions"][0]["TransactionType"] == "Payment"
        r = self.call(node, "ledger_current")
        assert r["ledger_current_index"] == 3

    def test_tx_and_account_tx(self, node):
        alice = KeyPair.from_passphrase("alice")
        tx = payment(node.master_keys, 1, alice.account_id, 1000 * XRP)
        node.submit(tx)
        node.close_ledger()
        r = self.call(node, "tx", transaction=tx.txid().hex())
        assert r["ledger_index"] == 2 and "meta" in r
        r = self.call(node, "account_tx", account=alice.human_account_id)
        assert len(r["transactions"]) == 1
        assert r["transactions"][0]["tx"]["hash"] == tx.txid().hex().upper()

    def test_account_lines(self, node):
        alice = KeyPair.from_passphrase("alice")
        gw = KeyPair.from_passphrase("gateway")
        fund(node, alice)
        fund(node, gw)
        node.close_ledger()
        trust = SerializedTransaction.build(
            TxType.ttTRUST_SET, alice.account_id, 1, 10,
            {sfLimitAmount: STAmount.from_iou(
                currency_from_iso("USD"), gw.account_id, 100, 0
            )},
        )
        trust.sign(alice)
        ter, _ = node.submit(trust)
        assert ter == TER.tesSUCCESS
        node.close_ledger()
        r = self.call(node, "account_lines", account=alice.human_account_id)
        assert len(r["lines"]) == 1
        line = r["lines"][0]
        assert line["account"] == gw.human_account_id
        assert line["currency"] == "USD"
        assert line["limit"] == "100"
        # optional fields follow the reference's presence rules
        # (AccountLines.cpp:102-112): absent when unset
        assert "quality_in" not in line and "no_ripple" not in line

    def test_account_lines_quality_and_flags(self, node):
        from stellard_tpu.engine.flags import tfSetNoRipple
        from stellard_tpu.protocol.sfields import (
            sfFlags as _sfFlags,
            sfQualityIn,
            sfQualityOut,
        )

        carol = KeyPair.from_passphrase("carol-q")
        gw = KeyPair.from_passphrase("gateway-q")
        fund(node, carol)
        fund(node, gw)
        node.close_ledger()
        trust = SerializedTransaction.build(
            TxType.ttTRUST_SET, carol.account_id, 1, 10,
            {
                sfLimitAmount: STAmount.from_iou(
                    currency_from_iso("EUR"), gw.account_id, 500, 0
                ),
                sfQualityIn: 990_000_000,   # values incoming at 0.99
                sfQualityOut: 1_010_000_000,
                _sfFlags: tfSetNoRipple,
            },
        )
        trust.sign(carol)
        ter, _ = node.submit(trust)
        assert ter == TER.tesSUCCESS, ter
        node.close_ledger()
        r = self.call(node, "account_lines", account=carol.human_account_id)
        eur = [l for l in r["lines"] if l["currency"] == "EUR"]
        assert len(eur) == 1
        line = eur[0]
        assert line["quality_in"] == 990_000_000
        assert line["quality_out"] == 1_010_000_000
        assert line.get("no_ripple") is True
        assert "peer_authorized" not in line
        # the PEER's view mirrors the same line with the roles flipped
        r2 = self.call(node, "account_lines", account=gw.human_account_id)
        eur2 = [l for l in r2["lines"] if l["currency"] == "EUR"]
        assert len(eur2) == 1
        assert eur2[0].get("no_ripple_peer") is True
        assert "quality_in" not in eur2[0]

    def test_ledger_entry(self, node):
        r = self.call(
            node, "ledger_entry",
            account_root=node.master_keys.human_account_id,
        )
        assert r["node"]["Account"] == node.master_keys.human_account_id

    def test_unknown_method(self, node):
        assert self.call(node, "bogus")["error"] == "unknownCmd"

    def test_get_counts(self, node):
        r = self.call(node, "get_counts")
        assert "verify_plane" in r


class TestSubscriptions:
    def test_ledger_and_tx_streams(self, node):
        from stellard_tpu.rpc.infosub import InfoSub, SubscriptionManager

        subs = SubscriptionManager(node.ops)
        got = []
        sub = InfoSub(got.append)
        result = subs.subscribe_streams(sub, ["ledger", "transactions"])
        assert result["ledger_index"] == 1
        alice = KeyPair.from_passphrase("alice")
        fund(node, alice)
        node.close_ledger()
        types = [m["type"] for m in got]
        assert "ledgerClosed" in types and "transaction" in types
        txmsg = next(m for m in got if m["type"] == "transaction")
        assert txmsg["engine_result"] == "tesSUCCESS"
        assert txmsg["validated"] is True

    def test_account_subscription(self, node):
        from stellard_tpu.rpc.infosub import InfoSub, SubscriptionManager

        subs = SubscriptionManager(node.ops)
        got = []
        sub = InfoSub(got.append)
        alice = KeyPair.from_passphrase("alice")
        subs.subscribe_accounts(sub, [alice.account_id])
        bob = KeyPair.from_passphrase("bob")
        fund(node, bob)  # not alice — no message for this one
        node.close_ledger()
        fund(node, alice)
        node.close_ledger()
        touched = [
            m for m in got
            if m["type"] == "transaction"
            and m["transaction"]["Destination"] == alice.human_account_id
        ]
        assert len(touched) == 1
        assert not any(
            m["transaction"].get("Destination") == bob.human_account_id
            for m in got if m["type"] == "transaction"
        )


class TestServerStream:
    def test_load_change_publishes_server_status(self, tmp_path):
        """monitor-test.js role: `server` stream subscribers get a
        serverStatus event when the load factor moves (pubServer)."""
        from stellard_tpu.node import Config, Node
        from stellard_tpu.rpc.infosub import InfoSub

        n = Node(Config(standalone=True, signature_backend="cpu")).setup()
        try:
            n.serve()
            got = []
            sub = InfoSub(got.append)
            n.subs.subscribe_streams(sub, ["server"])
            n.fee_track.raise_local_fee()
            # delivery rides the sharded fanout workers now — drain
            # them before asserting on the in-process sink
            assert n.subs.flush(timeout=5.0)
            statuses = [m for m in got if m.get("type") == "serverStatus"]
            assert statuses, got
            assert statuses[-1]["load_factor"] > 256
            before = len(statuses)
            n.fee_track.lower_local_fee()
            assert n.subs.flush(timeout=5.0)
            statuses = [m for m in got if m.get("type") == "serverStatus"]
            # the lowering itself must publish, and recovery lands back
            # at the normal factor
            assert len(statuses) > before
            assert statuses[-1]["load_factor"] == 256
        finally:
            n.stop()


class TestSigVerifyMemoization:
    def test_each_tx_verified_exactly_once(self, monkeypatch):
        """A tx verified at submit must NOT be host-re-verified at close
        (reference: LedgerConsensus::applyTransaction skips checkSign
        via SF_SIGGOOD, LedgerConsensus.cpp:2101-2106). Counts actual
        ed25519 verifications across submit + close + persist/publish.
        The host path is pinned to the python implementation so every
        verification — plane batches (CpuVerifier) and synchronous
        checkSign (sttx) — flows through the counted function."""
        import stellard_tpu.protocol.keys as keys_mod
        import stellard_tpu.protocol.sttx as sttx_mod

        monkeypatch.setenv("STELLARD_HOST_VERIFY", "python")

        calls = {"n": 0}
        orig = keys_mod.verify_signature

        def counting(*a, **kw):
            calls["n"] += 1
            return orig(*a, **kw)

        monkeypatch.setattr(keys_mod, "verify_signature", counting)
        # sttx binds the name at import time (checkSign's memoized path)
        monkeypatch.setattr(sttx_mod, "verify_signature", counting)

        n = Node(Config()).setup()
        try:
            alice = KeyPair.from_passphrase("memo-alice")
            n_tx = 8
            master = n.master_keys
            for i in range(n_tx):
                ter, _ = n.submit(
                    payment(master, i + 1, alice.account_id, 200 * XRP)
                )
                assert ter == TER.tesSUCCESS, ter
            n.close_ledger()
            n.close_ledger()  # second close: held/reapply paths
        finally:
            n.stop()
        assert calls["n"] > 0, (
            "counting hook never fired — the test is not observing the "
            "host verify path"
        )
        assert calls["n"] <= n_tx, (
            f"{calls['n']} host verifications for {n_tx} txs — "
            "close-time re-verification leak"
        )


class TestClusterConfig:
    def test_cluster_nodes_wire_into_overlay(self):
        """[cluster_nodes] (reference ConfigSections.h:40) decodes into
        the overlay's cluster set so mtCLUSTER load gossip flows."""
        from stellard_tpu.node.config import Config as Cfg

        member = KeyPair.from_passphrase("cluster-mate")
        cfg = Cfg.from_ini(
            f"""
[standalone]
0

[node_db]
type=memory

[peer_port]
0
"""
        )
        assert cfg.cluster_nodes == []
        cfg2 = Cfg.from_ini(
            f"""
[cluster_nodes]
{member.human_node_public} mate-comment
"""
        )
        assert cfg2.cluster_nodes == [member.human_node_public]

        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        cfg2.standalone = False
        cfg2.peer_port = port
        cfg2.validation_seed = KeyPair.from_passphrase("cl-self").human_seed
        n = Node(cfg2).setup()
        try:
            assert member.public in n.overlay.cluster
        finally:
            n.stop()


class TestCliSmoke:
    """End-to-end CLI smoke (reference: Main.cpp modes): a standalone
    server process with file-backed stores, the RPC CLIENT mode against
    it, then the offline --dump_ledger tooling over the persisted DB."""

    def test_server_client_and_offline_dump(self, tmp_path):
        import json
        import os
        import socket
        import subprocess
        import sys
        import time

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        cfg = tmp_path / "cli.cfg"
        cfg.write_text(f"""
[standalone]
1

[node_db]
type=sqlite
path={tmp_path}/ns.sqlite

[database_path]
{tmp_path}/db.sqlite

[signature_backend]
type=cpu

[rpc_port]
{port}
""")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        srv = subprocess.Popen(
            [sys.executable, "-m", "stellard_tpu", "--conf", str(cfg),
             "--start"],
            cwd=repo, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
        )
        try:
            def client(*cmd):
                r = subprocess.run(
                    [sys.executable, "-m", "stellard_tpu", "--conf",
                     str(cfg)] + list(cmd),
                    cwd=repo, env=env, capture_output=True, text=True,
                    timeout=30,
                )
                assert r.returncode == 0, r.stdout + r.stderr
                return json.loads(r.stdout)

            deadline = time.monotonic() + 90
            info = None
            while time.monotonic() < deadline:
                try:
                    info = client("server_info")
                    break
                except (AssertionError, json.JSONDecodeError,
                        subprocess.TimeoutExpired):
                    time.sleep(1.5)
            assert info is not None, "server never answered the CLI client"
            assert info["result"]["info"]["complete_ledgers"]
            accept = client("ledger_accept")
            assert accept["result"]["ledger_current_index"] >= 2
        finally:
            srv.terminate()
            try:
                srv.wait(timeout=10)
            except subprocess.TimeoutExpired:
                srv.kill()

        # offline tooling over the PERSISTED stores (server is down)
        r = subprocess.run(
            [sys.executable, "-m", "stellard_tpu", "--conf", str(cfg),
             "--dump_ledger", "1"],
            cwd=repo, env=env, capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        dumped = json.loads(r.stdout)
        assert dumped["ledger_index"] == 1
        assert dumped["accountState"], "dump carries the state entries"
