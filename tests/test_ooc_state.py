"""Out-of-core state plane (ISSUE 13): lazy node faulting
(state/shamap.py Stub/LazyInner/NodeSource), the bounded epoch-aware
hot-node cache (state/hotcache.py), and history shards
(nodestore/shards.py) — byte-identity between lazy and eager trees,
single-flight concurrent faulting, byte-bounded eviction with epoch
preference, shard seal/verify/serve, and the below-floor account_tx
routing."""

from __future__ import annotations

import hashlib
import threading

import pytest

from stellard_tpu.state.hotcache import HotNodeCache
from stellard_tpu.state.shamap import (
    SHAMap,
    SHAMapItem,
    LazyInner,
    Stub,
    configure_inner_cache,
    inner_node_cache,
)
from stellard_tpu.utils.hashes import sha512_half


def _tag(s) -> bytes:
    return hashlib.sha256(f"{s}".encode()).digest()


def _build(n: int, prefix: str = "k") -> tuple[SHAMap, dict]:
    m = SHAMap()
    m.bulk_update(sets=[
        SHAMapItem(_tag(f"{prefix}{i}"), f"payload-{i}".encode())
        for i in range(n)
    ])
    store: dict[bytes, bytes] = {}
    m.get_hash()
    m.flush(store.__setitem__)
    return m, store


@pytest.fixture(autouse=True)
def _fresh_cache():
    cache = inner_node_cache()
    cache.clear()
    configure_inner_cache(64)
    yield
    cache.clear()
    configure_inner_cache(64)


class TestLazyFaulting:
    def test_open_is_root_only(self):
        m, store = _build(2000)
        cache = inner_node_cache()
        cache.faults = 0
        lz = SHAMap.from_store(m.get_hash(), store.get, lazy=True)
        assert type(lz.root) is LazyInner
        assert cache.faults == 1  # the root, nothing else
        assert lz.get_hash() == m.get_hash()  # hash needs no walk
        assert cache.faults == 1

    def test_point_reads_fault_on_demand(self):
        m, store = _build(2000)
        cache = inner_node_cache()
        lz = SHAMap.from_store(m.get_hash(), store.get, lazy=True)
        before = cache.faults
        assert lz.get(_tag("k7")).data == b"payload-7"
        path_faults = cache.faults - before
        assert 0 < path_faults <= 8  # O(depth), not O(tree)
        # re-read: pure cache hits
        before = cache.faults
        assert lz.get(_tag("k7")).data == b"payload-7"
        assert cache.faults == before
        assert lz.get(_tag("absent-key")) is None

    def test_walk_and_len_parity(self):
        m, store = _build(500)
        lz = SHAMap.from_store(m.get_hash(), store.get, lazy=True)
        assert len(lz) == 500
        assert [l.item.tag for l in lz.leaves()] == \
            [l.item.tag for l in m.leaves()]

    def test_succ_cursor_parity(self):
        m, store = _build(300)
        lz = SHAMap.from_store(m.get_hash(), store.get, lazy=True)
        k = b"\x00" * 32
        walked = []
        while True:
            item = lz.succ(k)
            if item is None:
                break
            walked.append(item.tag)
            k = item.tag
        assert walked == sorted(l.item.tag for l in m.leaves())

    @pytest.mark.parametrize("use_native", [False, True])
    def test_mutation_byte_identity(self, use_native, monkeypatch):
        if use_native:
            from stellard_tpu.native import load_stser

            if load_stser() is None:
                pytest.skip("native stser unavailable")
        else:
            import stellard_tpu.state.shamap as sm

            monkeypatch.setattr(sm, "_native_merge", None)
            monkeypatch.setattr(sm, "_native_resolved", True)
        m, store = _build(800)
        lz = SHAMap.from_store(m.get_hash(), store.get, lazy=True)
        sets = [SHAMapItem(_tag(f"new{i}"), b"new-%d" % i)
                for i in range(50)]
        dels = [_tag(f"k{i}") for i in range(100, 160)]
        m.bulk_update(sets=sets, deletes=dels)
        lz.bulk_update(sets=sets, deletes=dels)
        assert lz.get_hash() == m.get_hash()
        # per-key mutations too (set_item / del_item fold-up paths)
        m.set_item(SHAMapItem(_tag("solo"), b"solo"))
        lz.set_item(SHAMapItem(_tag("solo"), b"solo"))
        m.del_item(_tag("k3"))
        lz.del_item(_tag("k3"))
        assert lz.get_hash() == m.get_hash()

    def test_compare_faults_only_the_delta(self):
        m, store = _build(2000)
        lz = SHAMap.from_store(m.get_hash(), store.get, lazy=True)
        other = m.snapshot()
        other.set_item(SHAMapItem(_tag("k17"), b"CHANGED"))
        cache = inner_node_cache()
        before = cache.faults
        delta = lz.compare(other)
        assert set(delta) == {_tag("k17")}
        # shared subtrees short-circuit on hashes: the walk faults a
        # path, not the tree
        assert cache.faults - before <= 10

    def test_flush_same_store_never_faults_cold_tail(self):
        m, store = _build(1000)
        known = set(store)  # "this store already holds these"
        lz = SHAMap.from_store(m.get_hash(), store.get, lazy=True,
                               store_known=known)
        lz.set_item(SHAMapItem(_tag("extra"), b"extra"))
        cache = inner_node_cache()
        out: dict[bytes, bytes] = {}
        before = cache.faults
        n = lz.flush(out.__setitem__, known=known)
        # only the dirty path was written, and flushing faulted nothing
        assert 0 < n <= 10
        assert cache.faults == before
        for h, blob in out.items():
            assert sha512_half(blob) == h

    def test_flush_to_foreign_store_materializes_everything(self):
        m, store = _build(300)
        lz = SHAMap.from_store(m.get_hash(), store.get, lazy=True,
                               store_known=set(store))
        other: dict[bytes, bytes] = {}
        n = lz.flush(other.__setitem__)
        assert n == len(store)
        assert set(other) == set(store)

    def test_corrupt_node_detected_at_fault_time(self):
        m, store = _build(200)
        victim = next(iter(store))
        store[victim] = store[victim] + b"x"
        lz = SHAMap.from_store(m.get_hash(), store.get, lazy=True)
        with pytest.raises((ValueError, KeyError)):
            for leaf in lz.leaves():
                pass

    def test_missing_node_raises_keyerror_at_fault(self):
        m, store = _build(200)
        h = m.get_hash()
        lz = SHAMap.from_store(h, store.get, lazy=True)
        # drop an interior node AFTER the lazy open
        victims = [k for k in store if k != h]
        for v in victims[:50]:
            del store[v]
        inner_node_cache().clear()
        with pytest.raises(KeyError):
            for leaf in lz.leaves():
                pass


class TestConcurrentFaulting:
    def test_two_threads_share_one_node_one_fetch(self):
        """Satellite pin: two threads faulting the same hash must share
        ONE node object, counters consistent, no double-fetch."""
        m, store = _build(400)
        fetches = {"n": 0}
        gate = threading.Event()

        def slow_fetch(h):
            fetches["n"] += 1
            gate.wait(1.0)  # widen the race window
            return store.get(h)

        lz = SHAMap.from_store(m.get_hash(), store.get, lazy=True)
        cache = inner_node_cache()
        cache.clear()
        lz._source.fetch = slow_fetch
        fetches["n"] = 0
        faults0, hits0, misses0 = cache.faults, cache.hits, cache.misses
        target = _tag("k5")
        results: list = []
        errors: list = []

        def walk():
            try:
                results.append(lz.get_leaf(target))
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=walk) for _ in range(6)]
        for t in threads:
            t.start()
        gate.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors
        assert len(results) == 6
        # same leaf OBJECT, not six parses of it
        assert all(r is results[0] for r in results)
        # every level fetched at most once across all six threads
        per_key = fetches["n"]
        distinct = cache.faults - faults0
        assert per_key == distinct, (per_key, distinct)
        # counters consistent: every lookup was a hit, a fault, or a
        # shared-flight wait — nothing double-counted
        j = cache.get_json()
        assert (j["hits"] - hits0) + (j["misses"] - misses0) >= distinct

    def test_failed_load_does_not_poison_the_key(self):
        m, store = _build(100)
        lz = SHAMap.from_store(m.get_hash(), store.get, lazy=True)
        cache = inner_node_cache()
        cache.clear()
        real = dict(store)
        broken = {"on": True}

        def flaky(h):
            if broken["on"]:
                return None  # transient miss
            return real.get(h)

        lz._source.fetch = flaky
        with pytest.raises(KeyError):
            lz.get(_tag("k1"))
        broken["on"] = False
        assert lz.get(_tag("k1")).data == b"payload-1"


class TestHotNodeCache:
    def test_byte_bound_evicts_lru(self):
        c = HotNodeCache("t", limit_bytes=10_000)

        class N:
            pass

        for i in range(100):
            c.put(_tag(i), N(), blob_len=0)
        assert c.resident_bytes <= 10_000
        assert c.evictions > 0
        # the most recently inserted keys survive
        assert c.get(_tag(99)) is not None
        assert c.get(_tag(0)) is None

    def test_epoch_entries_evicted_first(self):
        c = HotNodeCache("t", limit_bytes=1_000_000)

        class N:
            pass

        old = [_tag(f"old{i}") for i in range(20)]
        for k in old:
            c.put(k, N())
        c.advance_epoch(5)
        new = [_tag(f"new{i}") for i in range(20)]
        for k in new:
            c.put(k, N())
        # touch one OLD entry under the new epoch: it is promoted
        c.get(old[0])
        c.set_limit(c.resident_bytes - 1)  # force one eviction round
        # victims came from the old epoch, not the serving snapshot's
        assert c.epoch_first_evictions > 0
        assert all(c.get(k) is not None for k in new)
        assert c.get(old[0]) is not None  # promoted by the touch

    def test_get_or_load_single_flight_counters(self):
        c = HotNodeCache("t", limit_bytes=1 << 20)
        calls = {"n": 0}

        def loader(key):
            calls["n"] += 1
            return object(), 100

        k = _tag("x")
        a = c.get_or_load(k, loader)
        b = c.get_or_load(k, loader)
        assert a is b and calls["n"] == 1
        assert c.faults == 1 and c.hits == 1

    def test_eager_entries_capped_by_count(self):
        from stellard_tpu.state import hotcache as hc

        c = HotNodeCache("t", limit_bytes=1 << 30)  # byte bound inert

        class N:
            pass

        cap = 8
        orig = hc.EAGER_ENTRY_CAP
        hc.EAGER_ENTRY_CAP = cap
        try:
            for i in range(3 * cap):
                c.put(_tag(f"e{i}"), N(), eager=True)
            assert c._eager_count == cap
            assert c.evictions == 2 * cap
            # oldest eager entries were the victims; newest survive
            assert c.get(_tag(f"e{3 * cap - 1}")) is not None
            assert c.get(_tag("e0")) is None
            # byte-budget eviction keeps the eager count consistent
            c.set_limit(0)
            assert c._eager_count == 0 and c.resident_bytes == 0
            c.put(_tag("again"), N(), eager=True)
            c.clear()
            assert c._eager_count == 0
        finally:
            hc.EAGER_ENTRY_CAP = orig

    def test_cold_puts_are_first_eviction_victims(self):
        c = HotNodeCache("t", limit_bytes=1 << 20)

        class N:
            pass

        c.advance_epoch(7)
        hot = [_tag(f"hot{i}") for i in range(10)]
        for k in hot:
            c.put(k, N())
        # cold faults (a historical-ledger scan) stamp one epoch BEHIND
        # current, so they lose to the serving snapshot's working set
        # even within one epoch
        cold = [_tag(f"cold{i}") for i in range(10)]
        for k in cold:
            c.put(k, N(), cold=True)
        promoted = cold[0]
        c.get(promoted)  # a hit proves the entry shared: promote it
        c.set_limit(c.resident_bytes - 1)
        assert c.epoch_first_evictions > 0
        assert all(c.get(k) is not None for k in hot)
        assert c.get(promoted) is not None


class TestHistoryShards:
    def _ledger_chain(self, tmp_path, n_ledgers=6, accounts=30):
        """A real mini-chain persisted into a segstore Database:
        returns (db, headers ascending)."""
        from stellard_tpu.nodestore.core import make_database
        from stellard_tpu.state.ledger import Ledger
        from stellard_tpu.protocol.keys import KeyPair

        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           async_writes=False)
        master = KeyPair.from_passphrase("masterpassphrase")
        led = Ledger.genesis(master.account_id)
        headers = []
        for i in range(n_ledgers):
            led.close(close_time=1000 + 30 * i, close_resolution=30)
            led.save(db)
            headers.append({
                "hash": led.hash(), "seq": led.seq,
                "parent_hash": led.parent_hash,
                "account_hash": led.account_hash,
                "tx_hash": led.tx_hash,
            })
            nxt = led.open_successor()
            nxt.write_entry(
                _tag(f"acct-{i}"),
                led.read_entry(
                    __import__("stellard_tpu.state.indexes",
                               fromlist=["indexes"]
                               ).account_root_index(master.account_id)
                ),
            )
            led = nxt
        return db, headers

    def test_rotate_seal_verify_and_serve(self, tmp_path):
        from stellard_tpu.nodestore.shards import (
            SHARD_SEG_BASE,
            CombinedSegmentSource,
            HistoryShardStore,
            rotate_into_shards,
        )
        from stellard_tpu.node.inbound import iter_segment_records

        db, headers = self._ledger_chain(tmp_path)
        ss = HistoryShardStore(str(tmp_path / "shards"))
        retired, retained = headers[:4], headers[4:]
        sid = rotate_into_shards(db, ss, retired, retained)
        assert sid is not None
        # offline verification contract: per-record hashes + crc +
        # header chain, from the file alone
        report = ss.verify(sid)
        assert report["ok"], report
        # the live store really lost the retired-only nodes
        assert db.fetch(retired[0]["hash"]) is None
        assert db.fetch(retained[0]["hash"]) is not None
        # the combined manifest serves the shard over the same door,
        # every record self-verifying through the catch-up iterator
        src = CombinedSegmentSource(db.backend, ss)
        rows = src.segments()
        shard_rows = [r for r in rows if r["id"] >= SHARD_SEG_BASE]
        assert len(shard_rows) == 1
        meta, raw = src.fetch_segment(shard_rows[0]["id"])
        assert meta["size"] == len(raw) > 0
        n = 0
        for key, _tb, blob in iter_segment_records(raw):
            assert sha512_half(blob) == key
            n += 1
        assert n == meta["size"] // 40 or n > 0
        # chunked reads reassemble byte-identically
        out = bytearray()
        while len(out) < meta["size"]:
            _m, chunk = src.fetch_segment(
                shard_rows[0]["id"], offset=len(out), length=97
            )
            out += chunk
        assert bytes(out) == raw
        # the retired headers resolve FROM THE SHARD records (a cold
        # node ingesting them can rebuild the retired range)
        keys = {key for key, _tb, _blob in iter_segment_records(raw)}
        assert retired[0]["hash"] in keys
        db.close()
        ss.close()

    def test_index_survives_reopen(self, tmp_path):
        from stellard_tpu.nodestore.shards import HistoryShardStore, \
            rotate_into_shards

        db, headers = self._ledger_chain(tmp_path)
        ss = HistoryShardStore(str(tmp_path / "shards"))
        rotate_into_shards(db, ss, headers[:3], headers[3:])
        rng = ss.range()
        ss.close()
        ss2 = HistoryShardStore(str(tmp_path / "shards"))
        assert ss2.range() == rng
        assert ss2.verify(ss2.shards()[0]["id"])["ok"]
        db.close()
        ss2.close()

    def test_account_tx_rows_roundtrip(self, tmp_path):
        """Shard-served account_tx rows: the acct index pages in
        (ledger_seq, txn_seq) order with the exclusive marker, and tx
        blobs decode on demand from the shard records."""
        from stellard_tpu.nodestore.core import make_database
        from stellard_tpu.nodestore.shards import HistoryShardStore
        from stellard_tpu.state.ledger import Ledger
        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.protocol.formats import TxType
        from stellard_tpu.protocol.sfields import sfAmount, sfDestination
        from stellard_tpu.protocol.stamount import STAmount
        from stellard_tpu.protocol.sttx import SerializedTransaction

        master = KeyPair.from_passphrase("masterpassphrase")
        dest = KeyPair.from_passphrase("shard-dest").account_id
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           async_writes=False)
        led = Ledger.genesis(master.account_id)
        acct_rows = []
        headers = []
        txids_by_seq: dict[int, list[bytes]] = {}
        for seq_i in range(4):
            led.close(close_time=1000 + 30 * seq_i, close_resolution=30)
            led.save(db)
            headers.append({
                "hash": led.hash(), "seq": led.seq,
                "parent_hash": led.parent_hash,
                "account_hash": led.account_hash,
                "tx_hash": led.tx_hash,
            })
            led = led.open_successor()
            for t in range(2):
                tx = SerializedTransaction.build(
                    TxType.ttPAYMENT, master.account_id,
                    seq_i * 2 + t + 1, 10,
                    {sfAmount: STAmount.from_drops(1000),
                     sfDestination: dest},
                )
                tx.sign(master)
                txid = led.add_transaction(tx.serialize(), b"\x01\x02")
                acct_rows.append(
                    (master.account_id, led.seq, t, txid)
                )
                txids_by_seq.setdefault(led.seq, []).append(txid)
        led.close(close_time=2000, close_resolution=30)
        led.save(db)
        headers.append({
            "hash": led.hash(), "seq": led.seq,
            "parent_hash": led.parent_hash,
            "account_hash": led.account_hash,
            "tx_hash": led.tx_hash,
        })
        ss = HistoryShardStore(str(tmp_path / "shards"))
        from stellard_tpu.nodestore.shards import collect_retired

        def fetch(h):
            o = db.fetch(h, populate_cache=False)
            return o.data if o else None

        records = collect_retired(fetch, headers, set())
        ss.seal(headers[0]["seq"], headers[-1]["seq"], records,
                acct_rows, first_hash=headers[0]["hash"],
                last_hash=headers[-1]["hash"])
        rows = ss.account_tx(master.account_id, 1, 100, limit=100,
                             forward=True)
        assert [r["txid"] for r in rows] == [
            txid for _a, _s, _t, txid in acct_rows
        ]
        for r in rows:
            assert r["raw"] and r["meta"] == b"\x01\x02"
            assert "shard" in r
        # exclusive marker resume, both directions
        after = (rows[2]["ledger_seq"], rows[2]["txn_seq"])
        fwd = ss.account_tx(master.account_id, 1, 100, forward=True,
                            after=after)
        assert [r["txid"] for r in fwd] == [r["txid"] for r in rows[3:]]
        back = ss.account_tx(master.account_id, 1, 100, forward=False,
                             after=after)
        assert [r["txid"] for r in back] == [
            r["txid"] for r in reversed(rows[:2])
        ]
        db.close()
        ss.close()


class TestAccountTxShardRouting:
    def _ctx(self, floor, shard_range, marker=None, min_l=1, max_l=None):
        from types import SimpleNamespace

        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.rpc.handlers import Context, Role

        acct = KeyPair.from_passphrase("masterpassphrase")
        shardstore = SimpleNamespace(
            range=lambda: shard_range,
            account_tx=lambda *a, **k: [],
        )
        txdb = SimpleNamespace(
            retain_floor=floor,
            account_transactions=lambda *a, **k: [],
        )
        node = SimpleNamespace(txdb=txdb, shardstore=shardstore,
                               close_pipeline=None)
        params = {"account": acct.human_account_id,
                  "ledger_index_min": min_l}
        if max_l is not None:
            params["ledger_index_max"] = max_l
        if marker is not None:
            params["marker"] = marker
        return Context(node, params, Role.ADMIN)

    def test_window_below_oldest_shard_fails_cleanly(self):
        """History trimmed BEFORE shards were enabled is gone
        everywhere: a window or marker below the first sealed shard
        must keep the lgrIdxInvalid contract, never a quietly
        complete-looking empty page."""
        from stellard_tpu.rpc.handlers import RPCError, do_account_tx

        # shards cover [5, 9], floor 10: window entirely below shard 5
        with pytest.raises(RPCError):
            do_account_tx(self._ctx(10, (5, 9), min_l=1, max_l=3))
        # marker resuming below the oldest shard
        with pytest.raises(RPCError):
            do_account_tx(self._ctx(10, (5, 9),
                                    marker={"ledger": 2, "seq": 0}))
        # straddling window clamps to the oldest shard and echoes it
        out = do_account_tx(self._ctx(10, (5, 9), min_l=1, max_l=20))
        assert out["ledger_index_min"] == 5

    def test_no_shards_keeps_floor_contract(self):
        from stellard_tpu.rpc.handlers import RPCError, do_account_tx

        with pytest.raises(RPCError):
            do_account_tx(self._ctx(10, None, min_l=1, max_l=3))
        out = do_account_tx(self._ctx(10, None, min_l=1, max_l=20))
        assert out["ledger_index_min"] == 10


class TestNativeScan:
    def test_segrecs_scan_matches_python_iter(self, tmp_path):
        from stellard_tpu.native import load_native, scan_segment_records
        from stellard_tpu.nodestore.shards import (
            _iter_records_py, _pack_records,
        )

        lib = load_native()
        if lib is None or not getattr(lib, "has_segrecs_scan", False):
            pytest.skip("native segrecs_scan unavailable")
        records = []
        for i in range(64):
            blob = b"N" * (i % 7 + 1) + _tag(i)
            records.append((sha512_half(blob), i % 5, blob))
        img = _pack_records(records) + b"\x03torn"
        path = tmp_path / "recs.bin"
        path.write_bytes(img)
        native = scan_segment_records(str(path))
        py = list(_iter_records_py(img))
        assert [(k, t, o, ln) for k, t, o, ln in native] == py
        for (k, _t, off, ln), (_ek, _et, eblob) in zip(native, records):
            assert img[off: off + ln] == eblob