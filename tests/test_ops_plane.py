"""Ops-plane round-out: TaggedCache/KeyCache, NodeStore --import
migration, the sustain supervisor, and validator file/site sources.
"""

from __future__ import annotations

import http.server
import threading

import pytest

from stellard_tpu.node.config import Config
from stellard_tpu.node.sitefiles import (
    fetch_site_validators,
    load_validators_file,
    parse_validators_text,
)
from stellard_tpu.nodestore.core import NodeObjectType, make_database
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.utils.taggedcache import KeyCache, TaggedCache


class TestTaggedCache:
    def test_lru_and_expiry(self):
        now = [0.0]
        c = TaggedCache("t", target_size=3, expiration_s=10.0,
                        clock=lambda: now[0])
        for i in range(4):
            c.put(i, f"v{i}")
        assert len(c) == 3 and c.get(0) is None  # oldest evicted
        assert c.get(3) == "v3"
        now[0] = 11.0
        assert c.get(3) is None  # expired
        assert c.get_json()["hits"] == 1

    def test_fetch_loads_once(self):
        c = TaggedCache("t", target_size=8)
        calls = []

        def loader():
            calls.append(1)
            return "x"

        assert c.fetch("k", loader) == "x"
        assert c.fetch("k", loader) == "x"
        assert len(calls) == 1

    def test_sweep_and_keycache(self):
        now = [0.0]
        kc = KeyCache("full_below", expiration_s=5.0, clock=lambda: now[0])
        kc.insert(b"\x01")
        assert b"\x01" in kc
        now[0] = 6.0
        assert kc.sweep() == 1
        assert b"\x01" not in kc


class TestNodeStoreImport:
    def test_migrates_all_objects(self, tmp_path):
        from stellard_tpu.__main__ import _import_nodestore

        src = make_database(type="sqlite", path=str(tmp_path / "src.db"),
                            async_writes=False)
        for i in range(40):
            src.store(NodeObjectType.ACCOUNT_NODE, i.to_bytes(32, "big"),
                      b"obj-%d" % i)
        src.close()
        cfg = Config(node_db_type="sqlite",
                     node_db_path=str(tmp_path / "dst.db"))
        assert _import_nodestore(f"sqlite:{tmp_path/'src.db'}", cfg) == 0
        dst = make_database(type="sqlite", path=str(tmp_path / "dst.db"),
                            async_writes=False)
        assert sum(1 for _ in dst.backend.iterate()) == 40
        assert dst.fetch((11).to_bytes(32, "big")).data == b"obj-11"
        dst.close()


class TestSustain:
    def test_restarts_until_clean_exit(self, monkeypatch):
        import stellard_tpu.__main__ as m

        codes = iter([1, 1, 0])
        calls = []

        def fake_call(cmd):
            calls.append(cmd)
            return next(codes)

        monkeypatch.setattr("subprocess.call", fake_call)
        monkeypatch.setattr("time.sleep", lambda s: None)
        rc = m._sustain(["--sustain", "-a", "--rpc_port", "5005"])
        assert rc == 0
        assert len(calls) == 3
        # the child never re-enters sustain mode
        assert all("--sustain" not in c for c in calls)
        assert all("-a" in c for c in calls)


class TestValidatorSources:
    def test_parse_plain_and_sectioned(self):
        v1 = KeyPair.from_passphrase("vs-1").human_node_public
        v2 = KeyPair.from_passphrase("vs-2").human_node_public
        plain = f"# comment\n{v1} first validator\n{v2}\n"
        assert parse_validators_text(plain) == [
            (v1, "first validator"), (v2, "")
        ]
        sectioned = (
            "[domain]\nexample.com\n\n[validators]\n"
            f"{v1} alpha\n[other]\nignored\n"
        )
        assert parse_validators_text(sectioned) == [(v1, "alpha")]

    def test_node_loads_file_and_site_sources(self, tmp_path):
        from stellard_tpu.node.node import Node

        v_file = KeyPair.from_passphrase("vs-file").human_node_public
        v_site = KeyPair.from_passphrase("vs-site").human_node_public
        vf = tmp_path / "validators.txt"
        vf.write_text(f"{v_file} from-file\n")

        site_text = f"[validators]\n{v_site} from-site\n".encode()

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                self.send_response(200)
                self.end_headers()
                self.wfile.write(site_text)

            def log_message(self, *a):
                pass

        httpd = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            cfg = Config(
                standalone=True, signature_backend="cpu",
                validators_file=str(vf),
                validators_site=(
                    f"http://127.0.0.1:{httpd.server_address[1]}/stellar.txt"
                ),
            )
            node = Node(cfg).setup()
            try:
                import time

                from stellard_tpu.protocol.keys import decode_node_public

                assert decode_node_public(v_file) in node.unl
                # the site source fetches on a background thread (startup
                # must not block on a remote site): wait for it
                deadline = time.monotonic() + 10
                while (
                    decode_node_public(v_site) not in node.unl
                    and time.monotonic() < deadline
                ):
                    time.sleep(0.05)
                assert decode_node_public(v_site) in node.unl
                entries = {e["pubkey_validator"]: e["comment"]
                           for e in node.unl.get_json()}
                assert entries[v_file] == "from-file"
                assert entries[v_site] == "from-site"
            finally:
                node.verify_plane.stop()
                node.job_queue.stop()
        finally:
            httpd.shutdown()

    def test_unreachable_site_does_not_kill_node(self):
        from stellard_tpu.node.node import Node

        cfg = Config(
            standalone=True, signature_backend="cpu",
            validators_site="http://127.0.0.1:9/stellar.txt",
        )
        node = Node(cfg).setup()
        node.verify_plane.stop()
        node.job_queue.stop()


class TestLocalCredentials:
    def test_node_identity_persists_across_restarts(self, tmp_path):
        from stellard_tpu.node.node import Node

        cfg = Config(standalone=True, signature_backend="cpu",
                     database_path=str(tmp_path / "tx.db"))
        n1 = Node(cfg).setup()
        pub1 = n1.node_keys.public
        n1.verify_plane.stop()
        n1.job_queue.stop()
        n2 = Node(cfg).setup()
        try:
            assert n2.node_keys.public == pub1  # wallet.db role
        finally:
            n2.verify_plane.stop()
            n2.job_queue.stop()

    def test_ephemeral_without_database_path(self):
        from stellard_tpu.node.node import Node

        cfg = Config(standalone=True, signature_backend="cpu")
        n = Node(cfg).setup()
        try:
            assert n.node_keys is not None
        finally:
            n.verify_plane.stop()
            n.job_queue.stop()


class TestIntakeOrdering:
    """Ordered intake drain (networkops._enqueue_intake): same-account
    bursts must apply in submission order (no spurious terPRE_SEQ
    holds), and a poisoned entry must neither drop the rest of its
    batch nor wedge the drain flag."""

    def _node(self):
        from stellard_tpu.node.config import Config
        from stellard_tpu.node.node import Node

        return Node(Config(signature_backend="cpu")).setup()

    def test_burst_applies_in_order_no_holds(self):
        import threading

        from stellard_tpu.protocol.formats import TxType
        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.protocol.sfields import sfAmount, sfDestination
        from stellard_tpu.protocol.stamount import STAmount
        from stellard_tpu.protocol.sttx import SerializedTransaction

        node = self._node()
        try:
            master = KeyPair.from_passphrase("masterpassphrase")
            dest = KeyPair.from_passphrase("intake-dest")
            txs = []
            for i in range(200):
                tx = SerializedTransaction.build(
                    TxType.ttPAYMENT, master.account_id, 1 + i, 10,
                    {sfAmount: STAmount.from_drops(250_000_000),
                     sfDestination: dest.account_id},
                )
                tx.sign(master)
                txs.append(tx)
            done = threading.Semaphore(0)
            results = []

            def cb(tx, ter, applied):
                results.append((ter, applied))
                done.release()

            for tx in txs:
                node.ops.submit_transaction(tx, cb)
            for _ in txs:
                assert done.acquire(timeout=30)
            assert node.ops.stats.get("held", 0) == 0, "burst was held"
            assert all(applied for _, applied in results)
            node.ops.accept_ledger()
            assert node.ledger_master.closed_ledger().seq == 2
        finally:
            node.stop()

    def test_poisoned_callback_does_not_wedge_intake(self):
        import threading

        from stellard_tpu.protocol.formats import TxType
        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.protocol.sfields import sfAmount, sfDestination
        from stellard_tpu.protocol.stamount import STAmount
        from stellard_tpu.protocol.sttx import SerializedTransaction

        node = self._node()
        try:
            master = KeyPair.from_passphrase("masterpassphrase")
            dest = KeyPair.from_passphrase("intake-dest-2")

            def payment(seq):
                tx = SerializedTransaction.build(
                    TxType.ttPAYMENT, master.account_id, seq, 10,
                    {sfAmount: STAmount.from_drops(250_000_000),
                     sfDestination: dest.account_id},
                )
                tx.sign(master)
                return tx

            done = threading.Semaphore(0)

            def bomb(tx, ter, applied):
                done.release()
                raise RuntimeError("poisoned callback")

            def ok_cb(tx, ter, applied):
                done.release()

            node.ops.submit_transaction(payment(1), bomb)
            node.ops.submit_transaction(payment(2), ok_cb)
            for _ in range(2):
                assert done.acquire(timeout=30)
            # intake must still be alive for NEW submissions
            node.ops.submit_transaction(payment(3), ok_cb)
            assert done.acquire(timeout=30)
            assert not node.ops._intake_scheduled or node.ops._intake
        finally:
            node.stop()
