"""Overlay defense plane (ISSUE 11): enforced resource pricing,
validator-message squelching, bounded per-peer sendqs, RPC-door
pricing, and the 200+-node flood-survival scenario.

Covers the acceptance spine:
- squelch determinism (same UNL + seq -> same subset, cross-process),
  rotation across epochs AND on peer churn, kill-switch;
- byte-identical convergence squelched-vs-flooded on one seed;
- resource enforcement: WARN throttle, DROP disconnect + gated
  readmission, sweep/expiry on a fake clock, aggregate pressure ->
  LoadFeeTrack;
- the sendq discipline (drop-oldest + eviction);
- FEE_*_RPC pricing on the HTTP/WS doors with admin exemption;
- SegmentCatchup condemnation taking a FEE_GARBAGE_SEGMENT charge
  (unified peer scoring);
- flood survival at 100 nodes with cross-process scorecard identity,
  and the hostile client against a REAL TCP overlay (byzantine matrix
  promoted onto genuine sockets).
"""

from __future__ import annotations

import json
import os
import struct
import subprocess
import sys
import time
import types

import pytest

from stellard_tpu.node.hashrouter import HashRouter
from stellard_tpu.node.loadmgr import NORMAL_FEE, LoadFeeTrack
from stellard_tpu.overlay.resource import (
    DROP_THRESHOLD,
    FEE_BAD_DATA,
    FEE_GARBAGE_SEGMENT,
    FEE_INVALID_SIGNATURE,
    SECONDS_UNTIL_EXPIRATION,
    WARNING_THRESHOLD,
    Charge,
    Disposition,
    ResourceManager,
)
from stellard_tpu.overlay.squelch import SquelchPolicy, relay_rank
from stellard_tpu.protocol.keys import KeyPair

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- resource manager ------------------------------------------------------


class TestResourceManager:
    def _rm(self, now):
        return ResourceManager(
            key_fn=lambda a: a[0], clock=lambda: now[0]
        )

    def test_warn_then_drop_with_counters(self):
        now = [0.0]
        rm = self._rm(now)
        addr = ("1.2.3.4", 0)
        disp = Disposition.OK
        while disp == Disposition.OK:
            disp = rm.charge(addr, FEE_BAD_DATA)
        assert disp == Disposition.WARN
        assert rm.warned == 1 and rm.is_throttled(addr)
        assert rm.status(addr) == Disposition.WARN
        while disp != Disposition.DROP:
            disp = rm.charge(addr, FEE_BAD_DATA)
        assert rm.dropped >= 1
        assert not rm.should_admit(addr)
        # decay under the drop line re-admits
        now[0] += 300.0
        assert rm.should_admit(addr)

    def test_sweep_expires_idle_entries_fake_clock(self):
        """Satellite pin: sweep() expiry semantics on a fake clock —
        entries idle past SECONDS_UNTIL_EXPIRATION vanish; active ones
        with a live balance survive."""
        now = [0.0]
        rm = self._rm(now)
        rm.charge(("idle", 0), FEE_INVALID_SIGNATURE)
        now[0] = 10.0
        rm.charge(("busy", 0), Charge(100_000, "big"))
        now[0] = SECONDS_UNTIL_EXPIRATION + 5.0  # idle aged out; busy not
        rm.sweep()
        ent = rm.get_json()["entries"]
        assert "idle" not in ent and "busy" in ent
        # and once everything decays to dust, sweep empties the table
        now[0] += 3000.0
        rm.sweep()
        assert rm.get_json()["entries"] == {}
        assert rm.get_json()["entry_count"] == 0

    def test_admin_exemption(self):
        now = [0.0]
        rm = ResourceManager(
            key_fn=lambda a: a[0], clock=lambda: now[0], admin={"admin"}
        )
        for _ in range(100):
            assert rm.charge(("admin", 0), FEE_INVALID_SIGNATURE) == (
                Disposition.OK
            )
        assert rm.should_admit(("admin", 0))
        assert not rm.is_throttled(("admin", 0))

    def test_aggregate_pressure_rises_and_decays(self):
        now = [0.0]
        rm = self._rm(now)
        assert rm.aggregate_pressure() == 0.0
        for i in range(4):
            rm.charge((f"p{i}", 0), Charge(WARNING_THRESHOLD, "x"))
        assert rm.aggregate_pressure() == pytest.approx(4.0)
        now[0] += 32.0  # one half-life
        assert rm.aggregate_pressure() == pytest.approx(2.0, rel=0.01)

    def test_note_counters(self):
        now = [0.0]
        rm = self._rm(now)
        rm.note_refused(("x", 0))
        rm.note_throttled(3)
        rm.note_disconnect()
        j = rm.get_json()
        assert (j["refused"], j["throttled"], j["disconnects"]) == (1, 3, 1)

    def test_warned_counts_crossings_not_charges(self):
        """Review-pass regression: an endpoint parked between WARN and
        DROP bumps `warned` once per CROSSING, not once per charge —
        and decaying under the line re-arms the crossing."""
        now = [0.0]
        rm = self._rm(now)
        addr = ("w", 0)
        rm.charge(addr, Charge(WARNING_THRESHOLD + 50, "x"))
        assert rm.warned == 1
        for _ in range(20):  # charges while already warned: no bumps
            assert rm.charge(addr, Charge(1, "tick")) == Disposition.WARN
        assert rm.warned == 1
        now[0] += 300.0  # decay far under the line
        assert rm.charge(addr, Charge(1, "ok")) == Disposition.OK
        rm.charge(addr, Charge(WARNING_THRESHOLD + 50, "x"))  # re-cross
        assert rm.warned == 2


class TestLoadFeePressure:
    def test_network_pressure_feeds_floor_and_factor(self):
        ft = LoadFeeTrack()
        assert ft.network_floor == NORMAL_FEE
        ft.set_network_pressure(NORMAL_FEE * 3)
        assert ft.network_floor == NORMAL_FEE * 3
        assert ft.load_factor == NORMAL_FEE * 3
        assert ft.get_json()["overlay_fee"] == NORMAL_FEE * 3
        ft.set_network_pressure(0)  # clamped to NORMAL
        assert ft.network_floor == NORMAL_FEE and not ft.is_loaded


class TestHashRouterDupAttribution:
    def test_same_peer_resend_flagged(self):
        r = HashRouter()
        h = b"\x11" * 32
        assert r.note_peer(h, 1) == (True, False)   # new
        assert r.note_peer(h, 2) == (False, False)  # cross-peer dup: free
        assert r.note_peer(h, 1) == (False, True)   # same-peer re-send
        # legacy boolean API unchanged
        assert r.add_suppression_peer(b"\x22" * 32, 9) is True
        assert r.add_suppression_peer(b"\x22" * 32, 9) is False


# -- squelch ---------------------------------------------------------------


class TestSquelchDeterminism:
    CANDS = [bytes([i]) * 32 for i in range(24)]

    def test_pure_function_and_rotation(self):
        p = SquelchPolicy(size=6, rotate=16, relayer_id=b"R" * 32)
        signer = b"V" * 32
        a = p.subset(signer, 100, self.CANDS, key_fn=lambda c: c)
        b = p.subset(signer, 100, self.CANDS, key_fn=lambda c: c)
        assert a == b and len(a) == 6
        # epoch rotation: seqs in one epoch agree, crossing rotates
        same = p.subset(signer, 111, self.CANDS, key_fn=lambda c: c)
        assert same == a  # 100//16 == 111//16
        rotated = p.subset(signer, 160, self.CANDS, key_fn=lambda c: c)
        assert rotated != a

    def test_cross_process_identity(self):
        """Same UNL + seq -> the same relay subset in ANOTHER process
        with a different PYTHONHASHSEED (no hash-seed leakage)."""
        p = SquelchPolicy(size=6, rotate=16, relayer_id=b"R" * 32)
        ours = [
            c.hex() for c in p.subset(
                b"V" * 32, 100, self.CANDS, key_fn=lambda c: c
            )
        ]
        script = (
            "import json\n"
            "from stellard_tpu.overlay.squelch import SquelchPolicy\n"
            "cands = [bytes([i]) * 32 for i in range(24)]\n"
            "p = SquelchPolicy(size=6, rotate=16, relayer_id=b'R' * 32)\n"
            "out = p.subset(b'V' * 32, 100, cands, key_fn=lambda c: c)\n"
            "print(json.dumps([c.hex() for c in out]))\n"
        )
        env = dict(os.environ)
        env["PYTHONHASHSEED"] = "31337"
        env["JAX_PLATFORMS"] = "cpu"
        theirs = json.loads(subprocess.check_output(
            [sys.executable, "-c", script], env=env, cwd=REPO,
        ))
        assert theirs == ours

    def test_rotation_on_peer_churn(self):
        """The subset is always ranked over the CURRENT candidates: a
        departed member vanishes immediately (bump() drops the memo)."""
        p = SquelchPolicy(size=6, rotate=16, relayer_id=b"R" * 32)
        signer = b"V" * 32
        a = p.subset(signer, 100, self.CANDS, key_fn=lambda c: c)
        survivors = [c for c in self.CANDS if c != a[0]]
        p.bump()
        b = p.subset(signer, 100, survivors, key_fn=lambda c: c)
        assert a[0] not in b and len(b) == 6
        # rank order among survivors is stable: b is a superset-ranked
        # re-pick, not a reshuffle
        assert b[:5] == [c for c in a[1:6]]

    def test_trusted_always_included_and_demotion(self):
        p = SquelchPolicy(size=4, rotate=16, demote_factor=4,
                          relayer_id=b"R" * 32)
        trusted = set(self.CANDS[20:])
        full = p.subset(
            b"V" * 32, 5, self.CANDS, key_fn=lambda c: c,
            trusted=lambda c: c in trusted,
        )
        assert trusted <= set(full)
        demoted = p.subset(
            b"E" * 32, 5, self.CANDS, key_fn=lambda c: c,
            trusted=lambda c: c in trusted, demoted=True,
        )
        assert len(demoted) == 1  # size // demote_factor, no inclusion

    def test_kill_switch_full_flood(self):
        p = SquelchPolicy(size=0)
        assert not p.enabled
        assert p.subset(b"V" * 32, 1, self.CANDS, key_fn=lambda c: c) == (
            self.CANDS
        )

    def test_rank_is_relayer_salted(self):
        # two relayers pick different subsets (k-out digraph, not one
        # global k-subset that would strand messages)
        a = relay_rank(b"V" * 32, 3, b"A" * 32, b"c" * 32)
        b = relay_rank(b"V" * 32, 3, b"B" * 32, b"c" * 32)
        assert a != b

    def test_memo_never_aliases_across_senders(self):
        """Review-pass regression: callers rank over the FULL candidate
        set and filter the sender from the RESULT. Excluding the sender
        from the ranking INPUT aliased the (count-keyed) memo across
        senders, echoing relays back to whoever sent the message."""
        from stellard_tpu.overlay.simnet import SimNet

        net = SimNet(2, n_peers=8, squelch_size=3)
        relayer = net.nodes[2]
        sent: list[tuple[int, int]] = []
        net.send = lambda src, dst, data: sent.append((src, dst))
        for sender in (3, 4, 5, 6):
            net.relay_validator(
                relayer.nid, net.keys[0].public, b"x", relayer.squelch,
                exclude=(sender,),
            )
            echoes = [d for s, d in sent if d == sender]
            assert not echoes, f"relay echoed back to sender {sender}"
            sent.clear()


# -- simnet: squelched vs flooded ------------------------------------------


class TestSimnetSquelch:
    def test_squelched_vs_flooded_byte_identical_chain(self):
        """One seed, squelch on vs off: the converged chain is
        byte-identical (same final seq, same final hash, same commit
        set) — squelching changes the relay graph, never the outcome."""
        from stellard_tpu.testkit.scenario import run_simnet
        from stellard_tpu.testkit.scenarios import scenario_flood_survival

        flood = run_simnet(scenario_flood_survival(
            seed=3, n_peers=20, steps=36, flooder=False, squelch=0,
        ))
        squelched = run_simnet(scenario_flood_survival(
            seed=3, n_peers=20, steps=36, flooder=False, squelch=4,
        ))
        assert flood["converged"] and squelched["converged"]
        assert flood["single_hash"] and squelched["single_hash"]
        assert squelched["final_seq"] == flood["final_seq"]
        assert squelched["final_hash"] == flood["final_hash"]
        assert squelched["committed"] == flood["committed"]
        # anti-vacuity: the squelched run actually relayed via subsets,
        # bounded by size + |UNL|
        assert squelched["relay"]["relay_proposal"] > 0
        assert 0 < squelched["relay"]["relay_fanout_max"] <= 4 + 5

    def test_legacy_net_shape_unchanged(self):
        """squelch=0 + no peers: the net is byte-for-byte the legacy
        transport — no relay tier, no new net_stats keys, origin
        broadcast only."""
        from stellard_tpu.overlay.simnet import SimNet

        net = SimNet(4)
        assert net.nodes == net.validators
        assert "relay_fanout_max" not in net.net_stats
        assert all(v.squelch is None and v.resources is None
                   for v in net.validators)


# -- flood survival (small, fast) ------------------------------------------


class TestFloodSurvival:
    def _card(self, **kw):
        from stellard_tpu.testkit.scenario import run_simnet
        from stellard_tpu.testkit.scenarios import scenario_flood_survival

        return run_simnet(scenario_flood_survival(
            seed=11, n_peers=45, steps=40, **kw
        ))

    def test_flooder_dropped_and_net_converges(self):
        card = self._card()
        assert card["converged"] and card["single_hash"]
        assert card["committed"] == card["submitted"]
        res = card["resource"]
        assert res["dropped"] > 0 and res["refused"] > 0
        assert res["throttled"] > 0 and res["warned"] > 0
        fl = next(iter(card["flooders"].values()))
        assert fl["refused_by"] >= 24  # the whole flooded neighbor set
        assert fl["first_refusal_ms"] is not None
        assert card["relay"]["relay_fanout_max"] <= 8 + 5

    def test_scorecard_cross_process_identical(self):
        """Seed-determinism ACROSS processes (different PYTHONHASHSEED):
        the acceptance criterion that keeps the flood gate replayable."""
        ours = self._card()
        script = (
            "import json\n"
            "from stellard_tpu.testkit.scenario import run_simnet\n"
            "from stellard_tpu.testkit.scenarios import "
            "scenario_flood_survival\n"
            "card = run_simnet(scenario_flood_survival("
            "seed=11, n_peers=45, steps=40))\n"
            "print(json.dumps(card, sort_keys=True, default=str))\n"
        )
        env = dict(os.environ)
        env["PYTHONHASHSEED"] = "424242"
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.check_output(
            [sys.executable, "-c", script], env=env, cwd=REPO,
            timeout=300,
        )
        theirs = out.decode().strip().splitlines()[-1]
        assert theirs == json.dumps(ours, sort_keys=True, default=str)


# -- sendq discipline ------------------------------------------------------


class TestSendqDiscipline:
    def _peer(self, depth=4, evict=6):
        import socket as _socket

        from stellard_tpu.overlay.tcp import _Peer

        a, b = _socket.socketpair()
        p = _Peer(a, inbound=False, sendq_depth=depth, evict_drops=evict)
        p._writer = object()  # writer "running" but never draining
        return p, b

    def test_drop_oldest_never_blocks_sender(self):
        p, other = self._peer(depth=4, evict=100)
        for i in range(10):
            p.send(struct.pack(">I", i))
        assert p.sendq.qsize() == 4
        assert p.sendq_dropped == 6
        # OLDEST were shed: the queue holds the newest four
        held = [struct.unpack(">I", p.sendq.get_nowait())[0]
                for _ in range(4)]
        assert held == [6, 7, 8, 9]
        assert p.alive
        other.close()
        p.close()

    def test_consecutive_overflow_evicts(self):
        p, other = self._peer(depth=2, evict=5)
        for i in range(12):
            p.send(b"x" * 8)
        assert p.evicted and not p.alive
        other.close()

    def test_successful_send_resets_drop_streak(self):
        p, other = self._peer(depth=2, evict=3)
        p.send(b"a")
        p.send(b"b")
        p.send(b"c")  # overflow 1
        p.send(b"d")  # overflow 2
        p.sendq.get_nowait()
        p.sendq.get_nowait()  # drain (the writer's job)
        p.send(b"e")  # success -> streak resets
        assert p._consec_drops == 0 and not p.evicted
        p.send(b"f")
        p.send(b"g")  # overflow 1 again — streak restarted, no eviction
        assert p.alive
        other.close()
        p.close()


# -- RPC door pricing ------------------------------------------------------


class TestRpcDoorPricing:
    def _node(self, admin=()):
        return types.SimpleNamespace(
            rpc_resources=ResourceManager(admin=set(admin))
        )

    def test_heavy_client_hits_slowdown(self):
        from stellard_tpu.rpc.handlers import Role, charge_rpc_client

        node = self._node()
        refused = None
        for _ in range(50):
            refused = charge_rpc_client(node, "9.9.9.9", "sign", Role.GUEST)
            if refused is not None:
                break
        assert refused is not None and refused["error"] == "slowDown"
        assert node.rpc_resources.dropped >= 1
        # and the door REFUSES (charge-free) until the balance decays
        again = charge_rpc_client(node, "9.9.9.9", "server_info",
                                  Role.GUEST)
        assert again is not None and node.rpc_resources.refused >= 1

    def test_admin_never_charged(self):
        from stellard_tpu.rpc.handlers import Role, charge_rpc_client

        node = self._node(admin={"10.0.0.1"})
        for _ in range(100):
            assert charge_rpc_client(
                node, "10.0.0.1", "sign", Role.GUEST
            ) is None
            assert charge_rpc_client(
                node, "9.9.9.9", "sign", Role.ADMIN
            ) is None
        assert node.rpc_resources.dropped == 0

    def test_http_door_charges_and_refuses(self):
        from stellard_tpu.rpc.http_server import process_http_request
        from stellard_tpu.rpc.handlers import Role

        node = self._node()
        body = json.dumps({"method": "sign", "params": [{}]}).encode()
        last = None
        for _ in range(50):
            last = process_http_request(
                node, body, role=Role.GUEST, client_ip="6.6.6.6"
            )
            if last["result"].get("error") == "slowDown":
                break
        assert last["result"]["error"] == "slowDown"

    def test_malformed_requests_charged(self):
        from stellard_tpu.rpc.http_server import process_http_request
        from stellard_tpu.rpc.handlers import Role

        node = self._node()
        process_http_request(node, b"{not json", role=Role.GUEST,
                             client_ip="6.6.6.7")
        assert node.rpc_resources.balance(("6.6.6.7", 0)) > 0

    def test_malformed_path_honors_drop_gate(self):
        """Review-pass regression: a client past the drop line sending
        MALFORMED bodies gets slowDown, not normal error processing."""
        from stellard_tpu.rpc.http_server import process_http_request
        from stellard_tpu.rpc.handlers import Role

        node = self._node()
        node.rpc_resources.charge(("6.6.6.8", 0), Charge(10_000, "flood"))
        r = process_http_request(node, b"{not json", role=Role.GUEST,
                                 client_ip="6.6.6.8")
        assert r["result"]["error"] == "slowDown"
        r = process_http_request(
            node, json.dumps({"method": 7}).encode(),
            role=Role.GUEST, client_ip="6.6.6.8",
        )
        assert r["result"]["error"] == "slowDown"

    def test_warn_advisory_field_on_served_responses(self):
        """Review-pass regression: a client in WARN (but not DROP) gets
        `warning: "load"` attached to served responses — the documented
        advisory back-off signal."""
        from stellard_tpu.rpc.http_server import process_http_request
        from stellard_tpu.rpc.handlers import Role, rpc_warning

        node = self._node()
        ip = "6.6.6.9"
        node.rpc_resources.charge((ip, 0), Charge(WARNING_THRESHOLD, "x"))
        node.rpc_resources.charge((ip, 0), Charge(100, "x"))  # stay warned
        assert rpc_warning(node, ip, Role.GUEST) == "load"
        assert rpc_warning(node, ip, Role.ADMIN) is None
        r = process_http_request(
            node, json.dumps({"method": "server_info"}).encode(),
            role=Role.GUEST, client_ip=ip,
        )
        assert r["result"].get("warning") == "load"


# -- unified peer scoring (catch-up condemnation -> overlay charge) --------


class TestCondemnCharge:
    def test_condemned_transfer_fires_on_condemn(self):
        from stellard_tpu.node.inbound import SegmentCatchup

        condemned = []
        sent = []
        now = [0.0]
        sc = SegmentCatchup(
            send=lambda peer, msg: sent.append((peer, msg)),
            peers=lambda: ["p1", "p2"],
            store=lambda tb, k, b: None,
            clock=lambda: now[0],
            on_condemn=condemned.append,
        )
        sc.start()
        sc.on_manifest("p1", [(0, 64, 64, True)])
        # one garbage record: key != sha512h(blob)
        blob = b"\x00garbage"
        body = bytes([0]) + blob  # type byte + blob
        rec = (
            struct.pack("<IB", len(body), 0) + b"\xab" * 32 + body
        )
        from stellard_tpu.overlay.wire import SegmentData

        sc.on_data("p1", SegmentData(
            seg_id=0, total=len(rec), offset=0, data=rec,
        ))
        assert condemned == ["p1"]
        assert sc.counters.get("garbage_peers") == 1
        # session continues on the OTHER peer (per-peer fallback)
        assert sc.active and sent[-1][0] == "p2"

    def test_fee_garbage_segment_magnitude(self):
        # one condemnation lands the endpoint PAST the warning line
        # (relay/catch-up demotion), a second crosses the DROP line
        assert FEE_GARBAGE_SEGMENT.cost > WARNING_THRESHOLD
        assert 2 * FEE_GARBAGE_SEGMENT.cost >= DROP_THRESHOLD


# -- real TCP: hostile client vs a live overlay ----------------------------


class TestTcpHostileFlood:
    @pytest.fixture()
    def victim(self):
        ports = free_ports_local(1)
        key = KeyPair.from_passphrase("flood-victim")
        ov = make_overlay(key, ports[0])
        ov.start(KeyPair.from_passphrase("masterpassphrase").account_id,
                 close_time=20_000_000)
        yield ov
        ov.stop()

    def test_junk_tx_flood_dropped_and_refused(self, victim):
        """The byzantine matrix on the REAL TCP net: a handshaked
        hostile client flooding junk-tx frames is charged per frame,
        disconnected at the DROP line, and refused readmission."""
        from stellard_tpu.testkit.tcpnet import hostile_flood

        stats = hostile_flood(victim.port, frames=200, mode="junk_tx")
        assert stats["disconnected"], stats
        assert stats["reconnect_refused"], stats
        j = victim.resources.get_json()
        assert j["dropped"] >= 1 and j["disconnects"] >= 1
        assert j["refused"] >= 1
        assert not victim.resources.should_admit(("127.0.0.1", 0))

    def test_charge_peer_unifies_catchup_scoring(self, victim):
        """charge_peer (the SegmentCatchup condemnation seam) demotes a
        live peer out of segment_peers at WARN and disconnects at
        DROP."""
        import socket as _socket

        from stellard_tpu.overlay.tcp import _Peer

        a, b = _socket.socketpair()
        peer = _Peer(a, inbound=True)
        peer.node_public = b"\x02" + b"\x77" * 32
        peer.remote = ("10.1.1.1", 9999)
        with victim._peers_lock:
            victim.peers[peer.node_public] = peer
        assert victim.segment_peers() == [peer.node_public]
        assert victim.charge_peer(
            peer.node_public, FEE_GARBAGE_SEGMENT
        ) == Disposition.WARN
        assert victim.segment_peers() == []  # catch-up privilege gone
        assert victim.charge_peer(
            peer.node_public, FEE_GARBAGE_SEGMENT
        ) == Disposition.DROP
        assert not peer.alive  # relay/admission gone with it
        with victim._peers_lock:
            victim.peers.pop(peer.node_public, None)
        b.close()


def free_ports_local(n: int) -> list[int]:
    import socket as _socket

    socks, ports = [], []
    for _ in range(n):
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def make_overlay(key, port):
    from stellard_tpu.overlay.tcp import TcpOverlay

    t0 = time.monotonic()
    clock = lambda: (time.monotonic() - t0) * 5.0  # noqa: E731
    return TcpOverlay(
        key=key,
        unl={key.public},
        quorum=1,
        port=port,
        peer_addrs=[],
        network_time=lambda: 20_000_000 + int(clock()),
        clock=clock,
        timer_interval=0.2,
        idle_interval=4,
    )
