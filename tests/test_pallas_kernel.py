"""Differential test of the Pallas whole-verify-in-VMEM Ed25519 kernel
(ops/ed25519_pallas.py) against the host library, in interpreter mode on
the CPU backend (the real-chip A/B runs via tools/kernel_sweep.py).

Covers: multi-block grids, tail padding, and cryptographically planted
corruption (R byte, S low byte, public key byte, message swap) — the
same adversarial shapes the XLA kernel's suite pins, so both
implementations are held to the identical contract
(reference: crypto_sign_verify_detached semantics incl. canonical-S,
src/ripple_data/protocol/RippleAddress.cpp:190-252).
"""

import os

import numpy as np
import pytest

# small grid block keeps interpreter cost CI-sized; must be FORCED (not
# setdefault) before the module under test is imported (read once at
# import, jit-static) — an earlier node test's [kernel_tuning]
# application may already have set the 512 production default
os.environ["STELLARD_PALLAS_BLOCK"] = "128"

from stellard_tpu.ops.ed25519_jax import prepare_batch  # noqa: E402
from stellard_tpu.ops.ed25519_pallas import (  # noqa: E402
    verify_kernel_pallas,
)
from stellard_tpu.protocol.keys import KeyPair  # noqa: E402


@pytest.mark.slow  # ~2 min interpret-mode wall clock on the CI box
def test_pallas_verify_differential():
    rng = np.random.default_rng(31)
    keys = [
        KeyPair.from_seed(bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        for _ in range(4)
    ]
    n = 130  # > one 128-lane block: exercises the grid AND tail padding
    msgs = [
        bytes(rng.integers(0, 256, 32, dtype=np.uint8)) for _ in range(n)
    ]
    sigs = [keys[i % 4].sign(msgs[i]) for i in range(n)]
    pubs = [keys[i % 4].public for i in range(n)]
    expect = np.ones(n, bool)

    def corrupt(idx: int, kind: str) -> None:
        if kind == "r":
            s = bytearray(sigs[idx])
            s[5] ^= 0x40
            sigs[idx] = bytes(s)
        elif kind == "s":
            s = bytearray(sigs[idx])
            s[33] ^= 0x01
            sigs[idx] = bytes(s)
        elif kind == "a":
            p = bytearray(pubs[idx])
            p[7] ^= 0x20
            pubs[idx] = bytes(p)
        elif kind == "m":
            msgs[idx] = bytes(32)
        expect[idx] = False

    corrupt(3, "r")
    corrupt(9, "s")
    corrupt(17, "a")
    corrupt(25, "m")
    corrupt(129, "r")  # in the padded tail block

    got = np.asarray(verify_kernel_pallas(**prepare_batch(pubs, msgs, sigs)))
    assert got.shape == (n,)
    assert (got == expect).all(), np.nonzero(got != expect)


def test_pallas_lowers_for_tpu():
    """Cross-platform export must produce TPU MLIR: Mosaic supports a
    subset of primitives (no value dynamic_slice, no scatter, no 1-D
    iota...), and a refactor of the shared fe/pt helpers can silently
    reintroduce one. This catches it on the CPU host — on-chip tunnel
    time is too scarce to spend discovering lowering errors."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax import export

    from stellard_tpu.ops import ed25519_pallas as P

    with P._TRACE_LOCK:
        ktab = P._ensure_const_table()
    blk = P.BLOCK
    args = (
        jax.ShapeDtypeStruct((8, blk), jnp.uint32),
        jax.ShapeDtypeStruct((8, blk), jnp.uint32),
        jax.ShapeDtypeStruct((64, blk), jnp.int32),
        jax.ShapeDtypeStruct((64, blk), jnp.int32),
        jax.ShapeDtypeStruct((1, blk), jnp.int32),
        jax.ShapeDtypeStruct((64, 60, 16), jnp.int32),
        jax.ShapeDtypeStruct(ktab.shape, jnp.int32),
    )
    fn = functools.partial(P._call, interpret=False, nconst=ktab.shape[0])
    with P._TRACE_LOCK:
        try:
            exp = export.export(jax.jit(fn), platforms=["tpu"])(*args)
        except Exception as e:  # noqa: BLE001 — filter a known env gap
            if "Reductions over integers not implemented" in str(e):
                # this image's jax predates Mosaic integer-reduction
                # lowering; the check still guards every OTHER
                # primitive regression on jax versions that have it
                pytest.skip(
                    "installed jax's Mosaic cannot lower integer "
                    "reductions (environment, not a kernel regression)"
                )
            raise
    assert len(exp.mlir_module_serialized) > 0


@pytest.mark.slow  # ~2.5 min interpret-mode wall clock on the CI box
def test_pallas_matches_oracle_on_edge_cases():
    """The adversarial corpus the XLA kernel is pinned by (y=0 / identity
    / invalid-encoding / non-canonical-y pubkeys, bad R, non-canonical S,
    random bit flips) must give byte-identical verdicts from the Pallas
    kernel — both implementations answer to the same Python oracle."""
    from stellard_tpu.ops import ed25519_ref as ref
    from test_crypto_plane import _make_cases  # pytest's module name

    cases = _make_cases(48)
    pubs, msgs, sigs = (list(t) for t in zip(*cases))
    got = np.asarray(verify_kernel_pallas(**prepare_batch(pubs, msgs, sigs)))
    want = np.array([ref.verify(p, m, s) for p, m, s in cases])
    assert np.array_equal(got, want), np.nonzero(got != want)
