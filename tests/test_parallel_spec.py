"""Parallel speculative executor: differential + scheduling seams.

The one property the multi-worker Block-STM plane must never trade away
is the same one delta-replay pinned: a close fed by PARALLEL speculation
produces BYTE-IDENTICAL ledgers (hash + per-tx results) to the serial
path, at every worker count, on exactly the workloads engineered to
stress the validate/abort/retry scheduler — hot-account bursts, fully
dependent sequence chains, one-book offer crossings with cancels, and
tec/held promotion. Manual mode drives SEEDED worker schedules so the
conflict interleavings (stale executions, aborts, retries) replay
deterministically; thread and process modes exercise the real
transports. The close-info counter bundle and the fold-ordering
assertion (the two concurrency satellites) are pinned here too.
"""

from __future__ import annotations

import random
import threading

import pytest

from stellard_tpu.engine.engine import TxParams
from stellard_tpu.engine.specexec import PENDING, SpecExecutor
from stellard_tpu.node.config import Config
from stellard_tpu.node.ledgermaster import LedgerMaster
from stellard_tpu.node.metrics import AtomicCounters
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import (
    sfAmount,
    sfDestination,
    sfLimitAmount,
    sfOfferSequence,
    sfTakerGets,
    sfTakerPays,
)
from stellard_tpu.protocol.stamount import STAmount
from stellard_tpu.protocol.sttx import SerializedTransaction
from stellard_tpu.protocol.ter import TER

MASTER = KeyPair.from_passphrase("masterpassphrase")
USD = b"USD" + b"\x00" * 17
OPEN = TxParams.OPEN_LEDGER | TxParams.RETRY


def build(tx_type, kp, seq, fields, fee=10):
    tx = SerializedTransaction.build(tx_type, kp.account_id, seq, fee, fields)
    tx.sign(kp)
    return tx


def fresh(tx):
    return SerializedTransaction.from_bytes(tx.serialize())


def payment(kp, seq, dest, drops=250_000_000):
    return build(TxType.ttPAYMENT, kp, seq,
                 {sfAmount: STAmount.from_drops(drops), sfDestination: dest})


def run_workload(phases, workers=1, mode="manual", seed=None,
                 max_retries=3, step_prob=0.6):
    """Drive `phases` (one close per phase) through a fresh chain with
    the given executor configuration. In manual mode a SEEDED schedule
    interleaves stale executions between submissions, so the abort/retry
    machinery replays deterministically; thread/process use the real
    transports. -> (hashes, results, delta_stats, executor_json)."""
    lm = LedgerMaster()
    ex = None
    if workers > 1:
        ex = lm.spec_executor = SpecExecutor(
            workers=workers, mode=mode, max_retries=max_retries,
        )
    rng = random.Random(seed)
    lm.start_new_ledger(MASTER.account_id, close_time=1000)
    hashes, results_log = [], []
    try:
        for i, phase in enumerate(phases):
            for tx in phase:
                ter, ok = lm.do_transaction(fresh(tx), OPEN)
                if ter == TER.terPRE_SEQ:
                    lm.add_held_transaction(fresh(tx))
                if ex is not None and mode == "manual" \
                        and rng.random() < step_prob:
                    spec = getattr(lm.current, "_spec_state", None)
                    session = getattr(spec, "_exec_session", None)
                    if session is not None:
                        cand = [t.index for t in session.tasks
                                if t.state == PENDING]
                        if cand:
                            # execute a random pending task — possibly
                            # far ahead of the commit frontier, i.e. a
                            # deliberately stale schedule
                            ex.step(session, rng.choice(cand))
                            ex.pump(session)
            closed, results = lm.close_and_advance(2000 + i * 30, 30)
            hashes.append(closed.hash())
            results_log.append(sorted(
                (txid.hex(), int(ter)) for txid, ter in results.items()
            ))
        return (hashes, results_log, dict(lm.delta_stats),
                ex.get_json() if ex is not None else None)
    finally:
        if ex is not None:
            ex.stop()


def assert_identical(phases, configs, seed=11):
    """Serial run vs each (workers, mode) config: byte identity is the
    contract. Returns {label: executor_json} for counter assertions."""
    h0, r0, _stats, _ = run_workload(phases, workers=1)
    out = {}
    for workers, mode in configs:
        h, r, _s, j = run_workload(phases, workers=workers, mode=mode,
                                   seed=seed)
        assert h == h0, (
            f"workers={workers} mode={mode} diverged from serial"
        )
        assert r == r0, (
            f"workers={workers} mode={mode} results diverged from serial"
        )
        out[f"{mode}{workers}"] = j
    return out


def hot_account_burst():
    """Independent senders hammering ONE hot destination + the master's
    own dependent chain — the canonical conflict seam."""
    senders = [KeyPair.from_passphrase(f"ps-s{i}") for i in range(6)]
    hot = KeyPair.from_passphrase("ps-hot").account_id
    fund = [payment(MASTER, 1 + i, s.account_id, 2_000_000_000)
            for i, s in enumerate(senders)]
    work = []
    for rnd in range(3):
        for s in senders:
            work.append(payment(s, 1 + rnd, hot, 210_000_000))
    return [fund, work]


def dependent_chain():
    """One account's long sequence chain: every speculation depends on
    its predecessor, the worst case for optimistic execution."""
    dests = [KeyPair.from_passphrase(f"ps-d{i}").account_id
             for i in range(4)]
    return [
        [payment(MASTER, 1 + i, dests[i % 4]) for i in range(24)],
        [payment(MASTER, 25 + i, dests[i % 4]) for i in range(12)],
    ]


def offer_book():
    """Asks + crossing bids + cancels on one USD book: succ-walk range
    reads and entry deletions under the parallel scheduler."""
    gateway = KeyPair.from_passphrase("ps-gw")
    traders = [KeyPair.from_passphrase(f"ps-t{i}") for i in range(4)]
    fund = [payment(MASTER, 1 + i, who.account_id, 1_500_000_000)
            for i, who in enumerate([gateway] + traders)]
    trust = [
        build(TxType.ttTRUST_SET, t, 1,
              {sfLimitAmount: STAmount.from_iou(
                  USD, gateway.account_id, 10**9, 0)})
        for t in traders
    ]
    seqs = {gateway.account_id: 1}
    for t in traders:
        seqs[t.account_id] = 2
    work, live = [], []
    for i in range(28):
        if i % 7 == 6 and live:
            kp, oseq = live.pop(0)
            tx = build(TxType.ttOFFER_CANCEL, kp, seqs[kp.account_id],
                       {sfOfferSequence: oseq})
        elif i % 2 == 0:
            tx = build(
                TxType.ttOFFER_CREATE, gateway, seqs[gateway.account_id],
                {sfTakerPays: STAmount.from_drops((50 + i % 15) * 1_000_000),
                 sfTakerGets: STAmount.from_iou(
                     USD, gateway.account_id, 100, 0)},
            )
            live.append((gateway, seqs[gateway.account_id]))
        else:
            kp = traders[i % len(traders)]
            tx = build(
                TxType.ttOFFER_CREATE, kp, seqs[kp.account_id],
                {sfTakerPays: STAmount.from_iou(
                    USD, gateway.account_id, 100, 0),
                 sfTakerGets: STAmount.from_drops(
                     (40 + i % 20) * 1_000_000)},
            )
            live.append((kp, seqs[kp.account_id]))
        seqs[tx.account] = tx.sequence + 1
        work.append(tx)
    return [fund, trust, work]


def tec_and_promotion():
    """A below-reserve tec claim plus a sequence-gap hold promoted on
    the next close — final-pass timing under the parallel plane."""
    d = [KeyPair.from_passphrase(f"ps-h{i}").account_id for i in range(3)]
    return [
        [
            payment(MASTER, 1, d[0]),
            payment(MASTER, 2, d[1], drops=1_000_000),  # below reserve
            payment(MASTER, 3, d[2]),
            payment(MASTER, 5, d[0]),  # gap -> held
            payment(MASTER, 4, d[1]),
        ],
        [],
    ]


class TestByteIdentity:
    """Parallel-vs-serial byte identity at workers 2 and 4, over every
    adversarial seam, with seeded manual schedules (deterministic
    conflict interleavings) and the real thread transport."""

    CONFIGS = [(2, "manual"), (4, "manual"), (2, "thread"), (4, "thread")]

    def test_hot_account_burst(self):
        js = assert_identical(hot_account_burst(), self.CONFIGS)
        # the seeded stale schedules must actually exercise the abort
        # path somewhere, or this suite proves nothing
        assert any(j["validation_aborts"] > 0 or j["serial_fallbacks"] > 0
                   for j in js.values())

    def test_dependent_sequence_chain(self):
        js = assert_identical(dependent_chain(), self.CONFIGS, seed=23)
        for j in js.values():
            assert j["committed"] > 0

    def test_offer_crossings_and_cancels(self):
        assert_identical(offer_book(), self.CONFIGS, seed=5)

    def test_tec_claim_and_held_promotion(self):
        assert_identical(tec_and_promotion(), self.CONFIGS, seed=7)

    def test_seeded_schedules_replay_identically(self):
        """Same seed -> the same manual schedule -> identical counter
        trajectories: the interleaving is genuinely deterministic."""
        phases = hot_account_burst()
        _h1, _r1, _s1, j1 = run_workload(phases, workers=4, seed=99)
        _h2, _r2, _s2, j2 = run_workload(phases, workers=4, seed=99)
        for key in ("dispatched", "committed", "retries",
                    "validation_aborts", "serial_fallbacks"):
            assert j1[key] == j2[key], key

    def test_randomized_differential(self):
        """Seeded random mixed workloads x seeded random schedules."""
        for seed in (1, 2, 3):
            rng = random.Random(seed * 1000)
            accounts = [KeyPair.from_passphrase(f"pr-{seed}-{i}")
                        for i in range(5)]
            fund = [payment(MASTER, 1 + i, a.account_id, 3_000_000_000)
                    for i, a in enumerate(accounts)]
            seqs = {a.account_id: 1 for a in accounts}
            work = []
            for _ in range(30):
                kp = rng.choice(accounts)
                dest = rng.choice(
                    [a.account_id for a in accounts if a is not kp]
                )
                work.append(payment(kp, seqs[kp.account_id], dest,
                                    rng.choice([210_000_000, 1_000_000])))
                seqs[kp.account_id] += 1
            assert_identical([fund, work], [(2, "manual"), (4, "manual")],
                             seed=seed)


class TestProcessTransport:
    def test_process_workers_byte_identity(self):
        """The fork transport end to end: replica snapshots, read
        through the pipe, piggybacked deltas, epoch provenance."""
        phases = hot_account_burst()
        h0, r0, _s, _ = run_workload(phases, workers=1)
        h, r, _s2, j = run_workload(phases, workers=2, mode="process")
        assert h == h0 and r == r0
        assert j["worker_deaths"] == 0
        assert j["exec_errors"] == 0
        assert j["committed"] == j["dispatched"]

    def test_dead_pool_falls_back_serial(self):
        """Killing every worker mid-window must complete the window
        serially (records intact, close byte-identical) — not hang."""
        phases = dependent_chain()
        h0, r0, _s, _ = run_workload(phases, workers=1)
        lm = LedgerMaster()
        ex = lm.spec_executor = SpecExecutor(workers=2, mode="process",
                                             drain_timeout_s=2.0)
        lm.start_new_ledger(MASTER.account_id, close_time=1000)
        try:
            hashes, results_log = [], []
            killed = False
            for i, phase in enumerate(phases):
                for n, tx in enumerate(phase):
                    lm.do_transaction(fresh(tx), OPEN)
                    if not killed and n == len(phase) // 2:
                        killed = True
                        for w in ex._procs:
                            w.proc.terminate()
                            w.proc.join(timeout=5)
                closed, results = lm.close_and_advance(2000 + i * 30, 30)
                hashes.append(closed.hash())
                results_log.append(sorted(
                    (txid.hex(), int(t)) for txid, t in results.items()
                ))
            assert hashes == h0 and results_log == r0
        finally:
            ex.stop()

    def test_broken_pipe_mid_assign_reassigns_to_survivor(self):
        """A cmd-pipe send failure discovered DURING chunk assignment
        must requeue the casualty's chunk and hand it to the surviving
        worker. The old failure handling tail-called _assign_procs from
        _fail_worker while _assign_lock (non-reentrant) was still held,
        wedging the committer and leaving every later close to the
        forced-serial drain."""
        class _BrokenSend:
            # holds the real Connection open so the worker never sees
            # EOF — the ONLY discovery path is the failing send inside
            # the locked assignment pass
            def __init__(self, real):
                self._real = real

            def send(self, msg):
                raise OSError("test: broken pipe")

        phases = hot_account_burst()
        h0, r0, _s, _ = run_workload(phases, workers=1)
        lm = LedgerMaster()
        ex = lm.spec_executor = SpecExecutor(workers=2, mode="process",
                                             drain_timeout_s=10.0)
        lm.start_new_ledger(MASTER.account_id, close_time=1000)
        try:
            hashes, results_log = [], []
            broken = False
            for i, phase in enumerate(phases):
                for n, tx in enumerate(phase):
                    lm.do_transaction(fresh(tx), OPEN)
                    if not broken and i == 1 and n == len(phase) // 2:
                        broken = True
                        ex._procs[0].cmd = _BrokenSend(ex._procs[0].cmd)
                closed, results = lm.close_and_advance(2000 + i * 30, 30)
                hashes.append(closed.hash())
                results_log.append(sorted(
                    (txid.hex(), int(t)) for txid, t in results.items()
                ))
            assert hashes == h0 and results_log == r0
            # the failing send was discovered (worker marked dead,
            # whichever of the assignment / read-reply paths hit the
            # broken pipe first) and the window completed through the
            # survivor — not the drain's forced-serial completion
            j = ex.get_json()
            assert not ex._procs[0].alive
            assert j["worker_deaths"] == 1
            assert j["drains_forced"] == 0
        finally:
            # unblock the worker's recv so stop() doesn't wait out the
            # join timeout on a process we wedged on purpose
            cmd = ex._procs[0].cmd
            if isinstance(cmd, _BrokenSend):
                cmd._real.close()
            ex.stop()


class TestRetryMachinery:
    def test_retry_exhaustion_serial_fallback(self):
        """max_retries=0: every stale execution goes straight to the
        committing thread's serial in-order apply — and the ledger is
        still byte-identical."""
        phases = dependent_chain()
        h0, r0, _s, _ = run_workload(phases, workers=1)
        h, r, _s2, j = run_workload(phases, workers=4, mode="manual",
                                    seed=3, max_retries=0)
        assert h == h0 and r == r0
        assert j["serial_fallbacks"] > 0
        assert j["retries"] == 0

    def test_bounded_retries_then_fallback_counters(self):
        """With retries allowed, aborted executions retry (counted) and
        the abort/retry/fallback counter surfaces stay consistent."""
        phases = dependent_chain()
        _h, _r, _s, j = run_workload(phases, workers=4, mode="manual",
                                     seed=3, max_retries=2)
        # every abort is either re-queued (retries) or, once attempts
        # are exhausted, applied by the serial in-order fallback — and
        # retries only ever come from aborts (worker loss re-pends
        # without counting a retry)
        assert j["retries"] <= j["validation_aborts"]
        assert j["validation_aborts"] <= j["retries"] + j["serial_fallbacks"]
        assert j["validation_aborts"] > 0  # the seed must exercise aborts
        assert j["dispatched"] == j["committed"] + j["no_records"]

    def test_drain_completes_unexecuted_window(self):
        """Dispatched-but-never-executed tasks (a wedged pool) complete
        serially at the close — the drain's forced completion."""
        phases = [[payment(MASTER, 1 + i,
                           KeyPair.from_passphrase("ps-dr").account_id)
                   for i in range(6)]]
        h0, r0, _s, _ = run_workload(phases, workers=1)
        # manual mode with step_prob=0: nothing executes until the close
        h, r, _s2, j = run_workload(phases, workers=2, mode="manual",
                                    seed=1, step_prob=0.0)
        assert h == h0 and r == r0
        assert j["drains_forced"] >= 1
        assert j["serial_fallbacks"] == 6


class TestKillSwitch:
    def test_workers1_keeps_serial_inline_path(self):
        """workers=1 (the default) must not even create a session —
        speculation records appear synchronously at submit, exactly the
        pre-parallel behavior."""
        lm = LedgerMaster()
        lm.spec_executor = SpecExecutor(workers=1)
        assert not lm.spec_executor.active
        lm.start_new_ledger(MASTER.account_id, close_time=1000)
        dest = KeyPair.from_passphrase("ps-k").account_id
        ter, ok = lm.do_transaction(fresh(payment(MASTER, 1, dest)), OPEN)
        assert ok, ter
        spec = lm.current._spec_state
        assert getattr(spec, "_exec_session", None) is None
        assert len(spec.records) == 1  # recorded inline, synchronously

    def test_stopped_executor_falls_back_inline(self):
        """dispatch() refusing (executor stopped) must route the tx
        through the serial inline path, not lose the speculation."""
        lm = LedgerMaster()
        ex = lm.spec_executor = SpecExecutor(workers=2, mode="manual")
        lm.start_new_ledger(MASTER.account_id, close_time=1000)
        dest = KeyPair.from_passphrase("ps-k2").account_id
        ex.stop()
        ter, ok = lm.do_transaction(fresh(payment(MASTER, 1, dest)), OPEN)
        assert ok, ter
        assert len(lm.current._spec_state.records) == 1

    def test_committer_failure_degrades_to_serial(self):
        """A crashed commit machinery (_failed) must refuse new
        dispatches, complete the open window serially, and leave the
        node on the inline path — closes keep working, nothing hangs."""
        lm = LedgerMaster()
        ex = lm.spec_executor = SpecExecutor(workers=2, mode="manual")
        lm.start_new_ledger(MASTER.account_id, close_time=1000)
        dest = KeyPair.from_passphrase("ps-k3").account_id
        ter, ok = lm.do_transaction(fresh(payment(MASTER, 1, dest)), OPEN)
        assert ok, ter
        ex._failed = True  # what the committer's crash handler sets
        ter, ok = lm.do_transaction(fresh(payment(MASTER, 2, dest)), OPEN)
        assert ok, ter
        spec = lm.current._spec_state
        assert getattr(spec, "_exec_session", None) is None  # window ended
        assert len(spec.records) == 2  # serial completion + inline path
        closed, results = lm.close_and_advance(2000, 30)
        assert len(results) == 2
        assert all(int(t) == 0 for t in results.values())

    def test_failed_executor_does_not_churn_windows(self):
        """Once the commit machinery has crashed (_failed), the submit
        path must go straight to the inline serial speculation — not
        open a fresh window (snapshot broadcast, windows bump, drain,
        teardown) per transaction on its way there."""
        lm = LedgerMaster()
        ex = lm.spec_executor = SpecExecutor(workers=2, mode="manual")
        lm.start_new_ledger(MASTER.account_id, close_time=1000)
        dest = KeyPair.from_passphrase("ps-k4").account_id
        ex._failed = True  # what the committer's crash handler sets
        try:
            for i in range(5):
                ter, ok = lm.do_transaction(
                    fresh(payment(MASTER, 1 + i, dest)), OPEN
                )
                assert ok, ter
            assert ex.get_json()["windows"] == 0
            assert len(lm.current._spec_state.records) == 5
        finally:
            ex.stop()

    def test_config_stanza(self):
        cfg = Config.from_ini(
            "[spec]\nworkers=4\nmode=thread\nmax_retries=5\n"
            "drain_timeout_s=2.5\n"
        )
        assert cfg.spec_workers == 4
        assert cfg.spec_mode == "thread"
        assert cfg.spec_max_retries == 5
        assert cfg.spec_drain_timeout_s == 2.5
        assert Config().spec_workers == 1  # default: serial, off
        with pytest.raises(ValueError):
            Config.from_ini("[spec]\nmode=warp\n")


class TestFoldOrdering:
    def test_out_of_order_fold_fails_loudly(self):
        """The pre-seal building tree's ordering assertion (the
        concurrency satellite): an out-of-order fold is a scheduler
        commit-order bug and must raise, not corrupt the tree."""
        lm = LedgerMaster()
        lm.start_new_ledger(MASTER.account_id, close_time=1000)
        dests = [KeyPair.from_passphrase(f"ps-f{i}").account_id
                 for i in range(2)]
        for i in range(2):
            lm.do_transaction(fresh(payment(MASTER, 1 + i, dests[i])), OPEN)
        spec = lm.current._spec_state
        recs = sorted(spec.records.values(), key=lambda r: r.index)
        assert [r.index for r in recs] == [0, 1]
        # both already folded by the inline path; replaying the FIRST
        # one now arrives below the fold watermark
        with pytest.raises(AssertionError, match="out of order"):
            spec.fold_building(recs[0])


class TestCloseInfoCounters:
    def test_delta_stats_is_atomic_bundle(self):
        lm = LedgerMaster()
        assert isinstance(lm.delta_stats, AtomicCounters)

    def test_concurrent_hammer(self):
        """The satellite's pin: close-path, promotion-job and executor
        threads all bump close-info counters concurrently — the bundle
        must lose nothing and multi-key updates must stay atomic."""
        c = AtomicCounters("closes", "spliced", "fallback", "invalidated")
        N, THREADS = 2000, 8
        torn = []

        def writer():
            for _ in range(N):
                c.add_many(closes=1, spliced=3, fallback=1, invalidated=2)

        def reader():
            for _ in range(N):
                snap = c.snapshot()
                # multi-key atomicity: within one snapshot the fixed
                # ratios must hold — a torn add_many would break them
                if snap["spliced"] != 3 * snap["closes"] or \
                        snap["fallback"] != snap["closes"]:
                    torn.append(snap)

        threads = [threading.Thread(target=writer) for _ in range(THREADS)]
        threads += [threading.Thread(target=reader) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not torn, f"torn snapshot observed: {torn[:1]}"
        snap = c.snapshot()
        assert snap["closes"] == N * THREADS
        assert snap["spliced"] == 3 * N * THREADS
        assert snap["invalidated"] == 2 * N * THREADS

    def test_ledgermaster_concurrent_note(self):
        """Concurrent _note_delta_stats-shaped updates through the real
        LedgerMaster surface sum exactly."""
        lm = LedgerMaster()

        def bump():
            for _ in range(500):
                lm.delta_stats.add_many(closes=1, spliced=2, fallback=0,
                                        invalidated=1)

        threads = [threading.Thread(target=bump) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert lm.delta_stats["closes"] == 3000
        assert lm.delta_stats["spliced"] == 6000


class TestCounterSurfaces:
    def test_executor_json_in_delta_replay_json(self):
        lm = LedgerMaster()
        lm.spec_executor = SpecExecutor(workers=2, mode="manual")
        try:
            out = lm.delta_replay_json()
            assert out["spec"]["workers"] == 2
            assert out["spec"]["active"] is True
            for key in ("dispatched", "committed", "retries",
                        "validation_aborts", "serial_fallbacks"):
                assert key in out["spec"]
        finally:
            lm.spec_executor.stop()

    def test_node_counts_expose_spec_block(self):
        from stellard_tpu.node.node import Node
        from stellard_tpu.rpc.handlers import Context, Role, dispatch

        n = Node(Config(standalone=True, signature_backend="cpu",
                        spec_workers=2, spec_mode="thread")).setup()
        try:
            dest = KeyPair.from_passphrase("ps-rpc").account_id
            for i in range(5):
                ter, ok = n.submit(fresh(payment(MASTER, 1 + i, dest)))
                assert ok, ter
            n.close_ledger()
            state = dispatch(
                Context(n, {}, Role.ADMIN), "server_state"
            )["state"]
            assert state["spec"]["workers"] == 2
            assert state["spec"]["dispatched"] == 5
            counts = dispatch(Context(n, {}, Role.ADMIN), "get_counts")
            assert counts["spec"]["committed"] == 5
            assert state["delta_replay"]["spliced"] == 5
        finally:
            n.stop()
