"""Table-driven multi-hop payment corpus.

The reference validates pathfinding/execution against a declarative
scenario table (test/path-tests.json driven by path-test.js / the
new-path-test.coffee harness). This file plays the same role with our
own scenario schema: each case declares a ledger (accounts, trust lines,
IOU balances, offers), then asserts pathfinder alternatives and/or
payment-execution outcomes (delivered amount, spent amount, TER).

Coverage mirrors the reference table's semantic groups:
STR->IOU and IOU->STR via books, same-currency issuer chains,
cross-currency via books, bridged IOU->STR->IOU, partial payments,
sendmax ceilings, dry paths, and no-ripple blocking.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from fractions import Fraction
from typing import Optional

import pytest

from stellard_tpu.engine import views
from stellard_tpu.engine.engine import TransactionEngine, TxParams
from stellard_tpu.paths import OrderBookDB, find_paths, flow
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import (
    sfAmount,
    sfDestination,
    sfFlags,
    sfPaths,
    sfSendMax,
)
from stellard_tpu.protocol.stamount import (
    ACCOUNT_ZERO,
    STAmount,
    currency_from_iso,
)
from stellard_tpu.protocol.stobject import PathElement
from stellard_tpu.protocol.ter import TER
from stellard_tpu.state.entryset import LedgerEntrySet
from stellard_tpu.state.ledger import Ledger

XRP = 1_000_000
ROOT = KeyPair.from_passphrase("masterpassphrase")
tfPartialPayment = 0x00020000

_KEYS: dict[str, KeyPair] = {}


def K(name: str) -> KeyPair:
    if name not in _KEYS:
        _KEYS[name] = KeyPair.from_passphrase(f"corpus-{name}")
    return _KEYS[name]


def amt(spec: str) -> STAmount:
    """'10.0' = STR; '5/USD/G1' = IOU. Mirrors the reference table's
    amount notation."""
    if "/" in spec:
        value, cur, issuer = spec.split("/")
        f = Fraction(value)
        # scale to an integer mantissa
        scale = 0
        while f.denominator != 1:
            f *= 10
            scale -= 1
        return STAmount.from_iou(
            currency_from_iso(cur), K(issuer).account_id, int(f), scale
        )
    return STAmount.from_drops(int(Fraction(spec) * XRP))


@dataclass
class Scenario:
    """Declarative ledger: balances fund STR; trusts open lines;
    iou pays issue IOUs; offers rest in books; transfer_rates set
    gateway fees (reference: testutils.create_accounts/credit_limits/
    payments + account_set().transfer_rate())."""

    accounts: dict[str, str]  # name -> STR balance ('1000.0')
    trusts: list[str] = field(default_factory=list)  # 'A1:500/USD/G1'
    ious: list[str] = field(default_factory=list)  # 'A1:100/USD/G1' (G1 pays A1)
    offers: list[tuple[str, str, str]] = field(default_factory=list)
    # (owner, taker_pays, taker_gets)
    transfer_rates: dict[str, float] = field(default_factory=dict)
    # issuer name -> rate (1.1 = 10% gateway fee)

    def build(self) -> Ledger:
        ledger = Ledger.genesis(ROOT.account_id)
        ledger.parent_close_time = 700_000_000
        engine = TransactionEngine(ledger)
        seqs: dict[bytes, int] = {ROOT.account_id: 1}

        def apply(key: KeyPair, tx_type: TxType, fields: dict):
            from stellard_tpu.protocol.sttx import SerializedTransaction

            seq = seqs.setdefault(key.account_id, 1)
            tx = SerializedTransaction.build(tx_type, key.account_id, seq, 10)
            for f, v in fields.items():
                tx.obj[f] = v
            tx.sign(key)
            ter, did = engine.apply_transaction(tx, TxParams.NONE)
            assert ter == TER.tesSUCCESS, f"setup {tx_type.name}: {ter!r}"
            if did:
                seqs[key.account_id] = seq + 1

        for name, bal in self.accounts.items():
            apply(ROOT, TxType.ttPAYMENT, {
                sfDestination: K(name).account_id, sfAmount: amt(bal),
            })
        for name, rate in self.transfer_rates.items():
            from stellard_tpu.protocol.sfields import sfTransferRate

            apply(K(name), TxType.ttACCOUNT_SET, {
                sfTransferRate: int(rate * 1_000_000_000),
            })
        for t in self.trusts:
            holder, limit = t.split(":")
            from stellard_tpu.protocol.sfields import sfLimitAmount

            apply(K(holder), TxType.ttTRUST_SET, {sfLimitAmount: amt(limit)})
        for i in self.ious:
            holder, amount = i.split(":")
            a = amt(amount)
            issuer_name = [n for n in _KEYS if K(n).account_id == a.issuer][0]
            apply(K(issuer_name), TxType.ttPAYMENT, {
                sfDestination: K(holder).account_id, sfAmount: a,
            })
        for owner, pays, gets in self.offers:
            from stellard_tpu.protocol.sfields import sfTakerGets, sfTakerPays

            apply(K(owner), TxType.ttOFFER_CREATE, {
                sfTakerPays: amt(pays), sfTakerGets: amt(gets),
            })
        return ledger


def pay_via_paths(
    ledger: Ledger,
    src: str,
    dst: str,
    deliver: str,
    send_max: Optional[str] = None,
    partial: bool = False,
    use_found_paths: bool = True,
) -> tuple[TER, STAmount, STAmount]:
    """Execute a path payment through the flow engine, using pathfinder
    alternatives like a client would (find -> attach paths -> submit)."""
    dst_amount = amt(deliver)
    smax = amt(send_max) if send_max else dst_amount
    paths: list[list[PathElement]] = []
    if use_found_paths:
        alts = find_paths(
            ledger, K(src).account_id, K(dst).account_id, dst_amount,
            send_max=smax,
        )
        for alt in alts:
            paths.extend(alt["paths"])
    if not paths:
        paths = [[]]  # default path
    les = LedgerEntrySet(ledger)
    ter, spent, delivered = flow(
        les,
        K(src).account_id,
        K(dst).account_id,
        dst_amount,
        smax,
        paths,
        partial,
        ledger.parent_close_time,
    )
    if ter == TER.tesSUCCESS:
        les.apply()
    return ter, spent, delivered


def text(a: STAmount) -> str:
    return a.value_text()


# --------------------------------------------------------------------------
# the corpus


class TestCorpusSameCurrency:
    def test_issuer_hop(self):
        """A1 pays A2 USD through their shared gateway (T2-B shape)."""
        led = Scenario(
            accounts={"A1": "1000.0", "A2": "1000.0", "G1": "1000.0"},
            trusts=["A1:500/USD/G1", "A2:500/USD/G1"],
            ious=["A1:100/USD/G1"],
        ).build()
        ter, spent, got = pay_via_paths(led, "A1", "A2", "30/USD/G1")
        assert ter == TER.tesSUCCESS and text(got) == "30"
        les = LedgerEntrySet(led)
        assert views.ripple_balance(
            les, K("A2").account_id, K("G1").account_id, currency_from_iso("USD")
        ).value_text() == "30"

    def test_two_gateway_chain_dry_without_liquidity(self):
        """A1 holds USD/G1; A3 trusts only USD/G2 — no connector, dry
        (T1-style 'no alternative' case, executed)."""
        led = Scenario(
            accounts={"A1": "1000.0", "A3": "1000.0", "G1": "1000.0",
                      "G2": "1000.0"},
            trusts=["A1:500/USD/G1", "A3:500/USD/G2"],
            ious=["A1:100/USD/G1"],
        ).build()
        alts = find_paths(
            led, K("A1").account_id, K("A3").account_id, amt("10/USD/G2"),
            send_max=amt("20/USD/G1"),
        )
        assert alts == []
        ter, _s, _g = pay_via_paths(
            led, "A1", "A3", "10/USD/G2", send_max="20/USD/G1"
        )
        assert ter in (TER.tecPATH_DRY, TER.tecPATH_PARTIAL)

    def test_market_maker_connects_gateways(self):
        """M1 trusts both gateways: A1's USD/G1 reaches A3's USD/G2
        through M1's lines (T5 'ripple through' shape)."""
        led = Scenario(
            accounts={"A1": "1000.0", "A3": "1000.0", "G1": "1000.0",
                      "G2": "1000.0", "M1": "1000.0"},
            trusts=["A1:500/USD/G1", "A3:500/USD/G2",
                    "M1:1000/USD/G1", "M1:1000/USD/G2"],
            ious=["A1:100/USD/G1", "M1:100/USD/G2"],
        ).build()
        ter, spent, got = pay_via_paths(
            led, "A1", "A3", "25/USD/G2", send_max="40/USD/G1"
        )
        assert ter == TER.tesSUCCESS and text(got) == "25"
        # M1 was the connector: gained G1 IOUs, spent G2 IOUs
        les = LedgerEntrySet(led)
        assert views.ripple_balance(
            les, K("M1").account_id, K("G1").account_id, currency_from_iso("USD")
        ).value_text() == "25"


class TestCorpusCrossCurrency:
    def test_str_to_iou_via_book(self):
        """STR -> USD through a resting offer (T3 shape)."""
        led = Scenario(
            accounts={"A1": "1000.0", "A2": "1000.0", "G3": "1000.0",
                      "M1": "11000.0"},
            trusts=["A1:1000/ABC/G3", "A2:1000/ABC/G3", "M1:1000/ABC/G3"],
            ious=["M1:500/ABC/G3"],
            offers=[("M1", "100.0", "100/ABC/G3")],  # sells ABC for STR
        ).build()
        ter, spent, got = pay_via_paths(
            led, "A1", "A2", "50/ABC/G3", send_max="60.0"
        )
        assert ter == TER.tesSUCCESS
        assert text(got) == "50"
        assert spent.is_native and spent.drops() == 50 * XRP

    def test_iou_to_str_via_book(self):
        """USD -> STR through the mirrored book (T4 shape)."""
        led = Scenario(
            accounts={"A1": "1000.0", "A2": "1000.0", "G3": "1000.0",
                      "M1": "11000.0"},
            trusts=["A1:1000/ABC/G3", "M1:1000/ABC/G3"],
            ious=["A1:200/ABC/G3"],
            offers=[("M1", "100/ABC/G3", "1000.0")],  # sells STR for ABC
        ).build()
        before = led.account_root(K("A2").account_id)
        ter, spent, got = pay_via_paths(
            led, "A1", "A2", "100.0", send_max="20/ABC/G3"
        )
        assert ter == TER.tesSUCCESS
        assert got.is_native and got.drops() == 100 * XRP
        assert text(spent) == "10"  # 10 ABC at 10 STR/ABC

    def test_iou_to_iou_bridged_through_str(self):
        """USD -> STR -> EUR across two books when no direct book exists
        (the bridged shape; reference left its transactor unimplemented)."""
        led = Scenario(
            accounts={"A1": "1000.0", "A2": "1000.0", "G1": "1000.0",
                      "G2": "1000.0", "M1": "11000.0", "M2": "11000.0"},
            trusts=["A1:1000/USD/G1", "A2:1000/EUR/G2",
                    "M1:1000/USD/G1", "M2:1000/EUR/G2"],
            ious=["A1:200/USD/G1", "M2:500/EUR/G2"],
            offers=[
                ("M1", "100/USD/G1", "1000.0"),  # sells STR for USD
                ("M2", "1000.0", "100/EUR/G2"),  # sells EUR for STR
            ],
        ).build()
        ter, spent, got = pay_via_paths(
            led, "A1", "A2", "40/EUR/G2", send_max="80/USD/G1"
        )
        assert ter == TER.tesSUCCESS and text(got) == "40"
        # 40 EUR needs 400 STR needs 40 USD at these 1:10 prices
        assert text(spent) == "40"

    def test_partial_payment_delivers_liquidity_bound(self):
        """tfPartialPayment semantics: book only covers part of the
        target; partial succeeds with what it could move."""
        led = Scenario(
            accounts={"A1": "1000.0", "A2": "1000.0", "G3": "1000.0",
                      "M1": "11000.0"},
            trusts=["A1:1000/ABC/G3", "A2:1000/ABC/G3", "M1:1000/ABC/G3"],
            ious=["M1:30/ABC/G3"],
            offers=[("M1", "30.0", "30/ABC/G3")],
        ).build()
        # non-partial: fails (cannot deliver 50)
        ter, _s, _g = pay_via_paths(led, "A1", "A2", "50/ABC/G3",
                                    send_max="60.0")
        assert ter == TER.tecPATH_PARTIAL
        # partial: delivers the 30 that exists
        ter, spent, got = pay_via_paths(
            led, "A1", "A2", "50/ABC/G3", send_max="60.0", partial=True
        )
        assert ter == TER.tesSUCCESS and text(got) == "30"

    def test_sendmax_bounds_spend(self):
        """sendMax caps the source side even when more liquidity exists."""
        led = Scenario(
            accounts={"A1": "1000.0", "A2": "1000.0", "G3": "1000.0",
                      "M1": "11000.0"},
            trusts=["A1:1000/ABC/G3", "A2:1000/ABC/G3", "M1:1000/ABC/G3"],
            ious=["M1:500/ABC/G3"],
            offers=[("M1", "100.0", "100/ABC/G3")],
        ).build()
        ter, _s, _g = pay_via_paths(led, "A1", "A2", "50/ABC/G3",
                                    send_max="20.0")
        assert ter in (TER.tecPATH_PARTIAL, TER.tecPATH_DRY)
        ter, spent, got = pay_via_paths(
            led, "A1", "A2", "50/ABC/G3", send_max="20.0", partial=True
        )
        assert ter == TER.tesSUCCESS
        assert text(got) == "20"  # 1:1 book, 20 STR -> 20 ABC
        assert spent.drops() <= 20 * XRP


class TestCorpusPathfinder:
    def test_alternatives_ranked_by_quality(self):
        """Two books at different prices: the pathfinder's best
        alternative uses the cheaper source amount."""
        led = Scenario(
            accounts={"A1": "1000.0", "A2": "1000.0", "G3": "1000.0",
                      "M1": "11000.0", "M2": "11000.0"},
            trusts=["A1:1000/ABC/G3", "A2:1000/ABC/G3",
                    "M1:1000/ABC/G3", "M2:1000/ABC/G3"],
            ious=["M1:500/ABC/G3", "M2:500/ABC/G3"],
            offers=[
                ("M1", "200.0", "100/ABC/G3"),  # 2 STR per ABC
                ("M2", "100.0", "100/ABC/G3"),  # 1 STR per ABC (better)
            ],
        ).build()
        alts = find_paths(
            led, K("A1").account_id, K("A2").account_id, amt("50/ABC/G3"),
            send_max=amt("500.0"),
        )
        assert alts, "no alternatives found"
        best = alts[0]
        assert best["source_amount"].is_native
        # the better book covers all 50 at 1:1
        assert best["source_amount"].drops() == 50 * XRP


class TestCorpusReversePass:
    """The reverse pass must shrink upstream requests to downstream
    capacity (reference: calcNodeAccountRev clamping), so a strand never
    over-spends through a book for value a later line cannot carry."""

    def test_downstream_line_cap_limits_issuer_chain_spend(self):
        """A1 -> G3 -> A2 same-currency ripple where A2's trust for G3
        only admits 30: a partial payment of 50 delivers exactly 30 and
        SPENDS exactly 30 — the clamp shows up in the spent amount."""
        led = Scenario(
            accounts={"A1": "1000.0", "A2": "1000.0", "G3": "1000.0"},
            trusts=["A1:1000/ABC/G3", "A2:30/ABC/G3"],
            ious=["A1:500/ABC/G3"],
        ).build()
        ter, spent, got = pay_via_paths(
            led, "A1", "A2", "50/ABC/G3", partial=True
        )
        assert ter == TER.tesSUCCESS
        assert text(got) == "30"
        assert text(spent) == "30"

    def test_rev_clamp_stops_book_overbuy(self):
        """Cross-currency strand STR -> book -> ABC -> dst, where dst's
        trust line admits only 10 ABC: the book must only be asked for
        10, so the partial payment spends ~10 STR (1:1 book), not the
        full 100-ABC budget."""
        led = Scenario(
            accounts={"A1": "1000.0", "A2": "1000.0", "G3": "1000.0",
                      "M1": "11000.0"},
            trusts=["A2:10/ABC/G3", "M1:1000/ABC/G3"],
            ious=["M1:500/ABC/G3"],
            offers=[("M1", "100.0", "100/ABC/G3")],  # 1 STR per ABC
        ).build()
        ter, spent, got = pay_via_paths(
            led, "A1", "A2", "100/ABC/G3", send_max="500.0", partial=True
        )
        assert ter == TER.tesSUCCESS
        assert text(got) == "10"
        assert spent.is_native
        # 10 ABC at 1 STR each (+ issuer transfer at par): ~10 STR, and
        # certainly nowhere near the 100 the unclamped strand would buy
        assert spent.drops() <= 11 * XRP, spent.drops()

    def test_rev_pass_rejects_chain_with_no_line(self):
        """A pure ripple chain through a gateway the recipient never
        trusted is dry at the reverse pass already."""
        led = Scenario(
            accounts={"A1": "1000.0", "A2": "1000.0", "G3": "1000.0"},
            trusts=["A1:1000/ABC/G3"],
            ious=["A1:200/ABC/G3"],
        ).build()
        ter, _s, _g = pay_via_paths(led, "A1", "A2", "50/ABC/G3")
        assert ter in (TER.tecPATH_DRY, TER.tecPATH_PARTIAL)


# --------------------------------------------------------------------------
# cases mined from the reference's own JS corpus (test/path-test.js,
# path1-test.js, path-tests.json — VERDICT r3 missing #5 / next #7).
# These run payments through the ENGINE (payment transactor + attached
# build_path set), exactly as the JS harness submits them.


def pay_tx(
    led: Ledger,
    src: str,
    dst: str,
    deliver: str,
    send_max: Optional[str] = None,
    build_path: bool = False,
    partial: bool = False,
    seq: Optional[int] = None,
):
    """Submit a Payment like the JS tests do ($.remote.transaction()
    .payment(...).build_path(true)); returns the engine TER."""
    from stellard_tpu.paths.pathfinder import build_path_set
    from stellard_tpu.protocol.stobject import STPathSet
    from stellard_tpu.protocol.sttx import SerializedTransaction

    engine = TransactionEngine(led)
    root = led.account_root(K(src).account_id)
    from stellard_tpu.protocol.sfields import sfSequence

    tx = SerializedTransaction.build(
        TxType.ttPAYMENT, K(src).account_id,
        seq if seq is not None else root[sfSequence], 10,
    )
    tx.obj[sfAmount] = amt(deliver)
    tx.obj[sfDestination] = K(dst).account_id
    if send_max is not None:
        tx.obj[sfSendMax] = amt(send_max)
    if partial:
        tx.obj[sfFlags] = tfPartialPayment
    if build_path:
        paths = build_path_set(
            led, K(src).account_id, K(dst).account_id, amt(deliver),
            send_max=amt(send_max) if send_max else None,
        )
        if paths:
            tx.obj[sfPaths] = STPathSet(paths)
    tx.sign(K(src))
    ter, _did = engine.apply_transaction(tx, TxParams.NONE)
    return ter


def iou_balance(led: Ledger, holder: str, issuer: str, cur: str = "USD") -> str:
    les = LedgerEntrySet(led)
    return views.ripple_balance(
        les, K(holder).account_id, K(issuer).account_id, currency_from_iso(cur)
    ).value_text()


class TestReferenceIssueCases:
    """path-test.js suite('Issues') — the historical regression cases."""

    def test_issue5_no_path_is_dry(self):
        """'path negative: Issue #5': dan trusts everyone but is a dead
        end (bob trusts nobody), so alice cannot reach bob at all."""
        led = Scenario(
            accounts={"alice": "10000.0", "bob": "10000.0",
                      "carol": "10000.0", "dan": "10000.0"},
            trusts=["dan:100/USD/alice", "dan:100/USD/bob",
                    "dan:100/USD/carol", "alice:100/USD/bob",
                    "carol:100/USD/bob"],
        ).build()
        # bob sends carol 75 of his own issue first (as the JS test does)
        assert pay_tx(led, "bob", "carol", "75/USD/bob") == TER.tesSUCCESS
        assert iou_balance(led, "carol", "bob") == "75"
        # no alternatives alice -> bob
        alts = find_paths(
            led, K("alice").account_id, K("bob").account_id,
            amt("25/USD/bob"),
        )
        assert alts == []
        # and the payment is dry
        ter = pay_tx(led, "alice", "bob", "25/USD/alice", build_path=True)
        assert ter == TER.tecPATH_DRY, ter

    def test_issue23_smaller_split_delivery(self):
        """'ripple-client issue #23: smaller': 55 USD via the direct
        line (40 cap) plus the carol->dan chain (15 of its 20 cap) —
        balances match the reference's verify_balances table exactly."""
        led = Scenario(
            accounts={"alice": "10000.0", "bob": "10000.0",
                      "carol": "10000.0", "dan": "10000.0"},
            trusts=["bob:40/USD/alice", "bob:20/USD/dan",
                    "carol:20/USD/alice", "dan:20/USD/carol"],
        ).build()
        ter = pay_tx(led, "alice", "bob", "55/USD/bob", build_path=True)
        assert ter == TER.tesSUCCESS, ter
        assert iou_balance(led, "bob", "alice") == "40"
        assert iou_balance(led, "bob", "dan") == "15"

    def test_issue23_larger_split_delivery(self):
        """'ripple-client issue #23: larger': 50 USD split 25 via amazon
        + 25 via the carol->dan chain."""
        led = Scenario(
            accounts={"alice": "10000.0", "bob": "10000.0",
                      "carol": "10000.0", "dan": "10000.0",
                      "amazon": "10000.0"},
            trusts=["amazon:120/USD/alice", "bob:25/USD/amazon",
                    "bob:100/USD/dan", "carol:25/USD/alice",
                    "dan:75/USD/carol"],
        ).build()
        ter = pay_tx(led, "alice", "bob", "50/USD/bob", build_path=True)
        assert ter == TER.tesSUCCESS, ter
        assert iou_balance(led, "bob", "amazon") == "25"
        assert iou_balance(led, "bob", "dan") == "25"
        assert iou_balance(led, "carol", "alice") == "25"
        assert iou_balance(led, "carol", "dan") == "-25"
        assert iou_balance(led, "dan", "carol") == "25"
        assert iou_balance(led, "dan", "bob") == "-25"


class TestReferenceTransferRate:
    """path-test.js 'alternative paths - consume best transfer (first)':
    gateway transfer fees steer strand selection."""

    _SCENARIO = dict(
        accounts={"alice": "10000.0", "bob": "10000.0",
                  "mtgox": "10000.0", "bitstamp": "10000.0"},
        trusts=["alice:600/USD/mtgox", "alice:800/USD/bitstamp",
                "bob:700/USD/mtgox", "bob:900/USD/bitstamp"],
        ious=["alice:70/USD/bitstamp", "alice:70/USD/mtgox"],
        transfer_rates={"bitstamp": 1.1},
    )

    def test_consume_best_transfer(self):
        """70 USD fits entirely through the par gateway (mtgox); the
        1.1-rate gateway is untouched."""
        led = Scenario(**self._SCENARIO).build()
        ter = pay_tx(led, "alice", "bob", "70/USD/bob", build_path=True)
        assert ter == TER.tesSUCCESS, ter
        assert iou_balance(led, "alice", "mtgox") == "0"
        assert iou_balance(led, "alice", "bitstamp") == "70"
        assert iou_balance(led, "bob", "mtgox") == "70"
        assert iou_balance(led, "bob", "bitstamp") == "0"

    def test_consume_best_transfer_first(self):
        """77 USD: 70 through par mtgox first, the remaining 7 through
        bitstamp costing 7.7 (10% gateway fee) — alice ends with
        62.3/USD/bitstamp, the reference's exact expectation."""
        led = Scenario(**self._SCENARIO).build()
        ter = pay_tx(
            led, "alice", "bob", "77/USD/bob",
            send_max="100/USD/alice", build_path=True,
        )
        assert ter == TER.tesSUCCESS, ter
        assert iou_balance(led, "alice", "mtgox") == "0"
        assert iou_balance(led, "alice", "bitstamp") == "62.3"
        assert iou_balance(led, "bob", "mtgox") == "70"
        assert iou_balance(led, "bob", "bitstamp") == "7"


class TestReferencePathTable:
    """The declarative scenarios of test/path-tests.json, built
    literally (accounts A1-A3, gateways G1-G3, market maker M1)."""

    def _t12_ledger(self) -> Ledger:
        """Path Tests #1/#2 ledger."""
        return Scenario(
            accounts={"A1": "100000.0", "A2": "10000.0", "A3": "10000.0",
                      "G1": "10000.0", "G2": "10000.0", "G3": "10000.0",
                      "M1": "10000.0"},
            trusts=["A1:5000/XYZ/G1", "A1:5000/ABC/G3",
                    "A2:5000/XYZ/G2", "A2:5000/ABC/G3",
                    "A3:1000/ABC/A2",
                    "M1:100000/XYZ/G1", "M1:100000/ABC/G3",
                    "M1:100000/XYZ/G2"],
            ious=["A1:3500/XYZ/G1", "A1:1200/ABC/G3",
                  "M1:25000/XYZ/G2", "M1:25000/ABC/G3"],
            offers=[("M1", "1000/XYZ/G1", "1000/XYZ/G2"),
                    ("M1", "10000.0", "1000/ABC/G3")],
        ).build()

    def test_through_destination_wrong_currency(self):
        """A path may pass THROUGH the destination in the wrong currency
        and still complete (reference: addLink's 100000-priority
        candidate + STPath::hasSeen matching on (account, currency,
        issuer) triples, so the same account in another currency is not
        'seen'): alice holds EUR issued by bob and pays bob USD through
        bob's own EUR->USD book."""
        led = Scenario(
            accounts={"alice": "1000.0", "bob": "1000.0", "M1": "1000.0"},
            trusts=["alice:100/EUR/bob", "M1:100/EUR/bob",
                    "M1:100/USD/bob"],
            ious=["alice:30/EUR/bob", "M1:50/USD/bob"],
            offers=[("M1", "30/EUR/bob", "30/USD/bob")],
        ).build()
        alts = find_paths(
            led, K("alice").account_id, K("bob").account_id,
            amt("20/USD/bob"),
        )
        assert alts, "through-destination path not found"
        spends = [a["source_amount"] for a in alts]
        assert any(not s.is_native for s in spends), spends

    def test_t1_str_to_str_no_alternatives(self):
        """T1-A: STR->STR has no alternatives (native transfers don't
        path-find)."""
        led = self._t12_ledger()
        alts = find_paths(
            led, K("A1").account_id, K("A2").account_id, amt("10.0"),
            send_max=amt("10.0"),
        )
        assert alts == []

    def test_t2a_iou_to_issuer_via_str(self):
        """T2-A: A2 sends 10 ABC/G3 to G3 spending STR: one alternative
        costing 100 STR (M1's 10-STR-per-ABC book)."""
        led = self._t12_ledger()
        alts = find_paths(
            led, K("A2").account_id, K("G3").account_id, amt("10/ABC/G3"),
            send_max=amt("100000.0"),
        )
        assert len(alts) == 1, [a["source_amount"].value_text() for a in alts]
        assert alts[0]["source_amount"].is_native
        assert alts[0]["source_amount"].drops() == 100 * XRP

    def test_t2b_iou_to_holder_via_str(self):
        """T2-B: A1 sends 1 ABC (as accepted by A2) spending STR:
        10 STR through the book then G3."""
        led = self._t12_ledger()
        alts = find_paths(
            led, K("A1").account_id, K("A2").account_id, amt("1/ABC/A2"),
            send_max=amt("100000.0"),
        )
        assert len(alts) == 1
        assert alts[0]["source_amount"].is_native
        assert alts[0]["source_amount"].drops() == 10 * XRP

    def test_t2c_two_hop_issuer_chain_via_str(self):
        """T2-C: A1 -> A3 delivering 1 ABC/A3 (A3 only trusts A2's ABC):
        book -> G3 -> A2 -> A3, still 10 STR."""
        led = self._t12_ledger()
        alts = find_paths(
            led, K("A1").account_id, K("A3").account_id, amt("1/ABC/A3"),
            send_max=amt("100000.0"),
        )
        assert len(alts) == 1, [a["source_amount"].value_text() for a in alts]
        assert alts[0]["source_amount"].is_native
        assert alts[0]["source_amount"].drops() == 10 * XRP

    def test_t3_iou_to_str(self):
        """Path Tests #3: A1 pays A2 10 STR spending ABC: 1 ABC through
        G3 then the ABC->STR book."""
        led = Scenario(
            accounts={"A1": "10000.0", "A2": "10000.0", "G3": "10000.0",
                      "M1": "11000.0"},
            trusts=["A1:1000/ABC/G3", "A2:1000/ABC/G3",
                    "M1:100000/ABC/G3"],
            ious=["A1:1000/ABC/G3", "M1:1200/ABC/G3"],
            offers=[("M1", "1000/ABC/G3", "10000.0")],
        ).build()
        alts = find_paths(
            led, K("A1").account_id, K("A2").account_id, amt("10.0"),
            send_max=amt("1000/ABC/A1"),
        )
        assert len(alts) == 1, [a["source_amount"].value_text() for a in alts]
        a = alts[0]["source_amount"]
        assert not a.is_native
        assert a.value_text() == "1"


class TestLineQualities:
    """Trust-line QualityIn/QualityOut applied during rippling
    (reference: calcNodeRipple, RippleCalc.cpp:1253 — an interior node
    forwards in * qualityIn/qualityOut when qualityIn < qualityOut,
    never a bonus; qualities read from the node's own side of each
    line, LedgerEntrySet::rippleQualityIn/Out)."""

    def _ledger(self, qin, qout):
        led = Scenario(
            accounts={"alice": "1000.0", "mid": "1000.0", "bob": "1000.0"},
            trusts=["mid:1000/USD/alice", "bob:1000/USD/mid"],
        ).build()
        from stellard_tpu.engine.engine import TransactionEngine, TxParams
        from stellard_tpu.protocol.sfields import (
            sfLimitAmount,
            sfQualityIn,
            sfQualityOut,
            sfSequence,
        )
        from stellard_tpu.protocol.sttx import SerializedTransaction

        engine = TransactionEngine(led)
        mid = K("mid")
        seq = led.account_root(mid.account_id)[sfSequence]
        for limit, fields in (
            ("1000/USD/alice", {sfQualityIn: qin}),
            ("1/USD/bob", {sfQualityOut: qout}),
        ):
            tx = SerializedTransaction.build(
                TxType.ttTRUST_SET, mid.account_id, seq, 10
            )
            tx.obj[sfLimitAmount] = amt(limit)
            for f, v in fields.items():
                tx.obj[f] = v
            tx.sign(mid)
            ter, did = engine.apply_transaction(tx, TxParams.NONE)
            assert ter == TER.tesSUCCESS, ter
            assert did
            seq += 1
        return led

    def test_quality_out_charges_the_fee(self):
        """mid rates its outbound line to bob at 2.0: delivering 10 to
        bob consumes 20 arriving at mid."""
        led = self._ledger(qin=1_000_000_000, qout=2_000_000_000)
        ter, spent, got = pay_via_paths(
            led, "alice", "bob", "10/USD/mid", send_max="50/USD/alice"
        )
        assert ter == TER.tesSUCCESS, ter
        assert text(got) == "10", text(got)
        assert text(spent) == "20", text(spent)

    def test_quality_in_discount_is_never_a_bonus(self):
        """qualityIn > qualityOut is the no-fee branch: 1:1, never a
        multiplier below one (reference: calcNodeRipple 'No fees')."""
        led = self._ledger(qin=2_000_000_000, qout=1_000_000_000)
        ter, spent, got = pay_via_paths(
            led, "alice", "bob", "10/USD/mid", send_max="50/USD/alice"
        )
        assert ter == TER.tesSUCCESS, ter
        assert text(got) == "10"
        assert text(spent) == "10", text(spent)

    def test_parity_qualities_change_nothing(self):
        led = self._ledger(qin=1_000_000_000, qout=1_000_000_000)
        ter, spent, got = pay_via_paths(
            led, "alice", "bob", "10/USD/mid", send_max="50/USD/alice"
        )
        assert ter == TER.tesSUCCESS, ter
        assert text(got) == "10"
        assert text(spent) == "10"


class TestThirdPartyIssuerDefaultPath:
    def test_issue_along_line_without_held_balance(self):
        """A sender holding NONE of the issuer's IOUs can still deliver
        a third-party-issuer amount by ISSUING into a line the
        intermediary trusts (reference: the default path runs through
        RippleCalc, which permits issuance up to the line limit — a
        held-balance precheck wrongly rejected this shape)."""
        led = Scenario(
            accounts={"alice": "1000.0", "mid": "1000.0", "bob": "1000.0"},
            trusts=["mid:1000/USD/alice", "bob:1000/USD/mid"],
        ).build()
        ter = pay_tx(led, "alice", "bob", "10/USD/mid",
                     send_max="50/USD/alice")
        assert ter == TER.tesSUCCESS, ter
        les = LedgerEntrySet(led)
        USD = currency_from_iso("USD")
        assert views.ripple_balance(
            les, K("bob").account_id, K("mid").account_id, USD
        ).value_text() == "10"
        assert views.ripple_balance(
            les, K("mid").account_id, K("alice").account_id, USD
        ).value_text() == "10"


# --------------------------------------------------------------------------
# The remaining reference suites from test/new-path-test.coffee: the
# T4 non-native same-currency table (#4 and its second ledger), the
# Bitstamp+SnapSwap liquidity-provider-without-offers suite, and the
# production-shaped CNY scenario. Expected paths are written in the
# coffee harness's shorthand ("HKD/G1|G1" = account hop, "...|$" =
# order-book hop) and matched with the same hop-expansion rules as its
# expand_alternative/hop_matcher helpers. The reference marks T4
# F/G/H/I1/I2/I3 as `_skip` in its own table; they stay unported.


def _expand_hops(alt):
    """Mirror new-path-test.coffee expand_alternative: make currency and
    issuer explicit in every hop, carrying forward from source_amount."""
    from stellard_tpu.protocol.stamount import iso_from_currency

    src_amt = alt["source_amount"]
    prev_currency = "XRP" if src_amt.is_native else iso_from_currency(
        src_amt.currency)
    prev_issuer = None if src_amt.is_native else src_amt.issuer
    out = []
    for path in alt["paths"]:
        hops = []
        for el in path:
            if el.currency is not None:
                cur = ("XRP" if el.currency == b"\x00" * 20
                       else iso_from_currency(el.currency))
            else:
                cur = prev_currency
            if el.issuer is not None:
                issuer = el.issuer
            elif el.account is not None:
                issuer = el.account
            else:
                issuer = prev_issuer
            hops.append({"currency": cur,
                         "issuer": issuer,
                         "account": el.account})
            if el.currency is not None:
                prev_currency = cur
            if el.issuer is not None:
                prev_issuer = el.issuer
            elif el.account is not None:
                prev_issuer = el.account
        out.append(hops)
    return out


def _match_paths(alt, expected: list[list[str]]) -> None:
    """Assert the alternative's path set equals `expected` (shorthand,
    order-insensitive), reference: test_alternatives/match_path."""
    actual = _expand_hops(alt)
    assert len(actual) == len(expected), (
        f"expected {len(expected)} paths, got {len(actual)}: {actual}"
    )
    remaining = list(actual)
    for exp in expected:
        found = None
        for cand in remaining:
            if len(cand) != len(exp):
                continue
            ok = True
            for hop, decl in zip(cand, exp):
                ci, _, acct = decl.partition("|")
                cur, _, iss = ci.partition("/")
                if hop["currency"] != cur:
                    ok = False
                    break
                if iss and hop["issuer"] != K(iss).account_id:
                    ok = False
                    break
                if acct == "$":
                    if hop["account"] is not None:
                        ok = False
                        break
                elif hop["account"] != K(acct).account_id:
                    ok = False
                    break
            if ok:
                found = cand
                break
        assert found is not None, f"no path matches {exp} in {remaining}"
        remaining.remove(found)


class TestNewPathSuiteT4:
    """Path Tests #4 (non-XRP to non-XRP, same currency) — reference:
    test/new-path-test.coffee 'Path Tests #4' declarations."""

    def _ledger(self):
        return Scenario(
            accounts={"G1": "1000.0", "G2": "1000.0", "G3": "1000.0",
                      "G4": "1000.0", "A1": "1000.0", "A2": "1000.0",
                      "A3": "1000.0", "A4": "10000.0",
                      "M1": "11000.0", "M2": "11000.0"},
            trusts=["A1:2000/HKD/G1", "A2:2000/HKD/G2", "A3:2000/HKD/G1",
                    "M1:100000/HKD/G1", "M1:100000/HKD/G2",
                    "M2:100000/HKD/G1", "M2:100000/HKD/G2"],
            ious=["A1:1000/HKD/G1", "A2:1000/HKD/G2", "A3:1000/HKD/G1",
                  "M1:1200/HKD/G1", "M1:5000/HKD/G2",
                  "M2:1200/HKD/G1", "M2:5000/HKD/G2"],
            offers=[("M1", "1000/HKD/G1", "1000/HKD/G2"),
                    ("M2", "10000.0", "1000/HKD/G2"),
                    ("M2", "1000/HKD/G1", "10000.0")],
        ).build()

    def _alts(self, led, src, dst, send):
        return find_paths(
            led, K(src).account_id, K(dst).account_id, amt(send),
            send_max=amt(f"2000/HKD/{src}"),
        )

    def test_a_borrow_or_repay(self):
        """T4-A: Source -> Destination (repay source issuer); one
        alternative, default path only (no paths_computed)."""
        alts = self._alts(self._ledger(), "A1", "G1", "10/HKD/G1")
        assert len(alts) == 1
        assert alts[0]["source_amount"].value_text() == "10"
        assert alts[0]["paths"] == []

    def test_a2_borrow_or_repay_dst_issuer(self):
        """T4-A2: same, amount stated as issuer-of-destination."""
        alts = self._alts(self._ledger(), "A1", "G1", "10/HKD/A1")
        assert len(alts) == 1
        assert alts[0]["source_amount"].value_text() == "10"
        assert alts[0]["paths"] == []

    def test_b_common_gateway(self):
        """T4-B: Source -> AC -> Destination via the shared gateway."""
        alts = self._alts(self._ledger(), "A1", "A3", "10/HKD/A3")
        assert len(alts) == 1
        assert alts[0]["source_amount"].value_text() == "10"
        _match_paths(alts[0], [["HKD/G1|G1"]])

    def test_c_gateway_to_gateway(self):
        """T4-C: Source -> OB -> Destination; the four expected routes:
        both makers, the direct cross-issuer book, the XRP bridge."""
        alts = self._alts(self._ledger(), "G1", "G2", "10/HKD/G2")
        assert len(alts) == 1
        assert alts[0]["source_amount"].value_text() == "10"
        _match_paths(alts[0], [
            ["HKD/M2|M2"],
            ["HKD/M1|M1"],
            ["HKD/G2|$"],
            ["XRP|$", "HKD/G2|$"],
        ])

    def test_d_user_to_unlinked_gateway(self):
        """T4-D: Source -> AC -> OB -> Destination."""
        alts = self._alts(self._ledger(), "A1", "G2", "10/HKD/G2")
        assert len(alts) == 1
        assert alts[0]["source_amount"].value_text() == "10"
        _match_paths(alts[0], [
            ["HKD/G1|G1", "HKD/G2|$"],
            ["HKD/G1|G1", "HKD/M2|M2"],
            ["HKD/G1|G1", "HKD/M1|M1"],
            ["HKD/G1|G1", "XRP|$", "HKD/G2|$"],
        ])

    def test_i4_xrp_bridge(self):
        """T4-I4: Source -> AC -> OB to XRP -> OB from XRP -> AC ->
        Destination (plus the incidental maker routes)."""
        alts = self._alts(self._ledger(), "A1", "A2", "10/HKD/A2")
        assert len(alts) == 1
        assert alts[0]["source_amount"].value_text() == "10"
        _match_paths(alts[0], [
            ["HKD/G1|G1", "HKD/G2|$", "HKD/G2|G2"],
            ["HKD/G1|G1", "XRP|$", "HKD/G2|$", "HKD/G2|G2"],
            ["HKD/G1|G1", "HKD/M1|M1", "HKD/G2|G2"],
            ["HKD/G1|G1", "HKD/M2|M2", "HKD/G2|G2"],
        ])

    def test_e_gateway_to_user(self):
        """T4-E (second #4 ledger): Source -> OB -> AC -> Destination."""
        led = Scenario(
            accounts={"G1": "1000.0", "G2": "1000.0", "A1": "1000.0",
                      "A2": "1000.0", "A3": "1000.0", "M1": "11000.0"},
            trusts=["A1:2000/HKD/G1", "A2:2000/HKD/G2", "A3:2000/HKD/A2",
                    "M1:100000/HKD/G1", "M1:100000/HKD/G2"],
            ious=["A1:1000/HKD/G1", "A2:1000/HKD/G2",
                  "M1:5000/HKD/G1", "M1:5000/HKD/G2"],
            offers=[("M1", "1000/HKD/G1", "1000/HKD/G2")],
        ).build()
        alts = find_paths(
            led, K("G1").account_id, K("A2").account_id, amt("10/HKD/A2"),
            send_max=amt("2000/HKD/G1"),
        )
        assert len(alts) == 1
        assert alts[0]["source_amount"].value_text() == "10"
        _match_paths(alts[0], [
            ["HKD/G2|$", "HKD/G2|G2"],
            ["HKD/M1|M1", "HKD/G2|G2"],
        ])


class TestNewPathSuiteSnapSwap:
    """'Bitstamp + SnapSwap account holders | liquidity provider with no
    offers' — rippling through a maker that rests NO offers (pure trust
    liquidity). Reference: new-path-test.coffee BS P1-P5."""

    def _ledger(self):
        return Scenario(
            accounts={"G1BS": "1000.0", "G2SW": "1000.0", "A1": "1000.0",
                      "A2": "1000.0", "M1": "11000.0"},
            trusts=["A1:2000/HKD/G1BS", "A2:2000/HKD/G2SW",
                    "M1:100000/HKD/G1BS", "M1:100000/HKD/G2SW"],
            ious=["A1:1000/HKD/G1BS", "A2:1000/HKD/G2SW",
                  "M1:1200/HKD/G1BS", "M1:5000/HKD/G2SW"],
        ).build()

    def _alts(self, src, dst, send):
        return find_paths(
            self._ledger(), K(src).account_id, K(dst).account_id, amt(send),
            send_max=amt(f"2000/HKD/{src}"),
        )

    def test_p1_user_to_user(self):
        alts = self._alts("A1", "A2", "10/HKD/A2")
        assert len(alts) == 1
        _match_paths(alts[0], [["HKD/G1BS|G1BS", "HKD/M1|M1",
                                "HKD/G2SW|G2SW"]])

    def test_p2_user_to_user_reverse(self):
        alts = self._alts("A2", "A1", "10/HKD/A1")
        assert len(alts) == 1
        _match_paths(alts[0], [["HKD/G2SW|G2SW", "HKD/M1|M1",
                                "HKD/G1BS|G1BS"]])

    def test_p3_issuer_to_other_gateways_user(self):
        alts = self._alts("G1BS", "A2", "10/HKD/A2")
        assert len(alts) == 1
        assert alts[0]["source_amount"].value_text() == "10"
        _match_paths(alts[0], [["HKD/M1|M1", "HKD/G2SW|G2SW"]])

    def test_p4_other_issuer_to_user(self):
        alts = self._alts("G2SW", "A1", "10/HKD/A1")
        assert len(alts) == 1
        assert alts[0]["source_amount"].value_text() == "10"
        _match_paths(alts[0], [["HKD/M1|M1", "HKD/G1BS|G1BS"]])

    def test_p5_maker_repays_issuer(self):
        alts = self._alts("M1", "G1BS", "10/HKD/M1")
        assert len(alts) == 1
        assert alts[0]["paths"] == []  # default path (direct line)


class TestNewPathSuiteCNY:
    """The production-shaped 'CNY test' (new-path-test.coffee): two money
    makers, a production-like offer mosaic with odd-lot balances; SRC
    pays the gateway 10.1 CNY spending XRP — exactly one alternative
    (via XRP), filled across multiple price levels of the book."""

    def _ledger(self):
        return Scenario(
            accounts={"SRC": "4999.999898", "GATEWAY_DST": "10846.168060",
                      "MONEY_MAKER_1": "4291.430036",
                      "MONEY_MAKER_2": "106839.375770",
                      "A1": "1240.997150", "A2": "14115.046893",
                      "A3": "512087.883181"},
            trusts=["MONEY_MAKER_2:1001/CNY/MONEY_MAKER_1",
                    "MONEY_MAKER_2:1001/CNY/GATEWAY_DST",
                    "A1:1000000/CNY/MONEY_MAKER_1",
                    "A1:100000/USD/MONEY_MAKER_1",
                    "A1:10000/BTC/MONEY_MAKER_1",
                    "A1:1000/USD/GATEWAY_DST", "A1:1000/CNY/GATEWAY_DST",
                    "A2:3000/CNY/MONEY_MAKER_1", "A2:3000/CNY/GATEWAY_DST",
                    "A3:10000/CNY/MONEY_MAKER_1",
                    "A3:10000/CNY/GATEWAY_DST"],
            ious=["MONEY_MAKER_2:0.0000000003599/CNY/MONEY_MAKER_1",
                  "MONEY_MAKER_2:137.6852546843001/CNY/GATEWAY_DST",
                  "A1:0.0000000119761/CNY/MONEY_MAKER_1",
                  "A1:33.047994/CNY/GATEWAY_DST",
                  "A2:209.3081873019994/CNY/MONEY_MAKER_1",
                  "A2:694.6251706504019/CNY/GATEWAY_DST",
                  "A3:23.617050013581/CNY/MONEY_MAKER_1",
                  "A3:70.999614649799/CNY/GATEWAY_DST"],
            offers=[("MONEY_MAKER_2", "1.0", "1/CNY/GATEWAY_DST"),
                    ("MONEY_MAKER_2", "1/CNY/GATEWAY_DST", "1.0"),
                    ("MONEY_MAKER_2", "318000/CNY/GATEWAY_DST", "53000.0"),
                    ("MONEY_MAKER_2", "209.0", "4.18/CNY/MONEY_MAKER_2"),
                    ("MONEY_MAKER_2", "990000/CNY/MONEY_MAKER_1", "10000.0"),
                    ("MONEY_MAKER_2", "9990000/CNY/MONEY_MAKER_1",
                     "10000.0"),
                    ("MONEY_MAKER_2", "8870000/CNY/GATEWAY_DST", "10000.0"),
                    ("MONEY_MAKER_2", "232.0", "5.568/CNY/MONEY_MAKER_2"),
                    ("A2", "2000.0", "66.8/CNY/MONEY_MAKER_1"),
                    ("A2", "1200.0", "42/CNY/GATEWAY_DST"),
                    ("A2", "43.2/CNY/MONEY_MAKER_1", "900.0"),
                    ("A3", "2240/CNY/MONEY_MAKER_1", "50000.0")],
        ).build()

    def test_p101_via_xrp(self):
        led = self._ledger()
        alts = find_paths(
            led, K("SRC").account_id, K("GATEWAY_DST").account_id,
            amt("10.1/CNY/GATEWAY_DST"), send_max=amt("4999.0"),
        )
        assert len(alts) == 1, [a["source_amount"].value_text()
                                for a in alts]
        a = alts[0]
        assert a["source_amount"].is_native
        assert a["delivered"].value_text() == "10.1"
