"""Liquidity plane (ISSUE 17): incremental order-book index identity
on adversarial write-set seams, Q16.16 quality flattening, the routed
device evaluator's host/device byte-identity at every mesh width, the
PathPlane scheduling/shedding contract, the path_find result-cache
satellite, and the FEE_PATH_FIND door ladder."""

from __future__ import annotations

import types

import jax
import numpy as np
import pytest

jax.config.update("jax_platforms", "cpu")

from stellard_tpu.crypto.backend import make_path_evaluator  # noqa: E402
from stellard_tpu.engine import TransactionEngine  # noqa: E402
from stellard_tpu.node.config import Config  # noqa: E402
from stellard_tpu.node.node import Node  # noqa: E402
from stellard_tpu.ops.pathq_jax import Q16_MAX, Q16_ONE  # noqa: E402
from stellard_tpu.overlay.resource import (  # noqa: E402
    FEE_PATH_FIND,
    ResourceManager,
)
from stellard_tpu.paths import (  # noqa: E402
    LiveBookIndex,
    OrderBookDB,
    find_paths,
)
from stellard_tpu.paths.plane import PathPlane  # noqa: E402
from stellard_tpu.paths.quality import (  # noqa: E402
    MAX_HOPS,
    book_quality_q16,
    build_rate_matrix,
    rate_u64_to_q16,
)
from stellard_tpu.protocol.formats import TxType  # noqa: E402
from stellard_tpu.protocol.keys import KeyPair  # noqa: E402
from stellard_tpu.protocol.sfields import (  # noqa: E402
    sfAmount,
    sfDestination,
    sfOfferSequence,
    sfTakerGets,
    sfTakerPays,
)
from stellard_tpu.protocol.stamount import (  # noqa: E402
    ACCOUNT_ZERO,
    STAmount,
    currency_from_iso,
)
from stellard_tpu.protocol.stobject import PathElement, STPathSet  # noqa: E402
from stellard_tpu.rpc.handlers import (  # noqa: E402
    Context,
    Role,
    charge_rpc_client,
    dispatch,
    rpc_method_fee,
    rpc_warning,
)
from stellard_tpu.rpc.infosub import InfoSub, SubscriptionManager  # noqa: E402
from stellard_tpu.paths.orderbook import Book  # noqa: E402

from test_engine import ALICE, BOB, CAROL, GATEWAY, Net, USD  # noqa: E402

EUR = currency_from_iso("EUR")
XRP = b"\x00" * 20
M = 1_000_000


def iou(v, issuer=GATEWAY, cur=USD):
    return STAmount.from_iou(cur, issuer.account_id, v, 0)


def drops(v):
    return STAmount.from_drops(v)


def close(net: Net):
    """Seal the working ledger and open its successor (one validated
    close); returns the sealed ledger."""
    led = net.ledger
    led.close(led.parent_close_time + 10, 10)
    net.ledger = led.open_successor()
    net.engine = TransactionEngine(net.ledger)
    return led


def full_books(led) -> set:
    return OrderBookDB().setup(led).books


def offer(net: Net, key: KeyPair, pays: STAmount, gets: STAmount):
    """Place an offer; returns the tx sequence (for later cancel)."""
    seq = net.seq(key)
    net.apply(key, TxType.ttOFFER_CREATE,
              fields={sfTakerPays: pays, sfTakerGets: gets})
    return seq


def liquid_net() -> Net:
    net = Net(ALICE, BOB, CAROL, GATEWAY)
    net.trust(ALICE, GATEWAY, 10_000)
    net.trust(BOB, GATEWAY, 10_000)
    net.trust(CAROL, GATEWAY, 10_000)
    net.pay(GATEWAY, ALICE.account_id, iou(1_000))
    net.pay(GATEWAY, BOB.account_id, iou(1_000))
    return net


def check_identity(idx: LiveBookIndex, led):
    """THE contract: the incremental view equals the full scan."""
    db = idx.advance(led)
    assert db.books == full_books(led), f"divergence at seq {led.seq}"
    assert idx.seq == led.seq
    return db


# --------------------------------------------------------------------------
# incremental index identity on the adversarial seams


class TestLiveBookIndexIdentity:
    def test_first_advance_is_full_rebuild(self):
        net = liquid_net()
        offer(net, ALICE, drops(100 * M), iou(100))
        led = close(net)
        idx = LiveBookIndex()
        db = check_identity(idx, led)
        assert idx.full_rebuilds == 1
        assert idx.incremental_advances == 0
        assert len(db.books) == 1

    def test_zero_book_write_close_carries_without_reads(self):
        """Anti-vacuity: a close whose write set touches no books must
        carry the previous view forward without a single state read —
        pinned by the read counters, not just the result."""
        net = liquid_net()
        offer(net, ALICE, drops(100 * M), iou(100))
        idx = LiveBookIndex()
        db1 = idx.advance(close(net))
        # a plain STR payment: no book in the write set
        net.pay(ALICE, CAROL.account_id, drops(5 * M))
        led2 = close(net)
        scanned, rereads = idx.state_offers_scanned, idx.book_rereads
        db2 = idx.advance(led2)
        assert db2 is db1  # literally the same carried-forward object
        assert idx.carries == 1
        assert idx.state_offers_scanned == scanned  # zero offers scanned
        assert idx.book_rereads == rereads  # zero books re-read
        assert db2.books == full_books(led2)
        # a fully empty close carries too
        led3 = close(net)
        assert idx.advance(led3) is db1 and idx.carries == 2

    def test_book_creation_mid_flood(self):
        """New books appearing while other closes flood through: each
        close's delta touches only its own books."""
        net = liquid_net()
        idx = LiveBookIndex()
        idx.advance(close(net))
        assert idx.full_rebuilds == 1

        offer(net, ALICE, drops(10 * M), iou(10))  # USD/XRP book born
        led = close(net)
        check_identity(idx, led)
        assert idx.book_rereads == 1

        # two more offers in the SAME book + one brand-new book
        offer(net, ALICE, drops(20 * M), iou(10))
        offer(net, BOB, drops(30 * M), iou(10))
        # reverse direction, priced NOT to cross the forward book
        offer(net, BOB, iou(20), drops(10 * M))
        led = close(net)
        check_identity(idx, led)
        assert idx.book_rereads == 3  # 1 + exactly the 2 touched books
        assert idx.full_rebuilds == 1  # never fell back
        assert idx.incremental_advances == 2

    def test_crossing_consumes_tier_keeps_book(self):
        """A crossing that eats the best tier deletes offers without
        changing book membership — the incremental count must absorb
        the DeletedNode and keep the book alive."""
        net = liquid_net()
        # two tiers: alice sells USD at 1.0 and at 2.0 XRP/USD
        offer(net, ALICE, drops(100 * M), iou(100))
        offer(net, ALICE, drops(200 * M), iou(100))
        idx = LiveBookIndex()
        idx.advance(close(net))
        # bob crosses exactly the best tier (pays 100 XRP for 100 USD)
        offer(net, BOB, iou(100), drops(100 * M))
        led = close(net)
        db = check_identity(idx, led)
        assert len(db.books) == 1  # second tier keeps the book alive
        assert idx.full_rebuilds == 1  # delta applied, no fallback

    def test_crossing_empties_book(self):
        """Full consumption of a single-offer book: both the crossed
        offer and the taker's are gone, the book must vanish."""
        net = liquid_net()
        offer(net, ALICE, drops(100 * M), iou(100))
        idx = LiveBookIndex()
        db1 = idx.advance(close(net))
        assert len(db1.books) == 1
        offer(net, BOB, iou(100), drops(100 * M))
        led = close(net)
        db = check_identity(idx, led)
        assert len(db.books) == 0
        assert idx.full_rebuilds == 1

    def test_cancel_empties_book(self):
        net = liquid_net()
        seq = offer(net, ALICE, drops(100 * M), iou(100))
        idx = LiveBookIndex()
        assert len(idx.advance(close(net)).books) == 1
        net.apply(ALICE, TxType.ttOFFER_CANCEL,
                  fields={sfOfferSequence: seq})
        led = close(net)
        db = check_identity(idx, led)
        assert len(db.books) == 0
        assert idx.full_rebuilds == 1

    def test_quality_reorder_same_book(self):
        """A better-priced offer reorders the tiers: membership is
        unchanged (delta nets +1 on an existing book) but the quality
        probe must see the new best tier."""
        net = liquid_net()
        offer(net, ALICE, drops(200 * M), iou(100))  # 2.0 XRP per USD
        idx = LiveBookIndex()
        led = close(net)
        db = idx.advance(led)
        book = next(iter(db.books))
        q_before = book_quality_q16(led, book)
        offer(net, BOB, drops(100 * M), iou(100))  # 1.0 — jumps the queue
        led = close(net)
        db = check_identity(idx, led)
        assert db.books == {book}
        q_after = book_quality_q16(led, book)
        assert q_after < q_before  # cheaper best tier surfaced

    def test_kill_switch_full_rebuild_identity(self):
        """[paths] incremental=0: every advance is a full scan, and the
        two modes agree at every close."""
        net = liquid_net()
        inc, full = LiveBookIndex(incremental=True), LiveBookIndex(
            incremental=False)
        seq = None
        for step in range(4):
            if step == 0:
                seq = offer(net, ALICE, drops(100 * M), iou(100))
            elif step == 1:
                offer(net, BOB, iou(50), drops(60 * M))
            elif step == 2:
                net.apply(ALICE, TxType.ttOFFER_CANCEL,
                          fields={sfOfferSequence: seq})
            led = close(net)
            assert inc.advance(led).books == full.advance(led).books
            assert full.advance(led).books == full_books(led)
        assert full.full_rebuilds == 4
        assert full.incremental_advances == 0 and full.carries == 0
        assert inc.full_rebuilds == 1

    def test_gap_forces_rebuild(self):
        """Skipping a close breaks parent-hash continuity: the next
        advance must fall back to the full scan, not guess."""
        net = liquid_net()
        idx = LiveBookIndex()
        idx.advance(close(net))
        offer(net, ALICE, drops(100 * M), iou(100))
        close(net)  # never shown to the index
        offer(net, BOB, iou(10), drops(20 * M))
        led = close(net)
        db = check_identity(idx, led)
        assert idx.full_rebuilds == 2
        assert db.books == full_books(led)

    def test_books_if_current_never_mutates(self):
        net = liquid_net()
        idx = LiveBookIndex()
        led1 = close(net)
        assert idx.books_if_current(led1) is None  # cold: no advance
        db = idx.advance(led1)
        assert idx.books_if_current(led1) is db
        offer(net, ALICE, drops(100 * M), iou(100))
        led2 = close(net)
        before = idx.counters()
        assert idx.books_if_current(led2) is None  # current != led2
        assert idx.counters() == before  # ...and nothing moved

    def test_find_paths_identity_incremental_vs_full(self):
        """End to end: find_paths answers are identical whether served
        from the incremental index or a fresh full scan, at every seq."""
        net = liquid_net()
        idx = LiveBookIndex()

        def snapshot(led):
            out = []
            for books in (idx.advance(led), OrderBookDB().setup(led)):
                alts = find_paths(led, ALICE.account_id, CAROL.account_id,
                                  iou(10), books=books)
                out.append([
                    (STPathSet(a["paths"]).to_json(),
                     a["source_amount"].to_json())
                    for a in alts
                ])
            return out

        offer(net, BOB, drops(100 * M), iou(100))  # XRP -> USD liquidity
        led = close(net)
        inc, full = snapshot(led)
        assert inc == full and inc  # non-vacuous: there IS a book path
        offer(net, BOB, iou(100, cur=EUR), iou(100))  # EUR -> USD
        led = close(net)
        inc, full = snapshot(led)
        assert inc == full
        net.pay(ALICE, CAROL.account_id, drops(M))  # carry-forward close
        led = close(net)
        inc, full = snapshot(led)
        assert inc == full
        assert idx.carries >= 1 and idx.incremental_advances >= 1


# --------------------------------------------------------------------------
# Q16.16 flattening


class TestQualityFlattening:
    def test_rate_decode_parity_and_scale(self):
        # canonical STAmount rate 1.0: mantissa 1e15, offset -15
        q_parity = ((100 - 15) << 56) | 10 ** 15
        assert rate_u64_to_q16(q_parity) == Q16_ONE
        q_double = ((100 - 15) << 56) | 2 * 10 ** 15
        assert rate_u64_to_q16(q_double) == 2 * Q16_ONE
        assert rate_u64_to_q16(0) == Q16_ONE  # no quality = parity
        q_huge = ((100 + 20) << 56) | 10 ** 15
        assert rate_u64_to_q16(q_huge) == Q16_MAX  # saturates, not wraps

    def test_book_quality_probe(self):
        net = liquid_net()
        led = close(net)
        book = Book(XRP, ACCOUNT_ZERO, USD, GATEWAY.account_id)
        assert book_quality_q16(led, book) == Q16_MAX  # empty book
        offer(net, ALICE, drops(200 * M), iou(100))
        led = close(net)
        q2 = book_quality_q16(led, book)
        assert q2 < Q16_MAX
        offer(net, BOB, drops(100 * M), iou(100))
        led = close(net)
        assert book_quality_q16(led, book) < q2  # better tier wins

    def test_rate_matrix_shapes_and_saturation(self):
        net = liquid_net()
        offer(net, ALICE, drops(200 * M), iou(100))
        led = close(net)
        deep = [PathElement(account=BOB.account_id)] * (MAX_HOPS + 1)
        candidates = [
            ([], (XRP, ACCOUNT_ZERO)),  # empty path: identity row
            ([PathElement(currency=USD, issuer=GATEWAY.account_id)],
             (XRP, ACCOUNT_ZERO)),  # one book hop
            ([PathElement(account=GATEWAY.account_id)],
             (USD, ALICE.account_id)),  # account hop at parity
            (deep, (USD, GATEWAY.account_id)),  # over-deep: ranks last
        ]
        rows = build_rate_matrix(led, candidates)
        assert rows.shape == (4, MAX_HOPS) and rows.dtype == np.uint32
        assert (rows[0] == Q16_ONE).all()
        book = Book(XRP, ACCOUNT_ZERO, USD, GATEWAY.account_id)
        assert rows[1, 0] == book_quality_q16(led, book)
        assert (rows[1, 1:] == Q16_ONE).all()
        assert rows[2, 0] == Q16_ONE  # no TransferRate = parity
        assert (rows[3] == Q16_MAX).all()


# --------------------------------------------------------------------------
# routed device evaluator


class TestPathQualityEvaluator:
    def _rates(self, n, seed=0):
        rng = np.random.default_rng(seed)
        return rng.integers(1, 2 ** 32, size=(n, MAX_HOPS), dtype=np.uint32)

    @pytest.mark.parametrize("width", [1, 2, 4, 8])
    def test_host_device_byte_identity(self, width):
        """THE device-plane pin: the mesh arm is byte-identical to the
        host arm at every width (virtual 8-device CPU mesh)."""
        ev = make_path_evaluator(mesh=str(width), routing="device")
        for n in (1, 3, 37, 128):
            rates = self._rates(n, seed=n)
            host = ev.evaluate_host(rates)
            dev = ev.evaluate(rates)
            assert dev.dtype == np.uint32 and host.dtype == np.uint32
            assert np.array_equal(host, dev), f"width {width} batch {n}"
        assert ev.device_batches > 0 and ev.host_batches == 0
        j = ev.get_json()
        assert width in j["arm_widths"].values()  # honest width provenance

    def test_identity_and_saturation_rows(self):
        ev = make_path_evaluator(routing="host")
        rates = np.full((3, MAX_HOPS), Q16_ONE, dtype=np.uint32)
        rates[1, 0] = 2 * Q16_ONE
        rates[2, :] = Q16_MAX
        out = ev.evaluate(rates)
        assert out[0] == Q16_ONE  # identity composes to identity
        assert out[1] == 2 * Q16_ONE
        assert out[2] == Q16_MAX  # saturated stays saturated

    def test_cost_routing_floors_small_batches(self):
        ev = make_path_evaluator(mesh="2", routing="cost",
                                 min_device_batch=64)
        ev.evaluate(self._rates(8))
        assert ev.host_batches == 1 and ev.device_batches == 0
        for i in range(4):
            ev.evaluate(self._rates(256, seed=i))
        assert ev.device_batches > 0  # above the floor, arms explored
        assert ev.get_json()["rows_evaluated"] == 8 + 4 * 256

    def test_bad_routing_is_loud(self):
        with pytest.raises(ValueError):
            make_path_evaluator(routing="gpu")


# --------------------------------------------------------------------------
# PathPlane: pre-rank floor, budget, staleness, shedding


class TestPathPlane:
    def test_pre_rank_noop_below_floor(self):
        net = liquid_net()
        led = close(net)
        ev = make_path_evaluator(routing="host")
        plane = PathPlane(evaluator=ev, prune_floor=8, prune_keep=2)
        pre = plane.make_pre_rank(led)
        cands = [([PathElement(account=BOB.account_id)],
                  (USD, GATEWAY.account_id)) for _ in range(8)]
        assert pre(None, cands) is cands  # at the floor: untouched
        assert plane.prune_batches == 0

    def test_pre_rank_prunes_but_keeps_empty_paths(self):
        net = liquid_net()
        led = close(net)
        ev = make_path_evaluator(routing="host")
        plane = PathPlane(evaluator=ev, prune_floor=4, prune_keep=2)
        pre = plane.make_pre_rank(led)
        cands = [([PathElement(account=BOB.account_id)],
                  (USD, GATEWAY.account_id)) for _ in range(9)]
        cands.append(([], (XRP, ACCOUNT_ZERO)))  # the default path
        out = pre(None, cands)
        assert len(out) < len(cands)
        assert ([], (XRP, ACCOUNT_ZERO)) in out  # empty path survives
        # output preserves the original relative order
        idxs = [cands.index(c) for c in out]
        assert idxs == sorted(idxs)
        assert plane.prune_batches == 1
        assert plane.pruned_candidates == len(cands) - len(out)

    def test_no_evaluator_means_no_hook(self):
        assert PathPlane().make_pre_rank(None) is None
        ev = make_path_evaluator(routing="host")
        assert PathPlane(evaluator=ev,
                         device_prune=False).make_pre_rank(None) is None

    def test_budget_sheds_and_resets_per_close(self):
        plane = PathPlane(max_updates_per_close=2)
        plane.begin_close(10)
        assert plane.claim_update(("a", 1), 10)
        assert plane.claim_update(("b", 1), 10)
        assert not plane.claim_update(("c", 1), 10)  # shed, not queued
        assert plane.shed_budget == 1
        plane.begin_close(11)  # fresh budget
        assert plane.claim_update(("c", 1), 11)

    def test_stalest_first_ordering_and_staleness_histogram(self):
        plane = PathPlane(max_updates_per_close=8)
        plane.note_created(("a", 1), 5)
        plane.note_created(("b", 1), 5)
        plane.note_ranked(("a", 1), 7)
        # b last ranked at 5, a at 7: b goes first; never-seen first of all
        order = plane.order_keys([("a", 1), ("b", 1), ("z", 9)], 9)
        assert order == [("z", 9), ("b", 1), ("a", 1)]
        plane.note_ranked(("b", 1), 9)
        assert plane.staleness_max == 4  # b waited 9-5 closes
        assert plane.staleness_quantile(0.99) == 4
        plane.sync_live([("a", 1)])
        assert plane.get_json()["subs"] == 1

    def test_throttled_endpoint_is_shed_before_budget(self):
        t = [0.0]
        rm = ResourceManager(clock=lambda: t[0])
        spammer = ("6.6.6.6", 0)
        while not rm.is_throttled(spammer):
            rm.charge(spammer, FEE_PATH_FIND)
        plane = PathPlane(max_updates_per_close=8, resources=rm)
        plane.begin_close(3)
        assert not plane.claim_update(("s", 1), 3, endpoint=spammer)
        assert plane.shed_throttled == 1 and plane.shed_budget == 0
        # a polite client on the same close still gets its update
        assert plane.claim_update(("p", 1), 3, endpoint=("7.7.7.7", 0))
        # ...and the granted update was charged to its endpoint
        assert rm.balance(("7.7.7.7", 0)) > 0


# --------------------------------------------------------------------------
# subscription publishing through the plane (node-level)


@pytest.fixture
def node():
    n = Node(Config(signature_backend="cpu")).setup()
    yield n
    n.stop()


def fund(n: Node, kp: KeyPair, drops_: int = 1_000_000_000) -> None:
    from stellard_tpu.protocol.sfields import sfSequence
    from stellard_tpu.protocol.sttx import SerializedTransaction

    master = n.master_keys
    root = n.ledger_master.current_ledger().account_root(master.account_id)
    tx = SerializedTransaction.build(
        TxType.ttPAYMENT, master.account_id, root[sfSequence], 10,
        {sfAmount: STAmount.from_drops(drops_),
         sfDestination: kp.account_id},
    )
    tx.sign(master)
    ter, applied = n.submit(tx)
    assert applied, ter
    # seal immediately: the root sequence read above goes through the
    # validated state, so back-to-back funds need a close in between
    n.close_ledger()


class TestSubscriptionPlane:
    def test_node_wires_plane_and_close_hook(self, node):
        assert node.path_plane is not None  # [paths] enabled=1 default
        lcl, _ = node.close_ledger()
        # the on_ledger_closed hook advanced the index to the close
        assert node.path_plane.index.seq == lcl.seq
        assert node.path_plane.books_if_current(lcl) is not None
        counts = dispatch(Context(node, {}, Role.ADMIN), "get_counts")
        assert counts["paths"]["index"]["seq"] == lcl.seq

    def test_budget_alternates_stalest_first(self, node):
        """Two subscriptions, budget one: each close serves the stalest
        and SHEDS the other; across two closes both get exactly one
        update (bounded staleness, no queue growth)."""
        alice, carol = KeyPair.from_passphrase(
            "pp-alice"), KeyPair.from_passphrase("pp-carol")
        fund(node, alice)
        fund(node, carol)
        plane = PathPlane(max_updates_per_close=1)
        mgr = SubscriptionManager(node.ops)  # shards=0: inline delivery
        # publish closes by hand below — the constructor's close hook
        # would schedule a second (async) path update per close
        node.ops.on_ledger_closed.remove(mgr._pub_ledger)
        mgr.path_plane = plane
        req = {"src": alice.account_id, "dst": carol.account_id,
               "dst_amount": STAmount.from_drops(1000)}
        got1, got2 = [], []
        sub1, sub2 = InfoSub(got1.append), InfoSub(got2.append)
        mgr.create_path_request(sub1, dict(req))
        mgr.create_path_request(sub2, dict(req))

        lcl, _ = node.close_ledger()
        mgr._pub_path_updates(lcl)
        assert (len(got1), len(got2)) == (1, 0)  # sub1 served, sub2 shed
        assert plane.shed_budget == 1
        lcl, _ = node.close_ledger()
        mgr._pub_path_updates(lcl)
        assert (len(got1), len(got2)) == (1, 1)  # now the stalest went
        assert plane.shed_budget == 2
        assert plane.reranked == 2
        assert got1[0]["type"] == got2[0]["type"] == "path_find"

    def test_throttled_subscriber_shed_in_publish(self, node):
        alice, carol = KeyPair.from_passphrase(
            "pt-alice"), KeyPair.from_passphrase("pt-carol")
        fund(node, alice)
        fund(node, carol)
        rm = node.rpc_resources if node.rpc_resources is not None else (
            ResourceManager())
        plane = PathPlane(max_updates_per_close=8, resources=rm)
        mgr = SubscriptionManager(node.ops)
        node.ops.on_ledger_closed.remove(mgr._pub_ledger)
        mgr.path_plane = plane
        got = []
        sub = InfoSub(got.append, client_ip="6.6.6.6")
        while not rm.is_throttled(("6.6.6.6", 0)):
            rm.charge(("6.6.6.6", 0), FEE_PATH_FIND)
        mgr.create_path_request(sub, {
            "src": alice.account_id, "dst": carol.account_id,
            "dst_amount": STAmount.from_drops(1000)})
        lcl, _ = node.close_ledger()
        mgr._pub_path_updates(lcl)
        assert got == [] and plane.shed_throttled == 1


# --------------------------------------------------------------------------
# result-cache satellite + door pricing


class TestPathFindCacheAndDoor:
    def _seed_accounts(self, node):
        alice = KeyPair.from_passphrase("pc-alice")
        carol = KeyPair.from_passphrase("pc-carol")
        fund(node, alice)
        fund(node, carol)
        node.close_ledger()
        return alice, carol

    def _params(self, alice, carol):
        from stellard_tpu.protocol.keys import encode_account_id

        return {
            "source_account": encode_account_id(alice.account_id),
            "destination_account": encode_account_id(carol.account_id),
            "destination_amount": STAmount.from_drops(1000).to_json(),
            "ledger_index": "validated",
        }

    def test_ripple_path_find_cached_with_copy_on_hit(self, node):
        alice, carol = self._seed_accounts(node)
        params = self._params(alice, carol)
        r1 = dispatch(Context(node, dict(params), Role.GUEST),
                      "ripple_path_find")
        assert "error" not in r1
        h0 = node.read_cache.get_json()["hits"]
        r2 = dispatch(Context(node, dict(params), Role.GUEST),
                      "ripple_path_find")
        assert node.read_cache.get_json()["hits"] == h0 + 1
        r1["status"] = "annotated"  # door annotation must not leak back
        r3 = dispatch(Context(node, dict(params), Role.GUEST),
                      "ripple_path_find")
        assert "status" not in r3 and r3 == r2
        # a new validated close opens a new epoch: miss again
        node.close_ledger()
        dispatch(Context(node, dict(params), Role.GUEST),
                 "ripple_path_find")
        assert node.read_cache.get_json()["hits"] == h0 + 2

    def test_path_find_create_shares_the_cache(self, node):
        """HTTP-degenerate path_find create is the same pure search —
        it must hit the ripple_path_find slot, and the cached entry
        must tolerate the door's `id` annotation (copy-on-hit)."""
        alice, carol = self._seed_accounts(node)
        params = self._params(alice, carol)
        dispatch(Context(node, dict(params), Role.GUEST),
                 "ripple_path_find")
        h0 = node.read_cache.get_json()["hits"]
        r = dispatch(Context(node, dict(params), Role.GUEST), "path_find")
        assert "error" not in r
        assert node.read_cache.get_json()["hits"] == h0 + 1
        r2 = dispatch(Context(node, dict(params), Role.GUEST),
                      "ripple_path_find")
        assert "id" not in r2  # create's annotation stayed out of cache

    def test_fee_class(self):
        assert rpc_method_fee("path_find") is FEE_PATH_FIND
        assert rpc_method_fee("ripple_path_find") is FEE_PATH_FIND
        assert FEE_PATH_FIND.cost > rpc_method_fee("account_info").cost

    def test_door_ladder_warn_then_refuse(self):
        """FEE_PATH_FIND at the door: a path-spam client crosses WARN
        (advisory load warning) and then the drop line (hard slowDown
        refusal) in a handful of requests; admins are exempt."""
        node = types.SimpleNamespace(
            rpc_resources=ResourceManager(admin={"10.0.0.1"}))
        ip = "9.9.9.9"
        assert charge_rpc_client(node, ip, "path_find", Role.GUEST) is None
        assert rpc_warning(node, ip, Role.GUEST) is None  # 400 < WARN
        assert charge_rpc_client(node, ip, "path_find", Role.GUEST) is None
        assert rpc_warning(node, ip, Role.GUEST) == "load"  # 800 >= WARN
        refused = None
        for _ in range(4):
            refused = charge_rpc_client(node, ip, "path_find", Role.GUEST)
            if refused is not None:
                break
        assert refused is not None and refused["error"] == "slowDown"
        # admin IP and admin role never throttle
        for _ in range(10):
            assert charge_rpc_client(
                node, "10.0.0.1", "path_find", Role.GUEST) is None
            assert charge_rpc_client(
                node, ip, "path_find", Role.ADMIN) is None
