"""Path engine tests: flow execution, pathfinding, cross-currency
payments through order books (reference coverage: test/path-test.js,
new-path-test.coffee, indirect-test.js)."""

from __future__ import annotations

import pytest

from stellard_tpu.engine import views
from stellard_tpu.paths import OrderBookDB, find_paths, flow
from stellard_tpu.paths.flow import plan_strand, AccountHop, BookHop, PathError
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import (
    sfAmount,
    sfDestination,
    sfFlags,
    sfPaths,
    sfSendMax,
    sfTakerGets,
    sfTakerPays,
    sfTransferRate,
)
from stellard_tpu.protocol.stamount import ACCOUNT_ZERO, STAmount, currency_from_iso
from stellard_tpu.protocol.stobject import PathElement, STPathSet
from stellard_tpu.protocol.ter import TER
from stellard_tpu.state.entryset import LedgerEntrySet

from test_engine import ALICE, BOB, CAROL, GATEWAY, Net, USD

EUR = currency_from_iso("EUR")
XRP = b"\x00" * 20
M = 1_000_000


def iou(v, issuer, cur=USD):
    return STAmount.from_iou(cur, issuer.account_id, v, 0)


class TestPlanStrand:
    def test_default_iou_path_inserts_issuer(self):
        hops = plan_strand(
            ALICE.account_id, BOB.account_id, iou(10, GATEWAY),
            USD, GATEWAY.account_id, [],
        )
        assert [type(h) for h in hops] == [AccountHop, AccountHop]
        assert hops[0].dst == GATEWAY.account_id
        assert hops[1].dst == BOB.account_id

    def test_explicit_gateway_path(self):
        hops = plan_strand(
            ALICE.account_id, BOB.account_id, iou(10, CAROL),
            USD, CAROL.account_id, [PathElement(account=GATEWAY.account_id)],
        )
        # a USD/CAROL spend enters the network through CAROL (implied
        # head; reference: expandPath inserts the SendMax issuer node),
        # then the explicit gateway, then delivery to bob
        assert hops[0].dst == CAROL.account_id
        assert hops[1].dst == GATEWAY.account_id
        assert hops[-1].dst == BOB.account_id

    def test_cross_currency_inserts_book(self):
        hops = plan_strand(
            ALICE.account_id, BOB.account_id, iou(10, GATEWAY, EUR),
            USD, GATEWAY.account_id, [],
        )
        assert any(isinstance(h, BookHop) for h in hops)

    def test_xrp_cannot_ripple(self):
        with pytest.raises(PathError):
            plan_strand(
                ALICE.account_id, BOB.account_id, iou(10, GATEWAY),
                XRP, ACCOUNT_ZERO,
                [PathElement(account=CAROL.account_id),
                 PathElement(account=BOB.account_id)],
            )


class TestFlowSameCurrency:
    def _net(self):
        net = Net(ALICE, BOB, CAROL, GATEWAY)
        net.trust(ALICE, GATEWAY, 10_000)
        net.trust(BOB, GATEWAY, 10_000)
        net.pay(GATEWAY, ALICE.account_id, iou(500, GATEWAY))
        return net

    def test_payment_through_issuer(self):
        net = self._net()
        net.pay(ALICE, BOB.account_id, iou(120, GATEWAY))
        assert net.iou_balance(BOB, GATEWAY) == iou(120, GATEWAY)
        assert net.iou_balance(ALICE, GATEWAY) == iou(380, GATEWAY)

    def test_transfer_fee_charged_at_gateway(self):
        net = self._net()
        # gateway charges 0.2% (reference: TransferRate 1e9*1.002)
        net.apply(GATEWAY, TxType.ttACCOUNT_SET,
                  fields={sfTransferRate: 1_002_000_000})
        tx_fields = {
            sfDestination: BOB.account_id,
            sfAmount: iou(100, GATEWAY),
            sfSendMax: iou(101, GATEWAY),
        }
        net.apply(ALICE, TxType.ttPAYMENT, fields=tx_fields)
        assert net.iou_balance(BOB, GATEWAY) == iou(100, GATEWAY)
        # alice paid 100 * 1.002 = 100.2
        bal = net.iou_balance(ALICE, GATEWAY)
        assert iou(399, GATEWAY) < bal < iou(400, GATEWAY)

    def test_multihop_gateway_chain(self):
        # alice -USD/G-> G ... carol trusts G too; pay carol via G
        net = self._net()
        net.trust(CAROL, GATEWAY, 10_000)
        net.pay(ALICE, CAROL.account_id, iou(50, GATEWAY))
        assert net.iou_balance(CAROL, GATEWAY) == iou(50, GATEWAY)

    def test_insufficient_liquidity_fails_dry(self):
        net = self._net()
        net.pay(ALICE, BOB.account_id, iou(600, GATEWAY),
                expect=TER.tecPATH_PARTIAL)


class TestFlowCrossCurrency:
    def _net_with_book(self):
        """carol places an offer selling EUR/G for USD/G."""
        net = Net(ALICE, BOB, CAROL, GATEWAY)
        for k in (ALICE, BOB, CAROL):
            net.trust(k, GATEWAY, 100_000)
            net.trust(k, GATEWAY, 100_000, currency=EUR)
            net.apply(GATEWAY, TxType.ttPAYMENT, fields={
                sfDestination: k.account_id, sfAmount: iou(1000, GATEWAY)})
            net.apply(GATEWAY, TxType.ttPAYMENT, fields={
                sfDestination: k.account_id, sfAmount: iou(1000, GATEWAY, EUR)})
        # carol: pays USD 100, gets EUR 80 => price 1.25 USD/EUR
        net.apply(CAROL, TxType.ttOFFER_CREATE, fields={
            sfTakerPays: iou(100, GATEWAY),
            sfTakerGets: iou(80, GATEWAY, EUR),
        })
        return net

    def test_cross_currency_payment_via_book(self):
        net = self._net_with_book()
        # alice sends EUR 40 to bob paying in USD (sendmax 60)
        net.apply(ALICE, TxType.ttPAYMENT, fields={
            sfDestination: BOB.account_id,
            sfAmount: iou(40, GATEWAY, EUR),
            sfSendMax: iou(60, GATEWAY),
        })
        assert net.iou_balance(BOB, GATEWAY, EUR) == iou(1040, GATEWAY, EUR)
        # alice paid 40 * 1.25 = 50 USD
        assert net.iou_balance(ALICE, GATEWAY) == iou(950, GATEWAY)
        # carol's offer was half consumed
        assert net.iou_balance(CAROL, GATEWAY, EUR) == iou(960, GATEWAY, EUR)
        assert net.iou_balance(CAROL, GATEWAY) == iou(1050, GATEWAY)

    def test_sendmax_respected(self):
        net = self._net_with_book()
        # 40 EUR costs 50 USD; cap at 45 -> fails without partial flag
        net.apply(ALICE, TxType.ttPAYMENT, expect=TER.tecPATH_PARTIAL,
                  fields={
                      sfDestination: BOB.account_id,
                      sfAmount: iou(40, GATEWAY, EUR),
                      sfSendMax: iou(45, GATEWAY),
                  })

    def test_partial_payment_delivers_what_it_can(self):
        from stellard_tpu.engine.flags import tfPartialPayment

        net = self._net_with_book()
        net.apply(ALICE, TxType.ttPAYMENT, fields={
            sfDestination: BOB.account_id,
            sfAmount: iou(40, GATEWAY, EUR),
            sfSendMax: iou(45, GATEWAY),
            sfFlags: tfPartialPayment,
        })
        got = net.iou_balance(BOB, GATEWAY, EUR) - iou(1000, GATEWAY, EUR)
        assert iou(0, GATEWAY, EUR) < got < iou(40, GATEWAY, EUR)
        assert net.iou_balance(ALICE, GATEWAY) >= iou(955, GATEWAY)

    def test_xrp_to_iou_via_book(self):
        net = self._net_with_book()
        # carol sells USD for STR: pays 10 STR gets 100 USD? (taker view:
        # taker pays STR 10, taker gets USD 100)
        net.apply(CAROL, TxType.ttOFFER_CREATE, fields={
            sfTakerPays: STAmount.from_drops(10 * M),
            sfTakerGets: iou(100, GATEWAY),
        })
        net.apply(ALICE, TxType.ttPAYMENT, fields={
            sfDestination: BOB.account_id,
            sfAmount: iou(50, GATEWAY),
            sfSendMax: STAmount.from_drops(20 * M),
        })
        assert net.iou_balance(BOB, GATEWAY) == iou(1050, GATEWAY)


class TestPathfinder:
    def test_finds_gateway_path(self):
        net = Net(ALICE, BOB, GATEWAY)
        net.trust(ALICE, GATEWAY, 10_000)
        net.trust(BOB, GATEWAY, 10_000)
        net.pay(GATEWAY, ALICE.account_id, iou(500, GATEWAY))
        alts = find_paths(
            net.ledger, ALICE.account_id, BOB.account_id, iou(100, GATEWAY)
        )
        assert alts, "expected at least the default path"
        assert alts[0]["source_amount"] == iou(100, GATEWAY)

    def test_finds_book_path_cross_currency(self):
        net = TestFlowCrossCurrency()._net_with_book()
        alts = find_paths(
            net.ledger, ALICE.account_id, BOB.account_id,
            iou(40, GATEWAY, EUR), send_max=iou(60, GATEWAY),
        )
        assert alts
        # best source amount: 40 EUR at 1.25 = 50 USD
        assert alts[0]["source_amount"] == iou(50, GATEWAY)

    def test_no_path_returns_empty(self):
        net = Net(ALICE, BOB)
        alts = find_paths(
            net.ledger, ALICE.account_id, BOB.account_id, iou(10, CAROL)
        )
        assert alts == []


class TestOrderBookDB:
    def test_indexes_books(self):
        net = TestFlowCrossCurrency()._net_with_book()
        db = OrderBookDB().setup(net.ledger)
        assert len(db) == 1
        books = db.books_taking(USD, GATEWAY.account_id)
        assert len(books) == 1
        b = next(iter(books))
        assert b.out_currency == EUR


class TestReviewRegressions:
    def test_pathed_payment_without_sendmax(self):
        # paths + no SendMax: the placeholder source issuer (the sender)
        # must not imply a book hop
        net = Net(ALICE, BOB, CAROL, GATEWAY)
        for k in (ALICE, BOB, CAROL):
            net.trust(k, GATEWAY, 10_000)
        net.pay(GATEWAY, ALICE.account_id, iou(500, GATEWAY))
        net.apply(ALICE, TxType.ttPAYMENT, fields={
            sfDestination: BOB.account_id,
            sfAmount: iou(100, GATEWAY),
            sfPaths: STPathSet([[PathElement(account=GATEWAY.account_id)]]),
        })
        assert net.iou_balance(BOB, GATEWAY) == iou(100, GATEWAY)

    def test_cross_currency_self_conversion(self):
        net = TestFlowCrossCurrency()._net_with_book()
        # alice converts her own USD into EUR via the book
        net.apply(ALICE, TxType.ttPAYMENT, fields={
            sfDestination: ALICE.account_id,
            sfAmount: iou(40, GATEWAY, EUR),
            sfSendMax: iou(60, GATEWAY),
        })
        assert net.iou_balance(ALICE, GATEWAY, EUR) == iou(1040, GATEWAY, EUR)
        assert net.iou_balance(ALICE, GATEWAY) == iou(950, GATEWAY)

    def test_no_ripple_pair_blocks_intermediary(self):
        from stellard_tpu.engine.flags import tfSetNoRipple

        net = Net(ALICE, BOB, CAROL)
        # carol is the middle: alice and bob each trust carol's USD.
        # carol must set NoRipple while her balances are still >= 0
        # (the reference refuses the flag on a negative balance)
        net.trust(ALICE, CAROL, 1000)
        net.trust(BOB, CAROL, 1000)
        net.trust(CAROL, ALICE, 0, flags=tfSetNoRipple)
        net.trust(CAROL, BOB, 0, flags=tfSetNoRipple)
        net.pay(CAROL, ALICE.account_id, iou(100, CAROL))
        net.apply(ALICE, TxType.ttPAYMENT, expect=TER.tecPATH_DRY, fields={
            sfDestination: BOB.account_id,
            sfAmount: iou(50, CAROL),
            sfPaths: STPathSet([[PathElement(account=CAROL.account_id)]]),
        })

    def test_limit_quality_rejects_bad_rate(self):
        from stellard_tpu.engine.flags import tfLimitQuality

        net = TestFlowCrossCurrency()._net_with_book()
        # book price is 1.25 USD/EUR; sender demands 1:1 via LimitQuality
        net.apply(ALICE, TxType.ttPAYMENT, expect=TER.tecPATH_DRY, fields={
            sfDestination: BOB.account_id,
            sfAmount: iou(40, GATEWAY, EUR),
            sfSendMax: iou(40, GATEWAY),
            sfFlags: tfLimitQuality,
        })
