"""PeerFinder discovery + Resource DoS defense over real sockets.

Reference intents covered (SURVEY §2.6):
- bootstrap from ONE seed address into a full mesh via ENDPOINTS gossip
  (peerfinder/impl/PeerSlotLogic.h, Livecache/Bootcache),
- bootcache valence persistence across restarts (Bootcache.h),
- a garbage-flooding peer is charged and disconnected, and stays
  rejected while its balance is above the drop line
  (resource/impl/Logic.h:422-509, PeerImp.cpp:129-131),
- adversarial framing: malformed frames / oversized claims close the
  peer without wedging the overlay (hack-test.js intent).
"""

from __future__ import annotations

import os
import socket
import struct
import time

import pytest

from stellard_tpu.overlay.peerfinder import Bootcache, Livecache, PeerFinder
from stellard_tpu.overlay.resource import (
    Disposition,
    FEE_INVALID_SIGNATURE,
    ResourceManager,
)
from stellard_tpu.overlay.tcp import TcpOverlay
from stellard_tpu.protocol.keys import KeyPair

MASTER = KeyPair.from_passphrase("masterpassphrase")
SPEED = 5.0


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def wait_until(pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.1)
    return pred()


def make_overlay(key, unl, port, peer_addrs, ntime, clock, **kw):
    return TcpOverlay(
        key=key,
        unl=unl,
        quorum=3,
        port=port,
        peer_addrs=peer_addrs,
        network_time=ntime,
        clock=clock,
        timer_interval=0.15,
        idle_interval=4,
        gossip_interval=0.3,
        **kw,
    )


class TestUnits:
    def test_bootcache_valence_and_persistence(self, tmp_path):
        path = str(tmp_path / "bootcache.jsonl")
        bc = Bootcache(path)
        bc.insert(("10.0.0.1", 51235))
        bc.insert(("10.0.0.2", 51235))
        for _ in range(3):
            bc.on_success(("10.0.0.2", 51235))
        bc.on_failure(("10.0.0.1", 51235))
        assert bc.ranked()[0] == ("10.0.0.2", 51235)
        bc.save()
        bc2 = Bootcache(path)
        assert len(bc2) == 2
        assert bc2.ranked()[0] == ("10.0.0.2", 51235)

    def test_livecache_hops_and_expiry(self):
        now = [0.0]
        lc = Livecache(clock=lambda: now[0])
        lc.insert(("10.0.0.1", 1), hops=2)
        lc.insert(("10.0.0.1", 1), hops=1)  # lower hop wins
        lc.insert(("10.0.0.2", 2), hops=9)  # over maxHops: discarded
        assert lc.sample() == [("10.0.0.1", 1, 1)]
        now[0] = 31.0
        assert len(lc) == 0

    def test_peerfinder_policy_and_gossip(self):
        now = [0.0]
        pf = PeerFinder(
            fixed=[("127.0.0.1", 1000)], out_desired=3, clock=lambda: now[0]
        )
        pf.on_endpoints(
            [("0.0.0.0", 2000, 0), ("10.1.1.1", 3000, 2), ("bad", 0, 1)],
            sender=("10.9.9.9", 55555),
        )
        # hop-0 host rewritten to the sender's observed address
        assert ("10.9.9.9", 2000) in pf.livecache.addrs()
        targets = pf.dial_targets(set(), set(), 0, 0)
        assert targets[0] == ("127.0.0.1", 1000)  # fixed first
        assert ("10.9.9.9", 2000) in targets
        # failure backoff suppresses redial
        pf.on_failure(("127.0.0.1", 1000))
        assert ("127.0.0.1", 1000) not in pf.dial_targets(set(), set(), 0, 0)
        now[0] = 20.0
        assert ("127.0.0.1", 1000) in pf.dial_targets(set(), set(), 0, 0)
        # gossip: self at hop 0, re-shares at hop+1
        sample = pf.gossip_sample(("0.0.0.0", 1000))
        assert sample[0] == ("0.0.0.0", 1000, 0)
        assert ("10.1.1.1", 3000, 3) in sample

    def test_reconnect_backoff_exponential_with_jitter(self):
        """Consecutive dial failures back an address off exponentially
        (base * 2^(n-1), capped) with deterministic jitter; success
        resets the ladder (ISSUE 9 satellite: no tight reconnect spin
        against a dead address)."""
        now = [0.0]
        pf = PeerFinder(fixed=[("127.0.0.1", 1000)], clock=lambda: now[0])
        addr = ("127.0.0.1", 1000)
        assert pf.backoff_delay(addr) == 0.0
        delays = []
        for _ in range(5):
            pf.on_failure(addr)
            delays.append(pf.backoff_delay(addr))
        # exponential ladder: every rung at least ~1.6x the previous
        # (2x growth, jitter bounded at +25%)
        for a, b in zip(delays, delays[1:]):
            assert b >= a * 1.6
        # jitter present but bounded
        base = pf.backoff_base
        assert base <= delays[0] <= base * 1.25
        # capped
        for _ in range(10):
            pf.on_failure(addr)
        assert pf.backoff_delay(addr) <= pf.backoff_max * 1.25
        # jitter is a pure function: same count, same delay
        assert pf.backoff_delay(addr) == pf.backoff_delay(addr)
        # dial_targets honors the CURRENT rung
        assert addr not in pf.dial_targets(set(), set(), 0, 0)
        now[0] += pf.backoff_max * 1.25 + 1
        assert addr in pf.dial_targets(set(), set(), 0, 0)
        # success resets the ladder
        pf.on_success(addr)
        assert pf.backoff_delay(addr) == 0.0
        pf.on_failure(addr)
        assert pf.backoff_delay(addr) <= pf.backoff_base * 1.25

    def test_refusing_socket_dials_are_backed_off(self):
        """A live overlay dialing an address that refuses connections
        must space its attempts out on the backoff ladder instead of
        redialing every connect-loop tick."""
        # a port that actively refuses: bind+close so nothing listens
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        refused_port = s.getsockname()[1]
        s.close()
        port = free_ports(1)[0]
        key = KeyPair.from_passphrase("backoff-test")
        ov = make_overlay(
            key, set(), port, [("127.0.0.1", refused_port)],
            lambda: 0, time.monotonic,
        )
        # fast ladder so the test observes >1 rung quickly
        ov.peerfinder.backoff_base = 0.4
        attempts = []
        orig = ov.peerfinder.on_failure

        def counting_failure(addr):
            attempts.append(time.monotonic())
            orig(addr)

        ov.peerfinder.on_failure = counting_failure
        ov.start_network()
        try:
            time.sleep(3.0)
        finally:
            ov.stop()
        # a tight spin would rack up dozens of dials in 3s (the dial
        # itself fails in ~1ms on ECONNREFUSED); the ladder allows only
        # a handful, and the gaps must GROW
        assert 1 <= len(attempts) <= 6, attempts
        if len(attempts) >= 3:
            gaps = [b - a for a, b in zip(attempts, attempts[1:])]
            assert gaps[-1] > gaps[0] * 1.5

    def test_resource_decay_and_drop(self):
        now = [0.0]
        rm = ResourceManager(clock=lambda: now[0])
        addr = ("6.6.6.6", 123)
        disp = Disposition.OK
        for _ in range(15):
            disp = rm.charge(addr, FEE_INVALID_SIGNATURE)
        assert disp == Disposition.DROP
        assert not rm.should_admit(addr)
        now[0] = 120.0  # several decay half-lives later
        assert rm.should_admit(addr)
        now[0] = 500.0  # idle past secondsUntilExpiration
        rm.sweep()
        assert rm.get_json()["entries"] == {}


@pytest.fixture()
def seeded_net(tmp_path):
    """4 validators; #1 is the seed, #2-#4 know ONLY the seed address."""
    n = 4
    ports = free_ports(n)
    keys = [KeyPair.from_passphrase(f"pf-val-{i}") for i in range(n)]
    unl = {k.public for k in keys}
    t0 = time.monotonic()
    clock = lambda: (time.monotonic() - t0) * SPEED
    ntime = lambda: 30_000_000 + int(clock())
    overlays = []
    for i in range(n):
        peer_addrs = [] if i == 0 else [("127.0.0.1", ports[0])]
        overlays.append(
            make_overlay(
                keys[i],
                unl,
                ports[i],
                peer_addrs,
                ntime,
                clock,
                bootcache_path=str(tmp_path / f"bootcache{i}.jsonl"),
            )
        )
    for ov in overlays:
        ov.start(MASTER.account_id, close_time=ntime())
    yield overlays, ports
    for ov in overlays:
        ov.stop()


class TestDiscovery:
    def test_bootstrap_from_one_seed(self, seeded_net):
        overlays, ports = seeded_net
        # gossip must grow the net to a full mesh: every node sees all 3
        # others although only the seed was configured anywhere
        assert wait_until(
            lambda: all(ov.peer_count() == 3 for ov in overlays), 30
        ), [ov.peer_count() for ov in overlays]
        # consensus actually runs over the discovered mesh
        assert wait_until(
            lambda: all(ov.node.lm.closed_ledger().seq >= 3 for ov in overlays),
            30,
        )
        # bootcache learned non-seed endpoints (persisted on stop)
        assert all(len(ov.peerfinder.bootcache) >= 3 for ov in overlays)


class TestAbuse:
    def test_garbage_flooder_is_dropped_and_rejected(self, seeded_net):
        overlays, ports = seeded_net
        victim = overlays[0]
        assert wait_until(lambda: victim.peer_count() == 3, 30)

        # flood garbage frames: each connection costs a malformed-request
        # charge (10) and is closed; the balance accumulates per-endpoint
        # until the drop line (1500), after which the admission gate
        # refuses the connection before the handshake
        def flood_once() -> bool:
            """Returns True once the victim refuses us at admission."""
            try:
                s = socket.create_connection(("127.0.0.1", ports[0]), timeout=2)
            except OSError:
                return False
            try:
                s.settimeout(2.0)
                their_nonce = s.recv(32)
                if not their_nonce:
                    return True  # refused before handshake: gate is up
                s.sendall(os.urandom(32))  # our nonce
                junk = struct.pack(">IH", 10, 999) + os.urandom(10)
                for _ in range(50):
                    s.sendall(junk)
                    time.sleep(0.002)
                return False
            except OSError:
                return False  # charged + closed; reconnect and repeat
            finally:
                s.close()

        deadline = time.monotonic() + 60
        refused = False
        while time.monotonic() < deadline:
            if flood_once():
                refused = True
                break
        assert refused, victim.resources.get_json()
        # endpoint is now above the drop threshold: reconnects are refused
        # at accept time (admission gate)
        assert not victim.resources.should_admit(("127.0.0.1", 55555))
        # the legit mesh survived the flood
        assert victim.peer_count() == 3
        assert wait_until(
            lambda: all(
                ov.node.lm.closed_ledger().seq
                >= overlays[0].node.lm.closed_ledger().seq - 1
                for ov in overlays
            ),
            10,
        )


class TestDialChurn:
    def test_established_sessions_not_churned_by_redial_timer(self, seeded_net):
        """r2 regression guard: the connect loop must never dial over (and
        thereby displace) an established live session."""
        overlays, ports = seeded_net
        assert wait_until(lambda: all(ov.peer_count() == 3 for ov in overlays), 30)
        # snapshot session object identities
        def sessions(ov):
            with ov._peers_lock:
                return {pk: id(p) for pk, p in ov.peers.items()}

        # settle first: right after the count reaches 3, a legitimate
        # crossing-dial resolution can still replace one session (both
        # sides dialed simultaneously; the loser is dropped) — on a
        # loaded box that lands seconds late. Churn-by-REDIAL, the
        # regression under guard, only shows after the graph is quiet.
        before = [sessions(ov) for ov in overlays]
        deadline = time.time() + 30
        while time.time() < deadline:
            time.sleep(2)
            cur = [sessions(ov) for ov in overlays]
            if cur == before:
                break
            before = cur
        time.sleep(5)  # several redial sweeps (sweep period 2s)
        after = [sessions(ov) for ov in overlays]
        assert before == after, "established sessions were churned"


class TestAcquisitionScoring:
    """PeerSet-style selection: ledger-data requests route to the peer
    with the best observed reply rate, with periodic exploration."""

    def test_best_reply_rate_wins(self):
        from types import SimpleNamespace

        from stellard_tpu.overlay.tcp import _acq_score

        good = SimpleNamespace(acq_requests=10, acq_replies=9)
        bad = SimpleNamespace(acq_requests=10, acq_replies=1)
        fresh = SimpleNamespace(acq_requests=0, acq_replies=0)
        ranked = sorted([bad, good, fresh], key=_acq_score)
        # a fresh peer scores optimistically (1/1) so it gets tried
        # before anything with history; a proven-good peer beats a
        # proven-bad one
        assert ranked == [fresh, good, bad]

    def test_outstanding_breaks_ties(self):
        from types import SimpleNamespace

        from stellard_tpu.overlay.tcp import _acq_score

        caught_up = SimpleNamespace(acq_requests=9, acq_replies=9)
        backlogged = SimpleNamespace(acq_requests=19, acq_replies=9)
        # backlogged peer has 10 unanswered requests in flight — the
        # caught-up peer must rank first
        assert _acq_score(caught_up) < _acq_score(backlogged)


# ---------------------------------------------------------------------------
# discrete-event churn simulation (reference: peerfinder/sim/Tests.cpp —
# socket-free, deterministic, virtual clock; VERDICT r3 missing #4)


class _SimNode:
    def __init__(self, i: int, fixed, clock):
        self.addr = (f"10.0.0.{i}", 5000 + i)
        self.alive = True
        self.pf = PeerFinder(
            fixed=fixed, out_desired=3, max_peers=8, clock=clock
        )

    def neighbors(self, edges) -> set:
        out = {b for (a, b) in edges if a == self.addr}
        inn = {a for (a, b) in edges if b == self.addr}
        return out | inn

    def in_count(self, edges) -> int:
        return sum(1 for (a, b) in edges if b == self.addr)

    def out_count(self, edges) -> int:
        return sum(1 for (a, b) in edges if a == self.addr)


class _ChurnSim:
    """N nodes, one seed, random joins/leaves. Each tick: dial according
    to PeerFinder policy (receivers enforce slot caps and hand out
    redirects when full), then gossip over live edges."""

    def __init__(self, n: int, seed: int):
        import random

        self.rng = random.Random(seed)
        self.t = 0.0
        clock = lambda: self.t
        seed_addr = (f"10.0.0.0", 5000)
        self.nodes = {}
        for i in range(n):
            fixed = [] if i == 0 else [seed_addr]
            node = _SimNode(i, fixed, clock)
            self.nodes[node.addr] = node
        self.edges: set[tuple] = set()  # (dialer_addr, receiver_addr)

    def live(self):
        return [n for n in self.nodes.values() if n.alive]

    def tick(self):
        self.t += 1.0
        # drop edges touching dead nodes
        self.edges = {
            (a, b)
            for (a, b) in self.edges
            if self.nodes[a].alive and self.nodes[b].alive
        }
        for node in self.live():
            targets = node.pf.dial_targets(
                connected=node.neighbors(self.edges),
                dialing=set(),
                out_count=node.out_count(self.edges),
                total_count=len(node.neighbors(self.edges)),
            )
            for t in targets:
                recv = self.nodes.get(t)
                if recv is None or not recv.alive:
                    node.pf.on_failure(t)
                    continue
                reserved = node.addr in set(map(tuple, recv.pf.fixed))
                if not recv.pf.can_accept_inbound(
                    recv.in_count(self.edges), reserved
                ):
                    # redirect handout instead of a silent drop
                    sample = recv.pf.handout(exclude={recv.addr})
                    node.pf.on_endpoints(
                        [(h, p, 1) for (h, p) in sample], sender=t
                    )
                    node.pf.on_failure(t)
                    continue
                self.edges.add((node.addr, t))
                node.pf.on_success(t)
        # gossip over live edges, both directions
        for (a, b) in list(self.edges):
            for src, dst in ((a, b), (b, a)):
                sample = self.nodes[src].pf.gossip_sample(src)
                self.nodes[dst].pf.on_endpoints(sample, sender=src)

    def assert_caps(self):
        for node in self.live():
            inn = node.in_count(self.edges)
            # fixed-reserved connections may exceed the cap; count only
            # non-reserved inbound against max_in
            fixed_in = sum(
                1
                for (a, b) in self.edges
                if b == node.addr
                and a in set(map(tuple, node.pf.fixed))
            )
            assert inn - fixed_in <= node.pf.max_in, (
                f"{node.addr} inbound {inn} exceeds cap {node.pf.max_in}"
            )
            assert len(node.neighbors(self.edges)) <= node.pf.max_peers + len(
                node.pf.fixed
            )

    def converged(self) -> bool:
        live = self.live()
        if len(live) <= 1:
            return True
        start = live[0].addr
        seen = {start}
        frontier = [start]
        while frontier:
            cur = frontier.pop()
            for nxt in self.nodes[cur].neighbors(self.edges):
                if nxt not in seen and self.nodes[nxt].alive:
                    seen.add(nxt)
                    frontier.append(nxt)
        return seen == {n.addr for n in live}


class TestChurnSim:
    def test_bootstrap_converges_and_respects_caps(self):
        sim = _ChurnSim(n=24, seed=42)
        for _ in range(40):
            sim.tick()
            sim.assert_caps()
        assert sim.converged(), "bootstrap from one seed must mesh the net"

    def test_reconverges_after_churn(self):
        sim = _ChurnSim(n=24, seed=7)
        for _ in range(30):
            sim.tick()
        # churn phase: random kills and revivals (up to 6 dead at once)
        dead: list = []
        for _ in range(60):
            if sim.rng.random() < 0.3 and len(dead) < 6:
                victim = sim.rng.choice(sim.live()[1:])  # never the seed
                victim.alive = False
                dead.append(victim)
            if sim.rng.random() < 0.2 and dead:
                dead.pop(sim.rng.randrange(len(dead))).alive = True
            sim.tick()
            sim.assert_caps()
        for node in dead:
            node.alive = True
        # recovery: everyone alive again; the mesh must re-form
        for _ in range(80):
            sim.tick()
            sim.assert_caps()
            if sim.converged():
                break
        assert sim.converged(), "net must reconverge after churn"

    def test_full_seed_redirects_connectors(self):
        """When the seed's inbound slots fill, later joiners still mesh
        via handout addresses (the redirect path does real work)."""
        sim = _ChurnSim(n=30, seed=3)
        for _ in range(60):
            sim.tick()
        sim.assert_caps()
        assert sim.converged()
        # the seed must NOT be connected to everyone (slots capped) —
        # proof the mesh grew through redirects/gossip, not a star
        seed = sim.nodes[("10.0.0.0", 5000)]
        assert len(seed.neighbors(sim.edges)) < len(sim.live()) - 1
