"""Randomized property tests over the consensus-critical primitives —
CI-sized versions of the build-time soaks (120k/20k/4k iterations ran
clean 2026-07-30):

- STAmount multiply/divide differential vs exact Fractions
  (reference STAmount.cpp rounding: *“(m1*m2)/10^14 + 7”*,
  *“(num*10^17)/den + 5”*), add within one canonical ulp;
- STObject serialize→parse→serialize byte-stability over random field
  sets (also exercises the canonical-order sort-memo seeding);
- native C++ Ed25519 verifier agreement with the host library over
  valid + adversarially mutated batches.
"""

from __future__ import annotations

import random
from fractions import Fraction

import numpy as np
import pytest

from stellard_tpu.protocol import sfields as sf
from stellard_tpu.protocol.keys import KeyPair, verify_signature
from stellard_tpu.protocol.stamount import STAmount, currency_from_iso
from stellard_tpu.protocol.stobject import PathElement, STObject, STPathSet

USD = currency_from_iso("USD")
ISS = b"\x07" * 20


def _frac(a: STAmount) -> Fraction:
    f = Fraction(a.mantissa) * Fraction(10) ** a.offset
    return -f if a.negative else f


class TestSTAmountProperties:
    def test_mul_div_add_vs_fractions(self):
        rng = random.Random(20260730)

        def rand_iou():
            return STAmount(
                USD, ISS,
                rng.randint(10**15, 10**16 - 1),
                rng.randint(-35, 15),
                rng.random() < 0.5,
            )

        for _ in range(5000):
            a, b = rand_iou(), rand_iou()
            try:
                p = STAmount.multiply(a, b, USD, ISS)
            except ValueError:
                continue
            if not p.is_zero():
                exact = _frac(a) * _frac(b)
                assert abs(_frac(p) - exact) / abs(exact) < Fraction(1, 10**14)
            q = STAmount.divide(a, b, USD, ISS)
            exact = _frac(a) / _frac(b)
            assert abs(_frac(q) - exact) / abs(exact) < Fraction(1, 10**14)
            s = a + b
            exact = _frac(a) + _frac(b)
            if s.is_zero():
                assert abs(exact) < Fraction(10) ** (max(a.offset, b.offset) + 2)
            else:
                assert abs(_frac(s) - exact) <= Fraction(10) ** (
                    max(a.offset, b.offset) + 1
                )


class TestSTObjectRoundTrip:
    INT_FIELDS = [sf.sfSequence, sf.sfFlags, sf.sfOfferSequence,
                  sf.sfTransferRate, sf.sfQualityIn, sf.sfQualityOut,
                  sf.sfSourceTag, sf.sfDestinationTag]
    H256 = [sf.sfPreviousTxnID, sf.sfInvoiceID]
    AMT = [sf.sfAmount, sf.sfLimitAmount, sf.sfTakerPays, sf.sfTakerGets,
           sf.sfSendMax]
    ACCT = [sf.sfAccount, sf.sfDestination, sf.sfRegularKey]
    BLOB = [sf.sfSigningPubKey, sf.sfTxnSignature]

    def test_serialize_parse_serialize_byte_stable(self):
        rng = random.Random(42)

        def rand_amount():
            if rng.random() < 0.4:
                return STAmount.from_drops(rng.randint(0, 10**15))
            return STAmount(
                USD, bytes([rng.randint(0, 255)]) * 20,
                rng.randint(10**15, 10**16 - 1), rng.randint(-30, 10),
                rng.random() < 0.5,
            )

        def rand_obj():
            o = STObject()
            for f in rng.sample(self.INT_FIELDS, rng.randint(0, 4)):
                o[f] = rng.randint(0, 2**31)
            for f in rng.sample(self.H256, rng.randint(0, 2)):
                o[f] = bytes(rng.randint(0, 255) for _ in range(32))
            for f in rng.sample(self.AMT, rng.randint(0, 3)):
                o[f] = rand_amount()
            for f in rng.sample(self.ACCT, rng.randint(0, 2)):
                o[f] = bytes(rng.randint(0, 255) for _ in range(20))
            for f in rng.sample(self.BLOB, rng.randint(0, 2)):
                o[f] = bytes(
                    rng.randint(0, 255) for _ in range(rng.randint(0, 80))
                )
            if rng.random() < 0.25:
                pe = PathElement(
                    account=bytes(rng.randint(0, 255) for _ in range(20))
                )
                o[sf.sfPaths] = STPathSet([[pe]])
            return o

        for i in range(1500):
            o = rand_obj()
            blob = o.serialize()
            o2 = STObject.from_bytes(blob)
            assert o2.serialize() == blob, i


class TestEd25519Differential:
    def test_native_matches_host_library_adversarial(self):
        from stellard_tpu.native import native_available

        if not native_available():
            pytest.skip("native toolchain unavailable")
        from stellard_tpu.native import Ed25519NativeVerify

        rng = np.random.default_rng(99)
        keys = [
            KeyPair.from_seed(bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
            for _ in range(8)
        ]
        N = 256
        msgs = [bytes(rng.integers(0, 256, 32, dtype=np.uint8))
                for _ in range(N)]
        pubs = [keys[i % 8].public for i in range(N)]
        sigs = [keys[i % 8].sign(msgs[i]) for i in range(N)]
        for i in range(0, N, 2):
            kind = i % 12
            if kind == 0:
                b = bytearray(sigs[i])
                b[int(rng.integers(0, 32))] ^= 1 << int(rng.integers(0, 8))
                sigs[i] = bytes(b)
            elif kind == 2:
                b = bytearray(sigs[i])
                b[32 + int(rng.integers(0, 32))] ^= 1 << int(rng.integers(0, 8))
                sigs[i] = bytes(b)
            elif kind == 4:
                b = bytearray(pubs[i])
                b[int(rng.integers(0, 32))] ^= 1 << int(rng.integers(0, 8))
                pubs[i] = bytes(b)
            elif kind == 6:
                b = bytearray(msgs[i])
                b[int(rng.integers(0, 32))] ^= 1
                msgs[i] = bytes(b)
            elif kind == 8:
                sigs[i] = bytes(rng.integers(0, 256, 64, dtype=np.uint8))
            else:
                pubs[i] = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        got = Ed25519NativeVerify().verify_batch(pubs, msgs, sigs)
        want = np.array(
            [verify_signature(p, m, s) for p, m, s in zip(pubs, msgs, sigs)]
        )
        assert np.array_equal(got, want)
        assert 0 < int(want.sum()) < N  # both classes exercised
