"""Protocol-kernel tests: hashing, base58, serializer, amounts, objects, keys.

Golden values are derived from the reference's algorithms
(SHA-512-half, Base58Check with the Stellar alphabet, canonical field
ordering) and from independently-computable crypto primitives.
"""

import hashlib

import pytest

from stellard_tpu.protocol import (
    BinaryParser,
    KeyPair,
    STAmount,
    STArray,
    STObject,
    STPathSet,
    PathElement,
    Serializer,
    TER,
    TX_FORMATS,
    TxType,
    currency_from_iso,
    decode_account_id,
    encode_account_id,
    encode_vl_length,
    iso_from_currency,
    passphrase_to_seed,
    validate_against,
    verify_signature,
)
from stellard_tpu.protocol import sfields as sf
from stellard_tpu.utils.hashes import (
    HP_INNER_NODE,
    HP_TX_SIGN,
    prefix_hash,
    sha512_half,
    hash160,
)
from stellard_tpu.utils.base58 import b58_decode, b58_encode, b58check_encode, b58check_decode


class TestHashes:
    def test_sha512_half(self):
        assert sha512_half(b"") == hashlib.sha512(b"").digest()[:32]
        assert len(sha512_half(b"abc")) == 32

    def test_prefix_hash_domain_separation(self):
        # prefix is 3 chars + zero byte, big-endian prepended
        assert prefix_hash(HP_INNER_NODE, b"x") == hashlib.sha512(b"MIN\x00x").digest()[:32]
        assert prefix_hash(HP_TX_SIGN, b"x") == hashlib.sha512(b"STX\x00x").digest()[:32]
        assert prefix_hash(HP_TX_SIGN, b"x") != prefix_hash(HP_INNER_NODE, b"x")

    def test_hash160(self):
        inner = hashlib.sha256(b"pubkey").digest()
        h = hashlib.new("ripemd160")
        h.update(inner)
        assert hash160(b"pubkey") == h.digest()


class TestBase58:
    def test_roundtrip(self):
        for data in [b"", b"\x00", b"\x00\x00abc", b"hello world", bytes(range(32))]:
            assert b58_decode(b58_encode(data)) == data

    def test_leading_zeros_use_g(self):
        # Stellar alphabet zero char is 'g'
        assert b58_encode(b"\x00\x00\x01").startswith("gg")

    def test_check_roundtrip(self):
        s = b58check_encode(0, b"\x01" * 20)
        ver, payload = b58check_decode(s)
        assert ver == 0 and payload == b"\x01" * 20
        assert s.startswith("g")  # version-0 account IDs render g...

    def test_check_detects_corruption(self):
        s = b58check_encode(33, b"\x02" * 32)
        corrupted = s[:-1] + ("g" if s[-1] != "g" else "s")
        with pytest.raises(ValueError):
            b58check_decode(corrupted)


class TestSerializer:
    def test_integers_big_endian(self):
        s = Serializer()
        s.add8(0xAB)
        s.add16(0x1234)
        s.add32(0xDEADBEEF)
        s.add64(0x0102030405060708)
        assert s.data() == bytes.fromhex("ab1234deadbeef0102030405060708")

    def test_vl_length_boundaries(self):
        # reference Serializer.cpp addEncoded: 1/2/3-byte prefixes
        assert encode_vl_length(0) == b"\x00"
        assert encode_vl_length(192) == bytes([192])
        assert encode_vl_length(193) == bytes([193, 0])
        assert encode_vl_length(12480) == bytes([240, 255])
        assert encode_vl_length(12481) == bytes([241, 0, 0])
        assert encode_vl_length(918744) == bytes([254, 0xD4, 0x17])
        with pytest.raises(ValueError):
            encode_vl_length(918745)

    @pytest.mark.parametrize("n", [0, 1, 192, 193, 300, 12480, 12481, 20000, 918744])
    def test_vl_roundtrip(self, n):
        s = Serializer()
        payload = bytes(n % 256 for n in range(n))
        s.add_vl(payload)
        p = BinaryParser(s.data())
        assert p.read_vl() == payload
        assert p.empty()

    def test_field_id_packing(self):
        # common/common -> 1 byte; the rest per Serializer.cpp:193-223
        s = Serializer()
        s.add_field_id(2, 4)  # UINT32 Sequence
        assert s.data() == bytes([0x24])
        s = Serializer()
        s.add_field_id(2, 26)  # UINT32 InflateSeq
        assert s.data() == bytes([0x20, 26])
        s = Serializer()
        s.add_field_id(16, 1)  # UINT8 CloseResolution
        assert s.data() == bytes([0x01, 16])
        s = Serializer()
        s.add_field_id(17, 16)
        assert s.data() == bytes([0x00, 17, 16])

    def test_field_id_roundtrip(self):
        for t, n in [(1, 1), (2, 15), (2, 16), (14, 1), (15, 1), (16, 3), (17, 16), (19, 255)]:
            s = Serializer()
            s.add_field_id(t, n)
            assert BinaryParser(s.data()).read_field_id() == (t, n)


class TestSTAmount:
    def test_native_roundtrip(self):
        for drops in [0, 1, 10**6, 10**17 - 1, -5, -(10**12)]:
            a = STAmount.from_drops(drops)
            s = Serializer()
            a.serialize(s)
            b = STAmount.deserialize(BinaryParser(s.data()))
            assert b.drops() == drops

    def test_native_wire_positive_bit(self):
        s = Serializer()
        STAmount.from_drops(1).serialize(s)
        assert s.data() == (1 | (1 << 62)).to_bytes(8, "big")
        s = Serializer()
        STAmount.from_drops(-1).serialize(s)
        assert s.data() == (1).to_bytes(8, "big")

    def test_iou_roundtrip(self):
        usd = currency_from_iso("USD")
        issuer = b"\x07" * 20
        for mant, off, neg in [
            (10**15, 0, False),
            (9999999999999999, 80, False),
            (10**15, -96, True),
            (123456789, -5, False),  # non-canonical input, canonicalized
        ]:
            a = STAmount.from_iou(usd, issuer, mant, off, neg)
            s = Serializer()
            a.serialize(s)
            b = STAmount.deserialize(BinaryParser(s.data()))
            assert a == b

    def test_iou_zero_encoding(self):
        usd = currency_from_iso("USD")
        a = STAmount.zero_like(usd, b"\x01" * 20)
        s = Serializer()
        a.serialize(s)
        assert s.data()[:8] == bytes.fromhex("8000000000000000")

    def test_currency_iso_roundtrip(self):
        usd = currency_from_iso("USD")
        assert usd[12:15] == b"USD"
        assert iso_from_currency(usd) == "USD"
        assert iso_from_currency(currency_from_iso("STR")) == "STR"

    def test_canonicalization(self):
        usd = currency_from_iso("USD")
        a = STAmount.from_iou(usd, b"\x01" * 20, 1, 0)  # 1 -> 1e15 * 10^-15
        assert a.mantissa == 10**15 and a.offset == -15
        assert a.value_text() == "1"

    def test_add_sub_native(self):
        a = STAmount.from_drops(100)
        b = STAmount.from_drops(42)
        assert (a + b).drops() == 142
        assert (a - b).drops() == 58
        assert (b - a).drops() == -58

    def test_multiply_divide_reference_rounding(self):
        usd = currency_from_iso("USD")
        one = STAmount.from_iou(usd, b"\x01" * 20, 10**15, -15)  # 1.0
        three = STAmount.from_iou(usd, b"\x01" * 20, 3 * 10**15, -15)
        q = STAmount.divide(one, three, usd, b"\x01" * 20)
        # (1e15 * 10^17) / 3e15 + 5 = 33333333333333338 -> canonicalized
        assert q.mantissa == 3333333333333333 and q.offset == -16
        p = STAmount.multiply(three, three, usd, b"\x01" * 20)
        assert p.value_text() == "9"

    def test_tiny_cancelling_sum_is_zero(self):
        # reference operator+ collapses |aligned sum| <= 10 to canonical zero
        usd = currency_from_iso("USD")
        a = STAmount.from_iou(usd, b"\x01" * 20, 10**15 + 5, -15)
        b = STAmount.from_iou(usd, b"\x01" * 20, 10**15 - 2, -15, negative=True)
        assert (a + b).is_zero()
        assert (a + b).offset == -100  # canonical IOU zero

    def test_native_exponent_notation(self):
        # reference setValue normalizes the exponent away for native amounts
        assert STAmount.from_json("1e3").drops() == 1000
        assert STAmount.from_json("100.0").drops() == 100
        with pytest.raises(ValueError):
            STAmount.from_json("1.5")

    def test_ripemd160_fallback_matches_openssl(self):
        from stellard_tpu.utils.ripemd160 import ripemd160

        h = hashlib.new("ripemd160")
        h.update(b"stellard")
        assert ripemd160(b"stellard") == h.digest()

    def test_json_forms(self):
        assert STAmount.from_json("1000000").drops() == 1000000
        j = {"value": "2.5", "currency": "USD", "issuer": encode_account_id(b"\x09" * 20)}
        a = STAmount.from_json(j)
        assert not a.is_native and a.value_text() == "2.5"
        back = a.to_json()
        assert back["value"] == "2.5" and back["currency"] == "USD"

    def test_compare(self):
        assert STAmount.from_drops(5) < STAmount.from_drops(6)
        usd = currency_from_iso("USD")
        a = STAmount.from_json({"value": "1", "currency": "USD"})
        b = STAmount.from_json({"value": "10", "currency": "USD"})
        assert a < b and b > a and a == STAmount.from_json({"value": "1.0", "currency": "USD"})


class TestSTObject:
    def _payment(self):
        obj = STObject()
        obj[sf.sfTransactionType] = int(TxType.ttPAYMENT)
        obj[sf.sfAccount] = b"\x01" * 20
        obj[sf.sfDestination] = b"\x02" * 20
        obj[sf.sfAmount] = STAmount.from_drops(10**6)
        obj[sf.sfFee] = STAmount.from_drops(10)
        obj[sf.sfSequence] = 1
        obj[sf.sfSigningPubKey] = b"\x03" * 32
        obj[sf.sfTxnSignature] = b"\x04" * 64
        return obj

    def test_roundtrip(self):
        obj = self._payment()
        data = obj.serialize()
        back = STObject.from_bytes(data)
        assert back == obj

    def test_canonical_order_independent_of_insertion(self):
        a = self._payment()
        b = STObject()
        for f, v in reversed(list(a.fields())):
            b[f] = v
        assert a.serialize() == b.serialize()

    def test_signing_serialization_omits_signature(self):
        obj = self._payment()
        signed = obj.serialize()
        unsigned = obj.serialize(signing=True)
        assert len(unsigned) < len(signed)
        no_sig = obj.copy()
        del no_sig[sf.sfTxnSignature]
        assert unsigned == no_sig.serialize()

    def test_wire_layout_starts_with_tx_type(self):
        # first canonical field is (UINT16, 2) TransactionType -> header 0x12
        data = self._payment().serialize()
        assert data[0] == 0x12
        assert data[1:3] == (0).to_bytes(2, "big")

    def test_inner_object_and_array(self):
        memo = STObject({sf.sfMemoType: b"hi", sf.sfMemoData: b"there"})
        arr = STArray([(sf.sfMemo, memo)])
        obj = self._payment()
        obj[sf.sfMemos] = arr
        back = STObject.from_bytes(obj.serialize())
        assert back[sf.sfMemos] == arr

    def test_pathset_roundtrip(self):
        usd = currency_from_iso("USD")
        ps = STPathSet(
            [
                [PathElement(account=b"\x05" * 20), PathElement(currency=usd, issuer=b"\x06" * 20)],
                [PathElement(account=b"\x07" * 20)],
            ]
        )
        obj = self._payment()
        obj[sf.sfPaths] = ps
        back = STObject.from_bytes(obj.serialize())
        assert back[sf.sfPaths] == ps

    def test_template_validation(self):
        obj = self._payment()
        fmt = TX_FORMATS[int(TxType.ttPAYMENT)]
        assert validate_against(obj, fmt) == []
        del obj[sf.sfDestination]
        assert any("Destination" in p for p in validate_against(obj, fmt))
        obj[sf.sfDestination] = b"\x02" * 20
        obj[sf.sfOfferSequence] = 3  # not a Payment field
        assert any("OfferSequence" in p for p in validate_against(obj, fmt))


class TestKeys:
    def test_passphrase_seed(self):
        assert passphrase_to_seed("masterpassphrase") == sha512_half(b"masterpassphrase")

    def test_keypair_deterministic(self):
        k1 = KeyPair.from_passphrase("alice")
        k2 = KeyPair.from_passphrase("alice")
        assert k1.public == k2.public
        assert len(k1.public) == 32
        assert len(k1.account_id) == 20

    def test_account_id_encoding(self):
        k = KeyPair.from_passphrase("bob")
        human = k.human_account_id
        assert human.startswith("g")
        assert decode_account_id(human) == k.account_id

    def test_sign_verify(self):
        k = KeyPair.from_passphrase("carol")
        h = sha512_half(b"message")
        sig = k.sign(h)
        assert len(sig) == 64
        assert verify_signature(k.public, h, sig)
        assert not verify_signature(k.public, sha512_half(b"other"), sig)
        bad = bytearray(sig)
        bad[0] ^= 1
        assert not verify_signature(k.public, h, bytes(bad))

    def test_non_canonical_s_rejected(self):
        from stellard_tpu.protocol.keys import ED25519_L

        k = KeyPair.from_passphrase("dave")
        h = sha512_half(b"message")
        sig = bytearray(k.sign(h))
        # add group order l to S: same point equation, non-canonical encoding
        s = int.from_bytes(sig[32:], "little") + ED25519_L
        if s < (1 << 512):
            sig[32:] = s.to_bytes(32, "little") if s < (1 << 256) else sig[32:]
        assert not verify_signature(k.public, h, bytes(sig))


class TestTER:
    def test_ranges(self):
        assert TER.tesSUCCESS.is_tes and TER.tesSUCCESS.applied
        assert TER.tecPATH_DRY.is_tec and TER.tecPATH_DRY.applied
        assert TER.temBAD_SIGNATURE.is_tem and not TER.temBAD_SIGNATURE.applied
        assert TER.terPRE_SEQ.is_ter
        assert TER.tefPAST_SEQ.is_tef
        assert TER.telINSUF_FEE_P.is_tel


class TestRFC1751:
    """RFC 1751 human keys (reference: crypto/RFC1751.cpp). The live
    consumer is server_info's hostid word; key<->English is the full
    (vestigial in the reference) API, pinned to the RFC's own vectors."""

    def test_rfc_appendix_vectors(self):
        from stellard_tpu.utils.rfc1751 import english_to_key, key_to_english

        assert english_to_key(
            "RASH BUSH MILK LOOK BAD BRIM AVID GAFF BAIT ROT POD LOVE"
        ).hex().upper() == "CCAC2AED591056BE4F90FD441C534766"
        assert key_to_english(
            bytes.fromhex("EFF81F9BFBC65350920CDD7416DE8009")
        ) == "TROD MUTE TAIL WARM CHAR KONG HAAG CITY BORE O TEAL AWL"

    def test_roundtrip_and_normalization(self):
        import os as _os

        from stellard_tpu.utils.rfc1751 import english_to_key, key_to_english

        for _ in range(32):
            k = _os.urandom(16)
            assert english_to_key(key_to_english(k)) == k
        # lowercase + digit-for-letter confusables normalize (the
        # reference INTENDS this; its standard() is a no-op bug)
        assert english_to_key(
            "rash bush milk l00k bad brim avid gaff bait rot pod love"
        ).hex().upper() == "CCAC2AED591056BE4F90FD441C534766"

    def test_error_classes(self):
        import pytest as _pytest

        from stellard_tpu.utils.rfc1751 import english_to_key

        good = "RASH BUSH MILK LOOK BAD BRIM AVID GAFF BAIT ROT POD LOVE"
        with _pytest.raises(ValueError):  # wrong word count
            english_to_key("RASH BUSH")
        with _pytest.raises(ValueError):  # unknown word
            english_to_key(good.replace("MILK", "XYZQ"))
        with _pytest.raises(ValueError):  # parity broken by a word swap
            english_to_key(good.replace("BAD", "BAN"))

    def test_hostid_in_server_info(self):
        from stellard_tpu.node.config import Config
        from stellard_tpu.node.node import Node
        from stellard_tpu.rpc.handlers import Context, Role, dispatch
        from stellard_tpu.utils.rfc1751 import WORDS

        n = Node(Config(signature_backend="cpu")).setup()
        try:
            info = dispatch(Context(n, {}, Role.ADMIN), "server_info")
            assert info["info"]["hostid"] in WORDS
        finally:
            n.stop()
